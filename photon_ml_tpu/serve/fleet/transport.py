"""Fleet wire protocol: JSON-lines over TCP, plus an in-process client.

One message dispatch function (:func:`dispatch`) serves BOTH transports,
so the in-process fast path the tier-1 tests exercise and the TCP path the
multi-process harness/bench exercise run the identical replica code:

  * :class:`LocalReplicaClient` — direct in-process calls against a
    :class:`~photon_ml_tpu.serve.fleet.replica.ReplicaEngine` (no sockets,
    no serialization; contribution arrays pass through as float lists the
    same way the wire would carry them).
  * :class:`ReplicaServer` / :class:`TcpReplicaClient` — a threaded TCP
    server speaking one JSON object per line (the PR 6 serve protocol's
    framing), and a pooled client. No network framework — the deployment
    fronts this with whatever transport it has, exactly like the PR 6
    stdin/stdout loop.

JSON float round-trip note: contributions are f32 widened to f64 for the
wire; Python's ``repr``-based JSON floats round-trip f64 exactly, so the
router's f32 narrow-back is bitwise the replica's device output.
"""

from __future__ import annotations

import json
import logging
import queue
import socket
import socketserver
import threading
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.serve.fleet.replica import ReplicaEngine, StaleGenerationError

logger = logging.getLogger(__name__)


class ReplicaUnavailableError(OSError):
    """The replica could not be reached or failed the call — the router's
    cue to retry, reroute (fixed parts), or degrade (random parts)."""


def _np_to_wire(contribs: Dict[str, np.ndarray]) -> Dict[str, List[float]]:
    return {k: [float(x) for x in v] for k, v in contribs.items()}


def dispatch(engine: ReplicaEngine, msg: dict) -> dict:
    """One protocol message -> one response dict (shared by both
    transports). Every response carries ``ok``; failures are structured
    (``stale_generation`` lets the router re-score at the current epoch
    instead of degrading)."""
    cmd = msg.get("cmd")
    try:
        if cmd == "contribs":
            contribs = engine.contribs(
                msg.get("rows") or [],
                want_fixed=bool(msg.get("fixed")),
                want_random=list(msg.get("random") or []),
                epoch=msg.get("epoch"),
            )
            return {
                "ok": True,
                "epoch": engine.epoch,
                "contribs": _np_to_wire(contribs),
            }
        if cmd == "score":
            scores = engine.score_rows(msg.get("rows") or [])
            return {"ok": True, "scores": [float(s) for s in scores]}
        if cmd == "prepare":
            report = engine.prepare(
                msg.get("store_dir", ""), int(msg.get("epoch", -1))
            )
            return {"ok": True, **report}
        if cmd == "commit":
            return {"ok": True, **engine.commit(int(msg.get("epoch", -1)))}
        if cmd == "abandon":
            return {"ok": True, **engine.abandon()}
        if cmd == "ping":
            return {
                "ok": True,
                "replica": engine.replica_id,
                "epoch": engine.epoch,
            }
        if cmd == "stats":
            return {
                "ok": True,
                "stats": engine.stats.snapshot(),
                "new_request_compiles": engine.new_request_compiles(),
            }
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}
    except StaleGenerationError as e:
        # the replica's CURRENT epoch rides along so the router can fast-
        # forward a stale dispatch generation (e.g. a freshly started
        # router joining a fleet that already swapped)
        return {
            "ok": False,
            "stale_generation": True,
            "epoch": engine.epoch,
            "error": str(e),
        }
    except Exception as e:  # noqa: BLE001 — protocol fence: a bad message must fail ITS caller, not kill the replica loop
        logger.warning("replica %d %s failed: %s", engine.replica_id, cmd, e)
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


# ---------------------------------------------------------------------------
# in-process client (tier-1 fast path)
# ---------------------------------------------------------------------------


class LocalReplicaClient:
    """Direct calls against an in-process engine. ``fail_mode`` simulates a
    lost replica for chaos tests: once set, every call raises the same
    connection error a dead TCP peer produces."""

    def __init__(self, engine: ReplicaEngine):
        self.engine = engine
        self.fail_mode: Optional[str] = None

    def call(self, msg: dict, timeout: Optional[float] = None) -> dict:
        if self.fail_mode:
            raise ReplicaUnavailableError(
                f"replica {self.engine.replica_id} unavailable "
                f"({self.fail_mode})"
            )
        return dispatch(self.engine, msg)

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# TCP transport
# ---------------------------------------------------------------------------


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        engine = self.server.engine  # type: ignore[attr-defined]
        for raw in self.rfile:
            line = raw.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError as e:
                resp = {"ok": False, "error": f"bad JSON: {e}"}
            else:
                if msg.get("cmd") == "shutdown":
                    self.wfile.write(b'{"ok": true}\n')
                    self.server.shutdown_requested.set()  # type: ignore[attr-defined]
                    return
                resp = dispatch(engine, msg)
            self.wfile.write((json.dumps(resp) + "\n").encode("utf-8"))
            self.wfile.flush()


class ReplicaServer(socketserver.ThreadingTCPServer):
    """Threaded JSON-lines TCP front for one ReplicaEngine. Bind with
    port 0 to get an ephemeral port (``server_address[1]``)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, engine: ReplicaEngine, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__((host, port), _Handler)
        self.engine = engine
        self.shutdown_requested = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "ReplicaServer":
        self._thread = threading.Thread(
            target=self.serve_forever,
            name=f"photon-fleet-replica-{self.engine.replica_id}",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_until_shutdown(self, poll_s: float = 0.2) -> None:
        """Blocking variant for the CLI replica process: serve until a
        ``shutdown`` message arrives."""
        self.start()
        while not self.shutdown_requested.wait(poll_s):
            pass
        self.stop()

    def stop(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class TcpReplicaClient:
    """Pooled JSON-lines client: one persistent connection per concurrent
    call (connections return to the pool on success, drop on failure so a
    dead peer never poisons the pool)."""

    def __init__(self, address: str, connect_timeout_s: float = 5.0):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.connect_timeout_s = connect_timeout_s
        self._pool: "queue.Queue[socket.socket]" = queue.Queue()
        self._closed = False

    def _connect(self) -> socket.socket:
        try:
            return socket.create_connection(
                (self.host, self.port), timeout=self.connect_timeout_s
            )
        except OSError as e:
            raise ReplicaUnavailableError(
                f"cannot connect to replica at {self.host}:{self.port}: {e}"
            ) from e

    def call(self, msg: dict, timeout: Optional[float] = None) -> dict:
        if self._closed:
            raise ReplicaUnavailableError("client closed")
        try:
            conn = self._pool.get_nowait()
        except queue.Empty:
            conn = self._connect()
        try:
            conn.settimeout(timeout)
            conn.sendall((json.dumps(msg) + "\n").encode("utf-8"))
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = conn.recv(1 << 16)
                if not chunk:
                    raise ReplicaUnavailableError(
                        f"replica at {self.host}:{self.port} closed the "
                        "connection mid-call"
                    )
                buf += chunk
        except ReplicaUnavailableError:
            conn.close()
            raise
        except (OSError, ValueError) as e:
            conn.close()
            raise ReplicaUnavailableError(
                f"call to replica at {self.host}:{self.port} failed: {e}"
            ) from e
        self._pool.put(conn)
        return json.loads(buf.decode("utf-8"))

    def close(self) -> None:
        self._closed = True
        while True:
            try:
                self._pool.get_nowait().close()
            except queue.Empty:
                return
