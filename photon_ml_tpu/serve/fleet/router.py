"""Thin consistent-hash router over the serving-fleet replicas.

The router owns NO model state — just the
:class:`~photon_ml_tpu.serve.fleet.plan.ServeShardPlan` (bucket -> owner
lookup), the coordinate order from ``fleet.json``, and one client per
replica. Per request:

  1. **route** — each row's entity id maps to its slab-owner replica
     (plan lookup); each row's FIXED-effect contribution is computed by
     the row's "home" replica (the fixed vectors are replicated, so any
     live replica can serve them — a dead home just reroutes).
  2. **scatter** — one sub-request per involved replica, asking for the
     per-coordinate contribution arrays it can compute (fault site
     ``serve.replica_scatter``; a failed call is retried once on the same
     replica, then recovered: fixed parts reroute to a live replica,
     random parts degrade to the cold-entity 0 — never a hang).
  3. **gather + pinned-order sum** — contributions assemble into
     ``total = offset + fixed (store order) + random (store order)`` with
     eager f32 adds, the EXACT op order the single-store server and the
     batch scoring driver use — fleet scores are bitwise-equal to both.

Hedging: with ``hedge_ms`` set, a sub-request whose owner has not replied
within the hedge window fires a backup fixed-only request at another live
replica, bounding tail latency on the replicated half of the work.

Liveness rides the PR 5 heartbeat machinery: replicas write
``heartbeat-<r>.json`` (:class:`~photon_ml_tpu.parallel.multihost.
MultihostContext`), the router reads the ages and stops dispatching to a
replica whose heartbeat is stale — a killed replica is detected within the
heartbeat deadline and traffic keeps flowing in degraded mode.

Generations: every request is PINNED to the router's current generation
at submission (the PR 6 contract — a swap landing while the request is
queued does not move it) and scored entirely at that one generation; the
fleet swap flips the tag atomically for later submissions and fences
replica retirement on the old generation's drain. A replica that already
retired a generation answers ``stale_generation``, which re-scores the
whole request at the current one — mixed-generation scoring of a single
request is impossible.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor, TimeoutError as FutureTimeout
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from photon_ml_tpu.parallel.multihost import MultihostContext
from photon_ml_tpu.resilience import faults
from photon_ml_tpu.serve.fleet.plan import ServeShardPlan
from photon_ml_tpu.serve.fleet.replica import FIXED_PREFIX, RANDOM_PREFIX
from photon_ml_tpu.serve.fleet.transport import ReplicaUnavailableError
from photon_ml_tpu.serve.stats import FleetStats

logger = logging.getLogger(__name__)


class _StaleGeneration(Exception):
    """A replica already retired the generation this request was scattered
    at — re-score the WHOLE request at the current generation. Carries the
    replica's current epoch so the router can fast-forward."""

    def __init__(self, message: str, epoch: Optional[int] = None):
        super().__init__(message)
        self.epoch = epoch


class NoLiveReplicaError(OSError):
    """Every replica is dead (heartbeats stale / calls failing)."""


class FleetRouter:
    """Scatter/gather scoring over a replica fleet; duck-types the
    :func:`~photon_ml_tpu.serve.server.serve_json_lines` server surface
    (``submit_rows`` / ``drain`` / ``stats`` / ``new_request_compiles``)
    so the PR 6 JSON-lines loop fronts a fleet unchanged."""

    def __init__(
        self,
        fleet_meta: dict,
        clients: Sequence,
        heartbeat_dir: Optional[str] = None,
        heartbeat_deadline_s: float = 5.0,
        request_timeout_s: float = 30.0,
        hedge_ms: Optional[float] = None,
        failure_threshold: int = 2,
        probe_cooldown_s: float = 5.0,
        stats: Optional[FleetStats] = None,
        max_request_workers: int = 8,
    ):
        self.meta = fleet_meta
        self.plan = ServeShardPlan.from_json(fleet_meta["plan"])
        if len(clients) != self.plan.num_replicas:
            raise ValueError(
                f"{len(clients)} clients for a {self.plan.num_replicas}"
                "-replica plan"
            )
        self.clients = list(clients)
        self.num_replicas = self.plan.num_replicas
        self.fixed_names = [e["name"] for e in fleet_meta["fixed"]]
        self.random_coords = [
            (e["name"], e["re_id"]) for e in fleet_meta["random"]
        ]
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_deadline_s = heartbeat_deadline_s
        self.request_timeout_s = request_timeout_s
        self.hedge_s = hedge_ms / 1e3 if hedge_ms else None
        self.failure_threshold = failure_threshold
        self.probe_cooldown_s = probe_cooldown_s
        self.stats = stats if stats is not None else FleetStats()
        self._ctx = MultihostContext(
            process_id=0, num_processes=self.num_replicas
        )
        self._generation = 0
        self._gen_lock = threading.Lock()
        self._failures: Dict[int, int] = {}
        self._last_failure: Dict[int, float] = {}
        self._state_lock = threading.Lock()
        # two pools: request tasks scatter into the dispatch pool and WAIT
        # on its futures — sharing one pool would deadlock at saturation
        self._request_pool = ThreadPoolExecutor(
            max_workers=max_request_workers,
            thread_name_prefix="photon-fleet-request",
        )
        self._dispatch_pool = ThreadPoolExecutor(
            max_workers=2 * self.num_replicas + 4,
            thread_name_prefix="photon-fleet-scatter",
        )
        # hedged calls get their own pool: a dispatch-pool task must never
        # wait on futures queued into the dispatch pool (deadlock at
        # saturation)
        self._hedge_pool = ThreadPoolExecutor(
            max_workers=2 * self.num_replicas + 4,
            thread_name_prefix="photon-fleet-hedge",
        )
        self._outstanding = 0
        self._idle = threading.Event()
        self._idle.set()
        # per-generation in-flight counts (the PR 6 pinning, router form):
        # a request is tagged with the CURRENT generation at submission and
        # counted against it until it resolves, so the fleet swapper can
        # fence replica retirement on the old generation's drain instead of
        # pushing every queued request through the stale-rescore path
        self._gen_inflight: Dict[int, int] = {}
        self._gen_cond = threading.Condition(self._state_lock)
        self._closed = False

    # -- generation (the fleet swap flips this) ------------------------------
    @property
    def generation(self) -> int:
        return self._generation

    def flip_generation(self, epoch: int) -> None:
        with self._gen_lock:
            self._generation = epoch

    def _fast_forward(self, epoch: int) -> None:
        with self._gen_lock:
            if epoch > self._generation:
                self._generation = epoch

    def sync_generation(self, timeout: float = 5.0) -> int:
        """Adopt the fleet's current epoch (max over reachable replicas) —
        a freshly started router joining a long-lived fleet must not
        dispatch at generation 0 against replicas that already swapped.
        Best-effort: unreachable replicas are skipped (the stale-rescore
        fast-forward covers any replica this misses)."""
        for r, client in enumerate(self.clients):
            try:
                resp = client.call({"cmd": "ping"}, timeout=timeout)
                if resp.get("ok"):
                    self._fast_forward(int(resp.get("epoch") or 0))
            except (ReplicaUnavailableError, OSError, ValueError):
                continue
        return self._generation

    # -- liveness ------------------------------------------------------------
    def _record_failure(self, r: int) -> None:
        with self._state_lock:
            self._failures[r] = self._failures.get(r, 0) + 1
            self._last_failure[r] = time.monotonic()

    def _record_success(self, r: int) -> None:
        with self._state_lock:
            self._failures[r] = 0

    def live_replicas(self) -> Set[int]:
        """Replicas the router will dispatch to right now: heartbeat fresh
        (when a heartbeat dir is configured) and not circuit-broken by
        consecutive call failures (broken replicas are re-probed after a
        cooldown so a recovered process rejoins without intervention)."""
        now = time.monotonic()
        ages = (
            self._ctx.heartbeat_ages(self.heartbeat_dir)
            if self.heartbeat_dir
            else None
        )
        live: Set[int] = set()
        for r in range(self.num_replicas):
            if ages is not None:
                age = ages.get(r)
                if age is None or age > self.heartbeat_deadline_s:
                    self.stats.record_dead_replica_skip()
                    continue
            with self._state_lock:
                broken = self._failures.get(r, 0) >= self.failure_threshold
                recent = now - self._last_failure.get(r, 0.0)
            if broken and recent < self.probe_cooldown_s:
                self.stats.record_dead_replica_skip()
                continue
            live.add(r)
        return live

    # -- request surface -----------------------------------------------------
    def submit_rows(self, rows: List[dict]) -> Future:
        """Non-blocking fleet scoring; Future of (n,) f32 scores. The
        request is PINNED to the current generation here, at submission
        (the single server pins at featurize time — same contract): a swap
        that lands while this request is still queued does not move it."""
        gen = self._generation
        with self._state_lock:
            if self._closed:
                raise RuntimeError("router is closed")
            self._outstanding += 1
            self._idle.clear()
            self._gen_inflight[gen] = self._gen_inflight.get(gen, 0) + 1
        fut = self._request_pool.submit(self._score, rows, time.monotonic(), gen)
        fut.add_done_callback(lambda f, g=gen: self._on_done(g))
        return fut

    def _on_done(self, gen: int) -> None:
        with self._state_lock:
            self._outstanding -= 1
            if self._outstanding == 0:
                self._idle.set()
            left = self._gen_inflight.get(gen, 1) - 1
            if left <= 0:
                self._gen_inflight.pop(gen, None)
            else:
                self._gen_inflight[gen] = left
            self._gen_cond.notify_all()

    def drain_generation(self, gen: int, timeout: Optional[float] = None) -> bool:
        """Block until no request pinned to ``gen`` is in flight (the
        fleet swapper's fence before replicas retire that epoch)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._gen_cond:
            while self._gen_inflight.get(gen, 0) > 0:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._gen_cond.wait(remaining)
        return True

    def score_rows(self, rows: List[dict]) -> np.ndarray:
        if not rows:
            return np.zeros(0, np.float32)
        return self.submit_rows(rows).result()

    def drain(self, timeout: Optional[float] = None) -> bool:
        return self._idle.wait(timeout)

    def new_request_compiles(self) -> int:
        """Best-effort sum of the replicas' post-warmup compile counters
        (compiles happen on replicas; the router compiles nothing)."""
        total = 0
        for r in self.live_replicas():
            try:
                resp = self.clients[r].call({"cmd": "stats"}, timeout=5.0)
                total += int(resp.get("new_request_compiles") or 0)
            except (ReplicaUnavailableError, OSError, ValueError):
                continue
        return total

    def close(self) -> None:
        with self._state_lock:
            self._closed = True
        self._request_pool.shutdown(wait=True)
        self._dispatch_pool.shutdown(wait=True)
        self._hedge_pool.shutdown(wait=True)
        for c in self.clients:
            c.close()

    # -- scoring internals ---------------------------------------------------
    def _score(
        self, rows: List[dict], submitted: float,
        pinned_gen: Optional[int] = None,
    ) -> np.ndarray:
        faults.inject("serve.route", rows=len(rows))
        for _attempt in range(3):
            # first attempt honors the submission pin; a stale-generation
            # answer (the replica already retired that epoch) re-pins to
            # the current generation wholesale
            gen = (
                pinned_gen
                if _attempt == 0 and pinned_gen is not None
                else self._generation
            )
            try:
                scores = self._score_at(rows, gen)
                break
            except _StaleGeneration as stale:
                # the fleet swapped under this request (or this router just
                # started against an already-swapped fleet); fast-forward
                # and score wholesale at the current generation
                # (all-or-nothing — the request never mixes generations)
                if stale.epoch is not None:
                    self._fast_forward(stale.epoch)
                self.stats.record_stale_rescore()
        else:
            raise RuntimeError(
                "request kept racing fleet swaps (3 stale generations)"
            )
        self.stats.record_request(time.monotonic() - submitted, len(rows))
        return scores

    def _score_at(self, rows: List[dict], gen: int) -> np.ndarray:
        n = len(rows)
        offsets = np.asarray(
            [float(r.get("offset") or 0.0) for r in rows], np.float32
        )
        owners_by_coord = {
            name: self.plan.owners_of(
                [(r.get("ids") or {}).get(re_id) for r in rows]
            )
            for name, re_id in self.random_coords
        }
        live = self.live_replicas()
        if not live:
            raise NoLiveReplicaError(
                "no live replica (all heartbeats stale or circuit-broken)"
            )
        live_sorted = sorted(live)

        # home replica per row (fixed-effect owner): the first coordinate's
        # slab owner when live (contributions and entity rows then ride ONE
        # sub-request), else any live replica — fixed vectors are replicated
        home = np.full(n, -1, np.int32)
        for name, _re_id in self.random_coords:
            o = owners_by_coord[name]
            home = np.where(home < 0, o, home)
        for i in range(n):
            if home[i] < 0 or int(home[i]) not in live:
                if home[i] >= 0:
                    self.stats.record_reroute()
                home[i] = live_sorted[i % len(live_sorted)]

        # degraded rows: a coordinate whose owner is dead serves the
        # cold-entity fallback (contribution 0) instead of blocking
        degraded = 0
        for name, _re_id in self.random_coords:
            o = owners_by_coord[name]
            degraded += int(np.sum((o >= 0) & ~np.isin(o, live_sorted)))

        # per-replica sub-request: union of rows it serves, one message;
        # owned_counts tracks how many rows each coordinate REALLY owes
        # this replica (degradation accounting must not count home-only
        # rows that never carried a random contribution)
        plans = {}
        for r in live_sorted:
            need = home == r
            wants_random = []
            owned_counts = {}
            for name, _re_id in self.random_coords:
                mask = owners_by_coord[name] == r
                if mask.any():
                    wants_random.append(name)
                    owned_counts[name] = int(mask.sum())
                    need = need | mask
            idxs = np.flatnonzero(need)
            if len(idxs):
                plans[r] = {
                    "idxs": idxs,
                    "fixed": bool(np.any(home[idxs] == r)),
                    "random": wants_random,
                    "owned_counts": owned_counts,
                }
        self.stats.record_scatter(len(plans))

        futures = {
            r: self._dispatch_pool.submit(
                self._gather_replica, r, p, rows, gen, live_sorted
            )
            for r, p in plans.items()
        }
        results = {}
        deadline = time.monotonic() + self.request_timeout_s + 10.0
        for r, fut in futures.items():
            try:
                results[r] = fut.result(max(deadline - time.monotonic(), 0.1))
            except FutureTimeout:
                # a gather that outlives even the recovery budget degrades
                # exactly like a failed one (the task keeps running in the
                # background and is simply ignored) — the request must
                # answer, not hang or hard-fail
                self._record_failure(r)
                results[r] = None

        # per-coordinate degradation accounting: any owed contribution the
        # gather did not deliver (failed call, timeout, or a fixed-only
        # hedge answer) served the cold-entity 0 for its rows
        for r, p in plans.items():
            res = results.get(r)
            for name in p["random"]:
                if res is None or (RANDOM_PREFIX + name) not in res:
                    degraded += p["owned_counts"][name]
        if degraded:
            self.stats.record_degraded_rows(degraded)

        # pinned-order sum: offset, then fixed coordinates in store order,
        # then random coordinates in store order — eager f32 adds, the
        # exact op sequence ScoringServer._score_with / the batch driver
        # run, so fleet scores are bitwise-equal to both
        total = offsets
        for name in self.fixed_names:
            contrib = np.zeros(n, np.float32)
            for r, p in plans.items():
                res = results.get(r)
                if res is None or (FIXED_PREFIX + name) not in res:
                    continue
                vals = res[FIXED_PREFIX + name]
                mine = home[p["idxs"]] == r
                contrib[p["idxs"][mine]] = vals[mine]
            total = total + contrib
        for name, _re_id in self.random_coords:
            contrib = np.zeros(n, np.float32)
            o = owners_by_coord[name]
            for r, p in plans.items():
                res = results.get(r)
                if res is None or (RANDOM_PREFIX + name) not in res:
                    continue
                vals = res[RANDOM_PREFIX + name]
                mine = o[p["idxs"]] == r
                contrib[p["idxs"][mine]] = vals[mine]
            total = total + contrib
        return total

    def _dispatch(self, r: int, msg: dict) -> dict:
        faults.inject("serve.replica_scatter", replica=r)
        resp = self.clients[r].call(msg, timeout=self.request_timeout_s)
        if not resp.get("ok"):
            if resp.get("stale_generation"):
                raise _StaleGeneration(
                    resp.get("error", ""), epoch=resp.get("epoch")
                )
            raise ReplicaUnavailableError(
                f"replica {r} refused: {resp.get('error')}"
            )
        return resp

    def _gather_replica(
        self,
        r: int,
        p: dict,
        rows: List[dict],
        gen: int,
        live_sorted: List[int],
    ) -> Optional[Dict[str, np.ndarray]]:
        """One replica's contribution arrays (keyed like the wire, values
        (len(idxs),) f32), or None after full degradation. Never raises
        except :class:`_StaleGeneration` (whole-request re-score)."""
        sub_rows = [rows[i] for i in p["idxs"]]
        msg = {
            "cmd": "contribs",
            "epoch": gen,
            "rows": sub_rows,
            "fixed": p["fixed"],
            "random": p["random"],
        }
        resp = None
        from_primary = True
        try:
            if self.hedge_s is not None and p["fixed"]:
                resp, from_primary = self._call_hedged(
                    r, msg, sub_rows, live_sorted
                )
            else:
                resp = self._dispatch(r, msg)
        except _StaleGeneration:
            raise
        except (ReplicaUnavailableError, OSError, FutureTimeout):
            self._record_failure(r)
            # routed retry: one more attempt on the owner (it may have just
            # restarted or dropped one connection)
            try:
                self.stats.record_routed_retry()
                resp = self._dispatch(r, msg)
            except _StaleGeneration:
                raise
            except (ReplicaUnavailableError, OSError):
                self._record_failure(r)
                resp = None
        if resp is not None:
            if from_primary:
                self._record_success(r)
            else:
                # the owner never answered inside the deadline; the hedge's
                # fixed-only reply served — the slow owner counts as failed
                # (its random contributions degraded; the caller's per-
                # coordinate accounting sees the missing keys)
                self._record_failure(r)
            return {
                k: np.asarray(v, np.float32)
                for k, v in (resp.get("contribs") or {}).items()
            }
        # full degradation: random parts fall back to the cold-entity 0
        # (the caller's per-coordinate accounting records them); fixed
        # parts reroute to any live replica — the fixed vectors are
        # replicated, so the reroute is exact, not degraded
        out: Dict[str, np.ndarray] = {}
        if p["fixed"]:
            backup = next((b for b in live_sorted if b != r), None)
            if backup is not None:
                try:
                    self.stats.record_reroute()
                    bresp = self._dispatch(
                        backup,
                        {
                            "cmd": "contribs",
                            "epoch": gen,
                            "rows": sub_rows,
                            "fixed": True,
                            "random": [],
                        },
                    )
                    out = {
                        k: np.asarray(v, np.float32)
                        for k, v in (bresp.get("contribs") or {}).items()
                    }
                except (ReplicaUnavailableError, OSError):
                    self._record_failure(backup)
        return out or None

    def _call_hedged(
        self, r: int, msg: dict, sub_rows: List[dict],
        live_sorted: List[int],
    ) -> tuple:
        """Primary call with a fixed-only hedge: if the owner has not
        replied within the hedge window, a backup replica computes the
        replicated (fixed) half in parallel; the owner's reply still wins
        when it arrives (it carries the random parts the backup cannot
        compute). Returns ``(response, from_primary)``.

        Both calls run on the DEDICATED hedge pool: the caller is itself a
        dispatch-pool task, and nesting waits into that same pool would
        deadlock it at saturation (every worker blocked on a queued
        child)."""
        primary = self._hedge_pool.submit(self._dispatch, r, msg)
        try:
            return primary.result(self.hedge_s), True
        except FutureTimeout:
            pass
        backup = next((b for b in live_sorted if b != r), None)
        hedge = None
        if backup is not None:
            self.stats.record_hedge()
            hedge = self._hedge_pool.submit(
                self._dispatch,
                backup,
                {
                    "cmd": "contribs",
                    "epoch": msg["epoch"],
                    "rows": sub_rows,
                    "fixed": True,
                    "random": [],
                },
            )
        try:
            return primary.result(self.request_timeout_s), True
        except (ReplicaUnavailableError, OSError, FutureTimeout):
            if hedge is not None:
                try:
                    return (
                        hedge.result(max(self.request_timeout_s / 4, 1.0)),
                        False,
                    )
                except (ReplicaUnavailableError, OSError, FutureTimeout):
                    pass
            raise
