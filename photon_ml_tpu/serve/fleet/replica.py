"""One serving-fleet replica: a shard-store scoring engine + fleet hooks.

A replica is the PR 6 :class:`~photon_ml_tpu.serve.server.ScoringServer`
opened over its SHARDED store (its owned random-effect slab rows plus the
replicated fixed-effect vectors and feature maps) with three fleet-facing
extensions:

  * **per-coordinate contributions** (:meth:`ReplicaEngine.contribs`) —
    the router scatters sub-requests asking for exactly the contribution
    arrays this replica can compute (fixed effects: any replica; random
    effects: the slab owner). The math goes through the SAME instrumented
    kernels and ladder padding as full scoring, so warmed executables are
    reused and per-row results are bitwise what the single-store server
    computes for those rows.
  * **two-phase model roll** (:meth:`prepare` / :meth:`commit` /
    :meth:`abandon`) — the fleet-wide atomic swap splits the PR 6 swap
    into an epoch-tagged prepare (open + upload + probe the new store,
    watermark-asserted compile-free) and a commit (flip, retire the old
    epoch after its pinned requests drain). Between the phases BOTH epochs
    serve, so the router can flip the whole fleet atomically.
  * **heartbeats** — the PR 5 :class:`~photon_ml_tpu.parallel.multihost.
    MultihostContext` heartbeat writer runs on a background thread so the
    router (and any operator) can see replica liveness by file age.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.compile import compile_stats
from photon_ml_tpu.parallel.multihost import MultihostContext
from photon_ml_tpu.serve.model_store import ModelStore
from photon_ml_tpu.serve.server import ScoringServer

logger = logging.getLogger(__name__)

FIXED_PREFIX = "fixed:"
RANDOM_PREFIX = "random:"


class StaleGenerationError(RuntimeError):
    """A sub-request named an epoch this replica has already retired (the
    commit/scatter race). The router re-scores the whole request at the
    current epoch — all-or-nothing, so no request mixes generations."""


class ReplicaEngine(ScoringServer):
    """ScoringServer over a shard store + contribution/epoch/heartbeat
    surface for the fleet router."""

    def __init__(
        self,
        store: ModelStore,
        replica_id: int = 0,
        num_replicas: int = 1,
        heartbeat_dir: Optional[str] = None,
        heartbeat_interval_s: float = 1.0,
        drain_timeout_s: float = 60.0,
        **server_kwargs,
    ):
        super().__init__(store, **server_kwargs)
        self.replica_id = int(replica_id)
        self.num_replicas = int(num_replicas)
        self.drain_timeout_s = drain_timeout_s
        self._epoch = 0
        self._epoch_bundles = {0: self._model}
        self._staged: Optional[tuple] = None  # (epoch, bundle)
        self._epoch_lock = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if heartbeat_dir:
            ctx = MultihostContext(
                process_id=self.replica_id, num_processes=self.num_replicas
            )

            def beat() -> None:
                while not self._hb_stop.is_set():
                    try:
                        ctx.write_heartbeat(heartbeat_dir)
                    except OSError as e:
                        logger.warning(
                            "replica %d heartbeat failed: %s",
                            self.replica_id, e,
                        )
                    self._hb_stop.wait(heartbeat_interval_s)

            self._hb_thread = threading.Thread(
                target=beat,
                name=f"photon-fleet-heartbeat-{self.replica_id}",
                daemon=True,
            )
            self._hb_thread.start()

    # -- epoch bookkeeping ---------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._epoch

    def _bundle_for(self, epoch: Optional[int]):
        with self._epoch_lock:
            if epoch is None:
                epoch = self._epoch
            bundle = self._epoch_bundles.get(epoch)
            if bundle is None and self._staged is not None and self._staged[0] == epoch:
                # a prepared-but-not-yet-committed epoch is servable: the
                # router may flip its dispatch generation before this
                # replica's commit message lands
                bundle = self._staged[1]
            if bundle is None:
                raise StaleGenerationError(
                    f"replica {self.replica_id} has no epoch {epoch} "
                    f"(current {self._epoch})"
                )
            return bundle

    # -- contributions (the scatter target) ----------------------------------
    def contribs(
        self,
        rows: List[dict],
        want_fixed: bool,
        want_random: List[str],
        epoch: Optional[int] = None,
    ) -> Dict[str, np.ndarray]:
        """Per-coordinate contribution arrays for ``rows`` against one
        epoch's bundle: ``{"fixed:<name>": (n,) f32, "random:<name>":
        (n,) f32}``. Rows are chunked at ``max_batch_rows`` so every
        device call stays on a warmed ladder rung."""
        bundle = self._bundle_for(epoch)
        while not bundle.begin_request():
            bundle = self._bundle_for(epoch)  # raises once truly retired
        try:
            cap = self.batcher.max_batch_rows
            parts: List[Dict[str, np.ndarray]] = []
            for lo in range(0, len(rows), cap):
                chunk = rows[lo : lo + cap]
                batch = self.featurize(chunk, bundle)
                padded = batch.padded(self.bucketer)
                parts.append(
                    self._contrib_with(
                        bundle, padded, want_fixed, want_random, len(chunk)
                    )
                )
            if len(parts) == 1:
                return parts[0]
            return {
                k: np.concatenate([p[k] for p in parts]) for k in parts[0]
            }
        finally:
            bundle.end_request()

    def _contrib_with(
        self, bundle, batch, want_fixed: bool, want_random: List[str], n_real: int
    ) -> Dict[str, np.ndarray]:
        """One padded batch -> requested contribution arrays, through the
        exact kernels (and therefore executables) full scoring uses."""
        import jax
        import jax.numpy as jnp

        idx_dev = {s: jnp.asarray(a) for s, a in batch.shard_idx.items()}
        val_dev = {s: jnp.asarray(a) for s, a in batch.shard_val.items()}
        out: Dict[str, np.ndarray] = {}
        if want_fixed:
            for name, shard, w in bundle.fixed:
                c = self._fixed_kernel(w, idx_dev[shard], val_dev[shard])
                out[FIXED_PREFIX + name] = np.asarray(jax.device_get(c))[:n_real]
        if want_random:
            wanted = set(want_random)
            for name, _re_id, shard, slab, scales in bundle.random:
                if name in wanted:
                    c = self._re_contrib(
                        slab,
                        scales,
                        jnp.asarray(batch.ent_row[name]),
                        idx_dev[shard],
                        val_dev[shard],
                    )
                    out[RANDOM_PREFIX + name] = np.asarray(
                        jax.device_get(c)
                    )[:n_real]
        return out

    # -- two-phase fleet swap ------------------------------------------------
    def prepare(self, store_dir: str, epoch: int) -> dict:
        """Phase 1: open + upload + probe the new store as ``epoch``.
        Serving continues on the current epoch; the staged bundle also
        serves (the router may flip before commit lands). Raises (and
        leaves nothing staged) on any failure — the fleet swap aborts."""
        from photon_ml_tpu.serve.swap import ModelSwapper

        with self._epoch_lock:
            current = self._epoch
        if epoch <= current:
            # a HIGHER-than-next epoch is accepted (a restarted replica
            # rejoining a long-lived fleet adopts the fleet's sequence);
            # at-or-below-current would roll time backwards
            raise ValueError(
                f"replica {self.replica_id}: prepare epoch {epoch} is not "
                f"ahead of current epoch {current}"
            )
        new_store = ModelStore(store_dir)
        try:
            problems = ModelSwapper(self).validate_compatible(new_store)
            for p in problems:
                logger.warning(
                    "replica %d swap shape change: %s", self.replica_id, p
                )
            bundle = self._build_bundle(new_store)
            wm = compile_stats.watermark()
            self._probe_bundle(bundle)
            new_compiles = wm.new_traces()
        except BaseException:  # noqa: BLE001 — close-and-reraise: the staged store's mmaps must not leak on ANY prepare failure (incl. KeyboardInterrupt)
            new_store.close()
            raise
        with self._epoch_lock:
            if self._staged is not None:
                self._staged[1].store.close()
            self._staged = (epoch, bundle)
        return {
            "epoch": epoch,
            "new_compiles": int(new_compiles),
            "problems": problems,
        }

    def _probe_bundle(self, bundle) -> None:
        n = self._ladder_rungs(1, 1)[0] if self.bucketer else 1
        k = self.bucketer.canon(1) if self.bucketer else 1
        self._score_with(bundle, self._zero_batch(bundle, n, k))

    def commit(self, epoch: int) -> dict:
        """Phase 2: make the staged epoch current and retire the previous
        one once its pinned requests drain."""
        with self._epoch_lock:
            if self._staged is None or self._staged[0] != epoch:
                raise ValueError(
                    f"replica {self.replica_id}: no staged epoch {epoch} to "
                    "commit"
                )
            _, bundle = self._staged
            self._staged = None
            with self._swap_lock:
                old, self._model = self._model, bundle
            old_epoch = self._epoch
            self._epoch = epoch
            self._epoch_bundles[epoch] = bundle
        # gauges flip with the install (prepare must NOT record them —
        # an aborted swap's staged store never serves)
        self.stats.record_store_footprint(**bundle.store.footprint())
        self._retire(old_epoch, old)
        return {"epoch": epoch}

    def abandon(self) -> dict:
        """Drop a staged epoch (fleet swap aborted); current keeps serving."""
        with self._epoch_lock:
            staged, self._staged = self._staged, None
        if staged is not None:
            staged[1].store.close()
        return {"abandoned": staged[0] if staged is not None else None}

    def _retire(self, epoch: int, bundle) -> None:
        """Per-generation drain->retire fence (the PR 6 swapper's loop):
        once retire_if_idle returns True no new pin can land, so the old
        store's mmaps close safely."""
        deadline = time.monotonic() + self.drain_timeout_s
        retired = False
        while not retired:
            remaining = deadline - time.monotonic()
            if not bundle.drain(max(remaining, 0.0)):
                break
            retired = bundle.retire_if_idle()
        if retired:
            bundle.store.close()
            with self._epoch_lock:
                self._epoch_bundles.pop(epoch, None)
        else:
            logger.warning(
                "replica %d epoch %d still has in-flight requests after "
                "%.0fs; leaving its store open",
                self.replica_id, epoch, self.drain_timeout_s,
            )

    # ------------------------------------------------------------------
    def describe(self) -> str:
        return (
            f"replica {self.replica_id}/{self.num_replicas} epoch "
            f"{self._epoch}: {self.store.describe()}"
        )

    def close(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        with self._epoch_lock:
            staged, self._staged = self._staged, None
        if staged is not None:
            staged[1].store.close()
        super().close()
