"""Fleet-wide atomic model roll: a generation barrier over the PR 6 swap.

The single-server swap (serve/swap.py) flips one process's bundle pointer.
A fleet must flip TOGETHER — if replicas rolled independently, one
request's scatter could gather contributions from two model generations.
The barrier makes that impossible:

  1. **PREPARE (all replicas, in parallel)** — each replica opens the new
     generation's shard store, uploads its slabs, and probes a zero batch
     through the warmed executables (watermark-asserted compile-free,
     exactly the PR 6 probe). The old generation keeps serving throughout.
     ANY prepare failure aborts the whole swap: every staged bundle is
     abandoned and the fleet keeps serving the old generation — there is
     no partial state.
  2. **BARRIER** — fault site ``serve.fleet_swap_barrier`` fires between
     prepare-all-acked and the flip (the chaos tests' injection point: a
     barrier failure aborts exactly like a prepare failure).
  3. **FLIP + DRAIN + COMMIT** — the router's dispatch generation flips
     (one atomic int store: every request SUBMITTED after this instant
     carries the new tag, every request submitted before it stays pinned
     to the old tag end-to-end), the router drains the old generation's
     pinned requests, then each replica commits: staged becomes current,
     the old epoch retires. A replica whose commit message is slow keeps
     serving BOTH epochs meanwhile (staged bundles answer reads), so the
     flip is never blocked on a straggler.

Zero dropped requests holds by the same pinning argument as PR 6: an
old-generation request is pinned to old-epoch bundles on every replica it
touches, and retirement waits for the pins. A request that loses the race
entirely (scattered at G, arriving after G retired) is re-scored at the
current generation as a whole — degraded to one retry, never to a mix.
"""

from __future__ import annotations

import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from photon_ml_tpu.checkpoint import CheckpointRefError
from photon_ml_tpu.resilience import faults
from photon_ml_tpu.serve.fleet.plan import (
    ServeShardPlan,
    load_fleet_meta,
    replica_store_dir,
)
from photon_ml_tpu.serve.fleet.router import FleetRouter
from photon_ml_tpu.serve.fleet.transport import ReplicaUnavailableError

logger = logging.getLogger(__name__)


class FleetSwapError(CheckpointRefError):
    """The fleet swap aborted; the old generation is still serving
    everywhere (prepare is all-or-nothing)."""


class FleetSwapper:
    """Serialized fleet-wide rolls for one router."""

    def __init__(self, router: FleetRouter, prepare_timeout_s: float = 120.0):
        self.router = router
        self.prepare_timeout_s = prepare_timeout_s

    def swap(self, fleet_dir: str) -> dict:
        """Roll every replica to the sharded stores under ``fleet_dir``
        (a ``build_fleet_stores`` export) and flip the fleet atomically.

        Returns ``{"generation", "fleet_dir", "new_compiles",
        "dropped_requests", "problems", "commit_failures"}``; raises
        :class:`FleetSwapError` (old generation intact fleet-wide) on an
        incompatible plan, a prepare failure, or a barrier failure.
        """
        meta = load_fleet_meta(fleet_dir)  # refuses a mixed-dtype fleet
        new_plan = ServeShardPlan.from_json(meta["plan"])
        if not self.router.plan.same_assignment(new_plan):
            raise FleetSwapError(
                "refusing fleet swap: the new export's shard plan differs "
                "from the serving plan (slab ownership would diverge from "
                "routing — that is a re-shard, not a swap)"
            )
        cur_dtype = self.router.meta.get("store_dtype") or "f32"
        new_dtype = meta.get("store_dtype") or "f32"
        if cur_dtype != new_dtype:
            # a fleet-wide uniform dtype change is a legitimate roll, but
            # never a compile-free one: every replica's prepare probe
            # re-traces the gather kernels on the new slab dtype. Surface
            # it up front (the per-replica validate reports it too).
            logger.warning(
                "fleet swap changes store dtype %s -> %s: the prepare "
                "probes will compile the new gather executables",
                cur_dtype, new_dtype,
            )
        self._redrive_commits()
        epoch = self.router.generation + 1
        n = self.router.num_replicas

        # -- phase 1: prepare everywhere, old generation still serving ------
        prepared: List[int] = []
        problems: List[str] = []
        new_compiles = 0
        with ThreadPoolExecutor(max_workers=n) as pool:
            futs = {
                r: pool.submit(
                    self.router.clients[r].call,
                    {
                        "cmd": "prepare",
                        "store_dir": replica_store_dir(fleet_dir, r),
                        "epoch": epoch,
                    },
                    self.prepare_timeout_s,
                )
                for r in range(n)
            }
            failure: Optional[str] = None
            for r, fut in futs.items():
                try:
                    resp = fut.result(self.prepare_timeout_s + 10.0)
                except Exception as e:  # noqa: BLE001 — swap fence: ANY prepare failure aborts the whole roll below
                    failure = f"replica {r} prepare failed: {e}"
                    continue
                if not resp.get("ok"):
                    failure = f"replica {r} prepare refused: {resp.get('error')}"
                    continue
                prepared.append(r)
                new_compiles += int(resp.get("new_compiles") or 0)
                problems.extend(
                    f"replica {r}: {p}" for p in resp.get("problems") or []
                )
        if failure is None:
            # -- barrier: the chaos injection point between the phases ------
            try:
                faults.inject("serve.fleet_swap_barrier", epoch=epoch)
            except OSError as e:
                failure = f"fleet swap barrier failed: {e}"
        if failure is not None:
            self._abandon(prepared)
            raise FleetSwapError(
                f"fleet swap aborted ({failure}); old generation "
                f"{self.router.generation} still serving on all replicas"
            )

        # -- phase 2: flip the router, drain the old generation's pinned
        # requests (they were tagged at submission; replicas must not
        # retire the old epoch under them), then commit every replica ------
        old_epoch = self.router.generation
        self.router.flip_generation(epoch)
        # the fleet now serves the new export everywhere: adopt its meta
        # wholesale (dtype, per-coordinate quantization budgets, replica
        # store dirs) — the plan is already enforced identical above
        self.router.meta = meta
        if not self.router.drain_generation(old_epoch, self.prepare_timeout_s):
            # stragglers fall back to the stale-rescore safety net (the
            # request re-scores wholesale at the current generation) —
            # degraded to one retry, never to a mixed-generation score
            logger.warning(
                "old generation %d still has pinned requests after %.0fs; "
                "committing anyway (stragglers re-score at generation %d)",
                old_epoch, self.prepare_timeout_s, epoch,
            )
        commit_failures: List[str] = []
        for r in range(n):
            try:
                resp = self.router.clients[r].call(
                    {"cmd": "commit", "epoch": epoch},
                    self.prepare_timeout_s,
                )
                if not resp.get("ok"):
                    commit_failures.append(
                        f"replica {r}: {resp.get('error')}"
                    )
            except (ReplicaUnavailableError, OSError) as e:
                # the staged epoch still serves reads on that replica; the
                # commit (retire-the-old-epoch) can be re-driven later
                commit_failures.append(f"replica {r}: {e}")
        for msg in commit_failures:
            logger.warning("fleet swap commit straggler: %s", msg)
        report = {
            "generation": epoch,
            "fleet_dir": fleet_dir,
            "new_compiles": int(new_compiles),
            "dropped_requests": 0,
            "problems": problems,
            "commit_failures": commit_failures,
        }
        self.router.stats.record_swap(int(new_compiles))
        logger.info(
            "fleet swap -> generation %d (%d replicas, %d new compiles, "
            "%d commit stragglers)",
            epoch, n, new_compiles, len(commit_failures),
        )
        return report

    def rollout_delta(
        self, fleet_dir: str, retrain_dir: Optional[str] = None
    ) -> dict:
        """Roll a DELTA retrain's fleet export through the generation
        barrier as one atomic swap — the last arc of the daily loop
        (retrain → re-shard → export → fleet swap).

        Beyond :meth:`swap`, this validates the provenance seam first:
        ``fleet_dir``'s export must trace back to the retrain run's saved
        model (``retrain_dir``'s committed ``retrain.json``), so a fleet
        cannot atomically adopt an export built from some OTHER model than
        the retrain it claims to roll out. Fault site
        ``serve.fleet_delta_rollout`` fires between validation and the
        swap (the chaos tests' injection point); any failure — injected or
        real — aborts with the old generation intact everywhere, exactly
        like a prepare failure. A mid-swap replica loss inside the
        delegated :meth:`swap` aborts the same way.
        """
        failure: Optional[str] = None
        if retrain_dir is not None:
            from photon_ml_tpu.retrain.manifest import RetrainManifest

            try:
                rman = RetrainManifest.load(retrain_dir)
            except (OSError, ValueError, KeyError) as e:
                failure = (
                    f"retrain dir {retrain_dir} has no committed "
                    f"retrain.json ({e}) — the retrain did not finish; "
                    "nothing to roll out"
                )
            else:
                exported = load_fleet_meta(fleet_dir).get("source_model_dir")
                want = os.path.abspath(rman.model_dir)
                if exported is None or os.path.abspath(exported) != want:
                    failure = (
                        f"fleet export {fleet_dir} was built from "
                        f"{exported}, not the delta retrain's saved model "
                        f"{want} — refusing to roll out a mismatched model"
                    )
        if failure is None:
            try:
                faults.inject(
                    "serve.fleet_delta_rollout",
                    fleet_dir=fleet_dir, retrain_dir=retrain_dir,
                )
            except OSError as e:
                failure = f"delta rollout entry failed: {e}"
        if failure is not None:
            raise FleetSwapError(
                f"delta rollout aborted ({failure}); old generation "
                f"{self.router.generation} still serving on all replicas"
            )
        report = self.swap(fleet_dir)
        report["rollout"] = "delta"
        report["retrain_dir"] = retrain_dir
        return report

    def _redrive_commits(self) -> None:
        """Re-send commit to any replica still behind the router's
        generation (a commit message lost to a transient network blip must
        not wedge every future swap — the straggler's staged bundle is
        still there, serving reads, waiting to be committed)."""
        gen = self.router.generation
        if gen == 0:
            return
        for r, client in enumerate(self.router.clients):
            try:
                resp = client.call({"cmd": "ping"}, 10.0)
                if resp.get("ok") and int(resp.get("epoch") or 0) < gen:
                    logger.warning(
                        "re-driving commit(%d) on lagging replica %d "
                        "(at epoch %s)", gen, r, resp.get("epoch"),
                    )
                    client.call({"cmd": "commit", "epoch": gen}, 30.0)
            except (ReplicaUnavailableError, OSError, ValueError):
                # an unreachable replica fails the upcoming prepare, which
                # aborts the swap with the honest diagnosis
                continue

    def _abandon(self, prepared: List[int]) -> None:
        for r in prepared:
            try:
                self.router.clients[r].call({"cmd": "abandon"}, 30.0)
            except (ReplicaUnavailableError, OSError) as e:
                logger.warning(
                    "abandon after aborted swap failed on replica %d: %s",
                    r, e,
                )
