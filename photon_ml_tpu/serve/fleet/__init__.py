"""Sharded serving fleet: billion-coefficient GAME models behind a thin
consistent-hash router.

The paper's headline scale — hundreds of billions of coefficients — cannot
fit one replica's mmap'd store (PR 6). This package partitions the model
the same way PR 9 partitions training (deterministic balanced entity
blocking) and serves it owner-computes:

  * :mod:`.plan` — :class:`ServeShardPlan` (stable entity hash -> bucket
    -> balanced owner replica; the explicit placement object) and
    :func:`build_fleet_stores` (one sharded store per replica: owned
    random-effect slab rows + replicated fixed effects and feature maps).
  * :mod:`.replica` — :class:`ReplicaEngine`, the PR 6 ScoringServer over
    a shard store plus per-coordinate contribution scoring, the two-phase
    (prepare/commit) epoch roll, and PR 5 heartbeats.
  * :mod:`.transport` — JSON-lines protocol shared by the in-process
    client (tier-1 fast path) and the threaded TCP server/client the
    multi-process harness and bench use.
  * :mod:`.router` — :class:`FleetRouter`: consistent-hash scatter,
    hedged sub-requests, heartbeat liveness, degradation instead of
    hangs, and the pinned-order gather-sum that keeps fleet scores
    bitwise-equal to the single-store server and the batch driver.
  * :mod:`.swap` — :class:`FleetSwapper`: the fleet-wide atomic
    generation barrier (prepare-all -> flip -> commit; no mixed
    generations, zero new compiles, zero dropped requests).

Driver: ``photon_ml_tpu.cli.fleet_driver`` (build-stores / replica /
router modes); bench section ``serving_fleet``.
"""

from __future__ import annotations

from photon_ml_tpu.serve.fleet.plan import (
    DEFAULT_NUM_BUCKETS,
    ServeShardPlan,
    build_fleet_stores,
    is_fleet_dir,
    load_fleet_meta,
    replica_store_dir,
)
from photon_ml_tpu.serve.fleet.replica import ReplicaEngine, StaleGenerationError
from photon_ml_tpu.serve.fleet.router import FleetRouter, NoLiveReplicaError
from photon_ml_tpu.serve.fleet.swap import FleetSwapError, FleetSwapper
from photon_ml_tpu.serve.fleet.transport import (
    LocalReplicaClient,
    ReplicaServer,
    ReplicaUnavailableError,
    TcpReplicaClient,
)

__all__ = [
    "DEFAULT_NUM_BUCKETS",
    "FleetRouter",
    "FleetSwapError",
    "FleetSwapper",
    "LocalReplicaClient",
    "NoLiveReplicaError",
    "ReplicaEngine",
    "ReplicaServer",
    "ReplicaUnavailableError",
    "ServeShardPlan",
    "StaleGenerationError",
    "TcpReplicaClient",
    "build_fleet_stores",
    "is_fleet_dir",
    "load_fleet_meta",
    "replica_store_dir",
]
