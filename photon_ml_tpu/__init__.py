"""photon-ml-tpu: a TPU-native framework for Generalized Linear Models and
Generalized Additive Mixed Effect (GAME / GLMix) models.

A ground-up JAX/XLA re-design of the capabilities of LinkedIn's photon-ml
(Spark/Scala, reference layer map in SURVEY.md): GLM training (linear,
logistic, Poisson regression and smoothed-hinge linear SVM) with LBFGS /
OWL-QN / TRON optimizers, and GAME coordinate descent over fixed-effect,
per-entity random-effect, and factored (matrix-factorization) coordinates.

Design principles (TPU-first, not a port):
  * all hot math is jit-compiled XLA: objectives are pure functions,
    optimizers are ``lax.while_loop`` kernels with fixed-shape carried state;
  * data parallelism = batch sharding over a ``jax.sharding.Mesh`` with
    XLA-inserted (or explicit ``psum``) collectives — replacing Spark
    ``treeAggregate``/``broadcast``;
  * entity parallelism (random effects) = entities bucketed into padded
    ``(entities, samples, dims)`` tensors sharded over the mesh, with the
    local solver ``vmap``-ed across entities — replacing RDD joins;
  * host-side ingest produces a deterministic, device-ready columnar layout —
    replacing RDD lineage.
"""

from photon_ml_tpu.types import TaskType

__version__ = "0.1.0"

# lazy convenience exports (PEP 562): the common entry points are reachable
# as photon_ml_tpu.<Name> without paying their import cost (jax tracing,
# optimizer kernels) at package-import time — CLI startup stays light
_LAZY = {
    "OptimizerType": "photon_ml_tpu.types",
    "ConvergenceReason": "photon_ml_tpu.types",
    "OptimizerConfig": "photon_ml_tpu.optim.common",
    "GLMOptimizationProblem": "photon_ml_tpu.optim.problem",
    "RegularizationContext": "photon_ml_tpu.ops.regularization",
    "NormalizationContext": "photon_ml_tpu.ops.normalization",
    "GLMBatch": "photon_ml_tpu.ops.objective",
    "DenseFeatures": "photon_ml_tpu.ops.features",
    "SparseFeatures": "photon_ml_tpu.ops.features",
    "GeneralizedLinearModel": "photon_ml_tpu.models.glm",
    "Coefficients": "photon_ml_tpu.models.glm",
    "CoordinateDescent": "photon_ml_tpu.algorithm",
    "FixedEffectCoordinate": "photon_ml_tpu.algorithm",
    "RandomEffectCoordinate": "photon_ml_tpu.algorithm",
    "area_under_roc_curve": "photon_ml_tpu.evaluation",
    "read_libsvm": "photon_ml_tpu.io.libsvm",
    "to_batch": "photon_ml_tpu.io.libsvm",
    "train_glm_grid": "photon_ml_tpu.training",
    "MeshContext": "photon_ml_tpu.parallel",
    "data_mesh": "photon_ml_tpu.parallel",
    "ResilienceConfig": "photon_ml_tpu.resilience",
    "RetryPolicy": "photon_ml_tpu.resilience",
    "DivergenceGuard": "photon_ml_tpu.resilience",
    "FaultPlan": "photon_ml_tpu.resilience",
    "FaultSpec": "photon_ml_tpu.resilience",
    "fault_scope": "photon_ml_tpu.resilience",
    "resilience_scope": "photon_ml_tpu.resilience",
}

__all__ = ["TaskType", "__version__", *sorted(_LAZY)]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
