"""photon-ml-tpu: a TPU-native framework for Generalized Linear Models and
Generalized Additive Mixed Effect (GAME / GLMix) models.

A ground-up JAX/XLA re-design of the capabilities of LinkedIn's photon-ml
(Spark/Scala, reference layer map in SURVEY.md): GLM training (linear,
logistic, Poisson regression and smoothed-hinge linear SVM) with LBFGS /
OWL-QN / TRON optimizers, and GAME coordinate descent over fixed-effect,
per-entity random-effect, and factored (matrix-factorization) coordinates.

Design principles (TPU-first, not a port):
  * all hot math is jit-compiled XLA: objectives are pure functions,
    optimizers are ``lax.while_loop`` kernels with fixed-shape carried state;
  * data parallelism = batch sharding over a ``jax.sharding.Mesh`` with
    XLA-inserted (or explicit ``psum``) collectives — replacing Spark
    ``treeAggregate``/``broadcast``;
  * entity parallelism (random effects) = entities bucketed into padded
    ``(entities, samples, dims)`` tensors sharded over the mesh, with the
    local solver ``vmap``-ed across entities — replacing RDD joins;
  * host-side ingest produces a deterministic, device-ready columnar layout —
    replacing RDD lineage.
"""

from photon_ml_tpu.types import TaskType

__version__ = "0.1.0"

__all__ = ["TaskType", "__version__"]
