"""Dimensionality projectors for random-effect feature spaces.

Reference spec: projector/ProjectionMatrix.scala:31-119 (dense Gaussian
random projection: entries ~ N(0, 1)/k clipped to [-1, 1], optional dummy
intercept row selecting the last original column; projectFeatures = M @ x,
projectCoefficients = M.T @ c i.e. projected -> original),
projector/RandomEffectProjector.scala:35-77 (factory over ProjectorType),
projector/ProjectionMatrixBroadcast.scala:30-96 (shared matrix applied per
datum — here one dense matmul over the whole batch),
model/RandomEffectModelInProjectedSpace.scala:83 (project model coefficients
back for scoring).

TPU-native: the matrix is replicated (the pjit analogue of a Spark
broadcast); feature projection is a single (N, d) @ (d, k) matmul that XLA
tiles onto the MXU, and coefficient back-projection for a whole stacked
random-effect model is one (E, k) @ (k, d) matmul. The INDEX_MAP projector
(per-entity gather indices) lives in data/game.py where the entity tensors
are built; IDENTITY is the absence of projection.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.types import ProjectorType, real_dtype

Array = jax.Array

# MathConst.scala:24
RANDOM_SEED = 1234567890


def gaussian_random_projection_matrix(
    projected_dim: int,
    original_dim: int,
    keep_intercept: bool = True,
    seed: int = RANDOM_SEED,
) -> np.ndarray:
    """Dense Gaussian random projection matrix, reference semantics.

    Entries are drawn N(0, 1), divided by ``projected_dim`` (the reference
    deliberately uses std = k rather than sqrt(k) to keep magnitudes small,
    ProjectionMatrix.scala:96-99) and clipped to [-1, 1]. With
    ``keep_intercept`` a final row is appended that passes the last original
    column (the intercept) through untouched, so the output has
    ``projected_dim + 1`` rows.
    """
    rng = np.random.default_rng(seed)
    m = rng.standard_normal((projected_dim, original_dim)) / float(projected_dim)
    m = np.clip(m, -1.0, 1.0).astype(real_dtype())
    if keep_intercept:
        intercept_row = np.zeros((1, original_dim), real_dtype())
        intercept_row[0, original_dim - 1] = 1.0
        m = np.concatenate([m, intercept_row], axis=0)
    return m


@dataclasses.dataclass(frozen=True)
class ProjectionMatrixProjector:
    """Shared dense projection matrix, replicated across the mesh.

    ``matrix`` has shape (k, d): k = projected-space dim (incl. intercept
    row when kept), d = original-space dim.
    """

    matrix: Array  # (k, d)

    @property
    def projected_dim(self) -> int:
        return self.matrix.shape[0]

    @property
    def original_dim(self) -> int:
        return self.matrix.shape[1]

    def project_features(self, features: Array) -> Array:
        """(..., d) -> (..., k): batched M @ x as one MXU matmul."""
        return features @ self.matrix.T

    def project_sparse_features(
        self, indices: np.ndarray, values: np.ndarray, row_splits: np.ndarray
    ) -> np.ndarray:
        """Host-side CSR rows -> dense projected (N, k) without densifying
        the original d-wide matrix: gather the needed columns of M."""
        mat = np.asarray(self.matrix)
        n = len(row_splits) - 1
        out = np.zeros((n, mat.shape[0]), real_dtype())
        rows = np.repeat(np.arange(n), np.diff(row_splits))
        contrib = mat[:, indices].T * values[:, None]  # (nnz, k)
        np.add.at(out, rows, contrib)
        return out

    def project_coefficients(self, coefficients: Array) -> Array:
        """Projected-space coefficients (..., k) -> original space (..., d).

        One matmul for a whole stacked random-effect model
        (RandomEffectModelInProjectedSpace.toRandomEffectModel analogue).
        """
        return coefficients @ self.matrix

    def to_summary_string(self) -> str:
        flat = np.asarray(self.matrix).ravel()
        return (
            f"ProjectionMatrix(k={self.projected_dim}, d={self.original_dim}): "
            f"mean={flat.mean():.3e} var={flat.var():.3e} l2={np.linalg.norm(flat):.3e}"
        )


def build_projector(
    projector_type: ProjectorType,
    original_dim: int,
    projected_dim: Optional[int] = None,
    keep_intercept: bool = True,
    seed: int = RANDOM_SEED,
) -> Optional[ProjectionMatrixProjector]:
    """Factory mirroring RandomEffectProjector.buildRandomEffectProjector
    (projector/RandomEffectProjector.scala:54-77): RANDOM -> Gaussian matrix
    projector; INDEX_MAP / IDENTITY -> None (handled structurally by the
    random-effect dataset build)."""
    if projector_type == ProjectorType.RANDOM:
        if projected_dim is None:
            raise ValueError("RANDOM projector requires projected_dim")
        m = gaussian_random_projection_matrix(projected_dim, original_dim, keep_intercept, seed)
        return ProjectionMatrixProjector(jnp.asarray(m))
    return None
