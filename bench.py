"""Benchmark driver: GLM training throughput on the current accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: L2 logistic regression value+gradient passes (the hot loop of GLM
training — the reference's ValueAndGradientAggregator treeAggregate,
SURVEY.md §2.2) on a synthetic dense dataset sized like a realistic ads/feed
shard: N=262144 examples x D=512 features, bf16 matmul inputs with f32
accumulation semantics via XLA default.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is a single-host NumPy implementation of the identical computation
measured in-process (a stand-in for the reference's JVM/Breeze per-partition
CPU path, which it bounds from above). Values > 1 mean faster than baseline.
"""

import json
import sys
import time

import numpy as np


def _numpy_baseline(x, y, w, iters=3):
    t0 = time.perf_counter()
    for _ in range(iters):
        z = x @ w
        s = 1.0 / (1.0 + np.exp(-z))
        val = np.sum(np.maximum(z, 0) + np.log1p(np.exp(-np.abs(z))) - y * z)
        g = (s - y) @ x
        g = g + 0.1 * w
        val = val + 0.05 * np.sum(w * w)
    dt = (time.perf_counter() - t0) / iters
    return x.shape[0] / dt, float(val), g


def main():
    n, d = 262144, 512
    rng = np.random.default_rng(0)
    x_h = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32) * 0.1
    y_h = (1.0 / (1.0 + np.exp(-x_h @ w_true)) > rng.random(n)).astype(np.float32)

    base_eps, _, _ = _numpy_baseline(x_h, y_h, np.zeros(d, np.float32))

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.features import DenseFeatures
    from photon_ml_tpu.ops.normalization import NormalizationContext
    from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", file=sys.stderr)

    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x_h)), jnp.asarray(y_h))
    batch = jax.device_put(batch, dev)
    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()

    vg = jax.jit(lambda w: obj.value_and_grad(w, batch, norm, 0.1))
    w = jnp.zeros((d,), jnp.float32)

    # warmup + compile
    v, g = vg(w)
    jax.block_until_ready((v, g))

    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        v, g = vg(w)
    jax.block_until_ready((v, g))
    dt = (time.perf_counter() - t0) / iters
    eps = n / dt

    print(f"tpu: {eps:.3e} ex/s  baseline(numpy): {base_eps:.3e} ex/s", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "glm_logistic_value_and_grad_throughput",
                "value": round(eps, 1),
                "unit": "examples/sec/chip",
                "vs_baseline": round(eps / base_eps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
