"""Benchmark driver: GLM/GAME training throughput on the current accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

and ALWAYS prints it — backend init is retried with backoff, every
sub-benchmark is individually fenced, and any failure degrades to an
``errors`` field instead of erasing the round's perf record (a flaky
single-client device tunnel must never zero out a round).

Sub-benchmarks:
  1. Dense GLM hot loop (primary metric): L2 logistic value+gradient passes
     (the reference's ValueAndGradientAggregator treeAggregate, SURVEY.md
     §2.2) on N=262144 x D=512, bfloat16 feature storage. The path is
     AUTOTUNED at runtime: the single-pass fused Pallas kernel
     (ops/fused_glm.py) races the two-pass XLA pipeline on the live device
     and the winner is measured.
  2. Sparse-wide regime: D=1,048,576 features, 64 nnz/row through
     SparseFeatures (the reference's actual production shape — ~2M features,
     Driver.scala:334) — gather + segment-sum margins, scatter-add gradient.
  3. GAME coordinate descent: fixed + per-entity random effect logistic
     GLMix on synthetic data (20k entities), sec per coordinate-descent
     iteration (CoordinateDescent.scala:112-203 analogue), with the
     training AUC the timed model reaches.
  4. Full-GAME (BASELINE config-5 shape): fixed + per-user + per-item REs
     + a factored per-artist MF coordinate through the fused cycle.

Methodology: iterations are serialized ON-CHIP via ``lax.scan`` with a
gradient-dependent weight update, so the measured time is real sequential
compute — host-loop timing over an RPC tunnel pipelines/caches dispatches
and reports physically impossible rates. (GAME is host-orchestrated like
the real driver, timed over full iterations with a blocking fence.)

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is a single-host NumPy implementation of the identical dense
computation measured in-process (a stand-in for the reference's JVM/Breeze
per-partition CPU path, which it bounds from above). Values > 1 mean
faster than baseline.
"""

import json
import os
import sys
import time
import traceback

import numpy as np

# test-fixture generators (game_test_utils) are imported by the GAME
# benches; anchor to this file so bench.py runs from any cwd
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))

SCAN_ITERS = 50
STEP = 1e-6
METRIC = "glm_logistic_value_and_grad_throughput"
UNIT = "examples/sec/chip"

N_DENSE, D_DENSE = 262144, 512
N_SPARSE, D_SPARSE, K_SPARSE = 131072, 1 << 20, 64
HBM_PEAK_GB_S = 819  # TPU v5e HBM bandwidth (public spec)


def _emit(payload):
    print(json.dumps(payload))
    sys.stdout.flush()


def _log(msg):
    print(msg, file=sys.stderr)
    sys.stderr.flush()


def _probe_backend(errors, timeout_s):
    """Try backend init in a THROWAWAY subprocess — and NEVER kill it.

    A flaky tunnel can HANG inside PJRT client creation (not just raise),
    and a hang in-process is unrecoverable — so the accelerator is only
    touched in-process after a subprocess proved it comes up. CRITICAL
    (r3 postmortem): a timeout-KILLED probe can orphan the single-client
    tunnel's server-side session claim and wedge the tunnel for every later
    process. So on deadline the probe is DETACHED, not killed — it exits on
    its own (hung claims resolve server-side in ~25 min) and releases
    whatever it held. Returns the platform string or None."""
    import subprocess
    import tempfile

    out_f = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".out", prefix="tpu-probe-", delete=False
    )
    err_f = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".err", prefix="tpu-probe-", delete=False
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
        stdout=out_f,
        stderr=err_f,  # separate: teardown/warning logs must not be read
        # as the platform name (stdout's last line is the contract)
        start_new_session=True,  # survives the bench; never reparented-killed
    )
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(2)
    if proc.poll() is None:
        # keep the files: the detached child is still writing to them
        out_f.close()
        err_f.close()
        errors.setdefault("backend_attempts", []).append(
            f"no answer in {timeout_s}s; probe left running (pid {proc.pid}, "
            "never killed — see r3 claim-orphan postmortem)"
        )
        return None
    out_f.seek(0)
    text = out_f.read().strip()
    err_f.seek(0)
    err_text = err_f.read().strip()
    out_f.close()
    err_f.close()
    os.unlink(out_f.name)
    os.unlink(err_f.name)
    if proc.returncode != 0:
        errors.setdefault("backend_attempts", []).append(
            " | ".join(err_text.splitlines()[-3:] or text.splitlines()[-3:])
        )
        return None
    lines = [l for l in text.splitlines() if l.strip()]
    return lines[-1] if lines else None


def _probe_platform(errors):
    """Probe the accelerator in throwaway subprocesses with backoff; returns
    the platform string or None (VERDICT r2 weak #1: degrade, never hang)."""
    attempts = ((0, 240), (10, 150), (30, 150))
    platform = None
    for delay, timeout_s in attempts:
        if delay:
            _log(f"backend probe failed; retrying in {delay}s")
            time.sleep(delay)
        platform = _probe_backend(errors, timeout_s)
        if platform is not None:
            break
    return platform


def _numpy_baseline(x, y, w, iters=3):
    t0 = time.perf_counter()
    for _ in range(iters):
        z = x @ w
        s = 1.0 / (1.0 + np.exp(-z))
        val = np.sum(np.maximum(z, 0) + np.log1p(np.exp(-np.abs(z))) - y * z)
        g = (s - y) @ x
        g = g + 0.1 * w
        val = val + 0.05 * np.sum(w * w)
        w = w - STEP * g  # same dependency chain as the device loop
    dt = (time.perf_counter() - t0) / iters
    return x.shape[0] / dt, float(val), g


def _scan_throughput(value_and_grad, w0, n_rows, batch, iters=SCAN_ITERS):
    """examples/sec with iterations serialized on-chip via lax.scan.

    ``batch`` MUST flow in as a jit argument, never a closure capture: a
    captured array is inlined into the HLO as a literal constant, and over
    the remote-compile tunnel a 256 MB feature matrix in the request body
    gets rejected with HTTP 413 (observed r3) — args stay device-side.
    """
    import jax
    from jax import lax

    def run(w, b):
        def step(w, _):
            v, g = value_and_grad(w, b)
            return w - STEP * g, v

        return lax.scan(step, w, None, length=iters)

    scan = jax.jit(run)  # jit-ok: bench harness; carries reused across timed reps
    w1 = jax.block_until_ready(scan(w0, batch))[0]  # compile + warm
    # the timed call gets the warm call's carry, NOT w0 again: an identical
    # repeat could be served by a caching execution layer over the remote
    # tunnel (see fused_glm._time_value_and_grad)
    t0 = time.perf_counter()
    jax.block_until_ready(scan(w1, batch))
    dt = (time.perf_counter() - t0) / iters
    return n_rows / dt


def _bench_dense(extra, x_h, y_h, on_tpu=True):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops import fused_glm, losses
    from photon_ml_tpu.ops.features import DenseFeatures
    from photon_ml_tpu.ops.normalization import NormalizationContext
    from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective

    n, d = x_h.shape
    labels = jnp.asarray(y_h)
    feats_f32 = DenseFeatures(jnp.asarray(x_h))
    feats_bf16 = feats_f32.astype(jnp.bfloat16) if on_tpu else None
    # storage dtype is a PLATFORM choice: bf16 halves HBM traffic on TPU
    # (the hot loop is bandwidth-bound there), but CPUs have no native
    # bf16 — the emulation costs ~27% measured — so the CPU fallback
    # stores f32 (the same choice production ingest would make)
    store_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    feats_store = feats_bf16 if on_tpu else feats_f32
    norm = NormalizationContext.identity()

    # numerical parity gate at a NONZERO weight vector (w=0 would zero the
    # margins and leave the matvec path untested)
    rng = np.random.default_rng(7)
    w_probe = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.1)
    obj_plain = GLMObjective(losses.logistic)

    def vg(feats, w):
        return obj_plain.value_and_grad(w, GLMBatch.create(feats, labels), norm, 0.1)

    v32, g32 = jax.jit(vg)(feats_f32, w_probe)  # jit-ok: one-shot parity probe
    if on_tpu:
        # the bf16 parity gate guards the dtype the TPU measurement USES;
        # the CPU fallback stores f32, so emulated-bf16 divergence there
        # must not abort the bench
        v16, g16 = jax.jit(vg)(feats_bf16, w_probe)  # jit-ok: one-shot parity probe
        rel_v = abs(float(v16) - float(v32)) / max(abs(float(v32)), 1e-12)
        rel_g = float(jnp.linalg.norm(g16 - g32) / jnp.maximum(jnp.linalg.norm(g32), 1e-12))
        _log(f"bf16 parity: value rel {rel_v:.2e}, grad rel {rel_g:.2e}")
        if rel_v > 5e-2 or rel_g > 5e-2:
            raise AssertionError(f"bf16 storage diverged from f32 path ({rel_v}, {rel_g})")

    # runtime autotune: single-pass Pallas kernel families vs two-pass XLA.
    # The race is DIAGNOSTIC — a flaky remote-compile endpoint (r5: HTTP
    # transport error 53 min into the race) must not cost the headline
    # measurement, so any failure degrades to the plain XLA path.
    try:
        # ONE autotune race: the selected block AND the published
        # per-candidate record come from the same autotune_report call, so
        # the dense_race evidence always describes the winner actually used
        # (a second race could flip the ordering on a noisy tunnel and
        # publish a winner that differs from the measured block — ADVICE.md)
        report = fused_glm.autotune_report(losses.logistic, n, d, store_dtype)
        block = report["winner"]
        if on_tpu and report["candidates"]:
            # r5 phase-2 postmortem: garbage microsecond timings silently
            # picked XLA; keeping the race evidence in the record makes a
            # bogus winner VISIBLE
            extra["dense_race"] = report["candidates"]
    except Exception as e:  # noqa: BLE001 — any race failure degrades to the XLA two-pass (recorded)
        _log(f"autotune race failed ({type(e).__name__}); using XLA two-pass")
        extra["dense_race_error"] = f"{type(e).__name__}: {e}"[:300]
        block = None
    extra["fused_block_rows"] = block  # None = XLA two-pass won (or off-TPU)
    if block is not None:
        extra["fused_family"] = "{}:{}".format(*fused_glm._decode_block(block))
    obj = GLMObjective(losses.logistic, fused_block_rows=block)
    batch = GLMBatch.create(feats_store, labels)

    # fused-path parity gate before trusting its throughput (batch as a jit
    # ARG — a closure capture would inline 256 MB into the HLO, HTTP 413)
    if block is not None:
        vF, gF = jax.jit(lambda w, b: obj.value_and_grad(w, b, norm, 0.1))(w_probe, batch)  # jit-ok: one-shot parity probe
        rel_vf = abs(float(vF) - float(v32)) / max(abs(float(v32)), 1e-12)
        rel_gf = float(jnp.linalg.norm(gF - g32) / jnp.maximum(jnp.linalg.norm(g32), 1e-12))
        _log(f"fused parity (block={block}): value rel {rel_vf:.2e}, grad rel {rel_gf:.2e}")
        if rel_vf > 5e-2 or rel_gf > 5e-2:
            _log("fused kernel failed parity; falling back to XLA path")
            extra["fused_block_rows"] = None
            extra.pop("fused_family", None)  # the record must describe the
            obj = obj_plain                  # path that actually ran

    eps = _scan_throughput(
        lambda w, b: obj.value_and_grad(w, b, norm, 0.1),
        jnp.zeros((d,), jnp.float32),
        n,
        batch,
    )
    _log(f"dense: {eps:.3e} ex/s (path={'fused' if extra['fused_block_rows'] else 'xla'})")

    # roofline accounting (VERDICT r3 #2): this kernel is bandwidth-bound
    # (~2 FLOP per feature byte). The dominant traffic is the X matrix
    # (store_dtype: bf16 on TPU, f32 on the CPU fallback)
    # from HBM: once per pass for the fused single-pass kernel, twice for
    # the two-pass XLA pipeline (matvec margins + rmatvec gradient). Vector
    # traffic (y, w, z, d) is < 1% at D=512 and is ignored. TPU-only: the
    # 819 GB/s peak is the v5e HBM spec, meaningless against a CPU run.
    x_passes = 1 if extra["fused_block_rows"] else 2
    if extra["fused_block_rows"] and extra.get("fused_family", "").startswith("scan"):
        # the pure-XLA scan family is ALGORITHMICALLY one pass, but whether
        # the block actually stays resident between the matvec and the
        # rank-update is the compiler's call. 1-pass accounting UNDERSTATES
        # achieved bandwidth if XLA re-reads the block (the conservative
        # direction for an achieved-GB/s claim — 2-pass accounting could
        # print a physically impossible >100% of HBM peak); flag it.
        extra["dense_traffic_note"] = (
            "scan family: 1-pass accounting (understates achieved GB/s if "
            "XLA re-reads the block between contractions)"
        )
    bytes_per_example = d * jnp.dtype(store_dtype).itemsize * x_passes
    achieved_gbs = eps * bytes_per_example / 1e9
    extra["dense_achieved_gb_s"] = round(achieved_gbs, 1)
    if on_tpu:
        extra["dense_hbm_peak_gb_s"] = HBM_PEAK_GB_S
        extra["dense_pct_of_hbm_roofline"] = round(
            100.0 * achieved_gbs / HBM_PEAK_GB_S, 1
        )
        _log(
            f"roofline: {achieved_gbs:.0f} GB/s of ~{HBM_PEAK_GB_S} GB/s v5e HBM "
            f"({extra['dense_pct_of_hbm_roofline']:.1f}%, {x_passes}-pass X traffic)"
        )
    return eps


def _bench_sparse(extra, on_tpu):
    import jax.numpy as jnp

    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.features import SparseFeatures
    from photon_ml_tpu.ops.normalization import NormalizationContext
    from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective

    n_sparse = N_SPARSE if on_tpu else N_SPARSE // 8  # CPU fallback: smaller
    rng = np.random.default_rng(3)
    indices = rng.integers(0, D_SPARSE, size=(n_sparse, K_SPARSE), dtype=np.int32)
    values = rng.normal(size=(n_sparse, K_SPARSE)).astype(np.float32)
    labels_h = (rng.random(n_sparse) < 0.5).astype(np.float32)

    feats = SparseFeatures(
        jnp.asarray(indices), jnp.asarray(values, jnp.bfloat16), D_SPARSE
    )
    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()
    labels = jnp.asarray(labels_h)

    # race the two transpose-action layouts: random scatter-add vs the
    # sorted-segment-sum CSC view (with_transpose). The HEADLINE uses the
    # layout PRODUCTION ingest picks (ops.features.auto_transpose: scatter
    # everywhere since the r5 measurement showed it 1.6x ahead of the
    # sorted view on the v5e; env-overridable) so the recorded number is
    # the rate the real driver achieves, and the race keeps both rates in
    # the record in case a future chip/compiler flips the ordering.
    from photon_ml_tpu.ops.features import auto_transpose

    auto_sorted = auto_transpose(feats).t_idx is not None
    rates = {}
    for layout, f in (("scatter", feats), ("sorted", feats.with_transpose())):
        batch = GLMBatch.create(f, labels)
        rates[layout] = _scan_throughput(
            lambda w, b: obj.value_and_grad(w, b, norm, 0.1),
            jnp.zeros((D_SPARSE,), jnp.float32),
            n_sparse,
            batch,
            iters=10,
        )
        _log(
            f"sparse-wide (D={D_SPARSE}, nnz/row={K_SPARSE}, {layout}): "
            f"{rates[layout]:.3e} ex/s"
        )
    headline = rates["sorted" if auto_sorted else "scatter"]
    extra["sparse_wide_examples_per_sec"] = round(headline, 1)
    extra["sparse_wide_examples_per_sec_scatter"] = round(rates["scatter"], 1)
    extra["sparse_wide_examples_per_sec_sorted"] = round(rates["sorted"], 1)
    extra["sparse_wide_config"] = {"n": n_sparse, "d": D_SPARSE, "nnz_per_row": K_SPARSE}


def _bench_scoring(extra, on_tpu):
    """Device-side GAME scoring at scale (VERDICT r2 #6 claim): rows x
    entities via the per-entity-slab gather path of the scoring driver."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.cli.game_scoring_driver import _re_gather_contrib_impl

    n_rows = 1_000_000 if on_tpu else 100_000
    n_entities = 100_000 if on_tpu else 10_000
    d, k = 64, 16
    rng = np.random.default_rng(5)
    slab = jnp.asarray(rng.normal(size=(n_entities, d)).astype(np.float32))
    ent = jnp.asarray(rng.integers(0, n_entities, size=n_rows, dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, d, size=(n_rows, k), dtype=np.int32))
    vals = jnp.asarray(rng.normal(size=(n_rows, k)).astype(np.float32))

    fn = jax.jit(_re_gather_contrib_impl)  # jit-ok: read-only scoring gather probe
    jax.block_until_ready(fn(slab, ent, idx, vals))  # compile + warm
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        out = fn(slab, ent, idx, vals)
    jax.block_until_ready(out)
    rps = n_rows * reps / (time.perf_counter() - t0)
    _log(f"scoring: {n_rows} rows x {n_entities} entities -> {rps:.3e} rows/s")
    extra["scoring_rows_per_sec"] = round(rps, 1)
    extra["scoring_config"] = {"rows": n_rows, "entities": n_entities, "d": d, "nnz": k}


def _bench_serving(extra, on_tpu):
    """Online scoring service (photon_ml_tpu/serve): p50/p99 latency + QPS
    vs micro-batch size through the warm server, request scores BITWISE-
    equal to the batch game_scoring_driver on the same inputs, and a live
    model-swap arm (zero new compiles, zero dropped requests)."""
    import concurrent.futures
    import shutil
    import tempfile

    from game_test_utils import (
        game_avro_records,
        make_glmix_data,
        save_synthetic_game_model,
        serve_requests_from_records,
        write_game_avro,
    )

    from photon_ml_tpu.cli import game_scoring_driver
    from photon_ml_tpu.compile import ShapeBucketer, compile_stats
    from photon_ml_tpu.serve import (
        ModelStore,
        ModelSwapper,
        ScoringServer,
        ServeStats,
        build_model_store,
    )

    tmp = tempfile.mkdtemp(prefix="bench-serving-")
    try:
        rng = np.random.default_rng(11)
        num_users = 256 if on_tpu else 64
        d_fixed, d_random = 8, 6
        data, truth = make_glmix_data(
            rng, num_users=num_users, rows_per_user_range=(4, 10),
            d_fixed=d_fixed, d_random=d_random,
        )
        offsets = rng.normal(size=data.num_rows).astype(np.float32)
        model_dir = os.path.join(tmp, "model")
        save_synthetic_game_model(
            model_dir, rng, d_fixed=d_fixed, d_random=d_random,
            num_users=num_users,
        )
        in_dir = os.path.join(tmp, "in")
        os.makedirs(in_dir)
        write_game_avro(
            os.path.join(in_dir, "part-0.avro"), data,
            range(data.num_rows), truth, offsets,
        )
        store_dir = os.path.join(tmp, "store")
        build_model_store(model_dir, store_dir, bucketer=ShapeBucketer())

        # batch-driver oracle over the SAME feature space (the store's
        # feature index doubles as --offheap-indexmap-dir)
        drv = game_scoring_driver.main([
            "--input-dirs", in_dir,
            "--game-model-input-dir", model_dir,
            "--output-dir", os.path.join(tmp, "score-out"),
            "--offheap-indexmap-dir", os.path.join(store_dir, "features"),
            "--feature-shard-id-to-feature-section-keys-map",
            "global:fixedFeatures|per_user:userFeatures",
            "--delete-output-dir-if-exists", "true",
        ])
        records = list(
            game_avro_records(data, range(data.num_rows), truth, offsets)
        )
        reqs = serve_requests_from_records(records)
        sections = {"global": ["fixedFeatures"], "per_user": ["userFeatures"]}

        def fire(server, requests, workers=32):
            """One-row requests from concurrent client threads, results in
            submit order."""
            with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                futs = list(pool.map(lambda q: server.submit_rows([q]), requests))
            return np.concatenate([f.result() for f in futs])

        latency_vs_batch = {}
        bitwise = None
        for max_batch in (1, 8, 32, 128):
            server = ScoringServer(
                ModelStore(store_dir), shard_sections=sections,
                max_batch_rows=max_batch, max_wait_ms=2.0, stats=ServeStats(),
            )
            server.warmup(warm_nnz=16)
            served = fire(server, reqs)
            snap = server.stats.snapshot()
            latency_vs_batch[str(max_batch)] = {
                "p50_ms": snap["p50_ms"],
                "p99_ms": snap["p99_ms"],
                "qps": snap["qps"],
                "batch_fill": snap["batch_fill_ratio"],
            }
            if max_batch == 32:
                bitwise = bool(np.array_equal(served, drv.scores))
            _log(
                f"serving[batch<={max_batch}]: p50 {snap['p50_ms']}ms / "
                f"p99 {snap['p99_ms']}ms, {snap['qps']} req/s, "
                f"fill {snap['batch_fill_ratio']:.0%}"
            )
            server.close()
        if not bitwise:
            raise AssertionError(
                "served scores are not bitwise-equal to game_scoring_driver"
            )

        # swap arm: roll to a perturbed model (same entity count -> same
        # ladder rung) under live traffic
        model2 = os.path.join(tmp, "model2")
        save_synthetic_game_model(
            model2, np.random.default_rng(12), d_fixed=d_fixed,
            d_random=d_random, num_users=num_users,
        )
        store2 = os.path.join(tmp, "store2")
        build_model_store(model2, store2, bucketer=ShapeBucketer())
        server = ScoringServer(
            ModelStore(store_dir), shard_sections=sections,
            max_batch_rows=32, max_wait_ms=2.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=16)
        swapper = ModelSwapper(server)
        wm = compile_stats.watermark()
        with concurrent.futures.ThreadPoolExecutor(16) as pool:
            futs = [pool.submit(server.score_rows, [q]) for q in reqs]
            report = swapper.swap(store2)
            results = [f.result() for f in futs]  # raises on any drop/error
        dropped = sum(1 for r in results if r is None or len(r) != 1)
        server.close()
        _log(
            f"serving swap: gen {report['generation']}, "
            f"{report['new_compiles']} new compiles during swap, "
            f"{wm.new_traces()} traces over the whole swap window, "
            f"{dropped} dropped of {len(results)}"
        )
        if report["new_compiles"] != 0 or dropped != 0:
            raise AssertionError(
                f"model swap must be compile-free and lossless "
                f"(compiles={report['new_compiles']}, dropped={dropped})"
            )
        extra["serving_latency_vs_batch"] = latency_vs_batch
        extra["serving_bitwise_equal_to_driver"] = bool(bitwise)
        extra["serving_swap_new_compiles"] = int(report["new_compiles"])
        extra["serving_swap_dropped_requests"] = int(dropped)
        extra["serving_config"] = {
            "rows": int(data.num_rows), "entities": num_users,
            "d_fixed": d_fixed, "d_random": d_random,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_serving_fleet(extra, on_tpu):
    """Sharded serving fleet (photon_ml_tpu/serve/fleet): aggregate QPS and
    p99 vs replica count (1/2/4) under concurrent traffic, the
    bitwise-vs-single-store gate at 2 replicas, and a kill-one-replica
    availability arm (heartbeat detection + degraded serving, no hang).

    Replicas run as REAL subprocesses (the cli.fleet_driver replica mode
    over TCP), each subprocess-fenced with its own timeout. Honesty note
    (the perhost_streaming caveat, serving form): on one machine every
    replica time-shares the same cores with the router and the client
    threads, so QPS-vs-replicas here measures protocol/routing overhead
    and CAPACITY (each replica's slab is ~1/N of the model), not the
    linear throughput scaling a real N-host fleet gets. Replica children
    are pinned to CPU — the TPU tunnel is single-client and must not be
    claimed by N serving processes."""
    import concurrent.futures
    import shutil
    import signal  # noqa: F401 — documents the kill arm's mechanism
    import socket
    import subprocess
    import tempfile
    import time as _time

    from game_test_utils import (
        game_avro_records,
        make_glmix_data,
        save_synthetic_game_model,
        serve_requests_from_records,
    )

    from photon_ml_tpu.compile import ShapeBucketer
    from photon_ml_tpu.serve import (
        FleetStats,
        ModelStore,
        ScoringServer,
        ServeStats,
        build_model_store,
    )
    from photon_ml_tpu.serve.fleet import (
        FleetRouter,
        ServeShardPlan,
        TcpReplicaClient,
        build_fleet_stores,
        load_fleet_meta,
    )

    tmp = tempfile.mkdtemp(prefix="bench-serving-fleet-")
    here = os.path.dirname(os.path.abspath(__file__))
    sections_flag = "global:fixedFeatures|per_user:userFeatures"
    sections = {"global": ["fixedFeatures"], "per_user": ["userFeatures"]}
    procs_alive = []

    def spawn_replica(fleet_dir, r, n, hb_dir, timeout=240):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        log_path = os.path.join(tmp, f"replica-n{n}-{r}.log")
        # stderr to a FILE, stdout a pipe only for the one READY line (the
        # perhost lesson: children must never block on a full parent pipe)
        with open(log_path, "w") as lf:
            proc = subprocess.Popen(
                [sys.executable, "-m", "photon_ml_tpu.cli.fleet_driver",
                 "--fleet-dir", fleet_dir, "--replica-id", str(r),
                 "--num-fleet-replicas", str(n), "--heartbeat-dir", hb_dir,
                 "--feature-shard-id-to-feature-section-keys-map",
                 sections_flag,
                 "--max-batch-rows", "32", "--warm-nnz", "16"],
                stdout=subprocess.PIPE, stderr=lf, text=True,
                stdin=subprocess.DEVNULL, cwd=here, env=env,
            )
        procs_alive.append(proc)
        deadline = _time.monotonic() + timeout
        line = ""
        # select-bounded wait: a crashed child (EOF) or a silently hung
        # child must both hit THIS fence, not block readline forever or
        # busy-spin on an empty closed stream
        import select as _select

        while _time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            ready, _, _ = _select.select([proc.stdout], [], [], 0.5)
            if ready:
                line = proc.stdout.readline().strip()
                if line:
                    break
        if not line.startswith("READY "):
            proc.kill()
            with open(log_path) as f:
                tail = f.read()[-1500:]
            raise RuntimeError(
                f"fleet replica {r}/{n} failed to come up within {timeout}s "
                f"(got {line!r}):\n{tail}"
            )
        return proc, line.split()[1]

    def tcp_shutdown(addr):
        host, _, port = addr.rpartition(":")
        try:
            with socket.create_connection((host, int(port)), timeout=5) as s:
                s.sendall(b'{"cmd": "shutdown"}\n')
                s.recv(100)
        except OSError:
            pass

    try:
        rng = np.random.default_rng(19)
        num_users = 128
        d_fixed, d_random = 8, 6
        data, truth = make_glmix_data(
            rng, num_users=num_users, rows_per_user_range=(4, 8),
            d_fixed=d_fixed, d_random=d_random,
        )
        offsets = rng.normal(size=data.num_rows).astype(np.float32)
        model_dir = os.path.join(tmp, "model")
        save_synthetic_game_model(
            model_dir, rng, d_fixed=d_fixed, d_random=d_random,
            num_users=num_users,
        )
        records = list(
            game_avro_records(data, range(data.num_rows), truth, offsets)
        )
        reqs = serve_requests_from_records(records)

        # single-store reference (the bitwise oracle)
        store_dir = os.path.join(tmp, "store")
        build_model_store(model_dir, store_dir, bucketer=ShapeBucketer())
        server = ScoringServer(
            ModelStore(store_dir), shard_sections=sections,
            max_batch_rows=32, max_wait_ms=2.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=16)
        single_scores = server.score_rows(reqs)
        server.close()

        def fire(router, requests, workers=16):
            with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                futs = list(
                    pool.map(lambda q: router.submit_rows([q]), requests)
                )
            return np.concatenate([f.result(timeout=120) for f in futs])

        qps_vs_replicas = {}
        bitwise = None
        for n in (1, 2, 4):
            fleet_dir = os.path.join(tmp, f"fleet-{n}")
            build_fleet_stores(
                model_dir, fleet_dir, num_replicas=n,
                bucketer=ShapeBucketer(),
            )
            hb_dir = os.path.join(tmp, f"hb-{n}")
            procs, addrs = [], []
            for r in range(n):
                p, addr = spawn_replica(fleet_dir, r, n, hb_dir)
                procs.append(p)
                addrs.append(addr)
            router = FleetRouter(
                load_fleet_meta(fleet_dir),
                [TcpReplicaClient(a) for a in addrs],
                heartbeat_dir=hb_dir, heartbeat_deadline_s=3.0,
                request_timeout_s=60.0, stats=FleetStats(),
            )
            served = fire(router, reqs)  # warm connections + gate data
            snap0 = router.stats.snapshot()
            router.stats.reset()
            fire(router, reqs)  # the measured pass
            snap = router.stats.snapshot()
            qps_vs_replicas[str(n)] = {
                "qps": snap["qps"],
                "p50_ms": snap["p50_ms"],
                "p99_ms": snap["p99_ms"],
                "scatter_calls": snap["scatter_calls"],
            }
            _log(
                f"serving_fleet[{n} replica(s)]: {snap['qps']} req/s, "
                f"p50 {snap['p50_ms']}ms / p99 {snap['p99_ms']}ms "
                f"({snap['scatter_calls']} scatter calls; first pass "
                f"degraded_rows={snap0['degraded_rows']})"
            )
            if n == 2:
                bitwise = bool(np.array_equal(served, single_scores))

                # ---- kill-one-replica availability arm --------------------
                procs[1].kill()
                t0 = _time.monotonic()
                while 1 in router.live_replicas():
                    if _time.monotonic() - t0 > 15.0:
                        raise AssertionError(
                            "router failed to mark the killed replica dead "
                            "within the heartbeat deadline"
                        )
                    _time.sleep(0.2)
                detect_s = _time.monotonic() - t0
                router.stats.reset()
                t0 = _time.monotonic()
                degraded = fire(router, reqs)
                degrade_pass_s = _time.monotonic() - t0
                dsnap = router.stats.snapshot()
                plan = ServeShardPlan.from_json(
                    load_fleet_meta(fleet_dir)["plan"]
                )
                owners = plan.owners_of(
                    [q["ids"]["userId"] for q in reqs]
                )
                exact = owners == 0
                if not np.array_equal(degraded[exact], single_scores[exact]):
                    raise AssertionError(
                        "kill-one-replica: surviving replica's rows are "
                        "not exact"
                    )
                extra["serving_fleet_kill_one"] = {
                    "heartbeat_detect_s": round(detect_s, 2),
                    "answered": int(len(degraded)),
                    "requests": int(len(reqs)),
                    "degraded_rows": int(dsnap["degraded_rows"]),
                    "exact_rows": int(exact.sum()),
                    "pass_seconds": round(degrade_pass_s, 2),
                }
                _log(
                    f"serving_fleet kill-one: dead in {detect_s:.2f}s, "
                    f"{len(degraded)}/{len(reqs)} answered "
                    f"({int(exact.sum())} exact, "
                    f"{dsnap['degraded_rows']} degraded rows)"
                )
            router.close()
            for a in addrs:
                tcp_shutdown(a)
            for p in procs:
                try:
                    p.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        if not bitwise:
            raise AssertionError(
                "2-replica fleet scores are not bitwise-equal to the "
                "single-store server"
            )
        extra["serving_fleet_qps_vs_replicas"] = qps_vs_replicas
        extra["serving_fleet_bitwise_equal_to_single_store"] = True
        extra["serving_fleet_config"] = {
            "rows": int(data.num_rows), "entities": num_users,
            "d_fixed": d_fixed, "d_random": d_random,
            "note": (
                "replicas time-share one machine's cores with the router "
                "and clients; QPS-vs-replicas measures routing overhead "
                "and capacity, not N-host scaling"
            ),
        }
    finally:
        for p in procs_alive:
            if p.poll() is None:
                p.kill()
                p.wait()
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_perhost(extra, on_tpu):
    """Per-host ingest shuffle (parallel/shuffle + perhost_ingest): rows/sec
    through the full collective regroup — bucket-count psum, balanced owner
    map, all_to_all row exchange, owner-side slab build — plus the
    entity-sharded solve. The Spark partitionBy/groupByKey analogue's cost."""
    import jax
    import jax.numpy as jnp

    from game_test_utils import make_glmix_data

    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
    from photon_ml_tpu.parallel.perhost_ingest import (
        HostRows,
        PerHostRandomEffectSolver,
        per_host_re_dataset,
    )
    from photon_ml_tpu.types import OptimizerType, TaskType

    num_users = 20000 if on_tpu else 2000
    rng = np.random.default_rng(13)
    data, _ = make_glmix_data(
        rng, num_users=num_users, rows_per_user_range=(8, 16),
        d_fixed=8, d_random=8,
    )
    from photon_ml_tpu.parallel.perhost_ingest import csr_to_padded

    n = data.num_rows
    feats = data.shards["per_user"]
    fi, fv = csr_to_padded(feats, n)
    vocab = data.id_vocabs["userId"]
    rows = HostRows(
        entity_raw_ids=[vocab[i] for i in data.ids["userId"]],
        row_index=np.arange(n, dtype=np.int64),
        labels=data.response.astype(np.float32),
        weights=data.weight.astype(np.float32),
        offsets=data.offset.astype(np.float32),
        feat_idx=fi, feat_val=fv, global_dim=feats.dim,
    )
    ctx = MeshContext(data_mesh())
    # warm the shuffle collectives (shard_map all_to_all + count psums)
    # so the timed window measures throughput, not first-call compiles
    per_host_re_dataset(rows, ctx)
    t0 = time.perf_counter()
    sd = per_host_re_dataset(rows, ctx)
    jax.block_until_ready(sd.x)
    t_ingest = time.perf_counter() - t0
    solver = PerHostRandomEffectSolver(
        sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=15, tolerance=1e-7),
        RegularizationContext.l2(0.1), ctx,
    )
    resid = jnp.zeros((n,), jnp.float32)
    w, _ = solver.update(resid, solver.initial_coefficients())  # compile
    jax.block_until_ready(w)
    t0 = time.perf_counter()
    w, _ = solver.update(resid, solver.initial_coefficients())
    jax.block_until_ready(w)
    t_solve = time.perf_counter() - t0
    extra["perhost_shuffle_rows_per_sec"] = round(n / t_ingest, 1)
    extra["perhost_solve_sec"] = round(t_solve, 3)
    extra["perhost_config"] = {"rows": n, "entities": num_users}
    _log(
        f"per-host shuffle ingest: {n / t_ingest:.3e} rows/s "
        f"({num_users} entities); entity-sharded solve {t_solve:.3f}s"
    )


def _perhost_worker_main(argv):
    """Child mode (``--perhost-worker PID NPROCS PORT OUTDIR SCALE``): one
    SPMD process of the entity-sharded streaming bench workload. SCALE
    ``small`` runs a full streaming CD (streaming FE chunks + owner-computes
    RE blocks) and records sec/iter + a bitwise digest; SCALE ``268m``
    streams a 268,435,456-coefficient random effect (4,194,304 entities x
    64 IDENTITY dims) through the per-host block path and records the
    per-epoch sec/iter trajectory — the road-to-1B capture."""
    import hashlib
    import json as _json

    i = argv.index("--perhost-worker")
    pid, nprocs, port, outdir, scale = (
        int(argv[i + 1]), int(argv[i + 2]), argv[i + 3], argv[i + 4],
        argv[i + 5],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from photon_ml_tpu.parallel import multihost

    if nprocs > 1:
        multihost.initialize(
            coordinator_address=f"127.0.0.1:{port}", num_processes=nprocs,
            process_id=pid,
        )
    from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
    from photon_ml_tpu.algorithm.streaming_fixed_effect import (
        PerHostStreamingFixedEffectCoordinate,
    )
    from photon_ml_tpu.data.game import RandomEffectDataConfig
    from photon_ml_tpu.ops import losses as losses_mod
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationProblem
    from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
    from photon_ml_tpu.parallel.perhost_ingest import HostRows, csr_to_padded
    from photon_ml_tpu.parallel.perhost_streaming import (
        PerHostStreamingRandomEffectCoordinate,
        build_perhost_streaming_manifest,
    )
    from photon_ml_tpu.types import OptimizerType, TaskType

    ctx = MeshContext(data_mesh())
    result = {"process": pid}
    # every policy resolved ONCE from the env (photon_ml_tpu.compile.plan):
    # the compaction/sparse bench arm exports PHOTON_SOLVE_CHUNK /
    # PHOTON_SPARSE_KERNEL and reuses this same worker; the default arm
    # resolves all-off, so its path is byte-identical to before
    from photon_ml_tpu.compile.plan import ExecutionPlan

    exec_plan = ExecutionPlan.resolve(
        distributed=(nprocs > 1), streaming=True, num_processes=nprocs
    )
    if scale == "small":
        from game_test_utils import make_glmix_data

        rng = np.random.default_rng(101)
        data, _ = make_glmix_data(
            rng, num_users=2000, rows_per_user_range=(4, 10),
            d_fixed=16, d_random=16,
        )
        n = data.num_rows
        feats = data.shards["per_user"]
        fi, fv = csr_to_padded(feats, n)
        vocab = data.id_vocabs["userId"]
        lo = pid * (n // nprocs)
        hi = n if pid == nprocs - 1 else (pid + 1) * (n // nprocs)
        rows = HostRows(
            entity_raw_ids=[vocab[j] for j in data.ids["userId"][lo:hi]],
            row_index=np.arange(lo, hi, dtype=np.int64),
            labels=data.response[lo:hi].astype(np.float32),
            weights=data.weight[lo:hi].astype(np.float32),
            offsets=data.offset[lo:hi].astype(np.float32),
            feat_idx=fi[lo:hi], feat_val=fv[lo:hi], global_dim=feats.dim,
        )
        manifest = build_perhost_streaming_manifest(
            rows, RandomEffectDataConfig("userId", "per_user"),
            os.path.join(outdir, f"re-n{nprocs}-host{pid}"),
            ctx, nprocs, pid, block_entities=512,
            bucketer=exec_plan.bucketer,
        )
        re_coord = PerHostStreamingRandomEffectCoordinate(
            manifest, TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            # a realistic convergence profile (room to converge + a
            # practical tolerance): most lanes finish early, stragglers
            # run long — the skew the compaction arm's ledger measures
            optimizer_config=OptimizerConfig(
                max_iterations=30, tolerance=1e-6
            ),
            regularization=RegularizationContext.l2(0.2),
            state_root=os.path.join(outdir, f"state-n{nprocs}-host{pid}"),
            plan=exec_plan,
            ctx=ctx, num_processes=nprocs,
        )
        gf = data.shards["global"]
        x_fe = np.zeros((n, gf.dim), np.float32)
        x_fe[np.repeat(np.arange(n), np.diff(gf.indptr)), gf.indices] = gf.values
        chunk_rows = 4096
        chunk_sizes = [
            min(chunk_rows, n - c * chunk_rows)
            for c in range((n + chunk_rows - 1) // chunk_rows)
        ]
        owned = {}
        for c in range(len(chunk_sizes)):
            if c % nprocs != pid:
                continue
            s, e = c * chunk_rows, c * chunk_rows + chunk_sizes[c]

            def load(s=s, e=e):
                return {"x": x_fe[s:e], "y": data.response[s:e].astype(np.float32)}

            owned[c] = load
        fe_coord = PerHostStreamingFixedEffectCoordinate(
            chunk_sizes, owned, gf.dim,
            GLMOptimizationProblem(
                TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=8, tolerance=1e-8),
                RegularizationContext.l2(0.5),
            ),
            ctx=ctx, num_processes=nprocs,
        )
        labels = jnp.asarray(data.response.astype(np.float32))
        weights = jnp.asarray(data.weight.astype(np.float32))
        loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
        cd = CoordinateDescent(
            {"fixed": fe_coord, "per-user": re_coord},
            lambda s: jnp.sum(weights * loss.loss(s, labels)),
        )
        iters = 2

        def run_digest():
            res = cd.run(num_iterations=iters, num_rows=n)
            h = hashlib.sha256()
            h.update(np.asarray(res.coefficients["fixed"]).tobytes())
            h.update(np.asarray(res.total_scores).tobytes())
            h.update(repr([float(v) for v in res.objective_history]).encode())
            return h.hexdigest()

        t0 = time.perf_counter()
        digest = run_digest()
        elapsed = time.perf_counter() - t0
        result.update(
            sec_per_iter=elapsed / iters,
            digest=digest,
            rows=int(n), entities=2000,
        )
        if exec_plan.schedule is not None:
            # the compaction arm's honesty package: the lane-iteration
            # ledger this run actually executed, plus a fully-warm RERUN
            # (every kernel already traced) that must compile NOTHING new
            # and reproduce the digest bit-for-bit
            from photon_ml_tpu.compile import compile_stats
            from photon_ml_tpu.optim.scheduler import solve_stats

            result["lane_ledger"] = solve_stats.totals()
            wm = compile_stats.watermark()
            t0 = time.perf_counter()
            warm_digest = run_digest()
            warm_elapsed = time.perf_counter() - t0
            if warm_digest != digest:
                raise AssertionError(
                    "compacted rerun diverged from its own first run: "
                    f"{digest[:12]} vs {warm_digest[:12]}"
                )
            result["warm_sec_per_iter"] = warm_elapsed / iters
            result["warm_new_traces"] = wm.new_traces()
            result["warm_new_xla_misses"] = wm.new_xla_misses()
    elif scale == "268m":
        # 4,194,304 entities x 64 IDENTITY dims = 268,435,456 coefficients,
        # one row per entity; blocks of 65,536 entities stream from disk
        # (env PHOTON_BENCH_268M_ENTITIES downsizes for smoke runs)
        e_total = int(os.environ.get("PHOTON_BENCH_268M_ENTITIES", 4_194_304))
        d_loc = 64
        rng = np.random.default_rng(7)
        lo = pid * (e_total // nprocs)
        hi = e_total if pid == nprocs - 1 else (pid + 1) * (e_total // nprocs)
        n_loc = hi - lo
        width = len(str(e_total - 1))
        raw_ids = [f"e{j:0{width}d}" for j in range(lo, hi)]
        rows = HostRows(
            entity_raw_ids=raw_ids,
            row_index=np.arange(lo, hi, dtype=np.int64),
            labels=(np.arange(lo, hi) % 2).astype(np.float32),
            weights=np.ones(n_loc, np.float32),
            offsets=np.zeros(n_loc, np.float32),
            feat_idx=(np.arange(lo, hi, dtype=np.int64) % d_loc)
            .astype(np.int32)[:, None],
            feat_val=np.ones((n_loc, 1), np.float32),
            global_dim=d_loc,
        )
        shared_vocab = [f"e{j:0{width}d}" for j in range(e_total)]
        t0 = time.perf_counter()
        manifest = build_perhost_streaming_manifest(
            rows, RandomEffectDataConfig(
                "entityId", "per_entity", projector="IDENTITY"
            ),
            os.path.join(outdir, f"re268m-host{pid}"),
            ctx, nprocs, pid, block_entities=65536,
            shared_vocab=shared_vocab,
        )
        t_build = time.perf_counter() - t0
        coord = PerHostStreamingRandomEffectCoordinate(
            manifest, TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(
                max_iterations=1, tolerance=1e-9, num_corrections=3
            ),
            regularization=RegularizationContext.l2(1.0),
            state_root=os.path.join(outdir, f"state268m-host{pid}"),
            # env-resolved plan: the default capture runs flags-off; the
            # same knob that drives the compaction arm can drive a
            # compacted 268M capture without touching this file
            plan=exec_plan,
            ctx=ctx, num_processes=nprocs,
        )
        resid = jnp.zeros((e_total,), jnp.float32)
        state = coord.initial_coefficients()
        iter_secs = []
        for _ in range(2):
            t0 = time.perf_counter()
            state, _ = coord.update(resid, state)
            iter_secs.append(round(time.perf_counter() - t0, 2))
        t0 = time.perf_counter()
        scores = np.asarray(coord.score(state))
        t_score = time.perf_counter() - t0
        coefs = sum(
            b["num_entities"] * b["local_dim"] for b in manifest.blocks
        )
        result.update(
            coefficients_this_host=int(coefs),
            coefficients_total=int(e_total * d_loc),
            build_sec=round(t_build, 2),
            iter_secs=iter_secs,
            score_sec=round(t_score, 2),
            blocks_owned=len(manifest.blocks),
            blocks_total=manifest.num_blocks_total,
            score_nonzero=int(np.count_nonzero(scores)),
        )
    elif scale == "adaptive":
        # gap-guided adaptive scheduling (optim/convergence.py) on a SKEWED
        # block-convergence distribution: 8 ill-conditioned "hard" entities
        # (feature spectrum scaled 1..256, 48 rows each, so the size-sorted
        # block layout groups them into their own trailing block) next to
        # 512 easy 8-row ones. The iteration cap (12) is what separates the
        # scores: easy lanes converge under it and park at the relative
        # stopping threshold (~1e-3 absolute grad norm); hard lanes exhaust
        # it and stay an order of magnitude above — the gap the tolerance
        # arm's skip threshold lives in. The arm's policy comes from
        # PHOTON_ADAPTIVE_SCHEDULE via the env-resolved plan above, so this
        # one worker serves the always-visit baseline, the ordering-only
        # bitwise pin, and the tolerance mode.
        from photon_ml_tpu.algorithm.coordinate_descent import (
            CoordinateDescent as _CD,
        )
        from photon_ml_tpu.compile import compile_stats
        from photon_ml_tpu.optim.scheduler import solve_stats

        d_re = d_fe = 8
        n_hard, n_easy = 8, 512
        e_total = n_easy + n_hard
        rng = np.random.default_rng(23)
        counts = np.asarray([8] * n_easy + [48] * n_hard)
        ids = np.repeat(np.arange(e_total), counts)
        n = int(counts.sum())
        x_re = rng.normal(size=(n, d_re)).astype(np.float32)
        x_re[ids >= n_easy] *= np.geomspace(1.0, 256.0, d_re).astype(np.float32)
        w_true = (rng.normal(size=(e_total, d_re)) * 0.5).astype(np.float32)
        x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
        w_fe = (rng.normal(size=d_fe) * 0.2).astype(np.float32)
        z = (
            np.einsum("nd,nd->n", x_re.astype(np.float64), w_true[ids])
            + x_fe @ w_fe
        )
        y = (1.0 / (1.0 + np.exp(-z)) > rng.random(n)).astype(np.float32)
        # interleave rows (block row-selections must be non-contiguous)
        perm = rng.permutation(n)
        x_re, x_fe, y, ids = x_re[perm], x_fe[perm], y[perm], ids[perm]
        width = len(str(e_total - 1))
        vocab = [f"u{j:0{width}d}" for j in range(e_total)]
        lo = pid * (n // nprocs)
        hi = n if pid == nprocs - 1 else (pid + 1) * (n // nprocs)
        rows = HostRows(
            entity_raw_ids=[vocab[j] for j in ids[lo:hi]],
            row_index=np.arange(lo, hi, dtype=np.int64),
            labels=y[lo:hi],
            weights=np.ones(hi - lo, np.float32),
            offsets=np.zeros(hi - lo, np.float32),
            feat_idx=np.tile(np.arange(d_re, dtype=np.int32), (hi - lo, 1)),
            feat_val=x_re[lo:hi],
            global_dim=d_re,
        )
        manifest = build_perhost_streaming_manifest(
            rows, RandomEffectDataConfig("userId", "per_user"),
            os.path.join(outdir, f"re-adaptive-n{nprocs}-host{pid}"),
            ctx, nprocs, pid, block_entities=64,
            bucketer=exec_plan.bucketer,
        )
        re_coord = PerHostStreamingRandomEffectCoordinate(
            manifest, TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(
                max_iterations=12, tolerance=1e-6
            ),
            regularization=RegularizationContext.l2(0.2),
            state_root=os.path.join(
                outdir, f"state-adaptive-n{nprocs}-host{pid}"
            ),
            plan=exec_plan,
            ctx=ctx, num_processes=nprocs,
        )
        chunk_rows = 1024
        chunk_sizes = [
            min(chunk_rows, n - c * chunk_rows)
            for c in range((n + chunk_rows - 1) // chunk_rows)
        ]
        owned = {}
        for c in range(len(chunk_sizes)):
            if c % nprocs != pid:
                continue
            s, e = c * chunk_rows, c * chunk_rows + chunk_sizes[c]

            def load(s=s, e=e):
                return {"x": x_fe[s:e], "y": y[s:e]}

            owned[c] = load
        fe_coord = PerHostStreamingFixedEffectCoordinate(
            chunk_sizes, owned, d_fe,
            GLMOptimizationProblem(
                TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=8, tolerance=1e-8),
                RegularizationContext.l2(0.5),
            ),
            ctx=ctx, num_processes=nprocs,
        )
        labels = jnp.asarray(y)
        loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
        cd = _CD(
            {"fixed": fe_coord, "per-user": re_coord},
            lambda s: jnp.sum(loss.loss(s, labels)),
        )
        epochs = 6
        solve_stats.reset()

        def run_digest():
            res = cd.run(num_iterations=epochs, num_rows=n)
            h = hashlib.sha256()
            h.update(np.asarray(res.coefficients["fixed"]).tobytes())
            h.update(np.asarray(res.total_scores).tobytes())
            h.update(repr([float(v) for v in res.objective_history]).encode())
            return h.hexdigest(), [float(v) for v in res.objective_history]

        t0 = time.perf_counter()
        digest, hist = run_digest()
        elapsed = time.perf_counter() - t0
        blocks = solve_stats.block_totals()
        result.update(
            sec_per_iter=elapsed / epochs,
            digest=digest,
            objective_history=hist,
            lane_iterations=int(sum(b["executed"] for b in blocks.values())),
            block_visits=int(sum(b["visits"] for b in blocks.values())),
            block_skips=int(sum(b["skips"] for b in blocks.values())),
            skip_decisions=len(getattr(re_coord, "skip_decisions", ()) or ()),
            blocks_owned=len(manifest.blocks),
            adaptive=(
                exec_plan.adaptive.describe()
                if exec_plan.adaptive is not None else "off"
            ),
        )
        if exec_plan.adaptive is not None and exec_plan.adaptive.tolerance > 0:
            # fully-warm rerun: the ledger is warm (skips start earlier),
            # every kernel already traced — it must compile NOTHING new
            wm = compile_stats.watermark()
            run_digest()
            result["warm_new_traces"] = wm.new_traces()
            result["warm_new_xla_misses"] = wm.new_xla_misses()
    else:
        raise SystemExit(f"unknown perhost-worker scale {scale!r}")
    path = os.path.join(outdir, f"perhost-n{nprocs}-{scale}-{pid}.json")
    with open(path + ".tmp", "w") as f:
        _json.dump(result, f)
    os.replace(path + ".tmp", path)
    return 0


def _bench_perhost_streaming(extra, on_tpu):
    """Entity-sharded multihost streaming CD (parallel/perhost_streaming):
    sec/iter for 1 vs 2 processes on the SAME workload, the 1-vs-2-process
    bitwise gate, and the >=268M-coefficient multi-process capture.
    Collectives ride the Gloo CPU backend here (the harness is
    subprocess-per-host on one machine), so the recorded "speedup" is an
    honest measure of THIS capture — on one core, two processes time-share
    and the win is capacity (per-host memory/disk halves), not wall-clock."""
    import subprocess
    import tempfile

    here = os.path.abspath(__file__)
    out = tempfile.mkdtemp(prefix="perhost-streaming-bench-")

    def run_workers(nprocs, scale, timeout, env_extra=None):
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["JAX_PLATFORMS"] = "cpu"
        # the flags-off baseline arms must stay flags-off: pin the
        # worker plan's env knobs so an ambient PHOTON_SOLVE_CHUNK /
        # PHOTON_SPARSE_KERNEL (a leftover local experiment) cannot turn
        # the "uncompacted" arm compacted and void the comparison — the
        # compaction arm switches them on EXPLICITLY via env_extra
        env.update({
            "PHOTON_SOLVE_CHUNK": "off",
            "PHOTON_SPARSE_KERNEL": "off",
            "PHOTON_SHAPE_LADDER": "off",
            "PHOTON_ADAPTIVE_SCHEDULE": "off",
        })
        env.update(env_extra or {})
        # children get FILES, not our pipes (the isolated-section rule): a
        # pipe fills at ~64KB of XLA/JAX log noise, the blocked writer
        # stalls its Gloo collective, and the whole cohort "times out"
        # purely on log volume
        log_paths = [
            os.path.join(out, f"worker-n{nprocs}-{scale}-{p}.log")
            for p in range(nprocs)
        ]
        procs = []
        for p in range(nprocs):
            with open(log_paths[p], "w") as lf:
                procs.append(subprocess.Popen(
                    [sys.executable, here, "--perhost-worker", str(p),
                     str(nprocs), str(port), out, scale],
                    stdout=subprocess.DEVNULL, stderr=lf, env=env,
                ))

        def tail(p_id):
            try:
                with open(log_paths[p_id]) as lf:
                    return lf.read()[-1500:]
            except OSError:
                return "<no worker log>"

        try:
            for p_id, p in enumerate(procs):
                try:
                    p.communicate(timeout=timeout)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.communicate()
                    raise RuntimeError(
                        f"perhost worker ({nprocs} proc, {scale}) exceeded "
                        f"{timeout}s:\n{tail(p_id)}"
                    )
                if p.returncode != 0:
                    raise RuntimeError(
                        f"perhost worker failed rc={p.returncode}:\n{tail(p_id)}"
                    )
        except BaseException:  # noqa: BLE001 — cohort cleanup then re-raise, even on KeyboardInterrupt
            # one worker failing/timing out strands its Gloo peers inside a
            # collective with no timeout of their own — kill the whole
            # cohort before re-raising, or the orphans contend with every
            # later bench section (the r3 claim-orphan lesson, process form)
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            raise
        results = []
        for p_id in range(nprocs):
            with open(
                os.path.join(out, f"perhost-n{nprocs}-{scale}-{p_id}.json")
            ) as f:
                results.append(json.load(f))
        return results

    try:
        _bench_perhost_streaming_body(extra, run_workers)
    finally:
        # block files at 268M scale are GBs — never leak them on a failed
        # run (a raised bitwise gate / worker timeout must still clean up)
        import shutil

        shutil.rmtree(out, ignore_errors=True)


def _bench_perhost_streaming_body(extra, run_workers):
    r1 = run_workers(1, "small", 1200)
    r2 = run_workers(2, "small", 1800)
    sec1 = r1[0]["sec_per_iter"]
    sec2 = max(r["sec_per_iter"] for r in r2)
    bitwise = r1[0]["digest"] == r2[0]["digest"] == r2[1]["digest"]
    if not bitwise:
        raise AssertionError(
            "entity-sharded streaming CD is NOT bitwise host-count "
            f"invariant: digests {r1[0]['digest'][:12]} vs "
            f"{[r['digest'][:12] for r in r2]}"
        )
    extra["perhost_streaming_sec_per_iter_1proc"] = round(sec1, 3)
    extra["perhost_streaming_sec_per_iter_2proc"] = round(sec2, 3)
    extra["perhost_streaming_speedup_2proc"] = round(sec1 / sec2, 3)
    extra["perhost_streaming_bitwise_equal"] = True
    extra["perhost_streaming_config"] = dict(r1[0])
    _log(
        f"perhost streaming CD: {sec1:.3f}s/iter (1 proc) vs "
        f"{sec2:.3f}s/iter (2 proc), speedup {sec1 / sec2:.2f}x, "
        "1-vs-2-process BITWISE equal"
    )

    # ---- compaction + sparse arm on the billion-coefficient path ----------
    # the SAME workload through the SAME workers with the execution plan's
    # env knobs on: convergence-compacted block solves (PR 4) + the
    # sparse-kernel race (PR 7), previously fenced off this path. Honesty
    # package: the lane-iteration ledger actually executed, sec/iter next
    # to the uncompacted arm, a bitwise digest gate against the flags-off
    # run, and a fully-warm rerun that must compile ZERO new XLA programs
    # (CompileStats watermark, asserted in the worker).
    rc = run_workers(
        2, "small", 1800,
        env_extra={"PHOTON_SOLVE_CHUNK": "4", "PHOTON_SPARSE_KERNEL": "auto"},
    )
    if not all(r["digest"] == r1[0]["digest"] for r in rc):
        raise AssertionError(
            "compacted+sparse perhost streaming CD is NOT bitwise-equal to "
            f"the flags-off run: {r1[0]['digest'][:12]} vs "
            f"{[r['digest'][:12] for r in rc]}"
        )
    sec_c = max(r["sec_per_iter"] for r in rc)
    sec_cw = max(r["warm_sec_per_iter"] for r in rc)
    # updates are owner-computes, so each worker's solve_stats ledger
    # covers only ITS owned blocks — the fleet-wide ledger is the SUM
    ledger = {
        k: sum(r["lane_ledger"][k] for r in rc)
        for k in rc[0]["lane_ledger"]
    }
    for r in rc:
        if r["warm_new_traces"] or r["warm_new_xla_misses"]:
            raise AssertionError(
                "compacted warm rerun compiled something new: "
                f"{[(r['warm_new_traces'], r['warm_new_xla_misses']) for r in rc]}"
            )
    saved = ledger["saved_lane_iterations"]
    base_li = ledger["baseline_lane_iterations"]
    extra["perhost_streaming_compaction"] = {
        "sec_per_iter_2proc": round(sec_c, 3),
        "warm_sec_per_iter_2proc": round(sec_cw, 3),
        "uncompacted_sec_per_iter_2proc": round(sec2, 3),
        "lane_iterations_executed": ledger["executed_lane_iterations"],
        "lane_iterations_baseline": base_li,
        "lane_iterations_saved": saved,
        "lane_iterations_saved_pct": round(
            100.0 * saved / base_li, 1
        ) if base_li else 0.0,
        "sparse_kernel": "auto",
        "chunk": 4,
        "bitwise_equal_to_uncompacted": True,
        "warm_new_xla_compiles": 0,
    }
    _log(
        f"perhost streaming compaction+sparse arm (2 proc): {sec_c:.3f}s/iter "
        f"cold, {sec_cw:.3f}s/iter warm vs {sec2:.3f}s/iter uncompacted; "
        f"lane-iterations {ledger['executed_lane_iterations']} vs "
        f"{base_li} one-shot (saved {saved}, "
        f"{100.0 * saved / base_li if base_li else 0.0:.1f}%), digest "
        "BITWISE-equal, warm rerun compiled 0 new XLA programs"
    )

    # ---- the >=268M-coefficient multi-process capture ---------------------
    big = run_workers(2, "268m", 5100)
    total = big[0]["coefficients_total"]
    per_host = [b["coefficients_this_host"] for b in big]
    extra["perhost_268m"] = {
        "coefficients_total": total,
        "coefficients_per_host": per_host,
        "processes": 2,
        "blocks_total": big[0]["blocks_total"],
        "build_sec": max(b["build_sec"] for b in big),
        "iter_secs": [max(a, b) for a, b in zip(
            big[0]["iter_secs"], big[1]["iter_secs"]
        )],
        "score_sec": max(b["score_sec"] for b in big),
    }
    if total < 268_435_456 and not os.environ.get("PHOTON_BENCH_268M_ENTITIES"):
        raise AssertionError(f"268M capture undersized: {total}")
    _log(
        f"perhost streaming 268M capture: {total:,} coefficients over 2 "
        f"processes, sec/iter trajectory {extra['perhost_268m']['iter_secs']}"
    )


def _elastic_worker_main(argv):
    """Child mode (``--elastic-worker PID NPROCS PORT OUTDIR ARM``): one
    SPMD process of the elastic re-sharding bench workload
    (parallel/elastic.py). Arms:

      * ``fresh`` — uninterrupted streaming CD on the SURVIVOR topology
        (2 owner hosts). Doubles as the bitwise reference AND the honest
        full-restart cost: the pre-elastic recovery for a lost host was
        supervised relaunch + full re-ingest + retrain (per-host layouts
        could not restore across a topology change), i.e. this arm's
        build+train wall-clock — conservatively EXCLUDING process
        startup/jax init, which a real relaunch also pays.
      * ``elastic`` — 3 virtual owners on the 2 processes (owner 2
        co-located with process 0); owner 2 is reclaimed just before the
        fleet's first epoch-2 block solve, both processes drain at their
        streaming boundaries, agree plan v2, move ONLY the delta blocks
        (+ spilled coefficients), and resume through the plan-versioned
        checkpoint. Recovery cost is measured drain -> finish.
    """
    import hashlib
    import json as _json

    i = argv.index("--elastic-worker")
    pid, nprocs, port, outdir, arm = (
        int(argv[i + 1]), int(argv[i + 2]), argv[i + 3], argv[i + 4],
        argv[i + 5],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from photon_ml_tpu.parallel import multihost

    if nprocs > 1:
        multihost.initialize(
            coordinator_address=f"127.0.0.1:{port}", num_processes=nprocs,
            process_id=pid,
        )
    from game_test_utils import make_glmix_data

    from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
    from photon_ml_tpu.algorithm.streaming_fixed_effect import (
        PerHostStreamingFixedEffectCoordinate,
    )
    from photon_ml_tpu.checkpoint import CoordinateDescentCheckpointer
    from photon_ml_tpu.compile.plan import ExecutionPlan
    from photon_ml_tpu.data.game import RandomEffectDataConfig
    from photon_ml_tpu.ops import losses as losses_mod
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationProblem
    from photon_ml_tpu.parallel.elastic import (
        ElasticMonitor,
        ElasticSession,
        FleetMembership,
        ReplanRequired,
        declare_lost_hosts,
    )
    from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
    from photon_ml_tpu.parallel.perhost_ingest import HostRows, csr_to_padded
    from photon_ml_tpu.parallel.perhost_streaming import (
        PerHostStreamingRandomEffectCoordinate,
        build_perhost_streaming_manifest,
    )
    from photon_ml_tpu.types import OptimizerType, TaskType

    ctx = MeshContext(data_mesh())
    exec_plan = ExecutionPlan.resolve(
        distributed=(nprocs > 1), streaming=True, num_processes=nprocs
    )
    rng = np.random.default_rng(707)
    data, _ = make_glmix_data(
        rng, num_users=600, rows_per_user_range=(4, 10),
        d_fixed=8, d_random=8,
    )
    # sorted entity vocabulary — the production sorted-set decode order
    vocab0 = data.id_vocabs["userId"]
    order = np.argsort(np.asarray(vocab0, dtype=object))
    remap = np.empty(len(vocab0), np.int64)
    remap[order] = np.arange(len(vocab0))
    data.ids["userId"] = remap[data.ids["userId"]].astype(np.int32)
    data.id_vocabs["userId"] = [vocab0[j] for j in order]
    n = data.num_rows
    feats = data.shards["per_user"]
    fi, fv = csr_to_padded(feats, n)
    vocab = data.id_vocabs["userId"]
    lo = pid * (n // nprocs)
    hi = n if pid == nprocs - 1 else (pid + 1) * (n // nprocs)
    rows = HostRows(
        entity_raw_ids=[vocab[j] for j in data.ids["userId"][lo:hi]],
        row_index=np.arange(lo, hi, dtype=np.int64),
        labels=data.response[lo:hi].astype(np.float32),
        weights=data.weight[lo:hi].astype(np.float32),
        offsets=data.offset[lo:hi].astype(np.float32),
        feat_idx=fi[lo:hi], feat_val=fv[lo:hi], global_dim=feats.dim,
    )
    if arm == "elastic":
        membership = FleetMembership(1, [0, 1, 2], {0: 0, 1: 1, 2: 0})
    elif arm == "fresh":
        membership = FleetMembership.initial(nprocs)
    else:
        raise SystemExit(f"unknown elastic-worker arm {arm!r}")
    fleet_dir = os.path.join(outdir, f"fleet-{arm}")
    monitor = ElasticMonitor(
        fleet_dir, membership, process_id=pid,
        heartbeat_deadline=30.0, min_poll_interval=0.0,
        num_processes=nprocs,
    )
    session = ElasticSession(
        fleet_dir, pid, nprocs, monitor, barrier_timeout=180.0
    )
    elastic_arg = monitor if arm == "elastic" else None
    t_start = time.perf_counter()
    manifest = build_perhost_streaming_manifest(
        rows, RandomEffectDataConfig("userId", "per_user"),
        os.path.join(outdir, f"re-{arm}-host{pid}"),
        ctx, nprocs, pid, block_entities=64,
        bucketer=exec_plan.bucketer, membership=membership,
    )
    t_build = time.perf_counter() - t_start

    def make_re(man, initial_epoch=0):
        return PerHostStreamingRandomEffectCoordinate(
            man, TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(
                max_iterations=20, tolerance=1e-7
            ),
            regularization=RegularizationContext.l2(0.2),
            state_root=os.path.join(outdir, f"state-{arm}-host{pid}"),
            plan=exec_plan, elastic=elastic_arg,
            initial_epoch=initial_epoch,
            ctx=ctx, num_processes=nprocs,
        )

    re_coord = make_re(manifest)
    if arm == "elastic":
        # EVERY process reclaims virtual owner 2 at its OWN epoch-2
        # boundary (atomic idempotent marker writes), so no drain depends
        # on the peer's timing: process 1 fires at update ENTRY (always
        # drains before its collectives), process 0 just before its first
        # epoch-2 block solve (drains MID-EPOCH at the block boundary)
        _fired = {"done": False}

        def _reclaim():
            _fired["done"] = True
            monitor.silence_host(2)
            declare_lost_hosts(
                fleet_dir, [2], reason="bench: virtual owner reclaimed"
            )

        if pid == 0:
            _orig_slab = re_coord._slab_for
            _calls = {"n": 0}
            _first_epoch2 = len(manifest.blocks) + 1

            def _slab_hook(i, ds, _orig=_orig_slab):
                _calls["n"] += 1
                if not _fired["done"] and _calls["n"] == _first_epoch2:
                    _reclaim()
                return _orig(i, ds)

            re_coord._slab_for = _slab_hook
        else:
            _orig_update = re_coord.update

            def _entry_trigger(resid, state, resume=None,
                               _orig=_orig_update):
                if (not _fired["done"] and re_coord._epoch >= 1
                        and resume is None):
                    _reclaim()
                return _orig(resid, state, resume=resume)

            re_coord.update = _entry_trigger
    gf = data.shards["global"]
    x_fe = np.zeros((n, gf.dim), np.float32)
    x_fe[np.repeat(np.arange(n), np.diff(gf.indptr)), gf.indices] = gf.values
    chunk_rows = 1024
    chunk_sizes = [
        min(chunk_rows, n - c * chunk_rows)
        for c in range((n + chunk_rows - 1) // chunk_rows)
    ]
    owned = {}
    for c in range(len(chunk_sizes)):
        if c % nprocs != pid:
            continue
        s, e = c * chunk_rows, c * chunk_rows + chunk_sizes[c]

        def load(s=s, e=e):
            return {"x": x_fe[s:e], "y": data.response[s:e].astype(np.float32)}

        owned[c] = load
    fe_coord = PerHostStreamingFixedEffectCoordinate(
        chunk_sizes, owned, gf.dim,
        GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=8, tolerance=1e-8),
            RegularizationContext.l2(0.5),
        ),
        plan=exec_plan, elastic=elastic_arg,
        ctx=ctx, num_processes=nprocs,
    )
    labels = jnp.asarray(data.response.astype(np.float32))
    weights = jnp.asarray(data.weight.astype(np.float32))
    loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
    loss_fn = lambda s: jnp.sum(weights * loss.loss(s, labels))  # noqa: E731
    ck = CoordinateDescentCheckpointer(
        os.path.join(outdir, f"ckpt-{arm}-host{pid}"),
        run_fingerprint="elastic-bench", save_every=1,
    )
    t_drain = None
    replans = 0
    replan_sec = 0.0
    moved = total_blocks = 0
    t_train0 = time.perf_counter()
    while True:
        cd = CoordinateDescent(
            {"fixed": fe_coord, "per-user": re_coord}, loss_fn
        )
        try:
            run_res = cd.run(num_iterations=2, num_rows=n, checkpointer=ck)
            break
        except ReplanRequired as e:
            if t_drain is None:
                t_drain = time.perf_counter()
            replans += 1
            old_epoch = re_coord._epoch
            t_r = time.perf_counter()
            rr = session.replan(
                re_coord.manifest, e.proposal,
                state_dir=re_coord.replan_state_dirs(), epoch=old_epoch,
            )
            replan_sec += time.perf_counter() - t_r
            moved, total_blocks = rr.blocks_moved, rr.blocks_total
            exec_plan = exec_plan.record_replan(
                rr.plan_version, rr.decisions[0]
            )
            re_coord = make_re(rr.manifest, initial_epoch=old_epoch + 1)
    t_end = time.perf_counter()
    h = hashlib.sha256()
    h.update(np.asarray(run_res.coefficients["fixed"]).tobytes())
    h.update(np.asarray(run_res.total_scores).tobytes())
    h.update(repr([float(v) for v in run_res.objective_history]).encode())
    result = dict(
        process=pid, arm=arm, digest=h.hexdigest(),
        build_sec=round(t_build, 3),
        train_sec=round(t_end - t_train0, 3),
        total_sec=round(t_end - t_start, 3),
        rows=int(n), entities=600,
    )
    if arm == "elastic":
        if replans == 0:
            raise SystemExit("elastic arm never drained — trigger broken")
        result.update(
            replans=replans,
            replan_sec=round(replan_sec, 3),
            recovery_sec=round(t_end - t_drain, 3),
            blocks_moved=int(moved),
            blocks_total=int(total_blocks),
            plan_version=int(monitor.membership.version),
        )
    path = os.path.join(outdir, f"elastic-{arm}-{pid}.json")
    with open(path + ".tmp", "w") as f:
        _json.dump(result, f)
    os.replace(path + ".tmp", path)
    return 0


def _bench_elastic_reshard(extra, on_tpu):
    """Elastic re-shard cost vs full-restart cost on the small perhost
    streaming workload (parallel/elastic.py): kill one of 3 virtual owners
    mid-epoch, re-plan the fleet in place, and finish — against the
    pre-elastic recovery (relaunch + re-ingest + retrain from scratch on
    the survivor topology, measured as the fresh arm's build+train).
    Gates: the elastic run's digest is BITWISE-equal to the fresh
    survivor-topology run's, blocks genuinely moved (with blocks-moved /
    blocks-total accounting), and recovery costs less than the restart."""
    import shutil
    import socket
    import subprocess
    import tempfile

    here = os.path.abspath(__file__)
    out = tempfile.mkdtemp(prefix="elastic-reshard-bench-")

    def run_workers(arm, timeout, nprocs=2):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["JAX_PLATFORMS"] = "cpu"
        # the comparison must be flags-off on both arms: pin the plan's
        # env knobs (same rule as the perhost_streaming section)
        env.update({
            "PHOTON_SOLVE_CHUNK": "off",
            "PHOTON_SPARSE_KERNEL": "off",
            "PHOTON_SHAPE_LADDER": "off",
        })
        log_paths = [
            os.path.join(out, f"worker-{arm}-{p}.log") for p in range(nprocs)
        ]
        procs = []
        for p in range(nprocs):
            with open(log_paths[p], "w") as lf:
                procs.append(subprocess.Popen(
                    [sys.executable, here, "--elastic-worker", str(p),
                     str(nprocs), str(port), out, arm],
                    stdout=subprocess.DEVNULL, stderr=lf, env=env,
                ))

        def tail(p_id):
            try:
                with open(log_paths[p_id]) as lf:
                    return lf.read()[-1500:]
            except OSError:
                return "<no worker log>"

        try:
            for p_id, p in enumerate(procs):
                try:
                    p.communicate(timeout=timeout)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.communicate()
                    raise RuntimeError(
                        f"elastic worker ({arm}) exceeded {timeout}s:\n"
                        f"{tail(p_id)}"
                    )
                if p.returncode != 0:
                    raise RuntimeError(
                        f"elastic worker ({arm}) failed "
                        f"rc={p.returncode}:\n{tail(p_id)}"
                    )
        except BaseException:  # noqa: BLE001 — cohort cleanup then re-raise (a stranded Gloo peer contends with every later section)
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            raise
        results = []
        for p_id in range(nprocs):
            with open(os.path.join(out, f"elastic-{arm}-{p_id}.json")) as f:
                results.append(json.load(f))
        return results

    try:
        fresh = run_workers("fresh", 1500)
        el = run_workers("elastic", 1800)
    finally:
        shutil.rmtree(out, ignore_errors=True)

    digests = {r["digest"] for r in fresh} | {r["digest"] for r in el}
    if len(digests) != 1:
        raise AssertionError(
            "elastic re-shard run is NOT bitwise-equal to the fresh "
            f"survivor-topology run: fresh {[r['digest'][:12] for r in fresh]}"
            f" vs elastic {[r['digest'][:12] for r in el]}"
        )
    moved = el[0]["blocks_moved"]
    total = el[0]["blocks_total"]
    if moved <= 0:
        raise AssertionError("elastic arm re-planned but moved no blocks")
    # the pre-elastic recovery: full restart on the survivor topology
    # (re-ingest + retrain; process startup excluded — conservative)
    restart_sec = max(r["total_sec"] for r in fresh)
    recovery_sec = max(r["recovery_sec"] for r in el)
    replan_sec = max(r["replan_sec"] for r in el)
    if not recovery_sec < restart_sec:
        raise AssertionError(
            f"elastic recovery ({recovery_sec:.2f}s) is not cheaper than "
            f"the full restart ({restart_sec:.2f}s) on this workload"
        )
    extra["elastic_reshard_recovery_sec"] = round(recovery_sec, 3)
    extra["elastic_reshard_replan_sec"] = round(replan_sec, 3)
    extra["elastic_reshard_restart_sec"] = round(restart_sec, 3)
    extra["elastic_reshard_speedup_vs_restart"] = round(
        restart_sec / recovery_sec, 2
    )
    extra["elastic_reshard_blocks_moved"] = int(moved)
    extra["elastic_reshard_blocks_total"] = int(total)
    extra["elastic_reshard_bitwise_equal"] = True
    extra["elastic_reshard_config"] = {
        k: fresh[0][k] for k in ("rows", "entities")
    }
    _log(
        f"elastic re-shard: lost 1/3 virtual owners mid-epoch, re-planned "
        f"+ resumed in {recovery_sec:.2f}s (re-plan {replan_sec:.2f}s, "
        f"{moved}/{total} blocks moved) vs {restart_sec:.2f}s full restart "
        f"({restart_sec / recovery_sec:.1f}x), digest BITWISE-equal to the "
        "fresh survivor-topology run"
    )


def _bench_streaming(extra, on_tpu):
    """Out-of-core fixed-effect solve (optim/streaming.py, VERDICT r3 #5):
    rows/sec through one chunk-streamed value+grad pass (mmap'd per-stream .npy chunks,
    host->device per chunk) vs the in-memory pass — the cost of training
    when data >> device+host memory."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.features import DenseFeatures
    from photon_ml_tpu.ops.normalization import NormalizationContext
    from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective
    from photon_ml_tpu.optim.streaming import (
        ChunkedGLMSource,
        make_streaming_value_and_grad,
        write_chunk_files,
    )

    n = 262144 if on_tpu else 65536
    d = 256
    rng = np.random.default_rng(5)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32) * 0.1
    y = (1.0 / (1.0 + np.exp(-x @ w_true)) > rng.random(n)).astype(np.float32)

    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()
    w = jnp.zeros((d,), jnp.float32)

    # in-memory reference pass (the 1x "everything fits" case)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    mem = jax.jit(lambda w, b: obj.value_and_grad(w, b, norm, 0.1))  # jit-ok: one-shot in-memory reference pass
    jax.block_until_ready(mem(w, batch))
    t0 = time.perf_counter()
    jax.block_until_ready(mem(w, batch))
    t_mem = time.perf_counter() - t0

    # streamed passes at 8 and 64 chunks per epoch (VERDICT r4 weak #3: a
    # one-chunk "stream" only measured a host->device round-trip). The chunk
    # count IS the data-to-resident-memory ratio: with chunk_rows resident,
    # n rows on disk is an n/chunk_rows x overcommit.
    for n_chunks in (8, 64):
        chunk_rows = n // n_chunks
        tmp = tempfile.mkdtemp(prefix="bench-stream-")
        try:
            write_chunk_files(tmp, x, y, chunk_rows=chunk_rows)
            src = ChunkedGLMSource.from_chunk_dir(tmp)
            vg = make_streaming_value_and_grad(src, obj, norm, l2_weight=0.1)
            jax.block_until_ready(vg(w))  # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(vg(w))
            t_stream = time.perf_counter() - t0
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        overhead = t_stream / max(t_mem, 1e-9)
        _log(
            f"streaming pass ({n_chunks} chunks x {chunk_rows} rows): "
            f"{n / t_stream:.3e} rows/s ({overhead:.1f}x the in-memory pass)"
        )
        if n_chunks == 8:  # headline: the 8x overcommit case
            extra["streaming_rows_per_sec"] = round(n / t_stream, 1)
            extra["streaming_overhead_vs_in_memory"] = round(overhead, 2)
            extra["streaming_config"] = {"rows": n, "d": d, "chunk_rows": chunk_rows}
        else:
            extra["streaming_rows_per_sec_64x"] = round(n / t_stream, 1)
            extra["streaming_overhead_vs_in_memory_64x"] = round(overhead, 2)


def _bench_streaming_pipeline(extra, on_tpu):
    """Async pipelined out-of-core random effects (io/pipeline.py +
    io/tensor_cache.py): (a) pipelined vs synchronous streaming-RE update
    wall-clock — block k+1's disk read + H2D overlap block k's vmapped
    solve, so pipelined time approaches max(ingest, compute) instead of
    their sum; (b) cold vs warm content-addressed tensor cache — the warm
    run skips grouping/padding/ingest entirely (measured build time ~0)
    and must produce BIT-identical coefficients."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from game_test_utils import make_glmix_data

    from photon_ml_tpu.algorithm.streaming_random_effect import (
        StreamingRandomEffectCoordinate,
        write_re_entity_blocks,
    )
    from photon_ml_tpu.data.game import RandomEffectDataConfig
    from photon_ml_tpu.io.tensor_cache import TensorCache
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.types import OptimizerType, TaskType

    num_users = 8000 if on_tpu else 600  # CPU fallback: smaller
    n_blocks = 32 if on_tpu else 8
    rng = np.random.default_rng(17)
    data, _ = make_glmix_data(
        rng, num_users=num_users, rows_per_user_range=(8, 16),
        d_fixed=8, d_random=16,
    )
    n = data.num_rows
    cfg = RandomEffectDataConfig("userId", "per_user")
    tmp = tempfile.mkdtemp(prefix="bench-pipeline-")
    try:
        cache = TensorCache(os.path.join(tmp, "cache"))
        # synthetic data has no source files: key on the generator config
        # (the role file stats play for real inputs)
        key = cache.key_for(
            [], {"bench": "streaming_pipeline", "users": num_users,
                 "blocks": n_blocks, "seed": 17},
        )
        t0 = time.perf_counter()
        manifest = write_re_entity_blocks(
            data, cfg, os.path.join(tmp, "unused"),
            block_entities=max(num_users // n_blocks, 1),
            tensor_cache=cache, cache_key=key,
        )
        t_cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        manifest_warm = write_re_entity_blocks(
            data, cfg, os.path.join(tmp, "unused2"),
            block_entities=max(num_users // n_blocks, 1),
            tensor_cache=cache, cache_key=key,
        )
        t_warm = time.perf_counter() - t0
        _log(
            f"tensor cache: cold build {t_cold:.3f}s, warm hit {t_warm:.4f}s "
            f"({len(manifest.blocks)} blocks)"
        )

        # pure ingest pass (no solve): the I/O + H2D leg of the pipeline —
        # what a perfectly-overlapped run could hide behind compute
        for _, ds, _, _ in manifest.iter_blocks(0):  # page-cache warm
            del ds
        t0 = time.perf_counter()
        for _, ds, _, _ in manifest.iter_blocks(0):
            jax.block_until_ready(ds.x)
            del ds
        t_io = time.perf_counter() - t0

        resid = jnp.zeros((n,), jnp.float32)

        def timed_update(mani, depth, tag):
            coord = StreamingRandomEffectCoordinate(
                mani, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=12, tolerance=1e-7),
                RegularizationContext.l2(0.1),
                state_root=os.path.join(tmp, f"state-{tag}"),
                prefetch_depth=depth,
            )
            coord.update(resid, coord.initial_coefficients())  # compile+warm
            t0 = time.perf_counter()
            state, _ = coord.update(resid, coord.initial_coefficients())
            dt = time.perf_counter() - t0
            coefs = [state.block(i) for i in range(len(mani.blocks))]
            return dt, coefs

        t_sync, coefs_sync = timed_update(manifest, 0, "sync")
        t_pipe, coefs_pipe = timed_update(manifest, 2, "pipe")
        t_warm_solve, coefs_warm = timed_update(manifest_warm, 2, "warm")

        identical = all(
            np.array_equal(a, b) and np.array_equal(a, c)
            for a, b, c in zip(coefs_sync, coefs_pipe, coefs_warm)
        )
        hidden = t_sync - t_pipe
        hideable = min(t_io, max(t_sync - t_io, 1e-9))
        overlap_eff = max(min(hidden / max(hideable, 1e-9), 1.0), 0.0)
        _log(
            f"streaming pipeline: sync {t_sync:.3f}s vs pipelined "
            f"{t_pipe:.3f}s ({t_sync / max(t_pipe, 1e-9):.2f}x; ingest leg "
            f"{t_io:.3f}s, overlap efficiency {overlap_eff:.2f}); "
            f"bit-identical={identical}"
        )
        extra["streaming_pipeline_sync_sec"] = round(t_sync, 4)
        extra["streaming_pipeline_pipelined_sec"] = round(t_pipe, 4)
        extra["streaming_pipeline_speedup"] = round(
            t_sync / max(t_pipe, 1e-9), 3
        )
        extra["streaming_pipeline_ingest_leg_sec"] = round(t_io, 4)
        extra["streaming_pipeline_overlap_efficiency"] = round(overlap_eff, 3)
        extra["streaming_pipeline_bit_identical"] = bool(identical)
        extra["tensor_cache_cold_build_sec"] = round(t_cold, 4)
        extra["tensor_cache_warm_hit_sec"] = round(t_warm, 4)
        extra["tensor_cache_warm_skip_ratio"] = round(
            t_warm / max(t_cold, 1e-9), 5
        )
        extra["streaming_pipeline_config"] = {
            "rows": n, "entities": num_users,
            "blocks": len(manifest.blocks), "d_random": 16,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_compile_reuse(extra, on_tpu):
    """Compile-once execution layer (photon_ml_tpu/compile/): (a) a
    multi-block streaming-RE update with shape canonicalization ON vs OFF —
    the ladder collapses N block shapes onto ~log(N) compiled solver
    executables (trace counts from CompileStats), with bit-identical
    coefficients and cold (compiling) vs warm (steady-state) wall-clock for
    both arms; (b) persistent XLA compilation cache cold vs warm across
    FRESH processes — the warm run must report zero new XLA compiles for
    the solver sites. The subprocesses run on CPU deliberately: cache
    behavior needs no accelerator, and grandchildren must never contend
    for the single-client device tunnel."""
    import shutil
    import subprocess
    import tempfile

    import jax
    import jax.numpy as jnp

    from game_test_utils import make_glmix_data

    from photon_ml_tpu.algorithm.streaming_random_effect import (
        StreamingRandomEffectCoordinate,
        write_re_entity_blocks,
    )
    from photon_ml_tpu.compile import ShapeBucketer, compile_stats
    from photon_ml_tpu.data.game import RandomEffectDataConfig
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.types import OptimizerType, TaskType

    num_users = 4096 if on_tpu else 512
    rng = np.random.default_rng(29)
    # skewed entity sizes: block max-counts differ, so WITHOUT the ladder
    # nearly every block carries its own shape (the N-compiles regime).
    # The extents sit in the ladder's verified bit-exact regime (sample
    # counts <= 16 at d_loc 4 — photon_ml_tpu/compile/canonical.py): the
    # on-vs-off coefficient comparison below is BITWISE, not allclose.
    data, _ = make_glmix_data(
        rng, num_users=num_users, rows_per_user_range=(4, 16),
        d_fixed=8, d_random=4,
    )
    n = data.num_rows
    cfg = RandomEffectDataConfig("userId", "per_user")
    resid = jnp.zeros((n,), jnp.float32)
    tmp = tempfile.mkdtemp(prefix="bench-compile-reuse-")
    try:
        results = {}
        for tag, bucketer in (("off", None), ("on", ShapeBucketer(8, 2.0))):
            manifest = write_re_entity_blocks(
                data, cfg, os.path.join(tmp, f"blocks-{tag}"),
                block_entities=max(num_users // 16, 1),
                bucketer=bucketer,
            )
            coord = StreamingRandomEffectCoordinate(
                manifest, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=10, tolerance=1e-7),
                RegularizationContext.l2(0.1),
                state_root=os.path.join(tmp, f"state-{tag}"),
            )
            compile_stats.reset()
            t0 = time.perf_counter()
            state, _ = coord.update(resid, coord.initial_coefficients())
            t_cold = time.perf_counter() - t0
            traces = compile_stats.traces_of("streaming_re.block_update")
            t0 = time.perf_counter()
            state, _ = coord.update(resid, coord.initial_coefficients())
            t_warm = time.perf_counter() - t0
            coefs = [state.block(i) for i in range(len(manifest.blocks))]
            results[tag] = dict(
                manifest=manifest, traces=traces, cold=t_cold, warm=t_warm,
                coefs=coefs,
            )
        off, on = results["off"], results["on"]
        # ladder pads lanes/samples at the END: slicing the ladder arm's
        # stacks back to the natural shapes must reproduce the off arm
        # bit for bit
        identical = all(
            c_on[: meta["num_entities"], : meta["local_dim"]].tobytes()
            == c_off.tobytes()
            for c_off, c_on, meta in zip(
                off["coefs"], on["coefs"], off["manifest"].blocks
            )
        )
        _log(
            f"compile reuse ({len(off['manifest'].blocks)} blocks): "
            f"ladder off {off['traces']} solver compiles, on {on['traces']} "
            f"({off['cold']:.2f}s->{off['warm']:.2f}s vs "
            f"{on['cold']:.2f}s->{on['warm']:.2f}s cold->warm); "
            f"bit-identical={identical}"
        )
        extra["compile_reuse_blocks"] = len(off["manifest"].blocks)
        extra["compile_reuse_solver_compiles_ladder_off"] = off["traces"]
        extra["compile_reuse_solver_compiles_ladder_on"] = on["traces"]
        extra["compile_reuse_fewer_compiles"] = bool(on["traces"] < off["traces"])
        extra["compile_reuse_bit_identical"] = bool(identical)
        extra["compile_reuse_cold_update_sec_ladder_off"] = round(off["cold"], 4)
        extra["compile_reuse_warm_update_sec_ladder_off"] = round(off["warm"], 4)
        extra["compile_reuse_cold_update_sec_ladder_on"] = round(on["cold"], 4)
        extra["compile_reuse_warm_update_sec_ladder_on"] = round(on["warm"], 4)
        extra["compile_reuse_config"] = {
            "rows": n, "entities": num_users, "d_random": 4,
            "blocks": len(off["manifest"].blocks),
        }

        # ---- persistent cache: cold vs warm across fresh processes --------
        cache_dir = os.path.join(tmp, "xla-cache")
        child_src = (
            "import os, json, time\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import numpy as np\n"
            "import jax, jax.numpy as jnp\n"
            "from photon_ml_tpu import compat\n"
            "from photon_ml_tpu.compile import compile_stats\n"
            "compile_stats.install_xla_listeners()\n"
            f"assert compat.enable_persistent_cache({cache_dir!r})\n"
            "from photon_ml_tpu.ops import losses\n"
            "from photon_ml_tpu.ops.normalization import NormalizationContext\n"
            "from photon_ml_tpu.ops.objective import GLMObjective\n"
            "from photon_ml_tpu.optim.streaming import (\n"
            "    ChunkedGLMSource, lbfgs_minimize_streaming,\n"
            "    make_streaming_value_and_grad)\n"
            "from photon_ml_tpu.optim.common import OptimizerConfig\n"
            "rng = np.random.default_rng(7)\n"
            "x = rng.normal(size=(4096, 64)).astype(np.float32)\n"
            "y = (rng.random(4096) < 0.5).astype(np.float32)\n"
            "src = ChunkedGLMSource.from_arrays(x, y, chunk_rows=1024)\n"
            "obj = GLMObjective(losses.logistic)\n"
            "vg = make_streaming_value_and_grad(\n"
            "    src, obj, NormalizationContext.identity(), l2_weight=0.1,\n"
            "    prefetch_depth=0)\n"
            "t0 = time.perf_counter()\n"
            "res = lbfgs_minimize_streaming(\n"
            "    vg, jnp.zeros((64,), jnp.float32),\n"
            "    OptimizerConfig(max_iterations=5, tolerance=1e-7))\n"
            "jax.block_until_ready(res.coefficients)\n"
            "print(json.dumps({'sec': time.perf_counter() - t0,\n"
            "                  'misses': compile_stats.xla_cache_misses,\n"
            "                  'hits': compile_stats.xla_cache_hits}))\n"
        )
        runs = []
        for arm in ("cold", "warm"):
            proc = subprocess.run(
                [sys.executable, "-c", child_src],
                capture_output=True, text=True, timeout=600,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f"persistent-cache {arm} child failed: {proc.stderr[-500:]}"
                )
            runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        cold, warm = runs
        _log(
            f"persistent cache: cold {cold['misses']} compiles "
            f"{cold['sec']:.2f}s; warm {warm['misses']} new compiles, "
            f"{warm['hits']} cache hits, {warm['sec']:.2f}s"
        )
        extra["persistent_cache_cold_compiles"] = cold["misses"]
        extra["persistent_cache_cold_sec"] = round(cold["sec"], 3)
        extra["persistent_cache_warm_new_compiles"] = warm["misses"]
        extra["persistent_cache_warm_hits"] = warm["hits"]
        extra["persistent_cache_warm_sec"] = round(warm["sec"], 3)
        extra["persistent_cache_fully_warm"] = bool(warm["misses"] == 0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_ingest(extra):
    """Data-loader throughput: native C++ avro columnar ingest vs the pure
    python codec on an identical synthetic GAME file (host-side; no
    accelerator involved)."""
    import os
    import tempfile

    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import avro_data, schemas
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io import native_build

    rng = np.random.default_rng(13)
    n_rows, n_feats = 20000, 30
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "part-0.avro")
        feature_pool = [f"f{i}" for i in range(2000)]

        def records():
            for i in range(n_rows):
                picks = rng.choice(2000, size=n_feats, replace=False)
                yield {
                    "uid": str(i),
                    "label": float(rng.random() < 0.5),
                    "features": [
                        {"name": feature_pool[j], "term": "", "value": float(rng.normal())}
                        for j in picks
                    ],
                    "offset": None,
                    "weight": None,
                    "metadataMap": {"userId": f"u{i % 500}"},
                }

        schema = {
            "name": "Row", "namespace": "b", "type": "record", "fields": [
                {"name": "uid", "type": ["null", "string"], "default": None},
                {"name": "label", "type": "double"},
                {"name": "features", "type": {"type": "array", "items": schemas.FEATURE}},
                {"name": "offset", "type": ["null", "double"], "default": None},
                {"name": "weight", "type": ["null", "double"], "default": None},
                {"name": "metadataMap",
                 "type": ["null", {"type": "map", "values": "string"}],
                 "default": None},
            ],
        }
        avro_io.write_container(path, records(), schema)
        imaps = {"g": IndexMap.build(
            avro_data.collect_feature_keys([path]), add_intercept=True)}
        sections = {"g": ["features"]}

        # the native path must actually be live (g++ built, columns decode)
        # or the entry would silently report python-vs-python as a "native"
        # result; the warm-up also keeps the one-time g++ compile of the
        # decoder OUT of the timed region
        from photon_ml_tpu.io import avro_native

        if avro_native.read_columns(path) is None:
            _log("ingest: native decoder unavailable; skipping ingest bench")
            extra["ingest_native_unavailable"] = True
            return

        timings = {}
        for mode in ("native", "python"):
            prev = os.environ.pop("PHOTON_ML_TPU_NATIVE", None)
            if mode == "python":
                os.environ["PHOTON_ML_TPU_NATIVE"] = "0"
            native_build._cache.clear()
            try:
                t0 = time.perf_counter()
                gd = avro_data.read_game_data([path], imaps, sections, ["userId"])
                timings[mode] = time.perf_counter() - t0
            finally:
                if prev is not None:
                    os.environ["PHOTON_ML_TPU_NATIVE"] = prev
                else:
                    os.environ.pop("PHOTON_ML_TPU_NATIVE", None)
                native_build._cache.clear()
        rps = n_rows / timings["native"]
        _log(
            f"ingest: native {timings['native']:.2f}s vs python "
            f"{timings['python']:.2f}s ({timings['python']/timings['native']:.1f}x), "
            f"{rps:.0f} rows/s"
        )
        extra["ingest_rows_per_sec_native"] = round(rps, 1)
        extra["ingest_speedup_vs_python"] = round(
            timings["python"] / timings["native"], 2
        )


def _make_game_parts(on_tpu, num_users=None):
    """Shared GAME bench fixture: fixed + per-user RE coordinates on synthetic
    GLMix data with 15% label flips (VERDICT r4 weak #5: separable data made
    ``game_train_auc: 1.0`` a toothless gate — flipped labels bound the
    achievable training AUC well below 1 so under-training is detectable)."""
    import jax.numpy as jnp

    from game_test_utils import make_glmix_data

    from photon_ml_tpu.algorithm import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.data.game import (
        RandomEffectDataConfig,
        build_fixed_effect_batch,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationProblem
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.types import OptimizerType, TaskType

    if num_users is None:
        num_users = 20000 if on_tpu else 2000  # CPU fallback: smaller
    rng = np.random.default_rng(11)
    data, _ = make_glmix_data(
        rng,
        num_users=num_users,
        rows_per_user_range=(8, 16),
        d_fixed=32,
        d_random=8,
    )
    n = data.num_rows
    flip = rng.random(n) < 0.15
    data.response[flip] = 1.0 - data.response[flip]
    _log(f"GAME bench: {n} rows, {num_users} entities (15% labels flipped)")

    fixed = FixedEffectCoordinate(
        build_fixed_effect_batch(data, "global", dense=True),
        GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=30, tolerance=1e-7),
            RegularizationContext.l2(1e-2),
        ),
    )
    re_ds = build_random_effect_dataset(data, RandomEffectDataConfig("userId", "per_user"))
    random_c = RandomEffectCoordinate(
        re_ds,
        TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=20, tolerance=1e-6),
        RegularizationContext.l2(1e-1),
    )
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))
    return fixed, random_c, loss_fn, labels, n, num_users


def _bench_game(extra, on_tpu):
    from photon_ml_tpu.algorithm import CoordinateDescent

    fixed, random_c, loss_fn, labels, n, num_users = _make_game_parts(on_tpu)

    iters = 3
    per_iter = {}
    for fused in (False, True):
        cd = CoordinateDescent(
            {"fixed": fixed, "random": random_c}, loss_fn, fused_cycle=fused
        )
        cd.run(num_iterations=1, num_rows=n)  # compile + warm (cached executables)
        t0 = time.perf_counter()
        result = cd.run(num_iterations=iters, num_rows=n)
        result.total_scores.block_until_ready()
        per_iter[fused] = (time.perf_counter() - t0) / iters
        _log(
            f"GAME coord-descent ({'fused cycle' if fused else 'per-update'}): "
            f"{per_iter[fused]:.3f} s/iter"
        )
    # headline number = the better mode (fused cuts host dispatches ~8x);
    # both raw measurements recorded for round-over-round comparison
    extra["game_coord_descent_sec_per_iter"] = round(min(per_iter.values()), 4)
    extra["game_coord_descent_sec_per_iter_unfused"] = round(per_iter[False], 4)
    extra["game_coord_descent_sec_per_iter_fused"] = round(per_iter[True], 4)
    extra["game_config"] = {"rows": n, "entities": num_users, "d_fixed": 32, "d_random": 8}
    # the declared metric is "iter time @ fixed AUC" — record the AUC the
    # timed model actually reaches so the timing is tied to model quality
    # (full correctness gates live in PARITY.md; this is the in-bench tie)
    from photon_ml_tpu.evaluation.evaluators import area_under_roc_curve

    extra["game_train_auc"] = round(
        float(area_under_roc_curve(result.total_scores, labels)), 4
    )


def _bench_grid(extra, on_tpu):
    """Lambda-grid through the traced-lambda grid API
    (CoordinateDescent.run_grid, ONE compiled cycle for all combos) vs the
    reference-style per-combo rebuild (a fresh CoordinateDescent per combo,
    each paying its own trace+compile — what re-running the driver per
    combo costs, cli/game/training/Driver.scala:330-337). Compile time is
    IN both arms: compile amortization is the feature's win. The batched
    G-lane vmapped variant raced here in rounds 2-4, lost every measured
    race (0.8-0.86x), and was removed (VERDICT r4 #9)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.algorithm import CoordinateDescent

    g_lams = [0.01, 0.1, 1.0, 10.0]
    # data built ONCE, outside both timers: the comparison is grid
    # strategies, not data construction. Coordinate objects are rebuilt
    # per combo in the rebuild arm (fresh objects drop the jit caches —
    # that IS the re-trace cost being measured), but they share these
    # prebuilt parts.
    fixed, random_c, loss_fn, _, n, _ = _make_game_parts(on_tpu)
    lam = {
        "fixed": jnp.asarray(g_lams),
        "random": jnp.asarray([0.1] * len(g_lams)),
    }
    t0 = time.perf_counter()
    cd_g = CoordinateDescent({"fixed": fixed, "random": random_c}, loss_fn)
    grid_results = cd_g.run_grid(lam, num_iterations=2, num_rows=n)
    jax.block_until_ready(grid_results[-1].total_scores)
    t_shared = time.perf_counter() - t0

    import dataclasses as _dc

    t0 = time.perf_counter()
    for gl in g_lams:
        # the reference-style arm: every combo re-traces and re-compiles
        # its own descent AT ITS OWN LAMBDA (per-combo solve cost is
        # strongly lambda-dependent, so each combo must do the same solve
        # work as its shared-compile counterpart)
        f2 = _dc.replace(
            fixed,
            problem=_dc.replace(
                fixed.problem,
                regularization=type(fixed.problem.regularization).l2(gl),
            ),
        )
        cd_i = CoordinateDescent({"fixed": f2, "random": random_c}, loss_fn)
        r = cd_i.run(num_iterations=2, num_rows=n)
    jax.block_until_ready(r.total_scores)
    t_rebuild = time.perf_counter() - t0
    _log(
        f"GAME lambda-grid x{len(g_lams)}: shared-compile {t_shared:.3f}s "
        f"vs per-combo rebuild {t_rebuild:.3f}s "
        f"({t_rebuild / t_shared:.2f}x)"
    )
    extra["game_grid_shared_compile_sec"] = round(t_shared, 3)
    extra["game_grid_percombo_rebuild_sec"] = round(t_rebuild, 3)
    extra["game_grid_speedup"] = round(t_rebuild / t_shared, 2)
    extra["game_grid_note"] = (
        "vmapped G-lane variant removed (lost every measured race, "
        "VERDICT r4 #9); speedup = compile amortization of the "
        "traced-lambda grid vs per-combo re-trace"
    )


def _bench_game5(extra, on_tpu):
    """Full-GAME shape (BASELINE config 5): fixed + per-user RE + per-item
    RE + factored per-artist MF coordinate, fused-cycle coordinate descent.
    Reference analogue: cli/game/training/DriverTest full-model runs."""
    import jax.numpy as jnp

    from game_test_utils import make_full_game_coords, make_full_game_data

    from photon_ml_tpu.algorithm import CoordinateDescent
    from photon_ml_tpu.evaluation.evaluators import area_under_roc_curve
    from photon_ml_tpu.ops import losses

    scale = 1 if on_tpu else 10  # CPU fallback: smaller
    rng = np.random.default_rng(23)
    data, _ = make_full_game_data(
        rng,
        num_users=10000 // scale,
        num_items=2000 // scale,
        num_artists=200 // scale,
        rows_per_user_range=(8, 16),
        d_fixed=32,
        d_user=8,
        d_item=8,
        d_artist=16,
    )
    n = data.num_rows
    flip = rng.random(n) < 0.15  # non-separable labels: AUC gate has teeth
    data.response[flip] = 1.0 - data.response[flip]
    _log(f"GAME5 bench: {n} rows, {10000 // scale} users, "
         f"{2000 // scale} items, {200 // scale} artists (15% labels flipped)")

    # the same 4-coordinate wiring the correctness test validates
    coords = make_full_game_coords(data, fe_iters=30, re_iters=20, latent_dim=4)
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))

    iters = 3
    cd = CoordinateDescent(coords, loss_fn, fused_cycle=True)
    cd.run(num_iterations=1, num_rows=n)  # compile + warm
    t0 = time.perf_counter()
    result = cd.run(num_iterations=iters, num_rows=n)
    result.total_scores.block_until_ready()
    per_iter = (time.perf_counter() - t0) / iters
    _log(f"GAME5 coord-descent (fused cycle, 4 coords): {per_iter:.3f} s/iter")
    extra["game5_coord_descent_sec_per_iter"] = round(per_iter, 4)
    extra["game5_train_auc"] = round(
        float(area_under_roc_curve(result.total_scores, labels)), 4
    )
    extra["game5_config"] = {
        "rows": n,
        "users": 10000 // scale,
        "items": 2000 // scale,
        "artists": 200 // scale,
        "coords": "fixed+per-user+per-item+factored(latent=4)",
    }


def _bench_sparse_race(extra, on_tpu):
    """Fused sparse per-entity kernel race (ops/fused_sparse.py) on a
    SKEWED nnz distribution — the production per-entity regime: most rows
    carry a handful of non-zeros, a few are dense-ish, and the dense
    (E, M, D) slab pays full MXU/HBM cost for all of them. Races every
    sparse family (XLA scatter, XLA two-pass segment-sum baseline, fused
    single-pass Pallas GEVM incl. row-blocked variants) AND the dense
    incumbent through the solver-identical vmapped value+grad closure;
    records every candidate (failures with reasons — a candidate that
    failed to compile reads as failed, not absent), then gates the
    selected sparse family end-to-end through the compacted scheduler:
    bitwise-equal coefficients vs the kernel-off (segment baseline) path
    and ZERO extra XLA compiles after warmup (CompileStats-asserted)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.compile import compile_stats
    from photon_ml_tpu.ops import fused_sparse
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.scheduler import SolveSchedule, compacted_solve
    from photon_ml_tpu.types import OptimizerType, TaskType

    E = 1024 if on_tpu else 256
    M, D = 64, 2048
    rng = np.random.default_rng(17)
    # skewed nnz over a WIDE feature space: 85% of rows draw 1-4 non-zeros,
    # 15% draw 8-16 — the long-tail production shape (density < 1%) where
    # the dense slab pays D=2048 MXU/HBM columns for a handful of non-zeros
    nnz = np.where(
        rng.random((E, M)) < 0.85,
        rng.integers(1, 5, size=(E, M)),
        rng.integers(8, 17, size=(E, M)),
    )
    x = np.zeros((E, M, D), np.float32)
    for e in range(E):
        for m in range(M):
            cols = rng.choice(D, size=nnz[e, m], replace=False)
            x[e, m, cols] = rng.normal(size=nnz[e, m])
    w_true = (rng.normal(size=(E, D)) * 0.4).astype(np.float32)
    z = np.einsum("emd,ed->em", x.astype(np.float64), w_true)
    y = jnp.asarray((1.0 / (1.0 + np.exp(-z)) > rng.random((E, M))).astype(np.float32))
    off = jnp.zeros((E, M), jnp.float32)
    wt = jnp.ones((E, M), jnp.float32)

    slab = fused_sparse.build_sparse_slab(x)
    report = fused_sparse.race_sparse_kernels(
        TaskType.LOGISTIC_REGRESSION, slab, x, y, off, wt
    )
    extra["sparse_race"] = report
    stats = report["nnz"]
    _log(
        f"sparse_race: E={E} M={M} D={D} K={stats['padded_k']} "
        f"(mean nnz {stats['mean_nnz']}, density {stats['density']}); "
        f"winner={report['winner'] or 'dense'}"
    )
    for name, rec in sorted(report["candidates"].items()):
        if "failed" in rec:
            _log(f"  {name}: FAILED — {rec['failed']}")
        else:
            _log(f"  {name}: {rec['sec_per_pass']:.2e} s/pass")

    timed = {
        name: rec["sec_per_pass"]
        for name, rec in report["candidates"].items()
        if "sec_per_pass" in rec and name != "dense"
    }
    if not timed:
        raise AssertionError(
            "no sparse candidate survived the race "
            f"({ {n: r.get('failed') for n, r in report['candidates'].items()} })"
        )
    best_sparse = min(timed, key=timed.get)
    baseline_sec = timed.get(fused_sparse.SPARSE_BASELINE)
    extra["sparse_race_selected"] = best_sparse
    if baseline_sec:
        extra["sparse_race_speedup_vs_xla2pass"] = round(
            baseline_sec / timed[best_sparse], 3
        )
        _log(
            f"sparse_race: selected {best_sparse} at "
            f"{extra['sparse_race_speedup_vs_xla2pass']}x the "
            f"two-pass XLA baseline"
        )

    # end-to-end gate through the compacted scheduler: the selected family
    # must produce BITWISE the segment-baseline coefficients, and warm
    # re-solves must add zero XLA compiles
    cfg = OptimizerConfig(max_iterations=60, tolerance=1e-7)
    kw = dict(
        task=TaskType.LOGISTIC_REGRESSION,
        optimizer=OptimizerType.LBFGS,
        optimizer_config=cfg,
        regularization=RegularizationContext.l2(0.5),
    )
    w0 = jnp.zeros((E, D), jnp.float32)
    schedule = SolveSchedule(chunk_size=16)

    def solve(family):
        data = (slab.with_kernel(family), y, off, wt)
        res = compacted_solve(data, w0, schedule=schedule,
                              label=f"sparse_race[{family}]", **kw)
        jax.block_until_ready(res.coefficients)
        return res

    ref = solve(fused_sparse.SPARSE_BASELINE)
    got = solve(best_sparse)  # warmup (compiles the family's executables)
    mark = compile_stats.watermark()
    t0 = time.perf_counter()
    got = solve(best_sparse)
    t_sparse = time.perf_counter() - t0
    if not mark.clean():
        raise AssertionError(
            f"{mark.new_traces()} new traces / {mark.new_xla_misses()} XLA "
            "cache misses on a warm sparse re-solve — executable reuse "
            "regressed"
        )
    bitwise = np.array_equal(
        np.asarray(got.coefficients), np.asarray(ref.coefficients)
    )
    if not bitwise:
        raise AssertionError(
            f"solve through {best_sparse} is not bitwise-equal to the "
            "kernel-off (segment baseline) path"
        )
    # the honest dense-vs-sparse end-to-end number (different arithmetic,
    # so no bitwise claim — the race already decided who runs production)
    dense_data = tuple(jnp.asarray(a) for a in (x, np.asarray(y), np.zeros((E, M), np.float32), np.ones((E, M), np.float32)))
    compacted_solve(dense_data, w0, schedule=schedule, label="sparse_race[dense]", **kw)
    t0 = time.perf_counter()
    res_d = compacted_solve(dense_data, w0, schedule=schedule, label="sparse_race[dense]", **kw)
    jax.block_until_ready(res_d.coefficients)
    t_dense = time.perf_counter() - t0
    extra["sparse_race_bitwise_vs_kernel_off"] = bool(bitwise)
    extra["sparse_race_warm_new_compiles"] = 0
    extra["sparse_race_solve_ms"] = round(t_sparse * 1e3, 2)
    extra["sparse_race_dense_solve_ms"] = round(t_dense * 1e3, 2)
    extra["sparse_race_solve_speedup_vs_dense"] = round(
        t_dense / max(t_sparse, 1e-9), 3
    )
    _log(
        f"sparse_race: end-to-end {best_sparse} solve {t_sparse*1e3:.1f}ms vs "
        f"dense {t_dense*1e3:.1f}ms "
        f"({extra['sparse_race_solve_speedup_vs_dense']}x), bitwise vs "
        f"kernel-off, zero warm compiles"
    )


def _bench_compaction(extra, on_tpu):
    """Convergence-compacted solve scheduler (optim/scheduler.py) on a
    SKEWED convergence distribution — a few badly-conditioned entities next
    to many easy ones, the GLMix shape SURVEY §7.3 calls out: one-shot
    vmapping burns every lane until the slowest converges; the scheduler
    chunks the solve and repacks active lanes onto the ladder. Measures
    saved lane-iterations, wall-clock vs the one-shot kernel, bitwise
    equality, and ladder executable reuse (zero extra XLA compiles after
    the first compaction step, via CompileStats)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.algorithm.random_effect import entity_lane_fns
    from photon_ml_tpu.compile import compile_stats
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.scheduler import (
        SolveSchedule,
        compacted_solve,
        solve_stats,
    )
    from photon_ml_tpu.types import OptimizerType, TaskType

    E = 2048 if on_tpu else 512
    M, D, hard = 32, 16, 8
    rng = np.random.default_rng(11)
    x = rng.normal(size=(E, M, D)).astype(np.float32)
    # skew: a handful of ill-conditioned straggler lanes (big feature scale
    # -> big curvature spread -> 2-4x the iterations of the easy lanes,
    # which the L2 weight below makes converge within the FIRST chunk)
    x[:hard] *= np.geomspace(1.0, 64.0, D).astype(np.float32)
    w_true = (rng.normal(size=(E, D)) * 0.5).astype(np.float32)
    z = np.einsum("emd,ed->em", x.astype(np.float64), w_true)
    y = (1.0 / (1.0 + np.exp(-z)) > rng.random((E, M))).astype(np.float32)
    data = tuple(
        jnp.asarray(a)
        for a in (x, y, np.zeros((E, M), np.float32), np.ones((E, M), np.float32))
    )
    w0 = jnp.zeros((E, D), jnp.float32)

    task = TaskType.LOGISTIC_REGRESSION
    opt = OptimizerType.LBFGS
    cfg = OptimizerConfig(max_iterations=120, tolerance=1e-7)
    reg = RegularizationContext.l2(1.0)
    kw = dict(task=task, optimizer=opt, optimizer_config=cfg, regularization=reg)

    solve_one, *_ = entity_lane_fns(task, opt, cfg, reg)
    one_shot = jax.jit(jax.vmap(solve_one))  # jit-ok: bench baseline; inputs reused across reps
    ref = jax.block_until_ready(one_shot(*data, w0))  # compile + warm
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        ref = one_shot(*data, w0)
    jax.block_until_ready(ref)
    t_one = (time.perf_counter() - t0) / reps

    schedule = SolveSchedule(chunk_size=16)
    sites = ("scheduler.init", "scheduler.chunk",
             "scheduler.compact", "scheduler.scatter")
    traces_cold = {s: compile_stats.traces_of(s) for s in sites}
    solve_stats.reset()
    res = compacted_solve(data, w0, schedule=schedule, label="bench", **kw)
    jax.block_until_ready(res.coefficients)
    # ladder reuse WITHIN the first solve: one init + one full-batch chunk
    # + the first compacted rung's chunk/compact/scatter — every compaction
    # step after the first must reuse those executables, so exactly 5 new
    # traces appear (asserted below as zero EXTRA compiles)
    first_decay = " -> ".join(
        f"{c.active_lanes}/{c.batch_lanes}@{c.limit}"
        for c in solve_stats.snapshot()[-1].chunks
    )
    extra_compiles = (
        sum(compile_stats.traces_of(s) - traces_cold[s] for s in sites) - 5
    )
    traces_warm = {s: compile_stats.traces_of(s) for s in sites}
    solve_stats.reset()
    t0 = time.perf_counter()
    for _ in range(reps):
        res = compacted_solve(data, w0, schedule=schedule, label="bench", **kw)
    jax.block_until_ready(res.coefficients)
    t_comp = (time.perf_counter() - t0) / reps
    # steady state: identical warm solves add zero traces at any site
    extra_compiles += sum(
        compile_stats.traces_of(s) - traces_warm[s] for s in sites
    )

    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)
        for a, b in zip(res[:7], ref[:7])
        if a is not None
    )
    ledger = solve_stats.totals()
    saved = ledger["saved_lane_iterations"] // reps
    _log(
        f"compaction: E={E} (hard={hard}) one-shot {t_one*1e3:.1f}ms vs "
        f"compacted {t_comp*1e3:.1f}ms ({t_one/max(t_comp,1e-9):.2f}x); "
        f"saved {saved} lane-iterations/solve "
        f"({100*saved/max(ledger['baseline_lane_iterations']//reps,1):.1f}%), "
        f"bitwise={bitwise}, extra compiles after first compaction={extra_compiles}"
    )
    _log(f"compaction: first-solve active-lane decay: {first_decay}")
    _log(solve_stats.summary())
    if not bitwise:
        raise AssertionError("compacted solve is not bitwise-equal to one-shot")
    if saved <= 0:
        raise AssertionError(f"no lane-iterations saved ({saved})")
    if extra_compiles != 0:
        raise AssertionError(
            f"{extra_compiles} extra XLA compiles after the first compaction "
            "step — ladder reuse regressed"
        )
    extra["compaction_oneshot_ms"] = round(t_one * 1e3, 2)
    extra["compaction_compacted_ms"] = round(t_comp * 1e3, 2)
    extra["compaction_speedup"] = round(t_one / max(t_comp, 1e-9), 3)
    extra["compaction_saved_lane_iters_per_solve"] = int(saved)
    extra["compaction_saved_pct"] = round(
        100.0 * saved / max(ledger["baseline_lane_iterations"] // reps, 1), 1
    )
    extra["compaction_bitwise_equal"] = bool(bitwise)
    extra["compaction_extra_compiles_after_first"] = int(extra_compiles)
    extra["compaction_config"] = {
        "entities": E, "hard": hard, "samples": M, "dim": D,
        "chunk": schedule.chunk_size, "max_iter": cfg.max_iterations,
    }


def _merge_shards(n_shards):
    """Deterministic disjoint per-shard partials for the merge arms: a
    (n_shards, 4096, 16) float32 block where every row is written by
    exactly ONE shard (round-robin owner draw) — the merge_disjoint
    exactness precondition, so the host fold, the 2-process Gloo merge,
    and the device psum must all produce the SAME bytes."""
    rng = np.random.default_rng(17)
    rows, dim = 4096, 16
    full = rng.normal(size=(rows, dim)).astype(np.float32)
    shards = np.zeros((n_shards, rows, dim), np.float32)
    owners = rng.integers(0, n_shards, size=rows)
    shards[owners, np.arange(rows)] = full
    return shards


def _merge_worker_main(argv):
    """Child mode (``--merge-worker PID NPROCS PORT OUTDIR N_SHARDS``): one
    Gloo process of the fused_schedule section's merge comparator — the
    HOST-side exact-merge path (parallel/perhost_streaming.merge_disjoint
    over a real process group) timed on the same deterministic disjoint
    partials the in-process psum arm merges on the device mesh."""
    import hashlib
    import json as _json

    i = argv.index("--merge-worker")
    pid, nprocs, port, outdir, n_shards = (
        int(argv[i + 1]), int(argv[i + 2]), argv[i + 3], argv[i + 4],
        int(argv[i + 5]),
    )
    import jax

    jax.config.update("jax_platforms", "cpu")

    from photon_ml_tpu.parallel import multihost
    from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
    from photon_ml_tpu.parallel.perhost_streaming import merge_disjoint

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=nprocs,
        process_id=pid,
    )
    ctx = MeshContext(data_mesh())
    shards = _merge_shards(n_shards)
    # this host's partial: the fold of its round-robin share — still
    # disjoint ACROSS hosts (every element is written by at most one
    # shard, and each shard belongs to exactly one host)
    local = np.zeros(shards.shape[1:], shards.dtype)
    for s in range(pid, n_shards, nprocs):
        local = local + shards[s]
    merged = merge_disjoint(local, ctx, nprocs)  # warm the collective
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        merged = merge_disjoint(local, ctx, nprocs)
    sec = (time.perf_counter() - t0) / reps
    out = {
        "process": pid,
        "sec_per_merge": sec,
        "digest": hashlib.sha256(
            np.ascontiguousarray(merged).tobytes()
        ).hexdigest(),
    }
    with open(os.path.join(outdir, f"merge-{pid}.json"), "w") as f:
        _json.dump(out, f)


def _bench_fused_schedule(extra, on_tpu):
    """On-device whole-cycle compaction (optim/fused_schedule.py): the
    chunk->compact->resume loop fused into one XLA program per ladder
    rung vs the host chunk loop, on the skewed 8-hard/512-easy workload —
    sec/solve, HOST DISPATCHES per solve (the O(#rungs) claim), and the
    bitwise gate; plus the exact-merge arms: in-process shard_map+psum
    over the local device mesh vs the 2-process Gloo path on identical
    disjoint partials (same merge_disjoint discipline). The psum arm
    needs a multi-device mesh: absent the forced CPU flag it records a
    structured ``preflight:`` skip instead of wedging."""
    import hashlib
    import subprocess
    import tempfile

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu import compat
    from photon_ml_tpu.optim import fused_schedule
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.scheduler import (
        SolveSchedule,
        compacted_solve,
        solve_stats,
    )
    from photon_ml_tpu.types import OptimizerType, TaskType

    E = 2048 if on_tpu else 520  # 8 hard stragglers among the easy rest
    M, D, hard = 32, 16, 8
    rng = np.random.default_rng(11)
    x = rng.normal(size=(E, M, D)).astype(np.float32)
    x[:hard] *= np.geomspace(1.0, 64.0, D).astype(np.float32)
    w_true = (rng.normal(size=(E, D)) * 0.5).astype(np.float32)
    z = np.einsum("emd,ed->em", x.astype(np.float64), w_true)
    y = (1.0 / (1.0 + np.exp(-z)) > rng.random((E, M))).astype(np.float32)
    data = tuple(
        jnp.asarray(a)
        for a in (x, y, np.zeros((E, M), np.float32), np.ones((E, M), np.float32))
    )
    w0 = jnp.zeros((E, D), jnp.float32)
    cfg = OptimizerConfig(max_iterations=120, tolerance=1e-7)
    kw = dict(
        task=TaskType.LOGISTIC_REGRESSION, optimizer=OptimizerType.LBFGS,
        optimizer_config=cfg, regularization=RegularizationContext.l2(1.0),
    )
    host_sched = SolveSchedule(chunk_size=16)
    dev_sched = SolveSchedule(chunk_size=16, loop="device")

    ref = compacted_solve(data, w0, schedule=host_sched, label="warm_host", **kw)
    res = compacted_solve(data, w0, schedule=dev_sched, label="warm_dev", **kw)
    jax.block_until_ready(res.coefficients)
    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)
        for a, b in zip(res[:7], ref[:7])
        if a is not None
    )
    reps = 3
    solve_stats.reset()
    t0 = time.perf_counter()
    for _ in range(reps):
        ref = compacted_solve(
            data, w0, schedule=host_sched, label="host", **kw
        )
    jax.block_until_ready(ref.coefficients)
    t_host = (time.perf_counter() - t0) / reps
    rec_host = solve_stats.snapshot()[-1]
    t0 = time.perf_counter()
    for _ in range(reps):
        res = compacted_solve(data, w0, schedule=dev_sched, label="dev", **kw)
    jax.block_until_ready(res.coefficients)
    t_dev = (time.perf_counter() - t0) / reps
    rec_dev = solve_stats.snapshot()[-1]

    ladder = fused_schedule.rung_ladder(host_sched.bucketer, E)
    hops = " -> ".join(
        f"{c.active_lanes}/{c.batch_lanes}@{c.limit}" for c in rec_dev.chunks
    )
    _log(
        f"fused_schedule: E={E} (hard={hard}) host loop {t_host*1e3:.1f}ms"
        f"/{rec_host.dispatches} dispatches vs device loop "
        f"{t_dev*1e3:.1f}ms/{rec_dev.dispatches} dispatches "
        f"({rec_dev.device_chunks} in-program chunks), bitwise={bitwise}"
    )
    _log(f"fused_schedule: rung hops: {hops}")
    if not bitwise:
        raise AssertionError(
            "device loop is not bitwise-equal to the host chunk loop"
        )
    if rec_dev.executed != rec_host.executed:
        raise AssertionError(
            f"device ledger executed {rec_dev.executed} != host "
            f"{rec_host.executed} — the re-batching exactness claim broke"
        )
    if rec_dev.dispatches > len(ladder):
        raise AssertionError(
            f"device loop paid {rec_dev.dispatches} dispatches on a "
            f"{len(ladder)}-rung ladder — the O(#rungs) claim broke"
        )
    if rec_dev.dispatches >= rec_host.dispatches:
        raise AssertionError(
            f"device loop saved no dispatches ({rec_dev.dispatches} vs "
            f"host {rec_host.dispatches})"
        )
    extra["fused_schedule_host_ms"] = round(t_host * 1e3, 2)
    extra["fused_schedule_device_ms"] = round(t_dev * 1e3, 2)
    extra["fused_schedule_speedup"] = round(t_host / max(t_dev, 1e-9), 3)
    extra["fused_schedule_host_dispatches"] = int(rec_host.dispatches)
    extra["fused_schedule_device_dispatches"] = int(rec_dev.dispatches)
    extra["fused_schedule_device_chunks"] = int(rec_dev.device_chunks)
    extra["fused_schedule_bitwise_equal"] = bool(bitwise)
    extra["fused_schedule_config"] = {
        "entities": E, "hard": hard, "samples": M, "dim": D,
        "chunk": 16, "max_iter": cfg.max_iterations,
        "ladder_rungs": len(ladder),
    }

    # ---- exact-merge arms: device psum vs the 2-process Gloo path -------
    devs = jax.devices()
    n_dev = len(devs)
    psum_digest = None
    if n_dev < 2:
        forced = compat.forced_cpu_device_count()
        reason = (
            f"preflight: single-device {devs[0].platform} backend "
            f"(forced_cpu_devices={forced!r}); the psum merge arm needs a "
            "multi-device mesh — set --xla_force_host_platform_device_count"
        )
        extra["fused_schedule_psum"] = {"skipped": reason}
        _log(f"fused_schedule psum arm SKIPPED ({reason})")
    else:
        from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
        from photon_ml_tpu.parallel.perhost_streaming import (
            merge_disjoint_devices,
        )

        ctx = MeshContext(data_mesh())
        shards = _merge_shards(n_dev)
        merged = merge_disjoint_devices(shards, ctx)  # warm
        t0 = time.perf_counter()
        for _ in range(5):
            merged = merge_disjoint_devices(shards, ctx)
        t_psum = (time.perf_counter() - t0) / 5
        # exactness gate vs the host-side fold of the same partials
        fold = np.zeros(shards.shape[1:], shards.dtype)
        for s in range(n_dev):
            fold = fold + shards[s]
        if not np.array_equal(merged, fold):
            raise AssertionError(
                "device psum merge is not bitwise-equal to the host fold"
            )
        psum_digest = hashlib.sha256(
            np.ascontiguousarray(merged).tobytes()
        ).hexdigest()
        extra["fused_schedule_psum"] = {
            "devices": n_dev,
            "sec_per_merge": round(t_psum, 6),
            "digest": psum_digest[:16],
        }
        _log(
            f"fused_schedule: psum merge over {n_dev} devices "
            f"{t_psum*1e3:.2f}ms/merge"
        )

    # Gloo comparator: the same partials through the real 2-process
    # host-merge path (subprocess-fenced, cohort-killed on any failure)
    import socket

    n_shards = max(n_dev, 2)
    here = os.path.abspath(__file__)
    out = tempfile.mkdtemp(prefix="fused-merge-bench-")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    log_paths = [os.path.join(out, f"merge-worker-{p}.log") for p in range(2)]
    procs = []
    try:
        for p in range(2):
            with open(log_paths[p], "w") as lf:
                procs.append(subprocess.Popen(
                    [sys.executable, here, "--merge-worker", str(p), "2",
                     str(port), out, str(n_shards)],
                    stdout=subprocess.DEVNULL, stderr=lf, env=env,
                ))
        for p_id, p in enumerate(procs):
            try:
                p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                p.kill()
                p.communicate()
                raise RuntimeError(
                    f"merge worker {p_id} exceeded 300s: see {log_paths[p_id]}"
                )
            if p.returncode != 0:
                with open(log_paths[p_id]) as lf:
                    tail = lf.read()[-1500:]
                raise RuntimeError(
                    f"merge worker {p_id} failed rc={p.returncode}:\n{tail}"
                )
        results = []
        for p_id in range(2):
            with open(os.path.join(out, f"merge-{p_id}.json")) as f:
                results.append(json.load(f))
    except BaseException:  # noqa: BLE001 — cohort cleanup then re-raise (a stranded Gloo peer contends with every later section)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        raise
    finally:
        import shutil

        shutil.rmtree(out, ignore_errors=True)
    gloo_digest = results[0]["digest"]
    if results[1]["digest"] != gloo_digest:
        raise AssertionError(
            "Gloo merge digests disagree across processes: "
            f"{[r['digest'][:12] for r in results]}"
        )
    if psum_digest is not None and gloo_digest != psum_digest:
        raise AssertionError(
            "psum and Gloo merges of the same disjoint partials disagree: "
            f"{psum_digest[:12]} vs {gloo_digest[:12]} — the exact-merge "
            "discipline broke"
        )
    t_gloo = max(r["sec_per_merge"] for r in results)
    extra["fused_schedule_gloo"] = {
        "processes": 2,
        "sec_per_merge": round(t_gloo, 6),
        "digest": gloo_digest[:16],
        "matches_psum": bool(psum_digest is not None),
    }
    _log(
        f"fused_schedule: Gloo merge over 2 processes {t_gloo*1e3:.2f}ms"
        "/merge"
        + (", digest matches psum arm" if psum_digest is not None else "")
    )


def _bench_adaptive_schedule(extra, on_tpu):
    """Gap-guided adaptive solve scheduling (optim/convergence.py) on a
    SKEWED block-convergence workload — 8 ill-conditioned entities in
    their own block next to 512 easy ones: streaming CD should spend its
    epochs where convergence lives, not re-solving blocks that are done.
    Measures, for a single-host and a 2-process per-host arm: (1) the
    bitwise pin — the ordering-only mode (tolerance 0) must reproduce the
    always-visit digest bit-for-bit on every host; (2) tolerance mode's
    fleet-summed lane-iteration saving (>=30% required) at equal final
    objective tolerance, plus epochs-to-tolerance; (3) a fully-warm rerun
    of the tolerance arm that must compile nothing new."""
    import shutil
    import subprocess
    import tempfile

    here = os.path.abspath(__file__)
    out = tempfile.mkdtemp(prefix="adaptive-bench-")

    def run_arm(nprocs, adaptive, timeout):
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["JAX_PLATFORMS"] = "cpu"
        # every policy pinned off except the arm's own knob: an ambient
        # PHOTON_* leftover must not change what this arm measures
        env.update({
            "PHOTON_SOLVE_CHUNK": "off",
            "PHOTON_SPARSE_KERNEL": "off",
            "PHOTON_SHAPE_LADDER": "off",
            "PHOTON_ADAPTIVE_SCHEDULE": adaptive,
        })
        log_paths = [
            os.path.join(out, f"worker-n{nprocs}-{adaptive}-{p}.log")
            for p in range(nprocs)
        ]
        procs = []
        for p in range(nprocs):
            with open(log_paths[p], "w") as lf:
                procs.append(subprocess.Popen(
                    [sys.executable, here, "--perhost-worker", str(p),
                     str(nprocs), str(port), out, "adaptive"],
                    stdout=subprocess.DEVNULL, stderr=lf, env=env,
                ))

        def tail(p_id):
            try:
                with open(log_paths[p_id]) as lf:
                    return lf.read()[-1500:]
            except OSError:
                return "<no worker log>"

        try:
            for p_id, p in enumerate(procs):
                try:
                    p.communicate(timeout=timeout)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.communicate()
                    raise RuntimeError(
                        f"adaptive worker ({nprocs} proc, {adaptive!r}) "
                        f"exceeded {timeout}s:\n{tail(p_id)}"
                    )
                if p.returncode != 0:
                    raise RuntimeError(
                        f"adaptive worker failed rc={p.returncode}:\n"
                        f"{tail(p_id)}"
                    )
        except BaseException:  # noqa: BLE001 — cohort cleanup then re-raise (a stranded Gloo peer contends with every later section)
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            raise
        results = []
        for p_id in range(nprocs):
            with open(os.path.join(
                out, f"perhost-n{nprocs}-adaptive-{p_id}.json"
            )) as f:
                results.append(json.load(f))
        return results

    # the DECLARED tolerance contract of the tolerance arm: final objective
    # must match the always-visit baseline within this relative bound.
    # 1e-2 sits in the score gap the workload builds (easy blocks park at
    # ~2-8e-3 post-solve grad norm, the capped hard block an order of
    # magnitude above); the frozen easy blocks stop tracking the fixed
    # effect's late drift, which costs ~2e-3 relative objective — declared
    # at 5e-3 (>=2x margin).
    TOL_SPEC, OBJ_RTOL = "1e-2:2", 5e-3

    def epochs_to_tol(hist, target):
        for i, v in enumerate(hist):
            if abs(v - target) <= OBJ_RTOL * abs(target):
                return i + 1
        return len(hist)

    try:
        arms = {}
        for nprocs, timeout in ((1, 450), (2, 750)):
            base = run_arm(nprocs, "off", timeout)
            order = run_arm(nprocs, "0.0:1", timeout)  # ordering-only
            tol = run_arm(nprocs, TOL_SPEC, timeout)
            digests = {r["digest"] for r in base} | {r["digest"] for r in order}
            if len(digests) != 1:
                raise AssertionError(
                    f"adaptive ordering-only mode is NOT bitwise-identical "
                    f"to always-visit at {nprocs} proc: "
                    f"{sorted(d[:12] for d in digests)}"
                )
            base_iters = sum(r["lane_iterations"] for r in base)
            tol_iters = sum(r["lane_iterations"] for r in tol)
            saved_pct = 100.0 * (1.0 - tol_iters / max(base_iters, 1))
            skips = sum(r["block_skips"] for r in tol)
            decisions = sum(r["skip_decisions"] for r in tol)
            obj_base = base[0]["objective_history"][-1]
            obj_tol = tol[0]["objective_history"][-1]
            obj_err = abs(obj_tol - obj_base) / max(abs(obj_base), 1e-12)
            if skips > 0 and decisions < skips:
                raise AssertionError(
                    f"{skips} skipped blocks but only {decisions} recorded "
                    "skip decisions — a silent skip"
                )
            if obj_err > OBJ_RTOL:
                raise AssertionError(
                    f"tolerance-mode final objective drifted {obj_err:.2e} "
                    f"(> declared {OBJ_RTOL:g}) at {nprocs} proc"
                )
            warm_traces = sum(r.get("warm_new_traces", 0) for r in tol)
            if warm_traces != 0:
                raise AssertionError(
                    f"fully-warm adaptive rerun compiled {warm_traces} new "
                    f"traces at {nprocs} proc — executable reuse regressed"
                )
            arms[nprocs] = {
                "baseline_lane_iterations": int(base_iters),
                "adaptive_lane_iterations": int(tol_iters),
                "saved_pct": round(saved_pct, 1),
                "block_skips": int(skips),
                "skip_decisions": int(decisions),
                "objective_rel_err": float(obj_err),
                "epochs_to_tol_baseline": epochs_to_tol(
                    base[0]["objective_history"], obj_base
                ),
                "epochs_to_tol_adaptive": epochs_to_tol(
                    tol[0]["objective_history"], obj_base
                ),
                "sec_per_iter_baseline": round(base[0]["sec_per_iter"], 4),
                "sec_per_iter_adaptive": round(tol[0]["sec_per_iter"], 4),
                "warm_new_traces": int(warm_traces),
            }
            _log(
                f"adaptive_schedule[{nprocs}p]: lane-iters "
                f"{base_iters} -> {tol_iters} (saved {saved_pct:.1f}%), "
                f"{skips} skips/{decisions} decisions, obj rel err "
                f"{obj_err:.2e}, bitwise(order-only)=True, "
                f"warm new traces={warm_traces}"
            )
        # the acceptance gate rides the fleet-summed (2-process) ledger
        fleet_saved = arms[2]["saved_pct"]
        if fleet_saved < 30.0:
            raise AssertionError(
                f"adaptive schedule saved only {fleet_saved:.1f}% "
                "fleet-summed lane-iterations (< 30% required) on the "
                "skewed workload"
            )
        extra["adaptive_schedule"] = {
            "workload": {"hard": 8, "easy": 512, "epochs": 6,
                         "tolerance_spec": TOL_SPEC,
                         "objective_rtol": OBJ_RTOL},
            "single_host": arms[1],
            "two_process": arms[2],
        }
    finally:
        shutil.rmtree(out, ignore_errors=True)


def _bench_plan_auto(extra, on_tpu):
    """Cost-based plan optimizer (compile/cost.py + ExecutionPlan --plan
    auto) against hand-tuned solve-chunk configs on TWO workload shapes —
    skewed (a thin ill-conditioned tail next to an easy bulk) and uniform
    (every lane converges alike). Cost is the planner's own DETERMINISTIC
    unit — executed lane-iterations plus the chunk-pause tariff from the
    SolveStats ledger — never wall-clock, so the auto-vs-hand-tuned gates
    reproduce bitwise across runs. Three gates per shape: (1) the COLD
    planner (static priors) strictly beats the worst hand-tuned arm;
    (2) the WARM planner (re-resolved from the cost-model.json sidecar the
    cold run persisted, with every arm's realized cost banked into the
    model) lands within PLAN_AUTO_BOUND of the best arm; (3) across the
    two shapes the warm rerun REVISES at least one planned decision —
    realized costs actually changed the model's mind, the loop is closed."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.compile import ExecutionPlan
    from photon_ml_tpu.compile.cost import (
        CHUNK_PAUSE_COST,
        WorkloadProfile,
    )
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.scheduler import (
        SolveSchedule,
        compacted_solve,
        solve_stats,
    )
    from photon_ml_tpu.types import OptimizerType, TaskType

    PLAN_AUTO_BOUND = 1.05  # declared: warm auto within 5% of best arm
    E = 2048 if on_tpu else 512
    M, D, hard = 32, 16, 8
    task = TaskType.LOGISTIC_REGRESSION
    opt = OptimizerType.LBFGS
    cfg = OptimizerConfig(max_iterations=120, tolerance=1e-7)
    kw = dict(task=task, optimizer=opt, optimizer_config=cfg)

    def make_data(shape):
        rng = np.random.default_rng(11)
        x = rng.normal(size=(E, M, D)).astype(np.float32)
        if shape == "skewed":
            # a thin SEVERELY ill-conditioned tail (25-46 iters) next to
            # an easy bulk clustered at 12-16 iters: the band where the
            # chunk-size lever genuinely trades ceil-waste against the
            # pause tariff — and where the static priors (easy=6/hard=50)
            # misjudge the bulk, so the realized feedback has a real
            # correction to make
            x[:hard] *= np.geomspace(1.0, 1024.0, D).astype(np.float32)
            reg = RegularizationContext.l2(0.7)
        else:  # uniform: every lane identically easy, no tail to chase
            reg = RegularizationContext.l2(1.0)
        w_true = (rng.normal(size=(E, D)) * 0.5).astype(np.float32)
        z = np.einsum("emd,ed->em", x.astype(np.float64), w_true)
        with np.errstate(over="ignore"):  # huge |z|: sigmoid saturates to 0/1
            y = (1.0 / (1.0 + np.exp(-z)) > rng.random((E, M))).astype(
                np.float32
            )
        data = tuple(
            jnp.asarray(a)
            for a in (x, y, np.zeros((E, M), np.float32),
                      np.ones((E, M), np.float32))
        )
        return data, jnp.zeros((E, D), jnp.float32), reg

    # profiles describe the two shapes to the planner (signature() keys
    # the model's memory: skewed and uniform never contaminate each other)
    profiles = {
        "skewed": WorkloadProfile(
            num_lanes=E, max_rows=M * 100, median_rows=M, dim=D
        ),
        "uniform": WorkloadProfile(
            num_lanes=E, max_rows=M, median_rows=M, dim=D
        ),
    }

    def realized_of(schedule, data, w0, reg):
        """One measured config in planner units (ledger, not wall-clock)."""
        solve_stats.reset()
        res = compacted_solve(
            data, w0, schedule=schedule, label="plan-bench",
            regularization=reg, **kw,
        )
        jax.block_until_ready(res.coefficients)
        t = solve_stats.totals()
        return (
            float(t["executed_lane_iterations"]
                  + CHUNK_PAUSE_COST * t["chunk_dispatches"]),
            int(t["baseline_lane_iterations"]),
        )

    sidecar_dir = tempfile.mkdtemp(prefix="plan-auto-bench-")
    try:
        report = {}
        revised = []
        for shape in ("skewed", "uniform"):
            data, w0, reg = make_data(shape)
            profile = profiles[shape]

            # ---- hand-tuned arms: every chunk size + the one-shot burn --
            arms = {}
            baseline = None
            for c in (2, 4, 8, 16, 32):
                cost, baseline = realized_of(
                    SolveSchedule(chunk_size=c), data, w0, reg
                )
                arms[f"chunk:{c}"] = cost
            # one-shot = the vmapped burn the ledger already accounts as
            # baseline (every lane padded to the slowest lane's budget)
            arms["one-shot"] = float(baseline)
            best_arm = min(arms, key=lambda a: (arms[a], a))
            worst_arm = max(arms, key=lambda a: (arms[a], a))

            # ---- cold planner: static priors only ----------------------
            cold = ExecutionPlan.resolve(
                plan="auto", workload=profile, cost_model_dir=sidecar_dir,
            )
            cold_pick = next(
                d.planned_choice() for d in cold.decisions
                if d.policy == "schedule"
            )
            cold_cost = arms[cold_pick]
            cold.record_realized("schedule", cold_cost)
            # bank EVERY arm's realized cost — the hand-tuned sweep IS the
            # capture that feeds the model (the docs/*.json story)
            for action, cost in arms.items():
                if action != cold_pick:
                    cold.cost_model.observe(
                        "schedule", action, profile, cost
                    )
            cold.save_cost_model(sidecar_dir)

            # ---- warm planner: re-resolved from the persisted sidecar --
            warm = ExecutionPlan.resolve(
                plan="auto", workload=profile, cost_model_dir=sidecar_dir,
            )
            src = next(
                d for d in warm.decisions if d.policy == "cost-model"
            )
            if "loaded" not in src.action:
                raise AssertionError(
                    f"warm resolve did not load the sidecar: {src.action} "
                    f"({src.reason})"
                )
            warm_pick = next(
                d.planned_choice() for d in warm.decisions
                if d.policy == "schedule"
            )
            warm_cost = arms[warm_pick]
            warm.record_realized("schedule", warm_cost)
            warm.save_cost_model(sidecar_dir)
            if warm_pick != cold_pick:
                revised.append(
                    {"shape": shape, "policy": "schedule",
                     "cold": cold_pick, "warm": warm_pick}
                )

            # ---- the three gates ---------------------------------------
            if cold_cost >= arms[worst_arm]:
                raise AssertionError(
                    f"{shape}: cold auto ({cold_pick}, {cold_cost:.0f}) "
                    f"does not beat the worst hand-tuned arm "
                    f"({worst_arm}, {arms[worst_arm]:.0f})"
                )
            if warm_cost > PLAN_AUTO_BOUND * arms[best_arm]:
                raise AssertionError(
                    f"{shape}: warm auto ({warm_pick}, {warm_cost:.0f}) "
                    f"outside {PLAN_AUTO_BOUND}x of the best arm "
                    f"({best_arm}, {arms[best_arm]:.0f})"
                )
            sched_dec = next(
                d for d in warm.decisions if d.policy == "schedule"
            )
            if (sched_dec.predicted_cost is None
                    or sched_dec.realized_cost is None):
                raise AssertionError(
                    f"{shape}: schedule decision missing predicted/"
                    f"realized cost: {sched_dec.describe()}"
                )
            _log(
                f"plan_auto[{shape}]: arms "
                + " ".join(f"{a}={arms[a]:.0f}" for a in sorted(arms))
            )
            _log(
                f"plan_auto[{shape}]: cold={cold_pick} ({cold_cost:.0f}) "
                f"warm={warm_pick} ({warm_cost:.0f}) best={best_arm} "
                f"worst={worst_arm}; {sched_dec.describe()}"
            )
            report[shape] = {
                "arms": {a: round(arms[a], 1) for a in sorted(arms)},
                "cold_pick": cold_pick,
                "cold_cost": round(cold_cost, 1),
                "warm_pick": warm_pick,
                "warm_cost": round(warm_cost, 1),
                "best_arm": best_arm,
                "worst_arm": worst_arm,
                "within_bound": round(
                    warm_cost / max(arms[best_arm], 1e-9), 4
                ),
            }
        if not revised:
            raise AssertionError(
                "warm rerun revised no decision on either shape — the "
                "realized-cost feedback is not changing the model's mind"
            )
        _log(
            "plan_auto: warm rerun revised "
            + ", ".join(
                f"{r['shape']}:{r['policy']} {r['cold']}->{r['warm']}"
                for r in revised
            )
        )
        extra["plan_auto"] = {
            "bound": PLAN_AUTO_BOUND,
            "cost_unit": "executed lane-iterations + "
                         f"{CHUNK_PAUSE_COST:.0f}/chunk-dispatch pause "
                         "tariff (deterministic, never wall-clock)",
            "workloads": report,
            "revised": revised,
        }
    finally:
        shutil.rmtree(sidecar_dir, ignore_errors=True)


def _bench_preempt(extra, on_tpu):
    """Preemption-safe training (resilience/preemption.py +
    checkpoint_async.py): (1) emergency-checkpoint latency — how long the
    drain boundary blocks on save() with the synchronous writer vs the
    background-commit wrapper (the async save returns after the host
    snapshot; the commit overlaps the next solve); (2) preempt-and-resume
    overhead — a compacted solve interrupted at a chunk boundary and
    resumed from its snapshot vs running uninterrupted, pinned BITWISE, and
    the resume must reuse the warm shape-ladder executables (ZERO new
    solver compiles, CompileStats-asserted)."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.checkpoint import (
        CheckpointState,
        CoordinateDescentCheckpointer,
    )
    from photon_ml_tpu.checkpoint_async import AsyncCheckpointer
    from photon_ml_tpu.compile import compile_stats
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.scheduler import SolveSchedule, compacted_solve
    from photon_ml_tpu.resilience import preemption
    from photon_ml_tpu.resilience.preemption import Preempted
    from photon_ml_tpu.types import OptimizerType, TaskType

    # ---- emergency-checkpoint latency: sync vs async commit ---------------
    rng = np.random.default_rng(3)
    big = rng.normal(size=(2_000_000,)).astype(np.float32)  # ~8MB payload

    def state(step):
        return CheckpointState(
            step=step, params={"fe": jnp.asarray(big)},
            scores={"fe": jnp.asarray(big[:1000])},
            total_scores=jnp.asarray(big[:1000]),
            objective_history=[0.0], validation_history=[],
        )

    reps = 5
    with tempfile.TemporaryDirectory() as d:
        sync_ck = CoordinateDescentCheckpointer(d, keep=2)
        t0 = time.perf_counter()
        for s in range(1, reps + 1):
            sync_ck.save(state(s))
        t_sync = (time.perf_counter() - t0) / reps
    with tempfile.TemporaryDirectory() as d:
        async_ck = AsyncCheckpointer(
            CoordinateDescentCheckpointer(d, keep=2), max_pending=2
        )
        t0 = time.perf_counter()
        for s in range(1, reps + 1):
            async_ck.save(state(s))  # returns after the host snapshot
        t_async_save = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        async_ck.wait()  # the fence pays the remaining commit time ONCE
        t_fence = time.perf_counter() - t0
        async_ck.close()
    _log(
        f"preempt: checkpoint save stall {t_sync*1e3:.1f}ms sync vs "
        f"{t_async_save*1e3:.1f}ms async (+{t_fence*1e3:.1f}ms one-time "
        f"fence) — commit overlaps the solve"
    )
    if t_async_save >= t_sync:
        raise AssertionError(
            f"async save ({t_async_save*1e3:.1f}ms) did not beat the "
            f"synchronous save stall ({t_sync*1e3:.1f}ms)"
        )

    # ---- preempt -> emergency snapshot -> resume, bitwise + zero compiles -
    E = 1024 if on_tpu else 256
    M, D, hard = 24, 12, 6
    x = rng.normal(size=(E, M, D)).astype(np.float32)
    x[:hard] *= np.geomspace(1.0, 48.0, D).astype(np.float32)
    w_true = (rng.normal(size=(E, D)) * 0.5).astype(np.float32)
    z = np.einsum("emd,ed->em", x.astype(np.float64), w_true)
    y = (1.0 / (1.0 + np.exp(-z)) > rng.random((E, M))).astype(np.float32)
    data = tuple(
        jnp.asarray(a)
        for a in (x, y, np.zeros((E, M), np.float32), np.ones((E, M), np.float32))
    )
    w0 = jnp.zeros((E, D), jnp.float32)
    kw = dict(
        task=TaskType.LOGISTIC_REGRESSION, optimizer=OptimizerType.LBFGS,
        optimizer_config=OptimizerConfig(max_iterations=96, tolerance=1e-7),
        regularization=RegularizationContext.l2(1.0),
        schedule=SolveSchedule(chunk_size=12),
    )
    ref = compacted_solve(data, w0, label="warmup", **kw)  # compile + warm
    jax.block_until_ready(ref.coefficients)
    t0 = time.perf_counter()
    ref = compacted_solve(data, w0, label="uninterrupted", **kw)
    jax.block_until_ready(ref.coefficients)
    t_clean = time.perf_counter() - t0

    preemption.reset()
    preemption.install_plan({"chunk": 2})
    sites = ("scheduler.init", "scheduler.chunk",
             "scheduler.compact", "scheduler.scatter")
    t0 = time.perf_counter()
    try:
        compacted_solve(data, w0, label="interrupted", **kw)
        raise AssertionError("preemption plan never fired")
    except Preempted as e:
        partial = e.partial
    t_interrupted = time.perf_counter() - t0
    preemption.reset()
    traces_before = {s: compile_stats.traces_of(s) for s in sites}
    t0 = time.perf_counter()
    res = compacted_solve(data, w0, label="resumed", resume=partial, **kw)
    jax.block_until_ready(res.coefficients)
    t_resume = time.perf_counter() - t0
    new_compiles = sum(
        compile_stats.traces_of(s) - traces_before[s] for s in sites
    )
    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)
        for a, b in zip(res[:7], ref[:7])
        if a is not None
    )
    overhead = (t_interrupted + t_resume) / max(t_clean, 1e-9) - 1.0
    _log(
        f"preempt: uninterrupted {t_clean*1e3:.1f}ms vs interrupted+resume "
        f"{(t_interrupted + t_resume)*1e3:.1f}ms ({overhead*100:+.1f}% "
        f"overhead); bitwise={bitwise}, new solver compiles on warm "
        f"resume={new_compiles}"
    )
    if not bitwise:
        raise AssertionError("preempted+resumed solve is not bitwise-equal")
    if new_compiles != 0:
        raise AssertionError(
            f"{new_compiles} new solver compiles on warm resume — the "
            "snapshot restore must land on the existing shape-ladder "
            "executables"
        )
    extra["preempt_ckpt_sync_ms"] = round(t_sync * 1e3, 2)
    extra["preempt_ckpt_async_save_ms"] = round(t_async_save * 1e3, 2)
    extra["preempt_ckpt_fence_ms"] = round(t_fence * 1e3, 2)
    extra["preempt_uninterrupted_ms"] = round(t_clean * 1e3, 2)
    extra["preempt_resume_total_ms"] = round(
        (t_interrupted + t_resume) * 1e3, 2
    )
    extra["preempt_resume_overhead_pct"] = round(overhead * 100, 1)
    extra["preempt_bitwise_equal"] = bool(bitwise)
    extra["preempt_new_compiles_on_resume"] = int(new_compiles)


def _bench_retrain_delta(extra, on_tpu):
    """Incremental delta retraining (photon_ml_tpu/retrain): the daily
    90%-unchanged workload. Arms: (1) cold day-2 retrain vs delta retrain
    warm-started from day-1 — the delta run must reach the cold run's
    final objective/AUC in <= 50% of its wall-clock, with every frozen
    block's coefficients BITWISE-equal to the day-1 model; (2) a fully
    warm rerun (nothing changed) short-circuits with ZERO new XLA compiles
    (CompileStats watermark); (3) a day-3 delta retrain + store export +
    live ScoringServer swap while request traffic flows (0 new compiles,
    0 dropped requests)."""
    import concurrent.futures
    import dataclasses as _dc
    import shutil
    import tempfile
    import threading

    from game_test_utils import (
        dense_to_csr,
        game_avro_records,
        serve_requests_from_records,
        write_game_avro,
    )

    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.compile import compile_stats
    from photon_ml_tpu.data.game import GameData
    from photon_ml_tpu.io import model_io
    from photon_ml_tpu.serve import (
        ModelStore,
        ModelSwapper,
        ScoringServer,
        ServeStats,
    )

    tmp = tempfile.mkdtemp(prefix="bench-retrain-")
    try:
        # --- workload: per-file user cohorts with uniform row counts, so
        # the count-sorted entity blocking preserves cohort order and one
        # mutated file dirties ~1/num_files of the blocks (the daily
        # cohort shape: yesterday's members mostly quiet today)
        num_files = 10
        users_per_file = 96 if on_tpu else 60
        num_users = num_files * users_per_file
        d_fixed, d_random = 8, 6
        rng = np.random.default_rng(31)
        rows_per_user = np.full(num_users, 24)
        n = int(rows_per_user.sum())
        user_of_row = np.repeat(
            np.arange(num_users, dtype=np.int32), rows_per_user
        )
        x_fixed = rng.normal(size=(n, d_fixed)).astype(np.float32)
        x_random = rng.normal(size=(n, d_random)).astype(np.float32)
        w_fixed = rng.normal(size=d_fixed).astype(np.float32)
        w_users = (rng.normal(size=(num_users, d_random)) * 1.2).astype(
            np.float32
        )
        margin = x_fixed @ w_fixed + np.sum(
            x_random * w_users[user_of_row], axis=1
        )
        y = (1.0 / (1.0 + np.exp(-margin)) > rng.random(n)).astype(np.float32)
        gd = GameData(
            response=y, offset=np.zeros(n, np.float32),
            weight=np.ones(n, np.float32),
            ids={"userId": user_of_row},
            id_vocabs={"userId": [f"u{i:05d}" for i in range(num_users)]},
            shards={"global": dense_to_csr(x_fixed),
                    "per_user": dense_to_csr(x_random)},
        )
        truth = {"x_fixed": x_fixed, "x_random": x_random}
        # last 4 rows of EVERY user are validation (deterministic, so
        # per-user train counts stay uniform and the count-sorted blocking
        # stays file-aligned); the validation file never moves
        user_start = np.concatenate(
            [[0], np.cumsum(rows_per_user)[:-1]]
        )
        pos_in_user = np.arange(n) - user_start[user_of_row]
        val_mask = pos_in_user >= rows_per_user[user_of_row] - 4
        train_dir = os.path.join(tmp, "train")
        val_dir = os.path.join(tmp, "validate")
        os.makedirs(train_dir)
        os.makedirs(val_dir)
        file_rows = []
        for k in range(num_files):
            in_file = (
                (user_of_row >= users_per_file * k)
                & (user_of_row < users_per_file * (k + 1))
                & ~val_mask
            )
            rows = np.nonzero(in_file)[0]
            file_rows.append(rows)
            write_game_avro(
                os.path.join(train_dir, f"part-{k}.avro"), gd, rows, truth
            )
        write_game_avro(
            os.path.join(val_dir, "part-0.avro"), gd,
            np.nonzero(val_mask)[0], truth,
        )

        def mutate_file(k, seed):
            """Day rollover: file k's labels move (same rows, same users —
            the store slab shapes stay swap-compatible)."""
            mrng = np.random.default_rng(seed)
            y2 = np.array(gd.response)
            rows = file_rows[k]
            flip = rows[mrng.random(len(rows)) < 0.2]
            y2[flip] = 1.0 - y2[flip]
            time.sleep(0.02)  # mtime_ns must move on coarse filesystems
            write_game_avro(
                os.path.join(train_dir, f"part-{k}.avro"),
                _dc.replace(gd, response=y2), rows, truth,
            )

        def run(out, warm_from=None, export=None, cache="tcache"):
            # the cold day-2 arm gets its OWN cache dir: both the cold and
            # delta runs then pay the same full-decode miss on the changed
            # file set, so the measured delta win is the retrain loop's
            # (block reuse + solve skip + warm starts), not a same-cache
            # run-order artifact
            args = [
                "--train-input-dirs", train_dir,
                "--validate-input-dirs", val_dir,
                "--output-dir", out,
                "--task-type", "LOGISTIC_REGRESSION",
                "--feature-shard-id-to-feature-section-keys-map",
                "global:fixedFeatures|per_user:userFeatures",
                "--updating-sequence", "fixed,per-user",
                "--fixed-effect-data-configurations", "fixed:global,1",
                "--random-effect-data-configurations",
                "per-user:userId,per_user,1,-1,-1,-1,INDEX_MAP",
                "--fixed-effect-optimization-configurations",
                "fixed:100,1e-10,0.01,1,LBFGS,L2",
                "--random-effect-optimization-configurations",
                "per-user:100,1e-10,0.1,1,LBFGS,L2",
                "--evaluator-type", "AUC",
                "--delete-output-dir-if-exists", "true",
                # uniform per-user counts: every full block already shares
                # one (E, M, D) shape, so the solver executable is reused
                # across blocks without the shape ladder; blocks of 12
                # users -> 5 blocks per file cohort, cut on cohort
                # boundaries (60 % 12 == 0)
                "--re-memory-budget-mb", "0.0068",
                "--num-iterations", "6",
                "--tensor-cache", os.path.join(tmp, cache),
            ]
            if warm_from:
                args += ["--warm-start-from", warm_from]
            if export:
                args += ["--export-serve-store", export]
            t0 = time.perf_counter()
            driver = game_training_driver.main(args)
            return driver, time.perf_counter() - t0

        def best_metrics(driver):
            _, result, metrics = driver.results[driver.best_index]
            return float(result.objective_history[-1]), float(metrics["AUC"])

        # --- day 1: the prior (also warms every executable in-process,
        # so the cold-vs-delta day-2 comparison below is compile-fair)
        day1_out = os.path.join(tmp, "day1")
        store1 = os.path.join(tmp, "store1")
        d1, t_day1 = run(day1_out, export=store1)
        n_blocks = len(d1.streaming_manifests["per-user"].blocks)
        _log(f"retrain_delta: day-1 prior trained in {t_day1:.1f}s "
             f"({n_blocks} streaming blocks)")

        # --- day 2: one of ten files moves
        mutate_file(num_files - 1, seed=41)
        cold_out = os.path.join(tmp, "day2-cold")
        d_cold, t_cold = run(cold_out, cache="tcache-cold")
        obj_cold, auc_cold = best_metrics(d_cold)
        delta_out = os.path.join(tmp, "day2-delta")
        store2 = os.path.join(tmp, "store2")
        d_delta, t_delta = run(delta_out, warm_from=day1_out, export=store2)
        obj_delta, auc_delta = best_metrics(d_delta)
        deltas = d_delta.block_deltas["per-user"]
        frozen = d_delta._frozen_blocks["per-user"]
        _log(
            f"retrain_delta: day-2 cold {t_cold:.1f}s "
            f"(obj {obj_cold:.5g}, AUC {auc_cold:.4f}) vs delta "
            f"{t_delta:.1f}s (obj {obj_delta:.5g}, AUC {auc_delta:.4f}); "
            f"{len(frozen)}/{len(deltas)} blocks frozen"
        )
        if t_delta > 0.5 * t_cold:
            raise AssertionError(
                f"delta retrain took {t_delta:.1f}s > 50% of the cold "
                f"retrain's {t_cold:.1f}s"
            )
        if obj_delta > obj_cold * 1.02 or auc_delta < auc_cold - 0.01:
            raise AssertionError(
                f"delta retrain did not reach the cold run's quality: "
                f"obj {obj_delta:.6g} vs {obj_cold:.6g}, "
                f"AUC {auc_delta:.4f} vs {auc_cold:.4f}"
            )

        # --- bitwise gate: every frozen block's entities carry the day-1
        # coefficients bit-for-bit
        imap = d_delta.shard_index_maps["per_user"]
        means1, _, _, _ = model_io.load_random_effect(
            os.path.join(day1_out, "best"), "per-user", imap)
        means2, _, _, _ = model_io.load_random_effect(
            os.path.join(delta_out, "best"), "per-user", imap)
        m_delta = d_delta.streaming_manifests["per-user"]
        frozen_entities = 0
        for i in frozen:
            bm = m_delta.load_block_meta(i)
            for v in bm.entity_ids:
                raw = m_delta.vocab[v]
                if not np.array_equal(means1[raw], means2[raw]):
                    raise AssertionError(
                        f"frozen block {i} entity {raw} is not bitwise-"
                        "equal to the prior model"
                    )
                frozen_entities += 1
        _log(f"retrain_delta: {frozen_entities} frozen-block entities "
             "bitwise-equal to the day-1 model")

        # --- fully warm rerun: nothing changed since day-2-delta
        wm = compile_stats.watermark()
        rerun_out = os.path.join(tmp, "day2-rerun")
        d_rerun, t_rerun = run(rerun_out, warm_from=delta_out)
        rerun_compiles = wm.new_traces()
        if not (d_rerun.delta_plan and d_rerun.delta_plan.short_circuit):
            raise AssertionError("unchanged rerun did not short-circuit")
        if rerun_compiles != 0:
            raise AssertionError(
                f"{rerun_compiles} new traces on the fully warm rerun"
            )
        _log(f"retrain_delta: fully warm rerun {t_rerun:.2f}s, "
             "0 new XLA compiles, prior model reused wholesale")

        # --- day 3: delta retrain + hot swap while traffic flows against
        # the day-2 store
        sections = {"global": ["fixedFeatures"], "per_user": ["userFeatures"]}
        sample_rows = np.nonzero(val_mask)[0][:64]
        reqs = serve_requests_from_records(
            list(game_avro_records(gd, sample_rows, truth))
        )
        server = ScoringServer(
            ModelStore(store2), shard_sections=sections,
            max_batch_rows=32, max_wait_ms=2.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=16)
        stop = threading.Event()
        served = {"n": 0, "errors": 0}

        def traffic():
            i = 0
            while not stop.is_set():
                try:
                    out = server.score_rows([reqs[i % len(reqs)]])
                    if out is None or len(out) != 1:
                        served["errors"] += 1
                    served["n"] += 1
                except Exception:  # noqa: BLE001 — any scoring failure during the swap window is exactly what this arm counts
                    served["errors"] += 1
                i += 1

        threads = [threading.Thread(target=traffic) for _ in range(4)]
        for th in threads:
            th.start()
        try:
            mutate_file(0, seed=43)
            day3_out = os.path.join(tmp, "day3")
            store3 = os.path.join(tmp, "store3")
            d3, t_day3 = run(day3_out, warm_from=delta_out, export=store3)
            swapper = ModelSwapper(server)
            report = swapper.swap(store3)
        finally:
            stop.set()
            for th in threads:
                th.join()
        server.close()
        _log(
            f"retrain_delta: day-3 delta retrain {t_day3:.1f}s under live "
            f"traffic ({served['n']} requests, {served['errors']} errors); "
            f"swap gen {report['generation']}, "
            f"{report['new_compiles']} new compiles, "
            f"{report['dropped_requests']} drops"
        )
        if report["new_compiles"] != 0 or served["errors"] != 0:
            raise AssertionError(
                f"mid-retrain swap arm must be compile-free and lossless "
                f"(compiles={report['new_compiles']}, "
                f"errors={served['errors']})"
            )

        extra["retrain_config"] = {
            "files": num_files, "users": num_users,
            "rows": int(n), "blocks": n_blocks,
            "dirty_files_per_day": 1,
        }
        extra["retrain_day1_s"] = round(t_day1, 2)
        extra["retrain_cold_s"] = round(t_cold, 2)
        extra["retrain_delta_s"] = round(t_delta, 2)
        extra["retrain_speedup_vs_cold"] = round(t_cold / t_delta, 2)
        extra["retrain_cold_objective"] = obj_cold
        extra["retrain_delta_objective"] = obj_delta
        extra["retrain_cold_auc"] = auc_cold
        extra["retrain_delta_auc"] = auc_delta
        extra["retrain_blocks_frozen"] = len(frozen)
        extra["retrain_blocks_total"] = len(deltas)
        extra["retrain_frozen_entities_bitwise"] = int(frozen_entities)
        extra["retrain_warm_rerun_s"] = round(t_rerun, 2)
        extra["retrain_warm_rerun_new_compiles"] = int(rerun_compiles)
        extra["retrain_day3_delta_s"] = round(t_day3, 2)
        extra["retrain_swap_new_compiles"] = int(report["new_compiles"])
        extra["retrain_swap_dropped_requests"] = int(
            report["dropped_requests"]
        )
        extra["retrain_traffic_requests_during_retrain"] = int(served["n"])
        extra["retrain_traffic_errors"] = int(served["errors"])
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_delta_rollout(extra, on_tpu):
    """Fleet-wide delta rollout (serve/fleet/swap.rollout_delta): the last
    arc of the daily loop measured end to end — a committed delta
    retrain's fleet export rolls through the generation barrier as ONE
    atomic swap while request traffic flows. Arms: (1) provenance
    refusals — an export built from the WRONG model and an unfinished
    retrain (no committed retrain.json) must both abort with the old
    generation still serving; (2) the timed rollout under concurrent
    traffic: zero new compiles, zero dropped requests, and every
    in-flight request scored WHOLLY at one generation (bitwise vs the
    matching single-store oracle — never a mix); (3) post-rollout, the
    full request set is bitwise-equal to the new generation's oracle.

    Replicas are in-process (ReplicaEngine + LocalReplicaClient): the
    barrier/pinning logic under test is transport-independent, and the
    serving_fleet section already prices the TCP layer."""
    import shutil
    import tempfile
    import threading
    import time as _time

    from game_test_utils import (
        game_avro_records,
        make_glmix_data,
        save_synthetic_game_model,
        serve_requests_from_records,
    )

    from photon_ml_tpu.compile import ShapeBucketer
    from photon_ml_tpu.retrain.manifest import RetrainManifest
    from photon_ml_tpu.serve import (
        FleetStats,
        ModelStore,
        ScoringServer,
        ServeStats,
        build_model_store,
    )
    from photon_ml_tpu.serve.fleet import (
        FleetRouter,
        FleetSwapError,
        FleetSwapper,
        LocalReplicaClient,
        ReplicaEngine,
        build_fleet_stores,
        load_fleet_meta,
        replica_store_dir,
    )

    tmp = tempfile.mkdtemp(prefix="bench-delta-rollout-")
    sections = {"global": ["fixedFeatures"], "per_user": ["userFeatures"]}
    num_replicas = 2
    try:
        rng = np.random.default_rng(23)
        num_users = 96
        d_fixed, d_random = 8, 6
        data, truth = make_glmix_data(
            rng, num_users=num_users, rows_per_user_range=(4, 8),
            d_fixed=d_fixed, d_random=d_random,
        )
        offsets = rng.normal(size=data.num_rows).astype(np.float32)
        reqs = serve_requests_from_records(list(
            game_avro_records(data, range(data.num_rows), truth, offsets)
        ))

        # two model generations (same shapes — a delta retrain never
        # changes slab geometry) + their fleet exports and oracles
        model_dirs, fleet_dirs, oracle = [], [], []
        for g in range(2):
            mdir = os.path.join(tmp, f"model-g{g}")
            save_synthetic_game_model(
                mdir, np.random.default_rng(1142 + g), d_fixed=d_fixed,
                d_random=d_random, num_users=num_users,
            )
            fdir = os.path.join(tmp, f"fleet-g{g}")
            build_fleet_stores(
                mdir, fdir, num_replicas=num_replicas,
                bucketer=ShapeBucketer(),
            )
            sdir = os.path.join(tmp, f"store-g{g}")
            build_model_store(mdir, sdir, bucketer=ShapeBucketer())
            server = ScoringServer(
                ModelStore(sdir), shard_sections=sections,
                max_batch_rows=32, max_wait_ms=2.0, stats=ServeStats(),
            )
            server.warmup(warm_nnz=16)
            oracle.append(server.score_rows(reqs))
            server.close()
            model_dirs.append(mdir)
            fleet_dirs.append(fdir)

        engines = []
        for r in range(num_replicas):
            e = ReplicaEngine(
                ModelStore(replica_store_dir(fleet_dirs[0], r)),
                replica_id=r, num_replicas=num_replicas,
                shard_sections=sections, max_batch_rows=32,
                max_wait_ms=2.0, stats=ServeStats(),
            )
            e.warmup(warm_nnz=16)
            engines.append(e)
        router = FleetRouter(
            load_fleet_meta(fleet_dirs[0]),
            [LocalReplicaClient(e) for e in engines], stats=FleetStats(),
        )

        # per-request row offsets (a request may expand to >1 score row):
        # a gen-0 pre-pass both warms the fleet and records the widths
        lens = [len(router.score_rows([q])) for q in reqs]
        off = np.concatenate([[0], np.cumsum(lens)])
        assert np.array_equal(
            np.concatenate([router.score_rows([q]) for q in reqs]),
            oracle[0],
        ), "2-replica fleet diverges from the gen-0 single-store oracle"

        def committed_retrain(name, mdir):
            rd = os.path.join(tmp, name)
            os.makedirs(rd)
            RetrainManifest(
                output_dir=rd, model_dir=mdir,
                task="LOGISTIC_REGRESSION", file_stats=[], ingest_inputs={},
                ingest_digest="bench", updating_sequence=[], coordinates={},
            ).save(rd)
            return rd

        swapper = FleetSwapper(router)

        # --- arm 1: provenance refusals (old generation intact) -----------
        refusals = 0
        try:
            swapper.rollout_delta(
                fleet_dirs[1], committed_retrain("retrain-wrong",
                                                 model_dirs[0])
            )
        except FleetSwapError as e:
            assert "mismatched" in str(e), e
            refusals += 1
        unfinished = os.path.join(tmp, "retrain-unfinished")
        os.makedirs(unfinished)
        try:
            swapper.rollout_delta(fleet_dirs[1], unfinished)
        except FleetSwapError as e:
            assert "no committed" in str(e), e
            refusals += 1
        if refusals != 2 or router.generation != 0:
            raise AssertionError(
                f"provenance refusal arm: {refusals}/2 refusals, "
                f"generation {router.generation} (want 0)"
            )
        _log("delta_rollout: both provenance refusals held (gen 0 intact)")

        # --- arm 2: the timed rollout under concurrent traffic -----------
        retrain_dir = committed_retrain("retrain-ok", model_dirs[1])
        stop = threading.Event()
        served = {"g0": 0, "g1": 0, "mixed": 0, "errors": 0}
        lock = threading.Lock()

        def traffic(tid):
            i = tid
            while not stop.is_set():
                k = i % len(reqs)
                lo, hi = int(off[k]), int(off[k + 1])
                try:
                    got = router.score_rows([reqs[k]])
                except Exception:  # noqa: BLE001 — gate counts, assert below
                    with lock:
                        served["errors"] += 1
                else:
                    if np.array_equal(got, oracle[0][lo:hi]):
                        key = "g0"
                    elif np.array_equal(got, oracle[1][lo:hi]):
                        key = "g1"
                    else:
                        key = "mixed"
                    with lock:
                        served[key] += 1
                i += 3
        threads = [
            threading.Thread(target=traffic, args=(t,), daemon=True)
            for t in range(3)
        ]
        for t in threads:
            t.start()
        _time.sleep(0.3)  # traffic established before the roll begins
        t0 = _time.perf_counter()
        report = swapper.rollout_delta(fleet_dirs[1], retrain_dir)
        swap_s = _time.perf_counter() - t0
        _time.sleep(0.3)  # post-flip traffic must all land on gen 1
        stop.set()
        for t in threads:
            t.join(timeout=60)

        post = np.concatenate([router.score_rows([q]) for q in reqs])
        post_bitwise = bool(np.array_equal(post, oracle[1]))
        total = sum(served[k] for k in ("g0", "g1", "mixed"))
        _log(
            f"delta_rollout: swap {swap_s * 1e3:.1f}ms, "
            f"{report['new_compiles']} new compiles, "
            f"{report['dropped_requests']} dropped; traffic "
            f"{total} reqs (g0={served['g0']} g1={served['g1']} "
            f"mixed={served['mixed']} errors={served['errors']})"
        )

        extra["delta_rollout_config"] = {
            "replicas": num_replicas, "users": num_users,
            "requests": len(reqs), "traffic_threads": 3,
        }
        extra["delta_rollout_swap_ms"] = round(swap_s * 1e3, 1)
        extra["delta_rollout_generation"] = int(report["generation"])
        extra["delta_rollout_new_compiles"] = int(report["new_compiles"])
        extra["delta_rollout_dropped_requests"] = int(
            report["dropped_requests"]
        )
        extra["delta_rollout_provenance_refusals"] = refusals
        extra["delta_rollout_traffic_requests"] = int(total)
        extra["delta_rollout_traffic_g0"] = int(served["g0"])
        extra["delta_rollout_traffic_g1"] = int(served["g1"])
        extra["delta_rollout_traffic_mixed"] = int(served["mixed"])
        extra["delta_rollout_traffic_errors"] = int(served["errors"])
        extra["delta_rollout_post_bitwise"] = post_bitwise

        problems = []
        if report["new_compiles"]:
            problems.append(f"{report['new_compiles']} new compiles")
        if report["dropped_requests"]:
            problems.append(f"{report['dropped_requests']} dropped requests")
        if served["mixed"]:
            problems.append(f"{served['mixed']} mixed-generation scores")
        if served["errors"]:
            problems.append(f"{served['errors']} traffic errors")
        if served["g1"] == 0:
            problems.append("no traffic observed at the new generation")
        if not post_bitwise:
            problems.append("post-rollout scores diverge from gen-1 oracle")
        if problems:
            raise AssertionError(
                "delta rollout gates violated: " + "; ".join(problems)
            )

        router.close()
        for e in engines:
            e.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_quantized_serving(extra, on_tpu):
    """Quantized serving slabs (serve/quantize.py): the repo's first
    measured accuracy/speed dial. Races f32 vs bf16 vs int8 stores of ONE
    model on store slab bytes, export+open time, warm QPS, p50/p99, and
    the realized max per-score quantization error vs the PINNED budget
    recorded in store meta. Gates: int8 slab bytes <= ~30% and bf16 <=
    ~55% of f32; every quantized score inside its budget; the f32 default
    still BITWISE-equal to the batch scoring driver; an int8 -> int8 warm
    swap under live traffic compiles nothing and drops nothing."""
    import concurrent.futures
    import shutil
    import tempfile

    from game_test_utils import (
        game_avro_records,
        make_glmix_data,
        save_synthetic_game_model,
        serve_requests_from_records,
        serving_score_budget,
        write_game_avro,
    )

    from photon_ml_tpu.cli import game_scoring_driver
    from photon_ml_tpu.compile import ShapeBucketer, compile_stats
    from photon_ml_tpu.serve import (
        ModelStore,
        ModelSwapper,
        ScoringServer,
        ServeStats,
        build_model_store,
    )

    tmp = tempfile.mkdtemp(prefix="bench-quantized-serving-")
    try:
        rng = np.random.default_rng(29)
        # wide-enough slabs that the byte ratios are payload, not headers.
        # d_random = 31 puts the dense-request nnz (31 features +
        # intercept = 32) EXACTLY on a ladder rung, so the server's padded
        # reduction width equals the batch driver's and the f32 bitwise
        # gate is exact (off-rung widths split the f32 partial sums
        # differently — ulp noise, which the bitwise gate would refuse)
        num_users = 4096 if on_tpu else 2048
        d_fixed, d_random = 8, 31
        data, truth = make_glmix_data(
            rng, num_users=num_users, rows_per_user_range=(1, 3),
            d_fixed=d_fixed, d_random=d_random,
        )
        offsets = rng.normal(size=data.num_rows).astype(np.float32)
        model_dir = os.path.join(tmp, "model")
        save_synthetic_game_model(
            model_dir, rng, d_fixed=d_fixed, d_random=d_random,
            num_users=num_users,
        )
        in_dir = os.path.join(tmp, "in")
        os.makedirs(in_dir)
        write_game_avro(
            os.path.join(in_dir, "part-0.avro"), data,
            range(data.num_rows), truth, offsets,
        )
        records = list(
            game_avro_records(data, range(data.num_rows), truth, offsets)
        )
        reqs = serve_requests_from_records(records)[:512]
        sections = {"global": ["fixedFeatures"], "per_user": ["userFeatures"]}

        def re_slab_bytes(store_dir):
            base = os.path.join(store_dir, "random", "per-user")
            total = os.path.getsize(os.path.join(base, "slab.npy"))
            scales = os.path.join(base, "scales.npy")
            if os.path.exists(scales):
                total += os.path.getsize(scales)
            return total

        def fire(server, requests, workers=32):
            with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                futs = list(
                    pool.map(lambda q: server.submit_rows([q]), requests)
                )
            return np.concatenate([f.result() for f in futs])

        arms = {}
        stores = {}
        served = {}
        for dt in ("f32", "bf16", "int8"):
            store_dir = os.path.join(tmp, f"store-{dt}")
            t0 = time.perf_counter()
            meta = build_model_store(
                model_dir, store_dir, bucketer=ShapeBucketer(),
                store_dtype=dt,
            )
            t_export = time.perf_counter() - t0
            t0 = time.perf_counter()
            store = ModelStore(store_dir)
            t_open = time.perf_counter() - t0
            stores[dt] = (store_dir, meta)
            server = ScoringServer(
                store, shard_sections=sections,
                max_batch_rows=32, max_wait_ms=2.0, stats=ServeStats(),
            )
            server.warmup(warm_nnz=32)
            fire(server, reqs)  # warm pass
            server.stats.reset()
            out = fire(server, reqs)  # measured pass
            served[dt] = out
            snap = server.stats.snapshot()
            arms[dt] = {
                "slab_bytes": re_slab_bytes(store_dir),
                "export_ms": round(t_export * 1e3, 1),
                "open_ms": round(t_open * 1e3, 2),
                "qps": snap["qps"],
                "p50_ms": snap["p50_ms"],
                "p99_ms": snap["p99_ms"],
            }
            server.close()
            _log(
                f"quantized_serving[{dt}]: {arms[dt]['slab_bytes']} slab "
                f"bytes, open {arms[dt]['open_ms']}ms, "
                f"{snap['qps']} req/s, p50 {snap['p50_ms']}ms / "
                f"p99 {snap['p99_ms']}ms"
            )

        # --- accuracy gates -------------------------------------------------
        # f32: BITWISE vs the batch scoring driver (the untouched oracle)
        drv = game_scoring_driver.main([
            "--input-dirs", in_dir,
            "--game-model-input-dir", model_dir,
            "--output-dir", os.path.join(tmp, "score-out"),
            "--offheap-indexmap-dir",
            os.path.join(stores["f32"][0], "features"),
            "--feature-shard-id-to-feature-section-keys-map",
            "global:fixedFeatures|per_user:userFeatures",
            "--delete-output-dir-if-exists", "true",
        ])
        f32_bitwise = bool(
            np.array_equal(served["f32"], drv.scores[: len(reqs)])
        )
        if not f32_bitwise:
            raise AssertionError(
                "f32 store is no longer bitwise-equal to the batch "
                "scoring driver — the default path regressed"
            )
        # quantized: realized per-score error inside the pinned budget —
        # through the SAME policy helpers the serve/fleet tests assert
        # with (tolerances.py owns the slack; no hand-rolled bound here)
        from tolerances import assert_within_budget, quant_score_budget

        for dt in ("bf16", "int8"):
            budget = quant_score_budget(
                1.0,
                serving_score_budget(stores[dt][1], reqs, sections),
                ref_scores=served["f32"],
            )
            err = np.abs(
                served[dt].astype(np.float64) - served["f32"]
            )
            arms[dt]["max_score_err"] = float(err.max())
            arms[dt]["max_score_budget"] = float(budget.max())
            arms[dt]["coeff_err_budget"] = stores[dt][1]["random"][0][
                "quantization"
            ]["coeff_err_budget"]
            assert_within_budget(
                served[dt], served["f32"], budget,
                err_msg=f"{dt} serving vs the f32 server",
            )
            _log(
                f"quantized_serving[{dt}]: max per-score err "
                f"{err.max():.3e} within budget (max budget "
                f"{budget.max():.3e})"
            )

        # --- byte-ratio gates ----------------------------------------------
        f32_bytes = arms["f32"]["slab_bytes"]
        bf16_ratio = arms["bf16"]["slab_bytes"] / f32_bytes
        int8_ratio = arms["int8"]["slab_bytes"] / f32_bytes
        if bf16_ratio > 0.55 or int8_ratio > 0.30:
            raise AssertionError(
                f"store byte ratios missed the dial: bf16 {bf16_ratio:.3f} "
                f"(<= 0.55), int8 {int8_ratio:.3f} (<= 0.30)"
            )
        _log(
            f"quantized_serving: slab bytes f32 {f32_bytes} / "
            f"bf16 {bf16_ratio:.1%} / int8 {int8_ratio:.1%}"
        )

        # --- warm-swap arm: int8 -> int8 under live traffic ----------------
        model2 = os.path.join(tmp, "model2")
        save_synthetic_game_model(
            model2, np.random.default_rng(31), d_fixed=d_fixed,
            d_random=d_random, num_users=num_users,
        )
        store2 = os.path.join(tmp, "store2-int8")
        build_model_store(
            model2, store2, bucketer=ShapeBucketer(), store_dtype="int8"
        )
        server = ScoringServer(
            ModelStore(stores["int8"][0]), shard_sections=sections,
            max_batch_rows=32, max_wait_ms=2.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=32)
        swapper = ModelSwapper(server)
        wm = compile_stats.watermark()
        with concurrent.futures.ThreadPoolExecutor(16) as pool:
            futs = [pool.submit(server.score_rows, [q]) for q in reqs[:256]]
            report = swapper.swap(store2)
            results = [f.result() for f in futs]
        dropped = sum(1 for r in results if r is None or len(r) != 1)
        server.close()
        _log(
            f"quantized_serving swap[int8->int8]: "
            f"{report['new_compiles']} new compiles "
            f"({wm.new_traces()} traces in window), {dropped} dropped"
        )
        if report["new_compiles"] != 0 or dropped != 0:
            raise AssertionError(
                f"quantized warm swap must be compile-free and lossless "
                f"(compiles={report['new_compiles']}, dropped={dropped})"
            )

        extra["quantized_serving_arms"] = arms
        extra["quantized_serving_bytes_ratio"] = {
            "bf16_vs_f32": round(bf16_ratio, 4),
            "int8_vs_f32": round(int8_ratio, 4),
        }
        extra["quantized_serving_f32_bitwise_equal_to_driver"] = f32_bitwise
        extra["quantized_serving_swap_new_compiles"] = int(
            report["new_compiles"]
        )
        extra["quantized_serving_swap_dropped_requests"] = int(dropped)
        extra["quantized_serving_config"] = {
            "entities": num_users, "d_fixed": d_fixed,
            "d_random": d_random, "requests": len(reqs),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_day_in_life(extra, on_tpu):
    """One compressed day of serving life under a single enforced error
    budget (tools/day_in_life.py): a diurnal traffic curve from a
    synthetic multi-million-user population rides through a REAL delta
    retrain (--warm-start-from) -> quantized store export -> provenance-
    gated fleet-wide rollout, an elasticity event (owner kill -9 against
    live TCP replicas + membership replan with scale-up), seeded chaos at
    the registered fault sites, and a rolling f32->bf16 dtype migration
    (mixed-dtype refusal, then a clean same-dtype roll). Every phase runs
    against its declared SLO; the phase-attributed ledger IS the section
    capture — the run fails loudly (SLOViolation) if any phase breaks its
    p50/p99, overspends its error budget, or exhibits a degradation kind
    its SLO never declared."""
    import shutil
    import tempfile

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from day_in_life import DayConfig, run_day

    # env knob downsizes the per-phase wall for smoke runs (the full-fat
    # arms — real retrain, TCP kill — stay on; only the traffic window
    # shrinks, exactly like PHOTON_BENCH_268M_ENTITIES)
    phase_seconds = float(os.environ.get("PHOTON_BENCH_DAY_SECONDS", 3.0))
    tmp = tempfile.mkdtemp(prefix="bench-day-in-life-")
    try:
        result = run_day(DayConfig(
            out_dir=tmp,
            phase_seconds=phase_seconds,
            peak_qps=120.0,
            traffic_threads=3,
            real_retrain=True,
            kill_arm=True,
        ))
        ledger = result["ledger"]
        _log(
            f"day_in_life: ok={ledger['ok']} "
            f"{ledger['totals']['requests']} requests, "
            f"{sum(ledger['totals']['degradations'].values())} attributed "
            f"degradations, {ledger['totals']['bytes_moved']}B moved"
        )
        extra["day_in_life"] = {
            "phase_seconds": phase_seconds,
            "ledger": ledger,
            "harness": result["extra"],
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


SECTION_ORDER = (
    "dense", "sparse", "sparse_race", "game", "game5", "grid",
    "streaming", "streaming_pipeline", "compile_reuse", "compaction",
    "fused_schedule",
    "adaptive_schedule",
    "plan_auto",
    "preemption_resume",
    "perhost", "perhost_streaming", "elastic_reshard", "scoring", "serving",
    "serving_fleet",
    "quantized_serving",
    "retrain_delta",
    "delta_rollout",
    "day_in_life",
    "ingest",
)
# orchestrator per-section deadlines (s): generous — tunnel compiles are slow,
# and hitting a deadline DETACHES the child (never kills: r3 claim-orphan
# postmortem — a killed claim-holder wedges the single-client tunnel)
SECTION_DEADLINES = {"dense": 3600, "game": 3600, "game5": 2400, "grid": 2400,
                     # 1-proc + 2-proc + compacted-2-proc CD runs + the
                     # 268M two-process capture, all subprocess-fenced with
                     # own timeouts — the section deadline must EXCEED
                     # their sum (1200 + 1800 + 1800 + 5100) or a
                     # legitimately slow run is detached even though every
                     # worker honored its fence
                     "perhost_streaming": 10500,
                     # fresh-survivor + elastic 2-process cohorts, each
                     # subprocess-fenced (1500 + 1800) — deadline > sum
                     "elastic_reshard": 3600,
                     # 3 single-host (450 each) + 3 two-process (750 each)
                     # subprocess-fenced worker cohorts — deadline > sum
                     "adaptive_schedule": 3900,
                     # host/device loop arms in-process + one 2-process
                     # Gloo merge cohort fenced at 300s
                     "fused_schedule": 1800,
                     # 3 fleets (1/2/4 replicas) of warmed subprocess
                     # replicas + the kill arm, each spawn fenced at 240s
                     "serving_fleet": 3600,
                     # 5 full GAME training runs (day-1 prior, day-2
                     # cold + delta, warm rerun, day-3 under traffic)
                     "retrain_delta": 3600,
                     # 3 store exports + 3 warmed servers + a batch-driver
                     # oracle run + the int8 swap arm
                     "quantized_serving": 1800,
                     # 2 model generations (exports + oracles) + an
                     # in-process 2-replica fleet + the traffic'd roll
                     "delta_rollout": 1800,
                     # a full compressed day: real delta retrain + TCP
                     # replica spawns (kill arm) + 6 traffic'd phases +
                     # 4 store exports — each piece individually fenced
                     "day_in_life": 3600}
DEFAULT_SECTION_DEADLINE = 1800


def _dense_data():
    rng = np.random.default_rng(0)
    x_h = rng.normal(size=(N_DENSE, D_DENSE)).astype(np.float32)
    w_true = rng.normal(size=D_DENSE).astype(np.float32) * 0.1
    y_h = (1.0 / (1.0 + np.exp(-x_h @ w_true)) > rng.random(N_DENSE)).astype(
        np.float32
    )
    return x_h, y_h


# traceback signatures of a wedged device client: once one section dies
# this way, every later device section in the SAME process dies identically
# (r5 self-capture post-mortem) — record the root cause once, short entries
# after, instead of N duplicate tracebacks polluting the JSON tail
_WEDGE_SIGNATURES = ("UNAVAILABLE", "TPU device error", "DEADLINE_EXCEEDED")

# sections that never touch the device: still run after a failed preflight
HOST_ONLY_SECTIONS = ("ingest",)


def _device_preflight():
    """Accelerator health probe BEFORE any section runs: one tiny jit and —
    on a multi-device backend — one cross-device reduction, value-checked.
    The BENCH_r05 postmortem: an unhealthy TPU wedged mid-section with
    ``UNAVAILABLE: TPU device error`` and poisoned every later section in
    the process; probing up front converts that into ONE structured
    ``sections_failed`` reason per skipped section, recorded before any
    work is lost. Returns (ok, reason, info) — ``info`` reports the
    device topology, including whether a multi-device CPU mesh is FORCED
    (``--xla_force_host_platform_device_count``): the multi-device psum
    arms consult it to record a structured ``preflight:`` skip when the
    flag is absent, instead of wedging in a 1-device collective."""
    info = {}
    try:
        import jax
        import jax.numpy as jnp

        from photon_ml_tpu import compat

        devs = jax.devices()
        info = {
            "platform": devs[0].platform,
            "device_count": len(devs),
        }
        if devs[0].platform == "cpu":
            # a >1-device CPU mesh only exists when forced through
            # XLA_FLAGS; report the flag so arm-level skips can say WHY
            info["forced_cpu_devices"] = compat.forced_cpu_device_count()
        out = jax.jit(lambda x: x * 2.0 + 1.0)(jnp.arange(8.0))  # jit-ok: trivial preflight probe kernel, no state worth donating
        got = np.asarray(jax.block_until_ready(out))
        if not np.array_equal(got, np.arange(8.0) * 2.0 + 1.0):
            return False, f"probe kernel returned wrong values: {got[:4]}", info
        if len(devs) > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh

            ctx = MeshContext(data_mesh())
            arr = jax.device_put(
                np.ones((len(devs), 4), np.float32),
                NamedSharding(ctx.mesh, P(ctx.axis)),
            )
            red = jax.jit(  # jit-ok: preflight collective probe, no state worth donating
                lambda a: a.sum(axis=0),
                out_shardings=NamedSharding(ctx.mesh, P()),
            )(arr)
            rv = np.asarray(jax.block_until_ready(red))
            if not np.array_equal(rv, np.full(4, float(len(devs)), np.float32)):
                return False, f"collective probe returned wrong values: {rv}", info
        return True, None, info
    except Exception as e:  # noqa: BLE001 — ANY probe failure means the device is unusable; that is the signal
        return False, f"{type(e).__name__}: {str(e)[:200]}", info


def _run_sections(names, extra, errors, on_tpu, state=None, after=None):
    """Run the named bench sections in-process; returns the dense value.

    Per-section failure isolation: a section that raises records its
    traceback under ``errors[name]`` and the remaining sections still run.
    A DEVICE-WEDGE failure (UNAVAILABLE — the client is dead for the whole
    process) is recorded in full ONCE; later sections still run (they may
    be host-only, e.g. ingest) but a repeat of the same signature degrades
    to a one-line pointer at the wedging section."""
    value = 0.0
    device_names = [n for n in names if n not in HOST_ONLY_SECTIONS]
    if device_names:
        ok, reason, pinfo = _device_preflight()
        extra["preflight"] = dict(
            {"ok": bool(ok)} if ok else {"ok": False, "reason": reason},
            **pinfo,
        )
        if not ok:
            # structured up-front failure instead of letting an unhealthy
            # device wedge mid-section (BENCH_r05 perhost/scoring mode)
            _log(f"PREFLIGHT FAILED ({reason}); skipping device sections")
            for n in device_names:
                errors[n] = f"device preflight failed: {reason}"
                extra.setdefault("sections_failed", {})[n] = (
                    f"preflight: {reason}"[:200]
                )
            names = [n for n in names if n in HOST_ONLY_SECTIONS]
            if after is not None:
                after()
    wedged_by = None  # (section, signature) of the first wedge traceback
    for name in names:
        try:
            if name == "dense":
                x_h = y_h = None
                try:
                    x_h, y_h = _dense_data()
                    value = _bench_dense(extra, x_h, y_h, on_tpu)
                finally:
                    del x_h, y_h  # ~537MB must not outlive the section
                if state is not None:
                    state["value"] = value
            elif name == "sparse":
                _bench_sparse(extra, on_tpu)
            elif name == "sparse_race":
                _bench_sparse_race(extra, on_tpu)
            elif name == "game":
                _bench_game(extra, on_tpu)
            elif name == "game5":
                _bench_game5(extra, on_tpu)
            elif name == "grid":
                _bench_grid(extra, on_tpu)
            elif name == "streaming":
                _bench_streaming(extra, on_tpu)
            elif name == "streaming_pipeline":
                _bench_streaming_pipeline(extra, on_tpu)
            elif name == "compile_reuse":
                _bench_compile_reuse(extra, on_tpu)
            elif name == "compaction":
                _bench_compaction(extra, on_tpu)
            elif name == "fused_schedule":
                _bench_fused_schedule(extra, on_tpu)
            elif name == "adaptive_schedule":
                _bench_adaptive_schedule(extra, on_tpu)
            elif name == "plan_auto":
                _bench_plan_auto(extra, on_tpu)
            elif name == "preemption_resume":
                _bench_preempt(extra, on_tpu)
            elif name == "perhost":
                _bench_perhost(extra, on_tpu)
            elif name == "perhost_streaming":
                _bench_perhost_streaming(extra, on_tpu)
            elif name == "elastic_reshard":
                _bench_elastic_reshard(extra, on_tpu)
            elif name == "scoring":
                _bench_scoring(extra, on_tpu)
            elif name == "serving":
                _bench_serving(extra, on_tpu)
            elif name == "serving_fleet":
                _bench_serving_fleet(extra, on_tpu)
            elif name == "quantized_serving":
                _bench_quantized_serving(extra, on_tpu)
            elif name == "retrain_delta":
                _bench_retrain_delta(extra, on_tpu)
            elif name == "delta_rollout":
                _bench_delta_rollout(extra, on_tpu)
            elif name == "day_in_life":
                _bench_day_in_life(extra, on_tpu)
            elif name == "ingest":
                _bench_ingest(extra)
        except Exception:  # noqa: BLE001 — per-section fence: failure recorded in errors, bench continues
            tb = traceback.format_exc(limit=3)
            sig = next((s for s in _WEDGE_SIGNATURES if s in tb), None)
            if wedged_by is not None and sig == wedged_by[1]:
                # dedup ONLY an identical signature: a different failure
                # mode after a wedge is new information and keeps its
                # full traceback
                errors[name] = (
                    f"device client wedged ({sig} — same signature as "
                    f"section {wedged_by[0]!r}, see its traceback)"
                )
            else:
                errors[name] = tb
                if sig is not None and wedged_by is None:
                    wedged_by = (name, sig)
            # failed-with-reason marker in the PAYLOAD (not just errors —
            # which partial saves truncate): the capture records which
            # sections died and why in one line, and the run continues
            # (BENCH_r05 postmortem: a device wedge in perhost/scoring must
            # never erase the sections after it)
            last = tb.strip().splitlines()[-1] if tb.strip() else "unknown"
            extra.setdefault("sections_failed", {})[name] = last[:200]
        if after is not None:
            after()
    return value


def _section_child_main(argv):
    """Child mode (``--section NAME --out PATH``): run ONE section against a
    freshly-claimed device and write {value, platform, extra, errors} to
    PATH atomically. Always exits 0 — a device fault degrades to an errors
    entry, and the parent's next child re-claims a healthy device."""
    name = argv[argv.index("--section") + 1]
    out_path = argv[argv.index("--out") + 1]
    extra, errors = {}, {}
    platform = None
    value = 0.0
    try:
        import jax

        if os.environ.get("PHOTON_ML_TPU_BENCH_CPU"):
            jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        platform = devs[0].platform
        _log(f"[{name}] device: {devs[0]} ({platform})")
        from photon_ml_tpu.ops.fused_glm import _on_tpu

        value = _run_sections([name], extra, errors, _on_tpu())
    except Exception:  # noqa: BLE001 — single-section fence: failure recorded, JSON still emitted
        errors[name] = traceback.format_exc(limit=5)
    payload = {
        "value": value,
        "platform": platform,
        "extra": extra,
        "errors": {k: str(v) for k, v in errors.items()},
    }
    try:
        with open(out_path + ".tmp", "w") as f:
            json.dump(payload, f)
        os.replace(out_path + ".tmp", out_path)
    except Exception:  # noqa: BLE001 — the parent handles a missing file
        pass
    return 0


def _run_isolated_sections(names, extra, errors, state, save_partial):
    """Run each section as its OWN child process. Motivation (r5 self-capture
    post-mortem): a TPU kernel fault in the grid race wedged the shared
    process's device client and every later section died with UNAVAILABLE —
    but a FRESH process (tpu_capture phase 2) recovered the device fine.
    Children are never killed; on deadline they are detached and left to
    exit on their own, releasing the tunnel claim cleanly."""
    import subprocess
    import tempfile

    value = 0.0
    consecutive_hangs = 0
    for name in names:
        fd, out_path = tempfile.mkstemp(prefix=f"bench-{name}-", suffix=".json")
        os.close(fd)
        os.unlink(out_path)
        deadline = SECTION_DEADLINES.get(name, DEFAULT_SECTION_DEADLINE)
        log_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "bench_section_logs"
        )
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"{name}.log")
        _log(f"=== section {name} (child, deadline {deadline}s, log {log_path}) ===")
        # children get a FILE, not our pipes: a detached (hung) child holding
        # an inherited pipe would stall any supervisor reading us to EOF
        with open(log_path, "w") as lf:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--section", name, "--out", out_path],
                stdout=lf, stderr=lf,
                start_new_session=True,  # survives the parent; never killed
            )
        t_end = time.time() + deadline
        while time.time() < t_end and proc.poll() is None:
            time.sleep(2)
        if proc.poll() is None:
            errors[name] = (
                f"section exceeded {deadline}s; child pid {proc.pid} left "
                "running (never killed — tunnel claim hygiene)"
            )
            consecutive_hangs += 1
            save_partial()
            if consecutive_hangs >= 2:
                errors["isolation"] = (
                    "two consecutive section hangs; remaining sections skipped"
                )
                break
            continue
        consecutive_hangs = 0
        try:
            with open(log_path) as lf2:
                for ln in lf2.read().strip().splitlines()[-8:]:
                    _log(f"  [{name}] {ln}")
        except Exception:  # noqa: BLE001 — child log tail is best-effort
            pass
        try:
            with open(out_path) as f:
                payload = json.load(f)
            os.unlink(out_path)
        except Exception:  # noqa: BLE001 — missing/corrupt child result degrades to an error record
            errors[name] = f"child exited rc={proc.returncode} with no result file"
            save_partial()
            continue
        extra.update(payload.get("extra") or {})
        errors.update(payload.get("errors") or {})
        if payload.get("platform") and state.get("platform") is None:
            state["platform"] = payload["platform"]
        if name == "dense" and payload.get("value"):
            value = payload["value"]
            state["value"] = value
        save_partial()
    return value


def main():
    if "--list-sections" in sys.argv:
        # enumerate sections WITHOUT importing jax or any accelerator path
        # (smoke-testable everywhere, incl. hosts with no backend at all)
        for name in SECTION_ORDER:
            print(name)
        return
    if "--section" in sys.argv:
        # plain return, NOT sys.exit: SystemExit would be caught by the
        # __main__ BaseException fence and append a bogus fatal JSON line
        _section_child_main(sys.argv)
        return
    if "--perhost-worker" in sys.argv:
        # SPMD child of the perhost_streaming section (one process per
        # simulated host); same plain-return rule as --section
        _perhost_worker_main(sys.argv)
        return
    if "--merge-worker" in sys.argv:
        # Gloo child of the fused_schedule section's merge comparator;
        # same plain-return rule as --section
        _merge_worker_main(sys.argv)
        return
    if "--elastic-worker" in sys.argv:
        # SPMD child of the elastic_reshard section (fresh-survivor and
        # mid-epoch-re-plan arms); same plain-return rule as --section
        _elastic_worker_main(sys.argv)
        return

    errors = {}
    extra = {}
    state = {"value": 0.0, "platform": None}

    # baseline needs no device — compute it first so it survives any failure
    x_h, y_h = _dense_data()
    base_eps, _, _ = _numpy_baseline(x_h, y_h, np.zeros(D_DENSE, np.float32))
    del x_h, y_h
    _log(f"baseline(numpy): {base_eps:.3e} ex/s")

    partial_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_partial.json"
    )
    try:
        # a STALE checkpoint from a prior run must never masquerade as this
        # run's crash state
        os.unlink(partial_path)
    except OSError:
        pass

    def _save_partial():
        """Checkpoint progress to a side file after every section: if an
        external supervisor kills this process mid-run (observed risk: a
        long autotune race over a slow tunnel), the completed sections
        survive for post-mortem even though the stdout line never printed."""
        try:
            snap = {
                "partial": True,
                "value": round(state["value"], 1),
                "vs_baseline": round(state["value"] / base_eps, 3) if base_eps else 0.0,
                "platform": state["platform"],
                **extra,
            }
            if errors:
                snap["errors"] = {k: str(v)[:500] for k, v in errors.items()}
            with open(partial_path + ".tmp", "w") as f:
                json.dump(snap, f, indent=1)
            os.replace(partial_path + ".tmp", partial_path)
        except Exception:  # noqa: BLE001 — never let bookkeeping kill the bench
            pass

    names = list(SECTION_ORDER)
    sel = os.environ.get("PHOTON_ML_TPU_BENCH_SECTIONS")
    if sel:
        names = [s for s in sel.split(",") if s in SECTION_ORDER]
        unknown = [s for s in sel.split(",") if s and s not in SECTION_ORDER]
        if unknown:
            errors["sections"] = f"unknown section names ignored: {unknown}"
        if not names:
            raise SystemExit(
                f"PHOTON_ML_TPU_BENCH_SECTIONS={sel!r} selects no known section "
                f"(valid: {','.join(SECTION_ORDER)})"
            )

    value = 0.0
    if os.environ.get("PHOTON_ML_TPU_BENCH_CPU"):
        # explicit CPU run (dev/smoke): in-process, no tunnel involved
        import jax

        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        state["platform"] = devs[0].platform
        from photon_ml_tpu.ops.fused_glm import _on_tpu

        value = _run_sections(
            names, extra, errors, _on_tpu(), state=state, after=_save_partial
        )
    else:
        probed = _probe_platform(errors)
        if probed in ("tpu", "axon") and not os.environ.get(
            "PHOTON_ML_TPU_BENCH_NO_ISOLATE"
        ):
            value = _run_isolated_sections(names, extra, errors, state, _save_partial)
            state["platform"] = state["platform"] or probed
        else:
            # CPU fallback (tunnel down) or an unexpected probed platform:
            # run in-process. config.update (not the env var) because the
            # accelerator plugin's register() overrides JAX_PLATFORMS at
            # import time.
            import jax

            if probed is None:
                errors["backend"] = (
                    "accelerator unavailable after probe attempts; ran on CPU"
                )
                jax.config.update("jax_platforms", "cpu")
                _log("FALLBACK to CPU")
            try:
                devs = jax.devices()
            except Exception as e:  # noqa: BLE001 — no backend at all still emits the JSON line
                errors["backend"] = f"no backend at all: {type(e).__name__}: {e}"
                devs = None
            if devs is not None:
                state["platform"] = devs[0].platform
                _log(f"device: {devs[0]} ({state['platform']}) x{len(devs)}")
                from photon_ml_tpu.ops.fused_glm import _on_tpu

                value = _run_sections(
                    names, extra, errors, _on_tpu(), state=state, after=_save_partial
                )

    platform = state["platform"]
    vs_baseline = value / base_eps if base_eps else 0.0

    payload = {
        "metric": METRIC,
        "value": round(value, 1),
        "unit": UNIT,
        "vs_baseline": round(vs_baseline, 3),
        "platform": platform,
        **extra,
    }
    if platform != "tpu" and not (platform or "").startswith("axon"):
        # degraded run (tunnel down / CPU fallback): attach the most recent
        # preserved on-TPU self-capture so the round keeps a clearly-labelled
        # TPU record even when the end-of-round tunnel is wedged
        selfrun = _latest_tpu_selfrun()
        if selfrun is not None:
            payload["tpu_selfrun"] = selfrun
    if errors:
        payload["errors"] = errors
    _emit(payload)


def _latest_tpu_selfrun():
    """Most recent BENCH_SELFRUN_r*.json next to this script, if any."""
    import glob
    import os

    import re

    here = os.path.dirname(os.path.abspath(__file__))
    paths = glob.glob(os.path.join(here, "BENCH_SELFRUN_r*.json"))

    def _round_no(p):
        m = re.search(r"_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else -1

    # newest ROUND first (mtime lies after a fresh clone); fall back past
    # corrupt or non-TPU captures to the first valid one
    for path in sorted(paths, key=_round_no, reverse=True):
        try:
            with open(path) as f:
                data = json.load(f)
            # only a genuine on-TPU capture may stand in as the TPU record
            if isinstance(data, dict) and data.get("platform") == "tpu":
                data["source_file"] = os.path.basename(path)
                return data
        except Exception:  # noqa: BLE001 — a corrupt capture must not kill the emit
            continue
    return None


if __name__ == "__main__":
    try:
        main()
    except BaseException:  # noqa: BLE001 — last-ditch fence: the JSON line must ALWAYS appear
        _emit(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "errors": {"fatal": traceback.format_exc(limit=5)},
            }
        )
        sys.exit(0)
