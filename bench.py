"""Benchmark driver: GLM training throughput on the current accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload: the hot loop of GLM training — L2 logistic regression
value+gradient passes (the reference's ValueAndGradientAggregator
treeAggregate, SURVEY.md §2.2) on a synthetic dense dataset sized like a
realistic ads/feed shard: N=262144 examples x D=512 features. Features are
stored bfloat16 (the HBM-bandwidth lever; contraction accumulates f32 on
the MXU) after a numerical-parity check against the f32 path.

Methodology: iterations are serialized ON-CHIP via ``lax.scan`` with a
gradient-dependent weight update, so the measured time is real sequential
compute — host-loop timing over an RPC tunnel pipelines/caches dispatches
and reports physically impossible rates.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
baseline is a single-host NumPy implementation of the identical computation
measured in-process (a stand-in for the reference's JVM/Breeze
per-partition CPU path, which it bounds from above). Values > 1 mean
faster than baseline.
"""

import json
import sys
import time

import numpy as np

SCAN_ITERS = 50
STEP = 1e-6


def _numpy_baseline(x, y, w, iters=3):
    t0 = time.perf_counter()
    for _ in range(iters):
        z = x @ w
        s = 1.0 / (1.0 + np.exp(-z))
        val = np.sum(np.maximum(z, 0) + np.log1p(np.exp(-np.abs(z))) - y * z)
        g = (s - y) @ x
        g = g + 0.1 * w
        val = val + 0.05 * np.sum(w * w)
        w = w - STEP * g  # same dependency chain as the device loop
    dt = (time.perf_counter() - t0) / iters
    return x.shape[0] / dt, float(val), g


def main():
    n, d = 262144, 512
    rng = np.random.default_rng(0)
    x_h = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32) * 0.1
    y_h = (1.0 / (1.0 + np.exp(-x_h @ w_true)) > rng.random(n)).astype(np.float32)

    base_eps, _, _ = _numpy_baseline(x_h, y_h, np.zeros(d, np.float32))

    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops.features import DenseFeatures
    from photon_ml_tpu.ops.normalization import NormalizationContext
    from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective

    dev = jax.devices()[0]
    print(f"device: {dev} ({dev.platform})", file=sys.stderr)

    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()

    def value_and_grad(feats, labels, w):
        batch = GLMBatch.create(feats, labels)
        return obj.value_and_grad(w, batch, norm, 0.1)

    labels = jnp.asarray(y_h)
    feats_f32 = DenseFeatures(jnp.asarray(x_h))
    feats_bf16 = feats_f32.astype(jnp.bfloat16)
    w0 = jnp.zeros((d,), jnp.float32)

    # numerical parity gate at a NONZERO weight vector (w=0 would zero the
    # margins and leave the matvec path untested)
    w_probe = jnp.asarray(w_true)
    v32, g32 = jax.jit(value_and_grad)(feats_f32, labels, w_probe)
    v16, g16 = jax.jit(value_and_grad)(feats_bf16, labels, w_probe)
    rel_v = abs(float(v16) - float(v32)) / max(abs(float(v32)), 1e-12)
    rel_g = float(jnp.linalg.norm(g16 - g32) / jnp.maximum(jnp.linalg.norm(g32), 1e-12))
    print(f"bf16 parity: value rel {rel_v:.2e}, grad rel {rel_g:.2e}", file=sys.stderr)
    assert rel_v < 5e-2 and rel_g < 5e-2, "bf16 storage diverged from f32 path"

    # on-chip serialized loop: each step's weights depend on the previous
    # grad. The feature matrix enters as a jit ARGUMENT (traced, not an
    # embedded constant) and stays out of the scan carry.
    def scan_fn(w, f):
        def step(w_, _):
            v, g = value_and_grad(f, labels, w_)
            return w_ - STEP * g, v

        return jax.lax.scan(step, w, None, length=SCAN_ITERS)

    scan = jax.jit(scan_fn)
    jax.block_until_ready(scan(w0, feats_bf16))  # compile + warm
    t0 = time.perf_counter()
    out = scan(w0, feats_bf16)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / SCAN_ITERS
    eps = n / dt

    print(f"tpu: {eps:.3e} ex/s  baseline(numpy): {base_eps:.3e} ex/s", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "glm_logistic_value_and_grad_throughput",
                "value": round(eps, 1),
                "unit": "examples/sec/chip",
                "vs_baseline": round(eps / base_eps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
