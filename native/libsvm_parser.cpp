// Native LIBSVM text parser — the data-loader fast path.
//
// The reference's ingest runs on JVM executors (io/LibSVMInputDataFormat
// .scala:31, GLMSuite.scala:295-340 text parsing); this build's equivalent
// "native runtime" piece parses LIBSVM text in C++ (single pass over a
// read()-buffered file, strtod/strtol scanning) and hands CSR arrays back
// to Python through ctypes. Semantics are byte-for-byte those of
// photon_ml_tpu.io.libsvm.read_libsvm: '#' starts a comment, blank lines
// skipped, first token is the label, "idx:val" pairs follow, indices
// 1-based unless zero_based. Label {-1,1}->{0,1} remapping and the
// intercept append stay in Python (they need whole-dataset views).
//
// C API (ctypes):
//   void* lsv_parse(const char* path, int zero_based)  NULL on I/O error
//   long  lsv_rows(void*)
//   long  lsv_nnz(void*)
//   long  lsv_max_index(void*)    // -1 when the file has no features
//   int   lsv_ok(void*)           // 0 when a malformed token was seen
//   void  lsv_fill(void*, double* labels, long long* indptr,
//                  int* indices, double* values)
//   void  lsv_free(void*)

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Parsed {
  std::vector<double> labels;
  std::vector<long long> indptr;  // rows + 1
  std::vector<int> indices;
  std::vector<double> values;
  long long max_index = -1;
  bool ok = true;
};

}  // namespace

extern "C" {

void* lsv_parse(const char* path, int zero_based) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf;
  buf.resize(static_cast<size_t>(size));
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    return nullptr;
  }
  std::fclose(f);

  auto* out = new Parsed();
  out->indptr.push_back(0);
  const int base = zero_based ? 0 : 1;

  const char* p = buf.c_str();
  const char* end = p + buf.size();
  while (p < end) {
    // one line: up to '\n'; '#' cuts the rest
    const char* nl = static_cast<const char*>(std::memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    const char* hash = static_cast<const char*>(std::memchr(p, '#', line_end - p));
    const char* stop = hash ? hash : line_end;

    // skip leading whitespace
    while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    if (p < stop) {
      char* after = nullptr;
      double label = std::strtod(p, &after);
      if (after == p) {
        out->ok = false;  // malformed label
      } else {
        out->labels.push_back(label);
        p = after;
        // idx:val tokens
        while (p < stop) {
          while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
          if (p >= stop) break;
          char* a1 = nullptr;
          long idx = std::strtol(p, &a1, 10);
          if (a1 == p || a1 >= stop || *a1 != ':') {
            out->ok = false;
            break;
          }
          const char* vstart = a1 + 1;
          // the python parser rejects 'idx:' with whitespace/EOL after the
          // colon; strtod would skip it and steal the NEXT number — guard
          if (vstart >= stop || *vstart == ' ' || *vstart == '\t' ||
              *vstart == '\r' || *vstart == '\n') {
            out->ok = false;
            break;
          }
          char* a2 = nullptr;
          double val = std::strtod(vstart, &a2);
          if (a2 == vstart || a2 > stop) {
            out->ok = false;
            break;
          }
          long adj = idx - base;
          if (adj > 2147483647L || adj < -2147483648L) {
            out->ok = false;  // python raises OverflowError on int32 cast
            break;
          }
          out->indices.push_back(static_cast<int>(adj));
          out->values.push_back(val);
          if (adj > out->max_index) out->max_index = adj;
          p = a2;
        }
        out->indptr.push_back(static_cast<long long>(out->indices.size()));
      }
    }
    p = nl ? nl + 1 : end;
  }
  return out;
}

long lsv_rows(void* h) { return static_cast<Parsed*>(h)->labels.size(); }
long lsv_nnz(void* h) { return static_cast<Parsed*>(h)->indices.size(); }
long lsv_max_index(void* h) { return static_cast<Parsed*>(h)->max_index; }
int lsv_ok(void* h) { return static_cast<Parsed*>(h)->ok ? 1 : 0; }

void lsv_fill(void* h, double* labels, long long* indptr, int* indices,
              double* values) {
  auto* d = static_cast<Parsed*>(h);
  std::memcpy(labels, d->labels.data(), d->labels.size() * sizeof(double));
  std::memcpy(indptr, d->indptr.data(), d->indptr.size() * sizeof(long long));
  std::memcpy(indices, d->indices.data(), d->indices.size() * sizeof(int));
  std::memcpy(values, d->values.data(), d->values.size() * sizeof(double));
}

void lsv_free(void* h) { delete static_cast<Parsed*>(h); }

}  // extern "C"
