// Native Avro container decoder — the ingest fast path.
//
// The reference ingests Avro on JVM executors (AvroUtils.scala:53,
// DataProcessingUtils.scala:33-200); the byte-level decode there is
// generated-class Java. This build's equivalent native runtime piece walks
// the Avro 1.x container wire format in C++ — block framing + raw-deflate
// (zlib) + zigzag varints — and emits COLUMNS for a schema described by a
// compact descriptor, handed to Python via ctypes. Anything the descriptor
// grammar cannot express makes avd_parse return an error and Python falls
// back to the pure codec (io/avro.py), which stays the source of truth.
//
// Descriptor grammar (recursive, byte codes):
//   0x01 double   0x02 float   0x03 long   0x04 int   0x05 string
//   0x06 boolean  0x07 null
//   0x10 union:  [u8 n][n branch descriptors]
//   0x20 array:  [item descriptor]
//   0x30 map:    [value descriptor]
//   0x40 record: [u8 n_fields][field descriptors]
// The TOP-LEVEL descriptor must be a record; its fields become columns.
//
// Column layouts (per top-level field, queried by index):
//   numeric/boolean (or union with null): f64 data + u8 present mask
//   string (or union with null):          byte heap + i64 offsets + mask
//   array<...>:  per-record counts + the item's columns flattened
//   map<string>: per-record counts + key heap/offsets + value heap/offsets
//   record{...}: its fields' columns flattened (fixed offset into the
//                child column list)
//
// C API: see avd_* prototypes below. All getters copy into caller buffers.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

// ---------------------------------------------------------------- reader --
struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      ok = false;
      return false;
    }
    return true;
  }
  int64_t read_long() {  // zigzag varint
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) {
        ok = false;
        return 0;
      }
    }
    return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
  }
  double read_double() {
    if (!need(8)) return 0.0;
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  float read_float() {
    if (!need(4)) return 0.0f;
    float v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  bool read_bytes(const uint8_t** out, int64_t* len) {
    int64_t n = read_long();
    if (!ok || n < 0 || !need(static_cast<size_t>(n))) {
      ok = false;
      return false;
    }
    *out = p;
    *len = n;
    p += n;
    return true;
  }
};

// ------------------------------------------------------------ descriptor --
enum Code : uint8_t {
  D_DOUBLE = 0x01,
  D_FLOAT = 0x02,
  D_LONG = 0x03,
  D_INT = 0x04,
  D_STRING = 0x05,
  D_BOOL = 0x06,
  D_NULL = 0x07,
  D_UNION = 0x10,
  D_ARRAY = 0x20,
  D_MAP = 0x30,
  D_RECORD = 0x40,
};

struct Node {
  uint8_t code;
  std::vector<Node> children;  // union branches / array item / map value /
                               // record fields
  // column storage (filled during decode); which members are used depends
  // on code — see header comment
  std::vector<double> nums;
  std::vector<uint8_t> present;
  std::vector<uint8_t> heap;       // string bytes
  std::vector<int64_t> offsets;    // string end-offsets into heap
  std::vector<int64_t> counts;     // array/map: items per parent entry
  std::vector<uint8_t> kheap;      // map keys
  std::vector<int64_t> koffsets;
  std::vector<uint8_t> kinds;      // union: chosen branch index per entry
  bool lossy_long = false;         // a long exceeded 2^53 (f64-exact range)
};

bool parse_descriptor(const uint8_t*& d, const uint8_t* dend, Node* out) {
  if (d >= dend) return false;
  out->code = *d++;
  switch (out->code) {
    case D_DOUBLE: case D_FLOAT: case D_LONG: case D_INT:
    case D_STRING: case D_BOOL: case D_NULL:
      return true;
    case D_UNION: case D_RECORD: {
      if (d >= dend) return false;
      uint8_t n = *d++;
      out->children.resize(n);
      for (uint8_t i = 0; i < n; ++i)
        if (!parse_descriptor(d, dend, &out->children[i])) return false;
      return true;
    }
    case D_ARRAY: case D_MAP: {
      out->children.resize(1);
      return parse_descriptor(d, dend, &out->children[0]);
    }
    default:
      return false;
  }
}

// -------------------------------------------------------------- decoding --
// Decodes ONE datum of type `node`, appending to the node's columns.
bool decode_datum(Reader& r, Node& node) {
  switch (node.code) {
    case D_DOUBLE:
      node.nums.push_back(r.read_double());
      node.present.push_back(1);
      return r.ok;
    case D_FLOAT:
      node.nums.push_back(static_cast<double>(r.read_float()));
      node.present.push_back(1);
      return r.ok;
    case D_LONG:
    case D_INT: {
      int64_t v = r.read_long();
      // columns carry f64: a long outside +/-2^53 would silently round
      // (id collapse); flag it so the whole file falls back to the exact
      // python codec
      if (v > (1ll << 53) || v < -(1ll << 53)) node.lossy_long = true;
      node.nums.push_back(static_cast<double>(v));
      node.present.push_back(1);
      return r.ok;
    }
    case D_BOOL: {
      if (!r.need(1)) return false;
      node.nums.push_back(*r.p++ ? 1.0 : 0.0);
      node.present.push_back(1);
      return true;
    }
    case D_NULL:
      node.nums.push_back(0.0);
      node.present.push_back(0);
      return true;
    case D_STRING: {
      const uint8_t* s;
      int64_t len;
      if (!r.read_bytes(&s, &len)) return false;
      node.heap.insert(node.heap.end(), s, s + len);
      node.offsets.push_back(static_cast<int64_t>(node.heap.size()));
      node.present.push_back(1);
      return true;
    }
    case D_UNION: {
      int64_t branch = r.read_long();
      if (!r.ok || branch < 0 ||
          branch >= static_cast<int64_t>(node.children.size()))
        return false;
      Node& b = node.children[static_cast<size_t>(branch)];
      // union columns live on the UNION node itself: kinds records the
      // chosen branch per entry; nums/present are entry-aligned; offsets
      // advance only on string entries (python ranks them via kinds);
      // nested branches decode into their own child node.
      node.kinds.push_back(static_cast<uint8_t>(branch));
      if (b.code == D_NULL) {
        node.nums.push_back(0.0);
        node.present.push_back(0);
        return true;
      }
      switch (b.code) {
        case D_DOUBLE:
          node.nums.push_back(r.read_double());
          node.present.push_back(1);
          return r.ok;
        case D_FLOAT:
          node.nums.push_back(static_cast<double>(r.read_float()));
          node.present.push_back(1);
          return r.ok;
        case D_LONG:
        case D_INT: {
          int64_t v = r.read_long();
          if (v > (1ll << 53) || v < -(1ll << 53)) node.lossy_long = true;
          node.nums.push_back(static_cast<double>(v));
          node.present.push_back(1);
          return r.ok;
        }
        case D_BOOL: {
          if (!r.need(1)) return false;
          node.nums.push_back(*r.p++ ? 1.0 : 0.0);
          node.present.push_back(1);
          return true;
        }
        case D_STRING: {
          const uint8_t* s;
          int64_t len;
          if (!r.read_bytes(&s, &len)) return false;
          node.heap.insert(node.heap.end(), s, s + len);
          node.offsets.push_back(static_cast<int64_t>(node.heap.size()));
          node.nums.push_back(0.0);
          node.present.push_back(1);
          return true;
        }
        case D_MAP:
        case D_ARRAY:
        case D_RECORD: {
          node.nums.push_back(0.0);
          node.present.push_back(1);
          return decode_datum(r, b);
        }
        default:
          return false;
      }
    }
    case D_ARRAY: {
      int64_t total = 0;
      while (true) {
        int64_t n = r.read_long();
        if (!r.ok) return false;
        if (n == 0) break;
        if (n < 0) {  // block with byte size prefix
          n = -n;
          r.read_long();  // byte length, unused
          if (!r.ok) return false;
        }
        for (int64_t i = 0; i < n; ++i)
          if (!decode_datum(r, node.children[0])) return false;
        total += n;
      }
      node.counts.push_back(total);
      return true;
    }
    case D_MAP: {
      int64_t total = 0;
      while (true) {
        int64_t n = r.read_long();
        if (!r.ok) return false;
        if (n == 0) break;
        if (n < 0) {
          n = -n;
          r.read_long();
          if (!r.ok) return false;
        }
        for (int64_t i = 0; i < n; ++i) {
          const uint8_t* s;
          int64_t len;
          if (!r.read_bytes(&s, &len)) return false;
          node.kheap.insert(node.kheap.end(), s, s + len);
          node.koffsets.push_back(static_cast<int64_t>(node.kheap.size()));
          if (!decode_datum(r, node.children[0])) return false;
        }
        total += n;
      }
      node.counts.push_back(total);
      return true;
    }
    case D_RECORD: {
      for (auto& f : node.children)
        if (!decode_datum(r, f)) return false;
      return true;
    }
    default:
      return false;
  }
}

}  // namespace

// ------------------------------------------------------------------ state --
struct Decoded {
  Node root;
  int64_t num_records = 0;
  std::string error;
};

extern "C" {

void* avd_parse(const uint8_t* file_bytes, long file_len,
                const uint8_t* descriptor, long desc_len);
long avd_num_records(void* h);
const char* avd_error(void* h);
void avd_free(void* h);

// column accessors: `path`/`path_len` is a sequence of child indices from
// the root record (u32 each); returns sizes first, then fills.
long avd_col_size_nums(void* h, const uint32_t* path, long path_len);
long avd_col_size_heap(void* h, const uint32_t* path, long path_len);
long avd_col_size_counts(void* h, const uint32_t* path, long path_len);
long avd_col_size_kheap(void* h, const uint32_t* path, long path_len);
long avd_col_size_offsets(void* h, const uint32_t* path, long path_len);
long avd_col_size_present(void* h, const uint32_t* path, long path_len);
long avd_col_size_koffsets(void* h, const uint32_t* path, long path_len);
int avd_col_fetch(void* h, const uint32_t* path, long path_len,
                  double* nums, uint8_t* present, uint8_t* heap,
                  int64_t* offsets, int64_t* counts, uint8_t* kheap,
                  int64_t* koffsets);
}

namespace {

bool any_lossy(const Node& n) {
  if (n.lossy_long) return true;
  for (const auto& c : n.children)
    if (any_lossy(c)) return true;
  return false;
}

Node* walk(Decoded* d, const uint32_t* path, long path_len) {
  Node* n = &d->root;
  for (long i = 0; i < path_len; ++i) {
    if (path[i] >= n->children.size()) return nullptr;
    n = &n->children[path[i]];
  }
  return n;
}

}  // namespace

extern "C" {

void* avd_parse(const uint8_t* file_bytes, long file_len,
                const uint8_t* descriptor, long desc_len) {
  auto* d = new Decoded();
  const uint8_t* dp = descriptor;
  if (!parse_descriptor(dp, descriptor + desc_len, &d->root) ||
      d->root.code != D_RECORD) {
    d->error = "bad descriptor";
    return d;
  }

  Reader r{file_bytes, file_bytes + file_len};
  // header: magic
  if (!r.need(4) || std::memcmp(r.p, "Obj\x01", 4) != 0) {
    d->error = "bad magic";
    return d;
  }
  r.p += 4;
  // metadata map: we need avro.codec; schema compatibility is the CALLER's
  // responsibility (python passes a descriptor derived from the file's own
  // schema)
  std::string codec = "null";
  while (true) {
    int64_t n = r.read_long();
    if (!r.ok) {
      d->error = "bad metadata";
      return d;
    }
    if (n == 0) break;
    if (n < 0) {
      n = -n;
      r.read_long();
    }
    for (int64_t i = 0; i < n; ++i) {
      const uint8_t* k;
      int64_t klen;
      const uint8_t* v;
      int64_t vlen;
      if (!r.read_bytes(&k, &klen) || !r.read_bytes(&v, &vlen)) {
        d->error = "bad metadata entry";
        return d;
      }
      if (klen == 10 && std::memcmp(k, "avro.codec", 10) == 0)
        codec.assign(reinterpret_cast<const char*>(v),
                     static_cast<size_t>(vlen));
    }
  }
  if (codec != "null" && codec != "deflate") {
    d->error = "unsupported codec: " + codec;
    return d;
  }
  if (!r.need(16)) {
    d->error = "missing sync";
    return d;
  }
  uint8_t sync[16];
  std::memcpy(sync, r.p, 16);
  r.p += 16;

  std::vector<uint8_t> inflated;
  while (r.p < r.end) {
    int64_t count = r.read_long();
    if (!r.ok) {
      d->error = "bad block count";
      return d;
    }
    const uint8_t* payload;
    int64_t plen;
    if (!r.read_bytes(&payload, &plen)) {
      d->error = "bad block payload";
      return d;
    }
    Reader br{payload, payload + plen};
    if (codec == "deflate") {
      // raw deflate (no zlib header), unknown output size: grow-and-retry
      inflated.clear();
      size_t cap = static_cast<size_t>(plen) * 4 + 1024;
      int ret;
      do {
        inflated.resize(cap);
        z_stream zs;
        std::memset(&zs, 0, sizeof(zs));
        if (inflateInit2(&zs, -15) != Z_OK) {
          d->error = "inflateInit failed";
          return d;
        }
        zs.next_in = const_cast<uint8_t*>(payload);
        zs.avail_in = static_cast<uInt>(plen);
        zs.next_out = inflated.data();
        zs.avail_out = static_cast<uInt>(cap);
        ret = inflate(&zs, Z_FINISH);
        size_t produced = cap - zs.avail_out;
        inflateEnd(&zs);
        if (ret == Z_STREAM_END) {
          inflated.resize(produced);
          break;
        }
        cap *= 2;
      } while (ret == Z_BUF_ERROR && cap < (1ull << 33));
      if (ret != Z_STREAM_END) {
        d->error = "inflate failed";
        return d;
      }
      br = Reader{inflated.data(), inflated.data() + inflated.size()};
    }
    for (int64_t i = 0; i < count; ++i) {
      if (!decode_datum(br, d->root)) {
        d->error = "record decode failed";
        return d;
      }
    }
    d->num_records += count;
    if (!r.need(16) || std::memcmp(r.p, sync, 16) != 0) {
      d->error = "sync mismatch";
      return d;
    }
    r.p += 16;
  }
  if (any_lossy(d->root)) d->error = "long value exceeds 2^53";
  return d;
}

long avd_num_records(void* h) { return static_cast<Decoded*>(h)->num_records; }

const char* avd_error(void* h) {
  auto* d = static_cast<Decoded*>(h);
  return d->error.empty() ? nullptr : d->error.c_str();
}

void avd_free(void* h) { delete static_cast<Decoded*>(h); }

long avd_col_size_nums(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->nums.size()) : -1;
}
long avd_col_size_heap(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->heap.size()) : -1;
}
long avd_col_size_counts(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->counts.size()) : -1;
}
long avd_col_size_kheap(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->kheap.size()) : -1;
}
long avd_col_size_offsets(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->offsets.size()) : -1;
}
long avd_col_size_present(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->present.size()) : -1;
}
long avd_col_size_koffsets(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->koffsets.size()) : -1;
}
long avd_col_size_kinds(void* h, const uint32_t* path, long path_len) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  return n ? static_cast<long>(n->kinds.size()) : -1;
}
int avd_col_fetch_kinds(void* h, const uint32_t* path, long path_len,
                        uint8_t* kinds) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  if (!n) return -1;
  if (kinds && !n->kinds.empty())
    std::memcpy(kinds, n->kinds.data(), n->kinds.size());
  return 0;
}

int avd_col_fetch(void* h, const uint32_t* path, long path_len,
                  double* nums, uint8_t* present, uint8_t* heap,
                  int64_t* offsets, int64_t* counts, uint8_t* kheap,
                  int64_t* koffsets) {
  Node* n = walk(static_cast<Decoded*>(h), path, path_len);
  if (!n) return -1;
  if (nums && !n->nums.empty())
    std::memcpy(nums, n->nums.data(), n->nums.size() * sizeof(double));
  if (present && !n->present.empty())
    std::memcpy(present, n->present.data(), n->present.size());
  if (heap && !n->heap.empty())
    std::memcpy(heap, n->heap.data(), n->heap.size());
  if (offsets && !n->offsets.empty())
    std::memcpy(offsets, n->offsets.data(),
                n->offsets.size() * sizeof(int64_t));
  if (counts && !n->counts.empty())
    std::memcpy(counts, n->counts.data(), n->counts.size() * sizeof(int64_t));
  if (kheap && !n->kheap.empty())
    std::memcpy(kheap, n->kheap.data(), n->kheap.size());
  if (koffsets && !n->koffsets.empty())
    std::memcpy(koffsets, n->koffsets.data(),
                n->koffsets.size() * sizeof(int64_t));
  return 0;
}

}  // extern "C"
