// pmix_store: memory-mapped two-way feature index store (C API).
//
// The TPU-native replacement for the reference's PalDB off-heap index
// (util/PalDBIndexMap.scala:43-230 semantics): a partitioned name<->index
// store that many host processes can share via the page cache, with O(1)
// name->index lookup and O(1) index->name reverse lookup. Each partition is
// one file; global index = partition offset + local index, exactly the
// reference's global-offset scheme (PalDBIndexMap.scala:105-130) — the
// Python layer owns partitioning (hash) and offsets, this file owns the
// single-partition format:
//
//   header (32 B, little-endian):
//     u32 magic 'PMIX' (0x58494D50), u32 version = 1,
//     u64 num_keys, u64 table_capacity, u64 key_blob_size
//   hash table: table_capacity slots x 12 B: u32 local_index + 1 (0 = empty),
//     u64 FNV-1a hash of the key
//   offsets: (num_keys + 1) x u64 into the key blob
//   blob: UTF-8 key bytes, concatenated in local-index order
//
// Lookup: linear-probe the table by hash; on hash match, compare key bytes.
// Reverse: offsets[i]..offsets[i+1] slice the blob.

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x58494D50;  // "PMIX"
constexpr uint32_t kVersion = 1;
constexpr size_t kHeaderSize = 32;
constexpr size_t kSlotSize = 12;

struct Header {
  uint32_t magic;
  uint32_t version;
  uint64_t num_keys;
  uint64_t table_capacity;
  uint64_t key_blob_size;
};

struct Store {
  void* base = nullptr;
  size_t map_size = 0;
  Header header;
  const uint8_t* table = nullptr;    // capacity * 12 bytes
  const uint64_t* offsets = nullptr; // num_keys + 1
  const char* blob = nullptr;
};

inline uint64_t fnv1a(const char* data, long len) {
  uint64_t h = 14695981039346656037ULL;  // FNV-1a 64 offset basis
  for (long i = 0; i < len; ++i) {
    h ^= static_cast<uint8_t>(data[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

inline uint64_t next_pow2(uint64_t v) {
  uint64_t c = 1;
  while (c < v) c <<= 1;
  return c;
}

inline void slot_read(const uint8_t* table, uint64_t slot, uint32_t* idx1,
                      uint64_t* hash) {
  const uint8_t* p = table + slot * kSlotSize;
  std::memcpy(idx1, p, 4);
  std::memcpy(hash, p + 4, 8);
}

}  // namespace

extern "C" {

// Open a partition file read-only via mmap. Returns nullptr on failure.
void* pmix_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < kHeaderSize) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);  // mapping keeps the file alive
  if (base == MAP_FAILED) return nullptr;

  Store* s = new Store();
  s->base = base;
  s->map_size = st.st_size;
  std::memcpy(&s->header, base, sizeof(Header));
  if (s->header.magic != kMagic || s->header.version != kVersion) {
    munmap(base, st.st_size);
    delete s;
    return nullptr;
  }
  const uint8_t* p = static_cast<const uint8_t*>(base) + kHeaderSize;
  s->table = p;
  p += s->header.table_capacity * kSlotSize;
  s->offsets = reinterpret_cast<const uint64_t*>(p);
  p += (s->header.num_keys + 1) * sizeof(uint64_t);
  s->blob = reinterpret_cast<const char*>(p);
  return s;
}

void pmix_close(void* handle) {
  if (!handle) return;
  Store* s = static_cast<Store*>(handle);
  if (s->base) munmap(s->base, s->map_size);
  delete s;
}

long pmix_size(void* handle) {
  return handle ? static_cast<long>(static_cast<Store*>(handle)->header.num_keys)
                : -1;
}

// name -> local index; -1 if absent.
long pmix_get_index(void* handle, const char* key, long len) {
  if (!handle) return -1;
  const Store* s = static_cast<const Store*>(handle);
  if (s->header.num_keys == 0) return -1;
  const uint64_t cap = s->header.table_capacity;
  const uint64_t mask = cap - 1;
  const uint64_t h = fnv1a(key, len);
  for (uint64_t probe = 0; probe < cap; ++probe) {
    uint64_t slot = (h + probe) & mask;
    uint32_t idx1;
    uint64_t slot_hash;
    slot_read(s->table, slot, &idx1, &slot_hash);
    if (idx1 == 0) return -1;  // empty slot terminates the probe chain
    if (slot_hash == h) {
      uint64_t i = idx1 - 1;
      uint64_t start = s->offsets[i], end = s->offsets[i + 1];
      if (end - start == static_cast<uint64_t>(len) &&
          std::memcmp(s->blob + start, key, len) == 0) {
        return static_cast<long>(i);
      }
    }
  }
  return -1;
}

// local index -> key bytes into caller buffer; returns key length (may
// exceed cap, in which case nothing is written), or -1 if out of range.
long pmix_get_name(void* handle, long idx, char* buf, long cap) {
  if (!handle) return -1;
  const Store* s = static_cast<const Store*>(handle);
  if (idx < 0 || static_cast<uint64_t>(idx) >= s->header.num_keys) return -1;
  uint64_t start = s->offsets[idx], end = s->offsets[idx + 1];
  long len = static_cast<long>(end - start);
  if (len <= cap) std::memcpy(buf, s->blob + start, len);
  return len;
}

// Build a partition file from n keys given as a concatenated blob +
// (n + 1) offsets. Key i gets local index i. Returns 0 on success.
int pmix_build(const char* path, const char* blob, const uint64_t* offsets,
               long n) {
  if (n < 0) return 1;
  const uint64_t blob_size = offsets[n];
  const uint64_t cap = next_pow2(n > 0 ? static_cast<uint64_t>(n) * 2 : 1);

  Header header{kMagic, kVersion, static_cast<uint64_t>(n), cap, blob_size};

  uint8_t* table = new uint8_t[cap * kSlotSize]();
  const uint64_t mask = cap - 1;
  for (long i = 0; i < n; ++i) {
    const char* key = blob + offsets[i];
    long len = static_cast<long>(offsets[i + 1] - offsets[i]);
    uint64_t h = fnv1a(key, len);
    uint64_t slot = h & mask;
    while (true) {
      uint32_t idx1;
      uint64_t slot_hash;
      slot_read(table, slot, &idx1, &slot_hash);
      if (idx1 == 0) break;
      slot = (slot + 1) & mask;
    }
    uint8_t* p = table + slot * kSlotSize;
    uint32_t idx1 = static_cast<uint32_t>(i) + 1;
    std::memcpy(p, &idx1, 4);
    std::memcpy(p + 4, &h, 8);
  }

  FILE* f = std::fopen(path, "wb");
  if (!f) {
    delete[] table;
    return 2;
  }
  int err = 0;
  if (std::fwrite(&header, sizeof(Header), 1, f) != 1) err = 3;
  if (!err && cap && std::fwrite(table, kSlotSize, cap, f) != cap) err = 3;
  if (!err &&
      std::fwrite(offsets, sizeof(uint64_t), n + 1, f) !=
          static_cast<size_t>(n + 1))
    err = 3;
  if (!err && blob_size && std::fwrite(blob, 1, blob_size, f) != blob_size)
    err = 3;
  if (std::fclose(f) != 0 && !err) err = 4;
  delete[] table;
  return err;
}

}  // extern "C"
