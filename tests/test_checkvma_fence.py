"""The check_vma=False fence (VERDICT r4 #10).

``shard_map(check_vma=False)`` turns off the varying-manual-axes validation
JAX provides for free — on exactly the collectives where a silent sharding
bug would corrupt results. Every site that opts out MUST therefore carry a
compensating control: a sharded-vs-single-device equivalence test asserting
the shard_map computes what the unsharded oracle computes.

This meta-test makes that rule mechanical: every ``check_vma=False`` in the
package must be registered below TOGETHER with the name of its paired
equivalence test, and that test must actually exist in the named test
module. Adding a new ``check_vma=False`` without extending the registry —
or registering a test that does not exist — fails this file.
"""

import pathlib
import re

PKG = pathlib.Path(__file__).resolve().parents[1] / "photon_ml_tpu"
TESTS = pathlib.Path(__file__).resolve().parent

# file (relative to photon_ml_tpu/) -> list of
#   (occurrences, test_module, test_name) — the paired equivalence test
# asserting the shard_map's output equals the single-device oracle's.
REGISTRY = {
    "parallel/distributed.py": [
        # DistributedRandomEffectSolver.update
        (1, "test_parallel.py", "test_distributed_random_effect_matches_local"),
        # DistributedFactoredRandomEffectCoordinate._build
        (1, "test_parallel.py", "test_distributed_factored_matches_local"),
    ],
    "parallel/perhost_ingest.py": [
        # PerHostRandomEffectSolver.update
        (1, "test_perhost_ingest.py", "test_matches_unsharded_coordinate"),
        # PerHostBucketedRandomEffectSolver.update
        (1, "test_perhost_ingest.py", "test_bucketed_matches_monolithic"),
    ],
    "parallel/perhost_factored.py": [
        # PerHostFactoredRandomEffectCoordinate.update
        (1, "test_perhost_ingest.py",
         "test_factored_perhost_matches_single_device"),
    ],
}


def _sites():
    found = {}
    for f in sorted(PKG.rglob("*.py")):
        n = 0
        for line in f.read_text().splitlines():
            if line.lstrip().startswith("#"):
                continue  # rationale comments mention the flag; only count code
            n += len(re.findall(r"check_vma\s*=\s*False", line))
        if n:
            found[str(f.relative_to(PKG))] = n
    return found


def test_every_check_vma_false_site_is_registered():
    found = _sites()
    registered = {k: sum(c for c, _, _ in v) for k, v in REGISTRY.items()}
    assert found == registered, (
        "check_vma=False sites changed without updating the fence.\n"
        f"  in the package: {found}\n"
        f"  registered:     {registered}\n"
        "Every new site needs a paired sharded-vs-single-device equivalence "
        "test registered in tests/test_checkvma_fence.py."
    )


def test_every_registered_equivalence_test_exists():
    for rel, entries in REGISTRY.items():
        for _, module, test_name in entries:
            path = TESTS / module
            assert path.exists(), f"{rel}: test module {module} missing"
            text = path.read_text()
            assert re.search(rf"def {re.escape(test_name)}\b", text), (
                f"{rel}: paired equivalence test {module}::{test_name} "
                "does not exist — the fence names a test that cannot run"
            )
