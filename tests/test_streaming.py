"""Out-of-core (chunk-streamed) fixed-effect training (VERDICT r3 #5).

The host-loop LBFGS must reproduce the while_loop kernel's solution on the
same objective, and the chunked accumulation must be exact (additive
aggregator algebra) — together: training from disk-backed chunks equals
training in memory.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_minimize_
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMSource,
    lbfgs_minimize_streaming,
    make_streaming_value_and_grad,
    write_chunk_files,
)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(17)
    n, d = 3000, 12
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (1.0 / (1.0 + np.exp(-x @ w_true)) > rng.random(n)).astype(np.float32)
    wts = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    offs = rng.normal(scale=0.1, size=n).astype(np.float32)
    return x, y, offs, wts


def _kernel_result(problem, l2=0.3, l1=0.0, max_iter=60):
    x, y, offs, wts = problem
    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()
    batch = GLMBatch(
        DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
        jnp.asarray(wts),
    )
    vg = lambda w: obj.value_and_grad(w, batch, norm, l2)
    cfg = OptimizerConfig(max_iterations=max_iter, tolerance=1e-9)
    return lbfgs_minimize_(
        vg, jnp.zeros((x.shape[1],), jnp.float32), cfg, l1_weight=l1
    )


def _streaming_result(problem, chunk_rows, l2=0.3, l1=0.0, max_iter=60, source=None):
    x, y, offs, wts = problem
    if source is None:
        source = ChunkedGLMSource.from_arrays(
            x, y, chunk_rows, offsets=offs, weights=wts
        )
    obj = GLMObjective(losses.logistic)
    vg = make_streaming_value_and_grad(
        source, obj, NormalizationContext.identity(), l2_weight=l2
    )
    cfg = OptimizerConfig(max_iterations=max_iter, tolerance=1e-9)
    return lbfgs_minimize_streaming(
        vg, jnp.zeros((x.shape[1],), jnp.float32), cfg, l1_weight=l1
    )


class TestStreamingAggregation:
    def test_chunked_value_and_grad_is_exact(self, problem):
        """Σ over chunks == one pass (the aggregator algebra is additive)."""
        x, y, offs, wts = problem
        obj = GLMObjective(losses.logistic)
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        w = jnp.asarray(np.random.default_rng(0).normal(size=x.shape[1]), jnp.float32)
        f_full, g_full = obj.value_and_grad(w, batch, norm, 0.25)
        src = ChunkedGLMSource.from_arrays(x, y, 257, offsets=offs, weights=wts)
        vg = make_streaming_value_and_grad(src, obj, norm, l2_weight=0.25)
        f_s, g_s = vg(w)
        np.testing.assert_allclose(float(f_s), float(f_full), rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(g_s), np.asarray(g_full), rtol=2e-4, atol=2e-5
        )


class TestStreamingLBFGS:
    def test_matches_kernel_l2(self, problem):
        ker = _kernel_result(problem)
        st = _streaming_result(problem, chunk_rows=700)
        np.testing.assert_allclose(
            np.asarray(st.coefficients), np.asarray(ker.coefficients),
            rtol=1e-3, atol=1e-4,
        )
        # both declare a genuine convergence (not MaxIterations)
        from photon_ml_tpu.types import ConvergenceReason

        assert int(st.reason) in (
            int(ConvergenceReason.GRADIENT_CONVERGED),
            int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
        )

    def test_matches_kernel_owlqn(self, problem):
        """L1 (OWL-QN) path: same sparsity pattern and coefficients."""
        ker = _kernel_result(problem, l2=0.0, l1=2.0)
        st = _streaming_result(problem, chunk_rows=512, l2=0.0, l1=2.0)
        k = np.asarray(ker.coefficients)
        s = np.asarray(st.coefficients)
        np.testing.assert_array_equal(s == 0.0, k == 0.0)
        np.testing.assert_allclose(s, k, rtol=2e-3, atol=2e-4)

    def test_chunk_dir_source(self, problem, tmp_path):
        """Disk-backed chunks (mmap'd per-stream .npy files) train
        identically, and construction reads only headers."""
        x, y, offs, wts = problem
        write_chunk_files(str(tmp_path), x, y, 640, offsets=offs, weights=wts)
        src = ChunkedGLMSource.from_chunk_dir(str(tmp_path))
        assert src.num_rows == len(y) and src.dim == x.shape[1]
        st_disk = _streaming_result(problem, 0, source=src)
        st_mem = _streaming_result(problem, chunk_rows=640)
        np.testing.assert_allclose(
            np.asarray(st_disk.coefficients), np.asarray(st_mem.coefficients),
            rtol=1e-6,
        )


class TestStreamingFixedEffectCoordinate:
    def test_game_descent_with_streaming_fe(self, tmp_path):
        """Coordinate descent with an OUT-OF-CORE fixed effect (chunked
        batch on disk) must reproduce the in-memory two-coordinate descent:
        objectives and final scores."""
        from game_test_utils import make_glmix_data

        from photon_ml_tpu.algorithm import (
            CoordinateDescent,
            FixedEffectCoordinate,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.algorithm.streaming_fixed_effect import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.data.game import (
            RandomEffectDataConfig,
            build_fixed_effect_batch,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.optim.streaming import (
            ChunkedGLMSource,
            write_chunk_files,
        )
        from photon_ml_tpu.types import OptimizerType, TaskType

        rng = np.random.default_rng(23)
        data, _ = make_glmix_data(
            rng, num_users=15, rows_per_user_range=(10, 20), d_fixed=5, d_random=3
        )
        labels = jnp.asarray(data.response)
        loss_fn = lambda s: jnp.sum(losses.logistic.loss(s, labels))
        cfg = OptimizerConfig(max_iterations=25, tolerance=1e-9)
        problem = GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg,
            RegularizationContext.l2(0.1),
        )

        def re_coord():
            return RandomEffectCoordinate(
                build_random_effect_dataset(
                    data, RandomEffectDataConfig("userId", "per_user")
                ),
                TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg,
                RegularizationContext.l2(0.3),
            )

        batch = build_fixed_effect_batch(data, "global", dense=True)
        mem_cd = CoordinateDescent(
            {"fe": FixedEffectCoordinate(batch, problem), "re": re_coord()},
            loss_fn,
        )
        mem = mem_cd.run(num_iterations=2, num_rows=data.num_rows)

        # spill the FE batch to disk chunks and stream it
        x = np.asarray(batch.features.matrix)[: data.num_rows]
        write_chunk_files(
            str(tmp_path), x, data.response.astype(np.float32), 97,
            offsets=data.offset.astype(np.float32),
            weights=data.weight.astype(np.float32),
        )
        src = ChunkedGLMSource.from_chunk_dir(str(tmp_path))
        st_cd = CoordinateDescent(
            {"fe": StreamingFixedEffectCoordinate(src, problem),
             "re": re_coord()},
            loss_fn,
        )
        st = st_cd.run(num_iterations=2, num_rows=data.num_rows)

        np.testing.assert_allclose(
            np.asarray(st.objective_history),
            np.asarray(mem.objective_history), rtol=1e-4,
        )
        np.testing.assert_allclose(
            np.asarray(st.total_scores), np.asarray(mem.total_scores),
            rtol=5e-3, atol=5e-4,
        )

    def test_streaming_fe_rejects_tron(self):
        from photon_ml_tpu.algorithm.streaming_fixed_effect import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.optim.streaming import ChunkedGLMSource
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.types import OptimizerType, TaskType

        src = ChunkedGLMSource.from_arrays(
            np.zeros((8, 2), np.float32), np.zeros(8, np.float32), 4
        )
        with pytest.raises(ValueError, match="LBFGS/OWL-QN only"):
            StreamingFixedEffectCoordinate(
                src,
                GLMOptimizationProblem(
                    TaskType.LOGISTIC_REGRESSION, OptimizerType.TRON,
                    OptimizerConfig(max_iterations=5, tolerance=1e-5),
                    RegularizationContext.l2(0.1),
                ),
            )
