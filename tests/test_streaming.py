"""Out-of-core (chunk-streamed) fixed-effect training (VERDICT r3 #5).

The host-loop LBFGS must reproduce the while_loop kernel's solution on the
same objective, and the chunked accumulation must be exact (additive
aggregator algebra) — together: training from disk-backed chunks equals
training in memory.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_minimize_
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMSource,
    lbfgs_minimize_streaming,
    make_streaming_value_and_grad,
    write_chunk_files,
)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(17)
    n, d = 3000, 12
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (1.0 / (1.0 + np.exp(-x @ w_true)) > rng.random(n)).astype(np.float32)
    wts = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    offs = rng.normal(scale=0.1, size=n).astype(np.float32)
    return x, y, offs, wts


def _kernel_result(problem, l2=0.3, l1=0.0, max_iter=60):
    x, y, offs, wts = problem
    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()
    batch = GLMBatch(
        DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
        jnp.asarray(wts),
    )
    vg = lambda w: obj.value_and_grad(w, batch, norm, l2)
    cfg = OptimizerConfig(max_iterations=max_iter, tolerance=1e-9)
    return lbfgs_minimize_(
        vg, jnp.zeros((x.shape[1],), jnp.float32), cfg, l1_weight=l1
    )


def _streaming_result(problem, chunk_rows, l2=0.3, l1=0.0, max_iter=60, source=None):
    x, y, offs, wts = problem
    if source is None:
        source = ChunkedGLMSource.from_arrays(
            x, y, chunk_rows, offsets=offs, weights=wts
        )
    obj = GLMObjective(losses.logistic)
    vg = make_streaming_value_and_grad(
        source, obj, NormalizationContext.identity(), l2_weight=l2
    )
    cfg = OptimizerConfig(max_iterations=max_iter, tolerance=1e-9)
    return lbfgs_minimize_streaming(
        vg, jnp.zeros((x.shape[1],), jnp.float32), cfg, l1_weight=l1
    )


class TestStreamingAggregation:
    def test_chunked_value_and_grad_is_exact(self, problem):
        """Σ over chunks == one pass (the aggregator algebra is additive)."""
        x, y, offs, wts = problem
        obj = GLMObjective(losses.logistic)
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        w = jnp.asarray(np.random.default_rng(0).normal(size=x.shape[1]), jnp.float32)
        f_full, g_full = obj.value_and_grad(w, batch, norm, 0.25)
        src = ChunkedGLMSource.from_arrays(x, y, 257, offsets=offs, weights=wts)
        vg = make_streaming_value_and_grad(src, obj, norm, l2_weight=0.25)
        f_s, g_s = vg(w)
        np.testing.assert_allclose(float(f_s), float(f_full), rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(g_s), np.asarray(g_full), rtol=2e-4, atol=2e-5
        )


class TestStreamingLBFGS:
    def test_matches_kernel_l2(self, problem):
        ker = _kernel_result(problem)
        st = _streaming_result(problem, chunk_rows=700)
        np.testing.assert_allclose(
            np.asarray(st.coefficients), np.asarray(ker.coefficients),
            rtol=1e-3, atol=1e-4,
        )
        # both declare a genuine convergence (not MaxIterations)
        from photon_ml_tpu.types import ConvergenceReason

        assert int(st.reason) in (
            int(ConvergenceReason.GRADIENT_CONVERGED),
            int(ConvergenceReason.FUNCTION_VALUES_CONVERGED),
        )

    def test_matches_kernel_owlqn(self, problem):
        """L1 (OWL-QN) path: same sparsity pattern and coefficients."""
        ker = _kernel_result(problem, l2=0.0, l1=2.0)
        st = _streaming_result(problem, chunk_rows=512, l2=0.0, l1=2.0)
        k = np.asarray(ker.coefficients)
        s = np.asarray(st.coefficients)
        np.testing.assert_array_equal(s == 0.0, k == 0.0)
        np.testing.assert_allclose(s, k, rtol=2e-3, atol=2e-4)

    def test_chunk_dir_source(self, problem, tmp_path):
        """Disk-backed chunks (mmap'd per-stream .npy files) train
        identically, and construction reads only headers."""
        x, y, offs, wts = problem
        write_chunk_files(str(tmp_path), x, y, 640, offsets=offs, weights=wts)
        src = ChunkedGLMSource.from_chunk_dir(str(tmp_path))
        assert src.num_rows == len(y) and src.dim == x.shape[1]
        st_disk = _streaming_result(problem, 0, source=src)
        st_mem = _streaming_result(problem, chunk_rows=640)
        np.testing.assert_allclose(
            np.asarray(st_disk.coefficients), np.asarray(st_mem.coefficients),
            rtol=1e-6,
        )


class TestStreamingFixedEffectCoordinate:
    def test_game_descent_with_streaming_fe(self, tmp_path):
        """Coordinate descent with an OUT-OF-CORE fixed effect (chunked
        batch on disk) must reproduce the in-memory two-coordinate descent:
        objectives and final scores."""
        from game_test_utils import make_glmix_data

        from photon_ml_tpu.algorithm import (
            CoordinateDescent,
            FixedEffectCoordinate,
            RandomEffectCoordinate,
        )
        from photon_ml_tpu.algorithm.streaming_fixed_effect import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.data.game import (
            RandomEffectDataConfig,
            build_fixed_effect_batch,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.optim.streaming import (
            ChunkedGLMSource,
            write_chunk_files,
        )
        from photon_ml_tpu.types import OptimizerType, TaskType

        rng = np.random.default_rng(23)
        data, _ = make_glmix_data(
            rng, num_users=15, rows_per_user_range=(10, 20), d_fixed=5, d_random=3
        )
        labels = jnp.asarray(data.response)
        loss_fn = lambda s: jnp.sum(losses.logistic.loss(s, labels))
        cfg = OptimizerConfig(max_iterations=25, tolerance=1e-9)
        problem = GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg,
            RegularizationContext.l2(0.1),
        )

        def re_coord():
            return RandomEffectCoordinate(
                build_random_effect_dataset(
                    data, RandomEffectDataConfig("userId", "per_user")
                ),
                TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg,
                RegularizationContext.l2(0.3),
            )

        batch = build_fixed_effect_batch(data, "global", dense=True)
        mem_cd = CoordinateDescent(
            {"fe": FixedEffectCoordinate(batch, problem), "re": re_coord()},
            loss_fn,
        )
        mem = mem_cd.run(num_iterations=2, num_rows=data.num_rows)

        # spill the FE batch to disk chunks and stream it
        x = np.asarray(batch.features.matrix)[: data.num_rows]
        write_chunk_files(
            str(tmp_path), x, data.response.astype(np.float32), 97,
            offsets=data.offset.astype(np.float32),
            weights=data.weight.astype(np.float32),
        )
        src = ChunkedGLMSource.from_chunk_dir(str(tmp_path))
        st_cd = CoordinateDescent(
            {"fe": StreamingFixedEffectCoordinate(src, problem),
             "re": re_coord()},
            loss_fn,
        )
        st = st_cd.run(num_iterations=2, num_rows=data.num_rows)

        np.testing.assert_allclose(
            np.asarray(st.objective_history),
            np.asarray(mem.objective_history), rtol=1e-4,
        )
        np.testing.assert_allclose(
            np.asarray(st.total_scores), np.asarray(mem.total_scores),
            rtol=5e-3, atol=5e-4,
        )

    def test_streaming_fe_supports_tron(self, problem):
        """The streaming FE coordinate solves with TRON (r4 #5: the old
        LBFGS-only restriction is gone) and matches the kernel TRON fit."""
        from photon_ml_tpu.algorithm.streaming_fixed_effect import (
            StreamingFixedEffectCoordinate,
        )
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.optim.tron import tron_minimize_
        from photon_ml_tpu.types import OptimizerType, TaskType

        x, y, offs, wts = problem
        src = ChunkedGLMSource.from_arrays(
            x, y, 512, offsets=offs, weights=wts
        )
        cfg = OptimizerConfig(max_iterations=30, tolerance=1e-9)
        coord = StreamingFixedEffectCoordinate(
            src,
            GLMOptimizationProblem(
                TaskType.LOGISTIC_REGRESSION, OptimizerType.TRON, cfg,
                RegularizationContext.l2(0.3),
            ),
        )
        w_s, res_s = coord.update(
            jnp.zeros((x.shape[0],), jnp.float32), coord.initial_coefficients()
        )
        obj = GLMObjective(losses.logistic)
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        vg = lambda w: obj.value_and_grad(w, batch, norm, 0.3)
        hvp = lambda w, v: obj.hessian_vector(w, v, batch, norm, 0.3)
        res_k = tron_minimize_(
            vg, hvp, jnp.zeros((x.shape[1],), jnp.float32), cfg
        )
        np.testing.assert_allclose(
            np.asarray(w_s), np.asarray(res_k.coefficients),
            rtol=5e-4, atol=5e-5,
        )


class TestStreamingTron:
    def test_streamed_hvp_is_exact(self, problem):
        """Σ over chunks == one pass (the Hessian-vector algebra is
        additive over rows, HessianVectorAggregator.scala:90-116)."""
        from photon_ml_tpu.optim.streaming import make_streaming_hvp

        x, y, offs, wts = problem
        obj = GLMObjective(losses.logistic)
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        rng = np.random.default_rng(4)
        w = jnp.asarray(rng.normal(size=x.shape[1]).astype(np.float32) * 0.2)
        v = jnp.asarray(rng.normal(size=x.shape[1]).astype(np.float32))
        hv_mem = obj.hessian_vector(w, v, batch, norm, 0.3)
        src = ChunkedGLMSource.from_arrays(x, y, 700, offsets=offs, weights=wts)
        hv_stream = make_streaming_hvp(src, obj, norm, l2_weight=0.3)(w, v)
        np.testing.assert_allclose(
            np.asarray(hv_stream), np.asarray(hv_mem), rtol=1e-5, atol=1e-6
        )

    def test_streaming_tron_matches_kernel(self, problem):
        """Host-loop TRON over chunks == the while_loop kernel on the same
        objective: same solution, same convergence reason."""
        from photon_ml_tpu.optim.streaming import (
            make_streaming_hvp,
            tron_minimize_streaming,
        )
        from photon_ml_tpu.optim.tron import tron_minimize_

        x, y, offs, wts = problem
        obj = GLMObjective(losses.logistic)
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        cfg = OptimizerConfig(max_iterations=40, tolerance=1e-9)
        vg_mem = lambda w: obj.value_and_grad(w, batch, norm, 0.3)
        hvp_mem = lambda w, v: obj.hessian_vector(w, v, batch, norm, 0.3)
        res_k = tron_minimize_(
            vg_mem, hvp_mem, jnp.zeros((x.shape[1],), jnp.float32), cfg
        )
        src = ChunkedGLMSource.from_arrays(x, y, 512, offsets=offs, weights=wts)
        vg_s = make_streaming_value_and_grad(src, obj, norm, l2_weight=0.3)
        hvp_s = make_streaming_hvp(src, obj, norm, l2_weight=0.3)
        res_s = tron_minimize_streaming(
            vg_s, hvp_s, jnp.zeros((x.shape[1],), jnp.float32), cfg
        )
        np.testing.assert_allclose(
            np.asarray(res_s.coefficients), np.asarray(res_k.coefficients),
            rtol=5e-4, atol=5e-5,
        )
        assert int(res_s.reason) == int(res_k.reason)

    def test_streaming_tron_poisson_with_offsets(self):
        """The Poisson+offsets config through streaming TRON == kernel TRON
        (the parity configuration the r4 verdict names)."""
        from photon_ml_tpu.optim.streaming import (
            make_streaming_hvp,
            tron_minimize_streaming,
        )
        from photon_ml_tpu.optim.tron import tron_minimize_

        rng = np.random.default_rng(23)
        n, d = 2000, 8
        x = rng.normal(size=(n, d)).astype(np.float32) * 0.4
        w_true = rng.normal(size=d).astype(np.float32) * 0.3
        offs = rng.normal(scale=0.2, size=n).astype(np.float32)
        lam = np.exp(np.clip(x @ w_true + offs, -4, 4))
        y = rng.poisson(lam).astype(np.float32)
        wts = np.ones(n, np.float32)

        obj = GLMObjective(losses.poisson)
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        cfg = OptimizerConfig(max_iterations=40, tolerance=1e-9)
        vg_mem = lambda w: obj.value_and_grad(w, batch, norm, 0.5)
        hvp_mem = lambda w, v: obj.hessian_vector(w, v, batch, norm, 0.5)
        res_k = tron_minimize_(
            vg_mem, hvp_mem, jnp.zeros((d,), jnp.float32), cfg
        )
        src = ChunkedGLMSource.from_arrays(x, y, 300, offsets=offs, weights=wts)
        vg_s = make_streaming_value_and_grad(src, obj, norm, l2_weight=0.5)
        hvp_s = make_streaming_hvp(src, obj, norm, l2_weight=0.5)
        res_s = tron_minimize_streaming(
            vg_s, hvp_s, jnp.zeros((d,), jnp.float32), cfg
        )
        # chunked f32 sums differ from the one-pass sum in the last ulp and
        # the exp-loss trust-region trajectory amplifies that; the OBJECTIVE
        # at both solutions must still agree tightly
        np.testing.assert_allclose(
            np.asarray(res_s.coefficients), np.asarray(res_k.coefficients),
            rtol=5e-3, atol=1e-3,
        )
        f_at_s, _ = vg_mem(res_s.coefficients)
        np.testing.assert_allclose(
            float(f_at_s), float(res_k.value), rtol=1e-5
        )

    def test_streaming_tron_with_box_constraints(self, problem):
        """The clipped-step branch (recomputed gs/prered on the step
        actually taken): streaming TRON under ACTIVE bounds == kernel TRON
        under the same bounds."""
        from photon_ml_tpu.optim.streaming import (
            make_streaming_hvp,
            tron_minimize_streaming,
        )
        from photon_ml_tpu.optim.tron import tron_minimize_

        x, y, offs, wts = problem
        d = x.shape[1]
        obj = GLMObjective(losses.logistic)
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        # tight box so several coordinates end up AT a bound (clipping real)
        bounds = (jnp.full((d,), -0.05), jnp.full((d,), 0.05))
        cfg = OptimizerConfig(max_iterations=40, tolerance=1e-9)
        vg_mem = lambda w: obj.value_and_grad(w, batch, norm, 0.3)
        hvp_mem = lambda w, v: obj.hessian_vector(w, v, batch, norm, 0.3)
        res_k = tron_minimize_(
            vg_mem, hvp_mem, jnp.zeros((d,), jnp.float32), cfg, bounds=bounds
        )
        src = ChunkedGLMSource.from_arrays(x, y, 512, offsets=offs, weights=wts)
        vg_s = make_streaming_value_and_grad(src, obj, norm, l2_weight=0.3)
        hvp_s = make_streaming_hvp(src, obj, norm, l2_weight=0.3)
        res_s = tron_minimize_streaming(
            vg_s, hvp_s, jnp.zeros((d,), jnp.float32), cfg, bounds=bounds
        )
        assert bool(jnp.any(jnp.abs(res_k.coefficients) >= 0.05 - 1e-6))
        np.testing.assert_allclose(
            np.asarray(res_s.coefficients), np.asarray(res_k.coefficients),
            rtol=5e-4, atol=5e-5,
        )

    def test_glm_grid_streaming_tron(self, problem):
        """train_glm_grid_streaming accepts TRON end-to-end (the old
        reject is gone) and matches the in-memory grid's solutions."""
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.training import train_glm_grid, train_glm_grid_streaming
        from photon_ml_tpu.types import OptimizerType, TaskType

        x, y, offs, wts = problem
        cfg = OptimizerConfig(max_iterations=30, tolerance=1e-8)
        prob = GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION, OptimizerType.TRON, cfg,
            RegularizationContext.l2(1.0),
        )
        norm = NormalizationContext.identity()
        batch = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y), jnp.asarray(offs),
            jnp.asarray(wts),
        )
        mem = train_glm_grid(prob, batch, norm, [0.1, 1.0])
        src = ChunkedGLMSource.from_arrays(x, y, 512, offsets=offs, weights=wts)
        st = train_glm_grid_streaming(prob, src, norm, [0.1, 1.0])
        for wm, ws in zip(mem.models, st.models):
            np.testing.assert_allclose(
                np.asarray(ws.coefficients.means),
                np.asarray(wm.coefficients.means),
                rtol=1e-3, atol=1e-4,
            )
