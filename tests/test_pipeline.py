"""Async pipelined data path (io/pipeline.py) + content-addressed tensor
cache (io/tensor_cache.py): prefetcher ordering & exception propagation
(including under injected ``io.cache_read`` faults), cache hit/miss/
invalidation, and the tier-1 gate that streaming-RE results are
BIT-identical with pipelining on vs off."""

import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from game_test_utils import make_glmix_data

from photon_ml_tpu.algorithm import (
    StreamingRandomEffectCoordinate,
    write_re_entity_blocks,
)
from photon_ml_tpu.data.game import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.io.pipeline import Prefetcher, device_pipelined, prefetched
from photon_ml_tpu.io.tensor_cache import TensorCache, content_key
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.resilience import faults
from photon_ml_tpu.types import TaskType

pytestmark = pytest.mark.pipeline


# ---------------------------------------------------------------------------
# Prefetcher mechanics
# ---------------------------------------------------------------------------


class TestPrefetcher:
    def test_preserves_order(self):
        assert list(prefetched(lambda: iter(range(100)), depth=3)) == list(range(100))

    def test_depth_zero_is_synchronous_passthrough(self):
        produced = []

        def gen():
            for i in range(5):
                produced.append(i)
                yield i

        it = prefetched(gen, depth=0)
        assert produced == []  # nothing ran yet: no background thread
        assert next(it) == 0
        assert produced == [0]  # strictly demand-driven

    def test_runs_producer_on_background_thread(self):
        main = threading.get_ident()
        seen = []

        def gen():
            seen.append(threading.get_ident())
            yield 1

        assert list(prefetched(gen, depth=2)) == [1]
        assert seen and seen[0] != main

    def test_bounded_readahead(self):
        """The producer never runs more than depth items ahead."""
        produced = []
        depth = 2

        def gen():
            for i in range(50):
                produced.append(i)
                yield i

        it = iter(Prefetcher(gen, depth=depth))
        next(it)  # start the worker, consume item 0
        deadline = time.monotonic() + 5.0
        while len(produced) < 1 + depth and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)  # would overrun here if the bound were broken
        # worker can be at most depth buffered + 1 in-flight ahead
        assert len(produced) <= 1 + depth + 1
        it.close()

    def test_exception_propagates_in_order(self):
        """Items before the failure are delivered; the error surfaces at the
        failing item's position; iteration ends after it."""

        def gen():
            yield "a"
            yield "b"
            raise ValueError("boom at item 2")

        it = prefetched(gen, depth=4)
        assert next(it) == "a"
        assert next(it) == "b"
        with pytest.raises(ValueError, match="boom at item 2"):
            next(it)
        with pytest.raises(StopIteration):
            next(it)

    def test_injected_cache_read_fault_propagates(self, tmp_path):
        """A fault injected at io.cache_read inside the producer crosses the
        thread boundary: blocks before the faulting read arrive in order,
        then the InjectedIOError surfaces to the consumer."""
        plan = faults.FaultPlan(
            [faults.FaultSpec(site="io.cache_read", at=3, kind="io")]
        )

        def loads():
            for i in range(6):
                faults.inject("io.cache_read", block=i)
                yield i

        got = []
        with faults.fault_scope(plan):
            with pytest.raises(faults.InjectedIOError):
                for item in prefetched(loads, depth=2):
                    got.append(item)
        assert got == [0, 1]  # everything before the fault, in order
        assert plan.fire_count("io.cache_read") == 1

    def test_single_pass(self):
        p = Prefetcher(lambda: iter(range(3)), depth=2)
        assert list(p) == [0, 1, 2]
        with pytest.raises(RuntimeError, match="single-pass"):
            iter(p)


class TestDevicePipelined:
    def test_order_and_values(self):
        out = list(device_pipelined(range(10), lambda v: v * 2, depth=1))
        assert out == [v * 2 for v in range(10)]

    def test_places_ahead(self):
        placed = []
        out = []
        for v in device_pipelined(range(5), lambda v: placed.append(v) or v, depth=1):
            # by the time item v is yielded, item v+1 was already placed
            assert len(placed) >= min(v + 2, 5)
            out.append(v)
        assert out == list(range(5))

    def test_depth_zero_lazy(self):
        placed = []
        it = device_pipelined(range(5), lambda v: placed.append(v) or v, depth=0)
        assert placed == []
        assert next(it) == 0
        assert placed == [0]


# ---------------------------------------------------------------------------
# tensor cache
# ---------------------------------------------------------------------------


@pytest.fixture
def cache(tmp_path):
    return TensorCache(str(tmp_path / "tcache"))


class TestTensorCache:
    def test_miss_then_hit_roundtrip(self, cache):
        key = content_key([], {"a": 1})
        assert cache.get(key) is None
        arrays = {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "y": np.asarray([1, 2, 3])}
        cache.put(key, arrays, meta={"n": 3})
        hit = cache.get(key)
        assert hit is not None
        assert hit.meta == {"n": 3}
        np.testing.assert_array_equal(np.asarray(hit.arrays["x"]), arrays["x"])
        np.testing.assert_array_equal(np.asarray(hit.arrays["y"]), arrays["y"])

    def test_config_change_is_a_miss(self, cache, tmp_path):
        src = tmp_path / "part-0.bin"
        src.write_bytes(b"data")
        k1 = cache.key_for([str(src)], {"cap": 10})
        k2 = cache.key_for([str(src)], {"cap": 11})
        assert k1 != k2
        cache.put(k1, {"x": np.zeros(2)})
        assert cache.get(k2) is None  # changed config never hits stale tensors

    def test_source_change_is_a_miss(self, cache, tmp_path):
        src = tmp_path / "part-0.bin"
        src.write_bytes(b"data")
        k1 = cache.key_for([str(src)], {"cap": 10})
        src.write_bytes(b"data2")  # size change (mtime alone also suffices)
        k2 = cache.key_for([str(src)], {"cap": 10})
        assert k1 != k2

    def test_broken_entry_degrades_to_miss(self, cache):
        key = content_key([], {"b": 1})
        cache.put(key, {"x": np.zeros(4)})
        meta = os.path.join(cache.entry_dir(key), "meta.json")
        with open(meta, "w") as f:
            f.write("{not json")
        assert cache.get(key) is None
        assert not os.path.exists(cache.entry_dir(key))  # debris swept

    def test_read_fault_retries_then_degrades_to_miss(self, cache):
        """Transient injected io.cache_read faults are retried away; a
        persistent fault degrades to a miss (rebuild), never an error."""
        key = content_key([], {"c": 1})
        cache.put(key, {"x": np.ones(3)})
        # one transient fault -> retry succeeds -> still a hit
        with faults.fault_scope(faults.FaultPlan(
            [faults.FaultSpec(site="io.cache_read", at=1, kind="io")]
        )):
            assert cache.get(key) is not None
        # every attempt faults -> miss
        cache.put(key, {"x": np.ones(3)})
        with faults.fault_scope(faults.FaultPlan(
            [faults.FaultSpec(site="io.cache_read", rate=1.0, kind="io")]
        )):
            assert cache.get(key) is None

    def test_write_fault_retries_then_raises(self, cache):
        from photon_ml_tpu.resilience import RetryError

        key = content_key([], {"d": 1})
        with faults.fault_scope(faults.FaultPlan(
            [faults.FaultSpec(site="io.cache_write", at=1, kind="io")]
        )):
            cache.put(key, {"x": np.zeros(2)})  # one transient fault: retried
        assert cache.get(key) is not None
        key2 = content_key([], {"d": 2})
        with faults.fault_scope(faults.FaultPlan(
            [faults.FaultSpec(site="io.cache_write", rate=1.0, kind="io")]
        )):
            with pytest.raises(RetryError):
                cache.put(key2, {"x": np.zeros(2)})
        assert cache.get(key2) is None  # nothing half-written became live

    def test_dir_entries(self, cache):
        key = content_key([], {"e": 1})
        assert cache.get_dir(key) is None

        def build(tmp):
            with open(os.path.join(tmp, "blob.txt"), "w") as f:
                f.write("payload")

        entry = cache.build_dir(key, build)
        assert cache.get_dir(key) == entry
        with open(os.path.join(entry, "blob.txt")) as f:
            assert f.read() == "payload"


# ---------------------------------------------------------------------------
# wired paths: streaming RE + RE dataset builds
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def glmix():
    rng = np.random.default_rng(83)
    data, _ = make_glmix_data(
        rng, num_users=48, rows_per_user_range=(4, 20), d_fixed=4, d_random=3
    )
    return data


class TestPipelinedStreamingRE:
    def _solve(self, manifest, tmp_path, depth, tag):
        coord = StreamingRandomEffectCoordinate(
            manifest, TaskType.LOGISTIC_REGRESSION,
            optimizer_config=OptimizerConfig(max_iterations=12, tolerance=1e-8),
            regularization=RegularizationContext.l2(0.2),
            state_root=str(tmp_path / f"state-{tag}"),
            prefetch_depth=depth,
        )
        n = manifest.num_rows
        resid = jnp.asarray(np.linspace(-0.5, 0.5, n, dtype=np.float32))
        state, _ = coord.update(resid, coord.initial_coefficients())
        scores = np.asarray(coord.score(state))
        coefs = [state.block(i) for i in range(len(manifest.blocks))]
        return coefs, scores

    def test_pipelined_bit_identical_to_synchronous(self, glmix, tmp_path):
        """THE tier-1 gate: pipelining moves I/O off the solve path but must
        not change a single bit of the result."""
        manifest = write_re_entity_blocks(
            glmix, RandomEffectDataConfig("userId", "per_user"),
            str(tmp_path / "blocks"), block_entities=12,
        )
        coefs_sync, scores_sync = self._solve(manifest, tmp_path, 0, "sync")
        coefs_pipe, scores_pipe = self._solve(manifest, tmp_path, 3, "pipe")
        assert len(coefs_sync) == len(coefs_pipe) == len(manifest.blocks)
        for a, b in zip(coefs_sync, coefs_pipe):
            np.testing.assert_array_equal(a, b)  # bit-identical, not allclose
        np.testing.assert_array_equal(scores_sync, scores_pipe)

    def test_block_cache_warm_run_identical(self, glmix, tmp_path):
        """Cold build vs warm cache hit: the warm manifest serves the SAME
        committed blocks (no rebuild) and solves to identical coefficients."""
        cache = TensorCache(str(tmp_path / "cache"))
        key = cache.key_for([], {"kind": "test_blocks", "be": 12})
        cold = write_re_entity_blocks(
            glmix, RandomEffectDataConfig("userId", "per_user"),
            str(tmp_path / "ignored"), block_entities=12,
            tensor_cache=cache, cache_key=key,
        )
        assert not os.path.exists(str(tmp_path / "ignored"))  # built in-cache
        warm = write_re_entity_blocks(
            glmix, RandomEffectDataConfig("userId", "per_user"),
            str(tmp_path / "ignored2"), block_entities=12,
            tensor_cache=cache, cache_key=key,
        )
        assert warm.dir == cold.dir  # the committed entry, byte for byte
        c1, s1 = self._solve(cold, tmp_path, 2, "cold")
        c2, s2 = self._solve(warm, tmp_path, 2, "warm")
        for a, b in zip(c1, c2):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(s1, s2)
        # a default-constructed coordinate over a CACHE-RESIDENT manifest
        # must redirect its spill out of the shared immutable entry
        default_coord = StreamingRandomEffectCoordinate(
            warm, TaskType.LOGISTIC_REGRESSION
        )
        assert not default_coord.state_root.startswith(warm.dir)


class TestCachedREDatasetBuild:
    def test_hit_skips_build_and_matches(self, glmix, tmp_path):
        cache = TensorCache(str(tmp_path / "cache"))
        cfg = RandomEffectDataConfig("userId", "per_user")
        key = cache.key_for([], {"kind": "re", "cfg": "v1"})
        ds_cold = build_random_effect_dataset(
            glmix, cfg, tensor_cache=cache, cache_key=key
        )
        assert cache.get(key) is not None
        # poison the in-memory source: a true hit never touches GameData
        import dataclasses as _dc

        empty = _dc.replace(glmix, response=glmix.response[:0])
        ds_warm = build_random_effect_dataset(
            empty, cfg, tensor_cache=cache, cache_key=key
        )
        for f in ("row_index", "x", "labels", "base_offsets", "weights",
                  "entity_pos", "feat_idx", "feat_val", "local_to_global"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ds_cold, f)), np.asarray(getattr(ds_warm, f))
            )
        assert ds_warm.num_entities == ds_cold.num_entities
        assert ds_warm.global_dim == ds_cold.global_dim

    def test_config_change_rebuilds(self, glmix, tmp_path):
        cache = TensorCache(str(tmp_path / "cache"))
        k1 = cache.key_for([], {"kind": "re", "cap": None})
        k2 = cache.key_for([], {"kind": "re", "cap": 2})
        build_random_effect_dataset(
            glmix, RandomEffectDataConfig("userId", "per_user"),
            tensor_cache=cache, cache_key=k1,
        )
        ds_capped = build_random_effect_dataset(
            glmix,
            RandomEffectDataConfig("userId", "per_user", active_upper_bound=2),
            tensor_cache=cache, cache_key=k2,
        )
        # the capped build must NOT have been served from k1's tensors
        assert ds_capped.x.shape[1] == 2


class TestGameDataRoundtrip:
    def test_to_from_arrays(self, glmix):
        from photon_ml_tpu.data.game import (
            game_data_from_arrays,
            game_data_to_arrays,
        )

        arrays, meta = game_data_to_arrays(glmix)
        back = game_data_from_arrays(arrays, meta)
        np.testing.assert_array_equal(back.response, glmix.response)
        np.testing.assert_array_equal(back.offset, glmix.offset)
        np.testing.assert_array_equal(back.weight, glmix.weight)
        assert set(back.ids) == set(glmix.ids)
        for k in glmix.ids:
            np.testing.assert_array_equal(back.ids[k], glmix.ids[k])
            assert back.id_vocabs[k] == list(glmix.id_vocabs[k])
        for k, f in glmix.shards.items():
            np.testing.assert_array_equal(back.shards[k].indptr, f.indptr)
            np.testing.assert_array_equal(back.shards[k].indices, f.indices)
            np.testing.assert_array_equal(back.shards[k].values, f.values)
            assert back.shards[k].dim == f.dim


class TestDriverTensorCache:
    """--tensor-cache end-to-end: the warm run must not touch the Avro
    decoder at all and must train to bit-identical coefficients."""

    @pytest.fixture(scope="class")
    def train_dir(self, tmp_path_factory):
        from photon_ml_tpu.io import avro as avro_io
        from test_game_drivers import GAME_EXAMPLE_SCHEMA

        rng = np.random.default_rng(20260803)
        gd, truth = make_glmix_data(
            rng, num_users=10, rows_per_user_range=(8, 16), d_fixed=4, d_random=3
        )

        def records():
            for r in range(gd.num_rows):
                yield {
                    "uid": str(r),
                    "label": float(gd.response[r]),
                    "fixedFeatures": [
                        {"name": f"f{j}", "term": "", "value": float(v)}
                        for j, v in enumerate(truth["x_fixed"][r]) if v != 0.0
                    ],
                    "userFeatures": [
                        {"name": f"u{j}", "term": "", "value": float(v)}
                        for j, v in enumerate(truth["x_random"][r]) if v != 0.0
                    ],
                    "metadataMap": {
                        "userId": gd.id_vocabs["userId"][gd.ids["userId"][r]]
                    },
                    "weight": None,
                    "offset": None,
                }

        base = tmp_path_factory.mktemp("tcache-driver")
        d = base / "train"
        d.mkdir()
        avro_io.write_container(
            str(d / "part-0.avro"), records(), GAME_EXAMPLE_SCHEMA
        )
        return str(d)

    def test_warm_run_skips_avro_decode_bit_identical(
        self, train_dir, tmp_path, monkeypatch
    ):
        from photon_ml_tpu.cli import game_training_driver
        from photon_ml_tpu.io import avro_data
        from test_game_drivers import COMMON_FLAGS

        cache_dir = str(tmp_path / "tcache")

        def run(out):
            drv = game_training_driver.main(
                ["--train-input-dirs", train_dir,
                 "--output-dir", str(tmp_path / out),
                 "--num-iterations", "2",
                 "--tensor-cache", cache_dir]
                + COMMON_FLAGS
            )
            return drv.results[drv.best_index][1].coefficients

        cold = run("cold")

        # the warm run may scan features (index maps are rebuilt) but must
        # NEVER decode GAME data again — a call is a cache-wiring bug
        real = avro_data.read_game_data

        def boom(*a, **k):
            raise AssertionError("warm run called read_game_data (cache miss)")

        monkeypatch.setattr(avro_data, "read_game_data", boom)
        try:
            warm = run("warm")
        finally:
            monkeypatch.setattr(avro_data, "read_game_data", real)

        assert set(cold) == set(warm)
        for name in cold:
            np.testing.assert_array_equal(
                np.asarray(cold[name]), np.asarray(warm[name])
            )


class TestLintCoverage:
    def test_new_modules_pass_broad_except_lint(self):
        """io/pipeline.py + io/tensor_cache.py under the tools/lint_excepts
        gate explicitly (tier-1 already walks the whole package; this pins
        the NEW modules by name so a future path filter cannot drop them)."""
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(repo, "tools"))
        try:
            import lint_excepts
        finally:
            sys.path.pop(0)
        for mod in ("pipeline.py", "tensor_cache.py"):
            path = os.path.join(repo, "photon_ml_tpu", "io", mod)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            assert list(lint_excepts.check_source(path, src)) == []
