"""Evaluation.evaluate metric-map parity, model selection, bootstrap."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import bootstrap as bootstrap_mod
from photon_ml_tpu import model_selection
from photon_ml_tpu.evaluation import metrics as M
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.types import OptimizerType, TaskType


def _logistic_fixture(rng, n=400, d=5):
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-x @ w))
    y = (p > rng.random(n)).astype(np.float32)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    model = GeneralizedLinearModel(Coefficients(jnp.asarray(w)), TaskType.LOGISTIC_REGRESSION)
    return batch, model, x, w, y


def test_logistic_metric_map_keys_and_sanity(rng):
    batch, model, x, w, y = _logistic_fixture(rng)
    m = M.evaluate(model, batch)
    for key in (
        M.AREA_UNDER_PRECISION_RECALL,
        M.AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS,
        M.PEAK_F1_SCORE,
        M.DATA_LOG_LIKELIHOOD,
        M.AIKAKE_INFORMATION_CRITERION,
    ):
        assert key in m, key
    assert 0.5 < m[M.AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] <= 1.0
    assert 0.0 < m[M.PEAK_F1_SCORE] <= 1.0
    assert m[M.DATA_LOG_LIKELIHOOD] < 0.0
    # true-model LL should beat a null model's LL
    null = GeneralizedLinearModel(
        Coefficients(jnp.zeros_like(model.coefficients.means)), TaskType.LOGISTIC_REGRESSION
    )
    m0 = M.evaluate(null, batch)
    assert m[M.DATA_LOG_LIKELIHOOD] > m0[M.DATA_LOG_LIKELIHOOD]


def test_aupr_peak_f1_vs_sklearn_style_reference(rng):
    # hand-computed tiny case: scores separate perfectly
    scores = jnp.asarray([0.9, 0.8, 0.2, 0.1])
    labels = jnp.asarray([1.0, 1.0, 0.0, 0.0])
    assert float(M.area_under_pr(scores, labels)) == pytest.approx(1.0)
    assert float(M.peak_f1(scores, labels)) == pytest.approx(1.0)
    # worst ordering: all negatives first
    scores2 = jnp.asarray([0.9, 0.8, 0.2, 0.1])
    labels2 = jnp.asarray([0.0, 0.0, 1.0, 1.0])
    assert float(M.peak_f1(scores2, labels2)) == pytest.approx(2 / 3, abs=1e-6)


def test_linear_regression_metric_map(rng):
    n, d = 200, 4
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    y = (x @ w + 0.1 * rng.normal(size=n)).astype(np.float32)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    model = GeneralizedLinearModel(Coefficients(jnp.asarray(w)), TaskType.LINEAR_REGRESSION)
    m = M.evaluate(model, batch)
    assert set(m) == {M.MEAN_ABSOLUTE_ERROR, M.MEAN_SQUARE_ERROR, M.ROOT_MEAN_SQUARE_ERROR}
    assert m[M.ROOT_MEAN_SQUARE_ERROR] == pytest.approx(np.sqrt(m[M.MEAN_SQUARE_ERROR]))
    assert m[M.ROOT_MEAN_SQUARE_ERROR] < 0.2


def test_poisson_log_likelihood_formula(rng):
    margins = jnp.asarray([0.1, -0.2, 0.5])
    labels = jnp.asarray([1.0, 0.0, 3.0])
    got = float(M.poisson_log_likelihood(margins, labels))
    import math

    expect = np.mean(
        [
            1.0 * 0.1 - math.exp(0.1) - math.lgamma(2.0),
            0.0 * -0.2 - math.exp(-0.2) - math.lgamma(1.0),
            3.0 * 0.5 - math.exp(0.5) - math.lgamma(4.0),
        ]
    )
    assert got == pytest.approx(expect, rel=1e-6)


def test_select_best_model_logistic(rng):
    batch, model, x, w, y = _logistic_fixture(rng)
    good = model
    bad = GeneralizedLinearModel(
        Coefficients(-model.coefficients.means), TaskType.LOGISTIC_REGRESSION
    )
    lam, best, all_metrics = model_selection.select_best_model(
        [(0.1, bad), (1.0, good)], batch
    )
    assert lam == 1.0
    assert best is good
    assert set(all_metrics) == {0.1, 1.0}


def test_bootstrap_training(rng):
    n, d = 300, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = np.array([1.5, -2.0, 0.0], np.float32)
    p = 1.0 / (1.0 + np.exp(-x @ w_true))
    y = (p > rng.random(n)).astype(np.float32)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    problem = GLMOptimizationProblem(
        TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=30, tolerance=1e-8),
        RegularizationContext.l2(1.0),
    )
    res = bootstrap_mod.bootstrap_train(
        problem,
        batch,
        NormalizationContext.identity(),
        num_samples=8,
        seed=3,
        metrics_fn=lambda m: M.evaluate(m, batch),
    )
    assert len(res.models) == 8
    assert len(res.coefficient_summaries) == d
    # strong coefficients' CIs exclude zero; the null one includes it
    assert not res.coefficient_summaries[0].contains_zero()
    assert not res.coefficient_summaries[1].contains_zero()
    assert res.coefficient_summaries[2].contains_zero()
    auc = res.metric_summaries[M.AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS]
    assert auc.min > 0.6
    assert auc.min <= auc.median <= auc.max


def test_bootstrap_weights_shape_and_total(rng):
    import jax

    w = bootstrap_mod.bootstrap_weights(jax.random.PRNGKey(0), 4, 50)
    assert w.shape == (4, 50)
    np.testing.assert_allclose(np.asarray(w.sum(axis=1)), 50.0)


def test_metrics_padding_invariance(rng):
    """weight-0 padding rows must not change any metric."""
    batch, model, x, w, y = _logistic_fixture(rng, n=100)
    m1 = M.evaluate(model, batch)
    xp = np.concatenate([x, np.zeros((28, x.shape[1]), np.float32)])
    yp = np.concatenate([y, np.zeros(28, np.float32)])
    wp = np.concatenate([np.ones(100, np.float32), np.zeros(28, np.float32)])
    padded = GLMBatch(
        DenseFeatures(jnp.asarray(xp)), jnp.asarray(yp),
        jnp.zeros(128, jnp.float32), jnp.asarray(wp),
    )
    m2 = M.evaluate(model, padded)
    for k in m1:
        assert m1[k] == pytest.approx(m2[k], rel=1e-5), k


def test_confidently_wrong_is_penalized():
    scores = jnp.asarray([1.0 - 1e-12])  # p ~ 1 but label 0
    labels = jnp.asarray([0.0])
    ll = float(M.logistic_log_likelihood(scores, labels))
    assert ll < -15.0  # log(EPSILON), not +log(2)
