"""Synthetic GAME data generators — the test fixture library.

(Reference analogue: photon-test SparkTestUtils generators +
integTest GameTestUtils.scala:36-247 factories.)
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.game import GameData, HostFeatures


def dense_to_csr(x: np.ndarray) -> HostFeatures:
    n, d = x.shape
    mask = x != 0
    nnz_per_row = mask.sum(1)
    indptr = np.concatenate([[0], np.cumsum(nnz_per_row)]).astype(np.int64)
    indices = np.nonzero(mask)[1].astype(np.int32)
    values = x[mask].astype(np.float32)
    return HostFeatures(indptr, indices, values, d)


def make_glmix_data(
    rng: np.random.Generator,
    num_users: int = 20,
    rows_per_user_range: Tuple[int, int] = (5, 40),
    d_fixed: int = 8,
    d_random: int = 4,
    noise: float = 0.0,
) -> Tuple[GameData, Dict[str, np.ndarray]]:
    """Logistic GLMix: y ~ Bernoulli(sigmoid(x_f.w_fixed + x_r.w_user)).

    Returns (GameData with shards 'global' and 'per_user', truth dict).
    """
    rows_per_user = rng.integers(*rows_per_user_range, size=num_users)
    n = int(rows_per_user.sum())
    user_of_row = np.repeat(np.arange(num_users, dtype=np.int32), rows_per_user)
    # shuffle rows so entity grouping is non-trivial
    perm = rng.permutation(n)
    user_of_row = user_of_row[perm]

    x_fixed = rng.normal(size=(n, d_fixed)).astype(np.float32)
    x_random = rng.normal(size=(n, d_random)).astype(np.float32)
    w_fixed = (rng.normal(size=d_fixed) * 1.0).astype(np.float32)
    w_users = (rng.normal(size=(num_users, d_random)) * 1.5).astype(np.float32)

    margin = x_fixed @ w_fixed + np.sum(x_random * w_users[user_of_row], axis=1)
    if noise:
        margin = margin + rng.normal(size=n) * noise
    y = (1.0 / (1.0 + np.exp(-margin)) > rng.random(n)).astype(np.float32)

    data = GameData(
        response=y,
        offset=np.zeros(n, np.float32),
        weight=np.ones(n, np.float32),
        ids={"userId": user_of_row},
        id_vocabs={"userId": [f"u{i}" for i in range(num_users)]},
        shards={"global": dense_to_csr(x_fixed), "per_user": dense_to_csr(x_random)},
    )
    truth = {
        "w_fixed": w_fixed,
        "w_users": w_users,
        "x_fixed": x_fixed,
        "x_random": x_random,
        "user_of_row": user_of_row,
        "margin": margin,
    }
    return data, truth
