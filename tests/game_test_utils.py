"""Synthetic GAME data generators — the test fixture library.

(Reference analogue: photon-test SparkTestUtils generators +
integTest GameTestUtils.scala:36-247 factories.)
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.game import GameData, HostFeatures


def dense_to_csr(x: np.ndarray) -> HostFeatures:
    n, d = x.shape
    mask = x != 0
    nnz_per_row = mask.sum(1)
    indptr = np.concatenate([[0], np.cumsum(nnz_per_row)]).astype(np.int64)
    indices = np.nonzero(mask)[1].astype(np.int32)
    values = x[mask].astype(np.float32)
    return HostFeatures(indptr, indices, values, d)


def make_glmix_data(
    rng: np.random.Generator,
    num_users: int = 20,
    rows_per_user_range: Tuple[int, int] = (5, 40),
    d_fixed: int = 8,
    d_random: int = 4,
    noise: float = 0.0,
) -> Tuple[GameData, Dict[str, np.ndarray]]:
    """Logistic GLMix: y ~ Bernoulli(sigmoid(x_f.w_fixed + x_r.w_user)).

    Returns (GameData with shards 'global' and 'per_user', truth dict).
    """
    rows_per_user = rng.integers(*rows_per_user_range, size=num_users)
    n = int(rows_per_user.sum())
    user_of_row = np.repeat(np.arange(num_users, dtype=np.int32), rows_per_user)
    # shuffle rows so entity grouping is non-trivial
    perm = rng.permutation(n)
    user_of_row = user_of_row[perm]

    x_fixed = rng.normal(size=(n, d_fixed)).astype(np.float32)
    x_random = rng.normal(size=(n, d_random)).astype(np.float32)
    w_fixed = (rng.normal(size=d_fixed) * 1.0).astype(np.float32)
    w_users = (rng.normal(size=(num_users, d_random)) * 1.5).astype(np.float32)

    margin = x_fixed @ w_fixed + np.sum(x_random * w_users[user_of_row], axis=1)
    if noise:
        margin = margin + rng.normal(size=n) * noise
    y = (1.0 / (1.0 + np.exp(-margin)) > rng.random(n)).astype(np.float32)

    data = GameData(
        response=y,
        offset=np.zeros(n, np.float32),
        weight=np.ones(n, np.float32),
        ids={"userId": user_of_row},
        id_vocabs={"userId": [f"u{i}" for i in range(num_users)]},
        shards={"global": dense_to_csr(x_fixed), "per_user": dense_to_csr(x_random)},
    )
    truth = {
        "w_fixed": w_fixed,
        "w_users": w_users,
        "x_fixed": x_fixed,
        "x_random": x_random,
        "user_of_row": user_of_row,
        "margin": margin,
    }
    return data, truth


def make_full_game_data(
    rng: np.random.Generator,
    num_users: int = 50,
    num_items: int = 30,
    num_artists: int = 10,
    rows_per_user_range: Tuple[int, int] = (5, 20),
    d_fixed: int = 8,
    d_user: int = 4,
    d_item: int = 4,
    d_artist: int = 6,
    noise: float = 0.0,
) -> Tuple[GameData, Dict[str, np.ndarray]]:
    """Full-GAME logistic data (BASELINE config-5 shape): fixed effect +
    per-user RE + per-item RE + a per-artist section for a factored/MF
    coordinate, with each item owned by one artist (the yahoo-music
    song->artist structure the reference's DriverTest exercises).
    """
    rows_per_user = rng.integers(*rows_per_user_range, size=num_users)
    n = int(rows_per_user.sum())
    user_of_row = np.repeat(np.arange(num_users, dtype=np.int32), rows_per_user)
    perm = rng.permutation(n)
    user_of_row = user_of_row[perm]
    item_of_row = rng.integers(0, num_items, size=n).astype(np.int32)
    artist_of_item = rng.integers(0, num_artists, size=num_items).astype(np.int32)
    artist_of_row = artist_of_item[item_of_row]

    x_fixed = rng.normal(size=(n, d_fixed)).astype(np.float32)
    x_user = rng.normal(size=(n, d_user)).astype(np.float32)
    x_item = rng.normal(size=(n, d_item)).astype(np.float32)
    x_artist = rng.normal(size=(n, d_artist)).astype(np.float32)
    w_fixed = rng.normal(size=d_fixed).astype(np.float32)
    w_users = (rng.normal(size=(num_users, d_user)) * 1.2).astype(np.float32)
    w_items = (rng.normal(size=(num_items, d_item)) * 1.2).astype(np.float32)
    # low-rank per-artist structure so the factored coordinate has signal
    rank = 2
    w_artists = (
        rng.normal(size=(num_artists, rank)) @ rng.normal(size=(rank, d_artist))
    ).astype(np.float32)

    margin = (
        x_fixed @ w_fixed
        + np.sum(x_user * w_users[user_of_row], axis=1)
        + np.sum(x_item * w_items[item_of_row], axis=1)
        + np.sum(x_artist * w_artists[artist_of_row], axis=1)
    )
    if noise:
        margin = margin + rng.normal(size=n) * noise
    y = (1.0 / (1.0 + np.exp(-margin)) > rng.random(n)).astype(np.float32)

    data = GameData(
        response=y,
        offset=np.zeros(n, np.float32),
        weight=np.ones(n, np.float32),
        ids={
            "userId": user_of_row,
            "itemId": item_of_row,
            "artistId": artist_of_row,
        },
        id_vocabs={
            "userId": [f"u{i}" for i in range(num_users)],
            "itemId": [f"i{i}" for i in range(num_items)],
            "artistId": [f"a{i}" for i in range(num_artists)],
        },
        shards={
            "global": dense_to_csr(x_fixed),
            "per_user": dense_to_csr(x_user),
            "per_item": dense_to_csr(x_item),
            "per_artist": dense_to_csr(x_artist),
        },
    )
    truth = {
        "w_fixed": w_fixed,
        "w_users": w_users,
        "w_items": w_items,
        "w_artists": w_artists,
        "user_of_row": user_of_row,
        "item_of_row": item_of_row,
        "artist_of_row": artist_of_row,
        "margin": margin,
    }
    return data, truth


def make_full_game_coords(
    data: GameData,
    fe_iters: int = 30,
    re_iters: int = 20,
    mf_inner_iters: int = 1,
    mf_re_iters: int = 10,
    latent_dim: int = 4,
):
    """The 4-coordinate full-GAME stack (fixed + per-user RE + per-item RE
    + factored per-artist MF) over :func:`make_full_game_data` output —
    shared by the correctness test and bench.py so they exercise the SAME
    model wiring. The factored coordinate requires IDENTITY projection
    (local dim == global dim), passed explicitly rather than relying on
    INDEX_MAP collapsing to identity on dense synthetic shards.
    """
    from photon_ml_tpu.algorithm import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.algorithm.factored_random_effect import (
        FactoredRandomEffectCoordinate,
        MFOptimizationConfig,
    )
    from photon_ml_tpu.data.game import (
        RandomEffectDataConfig,
        build_fixed_effect_batch,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationProblem
    from photon_ml_tpu.ops.regularization import RegularizationContext
    from photon_ml_tpu.types import OptimizerType, TaskType

    def re_coord(id_name, shard):
        return RandomEffectCoordinate(
            build_random_effect_dataset(
                data, RandomEffectDataConfig(id_name, shard)
            ),
            TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=re_iters, tolerance=1e-6),
            RegularizationContext.l2(1e-1),
        )

    return {
        "fixed": FixedEffectCoordinate(
            build_fixed_effect_batch(data, "global", dense=True),
            GLMOptimizationProblem(
                TaskType.LOGISTIC_REGRESSION,
                OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=fe_iters, tolerance=1e-7),
                RegularizationContext.l2(1e-2),
            ),
        ),
        "per-user": re_coord("userId", "per_user"),
        "per-item": re_coord("itemId", "per_item"),
        "per-artist": FactoredRandomEffectCoordinate(
            dataset=build_random_effect_dataset(
                data,
                RandomEffectDataConfig(
                    "artistId", "per_artist", projector="IDENTITY"
                ),
            ),
            task=TaskType.LOGISTIC_REGRESSION,
            mf_config=MFOptimizationConfig(
                num_inner_iterations=mf_inner_iters,
                latent_space_dimension=latent_dim,
            ),
            re_optimizer_config=OptimizerConfig(
                max_iterations=mf_re_iters, tolerance=1e-6
            ),
            latent_optimizer_config=OptimizerConfig(
                max_iterations=mf_re_iters, tolerance=1e-6
            ),
        ),
    }



def launch_multihost(module: str, args, n_processes: int = 2,
                     result_expr: str = "", timeout: int = 600):
    """Run a multihost CLI module as n SPMD subprocesses on localhost
    (4 virtual CPU devices each) and return their stdouts. ``result_expr``
    is an optional print statement appended after main() (e.g. to emit a
    tagged JSON line the caller parses)."""
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    launcher = (
        "import jax; jax.config.update('jax_platforms','cpu'); "
        f"from photon_ml_tpu.cli.{module} import main; "
        "import sys, json; res = main(sys.argv[1:]); " + (result_expr or "pass")
    )
    procs = []
    for pid in range(n_processes):
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        procs.append(subprocess.Popen(
            [sys.executable, "-c", launcher,
             "--multihost-coordinator", f"127.0.0.1:{port}",
             "--multihost-num-processes", str(n_processes),
             "--multihost-process-id", str(pid)] + list(args),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=repo, env=env,
        ))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, (
            f"{module} failed:\n{out[-1200:]}\n{err[-2500:]}"
        )
        outs.append(out)
    return outs


# ---------------------------------------------------------------------------
# Avro fixture writing + a synthetic untrained GAME model (shared by
# tests/test_serve.py and the bench.py serving section)
# ---------------------------------------------------------------------------

def game_example_schema():
    """TrainingExampleAvro with two feature sections (fixedFeatures /
    userFeatures) — the multi-section record shape the driver tests use."""
    from photon_ml_tpu.io import schemas

    return {
        "name": "GameExampleAvro",
        "namespace": "test",
        "type": "record",
        "fields": [
            {"name": "uid", "type": ["null", "string"], "default": None},
            {"name": "label", "type": "double"},
            {"name": "fixedFeatures",
             "type": {"type": "array", "items": schemas.FEATURE}},
            {"name": "userFeatures",
             "type": {"type": "array",
                      "items": "com.linkedin.photon.avro.generated.FeatureAvro"}},
            {"name": "metadataMap",
             "type": ["null", {"type": "map", "values": "string"}],
             "default": None},
            {"name": "weight", "type": ["null", "double"], "default": None},
            {"name": "offset", "type": ["null", "double"], "default": None},
        ],
    }


def game_avro_records(data: "GameData", rows, truth: Dict[str, np.ndarray],
                      offsets: Optional[np.ndarray] = None):
    """make_glmix_data output -> GameExampleAvro record dicts (entity id in
    metadataMap; nonzero features only; optional per-row offsets)."""
    def feats(x_row, prefix):
        return [
            {"name": f"{prefix}{j}", "term": "", "value": float(v)}
            for j, v in enumerate(x_row)
            if v != 0.0
        ]

    vocab = data.id_vocabs["userId"]
    for r in rows:
        yield {
            "uid": str(r),
            "label": float(data.response[r]),
            "fixedFeatures": feats(truth["x_fixed"][r], "f"),
            "userFeatures": feats(truth["x_random"][r], "u"),
            "metadataMap": {"userId": vocab[data.ids["userId"][r]]},
            "weight": None,
            "offset": float(offsets[r]) if offsets is not None else None,
        }


def write_game_avro(path: str, data: "GameData", rows,
                    truth: Dict[str, np.ndarray],
                    offsets: Optional[np.ndarray] = None) -> None:
    from photon_ml_tpu.io import avro as avro_io

    avro_io.write_container(
        path, game_avro_records(data, rows, truth, offsets),
        game_example_schema(),
    )


def save_synthetic_game_model(
    model_dir: str,
    rng: np.random.Generator,
    d_fixed: int = 5,
    d_random: int = 3,
    num_users: int = 12,
    scale: float = 1.0,
    task=None,
):
    """Persist a random (untrained) GAME model in the reference layout:
    fixed effect 'fixed' on shard 'global' (features f0..f{d_fixed-1}) and
    random effect 'per-user' on shard 'per_user' (features u0..) over
    userId entities u0..u{num_users-1}. Returns (w_fixed, entity_means,
    fixed_map, user_map) — what serving/scoring must reproduce."""
    from photon_ml_tpu.io import model_io
    from photon_ml_tpu.io.index_map import IndexMap, feature_key
    from photon_ml_tpu.types import TaskType

    fmap = IndexMap.build(
        [feature_key(f"f{j}", "") for j in range(d_fixed)], add_intercept=True
    )
    umap = IndexMap.build(
        [feature_key(f"u{j}", "") for j in range(d_random)], add_intercept=True
    )
    task = task or TaskType.LOGISTIC_REGRESSION
    w_fixed = (rng.normal(size=len(fmap)) * scale).astype(np.float32)
    entity_means = {
        f"u{i}": (rng.normal(size=len(umap)) * scale).astype(np.float32)
        for i in range(num_users)
    }
    model_io.save_fixed_effect(
        model_dir, "fixed", task, w_fixed, fmap,
        feature_shard_id="global",
    )
    model_io.save_random_effect(
        model_dir, "per-user", task, entity_means,
        umap, random_effect_id="userId", feature_shard_id="per_user",
    )
    return w_fixed, entity_means, fmap, umap


def serve_requests_from_records(records) -> list:
    """GameExampleAvro record dicts -> serve-protocol request rows (the
    same features/ids/offset the batch driver reads from Avro)."""
    return [
        {
            "features": {
                "fixedFeatures": rec["fixedFeatures"],
                "userFeatures": rec["userFeatures"],
            },
            "ids": {"userId": (rec.get("metadataMap") or {}).get("userId")},
            "offset": rec.get("offset") or 0.0,
        }
        for rec in records
    ]


# ---------------------------------------------------------------------------
# Device-vs-oracle scoring comparison with a quantization error budget
# (shared by tests/test_serve.py, tests/test_serve_fleet.py, and the
# bench.py quantized_serving section)
# ---------------------------------------------------------------------------


def serving_score_budget(
    store_meta: dict, requests: list, shard_sections: Dict[str, list]
) -> np.ndarray:
    """(n,) per-score quantization budget for ``requests`` against a
    serving store's meta: each random-effect coordinate contributes
    ``||values||_1`` (its shard's sections, intercept included) times the
    coordinate's PINNED ``coeff_err_budget`` from the export. All-zero
    for f32 stores — where the contract is bitwise, the budget says so."""
    n = len(requests)
    budget = np.zeros(n, np.float64)
    for entry in store_meta.get("random") or []:
        coeff = float(
            (entry.get("quantization") or {}).get("coeff_err_budget") or 0.0
        )
        if coeff == 0.0:
            continue
        sections = shard_sections.get(entry["shard"]) or ["features"]
        for i, req in enumerate(requests):
            feats = req.get("features") or {}
            if isinstance(feats, list):
                feats = {"features": feats}
            l1 = 1.0  # the intercept slot's value
            for section in sections:
                for f in feats.get(section) or []:
                    l1 += abs(float(f["value"]))
            budget[i] += l1 * coeff
    return budget


def assert_scores_match_store(
    served, oracle_scores, store_meta: dict, requests: list,
    shard_sections: Dict[str, list], err_msg: str = "",
):
    """The serving oracle comparison, budget-aware: BITWISE for an f32
    store (the existing contract, untouched), the pinned per-score
    quantization budget for bf16/int8 stores."""
    from tolerances import assert_within_budget, quant_score_budget

    served = np.asarray(served)
    oracle_scores = np.asarray(oracle_scores)
    if (store_meta.get("store_dtype") or "f32") == "f32":
        assert np.array_equal(served, oracle_scores), (
            f"f32-store scores must stay BITWISE-equal to the oracle "
            f"(max diff {np.max(np.abs(served - oracle_scores)):.3e}). "
            + err_msg
        )
        return
    budget = serving_score_budget(store_meta, requests, shard_sections)
    # the per-coordinate l1 * coeff products are already summed in
    # `budget`, so the policy call just adds the shared f32-noise slack
    assert_within_budget(
        served, oracle_scores,
        quant_score_budget(1.0, budget, ref_scores=oracle_scores),
        err_msg=err_msg,
    )
