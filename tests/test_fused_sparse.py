"""Fused sparse per-entity kernels: slab construction, family bit-identity,
solver wiring, selection race, and executable reuse.

The discipline under test (ops/fused_sparse.py): every sparse family —
XLA scatter, the XLA two-pass segment-sum baseline, the fused single-pass
Pallas GEVM/HVP (whole-slab and row-blocked) — shares ONE arithmetic, so a
per-entity solve through the fused kernel is BITWISE-equal to the same
solve with the kernel off (the XLA baseline). The dense path is a
different arithmetic (XLA reassociates the dense dot), so dense agreement
is at float tolerance and switching a bucket to sparse at all is a raced,
per-bucket decision.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from photon_ml_tpu.ops import fused_sparse, losses
from photon_ml_tpu.ops.fused_sparse import (
    SPARSE_BASELINE,
    SparseSlab,
    build_sparse_slab,
    fused_hvp_parts,
    fused_value_grad_parts,
    race_sparse_kernels,
    resolve_sparse_kernel,
    slab_nnz_stats,
)

pytestmark = pytest.mark.sparse


def _skewed_dense(rng, e, m, d, max_nnz=None, pad_lanes=0):
    """Dense (E, M, D) stack with skewed per-row nnz; the last ``pad_lanes``
    lanes get zero-weight garbage rows beyond row m//2 (bucket padding)."""
    max_nnz = max_nnz or max(d // 4, 2)
    x = np.zeros((e, m, d), np.float32)
    for ei in range(e):
        for mi in range(m):
            nnz = int(rng.integers(0, max_nnz + 1))
            if nnz:
                cols = rng.choice(d, size=nnz, replace=False)
                x[ei, mi, cols] = rng.normal(size=nnz)
    wt = np.ones((e, m), np.float32)
    for ei in range(e - pad_lanes, e):
        wt[ei, m // 2:] = 0.0
        # garbage in padding rows must be masked to an exact zero
        x[ei, m // 2:] = rng.normal(size=(m - m // 2, d)) * 1e6
    y = (rng.random((e, m)) < 0.5).astype(np.float32)
    off = (rng.normal(size=(e, m)) * 0.1).astype(np.float32)
    return x, y, wt, off


class TestSlabBuild:
    def test_ascending_order_and_padding(self, rng):
        x, *_ = _skewed_dense(rng, 3, 8, 16)
        slab = build_sparse_slab(x)
        idx, val = np.asarray(slab.idx), np.asarray(slab.val)
        counts = (x != 0).sum(-1)
        assert slab.dim == 16
        assert idx.shape == val.shape == (3, 8, counts.max())
        for e in range(3):
            for m in range(8):
                k = counts[e, m]
                cols = np.nonzero(x[e, m])[0]
                assert (idx[e, m, :k] == cols).all()  # ascending column order
                np.testing.assert_array_equal(val[e, m, :k], x[e, m, cols])
                # padding slots: index 0, value 0
                assert (idx[e, m, k:] == 0).all()
                assert (val[e, m, k:] == 0).all()

    def test_all_zero_rows_and_k_floor(self):
        slab = build_sparse_slab(np.zeros((2, 4, 8), np.float32))
        assert slab.max_nnz == 1  # K >= 1 keeps downstream shapes sane
        assert (np.asarray(slab.val) == 0).all()
        stats = slab_nnz_stats(slab)
        assert stats["max_nnz"] == 0 and stats["mean_nnz"] == 0.0

    def test_empty_bucket(self):
        slab = build_sparse_slab(np.zeros((0, 4, 8), np.float32))
        assert slab.idx.shape == (0, 4, 1)

    def test_ladder_rounds_k(self, rng):
        from photon_ml_tpu.compile import ShapeBucketer

        x, *_ = _skewed_dense(rng, 2, 6, 32, max_nnz=9)
        k_raw = int((x != 0).sum(-1).max())
        slab = build_sparse_slab(x, bucketer=ShapeBucketer(base=8, growth=2.0))
        # K lands on the 8 * 2^k ladder rung >= raw max nnz, capped at D
        assert slab.max_nnz >= k_raw
        assert slab.max_nnz in (8, 16, 32)

    def test_dense_roundtrip(self, rng):
        x, *_ = _skewed_dense(rng, 1, 5, 12)
        slab = build_sparse_slab(x[0])
        np.testing.assert_array_equal(np.asarray(slab.to_dense()), x[0])


class TestFamilyBitIdentity:
    """scatter == segment == fused pallas (whole-slab AND row-blocked),
    bitwise; dense reference at float tolerance."""

    @pytest.fixture()
    def lane(self, rng):
        x, y, wt, off = _skewed_dense(rng, 1, 64, 24)
        slab = build_sparse_slab(x[0])
        w = jnp.asarray(rng.normal(size=24).astype(np.float32) * 0.3)
        return (
            slab, x[0], jnp.asarray(y[0]), jnp.asarray(wt[0]),
            jnp.asarray(off[0]), w,
        )

    def _baseline_parts(self, slab, y, wt, off, w, loss):
        # the scalar pieces reduce through the shared fixed-association
        # tree — the arithmetic every sparse family reproduces bitwise
        z = slab.matvec(w) + off
        wl = jnp.where(wt > 0, wt * loss.loss(z, y), 0.0)
        d = jnp.where(wt > 0, wt * loss.d1(z, y), 0.0)
        return (
            fused_sparse.tree_row_sum(wl),
            slab.rmatvec(d),
            fused_sparse.tree_row_sum(d),
        )

    @pytest.mark.parametrize("loss_name", ["logistic", "squared", "poisson"])
    def test_vg_families(self, lane, loss_name):
        slab, x, y, wt, off, w = lane
        loss = getattr(losses, loss_name)
        lv, g, sd = self._baseline_parts(slab, y, wt, off, w, loss)
        g_seg = slab.with_kernel("segment").rmatvec(
            jnp.where(wt > 0, wt * loss.d1(slab.matvec(w) + off, y), 0.0)
        )
        assert np.array_equal(np.asarray(g), np.asarray(g_seg))
        for kernel in ("pallas", "pallas:16"):
            lvF, gF, sdF = fused_value_grad_parts(
                loss, slab.with_kernel(kernel), y, wt, off, w
            )
            assert float(lvF) == float(lv), kernel
            assert np.array_equal(np.asarray(gF), np.asarray(g)), kernel
            assert float(sdF) == float(sd), kernel
        # the flat lane-offset family: unbatched it IS the plain scatter
        g_flat = slab.with_kernel("flat").rmatvec(
            jnp.where(wt > 0, wt * loss.d1(slab.matvec(w) + off, y), 0.0)
        )
        assert np.array_equal(np.asarray(g_flat), np.asarray(g))
        # dense reference: same math, different (reassociated) accumulation
        z_d = jnp.asarray(x) @ w + off
        lv_d = jnp.sum(jnp.where(wt > 0, wt * loss.loss(z_d, y), 0.0))
        np.testing.assert_allclose(float(lv), float(lv_d), rtol=1e-4)

    def test_hvp_families(self, lane, rng):
        slab, x, y, wt, off, w = lane
        loss = losses.logistic
        v = jnp.asarray(rng.normal(size=24).astype(np.float32))
        z = slab.matvec(w) + off
        d2 = jnp.where(wt > 0, wt * loss.d2(z, y), 0.0)
        c = d2 * (slab.matvec(v) + jnp.zeros(()))
        hv = slab.rmatvec(c)
        for kernel in ("pallas", "pallas:16"):
            hvF, scF = fused_hvp_parts(
                loss, slab.with_kernel(kernel), y, wt, off, w, v, jnp.zeros(())
            )
            assert np.array_equal(np.asarray(hvF), np.asarray(hv)), kernel
            assert float(scF) == float(fused_sparse.tree_row_sum(c)), kernel

    def test_flat_batched_rule_bitwise(self, rng):
        """The interesting path for "flat": under vmap the custom_vmap
        rule folds lane offsets into ONE (E*D,) scatter — lanes are
        disjoint, so it must be bitwise-equal to the batched per-lane
        scatter/segment lowerings."""
        x, y, wt, off = _skewed_dense(rng, 8, 32, 16)
        slab = build_sparse_slab(x)
        d = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32))

        def rm(kernel):
            fn = jax.vmap(
                lambda i, v, dd: SparseSlab(i, v, 16, kernel).rmatvec(dd)
            )
            return np.asarray(jax.jit(fn)(slab.idx, slab.val, d))  # jit-ok: test fixture

        ref = rm("segment")
        assert np.array_equal(rm("flat"), ref)
        assert np.array_equal(rm("scatter"), ref)

    def test_pad_rows_hard_masked(self, rng):
        # weight-0 rows carry garbage that would overflow poisson exp —
        # every family must contribute an exact 0 for them
        x, y, wt, off = _skewed_dense(rng, 2, 16, 8, pad_lanes=1)
        slab = build_sparse_slab(x)
        lane = 1  # the padded lane
        sl = SparseSlab(slab.idx[lane], slab.val[lane], 8, "pallas")
        w = jnp.asarray(rng.normal(size=8).astype(np.float32))
        lv, g, sd = fused_value_grad_parts(
            losses.poisson, sl, jnp.asarray(y[lane]), jnp.asarray(wt[lane]),
            jnp.asarray(off[lane]), w,
        )
        assert np.isfinite(float(lv)) and np.isfinite(np.asarray(g)).all()

    def test_ragged_m_single_block(self, rng):
        # M that no row-block divides: the whole-slab default covers it in
        # one grid step (the "tail chunk" of the sparse family)
        x, y, wt, off = _skewed_dense(rng, 1, 37, 12)
        slab = build_sparse_slab(x[0]).with_kernel("pallas")
        w = jnp.asarray(rng.normal(size=12).astype(np.float32))
        lv, g, sd = fused_value_grad_parts(
            losses.logistic, slab, jnp.asarray(y[0]), jnp.asarray(wt[0]),
            jnp.asarray(off[0]), w,
        )
        base = slab.with_kernel("scatter")
        z = base.matvec(w) + jnp.asarray(off[0])
        d = jnp.where(jnp.asarray(wt[0]) > 0,
                      jnp.asarray(wt[0]) * losses.logistic.d1(z, jnp.asarray(y[0])), 0.0)
        assert np.array_equal(np.asarray(g), np.asarray(base.rmatvec(d)))
        # a forced row block that does not tile M degrades to the
        # whole-slab grid (identical arithmetic) instead of aborting —
        # a global "pallas:<rows>" spec must survive heterogeneous rungs
        lvB, gB, sdB = fused_value_grad_parts(
            losses.logistic, slab.with_kernel("pallas:16"),
            jnp.asarray(y[0]), jnp.asarray(wt[0]), jnp.asarray(off[0]), w,
        )
        assert float(lvB) == float(lv)
        assert np.array_equal(np.asarray(gB), np.asarray(g))


class TestSolveBitIdentity:
    """Full per-entity solves: fused sparse path bitwise-equal to the
    kernel-off (XLA baseline) path; dense at tolerance."""

    @pytest.fixture()
    def problem(self, rng):
        from game_test_utils import make_glmix_data
        from photon_ml_tpu.data.game import (
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )

        data, _ = make_glmix_data(
            rng, num_users=10, rows_per_user_range=(4, 20), d_fixed=4,
            d_random=3,
        )
        ds = build_random_effect_dataset(
            data, RandomEffectDataConfig("userId", "per_user")
        )
        return ds, jnp.zeros((data.num_rows,))

    def _solve(self, ds, resid, kernel, optimizer="LBFGS", schedule=None):
        from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.types import OptimizerType, TaskType

        coord = RandomEffectCoordinate(
            ds, TaskType.LOGISTIC_REGRESSION, OptimizerType[optimizer],
            OptimizerConfig(max_iterations=8, tolerance=1e-8),
            RegularizationContext.l2(0.4),
            sparse_kernel=kernel, solve_schedule=schedule,
        )
        coefs, _ = coord.update(resid, coord.initial_coefficients())
        return np.asarray(coefs)

    @pytest.mark.parametrize("optimizer", ["LBFGS", "TRON"])
    def test_fused_bitwise_vs_kernel_off(self, problem, optimizer):
        ds, resid = problem
        w_off = self._solve(ds, resid, SPARSE_BASELINE, optimizer)
        for kernel in ("scatter", "flat", "pallas"):
            w_on = self._solve(ds, resid, kernel, optimizer)
            assert np.array_equal(w_on, w_off), kernel

    def test_dense_reference_at_tolerance(self, problem):
        ds, resid = problem
        w_dense = self._solve(ds, resid, None)
        w_sparse = self._solve(ds, resid, "scatter")
        # dense is a different arithmetic (XLA reassociates the dense dot):
        # agreement is at float tolerance, bitwise equality is NOT expected
        np.testing.assert_allclose(w_sparse, w_dense, rtol=1e-2, atol=1e-3)

    def test_scheduled_solve_bitwise(self, problem):
        from photon_ml_tpu.optim.scheduler import SolveSchedule

        ds, resid = problem
        one_shot = self._solve(ds, resid, "pallas")
        chunked = self._solve(
            ds, resid, "pallas", schedule=SolveSchedule(chunk_size=3)
        )
        assert np.array_equal(one_shot, chunked)

    def test_traced_construction_requires_prebuilt_slab(self, problem):
        from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
        from photon_ml_tpu.types import TaskType

        ds, resid = problem

        def build(ds):
            return RandomEffectCoordinate(
                ds, TaskType.LOGISTIC_REGRESSION, sparse_kernel="scatter"
            ).initial_coefficients()

        with pytest.raises(ValueError, match="under a trace"):
            jax.jit(build)(ds)  # jit-ok: test fixture exercising the guard


class TestExecutableReuse:
    def test_same_ladder_buckets_share_chunk_executable(self, rng):
        """Two buckets on the same (E, M, K) rung solve through ONE
        scheduler chunk executable; a warm re-solve adds zero compiles
        (the CompileStats watermark assertion from the acceptance gate)."""
        from photon_ml_tpu.compile import compile_stats
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.optim.scheduler import SolveSchedule, compacted_solve
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.types import OptimizerType, TaskType

        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(max_iterations=12, tolerance=1e-7),
            regularization=RegularizationContext.l2(0.5),
        )
        schedule = SolveSchedule(chunk_size=4)

        def solve(seed):
            r = np.random.default_rng(seed)
            x, y, wt, off = _skewed_dense(r, 8, 16, 12, max_nnz=4)
            # pin the rung: row (0,0) carries exactly the nnz cap, so both
            # seeds' slabs land on K=4 and share every executable
            x[0, 0] = 0.0
            x[0, 0, :4] = 1.0
            slab = build_sparse_slab(x).with_kernel("pallas")
            assert slab.idx.shape == (8, 16, 4)
            data = (slab, jnp.asarray(y), jnp.asarray(off), jnp.asarray(wt))
            res = compacted_solve(
                data, jnp.zeros((8, 12), jnp.float32), schedule=schedule,
                label=f"reuse{seed}", **kw,
            )
            jax.block_until_ready(res.coefficients)

        solve(0)  # cold: compiles the rung's chunk kernels
        mark = compile_stats.watermark()
        solve(1)  # same rung, different bucket: NO new executables
        assert mark.new_traces() == 0, (
            "a same-ladder bucket recompiled the scheduler kernels: "
            f"{mark.new_traces()} new traces"
        )


class TestSelectionRace:
    def test_every_candidate_accounted_for(self, rng):
        from photon_ml_tpu.types import TaskType

        x, y, wt, off = _skewed_dense(rng, 4, 16, 12)
        slab = build_sparse_slab(x)
        report = race_sparse_kernels(
            TaskType.LOGISTIC_REGRESSION, slab, x, jnp.asarray(y),
            jnp.asarray(off), jnp.asarray(wt),
        )
        raced = set(fused_sparse.sparse_candidates(32)) | {"dense"}
        # no silent caps: every raced name shows up with a timing or a
        # failure reason
        assert raced <= set(report["candidates"])
        for name, rec in report["candidates"].items():
            assert ("sec_per_pass" in rec) or ("failed" in rec), name
        assert report["baseline"] == SPARSE_BASELINE

    def test_f64_disqualifies_pallas_with_reason(self, rng):
        from photon_ml_tpu.compat import enable_x64
        from photon_ml_tpu.types import TaskType

        x, y, wt, off = _skewed_dense(rng, 3, 8, 8)
        with enable_x64():
            slab = build_sparse_slab(x, dtype=np.float64)
            report = race_sparse_kernels(
                TaskType.LOGISTIC_REGRESSION, slab,
                x.astype(np.float64), jnp.asarray(y), jnp.asarray(off),
                jnp.asarray(wt),
            )
        rec = report["candidates"]["pallas"]
        assert "failed" in rec and "float64" in rec["failed"]

    def test_forced_pallas_f64_runs_scatter_family(self, rng):
        """A FORCED pallas family under float64 must normalize to the
        family that actually executes (the objective's f64 gate falls back
        to the generic scatter) instead of lying in telemetry and keying a
        duplicate executable on a "pallas" static field."""
        from photon_ml_tpu.compat import enable_x64
        from photon_ml_tpu.types import TaskType

        x, y, wt, off = _skewed_dense(rng, 3, 8, 6)
        with enable_x64():
            with pytest.warns(UserWarning, match="ineligible under float64"):
                slab = fused_sparse.build_and_select(
                    TaskType.LOGISTIC_REGRESSION, x.astype(np.float64),
                    jnp.asarray(y), jnp.asarray(off), jnp.asarray(wt),
                    "pallas", "f64-forced",
                )
        assert slab is not None and slab.kernel == "scatter"

    def test_race_cache_keyed_by_dtype(self, rng, monkeypatch):
        """An f32 bucket's raced winner must not be reused for a
        same-shaped f64 slab — eligibility differs (pallas is out under
        f64), so the cache key carries the dtype."""
        from photon_ml_tpu.types import TaskType

        calls = []

        def fake_race(task, slab, *a, **kw):
            calls.append(jnp.dtype(slab.val.dtype).name)
            return {"winner": "flat"}

        monkeypatch.setattr(fused_sparse, "race_sparse_kernels", fake_race)
        monkeypatch.setattr(fused_sparse, "_race_cache", {})
        monkeypatch.setattr(fused_sparse, "_race_reports", {})
        x, y, wt, off = _skewed_dense(rng, 3, 8, 6)
        args = (jnp.asarray(y), jnp.asarray(off), jnp.asarray(wt))
        slab32 = build_sparse_slab(x)
        for _ in range(2):  # second call: cache hit, no re-race
            fused_sparse.select_sparse_kernel(
                TaskType.LOGISTIC_REGRESSION, slab32, x, *args, spec="auto"
            )
        assert calls == ["float32"]
        # same shape, f64 leaves (host numpy — the race is faked, so no
        # x64 mode needed): must MISS the f32 entry and race again
        slab64 = SparseSlab(
            np.asarray(slab32.idx), np.asarray(slab32.val, np.float64),
            slab32.dim,
        )
        fused_sparse.select_sparse_kernel(
            TaskType.LOGISTIC_REGRESSION, slab64, x, *args, spec="auto"
        )
        assert calls == ["float32", "float64"]

    def test_resolve_spec(self, monkeypatch):
        monkeypatch.delenv("PHOTON_SPARSE_KERNEL", raising=False)
        assert resolve_sparse_kernel(None) is None
        assert resolve_sparse_kernel("off") is None
        assert resolve_sparse_kernel("auto") == "auto"
        assert resolve_sparse_kernel("pallas:256") == "pallas:256"
        monkeypatch.setenv("PHOTON_SPARSE_KERNEL", "segment")
        assert resolve_sparse_kernel(None) == "segment"
        with pytest.raises(ValueError, match="bad sparse-kernel spec"):
            resolve_sparse_kernel("bogus")
        # ":<rows>" is pallas-only grammar — "flat:128" would silently run
        # the scatter schedule under a "flat:128" static key
        with pytest.raises(ValueError, match="bad sparse-kernel spec"):
            resolve_sparse_kernel("flat:128")

    def test_env_off_keeps_dense_path(self, rng, monkeypatch):
        from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
        from photon_ml_tpu.data.game import (
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.types import TaskType
        from game_test_utils import make_glmix_data

        monkeypatch.delenv("PHOTON_SPARSE_KERNEL", raising=False)
        data, _ = make_glmix_data(
            rng, num_users=4, rows_per_user_range=(3, 8), d_fixed=3,
            d_random=2,
        )
        ds = build_random_effect_dataset(
            data, RandomEffectDataConfig("userId", "per_user")
        )
        coord = RandomEffectCoordinate(ds, TaskType.LOGISTIC_REGRESSION)
        assert coord._slab is None


class TestCoordinateWiring:
    # slow: 2 full bucketed solves compile per-rung executables twice each —
    # tier-1 keeps the cheap cousins (solve bit-identity pins, env-driven
    # streaming bitwise, bucketed mesh/subs construction)
    @pytest.mark.slow
    def test_bucketed_per_bucket_bitwise(self, rng):
        from game_test_utils import make_glmix_data
        from photon_ml_tpu.algorithm.bucketed_random_effect import (
            BucketedRandomEffectCoordinate,
        )
        from photon_ml_tpu.data.game import RandomEffectDataConfig
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.types import OptimizerType, TaskType

        data, _ = make_glmix_data(
            rng, num_users=8, rows_per_user_range=(3, 20), d_fixed=4,
            d_random=4,
        )
        cfg = RandomEffectDataConfig("userId", "per_user")
        resid = jnp.zeros((data.num_rows,))

        def solve(kernel):
            coord = BucketedRandomEffectCoordinate(
                data, cfg, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=12, tolerance=1e-8),
                RegularizationContext.l2(0.3), sparse_kernel=kernel,
            )
            state, _ = coord.update(resid, coord.initial_coefficients())
            return [np.asarray(s) for s in state]

        w_off = solve(SPARSE_BASELINE)
        # flat, not pallas: per-bucket WIRING is what's under test here and
        # every bucket rung pays a fresh interpret-mode compile on CPU;
        # pallas solve bit-identity is pinned one-shot/scheduled/streaming
        w_fused = solve("flat")
        assert all(np.array_equal(a, b) for a, b in zip(w_fused, w_off))

    @pytest.mark.slow  # same budget rationale as the bucketed test above
    def test_streaming_blocks_bitwise(self, rng, tmp_path):
        from game_test_utils import make_glmix_data
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            StreamingRandomEffectCoordinate,
            write_re_entity_blocks,
        )
        from photon_ml_tpu.data.game import RandomEffectDataConfig
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.types import OptimizerType, TaskType

        data, _ = make_glmix_data(
            rng, num_users=10, rows_per_user_range=(3, 16), d_fixed=4,
            d_random=3,
        )
        cfg = RandomEffectDataConfig("userId", "per_user")
        manifest = write_re_entity_blocks(
            data, cfg, str(tmp_path / "blocks"), block_entities=5
        )
        resid = jnp.zeros((data.num_rows,))

        def solve(kernel):
            coord = StreamingRandomEffectCoordinate(
                manifest, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=10, tolerance=1e-8),
                RegularizationContext.l2(0.3), sparse_kernel=kernel,
                state_root=str(tmp_path / f"state-{kernel}"),
            )
            state, _ = coord.update(resid, coord.initial_coefficients())
            return [state.block(i) for i in range(len(manifest.blocks))]

        w_off = solve(SPARSE_BASELINE)
        w_fused = solve("pallas")
        assert all(np.array_equal(a, b) for a, b in zip(w_fused, w_off))

    def test_block_slab_cache_is_host_resident(self, rng, tmp_path):
        """The streaming contract keeps device memory O(one block): cached
        per-block slabs must hold HOST leaves (re-uploaded per touch like
        the block tensors), not device buffers that accumulate across the
        first epoch and OOM a manifest whose dense blocks streamed fine."""
        from game_test_utils import make_glmix_data
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            StreamingRandomEffectCoordinate,
            write_re_entity_blocks,
        )
        from photon_ml_tpu.data.game import RandomEffectDataConfig
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.types import OptimizerType, TaskType

        data, _ = make_glmix_data(
            rng, num_users=6, rows_per_user_range=(3, 8), d_fixed=3,
            d_random=2,
        )
        cfg = RandomEffectDataConfig("userId", "per_user")
        manifest = write_re_entity_blocks(
            data, cfg, str(tmp_path / "blocks"), block_entities=3
        )
        coord = StreamingRandomEffectCoordinate(
            manifest, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=3, tolerance=1e-6),
            RegularizationContext.l2(0.3), sparse_kernel="scatter",
            state_root=str(tmp_path / "state"),
        )
        coord.update(
            jnp.zeros((data.num_rows,)), coord.initial_coefficients()
        )
        slabs = [s for s in coord._sparse_slabs.values() if s is not None]
        assert slabs, "no block selected the sparse path"
        assert all(
            isinstance(s.idx, np.ndarray) and isinstance(s.val, np.ndarray)
            for s in slabs
        )


class TestMeshPathEnvImmunity:
    def test_distributed_solver_ignores_env_spec(self, rng, monkeypatch):
        """Regression: the distributed RE solver re-constructs the
        coordinate (dataclasses.replace) INSIDE shard_map — with
        PHOTON_SPARSE_KERNEL set it used to re-resolve the env under the
        trace and die on the traced-construction guard. The mesh path has
        no per-shard slab selection: it must pin sparse off and run."""
        from game_test_utils import make_glmix_data
        from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
        from photon_ml_tpu.data.game import (
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.parallel.distributed import DistributedRandomEffectSolver
        from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
        from photon_ml_tpu.types import OptimizerType, TaskType

        data, _ = make_glmix_data(
            rng, num_users=8, rows_per_user_range=(3, 10), d_fixed=3,
            d_random=2,
        )
        ds = build_random_effect_dataset(
            data, RandomEffectDataConfig("userId", "per_user")
        )
        coord = RandomEffectCoordinate(
            ds, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=10, tolerance=1e-7),
            RegularizationContext.l2(0.5),
        )
        solver = DistributedRandomEffectSolver(coord, MeshContext(data_mesh()))
        resid = jnp.zeros((data.num_rows,))
        monkeypatch.setenv("PHOTON_SPARSE_KERNEL", "auto")
        coefs, _ = solver.update(resid, solver.initial_coefficients())
        assert np.isfinite(np.asarray(coefs)).all()

    def test_bucketed_mesh_subs_skip_slab_build(self, rng, monkeypatch):
        """Under mesh_ctx the distributed solvers pin sparse off at the
        shard level — the per-bucket subs must not race/build slabs that
        update() will never use (wasted compiles + device-resident idx/val
        held for the coordinate's lifetime)."""
        from game_test_utils import make_glmix_data
        from photon_ml_tpu.algorithm.bucketed_random_effect import (
            BucketedRandomEffectCoordinate,
        )
        from photon_ml_tpu.data.game import RandomEffectDataConfig
        from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
        from photon_ml_tpu.types import TaskType

        monkeypatch.setenv("PHOTON_SPARSE_KERNEL", "auto")
        data, _ = make_glmix_data(
            rng, num_users=6, rows_per_user_range=(3, 8), d_fixed=3,
            d_random=2,
        )
        coord = BucketedRandomEffectCoordinate(
            data, RandomEffectDataConfig("userId", "per_user"),
            TaskType.LOGISTIC_REGRESSION, mesh_ctx=MeshContext(data_mesh()),
        )
        assert all(sub._slab is None for sub in coord._subs)


class TestStreamingEnvActivation:
    def test_env_spec_drives_streaming_blocks_and_score(self, rng, tmp_path,
                                                        monkeypatch):
        """Regression: the streaming coordinate owns slab selection; its
        per-block sub-coordinates (built INSIDE the block jit, where ds.x
        is a tracer) must never re-resolve PHOTON_SPARSE_KERNEL themselves
        — with the env set, update AND score used to die on the
        traced-construction guard."""
        from game_test_utils import make_glmix_data
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            StreamingRandomEffectCoordinate,
            write_re_entity_blocks,
        )
        from photon_ml_tpu.data.game import RandomEffectDataConfig
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.types import OptimizerType, TaskType

        data, _ = make_glmix_data(
            rng, num_users=6, rows_per_user_range=(3, 10), d_fixed=3,
            d_random=3,
        )
        cfg = RandomEffectDataConfig("userId", "per_user")
        manifest = write_re_entity_blocks(
            data, cfg, str(tmp_path / "blocks"), block_entities=3
        )
        resid = jnp.zeros((data.num_rows,))

        def solve(env, tag):
            if env is None:
                monkeypatch.delenv("PHOTON_SPARSE_KERNEL", raising=False)
            else:
                monkeypatch.setenv("PHOTON_SPARSE_KERNEL", env)
            coord = StreamingRandomEffectCoordinate(
                manifest, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
                OptimizerConfig(max_iterations=8, tolerance=1e-8),
                RegularizationContext.l2(0.3),
                state_root=str(tmp_path / f"state-{tag}"),
            )
            state, _ = coord.update(resid, coord.initial_coefficients())
            scores = np.asarray(coord.score(state))
            return [state.block(i) for i in range(len(manifest.blocks))], scores

        w_env, s_env = solve("flat", "flat")
        # the flat family is bitwise vs the segment baseline end-to-end
        w_seg, s_seg = solve("segment", "seg")
        assert all(np.array_equal(a, b) for a, b in zip(w_env, w_seg))
        # scoring is margin-only (dense path) — identical coefficients in,
        # identical scores out
        assert np.array_equal(s_env, s_seg)


class TestDenseAutotuneFailureLogging:
    def test_skipped_and_failed_candidates_read_as_failed(self, monkeypatch):
        """The dense race record must carry every candidate: one that never
        ran (probe too small) appears with a 'failed: skipped:' reason
        instead of silently vanishing from the report."""
        from photon_ml_tpu.ops import fused_glm

        monkeypatch.setenv("PHOTON_ML_TPU_FUSED", "1")
        fused_glm._autotune_cache.clear()
        fused_glm._autotune_timings.clear()
        fused_glm._autotune_failures.clear()
        n, d = 512, 128
        block = fused_glm.select_fused_block_rows(
            losses.logistic, n, d, dtype=jnp.float32,
            candidates=(256, 1 << 19),  # the second exceeds the probe rows
        )
        assert block == 256
        report = fused_glm.autotune_report(
            losses.logistic, n, d, dtype=jnp.float32
        )
        assert report["winner"] == 256
        skipped = report["candidates"]["grid:524288"]
        assert "failed" in skipped and "skipped" in skipped["failed"]
