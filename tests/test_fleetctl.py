"""fleetctl: the operator control plane writes EXACTLY the files the
elastic monitor polls.

The CLI is stdlib-only by design (it runs on an operator workstation
against shared storage), so the shared on-disk contract with
parallel/elastic.py is enforced here: file names, payload shapes, and
the byte-identical output of the library writers. Every mutating action
must validate against the committed membership BEFORE writing — a typo'd
host id fails at the CLI, not as a livelocked re-plan loop — and must
leave one JSON audit line behind.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import fleetctl  # noqa: E402

from photon_ml_tpu.parallel import elastic  # noqa: E402


def _commit(fleet_dir, version=1, hosts=(0, 1, 2), binding=None):
    mem = elastic.FleetMembership(
        version=version,
        hosts=list(hosts),
        binding=binding or {h: h for h in hosts},
    )
    elastic.commit_membership(str(fleet_dir), mem)
    return mem


class TestParsing:
    def test_host_list(self):
        assert fleetctl.parse_host_list("2,3") == [2, 3]
        assert fleetctl.parse_host_list("3, 1,1") == [1, 3]  # dedup + sort

    @pytest.mark.parametrize("bad", ["", ",", "2,x", "a"])
    def test_host_list_refused(self, bad):
        with pytest.raises(fleetctl.FleetctlError):
            fleetctl.parse_host_list(bad)

    def test_binding_list(self):
        assert fleetctl.parse_binding_list("4:0,5:1") == {4: 0, 5: 1}

    @pytest.mark.parametrize(
        "bad", ["", "4", "4:0:1", "4:x", "4:0,4:1"]
    )
    def test_binding_list_refused(self, bad):
        with pytest.raises(fleetctl.FleetctlError):
            fleetctl.parse_binding_list(bad)


class TestSharedContract:
    """fleetctl's constants and payloads match parallel/elastic.py's —
    the monitor consumes what the CLI writes, byte for byte."""

    def test_file_name_constants_match(self):
        assert fleetctl.MEMBERSHIP_FILE == elastic.MEMBERSHIP_FILE
        assert fleetctl.LOST_HOSTS_FILE == elastic.LOST_HOSTS_FILE
        assert fleetctl.SCALE_REQUEST_FILE == elastic.SCALE_REQUEST_FILE
        assert fleetctl.PROPOSALS_DIR == elastic.PROPOSALS_DIR

    def test_lost_hosts_bytes_match_library_writer(self, tmp_path):
        a, b = tmp_path / "a", tmp_path / "b"
        a.mkdir(), b.mkdir()
        _commit(a), _commit(b)
        fleetctl.declare_lost_hosts(str(a), [1, 2], "zone-b reclamation")
        elastic.declare_lost_hosts(str(b), [1, 2], "zone-b reclamation")
        assert (
            (a / elastic.LOST_HOSTS_FILE).read_bytes()
            == (b / elastic.LOST_HOSTS_FILE).read_bytes()
        )

    def test_scale_request_bytes_match_library_writer(self, tmp_path):
        a, b = tmp_path / "a", tmp_path / "b"
        a.mkdir(), b.mkdir()
        _commit(a), _commit(b)
        fleetctl.request_scale_up(str(a), {4: 0, 5: 1}, "capacity returned")
        elastic.request_scale_up(str(b), {4: 0, 5: 1}, "capacity returned")
        assert (
            (a / elastic.SCALE_REQUEST_FILE).read_bytes()
            == (b / elastic.SCALE_REQUEST_FILE).read_bytes()
        )

    def test_membership_reader_round_trips_committed_meta(self, tmp_path):
        mem = _commit(tmp_path, version=7, hosts=(0, 2), binding={0: 0, 2: 1})
        got = fleetctl.read_membership(str(tmp_path))
        assert got == mem.to_meta()


class TestDeclareLostHosts:
    def test_refused_without_membership(self, tmp_path):
        with pytest.raises(fleetctl.FleetctlError, match="no committed"):
            fleetctl.declare_lost_hosts(str(tmp_path), [1], "r")
        assert not (tmp_path / elastic.LOST_HOSTS_FILE).exists()

    def test_force_overrides_missing_membership(self, tmp_path):
        fleetctl.declare_lost_hosts(str(tmp_path), [1], "r", force=True)
        assert (tmp_path / elastic.LOST_HOSTS_FILE).exists()

    def test_refused_for_unknown_owner(self, tmp_path):
        _commit(tmp_path)
        with pytest.raises(fleetctl.FleetctlError, match=r"\[9\] are not in"):
            fleetctl.declare_lost_hosts(str(tmp_path), [1, 9], "r")
        assert not (tmp_path / elastic.LOST_HOSTS_FILE).exists()

    def test_refused_when_it_would_empty_the_fleet(self, tmp_path):
        _commit(tmp_path)
        with pytest.raises(fleetctl.FleetctlError, match="NO owners"):
            fleetctl.declare_lost_hosts(str(tmp_path), [0, 1, 2], "r")

    def test_missing_fleet_dir_refused(self, tmp_path):
        with pytest.raises(fleetctl.FleetctlError, match="does not exist"):
            fleetctl.declare_lost_hosts(str(tmp_path / "nope"), [0], "r")

    def test_audit_line_per_action(self, tmp_path):
        _commit(tmp_path)
        fleetctl.declare_lost_hosts(str(tmp_path), [2], "first")
        fleetctl.request_scale_up(str(tmp_path), {5: 0}, "second")
        lines = (tmp_path / fleetctl.AUDIT_LOG).read_text().splitlines()
        assert len(lines) == 2
        first, second = (json.loads(ln) for ln in lines)
        assert first["action"] == "declare-lost-hosts"
        assert first["hosts"] == [2] and first["reason"] == "first"
        assert first["membership_version"] == 1
        assert second["action"] == "request-scale-up"
        assert second["add"] == {"5": 0}
        for entry in (first, second):
            assert entry["operator"]  # who asked, answerable from the dir
            assert entry["time"] > 0


class TestRequestScaleUp:
    def test_refused_without_membership(self, tmp_path):
        with pytest.raises(fleetctl.FleetctlError, match="no committed"):
            fleetctl.request_scale_up(str(tmp_path), {4: 0}, "r")

    def test_refused_for_duplicate_logical_owner(self, tmp_path):
        _commit(tmp_path)
        with pytest.raises(fleetctl.FleetctlError, match="already in"):
            fleetctl.request_scale_up(str(tmp_path), {1: 0}, "r")
        assert not (tmp_path / elastic.SCALE_REQUEST_FILE).exists()

    def test_refused_for_negative_physical_binding(self, tmp_path):
        _commit(tmp_path)
        with pytest.raises(fleetctl.FleetctlError, match="negative"):
            fleetctl.request_scale_up(str(tmp_path), {4: -1}, "r")


class TestStatus:
    def test_snapshot_fields(self, tmp_path):
        _commit(tmp_path)
        fleetctl.declare_lost_hosts(str(tmp_path), [2], "r")
        from photon_ml_tpu.parallel.multihost import write_host_heartbeat

        write_host_heartbeat(
            os.path.join(str(tmp_path), fleetctl.HEARTBEATS_DIR), 0
        )
        status = fleetctl.fleet_status(str(tmp_path))
        assert status["membership"]["version"] == 1
        assert status["lost_hosts_request"]["hosts"] == [2]
        assert status["scale_request"] is None
        assert "0" in status["heartbeat_ages"]
        assert status["heartbeat_ages"]["0"] >= 0
        assert status["consumed_requests"] == []
        json.dumps(status)  # --json output must be serializable

    def test_consumed_requests_listed(self, tmp_path):
        _commit(tmp_path)
        # the monitor archives a consumed request by renaming it
        (tmp_path / f"{elastic.LOST_HOSTS_FILE}.consumed-v2").write_text("{}")
        status = fleetctl.fleet_status(str(tmp_path))
        assert status["consumed_requests"] == [
            f"{elastic.LOST_HOSTS_FILE}.consumed-v2"
        ]


class TestConvergenceStatus:
    """The status surface over the adaptive-schedule convergence ledgers:
    fleetctl reads the sidecars photon_ml_tpu/optim/convergence.py writes
    (the same shared-contract discipline as the membership files)."""

    def _write_ledger(self, directory, entries):
        from photon_ml_tpu.optim.convergence import ConvergenceLedger

        led = ConvergenceLedger()
        for gid, (score, visits, skips) in entries.items():
            for _ in range(visits):
                led.observe(gid, score, executed=4)
            for _ in range(skips):
                led.record_skip(gid)
        os.makedirs(directory, exist_ok=True)
        led.save(str(directory))

    def test_file_name_matches_library_writer(self, tmp_path):
        from photon_ml_tpu.optim import convergence

        assert fleetctl.LEDGER_FILE == convergence.LEDGER_FILENAME

    def test_aggregates_across_hosts_max_score_summed_counts(self, tmp_path):
        self._write_ledger(tmp_path / "h0", {0: (0.5, 2, 1), 1: (0.1, 3, 0)})
        self._write_ledger(tmp_path / "h1", {0: (0.9, 1, 2), 2: (2.0, 1, 0)})
        conv = fleetctl.read_convergence_ledgers(
            [str(tmp_path / "h0"), str(tmp_path / "h1")]
        )
        assert conv["ledger_dirs"] == 2
        assert conv["blocks"] == 3
        assert conv["visits"] == 7 and conv["skips"] == 3
        # per-block: counts sum, score takes the max across hosts
        assert conv["hottest"][0] == {"block": "2", "score": 2.0, "visits": 1}
        g0 = [h for h in conv["hottest"] if h["block"] == "0"][0]
        assert g0["score"] == 0.9 and g0["visits"] == 3

    def test_hottest_is_top_n_descending(self, tmp_path):
        self._write_ledger(
            tmp_path / "h0",
            {g: (float(g), 1, 0) for g in range(fleetctl.LEDGER_TOP_N + 3)},
        )
        conv = fleetctl.read_convergence_ledgers([str(tmp_path / "h0")])
        assert len(conv["hottest"]) == fleetctl.LEDGER_TOP_N
        scores = [h["score"] for h in conv["hottest"]]
        assert scores == sorted(scores, reverse=True)

    def test_unreadable_sidecars_skipped_none_when_zero(self, tmp_path):
        missing = tmp_path / "nope"
        bad = tmp_path / "bad"
        bad.mkdir()
        (bad / fleetctl.LEDGER_FILE).write_text(
            json.dumps({"format": 99, "blocks": {}})
        )
        torn = tmp_path / "torn"
        torn.mkdir()
        (torn / fleetctl.LEDGER_FILE).write_text("{torn")
        assert fleetctl.read_convergence_ledgers(
            [str(missing), str(bad), str(torn)]
        ) is None
        # one readable dir among the junk is enough for a fleet view
        self._write_ledger(tmp_path / "ok", {0: (0.5, 1, 0)})
        conv = fleetctl.read_convergence_ledgers(
            [str(missing), str(bad), str(tmp_path / "ok")]
        )
        assert conv is not None and conv["ledger_dirs"] == 1

    def test_status_carries_convergence_only_when_asked(self, tmp_path):
        _commit(tmp_path)
        self._write_ledger(tmp_path / "h0", {0: (0.5, 2, 1)})
        status = fleetctl.fleet_status(str(tmp_path))
        assert status["convergence"] is None
        status = fleetctl.fleet_status(
            str(tmp_path), block_dirs=[str(tmp_path / "h0")]
        )
        assert status["convergence"]["visits"] == 2
        json.dumps(status)  # --json output must stay serializable
        text = fleetctl._format_status(status)
        assert "adaptive blocks: 2 visits / 1 skips across 1 blocks" in text
        assert "hottest: g0(score=0.5, visits=2)" in text

    def test_status_cli_block_dir_flag(self, tmp_path, capsys):
        _commit(tmp_path)
        self._write_ledger(tmp_path / "h0", {0: (0.5, 2, 1)})
        self._write_ledger(tmp_path / "h1", {1: (0.7, 1, 0)})
        assert fleetctl.main(
            ["status", str(tmp_path), "--json",
             "--block-dir", str(tmp_path / "h0"),
             "--block-dir", str(tmp_path / "h1")]
        ) == 0
        status = json.loads(capsys.readouterr().out)
        assert status["convergence"]["ledger_dirs"] == 2
        assert status["convergence"]["visits"] == 3


class TestPlanStatus:
    """status --plan: the cost-model sidecar fleet view (compile/cost.py
    writes, fleetctl reads — same shared-contract discipline as the
    convergence ledgers)."""

    def _write_model(self, directory, observations=None, drift=None):
        os.makedirs(directory, exist_ok=True)
        payload = {
            "format": fleetctl.COST_MODEL_FORMAT,
            "observations": observations or {},
            "drift_log": drift or [],
        }
        with open(os.path.join(directory, fleetctl.COST_MODEL_FILE), "w") as f:
            json.dump(payload, f)

    def test_file_name_and_format_match_library_writer(self):
        from photon_ml_tpu.compile import cost

        assert fleetctl.COST_MODEL_FILE == cost.COST_MODEL_FILENAME
        assert fleetctl.COST_MODEL_FORMAT == cost.COST_MODEL_FORMAT
        assert fleetctl.PLAN_DRIFT_THRESHOLD == cost.DRIFT_THRESHOLD

    def test_aggregates_policies_and_flags_drift(self, tmp_path):
        self._write_model(
            tmp_path / "r0",
            observations={
                "schedule=chunk:8@skewed": {"cost": 5000.0, "n": 3},
                "ladder=on@skewed": {"cost": 900.0, "n": 1},
            },
            drift=[
                # 100% off: flagged
                {"policy": "schedule", "action": "chunk:8",
                 "signature": "skewed", "predicted": 2500.0,
                 "realized": 5000.0},
                # spot on: not flagged
                {"policy": "ladder", "action": "on",
                 "signature": "skewed", "predicted": 900.0,
                 "realized": 900.0},
            ],
        )
        self._write_model(
            tmp_path / "r1",
            observations={"schedule=one-shot@uniform": {"cost": 1.0, "n": 2}},
        )
        plan = fleetctl.read_cost_models(
            [str(tmp_path / "r0"), str(tmp_path / "r1")]
        )
        assert plan["sidecars"] == 2 and plan["unreadable"] == 0
        assert plan["policies"]["schedule"] == {"keys": 2, "samples": 5}
        assert plan["policies"]["ladder"] == {"keys": 1, "samples": 1}
        assert plan["drifted_total"] == 1
        d = plan["drifted"][0]
        assert d["policy"] == "schedule" and d["error"] == 1.0

    def test_torn_and_misformatted_sidecars_counted_not_fatal(self, tmp_path):
        torn = tmp_path / "torn"
        torn.mkdir()
        (torn / fleetctl.COST_MODEL_FILE).write_text("{torn")
        bad = tmp_path / "bad"
        bad.mkdir()
        (bad / fleetctl.COST_MODEL_FILE).write_text(
            json.dumps({"format": 99})
        )
        assert fleetctl.read_cost_models([str(tmp_path / "absent")]) is None
        plan = fleetctl.read_cost_models([str(torn), str(bad)])
        assert plan["sidecars"] == 0 and plan["unreadable"] == 2

    def test_status_cli_plan_flag(self, tmp_path, capsys):
        _commit(tmp_path)
        self._write_model(
            tmp_path / "run",
            observations={"prefetch=2@uniform": {"cost": 4.0, "n": 1}},
            drift=[{"policy": "prefetch", "action": "2",
                    "signature": "uniform", "predicted": 1.0,
                    "realized": 4.0}],
        )
        status = fleetctl.fleet_status(str(tmp_path))
        assert status["plan"] is None  # only when asked, like --block-dir
        assert fleetctl.main(
            ["status", str(tmp_path), "--json",
             "--plan", str(tmp_path / "run")]
        ) == 0
        status = json.loads(capsys.readouterr().out)
        assert status["plan"]["sidecars"] == 1
        assert status["plan"]["drifted_total"] == 1
        text = fleetctl._format_status(status)
        assert "plan cost models: 1 sidecars" in text
        assert "prefetch/2@uniform(err=300%)" in text


class TestCli:
    def test_refusal_exits_2_and_writes_nothing(self, tmp_path, capsys):
        _commit(tmp_path)
        rc = fleetctl.main(
            ["declare-lost-hosts", str(tmp_path), "--hosts", "9"]
        )
        assert rc == 2
        assert "refused" in capsys.readouterr().err
        assert not (tmp_path / elastic.LOST_HOSTS_FILE).exists()

    def test_declare_and_status_round_trip(self, tmp_path, capsys):
        _commit(tmp_path)
        assert fleetctl.main(
            ["declare-lost-hosts", str(tmp_path), "--hosts", "1,2",
             "--reason", "drill"]
        ) == 0
        assert "declared lost" in capsys.readouterr().out
        assert fleetctl.main(["status", str(tmp_path), "--json"]) == 0
        status = json.loads(capsys.readouterr().out)
        assert status["lost_hosts_request"]["hosts"] == [1, 2]

    def test_scale_up_cli(self, tmp_path, capsys):
        _commit(tmp_path)
        assert fleetctl.main(
            ["request-scale-up", str(tmp_path), "--add", "4:0,5:1"]
        ) == 0
        assert "scale-up requested" in capsys.readouterr().out
        payload = json.loads(
            (tmp_path / elastic.SCALE_REQUEST_FILE).read_text()
        )
        assert payload["add"] == {"4": 0, "5": 1}
