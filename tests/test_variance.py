"""Coefficient-variance computation and persistence.

Reference spec: GeneralizedLinearOptimizationProblem variance = element-wise
1 / Hessian-diagonal at the optimum
(LogisticRegressionOptimizationProblem.scala:109-124), back-transformed
through normalization (NormalizationContext.scala:72-90), persisted in
BayesianLinearModelAvro's variances list.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import OptimizerType, TaskType


def _logistic_batch(n=800, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32) * 0.5
    y = (1 / (1 + np.exp(-(x @ w))) > rng.random(n)).astype(np.float32)
    return (
        GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
            jnp.zeros((n,)), jnp.ones((n,)),
        ),
        x, y,
    )


def test_variance_is_inverse_hessian_diagonal():
    """variances == 1/diag(H) with H computed independently in numpy:
    H_jj = sum_i w_i * s_i (1 - s_i) x_ij^2 + lambda (logistic, L2)."""
    lam = 0.7
    batch, x, y = _logistic_batch()
    prob = GLMOptimizationProblem(
        TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=100, tolerance=1e-9),
        RegularizationContext.l2(lam),
        compute_variance=True,
    )
    model, _ = prob.run(batch, NormalizationContext.identity())
    w = np.asarray(model.coefficients.means, np.float64)
    s = 1 / (1 + np.exp(-(x.astype(np.float64) @ w)))
    h_diag = np.sum((s * (1 - s))[:, None] * x.astype(np.float64) ** 2, axis=0) + lam
    np.testing.assert_allclose(
        np.asarray(model.coefficients.variances), 1.0 / h_diag, rtol=2e-3
    )


def test_variance_linear_task():
    """Linear regression: H = X^T X + lambda I exactly (loss curvature 1)."""
    lam = 1.5
    rng = np.random.default_rng(3)
    n, d = 300, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ np.asarray([1.0, -1.0, 0.5], np.float32)).astype(np.float32)
    batch = GLMBatch(
        DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
        jnp.zeros((n,)), jnp.ones((n,)),
    )
    prob = GLMOptimizationProblem(
        TaskType.LINEAR_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=60, tolerance=1e-9),
        RegularizationContext.l2(lam),
        compute_variance=True,
    )
    model, _ = prob.run(batch, NormalizationContext.identity())
    h_diag = np.sum(x.astype(np.float64) ** 2, axis=0) + lam
    np.testing.assert_allclose(
        np.asarray(model.coefficients.variances), 1.0 / h_diag, rtol=1e-3
    )


def test_variance_through_driver_with_normalization(tmp_path):
    """--compute-variance true through the staged GLM driver with
    STANDARDIZATION: variances come back in RAW feature space
    (back-transform var * factor^2, NormalizationContext.scala:72-90)."""
    from photon_ml_tpu.cli import glm_driver

    data = "/root/reference/photon-ml/src/integTest/resources/DriverIntegTest/input"
    driver = glm_driver.main([
        "--training-data-directory", os.path.join(data, "heart.avro"),
        "--validating-data-directory", os.path.join(data, "heart_validation.avro"),
        "--output-directory", str(tmp_path / "out"),
        "--task", "LOGISTIC_REGRESSION",
        "--regularization-weights", "1",
        "--normalization-type", "STANDARDIZATION",
        "--compute-variance", "true",
        "--delete-output-dirs-if-exist", "true",
    ])
    variances = driver.best_model.coefficients.variances
    assert variances is not None
    v = np.asarray(variances)
    assert v.shape == np.asarray(driver.best_model.coefficients.means).shape
    assert (v > 0).all() and np.isfinite(v).all()


def test_variance_roundtrips_through_avro_model_layout(tmp_path):
    """Variances persist in BayesianLinearModelAvro records through the
    fixed-effect save/load layout (the reference's means+variances lists)."""
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_fixed_effect, save_fixed_effect

    imap = IndexMap.build(["f0", "f1"], add_intercept=True)
    d = len(imap)
    means = np.arange(1.0, d + 1)
    variances = 0.1 * np.arange(1.0, d + 1)
    save_fixed_effect(
        str(tmp_path), "fixed", TaskType.LOGISTIC_REGRESSION, means, imap,
        variances=variances,
    )
    got_means, got_vars, task, shard = load_fixed_effect(
        str(tmp_path), "fixed", imap
    )
    np.testing.assert_allclose(got_means, means)
    np.testing.assert_allclose(got_vars, variances)
    assert task == TaskType.LOGISTIC_REGRESSION
