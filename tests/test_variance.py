"""Coefficient-variance computation and persistence.

Reference spec: GeneralizedLinearOptimizationProblem variance = element-wise
1 / Hessian-diagonal at the optimum
(LogisticRegressionOptimizationProblem.scala:109-124), back-transformed
through normalization (NormalizationContext.scala:72-90), persisted in
BayesianLinearModelAvro's variances list.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import OptimizerType, TaskType


def _logistic_batch(n=800, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32) * 0.5
    y = (1 / (1 + np.exp(-(x @ w))) > rng.random(n)).astype(np.float32)
    return (
        GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
            jnp.zeros((n,)), jnp.ones((n,)),
        ),
        x, y,
    )


def test_variance_is_inverse_hessian_diagonal():
    """variances == 1/diag(H) with H computed independently in numpy:
    H_jj = sum_i w_i * s_i (1 - s_i) x_ij^2 + lambda (logistic, L2)."""
    lam = 0.7
    batch, x, y = _logistic_batch()
    prob = GLMOptimizationProblem(
        TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=100, tolerance=1e-9),
        RegularizationContext.l2(lam),
        compute_variance=True,
    )
    model, _ = prob.run(batch, NormalizationContext.identity())
    w = np.asarray(model.coefficients.means, np.float64)
    s = 1 / (1 + np.exp(-(x.astype(np.float64) @ w)))
    h_diag = np.sum((s * (1 - s))[:, None] * x.astype(np.float64) ** 2, axis=0) + lam
    np.testing.assert_allclose(
        np.asarray(model.coefficients.variances), 1.0 / h_diag, rtol=2e-3
    )


def test_variance_linear_task():
    """Linear regression: H = X^T X + lambda I exactly (loss curvature 1)."""
    lam = 1.5
    rng = np.random.default_rng(3)
    n, d = 300, 3
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ np.asarray([1.0, -1.0, 0.5], np.float32)).astype(np.float32)
    batch = GLMBatch(
        DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
        jnp.zeros((n,)), jnp.ones((n,)),
    )
    prob = GLMOptimizationProblem(
        TaskType.LINEAR_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=60, tolerance=1e-9),
        RegularizationContext.l2(lam),
        compute_variance=True,
    )
    model, _ = prob.run(batch, NormalizationContext.identity())
    h_diag = np.sum(x.astype(np.float64) ** 2, axis=0) + lam
    np.testing.assert_allclose(
        np.asarray(model.coefficients.variances), 1.0 / h_diag, rtol=1e-3
    )


def _synthetic_training_avro(path, n, d, seed):
    """heart.avro-shaped TRAINING_EXAMPLE container (the reference fixture
    is not mounted in every environment; the driver path under test —
    staged GLM + STANDARDIZATION + variance back-transform — only needs a
    dense labeled avro set with non-unit feature scales)."""
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io import schemas

    rng = np.random.default_rng(seed)
    scales = 10.0 ** rng.uniform(-1, 2, size=d)
    x = rng.normal(size=(n, d)) * scales
    w = rng.normal(size=d) / np.maximum(scales, 1e-6)
    y = (1 / (1 + np.exp(-(x @ w))) > rng.random(n)).astype(np.float32)

    def recs():
        for i in range(n):
            yield {
                "uid": str(i),
                "label": float(y[i]),
                "features": [
                    {"name": f"f{j}", "term": "", "value": float(x[i, j])}
                    for j in range(d)
                ],
                "metadataMap": None,
                "weight": None,
                "offset": None,
            }

    avro_io.write_container(str(path), recs(), schemas.TRAINING_EXAMPLE)


@pytest.mark.slow  # ~19s full staged GLM driver run; tier-1 siblings keep the contract: test_variance_is_inverse_hessian_diagonal / test_variance_linear_task pin the math, test_variance_roundtrips_through_avro_model_layout pins persistence
def test_variance_through_driver_with_normalization(tmp_path):
    """--compute-variance true through the staged GLM driver with
    STANDARDIZATION: variances come back in RAW feature space
    (back-transform var * factor^2, NormalizationContext.scala:72-90)."""
    from photon_ml_tpu.cli import glm_driver

    data = "/root/reference/photon-ml/src/integTest/resources/DriverIntegTest/input"
    if not os.path.isdir(data):
        # reference fixtures not mounted: drive the identical flag surface
        # over synthetic heart-shaped data instead of skipping the path
        data = str(tmp_path / "input")
        os.makedirs(data)
        _synthetic_training_avro(os.path.join(data, "heart.avro"), 300, 6, 0)
        _synthetic_training_avro(
            os.path.join(data, "heart_validation.avro"), 120, 6, 1
        )
    driver = glm_driver.main([
        "--training-data-directory", os.path.join(data, "heart.avro"),
        "--validating-data-directory", os.path.join(data, "heart_validation.avro"),
        "--output-directory", str(tmp_path / "out"),
        "--task", "LOGISTIC_REGRESSION",
        "--regularization-weights", "1",
        "--normalization-type", "STANDARDIZATION",
        "--compute-variance", "true",
        "--delete-output-dirs-if-exist", "true",
    ])
    variances = driver.best_model.coefficients.variances
    assert variances is not None
    v = np.asarray(variances)
    assert v.shape == np.asarray(driver.best_model.coefficients.means).shape
    assert (v > 0).all() and np.isfinite(v).all()


def test_variance_roundtrips_through_avro_model_layout(tmp_path):
    """Variances persist in BayesianLinearModelAvro records through the
    fixed-effect save/load layout (the reference's means+variances lists)."""
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_fixed_effect, save_fixed_effect

    imap = IndexMap.build(["f0", "f1"], add_intercept=True)
    d = len(imap)
    means = np.arange(1.0, d + 1)
    variances = 0.1 * np.arange(1.0, d + 1)
    save_fixed_effect(
        str(tmp_path), "fixed", TaskType.LOGISTIC_REGRESSION, means, imap,
        variances=variances,
    )
    got_means, got_vars, task, shard = load_fixed_effect(
        str(tmp_path), "fixed", imap
    )
    np.testing.assert_allclose(got_means, means)
    np.testing.assert_allclose(got_vars, variances)
    assert task == TaskType.LOGISTIC_REGRESSION


def _glmix_small(seed=11):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from game_test_utils import make_glmix_data

    rng = np.random.default_rng(seed)
    return make_glmix_data(
        rng, num_users=10, rows_per_user_range=(15, 30), d_fixed=4, d_random=3
    )


@pytest.mark.slow  # ~19s (per-entity numpy Hessians); the inverse-Hessian-diagonal contract itself stays tier-1 in test_variance_is_inverse_hessian_diagonal / test_variance_linear_task
def test_random_effect_per_entity_variance_vs_numpy():
    """coefficient_variances == 1/diag(H_e) per entity, H_e computed
    independently in numpy over that entity's own rows."""
    from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
    from photon_ml_tpu.data.game import (
        RandomEffectDataConfig,
        build_random_effect_dataset,
    )

    lam = 0.4
    data, truth = _glmix_small()
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfig("userId", "per_user")
    )
    coord = RandomEffectCoordinate(
        ds, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=60, tolerance=1e-9),
        RegularizationContext.l2(lam),
    )
    resid = jnp.zeros((data.num_rows,))
    coefs, _ = coord.update(resid, coord.initial_coefficients())
    var = np.asarray(coord.coefficient_variances(coefs, resid))
    assert var.shape == (ds.num_entities, ds.local_dim)

    # independent oracle for one entity: rows of user u in original order
    x_all = truth["x_random"].astype(np.float64)
    user_of_row = truth["user_of_row"]
    vocab_idx = {raw: i for i, raw in enumerate(data.id_vocabs["userId"])}
    entity_pos = np.asarray(ds.entity_pos)
    w_all = np.asarray(coord.global_coefficients(coefs), np.float64)
    checked = 0
    for u in range(3):
        rows = np.where(user_of_row == u)[0]
        # tensor position of this user's model
        tp = entity_pos[rows[0]]
        if tp < 0:
            continue
        xu = x_all[rows]
        wu = w_all[tp]
        s = 1 / (1 + np.exp(-(xu @ wu)))
        h = np.sum((s * (1 - s))[:, None] * xu**2, axis=0) + lam
        # local_to_global maps local dims; here dims are identity-ordered
        np.testing.assert_allclose(var[tp], 1.0 / h, rtol=5e-3)
        checked += 1
    assert checked >= 2


@pytest.mark.slow  # ~24s full GAME driver run; the RE variance math stays tier-1 via test_random-effect siblings and the avro round trip via test_variance_roundtrips_through_avro_model_layout
def test_game_driver_persists_re_variances(tmp_path):
    """--compute-variance true through the GAME driver: BOTH the fixed and
    the per-entity random-effect avro records carry variances, and they
    round-trip through load_random_effect."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_game_drivers import COMMON_FLAGS, _write_game_avro
    from game_test_utils import make_glmix_data
    from photon_ml_tpu.cli import game_training_driver
    from photon_ml_tpu.io import model_io

    rng = np.random.default_rng(5)
    gd, truth = make_glmix_data(
        rng, num_users=8, rows_per_user_range=(20, 30), d_fixed=4, d_random=3
    )
    data = {
        "y": gd.response,
        "x_fixed": truth["x_fixed"],
        "x_random": truth["x_random"],
        "user_raw": [gd.id_vocabs["userId"][i] for i in gd.ids["userId"]],
    }
    base = tmp_path / "game"
    (base / "train").mkdir(parents=True)
    _write_game_avro(str(base / "train" / "part-0.avro"), data, range(gd.num_rows))

    out = str(base / "out")
    driver = game_training_driver.main([
        "--train-input-dirs", str(base / "train"),
        "--output-dir", out,
        "--num-iterations", "2",
        "--compute-variance", "true",
    ] + COMMON_FLAGS)

    imap = driver.shard_index_maps["per_user"]
    variances = {}
    means, task, re_id, shard = model_io.load_random_effect(
        os.path.join(out, "best"), "per-user", imap, variances_out=variances
    )
    assert means and variances, "RE records must carry variances"
    assert set(variances) == set(means)
    for eid, v in variances.items():
        vv = v[v != 0]
        assert (vv > 0).all() and np.isfinite(vv).all()

    fe_imap = driver.shard_index_maps["global"]
    _, fe_vars, _, _ = model_io.load_fixed_effect(
        os.path.join(out, "best"), "fixed", fe_imap
    )
    assert fe_vars is not None and (np.asarray(fe_vars) > 0).any()
