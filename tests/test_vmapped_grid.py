"""Traced-lambda grid coordinate descent (CoordinateDescent.run_grid):
one compiled cycle serves every combo, matching per-combo descents
exactly. (The batched G-lane vmapped variant was removed after losing
every measured race, VERDICT r4 #9; the reference re-runs the whole
driver per grid combo, cli/game/training/Driver.scala:330-337.)
"""

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.algorithm import (
    CoordinateDescent,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.data.game import (
    RandomEffectDataConfig,
    build_fixed_effect_batch,
    build_random_effect_dataset,
)
from photon_ml_tpu.evaluation.evaluators import EvaluatorType, evaluator_for
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import OptimizerType, TaskType

from game_test_utils import make_glmix_data


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(21)
    data, _ = make_glmix_data(
        rng, num_users=12, rows_per_user_range=(15, 35), d_fixed=5, d_random=3
    )
    labels = jnp.asarray(data.response)
    loss_fn = lambda s: jnp.sum(losses.logistic.loss(s, labels))
    return data, labels, loss_fn


def _coords(data, fe_lam, re_lam):
    fixed = FixedEffectCoordinate(
        build_fixed_effect_batch(data, "global", dense=True),
        GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=25, tolerance=1e-8),
            RegularizationContext.l2(fe_lam),
        ),
    )
    random = RandomEffectCoordinate(
        build_random_effect_dataset(
            data, RandomEffectDataConfig("userId", "per_user")
        ),
        TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=20, tolerance=1e-7),
        RegularizationContext.l2(re_lam),
    )
    return {"fixed": fixed, "random": random}


@pytest.mark.slow  # ~15s: the grid-vs-sequential contract stays tier-1 via test_game_drivers.py TestVmappedGrid::test_vmapped_grid_matches_sequential and test_grid_warm_start_reaches_same_optima here
def test_grid_matches_sequential_runs(setup):
    data, labels, loss_fn = setup
    n = data.num_rows
    fe_lams = [0.01, 0.1, 1.0]
    re_lams = [0.05, 0.5, 5.0]

    # vmapped grid: base coordinates at combo-0 lambdas, overridden per lane
    cd = CoordinateDescent(_coords(data, fe_lams[0], re_lams[0]), loss_fn)
    grid_results = cd.run_grid(
        {"fixed": jnp.asarray(fe_lams), "random": jnp.asarray(re_lams)},
        num_iterations=2, num_rows=n,
    )
    assert len(grid_results) == 3

    for g, (fl, rl) in enumerate(zip(fe_lams, re_lams)):
        seq = CoordinateDescent(_coords(data, fl, rl), loss_fn).run(
            num_iterations=2, num_rows=n
        )
        np.testing.assert_allclose(
            np.asarray(grid_results[g].objective_history),
            np.asarray(seq.objective_history),
            rtol=1e-4,
        )
        for name in ("fixed", "random"):
            np.testing.assert_allclose(
                np.asarray(grid_results[g].coefficients[name]),
                np.asarray(seq.coefficients[name]),
                rtol=2e-3, atol=2e-4,
            )
        np.testing.assert_allclose(
            np.asarray(grid_results[g].total_scores),
            np.asarray(seq.total_scores),
            rtol=2e-3, atol=2e-3,
        )


def test_grid_validation_evaluators(setup):
    data, labels, loss_fn = setup
    n = data.num_rows
    # validation = training data here (wiring test, not generalization)
    auc = evaluator_for(EvaluatorType.AUC)
    cd = CoordinateDescent(
        _coords(data, 0.01, 0.1), loss_fn,
        validation_scorer=lambda params: sum(
            cd_coords[name].score(params[name]) for name in cd_coords
        ),
        validation_evaluators={"AUC": (auc, {"labels": labels})},
    )
    cd_coords = cd.coordinates
    results = cd.run_grid(
        {"fixed": jnp.asarray([0.01, 10.0]), "random": jnp.asarray([0.1, 10.0])},
        num_iterations=1, num_rows=n,
    )
    # 2 updates per iteration -> 2 validation entries each
    for r in results:
        assert len(r.validation_history) == 2
        assert 0.4 < r.validation_history[-1]["AUC"] <= 1.0
    # the lightly-regularized combo must fit better than lambda=10
    assert (
        results[0].validation_history[-1]["AUC"]
        > results[1].validation_history[-1]["AUC"]
    )


def test_grid_rejects_unsupported_coordinates(setup):
    data, labels, loss_fn = setup

    class NoGridCoord:
        def initial_coefficients(self):
            return jnp.zeros((3,))

        def update(self, off, w0):  # no reg_weight
            return w0, None

        def score(self, w):
            return jnp.zeros((10,))

        def regularization_term(self, w):
            return jnp.asarray(0.0)

    cd = CoordinateDescent({"c": NoGridCoord()}, loss_fn)
    with pytest.raises(ValueError, match="reg_weight"):
        cd.run_grid({"c": jnp.asarray([1.0])}, num_iterations=1, num_rows=10)


def test_grid_shape_validation(setup):
    data, labels, loss_fn = setup
    cd = CoordinateDescent(_coords(data, 0.1, 0.1), loss_fn)
    with pytest.raises(ValueError, match="keys"):
        cd.run_grid({"fixed": jnp.asarray([1.0])}, 1, data.num_rows)
    with pytest.raises(ValueError, match=r"\(G,\)"):
        cd.run_grid(
            {"fixed": jnp.asarray([1.0, 2.0]), "random": jnp.asarray([1.0])},
            1, data.num_rows,
        )


def test_grid_warm_start_reaches_same_optima(setup):
    """init_params warm-starts every lane from a shared point; final
    objectives must land at the same optima (different path). Since the
    score-seeding fix (run_grid now mirrors run(initial_params=...): a
    warm-started coordinate contributes its CURRENT scores from step zero
    instead of training the first cycle against zero offsets), the warm
    trajectory genuinely diverges from cold early on — so the bound is
    'same optimum to ~1e-3 and never worse', not trajectory equality."""
    data, labels, loss_fn = setup
    coords = _coords(data, 0.1, 0.1)
    cd = CoordinateDescent(coords, loss_fn)
    lam = {"fixed": jnp.asarray([0.05, 0.5]), "random": jnp.asarray([0.1, 0.1])}
    cold = cd.run_grid(lam, num_iterations=2, num_rows=data.num_rows)
    pre = cd.run_grid(
        {"fixed": jnp.asarray([0.5]), "random": jnp.asarray([0.1])},
        num_iterations=1, num_rows=data.num_rows,
    )
    warm = cd.run_grid(
        lam, num_iterations=2, num_rows=data.num_rows,
        init_params=pre[0].coefficients,
    )
    for c, w in zip(cold, warm):
        assert w.objective_history[-1] == pytest.approx(
            c.objective_history[-1], rel=2e-3
        )
        # a correctly-seeded warm start must never END worse than cold
        assert w.objective_history[-1] <= c.objective_history[-1] * (1 + 1e-4)
    # the seeding itself: the warm grid's FIRST objective must reflect the
    # warm model's scores, not a zero-offset cold start
    assert warm[0].objective_history[0] < cold[0].objective_history[0] * 1.5
