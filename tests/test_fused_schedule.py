"""On-device whole-cycle compaction (optim/fused_schedule.py).

The load-bearing claims, pinned BITWISE:

  * the fused device loop — chunk→compact→resume inside one
    ``lax.while_loop`` per ladder rung — equals the host chunk loop AND
    the one-shot kernel bit for bit (LBFGS / OWL-QN / TRON), with the
    same executed-lane-iteration count as the host loop;
  * host dispatches per solve are O(#rungs): one ChunkRecord per rung
    hop, widths strictly decreasing, with the in-program chunk count on
    the new ``SolveRecord.device_chunks`` ledger field;
  * preemption at the ``"rung"`` site snapshots the same
    ``kind="scheduler"`` carried pytree the host loop emits, and the
    snapshot resumes bitwise on EITHER loop;
  * the ``optim.device_drain`` fault site degrades the solve to the host
    chunk loop — results stay bitwise, and the next solve is fused again.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from game_test_utils import make_glmix_data

from photon_ml_tpu.algorithm.random_effect import (
    RandomEffectCoordinate,
    entity_lane_fns,
)
from photon_ml_tpu.compile import ShapeBucketer
from photon_ml_tpu.data.game import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.optim import fused_schedule
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.scheduler import (
    SolveSchedule,
    compacted_solve,
    resolve_schedule,
    solve_stats,
)
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.resilience import faults, preemption
from photon_ml_tpu.types import OptimizerType, TaskType

pytestmark = pytest.mark.compaction


def assert_results_bitwise(a, b):
    for name, x, y in zip(a._fields, a, b):
        if x is None or y is None:
            assert x is y, name
            continue
        assert np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True), name


def skewed_lane_problem(rng, E=40, M=10, D=4, hard=4):
    """A few ill-conditioned lanes among many easy ones."""
    x = rng.normal(size=(E, M, D)).astype(np.float32)
    x[:hard] *= np.geomspace(1.0, 32.0, D).astype(np.float32)
    w_true = (rng.normal(size=(E, D)) * 0.5).astype(np.float32)
    z = np.einsum("emd,ed->em", x.astype(np.float64), w_true)
    y = (1.0 / (1.0 + np.exp(-z)) > rng.random((E, M))).astype(np.float32)
    data = tuple(
        jnp.asarray(a)
        for a in (x, y, np.zeros((E, M), np.float32), np.ones((E, M), np.float32))
    )
    return data, jnp.zeros((E, D), jnp.float32)


# ---------------------------------------------------------------------------
# rung ladder geometry
# ---------------------------------------------------------------------------


class TestRungLadder:
    def test_ladder_is_full_width_then_descending_rungs(self):
        b = ShapeBucketer()  # base 8, growth 2: 8, 16, 32, 64, ...
        assert fused_schedule.rung_ladder(b, 40) == [40, 32, 16, 8]
        assert fused_schedule.rung_ladder(b, 8) == [8]
        assert fused_schedule.rung_ladder(b, 5) == [5]
        assert fused_schedule.rung_ladder(b, 64) == [64, 32, 16, 8]

    def test_next_lower_rung(self):
        b = ShapeBucketer()
        assert fused_schedule.next_lower_rung(b, 64) == 32
        assert fused_schedule.next_lower_rung(b, 40) == 32
        assert fused_schedule.next_lower_rung(b, 16) == 8
        assert fused_schedule.next_lower_rung(b, 8) == 0
        assert fused_schedule.next_lower_rung(b, 3) == 0

    def test_hop_targets_guarantee_progress(self):
        # target < rung for every ladder width => every dispatch retires
        # at least one chunk, so the hop loop terminates
        b = ShapeBucketer()
        for lanes in (3, 8, 9, 40, 64, 513):
            for rung in fused_schedule.rung_ladder(b, lanes):
                assert fused_schedule.next_lower_rung(b, rung) < rung


# ---------------------------------------------------------------------------
# bitwise: device loop == host loop == one-shot
# ---------------------------------------------------------------------------


class TestDeviceSolveBitwise:
    @pytest.mark.parametrize(
        "optimizer,reg",
        [
            (OptimizerType.LBFGS, RegularizationContext.l2(0.5)),
            pytest.param(
                OptimizerType.LBFGS,
                RegularizationContext.elastic_net(0.3, 0.5),
                # ~5s of OWL-QN rung-program compiles; tier-1 keeps the
                # LBFGS + TRON device pins here, and the OWL-QN chunked
                # vs one-shot pin in test_scheduler.py covers the l1
                # kernel's resumability — the device loop advances lanes
                # through that same kernel
                marks=pytest.mark.slow,
            ),
            (OptimizerType.TRON, RegularizationContext.l2(0.5)),
        ],
        ids=["lbfgs-l2", "owlqn-l1", "tron"],
    )
    def test_bitwise_vs_one_shot_and_host_loop(self, rng, optimizer, reg):
        data, w0 = skewed_lane_problem(rng)
        cfg = (
            OptimizerConfig(max_iterations=25, tolerance=1e-6)
            if optimizer == OptimizerType.TRON
            else OptimizerConfig(max_iterations=60, tolerance=1e-7)
        )
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=optimizer,
            optimizer_config=cfg,
            regularization=reg,
        )
        solve_one, *_ = entity_lane_fns(**kw)
        one = jax.jit(jax.vmap(solve_one))(*data, w0)
        solve_stats.reset()
        host = compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=5), label="host", **kw
        )
        dev = compacted_solve(
            data, w0,
            schedule=SolveSchedule(chunk_size=5, loop="device"),
            label="dev", **kw,
        )
        assert_results_bitwise(host, one)
        assert_results_bitwise(dev, one)
        assert_results_bitwise(dev, host)
        # re-batching changes WHICH lanes burn iterations, never any
        # lane's arithmetic — so the two ledgers agree exactly
        rec_host, rec_dev = solve_stats.snapshot()[-2:]
        assert rec_host.label == "host" and rec_dev.label == "dev"
        assert rec_dev.executed == rec_host.executed
        assert rec_dev.saved == rec_host.saved

    def test_dispatches_are_o_rungs(self, rng):
        data, w0 = skewed_lane_problem(rng, E=40, hard=4)
        # same config as the bitwise pin above: the chunk executables are
        # already warm, so this test only pays for its assertions
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(max_iterations=60, tolerance=1e-7),
            regularization=RegularizationContext.l2(0.5),
        )
        solve_stats.reset()
        compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=5), label="host", **kw
        )
        compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=5, loop="device"),
            label="dev", **kw,
        )
        rec_host, rec_dev = solve_stats.snapshot()[-2:]
        # the host loop pays one dispatch per chunk boundary; the device
        # loop pays one per rung hop, bounded by the ladder depth
        ladder = fused_schedule.rung_ladder(SolveSchedule().bucketer, 40)
        assert rec_dev.dispatches <= len(ladder)
        assert rec_dev.dispatches < rec_host.dispatches
        widths = [c.batch_lanes for c in rec_dev.chunks]
        assert widths == sorted(widths, reverse=True)
        assert len(set(widths)) == len(widths)  # strictly decreasing
        # the in-program chunk count rides the device ledger; the host
        # loop's chunk iterations all count as dispatches instead
        assert rec_dev.device_chunks >= rec_dev.dispatches
        assert rec_host.device_chunks == 0
        totals = solve_stats.totals()
        assert totals["device_chunk_iterations"] == rec_dev.device_chunks
        assert totals["chunk_dispatches"] == (
            rec_host.dispatches + rec_dev.dispatches
        )

    def test_rung_programs_reuse_compiled_executables(self, rng):
        from photon_ml_tpu.compile import compile_stats

        data, w0 = skewed_lane_problem(rng, E=40, hard=4)
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(max_iterations=60, tolerance=1e-7),
            regularization=RegularizationContext.l2(0.5),
        )
        schedule = SolveSchedule(chunk_size=5, loop="device")
        compacted_solve(data, w0, schedule=schedule, label="warm", **kw)
        before = compile_stats.traces_of("scheduler.rung")
        compacted_solve(data, w0, schedule=schedule, label="reuse", **kw)
        assert compile_stats.traces_of("scheduler.rung") == before, (
            "scheduler.rung recompiled on an identical warm solve"
        )


# ---------------------------------------------------------------------------
# schedule spellings
# ---------------------------------------------------------------------------


class TestDeviceSpellings:
    def test_resolve_schedule_device_spellings(self, monkeypatch):
        d = resolve_schedule("device")
        assert d.loop == "device"
        assert d.chunk_size == SolveSchedule().chunk_size
        d12 = resolve_schedule("device:12")
        assert (d12.loop, d12.chunk_size) == ("device", 12)
        assert "loop=device" in d12.describe()
        assert "loop" not in SolveSchedule().describe()
        with pytest.raises(ValueError, match="off"):
            resolve_schedule("device:off")
        with pytest.raises(ValueError):
            resolve_schedule("device:sideways")
        monkeypatch.setenv("PHOTON_SOLVE_CHUNK", "device:7")
        env = resolve_schedule(None)
        assert (env.loop, env.chunk_size) == ("device", 7)

    def test_schedule_rejects_unknown_loop(self):
        with pytest.raises(ValueError, match="'host' or 'device'"):
            SolveSchedule(loop="gpu")


# ---------------------------------------------------------------------------
# preemption: drain at the rung boundary, resume on either loop
# ---------------------------------------------------------------------------


class TestRungPreemption:
    @pytest.fixture(autouse=True)
    def _clean_preemption(self):
        yield
        preemption.reset()

    def test_rung_preempt_snapshots_and_resumes_on_either_loop(self, rng):
        data, w0 = skewed_lane_problem(rng)
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(max_iterations=60, tolerance=1e-7),
            regularization=RegularizationContext.l2(0.5),
        )
        dev = SolveSchedule(chunk_size=5, loop="device")
        clean = compacted_solve(data, w0, schedule=dev, label="clean", **kw)

        preemption.install_plan({"rung": 1})
        with pytest.raises(preemption.Preempted) as ei:
            compacted_solve(data, w0, schedule=dev, label="interrupted", **kw)
        assert ei.value.site == "rung"
        partial = ei.value.partial
        assert partial["meta"]["kind"] == "scheduler"
        assert 0 < partial["meta"]["limit"] < kw["optimizer_config"].max_iterations

        preemption.reset()
        resumed_dev = compacted_solve(
            data, w0, schedule=dev, label="resumed-dev", resume=partial, **kw
        )
        assert_results_bitwise(resumed_dev, clean)
        # the snapshot is the host loop's kind="scheduler" contract: a
        # device-loop drain resumes on the HOST loop too, bitwise
        resumed_host = compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=5),
            label="resumed-host", resume=partial, **kw,
        )
        assert_results_bitwise(resumed_host, clean)

    def test_host_chunk_preempt_resumes_on_device_loop(self, rng):
        data, w0 = skewed_lane_problem(rng)
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(max_iterations=60, tolerance=1e-7),
            regularization=RegularizationContext.l2(0.5),
        )
        clean = compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=5), label="clean", **kw
        )
        preemption.install_plan({"chunk": 2})
        with pytest.raises(preemption.Preempted) as ei:
            compacted_solve(
                data, w0, schedule=SolveSchedule(chunk_size=5),
                label="interrupted", **kw,
            )
        preemption.reset()
        resumed = compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=5, loop="device"),
            label="resumed", resume=ei.value.partial, **kw,
        )
        assert_results_bitwise(resumed, clean)


# ---------------------------------------------------------------------------
# chaos: the optim.device_drain fault site degrades to the host loop
# ---------------------------------------------------------------------------


@pytest.mark.faults
class TestChaosDegrade:
    def test_device_drain_fault_degrades_to_host_loop(self, rng):
        data, w0 = skewed_lane_problem(rng)
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(max_iterations=60, tolerance=1e-7),
            regularization=RegularizationContext.l2(0.5),
        )
        dev = SolveSchedule(chunk_size=5, loop="device")
        host_res = compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=5), label="host", **kw
        )
        solve_stats.reset()
        with faults.fault_scope(faults.FaultPlan(
            [faults.FaultSpec("optim.device_drain", at=1)]
        )):
            degraded = compacted_solve(
                data, w0, schedule=dev, label="degraded", **kw
            )
        assert_results_bitwise(degraded, host_res)
        assert solve_stats.snapshot()[-1].device_chunks == 0  # ran on host
        # the NEXT solve (fault plan gone) is fused again
        fused = compacted_solve(data, w0, schedule=dev, label="refused", **kw)
        assert_results_bitwise(fused, host_res)
        assert solve_stats.snapshot()[-1].device_chunks > 0


# ---------------------------------------------------------------------------
# coordinate wiring: one-shot / bucketed / streaming vs the device loop
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def glmix():
    rng = np.random.default_rng(77)
    data, _ = make_glmix_data(
        rng, num_users=40, rows_per_user_range=(3, 30), d_fixed=4, d_random=3
    )
    return data


class TestCoordinateWiring:
    def test_random_effect_coordinate_device_bitwise(self, glmix):
        ds = build_random_effect_dataset(
            glmix, RandomEffectDataConfig("userId", "per_user")
        )
        kw = dict(
            dataset=ds,
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            regularization=RegularizationContext.l2(0.1),
        )
        plain = RandomEffectCoordinate(**kw)
        dev = RandomEffectCoordinate(
            **kw, solve_schedule=SolveSchedule(chunk_size=6, loop="device")
        )
        assert dev.cd_jit is False
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        w_plain, res_plain = jax.jit(plain.update)(
            resid, plain.initial_coefficients()
        )
        w_dev, res_dev = dev.update(resid, dev.initial_coefficients())
        assert np.array_equal(np.asarray(w_plain), np.asarray(w_dev))
        assert_results_bitwise(res_dev, jax.tree.map(jnp.asarray, res_plain))
        assert np.array_equal(
            np.asarray(plain.score(w_plain)), np.asarray(dev.score(w_dev))
        )

    @pytest.mark.slow  # ~15s of per-bucket chunk kernels; tier-1 pins the
    # same composition via the RE-coordinate device test above plus the
    # host-loop bucketed pin in test_scheduler.py — the device loop enters
    # through the identical compacted_solve seam in all three
    def test_bucketed_coordinate_device_bitwise(self, glmix):
        from photon_ml_tpu.algorithm.bucketed_random_effect import (
            BucketedRandomEffectCoordinate,
        )

        cfg = RandomEffectDataConfig("userId", "per_user")
        kw = dict(
            data=glmix,
            config=cfg,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization=RegularizationContext.l2(0.2),
        )
        host = BucketedRandomEffectCoordinate(
            **kw, solve_schedule=SolveSchedule(chunk_size=6)
        )
        dev = BucketedRandomEffectCoordinate(
            **kw,
            bundle=host.bundle,  # share the built stacks
            solve_schedule=SolveSchedule(chunk_size=6, loop="device"),
        )
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        st_host, _ = host.update(resid, host.initial_coefficients())
        st_dev, _ = dev.update(resid, dev.initial_coefficients())
        for a, b in zip(st_host, st_dev):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow  # ~4s of per-block rung compiles; tier-1 pins this
    # seam via the RE-coordinate device test above plus the host-loop
    # streaming pin in test_scheduler.py — streaming blocks call the same
    # compacted_solve the plain coordinate does
    def test_streaming_coordinate_device_bitwise(self, glmix, tmp_path):
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            StreamingRandomEffectCoordinate,
            write_re_entity_blocks,
        )

        manifest = write_re_entity_blocks(
            glmix,
            RandomEffectDataConfig("userId", "per_user"),
            str(tmp_path / "blocks"),
            block_entities=16,
        )
        kw = dict(
            manifest=manifest,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization=RegularizationContext.l2(0.1),
        )
        host = StreamingRandomEffectCoordinate(
            **kw,
            state_root=str(tmp_path / "state-host"),
            solve_schedule=SolveSchedule(chunk_size=6),
        )
        dev = StreamingRandomEffectCoordinate(
            **kw,
            state_root=str(tmp_path / "state-dev"),
            solve_schedule=SolveSchedule(chunk_size=6, loop="device"),
        )
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        st_host, _ = host.update(resid, host.initial_coefficients())
        st_dev, _ = dev.update(resid, dev.initial_coefficients())
        for i in range(len(manifest.blocks)):
            assert np.array_equal(st_host.block(i), st_dev.block(i)), i
        assert np.array_equal(
            np.asarray(host.score(st_host)), np.asarray(dev.score(st_dev))
        )
