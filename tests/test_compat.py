"""compat forced-CPU-mesh helpers: the multi-device-single-host story
(``merge_disjoint_devices``, the bench psum arm) rides
``--xla_force_host_platform_device_count``, which XLA reads exactly once
at backend instantiation — these helpers are how callers detect the flag,
detect the latch, and pin the flag safely before it latches.

The test process itself runs on the conftest-forced 8-device CPU mesh
(tests/conftest.py sets XLA_FLAGS before any jax import), which doubles
as the live-backend fixture for the post-init branches below.
"""

import os

import pytest

import jax

from photon_ml_tpu import compat

FLAG = "--xla_force_host_platform_device_count"


class TestForcedCpuDeviceCount:
    def test_absent_flag_is_none(self):
        assert compat.forced_cpu_device_count(flags="") is None
        assert compat.forced_cpu_device_count(flags="--foo=1 --bar") is None

    def test_parses_count(self):
        assert compat.forced_cpu_device_count(flags=f"{FLAG}=4") == 4
        assert (
            compat.forced_cpu_device_count(flags=f"--foo=1 {FLAG}=12 --bar")
            == 12
        )

    def test_last_occurrence_wins(self):
        # XLA's own parse keeps the last value; the helper must agree
        assert (
            compat.forced_cpu_device_count(flags=f"{FLAG}=2 {FLAG}=6") == 6
        )

    def test_malformed_value_is_none(self):
        assert compat.forced_cpu_device_count(flags=f"{FLAG}=lots") is None

    def test_default_reads_process_env(self):
        # conftest.py forces the 8-device CPU mesh for the whole suite
        assert compat.forced_cpu_device_count() == 8


class TestForceCpuDevices:
    def test_rejects_nonpositive(self):
        with pytest.raises(ValueError, match="n >= 1"):
            compat.force_cpu_devices(0)

    def test_post_init_reports_live_backend(self):
        # jax is long since initialized here: the env is latched, so the
        # answer is whether the LIVE backend satisfies the request
        assert compat.backends_initialized()
        assert compat.force_cpu_devices(8) is True
        assert compat.force_cpu_devices(2) is True  # 8 >= 2
        assert compat.force_cpu_devices(64) is False

    def test_pre_init_rewrites_env(self, monkeypatch):
        monkeypatch.setattr(compat, "backends_initialized", lambda: False)
        monkeypatch.setenv("XLA_FLAGS", f"--foo=1 {FLAG}=2")
        assert compat.force_cpu_devices(4) is True
        # prior occurrence replaced, unrelated flags preserved
        assert os.environ["XLA_FLAGS"] == f"--foo=1 {FLAG}=4"

    def test_pre_init_matching_flag_is_untouched(self, monkeypatch):
        monkeypatch.setattr(compat, "backends_initialized", lambda: False)
        monkeypatch.setenv("XLA_FLAGS", f"{FLAG}=4 --foo=1")
        assert compat.force_cpu_devices(4) is True
        # already pinned at the requested count: no rewrite at all
        assert os.environ["XLA_FLAGS"] == f"{FLAG}=4 --foo=1"


def test_forced_mesh_is_live_in_this_process():
    # the helpers' promise end-to-end: the flag conftest pinned is the
    # mesh this process actually got
    assert len(jax.devices("cpu")) == 8
