"""Entity-sharded multihost streaming coordinate descent (the
billion-coefficient path): per-host streaming block solves + exact mesh
merges, pinned BITWISE against the single-host streaming run.

Tier-1 (fast, single-process) coverage: the agreed plan reproduces the
single-host blocking; the per-host coordinates degrade to bitwise copies of
the plain streaming coordinates at num_processes=1; routing/reduction fault
sites are chaos-injectable; the tensor cache's shard scope separates
per-host entries. The 2-process harness (slow) proves the real thing:
update + score + one full CD cycle over {streaming FE, streaming RE},
2 processes x 4 virtual CPU devices, bitwise-equal to the single-host run
— plus a lost-host-mid-block chaos injection that must surface a
diagnosable BarrierTimeoutError instead of hanging the survivors."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from game_test_utils import make_glmix_data

from photon_ml_tpu.algorithm.streaming_fixed_effect import (
    PerHostStreamingFixedEffectCoordinate,
    StreamingFixedEffectCoordinate,
)
from photon_ml_tpu.algorithm.streaming_random_effect import (
    StreamingRandomEffectCoordinate,
    plan_entity_blocks,
    write_re_entity_blocks,
)
from photon_ml_tpu.data.game import RandomEffectDataConfig
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.optim.streaming import ChunkedGLMSource
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
from photon_ml_tpu.parallel.perhost_ingest import HostRows, csr_to_padded
from photon_ml_tpu.parallel.perhost_streaming import (
    EntityShardPlan,
    PerHostStreamingRandomEffectCoordinate,
    build_perhost_streaming_manifest,
    merge_disjoint,
    merge_disjoint_devices,
)
from photon_ml_tpu.types import OptimizerType, TaskType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "perhost_streaming_worker.py")

RE_CFG = RandomEffectDataConfig("userId", "per_user")
RE_OPT = OptimizerConfig(max_iterations=6, tolerance=1e-8)
RE_REG = RegularizationContext.l2(0.2)


def _sorted_vocab_data(rng=None, **kw):
    """GLMix data with the entity vocabulary in SORTED order — the order
    the per-host raw-id agreement (and the production sorted-set decode)
    produces, so dense ids agree between the reference and the plan."""
    rng = rng or np.random.default_rng(41)
    data, _ = make_glmix_data(rng, **kw)
    vocab = data.id_vocabs["userId"]
    order = np.argsort(np.asarray(vocab, dtype=object))
    remap = np.empty(len(vocab), np.int64)
    remap[order] = np.arange(len(vocab))
    data.ids["userId"] = remap[data.ids["userId"]].astype(np.int32)
    data.id_vocabs["userId"] = [vocab[i] for i in order]
    return data


def _host_rows(data):
    feats = data.shards["per_user"]
    fi, fv = csr_to_padded(feats, data.num_rows)
    vocab = data.id_vocabs["userId"]
    return HostRows(
        entity_raw_ids=[vocab[i] for i in data.ids["userId"]],
        row_index=np.arange(data.num_rows, dtype=np.int64),
        labels=data.response.astype(np.float32),
        weights=data.weight.astype(np.float32),
        offsets=data.offset.astype(np.float32),
        feat_idx=fi, feat_val=fv, global_dim=feats.dim,
    )


@pytest.fixture(scope="module")
def glmix():
    return _sorted_vocab_data(
        num_users=40, rows_per_user_range=(3, 12), d_fixed=4, d_random=3
    )


@pytest.fixture(scope="module")
def mesh_ctx():
    return MeshContext(data_mesh())


class TestPlan:
    def test_plan_matches_single_host_blocking(self, glmix, tmp_path):
        """EntityShardPlan.build over the merged counts must reproduce the
        single-host write_re_entity_blocks blocking exactly — block
        composition is the bitwise foundation."""
        ref = write_re_entity_blocks(
            glmix, RE_CFG, str(tmp_path / "ref"), block_entities=16
        )
        ids = glmix.ids["userId"]
        counts = np.bincount(ids, minlength=int(ids.max()) + 1)
        plan = EntityShardPlan.build(
            counts, 2, global_dim=glmix.shards["per_user"].dim,
            block_entities=16,
        )
        assert len(plan.blocks) == len(ref.blocks)
        for gi, ents in enumerate(plan.blocks):
            z = np.load(os.path.join(ref.dir, ref.blocks[gi]["file"]))
            np.testing.assert_array_equal(ents, z["entity_ids"])
        # every present entity owned by exactly one block; owners in range
        assert plan.num_entities == 40
        assert set(plan.owners.tolist()) <= {0, 1}
        owned = plan.owned_block_ids(0) + plan.owned_block_ids(1)
        assert sorted(owned) == list(range(len(plan.blocks)))

    def test_plan_budget_mode_matches(self, glmix, tmp_path):
        budget = 8_000
        ref = write_re_entity_blocks(
            glmix, RE_CFG, str(tmp_path / "ref"), memory_budget_bytes=budget
        )
        ids = glmix.ids["userId"]
        counts = np.bincount(ids, minlength=int(ids.max()) + 1)
        blocks = plan_entity_blocks(
            counts, global_dim=glmix.shards["per_user"].dim,
            memory_budget_bytes=budget,
        )
        assert len(blocks) == len(ref.blocks)

    def test_plan_requires_exactly_one_sizing(self):
        with pytest.raises(ValueError, match="exactly one"):
            plan_entity_blocks(np.asarray([3, 2]), global_dim=4)


class TestSingleProcessBitwise:
    """num_processes=1 perhost coordinates are bitwise copies of the plain
    streaming coordinates (the merge is the identity); this plus the
    host-count-invariant design is what the 2-process harness then proves
    cross-host."""

    def test_re_blocks_and_coordinate_bitwise(self, glmix, mesh_ctx, tmp_path):
        ref_man = write_re_entity_blocks(
            glmix, RE_CFG, str(tmp_path / "ref"), block_entities=16
        )
        ref = StreamingRandomEffectCoordinate(
            ref_man, TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS, RE_OPT, RE_REG,
            state_root=str(tmp_path / "ref-state"),
        )
        man = build_perhost_streaming_manifest(
            _host_rows(glmix), RE_CFG, str(tmp_path / "ph"), mesh_ctx, 1, 0,
            block_entities=16, shared_vocab=glmix.id_vocabs["userId"],
        )
        ph = PerHostStreamingRandomEffectCoordinate(
            man, TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS, RE_OPT, RE_REG,
            state_root=str(tmp_path / "ph-state"),
            ctx=mesh_ctx, num_processes=1,
        )
        # identical block FILES (tensors byte-for-byte)
        assert [b["file"] for b in man.blocks] == [b["file"] for b in ref_man.blocks]
        for b in ref_man.blocks:
            z1 = np.load(os.path.join(ref_man.dir, b["file"]))
            z2 = np.load(os.path.join(man.dir, b["file"]))
            for k in z1.files:
                np.testing.assert_array_equal(z1[k], z2[k], err_msg=(b["file"], k))
        resid = jnp.asarray(
            np.random.default_rng(5).normal(size=glmix.num_rows)
            .astype(np.float32)
        )
        s_ref, _ = ref.update(resid, ref.initial_coefficients())
        s_ph, _ = ph.update(resid, ph.initial_coefficients())
        np.testing.assert_array_equal(
            np.asarray(ref.score(s_ref)), np.asarray(ph.score(s_ph))
        )
        assert float(ref.regularization_term(s_ref)) == float(
            ph.regularization_term(s_ph)
        )
        assert ph.num_entities == 40

    @pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON])
    def test_fe_coordinate_bitwise(self, mesh_ctx, opt):
        rng = np.random.default_rng(3)
        n, d = 700, 6
        x = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        y = (1 / (1 + np.exp(-x @ w_true)) > rng.random(n)).astype(np.float32)
        prob = GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION, opt,
            OptimizerConfig(max_iterations=6, tolerance=1e-8),
            RegularizationContext.l2(0.3),
        )
        src = ChunkedGLMSource.from_arrays(x, y, 128)
        ref = StreamingFixedEffectCoordinate(src, prob)
        sizes = [len(load()["y"]) for load in src.loaders]
        ph = PerHostStreamingFixedEffectCoordinate(
            sizes, dict(enumerate(src.loaders)), d, prob,
            ctx=mesh_ctx, num_processes=1,
        )
        resid = jnp.asarray(rng.normal(size=n).astype(np.float32))
        w_ref, _ = ref.update(resid, ref.initial_coefficients())
        w_ph, _ = ph.update(resid, ph.initial_coefficients())
        np.testing.assert_array_equal(np.asarray(w_ref), np.asarray(w_ph))
        np.testing.assert_array_equal(
            np.asarray(ref.score(w_ref)), np.asarray(ph.score(w_ph))
        )

    def test_merge_disjoint_single_process_identity(self, mesh_ctx):
        a = np.random.default_rng(0).normal(size=(3, 5)).astype(np.float32)
        out = merge_disjoint(a, mesh_ctx, 1)
        np.testing.assert_array_equal(out, a)
        a64 = a.astype(np.float64)
        np.testing.assert_array_equal(merge_disjoint(a64, mesh_ctx, 1), a64)


class TestFaultSites:
    """The new multihost fault/preempt surfaces are chaos-injectable (and
    therefore registered — photon-lint's fault-sites two-way check)."""

    def test_block_write_fault_retried(self, glmix, mesh_ctx, tmp_path,
                                       monkeypatch):
        monkeypatch.setenv("PHOTON_FAULTS", "io.perhost_block_write:at=1")
        man = build_perhost_streaming_manifest(
            _host_rows(glmix), RE_CFG, str(tmp_path / "ph"), mesh_ctx, 1, 0,
            block_entities=16, shared_vocab=glmix.id_vocabs["userId"],
        )
        assert len(man.blocks) == 3  # survived the injected write failure

    def test_entity_route_fault_fires_single_process(self, glmix, mesh_ctx,
                                                     tmp_path, monkeypatch):
        from photon_ml_tpu.resilience.faults import InjectedIOError

        monkeypatch.setenv(
            "PHOTON_FAULTS", "multihost.entity_route:rate=1.0,seed=7"
        )
        with pytest.raises(InjectedIOError, match="entity_route"):
            build_perhost_streaming_manifest(
                _host_rows(glmix), RE_CFG, str(tmp_path / "ph"), mesh_ctx,
                1, 0, block_entities=16,
                shared_vocab=glmix.id_vocabs["userId"],
            )

    def test_streaming_reduce_fault_retried(self, mesh_ctx, monkeypatch):
        monkeypatch.setenv("PHOTON_FAULTS", "multihost.streaming_reduce:at=1")
        a = np.ones((4,), np.float32)
        np.testing.assert_array_equal(merge_disjoint(a, mesh_ctx, 1), a)


class TestDeviceMerge:
    """merge_disjoint_devices: the in-program shard_map+psum merge over
    the conftest-forced multi-device CPU mesh is bitwise-equal to the
    host-side fold of the same disjoint partials."""

    def _disjoint_shards(self, n_dev, rows=64, dim=5, seed=3):
        rng = np.random.default_rng(seed)
        full = rng.normal(size=(rows, dim)).astype(np.float32)
        owners = rng.integers(0, n_dev, size=rows)
        shards = np.zeros((n_dev, rows, dim), np.float32)
        shards[owners, np.arange(rows)] = full
        return shards, full

    def test_psum_merge_bitwise_vs_host_fold(self, mesh_ctx):
        shards, full = self._disjoint_shards(mesh_ctx.num_devices)
        out = merge_disjoint_devices(shards, mesh_ctx)
        assert out.dtype == np.float32
        # disjoint partials: psum adds each value to zeros (the IEEE
        # identity), so the merge IS the original — and bitwise-equal to
        # the host fold merge_disjoint performs over the same partials
        np.testing.assert_array_equal(out, full)
        host = np.zeros_like(full)
        for s in shards:
            host = host + s
        np.testing.assert_array_equal(out, host)

    def test_wrong_leading_shape_raises(self, mesh_ctx):
        bad = np.zeros((mesh_ctx.num_devices + 1, 4), np.float32)
        with pytest.raises(ValueError, match="leading shard"):
            merge_disjoint_devices(bad, mesh_ctx)

    def test_single_device_mesh_is_identity(self):
        ctx1 = MeshContext(data_mesh(n_devices=1))
        a = np.random.default_rng(5).normal(size=(1, 7)).astype(np.float32)
        np.testing.assert_array_equal(merge_disjoint_devices(a, ctx1), a[0])

    def test_device_merge_fault_retried(self, mesh_ctx, monkeypatch):
        # the DEVICE merge rides the same multihost.streaming_reduce fault
        # site as the host merge: one chaos plan covers both paths
        monkeypatch.setenv("PHOTON_FAULTS", "multihost.streaming_reduce:at=1")
        shards, full = self._disjoint_shards(mesh_ctx.num_devices, seed=9)
        np.testing.assert_array_equal(
            merge_disjoint_devices(shards, mesh_ctx), full
        )


class TestShardScopedCache:
    """Satellite: per-host cache entries on a shared filesystem must not
    collide or cross-read — the shard scope is folded into every key."""

    def test_scope_separates_hosts_same_sources(self, tmp_path):
        from photon_ml_tpu.io.tensor_cache import (
            TensorCache,
            process_shard_scope,
        )

        src = tmp_path / "input.bin"
        src.write_bytes(b"shared source file")
        cfg = {"kind": "streaming_re_blocks", "coord": "per-user"}
        c0 = TensorCache(
            str(tmp_path / "cache"),
            shard_scope=process_shard_scope(0, 2),
        )
        c1 = TensorCache(
            str(tmp_path / "cache"),
            shard_scope=process_shard_scope(1, 2),
        )
        k0, k1 = c0.key_for([str(src)], cfg), c1.key_for([str(src)], cfg)
        assert k0 != k1  # same sources+config, different hosts: no collision
        c0.put(k0, {"w": np.zeros(3, np.float32)}, meta={"host": 0})
        c1.put(k1, {"w": np.ones(3, np.float32)}, meta={"host": 1})
        # no cross-read: each host gets ITS tensors back
        assert c0.get(k0).meta["host"] == 0
        assert c1.get(k1).meta["host"] == 1
        np.testing.assert_array_equal(c1.get(k1).arrays["w"], np.ones(3))
        # a topology change re-scopes (2 hosts -> 4 must rebuild, not reuse)
        c0b = TensorCache(
            str(tmp_path / "cache"),
            shard_scope=process_shard_scope(0, 4),
        )
        assert c0b.key_for([str(src)], cfg) != k0

    def test_unscoped_keys_unchanged(self, tmp_path):
        """shard_scope=None hashes exactly as before (existing caches stay
        warm across this upgrade)."""
        from photon_ml_tpu.io.tensor_cache import TensorCache, content_key

        src = tmp_path / "input.bin"
        src.write_bytes(b"x")
        cache = TensorCache(str(tmp_path / "cache"))
        assert cache.key_for([str(src)], {"a": 1}) == content_key(
            [str(src)], {"a": 1}
        )


class TestParams:
    """The streaming x distributed fence is GONE and the combination
    parses; the neighbouring fences stay."""

    def _parse(self, *extra):
        from photon_ml_tpu.cli.game_params import parse_training_params

        return parse_training_params([
            "--train-input-dirs", "in", "--task-type", "LOGISTIC_REGRESSION",
            "--output-dir", "out", "--updating-sequence", "fixed",
            "--fixed-effect-data-configurations", "fixed:global,1",
            *extra,
        ])

    def test_streaming_with_distributed_parses(self):
        p = self._parse(
            "--streaming-random-effects", "true", "--distributed", "true"
        )
        assert p.streaming_random_effects and p.distributed

    def test_memory_budget_with_distributed_parses(self):
        p = self._parse(
            "--re-memory-budget-mb", "64", "--distributed", "true"
        )
        assert p.streaming_random_effects and p.re_memory_budget_mb == 64.0

    def test_old_fence_error_gone(self):
        import pytest as _pytest

        try:
            self._parse(
                "--streaming-random-effects", "true", "--distributed", "true"
            )
        except ValueError as e:  # pragma: no cover - regression guard
            _pytest.fail(f"streaming x distributed fence resurfaced: {e}")

    def test_streaming_fused_cycle_fence_deleted(self):
        """The streaming x fused-cycle fence is DELETED: the block loop
        hands each block one fused solve (cycle fusion at solve
        granularity — tests/test_exec_plan.py pins the plan decision), so
        the CLI combination parses."""
        p = self._parse(
            "--streaming-random-effects", "true", "--fused-cycle", "true"
        )
        assert p.streaming_random_effects and p.fused_cycle

    def test_streaming_bucketed_subsumed_not_fenced(self):
        """The streaming x bucketed fence is DELETED: streaming already
        sorts entities into tightly-padded size blocks, so the plan
        SUBSUMES --bucketed-random-effects with a recorded decision and
        the combination parses."""
        p = self._parse(
            "--streaming-random-effects", "true",
            "--bucketed-random-effects", "true",
        )
        assert p.streaming_random_effects and p.bucketed_random_effects
        from photon_ml_tpu.compile.plan import ExecutionPlan

        plan = ExecutionPlan.resolve(streaming=True, bucketed=True)
        assert plan.bucketed_subsumed()
        assert any(
            d.policy == "bucketed" and d.action == "subsumed"
            for d in plan.decisions
        )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_workers(tmp_path, env_extra=None):
    port = _free_port()
    # pin the worker plan's env knobs so the flags-off arms stay flags-off
    # under any ambient environment; the all-flags arm overrides explicitly
    env = {
        **os.environ,
        "PHOTON_SOLVE_CHUNK": "off",
        "PHOTON_SPARSE_KERNEL": "off",
        "PHOTON_SHAPE_LADDER": "off",
        "PHOTON_ADAPTIVE_SCHEDULE": "off",
        **(env_extra or {}),
    }
    return [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env,
        )
        for i in range(2)
    ]


def _single_host_reference(tmp_path):
    """The flags-off single-host streaming CD run of the workers' seeded
    dataset — the fenced baseline BOTH worker arms (flags-off and
    all-flags-on) must match bitwise."""
    data = _sorted_vocab_data(
        np.random.default_rng(97),
        num_users=60, rows_per_user_range=(4, 16), d_fixed=5, d_random=4,
    )
    N = data.num_rows
    man = write_re_entity_blocks(
        data, RE_CFG, str(tmp_path / "ref-blocks"), block_entities=16
    )
    re_ref = StreamingRandomEffectCoordinate(
        man, TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS, RE_OPT, RE_REG,
        state_root=str(tmp_path / "ref-state"),
    )
    gf = data.shards["global"]
    x_fe = np.zeros((N, gf.dim), np.float32)
    x_fe[np.repeat(np.arange(N), np.diff(gf.indptr)), gf.indices] = gf.values
    fe_ref = StreamingFixedEffectCoordinate(
        ChunkedGLMSource.from_arrays(
            x_fe, data.response.astype(np.float32), 128
        ),
        GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=6, tolerance=1e-8),
            RegularizationContext.l2(0.5),
        ),
    )
    from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent

    labels = jnp.asarray(data.response.astype(np.float32))
    weights = jnp.asarray(data.weight.astype(np.float32))
    loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
    cd = CoordinateDescent(
        {"fixed": fe_ref, "per-user": re_ref},
        lambda s: jnp.sum(weights * loss.loss(s, labels)),
    )
    ref = cd.run(num_iterations=2, num_rows=N)
    ref_means = re_ref.entity_means_by_raw_id(ref.coefficients["per-user"])
    return ref, ref_means


def _assert_workers_match_reference(tmp_path, ref, ref_means):
    run = np.load(tmp_path / "run.npz")
    np.testing.assert_array_equal(
        run["fe"], np.asarray(ref.coefficients["fixed"])
    )
    np.testing.assert_array_equal(
        run["total_scores"], np.asarray(ref.total_scores)
    )
    np.testing.assert_array_equal(
        run["objectives"], np.asarray(ref.objective_history, np.float64)
    )
    # per-entity coefficients: the union of the two hosts' owned means must
    # equal the single-host export exactly, entity for entity
    merged = {}
    for pid in range(2):
        z = np.load(tmp_path / f"means-host{pid}.npz", allow_pickle=True)
        for name, vec in zip(z["names"], z["stack"]):
            assert name not in merged  # owner-computes: disjoint ownership
            merged[str(name)] = vec
    assert sorted(merged) == sorted(ref_means)
    for k, vec in ref_means.items():
        np.testing.assert_array_equal(merged[k], vec, err_msg=k)


@pytest.mark.slow
def test_two_process_streaming_cd_bitwise_vs_single_host(tmp_path):
    """THE acceptance gate: the 2-process entity-sharded streaming CD run
    (agree -> plan -> route -> owned blocks -> streaming CD with exact mesh
    merges) is bitwise-equal to the single-host streaming run of the same
    data — update + score + full CD cycles over both coordinates."""
    procs = _launch_workers(tmp_path)
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=900)
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}\n{err[-3000:]}"
        outs.append(out)
    assert all("PHSOK" in o for o in outs)
    ref, ref_means = _single_host_reference(tmp_path)
    _assert_workers_match_reference(tmp_path, ref, ref_means)


@pytest.mark.slow
def test_two_process_all_flags_on_bitwise_vs_single_host(tmp_path):
    """The composable-execution-plan acceptance gate at multihost scale:
    the SAME 2-process harness with --solve-compaction (PHOTON_SOLVE_CHUNK)
    AND the sparse-kernel race (PHOTON_SPARSE_KERNEL=auto) switched on
    through the workers' env-resolved ExecutionPlan stays bitwise-equal to
    the flags-off single-host streaming reference: compacted perhost solve
    == one-shot perhost solve == single-host solve. (The shape ladder rides
    both sides of its own comparison in the single-process matrix test —
    its on-vs-off contract is PR 3's regime-limited one.)"""
    procs = _launch_workers(
        tmp_path,
        env_extra={
            "PHOTON_SOLVE_CHUNK": "3",
            "PHOTON_SPARSE_KERNEL": "auto",
        },
    )
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=900)
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}\n{err[-3000:]}"
        outs.append(out)
    assert all("PHSOK" in o for o in outs)
    # compaction genuinely engaged (the worker reports its ledger)
    assert all("compaction_saved=" in o for o in outs)
    ref, ref_means = _single_host_reference(tmp_path)
    _assert_workers_match_reference(tmp_path, ref, ref_means)


@pytest.mark.slow
def test_two_process_adaptive_ordering_only_bitwise_vs_single_host(tmp_path):
    """Adaptive-schedule acceptance at multihost scale: the SAME 2-process
    harness with PHOTON_ADAPTIVE_SCHEDULE=0.0:1 (descending-gap visitation,
    tolerance 0 so nothing ever skips) stays bitwise-equal to the flags-off
    single-host reference — the convergence-ledger-ordered visit sequence
    must be invisible in every coefficient, score, and objective. Tier-1
    siblings: tests/test_adaptive_schedule.py
    TestStreamingAdaptive::test_ordering_only_mode_is_bitwise (single-host)
    and TestPlanComposition (the env->plan resolution)."""
    procs = _launch_workers(
        tmp_path, env_extra={"PHOTON_ADAPTIVE_SCHEDULE": "0.0:1"}
    )
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=900)
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}\n{err[-3000:]}"
        outs.append(out)
    assert all("PHSOK" in o for o in outs)
    ref, ref_means = _single_host_reference(tmp_path)
    _assert_workers_match_reference(tmp_path, ref, ref_means)
    # the ordering engaged: each worker's manifest dir now carries the
    # convergence-ledger sidecar for exactly its owned blocks
    from photon_ml_tpu.optim.convergence import ConvergenceLedger

    for pid in range(2):
        led = ConvergenceLedger.load(str(tmp_path / f"re-host{pid}"))
        assert led is not None and led.gids()


@pytest.mark.slow
def test_multihost_driver_streaming_random_effects(tmp_path):
    """Driver-level end-to-end: the 2-process multihost driver with
    --streaming-random-effects runs the per-host streaming path (per-host
    manifest layout under the output dir, per-file FE chunk passes,
    per-host model parts) and matches the single-process streaming driver's
    model and validation metrics."""
    from game_test_utils import launch_multihost, make_glmix_data, write_game_avro

    rng = np.random.default_rng(23)
    data, truth = make_glmix_data(
        rng, num_users=18, rows_per_user_range=(6, 16), d_fixed=4, d_random=3
    )
    n_all = data.num_rows
    n = int(n_all * 0.85)
    train_dir = tmp_path / "train"
    val_dir = tmp_path / "validate"
    train_dir.mkdir(); val_dir.mkdir()
    bounds = np.linspace(0, n, 5).astype(int)  # 4 train parts (FE chunks)
    for pi in range(4):
        write_game_avro(
            str(train_dir / f"part-{pi}.avro"), data,
            range(bounds[pi], bounds[pi + 1]), truth,
        )
    vb = np.linspace(n, n_all, 3).astype(int)
    # the two hosts must decode DIFFERENT max-nnz widths (real data skew):
    # validation file 1's rows keep only their first random feature, so the
    # routed-scoring exchange only works if the hosts collectively agree
    # the record width before packing (regression for the width-agreement)
    truth["x_random"][vb[1]:vb[2], 1:] = 0.0
    for pi in range(2):
        write_game_avro(
            str(val_dir / f"part-{pi}.avro"), data,
            range(vb[pi], vb[pi + 1]), truth,
        )
    from photon_ml_tpu.cli import feature_indexing, game_training_driver
    from photon_ml_tpu.io import model_io
    from photon_ml_tpu.io.offheap import load_shard_index_map

    idx_dir = str(tmp_path / "index")
    feature_indexing.main([
        "--data-input-dirs", str(train_dir),
        "--output-dir", idx_dir, "--partition-num", "1",
        "--feature-shard-id-to-feature-section-keys-map",
        "global:fixedFeatures|per_user:userFeatures",
    ])
    flags = [
        "--train-input-dirs", str(train_dir),
        "--validate-input-dirs", str(val_dir),
        "--evaluator-type", "AUC",
        "--task-type", "LOGISTIC_REGRESSION",
        "--updating-sequence", "fixed,per-user",
        "--feature-shard-id-to-feature-section-keys-map",
        "global:fixedFeatures|per_user:userFeatures",
        "--fixed-effect-optimization-configurations",
        "fixed:30,1e-9,0.1,1,LBFGS,L2",
        "--fixed-effect-data-configurations", "fixed:global,2",
        "--random-effect-optimization-configurations",
        "per-user:25,1e-9,0.5,1,LBFGS,L2",
        "--random-effect-data-configurations",
        "per-user:userId,per_user,2,-1,0,-1,index_map",
        "--num-iterations", "2",
        "--streaming-random-effects", "true",
        # threads the solve schedule through BOTH drivers' execution plans
        # (the multihost build_coords hands it to the per-host coordinate;
        # compaction is bitwise, so the cross-driver parity bound is
        # unchanged) — driver-level proof of the composable-plan wiring
        "--solve-compaction", "4",
        "--offheap-indexmap-dir", idx_dir,
        "--delete-output-dir-if-exists", "true",
    ]
    import json as _json

    outs = launch_multihost(
        "game_multihost_driver",
        ["--output-dir", str(tmp_path / "mh-out")] + flags,
        result_expr="print('MHVAL', json.dumps(res['validation_metrics']))",
    )
    mh_metrics = [
        _json.loads(line.split("MHVAL ", 1)[1])
        for o in outs for line in o.splitlines() if line.startswith("MHVAL")
    ]
    assert len(mh_metrics) == 2 and mh_metrics[0] == mh_metrics[1]

    # per-host manifest layout on disk: each process built only ITS blocks
    for pid in range(2):
        assert (
            tmp_path / "mh-out" / "streaming-re" / "per-user"
            / f"process-{pid}" / "manifest.json"
        ).exists()

    sp = game_training_driver.main(
        ["--output-dir", str(tmp_path / "sp-out")] + flags
    )
    sp_metrics = sp.results[sp.best_index][2]
    assert mh_metrics[0]["AUC"] == pytest.approx(sp_metrics["AUC"], abs=2e-3)
    imap_u = load_shard_index_map(idx_dir, "per_user")
    re_mh, _, re_id, _ = model_io.load_random_effect(
        str(tmp_path / "mh-out" / "best"), "per-user", imap_u
    )
    re_sp, _, _, _ = model_io.load_random_effect(
        str(tmp_path / "sp-out" / "best"), "per-user", imap_u
    )
    assert re_id == "userId"
    assert set(re_mh) == set(re_sp)  # every entity, real raw ids
    for eid in re_sp:
        np.testing.assert_allclose(
            re_mh[eid], re_sp[eid], rtol=5e-3, atol=5e-4, err_msg=eid
        )
    # the model was written as per-host part files (owner-computes save)
    parts = os.listdir(
        tmp_path / "mh-out" / "best" / "random-effect" / "per-user"
        / "coefficients"
    )
    assert len(parts) == 2


@pytest.mark.slow
def test_two_process_lost_host_mid_block_is_diagnosable(tmp_path):
    """Chaos: host 1 dies HARD after its first block spill inside the
    update. The survivor must NOT hang: either our cooperative barrier
    deadline fires (BarrierTimeoutError naming the heartbeat diagnosis
    path, the PR-5 health-fencing contract) or jax's coordination service
    detects the dead peer's missed heartbeats first and fails the job with
    an UNAVAILABLE diagnosis — both are diagnosable failures whose
    recovery is the restart supervisor, and both must land well inside the
    harness deadline."""
    procs = _launch_workers(
        tmp_path,
        env_extra={"PERHOST_LOSE_HOST": "1", "PHOTON_BARRIER_TIMEOUT": "25"},
    )
    outs, codes = [], []
    for p in procs:
        out, err = p.communicate(timeout=600)  # the no-hang gate
        outs.append(out + err)
        codes.append(p.returncode)
    assert codes[1] == 17, outs[1][-2000:]  # the lost host died where told
    assert "LOSTHOST-DYING" in outs[1]
    assert codes[0] != 0, outs[0][-2000:]  # survivor failed, not hung
    assert "LOSTHOST-UNDETECTED" not in outs[0]
    diagnosed = (
        "LOSTHOST-DETECTED BarrierTimeoutError" in outs[0]  # our fence
        or "heartbeat timeout" in outs[0]  # the runtime's fence beat ours
        or "UNAVAILABLE" in outs[0]
    )
    assert diagnosed, outs[0][-2000:]
