"""bench.py CLI surface that must work WITHOUT a device: section
enumeration (the orchestrator / CI smoke path) never imports jax or any
TPU-only module, so a wedged tunnel or backend-free host can still list
what the bench would run."""

import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def test_list_sections_enumerates_all_sections():
    out = subprocess.run(
        [sys.executable, BENCH, "--list-sections"],
        capture_output=True, text=True, timeout=120,
        # a poisoned platform value must not matter: --list-sections exits
        # before any backend (or photon_ml_tpu module) import
        env={**os.environ, "JAX_PLATFORMS": "this-backend-does-not-exist"},
    )
    assert out.returncode == 0, out.stderr
    sections = out.stdout.split()
    assert sections == [
        "dense", "sparse", "sparse_race", "game", "game5", "grid",
        "streaming", "streaming_pipeline", "compile_reuse", "compaction",
        "fused_schedule",
        "adaptive_schedule",
        "plan_auto",
        "preemption_resume",
        "perhost", "perhost_streaming", "elastic_reshard", "scoring",
        "serving",
        "serving_fleet", "quantized_serving", "retrain_delta",
        "delta_rollout", "day_in_life", "ingest",
    ]


def test_list_sections_does_not_touch_jax():
    """The flag must list sections even where importing jax would crash
    outright — audit via an import tripwire."""
    tripwire = (
        "import builtins, sys\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    if name == 'jax' or name.startswith('jax.'):\n"
        "        raise RuntimeError('jax imported during --list-sections')\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        f"sys.argv = ['bench.py', '--list-sections']\n"
        f"__file__ = {BENCH!r}\n"
        f"exec(compile(open({BENCH!r}).read(), 'bench.py', 'exec'))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", tripwire],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "compaction" in out.stdout.split()
