"""Property-based invariants for the core math (hypothesis).

These encode the contracts the rest of the framework leans on: loss
derivatives match finite differences, convexity of twice-diff losses,
normalization folding is exact, sparse and dense feature layouts are the
same linear operator, and the feature index is a deterministic bijection.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytest.importorskip(
    "hypothesis", reason="property suite needs hypothesis (absent in some images)"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.ops.features import DenseFeatures, SparseFeatures, from_scipy_like

SET = settings(max_examples=25, deadline=None)

finite_f = st.floats(
    min_value=-20.0, max_value=20.0, allow_nan=False, allow_infinity=False
)


class TestLossProperties:
    @pytest.mark.parametrize("loss", [
        losses_mod.logistic, losses_mod.squared, losses_mod.poisson,
        losses_mod.smoothed_hinge,
    ])
    @SET
    @given(z=finite_f, y=st.sampled_from([0.0, 1.0]))
    def test_d1_matches_finite_difference(self, loss, z, y):
        if loss is losses_mod.poisson and z > 10:
            z = 10.0  # keep exp(z) in a numerically testable range
        eps = 1e-4
        za = jnp.asarray(z, jnp.float64) if jax.config.jax_enable_x64 else jnp.asarray(z)
        ya = jnp.asarray(y)
        num = (float(loss.loss(za + eps, ya)) - float(loss.loss(za - eps, ya))) / (2 * eps)
        ana = float(loss.d1(za, ya))
        scale = max(1.0, abs(ana))
        assert abs(num - ana) / scale < 5e-2, (num, ana)

    @pytest.mark.parametrize("loss", [
        losses_mod.logistic, losses_mod.squared, losses_mod.poisson,
    ])
    @SET
    @given(z=finite_f, y=st.sampled_from([0.0, 1.0, 3.0]))
    def test_twice_diff_losses_are_convex(self, loss, z, y):
        if loss is losses_mod.poisson and z > 10:
            z = 10.0
        assert float(loss.d2(jnp.asarray(z), jnp.asarray(y))) >= 0.0

    @SET
    @given(z=st.floats(min_value=-1e4, max_value=1e4, allow_nan=False))
    def test_logistic_stable_at_extreme_margins(self, z):
        for y in (0.0, 1.0):
            v = float(losses_mod.logistic.loss(jnp.asarray(z), jnp.asarray(y)))
            d = float(losses_mod.logistic.d1(jnp.asarray(z), jnp.asarray(y)))
            assert np.isfinite(v) and v >= 0.0
            assert np.isfinite(d) and -1.0 <= d <= 1.0


class TestFeatureLayoutEquivalence:
    @SET
    @given(
        n=st.integers(2, 12),
        d=st.integers(2, 9),
        seed=st.integers(0, 2**16),
    )
    def test_sparse_equals_dense_operator(self, n, d, seed):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(n, d)).astype(np.float32)
        x[rng.random((n, d)) < 0.5] = 0.0  # genuine sparsity
        rows = [
            (np.nonzero(x[i])[0].tolist(), x[i][np.nonzero(x[i])[0]].tolist())
            for i in range(n)
        ]
        sp = from_scipy_like(rows, d)
        dn = DenseFeatures(jnp.asarray(x))
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        v = jnp.asarray(rng.normal(size=n).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(sp.matvec(w)), np.asarray(dn.matvec(w)), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(sp.rmatvec(v)), np.asarray(dn.rmatvec(v)), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(sp.sq_rmatvec(v)), np.asarray(dn.sq_rmatvec(v)),
            rtol=1e-4, atol=1e-4,
        )
        # the sorted-transpose (CSC) layout is the same operator again
        spt = sp.with_transpose()
        np.testing.assert_allclose(
            np.asarray(spt.rmatvec(v)), np.asarray(dn.rmatvec(v)), rtol=1e-5, atol=1e-5
        )


class TestNormalizationFolding:
    @SET
    @given(n=st.integers(3, 10), d=st.integers(2, 6), seed=st.integers(0, 2**16))
    def test_folded_objective_equals_explicit_transform(self, n, d, seed):
        """value/grad with normalization folded into the margin algebra ==
        value/grad on explicitly pre-normalized data (the aggregator trick,
        ValueAndGradientAggregator.scala:87-113)."""
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective

        rng = np.random.default_rng(seed)
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        factors = rng.uniform(0.5, 2.0, size=d).astype(np.float32)
        shifts = rng.normal(size=d).astype(np.float32) * 0.5
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))

        obj = GLMObjective(losses_mod.logistic)
        norm = NormalizationContext(
            factors=jnp.asarray(factors), shifts=jnp.asarray(shifts)
        )
        batch_raw = GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
            jnp.zeros((n,)), jnp.ones((n,)),
        )
        v1, g1 = obj.value_and_grad(w, batch_raw, norm, 0.3)

        x_t = (x - shifts) * factors
        batch_t = GLMBatch(
            DenseFeatures(jnp.asarray(x_t)), jnp.asarray(y),
            jnp.zeros((n,)), jnp.ones((n,)),
        )
        v2, g2 = obj.value_and_grad(
            w, batch_t, NormalizationContext.identity(), 0.3
        )
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-3, atol=1e-4)


class TestRegularizationSplit:
    @SET
    @given(
        lam=st.floats(min_value=0.0, max_value=1e3, allow_nan=False),
        alpha=st.floats(min_value=0.0, max_value=1.0, allow_nan=False),
    )
    def test_elastic_net_split_conserves_total(self, lam, alpha):
        from photon_ml_tpu.ops.regularization import RegularizationContext

        ctx = RegularizationContext.elastic_net(lam, alpha)
        assert ctx.l1_weight + ctx.l2_weight == pytest.approx(lam, rel=1e-6, abs=1e-9)
        assert ctx.l1_weight == pytest.approx(alpha * lam, rel=1e-6, abs=1e-9)

    @SET
    @given(lam=st.floats(min_value=1e-6, max_value=1e3, allow_nan=False))
    def test_with_weight_rescales(self, lam):
        from photon_ml_tpu.ops.regularization import RegularizationContext

        base = RegularizationContext.elastic_net(1.0, 0.25)
        re = base.with_weight(lam)
        assert re.l1_weight + re.l2_weight == pytest.approx(lam, rel=1e-6)
        # split ratio preserved
        assert re.l1_weight == pytest.approx(0.25 * lam, rel=1e-6)


class TestIndexMapProperties:
    @SET
    @given(
        keys=st.lists(
            st.text(
                alphabet=st.characters(
                    whitelist_categories=("Ll", "Lu", "Nd"), max_codepoint=0x7F
                ),
                min_size=1, max_size=8,
            ),
            min_size=1, max_size=30, unique=True,
        ),
        parts=st.integers(1, 4),
    )
    def test_build_is_deterministic_bijection(self, keys, parts):
        from photon_ml_tpu.io.index_map import IndexMap

        m1 = IndexMap.build(keys, add_intercept=True, num_partitions=parts)
        m2 = IndexMap.build(list(reversed(keys)), add_intercept=True, num_partitions=parts)
        # input order must not matter (deterministic ingest contract)
        assert m1.name_to_index == m2.name_to_index
        # bijection over keys + intercept
        assert len(m1) == len(set(keys) | {m1.index_to_name[m1.intercept_index]})
        for k in keys:
            idx = m1.get_index(k)
            assert idx >= 0
            assert m1.get_feature_name(idx) == k


class TestShuffleProperties:
    """Invariants of the collective-shuffle core (parallel/shuffle.py) the
    per-host ingest leans on: delivery is exactly-once, owner maps are a
    pure function of the global counts, and the reservoir priority is a
    pure function of (entity, row) — never of partitioning."""

    @SET
    @given(
        n=st.integers(min_value=0, max_value=300),
        seed=st.integers(min_value=0, max_value=2**31 - 1),
    )
    def test_exchange_exactly_once(self, n, seed):
        from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
        from photon_ml_tpu.parallel import shuffle as sh

        ctx = MeshContext(data_mesh())
        rng = np.random.default_rng(seed)
        dest = rng.integers(0, ctx.num_devices, size=n).astype(np.int64)
        ints = np.stack(
            [np.arange(n), rng.integers(0, 9, n)], axis=1
        ).astype(np.int64) if n else np.zeros((0, 2), np.int64)
        flts = rng.normal(size=(n, 2)).astype(np.float32)
        ex = sh.exchange_rows(dest, ints, flts, ctx, 1, 0)
        got = np.concatenate([b[:, 0] for b in ex.int_rows]) if n else np.zeros(0)
        assert sorted(got.tolist()) == list(range(n))
        # each row landed at exactly its destination device
        for d, bi in enumerate(ex.int_rows):
            if len(bi):
                np.testing.assert_array_equal(dest[bi[:, 0]], d)

    @SET
    @given(
        counts=st.lists(
            st.integers(min_value=0, max_value=1000), min_size=8, max_size=64
        ),
        n_dev=st.sampled_from([2, 4, 8]),
    )
    def test_balanced_owners_deterministic_and_bounded(self, counts, n_dev):
        from photon_ml_tpu.parallel import shuffle as sh

        c = np.asarray(counts, np.int64)
        o1 = sh.balanced_bucket_owners(c, n_dev)
        o2 = sh.balanced_bucket_owners(c.copy(), n_dev)
        np.testing.assert_array_equal(o1, o2)  # pure function of counts
        assert o1.min() >= 0 and o1.max() < n_dev
        loads = np.bincount(o1, weights=c, minlength=n_dev)
        # greedy bin-packing bound: max load exceeds min by at most one item
        assert loads.max() - loads.min() <= (c.max() if len(c) else 0)

    @SET
    @given(
        ids=st.lists(
            st.text(min_size=1, max_size=20), min_size=1, max_size=50, unique=True
        ),
        rows=st.integers(min_value=1, max_value=100),
    )
    def test_priority_partitioning_invariant(self, ids, rows):
        from photon_ml_tpu.parallel import shuffle as sh

        keys = sh.stable_entity_keys(ids * rows)[: len(ids) * min(rows, 3)]
        ridx = np.arange(len(keys), dtype=np.int64)
        p_full = sh.stable_row_priority(keys, ridx)
        # any subset/order of rows produces the identical per-row priority
        perm = np.random.default_rng(0).permutation(len(keys))
        p_perm = sh.stable_row_priority(keys[perm], ridx[perm])
        np.testing.assert_array_equal(p_full[perm], p_perm)
