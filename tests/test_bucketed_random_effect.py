"""Size-bucketed random-effect solves (SURVEY §7.3 'hard part'): equality
with the unbucketed coordinate + the padding-volume win on skewed entity
size distributions + CoordinateDescent integration."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.algorithm.bucketed_random_effect import (
    BucketedRandomEffectCoordinate,
    partition_entities_by_size,
)
from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_ml_tpu.data.game import (
    GameData,
    HostFeatures,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.types import OptimizerType, TaskType


def _skewed_glmix(rng, sizes, d=4):
    """One entity per element of ``sizes`` with that many rows."""
    rows = []
    ids = []
    for e, m in enumerate(sizes):
        rows.append(rng.normal(size=(m, d)).astype(np.float32))
        ids.extend([e] * m)
    x = np.concatenate(rows)
    ids = np.asarray(ids, np.int32)
    w_true = rng.normal(size=(len(sizes), d)).astype(np.float32)
    z = np.einsum("nd,nd->n", x, w_true[ids])
    y = (1.0 / (1.0 + np.exp(-z)) > rng.random(len(ids))).astype(np.float32)
    n = len(ids)
    indptr = np.arange(n + 1, dtype=np.int64) * d
    feats = HostFeatures(
        indptr, np.tile(np.arange(d, dtype=np.int32), n),
        x.reshape(-1).astype(np.float32), d,
    )
    # interleave rows so bucket row-selections are non-contiguous
    perm = rng.permutation(n)
    sub = HostFeatures(
        np.arange(n + 1, dtype=np.int64) * d,
        feats.indices.reshape(n, d)[perm].reshape(-1),
        feats.values.reshape(n, d)[perm].reshape(-1),
        d,
    )
    return GameData(
        response=y[perm],
        offset=np.zeros(n, np.float32),
        weight=np.ones(n, np.float32),
        ids={"userId": ids[perm]},
        id_vocabs={"userId": [f"u{e}" for e in range(len(sizes))]},
        shards={"per_user": sub},
    )


CFG = RandomEffectDataConfig("userId", "per_user", projector="IDENTITY")


class TestPartition:
    def test_geometric_buckets(self):
        counts = np.asarray([0, 1, 2, 3, 9, 64, 1000])
        buckets = partition_entities_by_size(counts, max_buckets=12)
        flat = np.concatenate(buckets)
        assert sorted(flat.tolist()) == [1, 2, 3, 4, 5, 6]  # entity 0 empty
        # the giant entity is alone in the last bucket
        assert buckets[-1].tolist() == [6]
        # clipping merges the tail when max_buckets is small
        merged = partition_entities_by_size(counts, max_buckets=2)
        assert sorted(np.concatenate(merged).tolist()) == [1, 2, 3, 4, 5, 6]
        assert len(merged) <= 2

    def test_empty(self):
        assert partition_entities_by_size(np.zeros(4, np.int64)) == []


class TestEquality:
    @pytest.mark.slow  # ~15s: tier-1 rides the 870s budget's edge (ROADMAP re-anchor note); test_in_coordinate_descent keeps the bucketed-equality contract tier-1 (and the scheduler/preemption bucket pins exercise the same solves)
    def test_matches_unbucketed(self, rng):
        sizes = [3, 5, 6, 9, 17, 33, 150]  # heavily skewed
        data = _skewed_glmix(rng, sizes)
        opt = OptimizerConfig(max_iterations=30, tolerance=1e-9)
        reg = RegularizationContext.l2(0.5)

        plain = RandomEffectCoordinate(
            build_random_effect_dataset(data, CFG),
            TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS, opt, reg,
        )
        bucketed = BucketedRandomEffectCoordinate(
            data, CFG, TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS, opt, reg,
        )
        resid = jnp.zeros((data.num_rows,), jnp.float32)
        w_plain, _ = plain.update(resid, plain.initial_coefficients())
        s_plain = np.asarray(plain.score(w_plain))
        st, _ = bucketed.update(resid, bucketed.initial_coefficients())
        s_bucketed = np.asarray(bucketed.score(st))
        np.testing.assert_allclose(s_bucketed, s_plain, rtol=5e-4, atol=5e-4)
        # regularization terms agree too
        np.testing.assert_allclose(
            float(bucketed.regularization_term(st)),
            float(plain.regularization_term(w_plain)),
            rtol=5e-4,
        )

    def test_padding_volume_shrinks(self, rng):
        # 60 tiny entities + one 1500-row giant: the single global stack
        # pads every lane to 1500
        sizes = [4] * 60 + [1500]
        data = _skewed_glmix(rng, sizes)
        plain_ds = build_random_effect_dataset(data, CFG)
        plain_elems = int(np.prod(plain_ds.x.shape))
        bucketed = BucketedRandomEffectCoordinate(
            data, CFG, TaskType.LOGISTIC_REGRESSION,
            optimizer_config=OptimizerConfig(max_iterations=2),
        )
        assert len(bucketed.buckets) >= 2
        assert bucketed.num_entities == 61
        # >= 90% padded-volume reduction on this skew
        assert bucketed.padded_elements() < plain_elems * 0.1, (
            bucketed.padded_elements(), plain_elems,
        )

    def test_in_coordinate_descent(self, rng):
        from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
        from photon_ml_tpu.ops import losses

        sizes = [5, 8, 30, 200]
        data = _skewed_glmix(rng, sizes)
        coord = BucketedRandomEffectCoordinate(
            data, CFG, TaskType.LOGISTIC_REGRESSION,
            optimizer_config=OptimizerConfig(max_iterations=20, tolerance=1e-7),
            regularization=RegularizationContext.l2(0.1),
        )
        labels = jnp.asarray(data.response)
        loss_fn = lambda s: jnp.sum(losses.logistic.loss(s, labels))
        cd = CoordinateDescent({"re": coord}, loss_fn)
        result = cd.run(num_iterations=2, num_rows=data.num_rows)
        hist = result.objective_history
        # converges in iteration 1; allow f32 jitter on the flat tail
        assert hist[-1] <= hist[0] * (1 + 1e-5)
        assert np.all(np.isfinite(np.asarray(result.total_scores)))


@pytest.mark.slow  # ~15s: tier-1 rides the 870s budget's edge (ROADMAP re-anchor note); the bucketed x --distributed composition stays tier-1 at the driver level via test_game_drivers TestBucketedDistributedDriver
def test_bucketed_composes_with_entity_sharding(rng):
    """mesh_ctx set: every bucket entity-shards over the mesh (per-bucket
    DistributedRandomEffectSolver) and must match the single-device
    bucketed solve."""
    from photon_ml_tpu.parallel import MeshContext, data_mesh

    sizes = [5, 7, 9, 40, 130]
    data = _skewed_glmix(rng, sizes)
    opt = OptimizerConfig(max_iterations=25, tolerance=1e-9)
    reg = RegularizationContext.l2(0.5)
    local = BucketedRandomEffectCoordinate(
        data, CFG, TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS, opt, reg,
    )
    dist = BucketedRandomEffectCoordinate(
        data, CFG, TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS, opt, reg,
        bundle=local.bundle,  # identical per-bucket datasets
        mesh_ctx=MeshContext(data_mesh(8)),
    )
    resid = jnp.zeros((data.num_rows,), jnp.float32)
    st_l, _ = local.update(resid, local.initial_coefficients())
    st_d, _ = dist.update(resid, dist.initial_coefficients())
    np.testing.assert_allclose(
        np.asarray(dist.score(st_d)), np.asarray(local.score(st_l)),
        rtol=5e-4, atol=5e-4,
    )
    np.testing.assert_allclose(
        float(dist.regularization_term(st_d)),
        float(local.regularization_term(st_l)),
        rtol=5e-4,
    )
    # model export agrees too (exercises the padded-entity slicing)
    ml = local.entity_means_by_raw_id(st_l)
    md = dist.entity_means_by_raw_id(st_d)
    assert set(ml) == set(md)
    for k in ml:
        np.testing.assert_allclose(md[k], ml[k], rtol=5e-4, atol=5e-4)
