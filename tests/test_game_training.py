"""End-to-end GAME training: coordinate descent on synthetic GLMix data.

(Reference analogue: integTest cli/game/training/DriverTest.scala:44-393 —
train fixed / random / full models, assert output shapes + metric wiring;
BaseGLMIntegTest-style statistical validators instead of exact weights.)
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.algorithm import (
    CoordinateDescent,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.data.game import (
    RandomEffectDataConfig,
    build_fixed_effect_batch,
    build_random_effect_dataset,
)
from photon_ml_tpu.evaluation import area_under_roc_curve
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import OptimizerType, TaskType

from game_test_utils import make_glmix_data


@pytest.fixture(scope="module")
def glmix():
    rng = np.random.default_rng(42)
    data, truth = make_glmix_data(
        rng, num_users=15, rows_per_user_range=(20, 60), d_fixed=6, d_random=3
    )
    return data, truth


def build_coordinates(data, re_cfg=None):
    fixed_batch = build_fixed_effect_batch(data, "global", dense=True)
    fixed = FixedEffectCoordinate(
        fixed_batch,
        GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=50, tolerance=1e-7),
            RegularizationContext.l2(1e-2),
        ),
    )
    re_cfg = re_cfg or RandomEffectDataConfig("userId", "per_user")
    re_ds = build_random_effect_dataset(data, re_cfg)
    random = RandomEffectCoordinate(
        re_ds,
        TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=40, tolerance=1e-6),
        RegularizationContext.l2(1e-1),
    )
    return fixed, random


def test_coordinate_descent_glmix(glmix):
    data, truth = glmix
    fixed, random = build_coordinates(data)
    n = data.num_rows
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))

    cd = CoordinateDescent({"fixed": fixed, "random": random}, loss_fn)
    result = cd.run(num_iterations=2, num_rows=n)

    # objective decreases over updates
    hist = result.objective_history
    assert hist[-1] < hist[0]
    # GAME model separates classes far better than fixed effect alone
    auc_game = float(area_under_roc_curve(result.total_scores, labels))

    cd_fixed = CoordinateDescent({"fixed": build_coordinates(data)[0]}, loss_fn)
    result_fixed = cd_fixed.run(num_iterations=1, num_rows=n)
    auc_fixed = float(area_under_roc_curve(result_fixed.total_scores, labels))

    assert auc_game > auc_fixed + 0.02, (auc_game, auc_fixed)
    assert auc_game > 0.9, auc_game

    # total score == sum of coordinate scores (GAMEModel.scala:92-94)
    total = sum(
        np.asarray(
            (fixed if name == "fixed" else random).score(result.coefficients[name])
        )
        for name in ("fixed", "random")
    )
    np.testing.assert_allclose(np.asarray(result.total_scores), total, rtol=1e-4, atol=1e-4)


def test_random_effect_recovers_per_user_signal(glmix):
    """With no fixed effect, per-user solves should approximate w_users on
    entities with enough data."""
    data, truth = glmix
    _, random = build_coordinates(data)
    n = data.num_rows
    zero_off = jnp.zeros((n,), jnp.float32)
    # include the fixed-effect part of the margin as offsets (oracle), so the
    # random-effect solve sees exactly its own residual problem
    oracle_off = jnp.asarray(truth["x_fixed"] @ truth["w_fixed"])
    coeffs, results = jax.jit(random.update)(oracle_off, random.initial_coefficients())
    # scoring correlation with the true per-user margin component
    score = np.asarray(random.score(coeffs))
    true_component = np.sum(
        truth["x_random"] * truth["w_users"][truth["user_of_row"]], axis=1
    )
    corr = np.corrcoef(score, true_component)[0, 1]
    assert corr > 0.85, corr


def test_tron_random_effect(glmix):
    data, truth = glmix
    re_ds = build_random_effect_dataset(data, RandomEffectDataConfig("userId", "per_user"))
    random = RandomEffectCoordinate(
        re_ds,
        TaskType.LOGISTIC_REGRESSION,
        OptimizerType.TRON,
        OptimizerConfig(max_iterations=10, tolerance=1e-5),
        RegularizationContext.l2(1e-1),
    )
    n = data.num_rows
    coeffs, results = jax.jit(random.update)(
        jnp.zeros((n,), jnp.float32), random.initial_coefficients()
    )
    assert np.all(np.isfinite(np.asarray(coeffs)))
    # per-entity convergence reasons are tracked per lane
    assert results.reason.shape == (re_ds.num_entities,)
    assert np.all(np.asarray(results.reason) > 0)


def test_sharded_random_effect_update(glmix):
    """Entity axis sharded over the mesh: vmapped solves distribute."""
    data, truth = glmix
    n_dev = len(jax.devices())
    re_cfg = RandomEffectDataConfig("userId", "per_user", num_shards=n_dev)
    re_ds = build_random_effect_dataset(data, re_cfg)
    assert re_ds.num_entities % n_dev == 0
    random = RandomEffectCoordinate(
        re_ds, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=30, tolerance=1e-6),
        RegularizationContext.l2(1e-1),
    )
    n = data.num_rows

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("entity",))
    sharding = NamedSharding(mesh, P("entity"))
    w0 = jax.device_put(random.initial_coefficients(), sharding)
    coeffs, _ = jax.jit(random.update)(jnp.zeros((n,), jnp.float32), w0)
    coeffs_local, _ = jax.jit(random.update)(
        jnp.zeros((n,), jnp.float32), random.initial_coefficients()
    )
    np.testing.assert_allclose(np.asarray(coeffs), np.asarray(coeffs_local),
                               rtol=1e-4, atol=1e-4)


def test_fused_cycle_matches_unfused(glmix):
    """fused_cycle=True (one XLA program per full iteration) must reproduce
    the per-update loop exactly: same coefficients, same objective history
    length and values, same total scores."""
    data, _ = glmix
    n = data.num_rows
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))

    results = {}
    for fused in (False, True):
        fixed, random = build_coordinates(data)
        cd = CoordinateDescent(
            {"fixed": fixed, "random": random}, loss_fn, fused_cycle=fused
        )
        results[fused] = cd.run(num_iterations=2, num_rows=n)

    a, b = results[False], results[True]
    assert len(a.objective_history) == len(b.objective_history) == 4
    np.testing.assert_allclose(
        np.asarray(b.objective_history), np.asarray(a.objective_history),
        rtol=1e-5,
    )
    for name in ("fixed", "random"):
        np.testing.assert_allclose(
            np.asarray(b.coefficients[name]), np.asarray(a.coefficients[name]),
            rtol=1e-4, atol=1e-5,
        )
    np.testing.assert_allclose(
        np.asarray(b.total_scores), np.asarray(a.total_scores), rtol=1e-4, atol=1e-4
    )
    assert "(fused-cycle)" in b.timings


def test_fused_cycle_checkpoint_iteration_granularity(glmix, tmp_path):
    """Fused-cycle checkpoints land at iteration boundaries and resume
    bit-exactly into a fresh fused run."""
    from photon_ml_tpu.checkpoint import CoordinateDescentCheckpointer

    data, _ = glmix
    n = data.num_rows
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))

    def make_cd():
        fixed, random = build_coordinates(data)
        return CoordinateDescent(
            {"fixed": fixed, "random": random}, loss_fn, fused_cycle=True
        )

    ck = CoordinateDescentCheckpointer(str(tmp_path / "ck"), run_fingerprint="f")
    full = make_cd().run(num_iterations=2, num_rows=n, checkpointer=ck)
    assert ck.latest_step() == 4  # 2 iterations x 2 coordinates

    # resume from the checkpoint: no further iterations needed, identical state
    resumed = make_cd().run(num_iterations=2, num_rows=n,
                            checkpointer=CoordinateDescentCheckpointer(
                                str(tmp_path / "ck"), run_fingerprint="f"))
    np.testing.assert_array_equal(
        np.asarray(resumed.total_scores), np.asarray(full.total_scores)
    )


def test_fused_resume_rejects_mid_iteration_checkpoint(glmix, tmp_path):
    """A per-update checkpoint taken MID-iteration cannot resume into
    fused-cycle mode (which replays whole iterations): the guard must raise
    with guidance instead of silently recomputing or skipping updates."""
    import shutil

    from photon_ml_tpu.checkpoint import CoordinateDescentCheckpointer

    data, _ = glmix
    n = data.num_rows
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))

    fixed, random = build_coordinates(data)
    ck_dir = str(tmp_path / "ck")
    cd = CoordinateDescent({"fixed": fixed, "random": random}, loss_fn)
    cd.run(num_iterations=1, num_rows=n,
           checkpointer=CoordinateDescentCheckpointer(ck_dir, run_fingerprint="x"))
    # drop the iteration-final checkpoint so only the mid-iteration one
    # (after coordinate 1 of 2) remains
    shutil.rmtree(str(tmp_path / "ck" / "step-2"))
    ck = CoordinateDescentCheckpointer(ck_dir, run_fingerprint="x")
    assert ck.latest_step() == 1

    fixed2, random2 = build_coordinates(data)
    cd_fused = CoordinateDescent(
        {"fixed": fixed2, "random": random2}, loss_fn, fused_cycle=True
    )
    with pytest.raises(ValueError, match="mid-iteration"):
        cd_fused.run(num_iterations=1, num_rows=n, checkpointer=ck)


def test_trackers_surface_per_coordinate_convergence(glmix):
    """CoordinateDescentResult.trackers: the last update's OptResult per
    coordinate (per-entity stacked for random effects) — the reference's
    OptimizationTracker raw material."""
    from photon_ml_tpu.optim.common import OptResult
    from photon_ml_tpu.types import ConvergenceReason

    data, _ = glmix
    n = data.num_rows
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))
    fixed, random = build_coordinates(data)
    cd = CoordinateDescent({"fixed": fixed, "random": random}, loss_fn)
    result = cd.run(num_iterations=1, num_rows=n)

    assert set(result.trackers) == {"fixed", "random"}
    fe = result.trackers["fixed"]
    assert isinstance(fe, OptResult) and np.asarray(fe.reason).ndim == 0
    assert int(fe.iterations) > 0
    re = result.trackers["random"]
    reasons = np.asarray(re.reason)
    assert reasons.shape == (random.num_entities,)
    valid = {r.value for r in ConvergenceReason}
    assert set(np.unique(reasons).tolist()) <= valid

    # fused mode documents empty trackers
    fixed2, random2 = build_coordinates(data)
    cd_f = CoordinateDescent(
        {"fixed": fixed2, "random": random2}, loss_fn, fused_cycle=True
    )
    assert cd_f.run(num_iterations=1, num_rows=n).trackers == {}


def test_summarize_tracker_formats_all_shapes(glmix):
    """_summarize_tracker must actually emit text for every tracker shape
    (OptResult is a NamedTuple, i.e. a tuple — the bucketed branch must not
    shadow it)."""
    from photon_ml_tpu.cli.game_training_driver import _summarize_tracker

    data, _ = glmix
    n = data.num_rows
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))
    fixed, random = build_coordinates(data)
    cd = CoordinateDescent({"fixed": fixed, "random": random}, loss_fn)
    result = cd.run(num_iterations=1, num_rows=n)

    fe_summary = _summarize_tracker(result.trackers["fixed"])
    assert "reason=" in fe_summary and "iters=" in fe_summary
    re_summary = _summarize_tracker(result.trackers["random"])
    assert "convergenceReasons=" in re_summary
    assert f"entities={random.num_entities}" in re_summary
    # bucketed trackers: a tuple OF OptResults renders per bucket
    both = _summarize_tracker((result.trackers["random"], result.trackers["random"]))
    assert both.count("convergenceReasons=") == 2 and "bucket0:" in both
    assert _summarize_tracker(None) == ""


def test_distributed_trackers_are_trimmed_at_source(glmix):
    """Entity-sharded solvers must return trackers covering REAL entities
    only — the padding pseudo-solves the mesh adds are trimmed before any
    consumer sees them (trim_entity_tracker), so convergence logs are not
    skewed by zero-row lanes."""
    from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
    from photon_ml_tpu.data.game import (
        RandomEffectDataConfig,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.parallel import MeshContext, data_mesh
    from photon_ml_tpu.parallel.distributed import DistributedRandomEffectSolver

    data, _ = glmix
    ds = build_random_effect_dataset(
        data, RandomEffectDataConfig("userId", "per_user")
    )
    coord = RandomEffectCoordinate(
        ds,
        TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=10, tolerance=1e-7),
        RegularizationContext.l2(0.1),
    )
    solver = DistributedRandomEffectSolver(coord, MeshContext(data_mesh(8)))
    assert solver.padded_entities > ds.num_entities  # padding actually happens
    resid = jnp.zeros((data.num_rows,), jnp.float32)
    coefs, tracker = solver.update(resid, solver.initial_coefficients())
    # coefficients keep the padded sharded shape; the tracker does not
    assert coefs.shape[0] == solver.padded_entities
    assert np.asarray(tracker.reason).shape[0] == ds.num_entities
    assert np.asarray(tracker.iterations).shape[0] == ds.num_entities


def test_full_game_four_coordinate_cycle():
    """make_full_game_data (BASELINE config-5 shape) through coordinate
    descent with the SHARED 4-coordinate stack (make_full_game_coords —
    the same wiring bench.py times): objective decreases across cycles,
    scores finite, AUC strong, fused == unfused."""
    from game_test_utils import make_full_game_coords, make_full_game_data

    rng = np.random.default_rng(9)
    data, _ = make_full_game_data(
        rng, num_users=20, num_items=8, num_artists=4,
        rows_per_user_range=(6, 12),
        d_fixed=5, d_user=3, d_item=3, d_artist=4,
    )
    n = data.num_rows
    coords = make_full_game_coords(
        data, fe_iters=20, re_iters=15, mf_re_iters=8, latent_dim=2
    )
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))
    for fused in (False, True):
        cd = CoordinateDescent(coords, loss_fn, fused_cycle=fused)
        result = cd.run(num_iterations=2, num_rows=n)
        objs = result.objective_history
        assert len(objs) == 8  # 2 iterations x 4 coordinates
        # descent across full cycles (per-update values can wiggle when a
        # coordinate re-fits against new residuals)
        assert objs[-1] <= objs[0]
        total = np.asarray(result.total_scores)
        assert np.isfinite(total).all()
        from photon_ml_tpu.evaluation import area_under_roc_curve

        assert float(area_under_roc_curve(result.total_scores, labels)) > 0.8


@pytest.mark.slow  # ~8s: warm-start-from-initial-params stays tier-1 via test_retrain.py's warm-start pins and test_vmapped_grid.py test_grid_warm_start_reaches_same_optima
def test_initial_params_warm_start(glmix):
    """run(initial_params=...) seeds named coordinates from a previous
    result (the grid warm-start hook): a second run warm-started from a
    converged fit must land on the same solution and not regress the
    objective on its first update."""
    data, _ = glmix
    fixed, random = build_coordinates(data)
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))
    cd = CoordinateDescent({"fixed": fixed, "random": random}, loss_fn)
    first = cd.run(num_iterations=3, num_rows=data.num_rows)

    f2, r2 = build_coordinates(data)
    cd2 = CoordinateDescent({"fixed": f2, "random": r2}, loss_fn)
    warm = cd2.run(
        num_iterations=1, num_rows=data.num_rows,
        initial_params=first.coefficients,
    )
    # warm-started single iteration stays at/below the 3-iteration
    # objective (the warm params' scores seed the residuals, so update one
    # CONTINUES the descent rather than restarting it) ...
    assert warm.objective_history[-1] <= first.objective_history[-1] + 1e-3
    # ... and beats a cold single iteration
    f4, r4 = build_coordinates(data)
    cold = CoordinateDescent({"fixed": f4, "random": r4}, loss_fn).run(
        num_iterations=1, num_rows=data.num_rows
    )
    assert warm.objective_history[-1] <= cold.objective_history[-1] + 1e-3
    # partial maps fall back to the coordinate's own init for missing names
    only_fixed = {"fixed": first.coefficients["fixed"]}
    f3, r3 = build_coordinates(data)
    cd3 = CoordinateDescent({"fixed": f3, "random": r3}, loss_fn)
    partial = cd3.run(
        num_iterations=1, num_rows=data.num_rows, initial_params=only_fixed
    )
    assert np.isfinite(partial.objective_history[-1])
