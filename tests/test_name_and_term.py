"""NameAndTermFeatureSetContainer — the deprecated whole-dataset vocabulary
path (avro/data/NameAndTermFeatureSetContainer.scala:38-260; VERDICT r2
missing #4): generation CLI, text round-trip, section-union index maps, and
GAME-driver integration via --feature-name-and-term-set-path.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import schemas
from photon_ml_tpu.io.index_map import feature_key
from photon_ml_tpu.io.name_and_term import (
    INTERCEPT_NAME_AND_TERM,
    NameAndTermFeatureSetContainer,
    main as nt_main,
)

SCHEMA = {
    "name": "Row",
    "namespace": "t",
    "type": "record",
    "fields": [
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": schemas.FEATURE}},
        {
            "name": "userFeatures",
            "type": {
                "type": "array",
                "items": "com.linkedin.photon.avro.generated.FeatureAvro",
            },
        },
    ],
}


@pytest.fixture
def avro_dir(tmp_path):
    recs = [
        {
            "label": 1.0,
            "features": [
                {"name": "age", "term": "", "value": 1.0},
                {"name": "geo", "term": "us", "value": 1.0},
            ],
            "userFeatures": [{"name": "u", "term": "0", "value": 0.5}],
        },
        {
            "label": 0.0,
            "features": [{"name": "geo", "term": "de", "value": 1.0}],
            "userFeatures": [{"name": "u", "term": "1", "value": 0.25}],
        },
    ]
    d = tmp_path / "data"
    d.mkdir()
    avro_io.write_container(str(d / "p.avro"), recs, SCHEMA)
    return str(d)


class TestContainer:
    def test_generate_save_read_round_trip(self, avro_dir, tmp_path):
        out = str(tmp_path / "nt")
        container = nt_main(
            [
                "--data-input-directory", avro_dir,
                "--feature-name-and-term-set-output-dir", out,
                "--feature-section-keys", "features,userFeatures",
            ]
        )
        assert container.feature_sets["features"] == {
            ("age", ""), ("geo", "us"), ("geo", "de"),
        }
        assert container.feature_sets["userFeatures"] == {("u", "0"), ("u", "1")}
        # text layout: one subdir per section, name\tterm lines
        lines = open(os.path.join(out, "features", "part-00000")).read().splitlines()
        assert "geo\tus" in lines and "age\t" in lines

        back = NameAndTermFeatureSetContainer.read_from_text(
            out, ["features", "userFeatures"]
        )
        assert back.feature_sets == container.feature_sets

    def test_union_index_map_with_intercept(self, avro_dir, tmp_path):
        out = str(tmp_path / "nt")
        container = nt_main(
            [
                "--data-input-directory", avro_dir,
                "--feature-name-and-term-set-output-dir", out,
                "--feature-section-keys", "features,userFeatures",
            ]
        )
        m = container.feature_name_and_term_to_index_map(
            ["features", "userFeatures"], add_intercept=True
        )
        assert len(m) == 6  # 5 features + intercept
        assert m[INTERCEPT_NAME_AND_TERM] == 5  # intercept appended last
        assert set(m.values()) == set(range(6))

        imap = container.index_map(["features"], add_intercept=False)
        assert len(imap) == 3
        assert imap.get_index(feature_key("geo", "us")) >= 0
        assert imap.get_index(feature_key("u", "0")) < 0  # other section

    def test_malformed_line_raises(self, tmp_path):
        d = tmp_path / "nt" / "features"
        d.mkdir(parents=True)
        (d / "part-00000").write_text("a\tb\tc\n")
        with pytest.raises(ValueError, match="tab-separated"):
            NameAndTermFeatureSetContainer.read_from_text(str(tmp_path / "nt"), ["features"])


class TestGameDriverIntegration:
    def test_driver_uses_name_and_term_vocab(self, avro_dir, tmp_path):
        """Training with --feature-name-and-term-set-path must build shard
        maps from the saved vocabulary, not a dataset scan: a feature absent
        from the vocab (but present in data) gets no index."""
        from photon_ml_tpu.cli import game_training_driver

        nt_dir = str(tmp_path / "nt")
        nt_main(
            [
                "--data-input-directory", avro_dir,
                "--feature-name-and-term-set-output-dir", nt_dir,
                "--feature-section-keys", "features,userFeatures",
            ]
        )
        # drop one feature from the saved vocab to prove the vocab governs
        feats_file = os.path.join(nt_dir, "features", "part-00000")
        kept = [l for l in open(feats_file).read().splitlines() if not l.startswith("age")]
        open(feats_file, "w").write("\n".join(kept) + "\n")

        driver = game_training_driver.main(
            [
                "--train-input-dirs", avro_dir,
                "--output-dir", str(tmp_path / "out"),
                "--task-type", "LOGISTIC_REGRESSION",
                "--updating-sequence", "fixed",
                "--feature-shard-id-to-feature-section-keys-map", "global:features",
                "--feature-name-and-term-set-path", nt_dir,
                "--fixed-effect-data-configurations", "fixed:global,1",
                "--fixed-effect-optimization-configurations", "fixed:5,1e-4,1,1,LBFGS,L2",
                "--delete-output-dir-if-exists", "true",
            ]
        )
        imap = driver.shard_index_maps["global"]
        assert imap.get_index(feature_key("geo", "us")) >= 0
        assert imap.get_index(feature_key("age", "")) < 0  # dropped from vocab
        assert len(imap) == 3  # geo:us, geo:de + intercept
