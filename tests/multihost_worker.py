"""Worker for the 2-process multi-host harness (launched by
test_multihost.py; also runnable by hand:

    python tests/multihost_worker.py <proc_id> <nprocs> <port>

Each process gets 4 virtual CPU devices, ingests ONLY its row block of a
synthetic GLM dataset (per-host ingest), assembles the globally row-sharded
batch, runs the SAME DistributedFixedEffectSolver SPMD program, and prints
the trained coefficients. The test asserts both processes print coefficients
identical to a single-process fit — proving the psum-in-kernel solver is
host-count-invariant (SURVEY.md §3.5 driver/executor split, re-expressed)."""

import os
import sys

proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from photon_ml_tpu.parallel import multihost

mh = multihost.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nprocs, process_id=proc_id
)
assert mh.num_processes == nprocs and mh.process_id == proc_id
assert len(jax.devices()) == 4 * nprocs, jax.devices()

import jax.numpy as jnp

from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.parallel.distributed import DistributedFixedEffectSolver
from photon_ml_tpu.types import OptimizerType, TaskType

# -- the full dataset is DEFINED globally (seeded), INGESTED per host -------
# N deliberately NOT divisible by hosts*devices: the tail host's short block
# is zero-padded back to the uniform rows_per_host size (weight 0)
N, D = 500, 6
rng = np.random.default_rng(42)
x_all = rng.normal(size=(N, D)).astype(np.float32)
w_true = rng.normal(size=D).astype(np.float32)
y_all = (1.0 / (1.0 + np.exp(-x_all @ w_true)) > rng.random(N)).astype(np.float32)

ctx = mh.mesh_context()
sl = mh.host_row_slice(N, ctx)  # this host reads ONLY its block
x_loc, y_loc = x_all[sl], y_all[sl]

x_g = mh.global_row_sharded(x_loc, ctx, n_global=N)
y_g = mh.global_row_sharded(y_loc, ctx, n_global=N)
w_g = mh.global_row_sharded(np.ones(len(y_loc), np.float32), ctx, n_global=N)
batch = GLMBatch.create(DenseFeatures(x_g), y_g, weights=w_g)

problem = GLMOptimizationProblem(
    TaskType.LOGISTIC_REGRESSION,
    OptimizerType.LBFGS,
    OptimizerConfig(max_iterations=40, tolerance=1e-9),
    RegularizationContext.l2(0.5),
)
solver = DistributedFixedEffectSolver(problem, ctx)
model, result = solver.run(batch, NormalizationContext.identity())
coefs = np.asarray(jax.device_get(model.coefficients.means))

mh.barrier("after-solve")
# coordinator-gated side effect: only process 0 writes the model file
outdir = sys.argv[4] if len(sys.argv) > 4 else None
if outdir and mh.coordinator_only_io():
    np.save(os.path.join(outdir, "coefs.npy"), coefs)
mh.barrier("after-save")

# -- multihost-safe checkpoint: sharded leaves allgathered, coordinator
# writes, barriers fence (checkpoint.py multihost mode) ---------------------
if outdir:
    from photon_ml_tpu.checkpoint import CheckpointState, CoordinateDescentCheckpointer

    scores = jax.jit(lambda b, w: b.features.matvec(w))(
        batch, model.coefficients.means
    )  # (N,) row-sharded ACROSS HOSTS -> not fully addressable
    assert not scores.is_fully_addressable
    ck = CoordinateDescentCheckpointer(
        os.path.join(outdir, "ckpt"), run_fingerprint="mh-test", multihost=mh
    )
    ck.save(
        CheckpointState(
            step=1,
            params={"fe": model.coefficients.means},
            scores={"fe": scores},
            total_scores=scores,
            objective_history=[float(result.value)],
            validation_history=[],
        )
    )
    if mh.coordinator_only_io():
        n_pad = x_g.shape[0]  # global rows incl. the tail host's zero padding
        restored = ck.restore(
            {"fe": np.zeros(D, np.float32)},
            {"fe": np.zeros(n_pad, np.float32)},
            np.zeros(n_pad, np.float32),
            # coordinator-only read-back: the collective-min agreement
            # would deadlock (process 1 is not in this branch)
            agree=False,
        )
        full_scores = x_all @ coefs
        got = np.asarray(restored.total_scores)
        np.testing.assert_allclose(got[:N], full_scores, rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(got[N:], 0.0)  # padding rows score 0
        print("MHCKPT-OK", flush=True)
    mh.barrier("after-ckpt-check")

# -- multihost health fencing: per-host heartbeats, barrier deadline (the
# completing path), and the collective-min restore-step agreement — host 1
# deliberately MISSES the latest checkpoint step, and both hosts must agree
# to restore the newest step EVERY host can serve ---------------------------
if outdir:
    hb_dir = os.path.join(outdir, "heartbeats")
    mh.write_heartbeat(hb_dir, step=1)
    mh.barrier("heartbeats-written", timeout=60)  # deadline path, completing
    ages = mh.heartbeat_ages(hb_dir)
    assert sorted(ages) == list(range(nprocs)), ages
    assert all(age < 60 for age in ages.values()), ages
    if mh.coordinator_only_io():
        desc = mh.describe_heartbeats(hb_dir)
        assert "NO HEARTBEAT" not in desc, desc
        print("MHHB-OK", flush=True)

    # per-host (NON-shared) checkpoint dirs: host 0 commits steps 1 and 2,
    # host 1 only step 1 (its "crash" lost the latest commit)
    per_host_dir = os.path.join(outdir, f"ckpt-host-{proc_id}")
    local_ck = CoordinateDescentCheckpointer(per_host_dir, run_fingerprint="agree")
    tiny = np.arange(4, dtype=np.float32)

    def tiny_state(step):
        return CheckpointState(
            step=step, params={"w": tiny + step}, scores={"w": tiny},
            total_scores=tiny, objective_history=[float(step)],
            validation_history=[],
        )

    local_ck.save(tiny_state(1))
    if proc_id == 0:
        local_ck.save(tiny_state(2))
    agreed = mh.agree_restore_step(local_ck.latest_step())
    assert agreed == 1, (proc_id, agreed)
    restored = local_ck.restore(
        {"w": tiny}, {"w": tiny}, tiny, max_step=agreed
    )
    assert restored is not None and restored.step == 1, proc_id
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), tiny + 1)
    mh.barrier("agree-check")
    if mh.coordinator_only_io():
        print("MHAGREE-OK", flush=True)

print(f"MHOK proc={proc_id} coefs={','.join(f'{c:.6f}' for c in coefs)}", flush=True)

# -- entity parallelism ACROSS HOSTS: each host ingests only ITS entity
# block (per-host entity ingest, the RandomEffectIdPartitioner analogue at
# host granularity), solves its entities' local GLMs with the vmapped
# kernel under shard_map, and scores its own rows locally ---------------------
import jax.numpy as jnp2  # noqa: E402 (alias to keep the FE section intact)
from photon_ml_tpu.compat import shard_map  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from photon_ml_tpu.optim.lbfgs import lbfgs_minimize_  # noqa: E402
from photon_ml_tpu.ops.features import DenseFeatures as DF  # noqa: E402
from photon_ml_tpu.ops.normalization import NormalizationContext as NC  # noqa: E402
from photon_ml_tpu.ops.objective import GLMBatch as GB, GLMObjective  # noqa: E402
from photon_ml_tpu.ops import losses as losses_mod  # noqa: E402
from photon_ml_tpu.optim.common import OptimizerConfig as OC  # noqa: E402

E_GLOBAL, M, DR = 16, 6, 3  # entities x samples-per-entity x local dim
rng_re = np.random.default_rng(7)
x_re_all = rng_re.normal(size=(E_GLOBAL, M, DR)).astype(np.float32)
w_true_re = rng_re.normal(size=(E_GLOBAL, DR)).astype(np.float32)
z_all = np.einsum("emd,ed->em", x_re_all, w_true_re)
y_re_all = (1.0 / (1.0 + np.exp(-z_all)) > rng_re.random((E_GLOBAL, M))).astype(np.float32)

e_per = E_GLOBAL // nprocs
esl = slice(proc_id * e_per, (proc_id + 1) * e_per)  # this host's entity block
mesh = ctx.mesh
esh = NamedSharding(mesh, P(ctx.axis))
x_re = jax.make_array_from_process_local_data(esh, x_re_all[esl])
y_re = jax.make_array_from_process_local_data(esh, y_re_all[esl])

obj = GLMObjective(losses_mod.logistic)
cfg = OC(max_iterations=25, tolerance=1e-9)


def solve_shard(x_s, y_s):
    def solve_one(x_e, y_e):
        batch = GB.create(DF(x_e), y_e)
        vg = lambda wt: obj.value_and_grad(wt, batch, NC.identity(), 1.0)
        return lbfgs_minimize_(vg, jnp.zeros((DR,), jnp.float32), cfg).coefficients

    return jax.vmap(solve_one)(x_s, y_s)


re_solve = jax.jit(
    shard_map(
        solve_shard, mesh=mesh, in_specs=(P(ctx.axis), P(ctx.axis)),
        out_specs=P(ctx.axis), check_vma=False,
    )
)
w_re = re_solve(x_re, y_re)  # (E_GLOBAL, DR) entity-sharded across hosts
# owner-computes scoring of THIS HOST's rows (it ingested its entities' rows)
w_re_local = np.asarray(
    jax.device_get([s.data for s in w_re.addressable_shards])
).reshape(-1, DR)
scores_local = np.einsum("emd,ed->em", x_re_all[esl], w_re_local)
mh.barrier("re-done")
print(
    f"MHRE proc={proc_id} wsum={float(np.sum(w_re_local)):.6f} "
    f"ssum={float(np.sum(scores_local)):.6f}",
    flush=True,
)

# -- the PRODUCTION random-effect stack across hosts, with TRUE per-host
# ingest: each host converts only ITS row block to HostRows, the collective
# shuffle routes rows to entity owners, and each host builds only its slab
# (parallel.perhost_ingest — no replicated host-side build anywhere) --------
import tracemalloc  # noqa: E402

from game_test_utils import make_glmix_data  # noqa: E402
from photon_ml_tpu.parallel.perhost_ingest import (  # noqa: E402
    HostRows,
    PerHostRandomEffectSolver,
    per_host_re_dataset,
)

rng_g = np.random.default_rng(31)  # the DATASET is seeded; the DECODE is per host
gdata, _ = make_glmix_data(
    rng_g, num_users=1500, rows_per_user_range=(8, 20), d_fixed=4, d_random=6
)
n_rows_g = gdata.num_rows
# simulate this host's Avro partition decode: keep ONLY the host's row block
lo = proc_id * (n_rows_g // nprocs)
hi = n_rows_g if proc_id == nprocs - 1 else (proc_id + 1) * (n_rows_g // nprocs)
feats_g = gdata.shards["per_user"]
nnz = np.diff(feats_g.indptr)[lo:hi]
k_loc = max(int(nnz.max()) if len(nnz) else 1, 1)
fi_h = np.full((hi - lo, k_loc), -1, np.int32)
fv_h = np.zeros((hi - lo, k_loc), np.float32)
for r in range(hi - lo):
    s, e = feats_g.indptr[lo + r], feats_g.indptr[lo + r + 1]
    fi_h[r, : e - s] = feats_g.indices[s:e]
    fv_h[r, : e - s] = feats_g.values[s:e]
vocab_g = gdata.id_vocabs["userId"]
host_rows = HostRows(
    entity_raw_ids=[vocab_g[i] for i in gdata.ids["userId"][lo:hi]],
    row_index=np.arange(lo, hi, dtype=np.int64),
    labels=gdata.response[lo:hi].astype(np.float32),
    weights=gdata.weight[lo:hi].astype(np.float32),
    offsets=gdata.offset[lo:hi].astype(np.float32),
    feat_idx=fi_h,
    feat_val=fv_h,
    global_dim=feats_g.dim,
)
global_dim_g = feats_g.dim
del gdata, feats_g, fi_h, fv_h  # the full build must never exist on a host

tracemalloc.start()
sharded_ds = per_host_re_dataset(host_rows, ctx, nprocs, proc_id)
_, ingest_peak = tracemalloc.get_traced_memory()
tracemalloc.stop()

solver = PerHostRandomEffectSolver(
    sharded_ds,
    TaskType.LOGISTIC_REGRESSION,
    OptimizerType.LBFGS,
    OptimizerConfig(max_iterations=30, tolerance=1e-9),
    RegularizationContext.l2(0.3),
    ctx,
)
resid0 = mh.global_replicated(np.zeros(n_rows_g, np.float32), ctx)
coefs_re, tracker = solver.update(resid0, solver.initial_coefficients())
scores_dev = solver.score(coefs_re)  # psum-merged -> replicated, addressable
scores_re = np.asarray(jax.device_get(scores_dev))
from jax.experimental import multihost_utils  # noqa: E402

coefs_full = np.asarray(multihost_utils.process_allgather(coefs_re, tiled=True))
keys_full = np.asarray(
    multihost_utils.process_allgather(sharded_ds.entity_keys, tiled=True)
)
mask_full = np.asarray(
    multihost_utils.process_allgather(sharded_ds.entity_mask, tiled=True)
)
l2g_full = np.asarray(
    multihost_utils.process_allgather(sharded_ds.local_to_global, tiled=True)
)
mh.barrier("solver-re-done")
if outdir and mh.coordinator_only_io():
    np.savez(
        os.path.join(outdir, "re_perhost.npz"),
        coefs=coefs_full, keys=keys_full, mask=mask_full, l2g=l2g_full,
        global_dim=global_dim_g,
    )
    np.save(os.path.join(outdir, "re_scores.npy"), scores_re)
mh.barrier("solver-re-saved")
csum = float(np.sum(coefs_full[mask_full]))
# ingest_peak BEFORE csum: __graft_entry__ parses csum as the LAST token to
# assert cross-host agreement, and the peaks legitimately differ per host
print(
    f"MHRESOLVER proc={proc_id} ingest_peak={ingest_peak} csum={csum:.6f}",
    flush=True,
)

# -- UNCAPPED skewed distribution through SIZE-BUCKETED per-host slabs ------
# (VERDICT r4 next-round #2): one giant entity among thousands of
# singletons, rows interleaved across hosts. The global-max-padded slab for
# this shape would be ~singletons/devices x giant-width — never built here;
# the bucketed build pads each entity only to its bucket's width, so the
# per-host ingest peak must stay ~1/n_hosts of a single host's.
from photon_ml_tpu.parallel.perhost_ingest import (  # noqa: E402
    PerHostBucketedRandomEffectSolver,
)

rng_s = np.random.default_rng(53)
GIANT, SING, DS = 2048, 3000, 6
n_skew = GIANT + SING
ids_sk = np.array(["giant"] * GIANT + [f"s{i}" for i in range(SING)])
fi_sk = rng_s.integers(0, DS, size=(n_skew, 3)).astype(np.int32)
fv_sk = rng_s.normal(size=(n_skew, 3)).astype(np.float32)
y_sk = (rng_s.random(n_skew) < 0.5).astype(np.float32)
perm_sk = rng_s.permutation(n_skew)  # giant's rows land on BOTH hosts
ids_sk, fi_sk, fv_sk, y_sk = (
    ids_sk[perm_sk], fi_sk[perm_sk], fv_sk[perm_sk], y_sk[perm_sk]
)
lo_s = proc_id * (n_skew // nprocs)
hi_s = n_skew if proc_id == nprocs - 1 else (proc_id + 1) * (n_skew // nprocs)
skew_rows = HostRows(
    entity_raw_ids=list(ids_sk[lo_s:hi_s]),
    row_index=np.arange(lo_s, hi_s, dtype=np.int64),
    labels=y_sk[lo_s:hi_s],
    weights=np.ones(hi_s - lo_s, np.float32),
    offsets=np.zeros(hi_s - lo_s, np.float32),
    feat_idx=fi_sk[lo_s:hi_s],
    feat_val=fv_sk[lo_s:hi_s],
    global_dim=DS,
)
tracemalloc.start()
skew_ds = per_host_re_dataset(
    skew_rows, ctx, nprocs, proc_id, size_buckets=8
)
_, skew_peak = tracemalloc.get_traced_memory()
tracemalloc.stop()
bsolver = PerHostBucketedRandomEffectSolver(
    skew_ds,
    TaskType.LOGISTIC_REGRESSION,
    OptimizerType.LBFGS,
    OptimizerConfig(max_iterations=20, tolerance=1e-8),
    RegularizationContext.l2(0.3),
    ctx,
)
resid_sk = mh.global_replicated(np.zeros(n_skew, np.float32), ctx)
w_sk, _ = bsolver.update(resid_sk, bsolver.initial_coefficients())
ssum_sk = float(np.sum(np.asarray(jax.device_get(bsolver.score(w_sk)))))
print(
    f"MHSKEW proc={proc_id} ingest_peak={skew_peak} "
    f"padded={skew_ds.padded_elements} ssum={ssum_sk:.6f}",
    flush=True,
)
