"""GAME CLI config-string grammar units.

Reference specs: GLMOptimizationConfiguration.scala:41-75 (opt config
string), RandomEffectDataConfiguration.scala:66-124 (data config string),
MFOptimizationConfiguration.scala:23-55, grid via ';' separation
(cli/game/training/Driver.scala:330-337), shard-section maps
(cli/game/FeatureParams.scala).
"""

import pytest

from photon_ml_tpu.cli.game_params import (
    CoordinateOptConfig,
    parse_coordinate_config_grid,
    parse_coordinate_config_map,
    parse_evaluators,
    parse_factored_config_map,
    parse_fixed_effect_data_configs,
    parse_random_effect_data_configs,
    parse_shard_intercepts,
    parse_shard_sections,
)
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.types import OptimizerType, RegularizationType


class TestOptConfigGrammar:
    def test_full_string(self):
        c = CoordinateOptConfig.parse("50,1e-7,0.3,0.8,LBFGS,L2")
        assert c.max_iterations == 50
        assert c.tolerance == 1e-7
        assert c.reg_weight == 0.3
        assert c.down_sampling_rate == 0.8
        assert c.optimizer == OptimizerType.LBFGS
        assert c.reg_type == RegularizationType.L2

    def test_reference_default_equivalent(self):
        # GLMOptimizationConfiguration.scala:28 default: TRON(20, 1e-5), NONE
        c = CoordinateOptConfig()
        assert c.optimizer == OptimizerType.TRON
        assert (c.max_iterations, c.tolerance) == (20, 1e-5)
        assert c.reg_type == RegularizationType.NONE

    @pytest.mark.parametrize("bad", [
        "50,1e-7,0.3,0.8,LBFGS",          # 5 parts
        "50,1e-7,0.3,0.8,LBFGS,L2,extra", # 7 parts
        "50,1e-7,0.3,0,LBFGS,L2",         # rate 0
        "50,1e-7,0.3,1.5,LBFGS,L2",       # rate > 1
        "50,1e-7,0.3,1,SGD,L2",           # unknown optimizer
        "50,1e-7,0.3,1,LBFGS,L3",         # unknown reg type
    ])
    def test_rejects(self, bad):
        with pytest.raises(ValueError):
            CoordinateOptConfig.parse(bad)

    def test_case_insensitive_enums(self):
        c = CoordinateOptConfig.parse("10,1e-5,0,1,lbfgs,l1")
        assert c.optimizer == OptimizerType.LBFGS
        assert c.reg_type == RegularizationType.L1

    def test_map_and_grid(self):
        m = parse_coordinate_config_map("a:10,1e-5,0,1,LBFGS,L2|b:20,1e-4,1,1,TRON,NONE")
        assert set(m) == {"a", "b"}
        assert m["b"].optimizer == OptimizerType.TRON
        grid = parse_coordinate_config_grid(
            "a:10,1e-5,0.1,1,LBFGS,L2;a:10,1e-5,1.0,1,LBFGS,L2"
        )
        assert len(grid) == 2
        assert grid[0]["a"].reg_weight == 0.1 and grid[1]["a"].reg_weight == 1.0
        assert parse_coordinate_config_grid(None) == [{}]
        assert parse_coordinate_config_grid("") == [{}]

    def test_regularization_context_elastic_net(self):
        c = CoordinateOptConfig.parse("10,1e-5,2.0,1,LBFGS,ELASTIC_NET")
        ctx = c.regularization_context()
        # alpha-split of the total weight (RegularizationContext.scala)
        assert ctx.l1_weight + ctx.l2_weight == pytest.approx(2.0)


class TestDataConfigGrammar:
    def test_fixed_effect(self):
        m = parse_fixed_effect_data_configs("fixed:global,4|other:shardB,1")
        assert m["fixed"].feature_shard_id == "global"
        assert m["fixed"].min_partitions == 4  # accepted, obsolete
        assert parse_fixed_effect_data_configs(None) == {}

    def test_random_effect_full(self):
        m = parse_random_effect_data_configs(
            "per-user:userId,shardA,8,100,20,2.5,INDEX_MAP"
        )
        cfg = m["per-user"]
        assert cfg.random_effect_id == "userId"
        assert cfg.feature_shard_id == "shardA"
        assert cfg.active_upper_bound == 100
        assert cfg.passive_lower_bound == 20
        assert cfg.features_to_samples_ratio == 2.5
        assert cfg.projector == "INDEX_MAP"

    def test_negative_bounds_mean_unbounded(self):
        cfg = parse_random_effect_data_configs(
            "r:userId,s,1,-1,-1,-1,IDENTITY"
        )["r"]
        assert cfg.active_upper_bound is None
        assert cfg.passive_lower_bound is None
        assert cfg.features_to_samples_ratio is None

    def test_random_projector_dimension(self):
        cfg = parse_random_effect_data_configs(
            "r:userId,s,1,-1,-1,-1,RANDOM=16"
        )["r"]
        assert cfg.projector == "RANDOM" and cfg.random_projection_dim == 16
        with pytest.raises(ValueError, match="RANDOM projector"):
            parse_random_effect_data_configs("r:userId,s,1,-1,-1,-1,RANDOM")

    def test_wrong_arity_rejected(self):
        with pytest.raises(ValueError, match="expected reId"):
            parse_random_effect_data_configs("r:userId,s,1,-1,-1,IDENTITY")

    def test_factored_nested_configs(self):
        m = parse_factored_config_map(
            "mf:10,1e-5,0.5,1,LBFGS,L2:20,1e-6,1.0,1,LBFGS,L2:3,4"
        )
        spec = m["mf"]
        assert spec.random_effect.reg_weight == 0.5
        assert spec.latent_factor.max_iterations == 20
        assert (spec.mf_num_iterations, spec.latent_dim) == (3, 4)
        with pytest.raises(ValueError, match="mfIters,latentDim"):
            parse_factored_config_map("mf:10,1e-5,0,1,LBFGS,L2:20,1e-6,0,1,LBFGS,L2:3")


class TestShardAndEvaluatorGrammar:
    def test_shard_sections(self):
        m = parse_shard_sections("global:features,ctx|per_user:userFeatures")
        assert m["global"] == ["features", "ctx"]
        assert m["per_user"] == ["userFeatures"]
        assert parse_shard_sections(None) == {}

    def test_shard_intercepts(self):
        m = parse_shard_intercepts("global:true|per_user:false")
        assert m == {"global": True, "per_user": False}

    def test_evaluators(self):
        evs = parse_evaluators("AUC,RMSE,PRECISION@5:documentId,LOGISTIC_LOSS")
        assert evs[0] == (EvaluatorType.AUC, None, None)
        assert evs[2] == (EvaluatorType.PRECISION_AT_K, 5, "documentId")
        assert parse_evaluators(None) == []
        with pytest.raises(ValueError):
            parse_evaluators("NOT_A_METRIC")


class TestObsoleteSparkFlags:
    def test_training_parser_accepts_spark_era_flags(self):
        """A reference spark-submit command migrated verbatim must parse:
        partitioning knobs are accepted (and ignored on TPU), Appendix A.2."""
        from photon_ml_tpu.cli.game_params import parse_training_params

        p = parse_training_params([
            "--train-input-dirs", "/in",
            "--task-type", "LOGISTIC_REGRESSION",
            "--output-dir", "/out",
            "--updating-sequence", "fixed",
            "--fixed-effect-data-configurations", "fixed:global,4",
            "--min-partitions-for-validation", "8",
            "--offheap-indexmap-num-partitions", "2",
        ])
        assert p.updating_sequence == ["fixed"]

    def test_scoring_parser_accepts_spark_era_flags(self):
        from photon_ml_tpu.cli.game_params import parse_scoring_params

        p = parse_scoring_params([
            "--input-dirs", "/in",
            "--game-model-input-dir", "/model",
            "--output-dir", "/out",
            "--min-partitions-for-random-effect-model", "16",
            "--offheap-indexmap-num-partitions", "2",
        ])
        assert p.output_dir == "/out"


class TestSolveCompactionFlag:
    _BASE = [
        "--train-input-dirs", "/in",
        "--task-type", "LOGISTIC_REGRESSION",
        "--output-dir", "/out",
        "--updating-sequence", "fixed",
        "--fixed-effect-data-configurations", "fixed:global,4",
    ]

    def _parse(self, *extra):
        from photon_ml_tpu.cli.game_params import parse_training_params

        return parse_training_params(self._BASE + list(extra))

    def test_spellings(self, monkeypatch):
        from photon_ml_tpu.optim.scheduler import resolve_schedule

        # the default (no flag) genuinely defers to PHOTON_SOLVE_CHUNK
        assert self._parse().solve_compaction is None
        monkeypatch.delenv("PHOTON_SOLVE_CHUNK", raising=False)
        assert resolve_schedule(self._parse().solve_compaction) is None
        monkeypatch.setenv("PHOTON_SOLVE_CHUNK", "8")
        assert resolve_schedule(self._parse().solve_compaction).chunk_size == 8
        # an explicit flag beats the env
        assert self._parse("--solve-compaction", "off").solve_compaction == "off"
        assert resolve_schedule(
            self._parse("--solve-compaction", "off").solve_compaction
        ) is None
        p = self._parse("--solve-compaction", "16")
        assert resolve_schedule(p.solve_compaction).chunk_size == 16
        p = self._parse("--solve-compaction", "on")
        assert resolve_schedule(p.solve_compaction) is not None

    def test_bad_spec_rejected(self):
        with pytest.raises(ValueError, match="solve-compaction"):
            self._parse("--solve-compaction", "sideways")

    def test_fused_cycle_promotes_to_device_loop(self):
        """The --solve-compaction x --fused-cycle fence is DELETED: the
        plan promotes the schedule to the on-device rung loop
        (optim/fused_schedule.py) so no chunk pause re-enters the host —
        the combination parses and resolves with cycle_fusion='solve'."""
        p = self._parse("--solve-compaction", "on", "--fused-cycle", "true")
        assert p.fused_cycle and p.solve_compaction == "on"
        from photon_ml_tpu.compile.plan import ExecutionPlan

        plan = ExecutionPlan.resolve(
            solve_compaction=p.solve_compaction, fused_cycle=True
        )
        assert plan.schedule.loop == "device"
        assert plan.cycle_fusion == "solve"

    def test_distributed_composes(self):
        """The --solve-compaction x --distributed fence is DELETED: the
        plan composes them (GSPMD-sharded chunk kernels; the compaction
        loop stays host-side outside the mesh program)."""
        p = self._parse("--solve-compaction", "8", "--distributed", "true")
        assert p.distributed and p.solve_compaction == "8"
        from photon_ml_tpu.compile.plan import ExecutionPlan

        plan = ExecutionPlan.resolve(
            solve_compaction=p.solve_compaction, distributed=True
        )
        assert plan.sharding == "mesh" and plan.schedule.chunk_size == 8
        assert any(d.action == "composed" for d in plan.decisions)

    def test_spec_error_and_fence_reported_together(self):
        """validate() keeps its report-everything-at-once contract: a bad
        ladder spec is normalized to 'off' for the fence checks, so the
        spec error AND the adaptive-schedule x fused-cycle fence (a pair
        the plan still keeps) land in ONE error list instead of surfacing
        across two runs."""
        with pytest.raises(ValueError) as ei:
            self._parse(
                "--shape-canonicalization", "sideways",
                "--adaptive-schedule", "1e-2",
                "--fused-cycle", "true",
            )
        msg = str(ei.value)
        assert "--shape-canonicalization" in msg and "fused-cycle" in msg

    def test_vmapped_grid_true_fence_is_loud(self):
        """--vmapped-grid true x --solve-compaction: the silent runtime
        fallback is now a loud validate-time error (pinned message);
        'auto' keeps the documented fallback."""
        with pytest.raises(
            ValueError,
            match="--vmapped-grid true cannot compose with --solve-compaction",
        ):
            self._parse("--vmapped-grid", "true", "--solve-compaction", "4")
        p = self._parse("--vmapped-grid", "auto", "--solve-compaction", "4")
        assert p.vmapped_grid == "auto"
