"""Utility layer (timer/logger/date-range/text IO) + data validators."""

import datetime
import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import validators
from photon_ml_tpu.io.index_map import IndexMap, feature_key
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.types import DataValidationType, TaskType
from photon_ml_tpu.utils import (
    DateRange,
    PhotonLogger,
    Timer,
    expand_date_range_paths,
    prepare_output_dir,
    read_models_from_text,
    write_models_in_text,
)


# -- validators --------------------------------------------------------------


def _batch(x, y, offsets=None):
    return GLMBatch.create(
        DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
        jnp.asarray(offsets) if offsets is not None else None,
    )


def test_validators_pass_clean_data(rng):
    x = rng.normal(size=(20, 3)).astype(np.float32)
    y = (rng.random(20) > 0.5).astype(np.float32)
    validators.sanity_check_data(_batch(x, y), TaskType.LOGISTIC_REGRESSION)


def test_validators_reject_nonbinary_labels_for_logistic(rng):
    x = rng.normal(size=(10, 2)).astype(np.float32)
    y = np.linspace(0, 2, 10).astype(np.float32)
    with pytest.raises(ValueError, match="Binary labels"):
        validators.sanity_check_data(_batch(x, y), TaskType.LOGISTIC_REGRESSION)


def test_validators_reject_nan_features_and_offsets(rng):
    x = rng.normal(size=(10, 2)).astype(np.float32)
    x[3, 1] = np.nan
    y = (rng.random(10) > 0.5).astype(np.float32)
    with pytest.raises(ValueError, match="Finite features"):
        validators.sanity_check_data(_batch(x, y), TaskType.LOGISTIC_REGRESSION)
    x2 = rng.normal(size=(10, 2)).astype(np.float32)
    off = np.zeros(10, np.float32)
    off[0] = np.inf
    with pytest.raises(ValueError, match="Finite offsets"):
        validators.sanity_check_data(_batch(x2, y, off), TaskType.LOGISTIC_REGRESSION)


def test_validators_poisson_negative_labels(rng):
    x = rng.normal(size=(10, 2)).astype(np.float32)
    y = rng.normal(size=10).astype(np.float32)  # has negatives
    with pytest.raises(ValueError, match="Non-negative labels"):
        validators.sanity_check_data(_batch(x, y), TaskType.POISSON_REGRESSION)
    # disabled skips the check entirely
    validators.sanity_check_data(
        _batch(x, y), TaskType.POISSON_REGRESSION, DataValidationType.VALIDATE_DISABLED
    )


# -- timer / logger ----------------------------------------------------------


def test_timer_spans():
    t = Timer()
    with t.measure("phase1"):
        pass
    with t.measure("phase1"):
        pass
    assert t.totals["phase1"] >= 0.0
    with pytest.raises(RuntimeError):
        t.stop("never-started")
    assert "phase1" in t.summary()


def test_photon_logger_copies_on_close(tmp_path):
    out = tmp_path / "logs" / "photon.log"
    with PhotonLogger(str(out), echo=False) as log:
        log.info("hello world")
        log.debug("dropped below level")
    text = out.read_text()
    assert "hello world" in text
    assert "dropped" not in text


# -- date range --------------------------------------------------------------


def test_date_range_parsing_and_paths(tmp_path):
    r = DateRange.from_string("20160101-20160103")
    assert r.days() == [
        datetime.date(2016, 1, 1),
        datetime.date(2016, 1, 2),
        datetime.date(2016, 1, 3),
    ]
    for d in ("01", "03"):  # day 02 missing
        os.makedirs(tmp_path / "daily" / "2016" / "01" / d)
    paths = expand_date_range_paths(str(tmp_path), r)
    assert len(paths) == 2 and paths[0].endswith("01") and paths[1].endswith("03")
    with pytest.raises(FileNotFoundError):
        expand_date_range_paths(str(tmp_path), DateRange.from_string("20200101-20200102"))

    today = datetime.date(2016, 1, 10)
    r2 = DateRange.from_days_ago("9-7", today=today)
    assert r2.start == datetime.date(2016, 1, 1) and r2.end == datetime.date(2016, 1, 3)

    with pytest.raises(ValueError):
        DateRange.from_string("20160103-20160101")


# -- text model IO -----------------------------------------------------------


def test_write_read_models_in_text(tmp_path):
    imap = IndexMap.build([feature_key("f1", "a"), feature_key("f2", "")],
                          add_intercept=False)
    d = len(imap)
    means = np.zeros(d, np.float32)
    means[imap.get_index(feature_key("f1", "a"))] = 2.5
    means[imap.get_index(feature_key("f2", ""))] = -1.0
    model = GeneralizedLinearModel(Coefficients(jnp.asarray(means)),
                                   TaskType.LOGISTIC_REGRESSION)
    write_models_in_text([(0.5, model)], str(tmp_path / "models"), imap)
    back = read_models_from_text(str(tmp_path / "models"))
    assert back[0.5][("f1", "a")] == pytest.approx(2.5)
    assert back[0.5][("f2", "")] == pytest.approx(-1.0)
    # descending order by value in the file
    lines = (tmp_path / "models" / "part-00000.txt").read_text().splitlines()
    assert lines[0].startswith("f1\ta\t2.5")


def test_prepare_output_dir(tmp_path):
    target = tmp_path / "out"
    prepare_output_dir(str(target))
    (target / "junk.txt").write_text("x")
    with pytest.raises(FileExistsError):
        prepare_output_dir(str(target))
    prepare_output_dir(str(target), delete_if_exists=True)
    assert not list(target.iterdir())


def test_write_basic_statistics_avro(tmp_path, rng):
    from photon_ml_tpu.io.avro import read_container
    from photon_ml_tpu.ops.stats import summarize
    from photon_ml_tpu.utils import write_basic_statistics

    imap = IndexMap.build([feature_key("f1", ""), feature_key("f2", "t")],
                          add_intercept=False)
    x = rng.normal(size=(30, len(imap))).astype(np.float32)
    y = np.zeros(30, np.float32)
    summary = summarize(_batch(x, y))
    write_basic_statistics(summary, str(tmp_path / "stats"), imap)
    recs = list(read_container(str(tmp_path / "stats" / "part-00000.avro")))
    assert len(recs) == 2
    by_name = {(r["featureName"], r["featureTerm"]): r["metrics"] for r in recs}
    col = imap.get_index(feature_key("f2", "t"))
    assert by_name[("f2", "t")]["mean"] == pytest.approx(float(x[:, col].mean()), abs=1e-5)
    assert set(recs[0]["metrics"]) == {"max", "min", "mean", "normL1", "normL2",
                                       "numNonzeros", "variance"}


class TestProfilerHooks:
    """PHOTON_ML_TPU_PROFILE device-trace hooks (SURVEY §5.1 upgrade)."""

    def test_no_env_is_noop(self, monkeypatch):
        from photon_ml_tpu.utils.profiling import maybe_trace

        monkeypatch.delenv("PHOTON_ML_TPU_PROFILE", raising=False)
        with maybe_trace("stage"):
            pass  # must not require a profiler session

    @pytest.mark.slow  # ~20s: a real jax.profiler device trace; the hook's noop/enable contract stays tier-1 in test_no_env_is_noop
    def test_trace_writes_artifacts(self, monkeypatch, tmp_path):
        import jax.numpy as jnp

        from photon_ml_tpu.utils.profiling import annotate, maybe_trace

        monkeypatch.setenv("PHOTON_ML_TPU_PROFILE", str(tmp_path))
        with maybe_trace("unit"):
            with annotate("solve"):
                jnp.sum(jnp.ones((64, 64))).block_until_ready()
        stage_dir = tmp_path / "unit"
        assert stage_dir.is_dir()
        # a trace run produces at least one artifact under the stage dir
        assert any(stage_dir.rglob("*")), "no profiler artifacts written"


class TestNativeLibsvmParser:
    """native/libsvm_parser.cpp fast path vs the pure-Python parser —
    byte-identical CSR output (the data-loader half of the native runtime)."""

    def _write(self, path):
        path.write_text(
            "1 1:0.5 3:-1.25 7:2e-3  # trailing comment\n"
            "\n"
            "-1 2:1.0\n"
            "# full-line comment\n"
            "1 1:3.5\n"
            "-1 5:0.125 6:-0.5\n"
        )

    def test_differential_vs_python(self, tmp_path, monkeypatch):
        import numpy as np

        from photon_ml_tpu.io import libsvm, native_build

        f = tmp_path / "data.txt"
        self._write(f)
        native_lib = libsvm._load_lsv_native()
        if native_lib is None:
            pytest.skip("no native toolchain")
        ds_n = libsvm.read_libsvm(str(f))

        monkeypatch.setenv(native_build.NATIVE_ENV, "0")
        native_build._cache.clear()
        ds_p = libsvm.read_libsvm(str(f))
        native_build._cache.clear()  # don't leak the disabled state

        np.testing.assert_array_equal(ds_n.labels, ds_p.labels)
        np.testing.assert_array_equal(ds_n.indptr, ds_p.indptr)
        np.testing.assert_array_equal(ds_n.indices, ds_p.indices)
        np.testing.assert_array_equal(ds_n.values, ds_p.values)
        assert ds_n.dim == ds_p.dim
        # {-1,1} labels remapped to {0,1} on both paths
        assert set(np.unique(ds_n.labels).tolist()) == {0.0, 1.0}

    def test_zero_based_and_explicit_dim(self, tmp_path):
        from photon_ml_tpu.io import libsvm

        f = tmp_path / "zb.txt"
        f.write_text("0 0:1.0 2:2.0\n1 1:3.0\n")
        ds = libsvm.read_libsvm(str(f), zero_based=True, add_intercept=False, dim=5)
        assert ds.dim == 5
        assert ds.indices.tolist() == [0, 2, 1]
