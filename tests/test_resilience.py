"""Unit tests for the resilience subsystem: fault registry, retry policies,
divergence guards, and the corrupt-shard / retry wiring in the I/O layer."""

import json
import os

import numpy as np
import pytest

from photon_ml_tpu import resilience
from photon_ml_tpu.resilience import faults
from photon_ml_tpu.resilience.guards import DivergenceGuard, tree_all_finite
from photon_ml_tpu.resilience.retry import RetryError, RetryPolicy, call_with_retry


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# fault registry
# ---------------------------------------------------------------------------


class TestFaults:
    def test_no_plan_is_noop(self):
        faults.inject("io.read_block", path="x")
        assert faults.corrupt("optim.step", {"a": 1}) == {"a": 1}

    def test_at_fires_exactly_once_on_nth_hit(self):
        plan = faults.FaultPlan([faults.FaultSpec("io.read_block", at=3)])
        with faults.fault_scope(plan):
            faults.inject("io.read_block")
            faults.inject("io.read_block")
            with pytest.raises(faults.InjectedIOError):
                faults.inject("io.read_block")
            faults.inject("io.read_block")  # times defaults to 1 for `at`
        assert plan.fire_count("io.read_block") == 1
        assert plan.hits("io.read_block") == 4

    def test_rate_is_deterministic_per_seed(self):
        def run(seed):
            plan = faults.FaultPlan(
                [faults.FaultSpec("io.read_block", rate=0.5, seed=seed, times=None)]
            )
            fired = []
            with faults.fault_scope(plan):
                for i in range(32):
                    try:
                        faults.inject("io.read_block", i=i)
                        fired.append(False)
                    except faults.InjectedIOError:
                        fired.append(True)
            return fired

        assert run(7) == run(7)
        assert any(run(7)) and not all(run(7))

    def test_fatal_kind(self):
        plan = faults.FaultPlan([faults.FaultSpec("multihost.barrier", at=1, kind="fatal")])
        with faults.fault_scope(plan), pytest.raises(faults.InjectedFatalError):
            faults.inject("multihost.barrier")

    def test_corrupt_pours_nan_into_first_leaf(self):
        import jax.numpy as jnp

        plan = faults.FaultPlan([faults.FaultSpec("optim.step", at=1, kind="nan")])
        tree = {"w": jnp.ones(4), "b": jnp.zeros(2)}
        with faults.fault_scope(plan):
            out = faults.corrupt("optim.step", tree)
        leaves = [np.asarray(v) for v in out.values()]
        assert any(np.isnan(leaf).all() for leaf in leaves)
        # second call: spec exhausted (times=1), tree untouched
        with faults.fault_scope(plan):
            out2 = faults.corrupt("optim.step", tree)
        assert all(np.isfinite(np.asarray(v)).all() for v in out2.values())

    def test_env_parsing_roundtrip(self):
        plan = faults.parse_fault_env(
            "io.read_block:rate=0.25,seed=9;optim.step:at=2,kind=nan;io.checkpoint_write:rate=1.0,times=2"
        )
        assert plan.spec("io.read_block").rate == 0.25
        assert plan.spec("optim.step").kind == "nan"
        assert plan.spec("io.checkpoint_write").times == 2
        with pytest.raises(ValueError):
            faults.parse_fault_env("io.read_block:bogus=1")

    def test_env_activation(self, monkeypatch):
        monkeypatch.setenv(faults.FAULT_ENV, "io.index_load:at=1")
        with pytest.raises(faults.InjectedIOError):
            faults.inject("io.index_load")

    def test_events_record_context(self):
        plan = faults.FaultPlan([faults.FaultSpec("io.read_block", at=1)])
        with faults.fault_scope(plan):
            with pytest.raises(faults.InjectedIOError):
                faults.inject("io.read_block", path="p.avro", block=4)
        assert plan.events == [("io.read_block", {"path": "p.avro", "block": 4, "hit": 1})]


# ---------------------------------------------------------------------------
# retry policies
# ---------------------------------------------------------------------------


class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "done"

        slept = []
        out = call_with_retry(
            flaky,
            RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.0),
            sleep=slept.append,
        )
        assert out == "done"
        assert len(calls) == 3
        assert slept == [0.1, 0.2]  # exponential, no jitter

    def test_exhaustion_raises_retry_error_with_cause(self):
        def always():
            raise OSError("nope")

        with pytest.raises(RetryError) as ei:
            call_with_retry(
                always, RetryPolicy(max_attempts=3, base_delay=0.0), describe="op"
            )
        assert isinstance(ei.value.__cause__, OSError)
        assert "3 attempt" in str(ei.value)

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("corrupt")

        with pytest.raises(ValueError):
            call_with_retry(bad, RetryPolicy(max_attempts=5, base_delay=0.0))
        assert len(calls) == 1

    def test_deadline_bounds_total_retry_time(self):
        now = [0.0]

        def clock():
            return now[0]

        def sleep(d):
            now[0] += d

        def always():
            raise OSError("x")

        calls = []

        def counting():
            calls.append(1)
            raise OSError("x")

        with pytest.raises(RetryError):
            call_with_retry(
                counting,
                RetryPolicy(max_attempts=100, base_delay=1.0, multiplier=1.0,
                            jitter=0.0, deadline=2.5),
                sleep=sleep,
                clock=clock,
            )
        assert len(calls) == 3  # attempt, +1s retry, +1s retry, next would pass 2.5s

    def test_delay_capped_and_jittered_deterministically(self):
        import random

        p = RetryPolicy(base_delay=1.0, max_delay=3.0, multiplier=10.0, jitter=0.5)
        d = p.delay_for(5, random.Random(0))
        assert 1.5 <= d <= 4.5  # 3.0 capped, +/-50%
        assert p.delay_for(5, random.Random(0)) == d

    def test_injected_fault_is_retryable(self):
        plan = faults.FaultPlan([faults.FaultSpec("io.index_load", at=1)])
        calls = []

        def read():
            calls.append(1)
            faults.inject("io.index_load")
            return 42

        with faults.fault_scope(plan):
            out = call_with_retry(read, RetryPolicy(max_attempts=3, base_delay=0.0))
        assert out == 42 and len(calls) == 2


# ---------------------------------------------------------------------------
# config scoping
# ---------------------------------------------------------------------------


class TestConfig:
    def test_defaults(self):
        cfg = resilience.current_config()
        assert cfg.on_corrupt == "raise"
        assert cfg.io_policy.max_attempts >= 1

    def test_scope_installs_and_restores(self):
        cfg = resilience.ResilienceConfig(on_corrupt="skip", corrupt_skip_budget=2)
        with resilience.resilience_scope(cfg):
            assert resilience.current_config().on_corrupt == "skip"
        assert resilience.current_config().on_corrupt == "raise"

    def test_validation(self):
        with pytest.raises(ValueError):
            resilience.ResilienceConfig(on_corrupt="explode")
        with pytest.raises(ValueError):
            resilience.ResilienceConfig(corrupt_skip_budget=-1)


# ---------------------------------------------------------------------------
# divergence guard
# ---------------------------------------------------------------------------


class TestGuards:
    def test_tree_all_finite(self):
        import jax.numpy as jnp

        assert tree_all_finite({"a": jnp.ones(3), "n": np.arange(3)})
        assert not tree_all_finite({"a": jnp.array([1.0, np.nan])})
        assert not tree_all_finite([jnp.array([np.inf])])
        # integer arrays can't be non-finite
        assert tree_all_finite({"i": np.array([1, 2], np.int32)})

    def test_rollback_returns_last_good_state(self):
        import jax.numpy as jnp

        g = DivergenceGuard()
        good_w, good_s = jnp.ones(3), jnp.zeros(5)
        bad_w = jnp.array([1.0, np.nan, 2.0])
        w, s, ok = g.filter_update("fixed", 3, bad_w, good_s, good_w, good_s)
        assert not ok
        assert np.allclose(np.asarray(w), 1.0)
        assert g.events[0].coordinate == "fixed" and g.events[0].step == 3

    def test_finite_update_passes_through(self):
        import jax.numpy as jnp

        g = DivergenceGuard()
        w, s, ok = g.filter_update("c", 1, jnp.ones(2), jnp.ones(2), None, None)
        assert ok and not g.events

    def test_max_events_exhaustion_raises(self):
        import jax.numpy as jnp

        g = DivergenceGuard(max_events=1)
        bad = jnp.array([np.nan])
        g.filter_update("c", 1, bad, bad, jnp.ones(1), jnp.ones(1))
        with pytest.raises(FloatingPointError):
            g.filter_update("c", 2, bad, bad, jnp.ones(1), jnp.ones(1))

    def test_invalid_mode(self):
        with pytest.raises(ValueError):
            DivergenceGuard(mode="panic")


# ---------------------------------------------------------------------------
# I/O wiring: index map + offheap loads retry under injected faults
# ---------------------------------------------------------------------------


class TestIOWiring:
    def test_index_map_load_retries_injected_faults(self, tmp_path):
        from photon_ml_tpu.io.index_map import IndexMap

        path = str(tmp_path / "feature-index.json")
        IndexMap.build(["a\x01", "b\x01"]).save(path)
        plan = faults.FaultPlan([faults.FaultSpec("io.index_load", at=1)])
        with faults.fault_scope(plan):
            m = IndexMap.load(path)
        assert len(m) == 3  # two keys + intercept
        assert plan.fire_count("io.index_load") == 1

    def test_offheap_load_retries_injected_faults(self, tmp_path):
        from photon_ml_tpu.io.offheap import OffHeapIndexMap, build_offheap_store

        store = str(tmp_path / "store")
        build_offheap_store(store, ["a\x01", "b\x01", "c\x01"], num_partitions=2)
        plan = faults.FaultPlan([faults.FaultSpec("io.index_load", at=1)])
        with faults.fault_scope(plan):
            m = OffHeapIndexMap(store, force_python=True)
        assert m.get_index("a\x01") >= 0
        m.close()

    def test_multihost_barrier_site_retries(self):
        from photon_ml_tpu.parallel.multihost import MultihostContext

        ctx = MultihostContext(process_id=0, num_processes=1)
        plan = faults.FaultPlan([faults.FaultSpec("multihost.barrier", at=1)])
        with faults.fault_scope(plan):
            ctx.barrier("test-fence")  # retried internally, must not raise
        assert plan.fire_count("multihost.barrier") == 1


# ---------------------------------------------------------------------------
# coordinate-descent guard integration (mock coordinates — no solver cost)
# ---------------------------------------------------------------------------


class _CountingCoordinate:
    """Deterministic toy coordinate: params start at 0 and +1 each update."""

    def __init__(self, n):
        import jax.numpy as jnp

        self.n = n
        self._jnp = jnp

    def initial_coefficients(self):
        return self._jnp.zeros(1)

    def update(self, offsets, init, **_):
        return init + 1.0, None

    def score(self, params):
        return self._jnp.broadcast_to(params, (self.n,))

    def regularization_term(self, params, *_):
        return self._jnp.sum(params) * 0.0


@pytest.mark.faults
class TestCoordinateDescentGuard:
    def _cd(self, mode):
        import jax.numpy as jnp

        from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent

        n = 4
        coords = {"a": _CountingCoordinate(n), "b": _CountingCoordinate(n)}
        return (
            CoordinateDescent(
                coords,
                training_loss=lambda s: jnp.sum(s),
                divergence_guard=DivergenceGuard(mode=mode),
            ),
            n,
        )

    def test_rollback_keeps_descending_other_coordinates(self):
        cd, n = self._cd("rollback")
        plan = faults.FaultPlan([faults.FaultSpec("optim.step", at=3, kind="nan")])
        with faults.fault_scope(plan):
            result = cd.run(num_iterations=3, num_rows=n)
        # coordinate a: update at step 3 (iteration 2) rolled back -> 2 not 3
        assert float(result.coefficients["a"][0]) == 2.0
        # coordinate b: unaffected, all 3 updates landed
        assert float(result.coefficients["b"][0]) == 3.0
        assert [e.action for e in result.guard_events] == ["rollback"]
        assert len(result.objective_history) == 6  # histories stay aligned

    def test_skip_cycle_abandons_rest_of_iteration(self, tmp_path):
        from photon_ml_tpu.checkpoint import CoordinateDescentCheckpointer

        cd, n = self._cd("skip_cycle")
        plan = faults.FaultPlan([faults.FaultSpec("optim.step", at=3, kind="nan")])
        ckpt = CoordinateDescentCheckpointer(str(tmp_path), "fp")
        with faults.fault_scope(plan):
            result = cd.run(num_iterations=3, num_rows=n, checkpointer=ckpt)
        # step 3 (a, iteration 2) poisoned -> rolled back AND b's step-4
        # update skipped; both catch up in iteration 3
        assert float(result.coefficients["a"][0]) == 2.0
        assert float(result.coefficients["b"][0]) == 2.0
        assert [e.action for e in result.guard_events] == ["skip_cycle"]
        # histories and the final checkpoint stay step-aligned
        assert len(result.objective_history) == 6
        assert ckpt.latest_step() == 6

    def test_fused_cycle_rollback_keeps_histories_aligned(self):
        import jax.numpy as jnp

        from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent

        n = 4

        class _DivergingCoordinate(_CountingCoordinate):
            """Counts 0->1->2, then every further update produces NaN —
            in-graph divergence the fused (compiled) cycle can hit."""

            def update(self, offsets, init, **_):
                nxt = init + 1.0
                return jnp.where(init >= 2.0, jnp.nan, nxt), None

        coords = {"a": _DivergingCoordinate(n), "b": _CountingCoordinate(n)}
        cd = CoordinateDescent(
            coords,
            training_loss=lambda s: jnp.sum(s),
            fused_cycle=True,
            divergence_guard=DivergenceGuard(),
        )
        result = cd.run(num_iterations=4, num_rows=n)
        # iterations 3 and 4 diverge and roll back WHOLE iterations
        assert [e.action for e in result.guard_events] == ["rollback", "rollback"]
        assert all(e.coordinate == "(fused-cycle)" for e in result.guard_events)
        assert float(result.coefficients["a"][0]) == 2.0
        assert float(result.coefficients["b"][0]) == 2.0
        # histories keep one entry per update (the step-aligned contract),
        # so the driver's objective_history[-1] report never IndexErrors
        assert len(result.objective_history) == 8
        assert np.isfinite(result.objective_history).all()
