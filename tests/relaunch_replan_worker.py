"""Worker for the 2-process SUPERVISED-RELAUNCH harness (launched by
test_survivable_loop.py; also runnable by hand:

    RELAUNCH_PHASE=seed     python tests/relaunch_replan_worker.py <pid> 2 <port> <dir>
    RELAUNCH_PHASE=relaunch python tests/relaunch_replan_worker.py 0 1 - <dir>

Unlike the in-band elastic arms (elastic_reshard_worker.py), this
exercises the path the ElasticSession CANNOT take: the cohort itself
changes across a process boundary. Phase ``seed`` runs a 2-process
streaming CD for ONE checkpointed iteration and exits — the simulated
preemption: host 1's capacity is gone and will not come back. Phase
``relaunch`` starts ONE fresh process (the survivor), which must NOT
re-ingest: it restores the prior cohort's plan-versioned sidecars,
re-plans onto the 1-host cohort (relaunch_replan), delta-copies only the
block/state files it newly owns, re-derives its fixed-effect chunk share
from the plan's recorded FE ownership, and resumes the descent from the
step-aligned checkpoint — finishing BITWISE-equal to an uninterrupted
2-iteration run on the final topology (the single-host reference, which
PR 9 pins equal to every topology)."""

import os
import sys
import time

proc_id, nprocs, port, outdir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
PHASE = os.environ.get("RELAUNCH_PHASE", "seed")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.parallel import multihost

mh = None
ctx = None
if PHASE == "seed":
    mh = multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=nprocs,
        process_id=proc_id,
    )
    ctx = mh.mesh_context()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from game_test_utils import make_glmix_data  # noqa: E402

from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent  # noqa: E402
from photon_ml_tpu.algorithm.streaming_fixed_effect import (  # noqa: E402
    PerHostStreamingFixedEffectCoordinate,
)
from photon_ml_tpu.checkpoint import CoordinateDescentCheckpointer  # noqa: E402
from photon_ml_tpu.compile.plan import ExecutionPlan  # noqa: E402
from photon_ml_tpu.data.game import RandomEffectDataConfig  # noqa: E402
from photon_ml_tpu.ops import losses as losses_mod  # noqa: E402
from photon_ml_tpu.ops.regularization import RegularizationContext  # noqa: E402
from photon_ml_tpu.optim.common import OptimizerConfig  # noqa: E402
from photon_ml_tpu.optim.problem import GLMOptimizationProblem  # noqa: E402
from photon_ml_tpu.parallel.elastic import (  # noqa: E402
    FleetMembership,
    relaunch_replan,
)
from photon_ml_tpu.parallel.perhost_ingest import HostRows, csr_to_padded  # noqa: E402
from photon_ml_tpu.parallel.perhost_streaming import (  # noqa: E402
    PerHostStreamingRandomEffectCoordinate,
    attach_fe_chunks_to_sidecars,
    build_perhost_streaming_manifest,
)
from photon_ml_tpu.types import OptimizerType, TaskType  # noqa: E402

# ---- the globally seeded dataset (identical in every process) -------------
rng = np.random.default_rng(97)
data, _ = make_glmix_data(
    rng, num_users=60, rows_per_user_range=(4, 16), d_fixed=5, d_random=4
)
N = data.num_rows
D_FE = data.shards["global"].dim
CHUNK_ROWS = 128
BLOCK_ENTITIES = 16
RE_CFG = RandomEffectDataConfig("userId", "per_user")
FE_PROBLEM = GLMOptimizationProblem(
    TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
    OptimizerConfig(max_iterations=6, tolerance=1e-8),
    RegularizationContext.l2(0.5),
)
RE_OPT = OptimizerConfig(max_iterations=6, tolerance=1e-8)
RE_REG = RegularizationContext.l2(0.2)
FINGERPRINT = "relaunch-harness"

coord_root = os.path.join(outdir, "streaming-re", "per-user")
state_root = lambda pid: os.path.join(outdir, f"re-state-host{pid}")  # noqa: E731

exec_plan = ExecutionPlan.resolve(
    distributed=(nprocs > 1), streaming=True, num_processes=nprocs
)

# full-dataset FE design matrix (chunk c = rows [c*128, ...) — chunk
# composition is host-invariant; only OWNERSHIP is split)
gf = data.shards["global"]
x_fe = np.zeros((N, D_FE), np.float32)
x_fe[np.repeat(np.arange(N), np.diff(gf.indptr)), gf.indices] = gf.values
chunk_sizes = [
    min(CHUNK_ROWS, N - c * CHUNK_ROWS)
    for c in range((N + CHUNK_ROWS - 1) // CHUNK_ROWS)
]


def fe_loaders(owned_chunks):
    loaders = {}
    for c in owned_chunks:
        s = c * CHUNK_ROWS
        e = s + chunk_sizes[c]

        def load(s=s, e=e):
            return {"x": x_fe[s:e], "y": data.response[s:e].astype(np.float32)}

        loaders[c] = load
    return loaders


def make_re_coord(man, pid, initial_epoch=0, num_processes=1, mesh=None):
    return PerHostStreamingRandomEffectCoordinate(
        man, TaskType.LOGISTIC_REGRESSION,
        optimizer=OptimizerType.LBFGS, optimizer_config=RE_OPT,
        regularization=RE_REG,
        state_root=state_root(pid),
        plan=exec_plan, initial_epoch=initial_epoch,
        ctx=mesh, num_processes=num_processes,
    )


def run_cd(fe_coord, re_coord, pid, num_iterations):
    labels = jnp.asarray(data.response.astype(np.float32))
    weights = jnp.asarray(data.weight.astype(np.float32))
    loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
    ck = CoordinateDescentCheckpointer(
        os.path.join(outdir, f"ckpt-host{pid}"),
        run_fingerprint=FINGERPRINT, save_every=1,
    )
    resumed = ck.latest_step()
    print(f"resumed_from_step={resumed if resumed is not None else 0}",
          flush=True)
    cd = CoordinateDescent(
        {"fixed": fe_coord, "per-user": re_coord},
        lambda s: jnp.sum(weights * loss.loss(s, labels)),
    )
    return cd.run(num_iterations=num_iterations, num_rows=N, checkpointer=ck)


if PHASE == "seed":
    # ---- 2-process cohort: one checkpointed iteration, then exit ----------
    membership = FleetMembership.initial(nprocs)
    lo = proc_id * (N // nprocs)
    hi = N if proc_id == nprocs - 1 else (proc_id + 1) * (N // nprocs)
    feats = data.shards["per_user"]
    fi_all, fv_all = csr_to_padded(feats, N)
    vocab0 = data.id_vocabs["userId"]
    host_rows = HostRows(
        entity_raw_ids=[vocab0[i] for i in data.ids["userId"][lo:hi]],
        row_index=np.arange(lo, hi, dtype=np.int64),
        labels=data.response[lo:hi].astype(np.float32),
        weights=data.weight[lo:hi].astype(np.float32),
        offsets=data.offset[lo:hi].astype(np.float32),
        feat_idx=fi_all[lo:hi],
        feat_val=fv_all[lo:hi],
        global_dim=feats.dim,
    )
    manifest = build_perhost_streaming_manifest(
        host_rows, RE_CFG, os.path.join(coord_root, f"process-{proc_id}"),
        ctx, nprocs, proc_id, block_entities=BLOCK_ENTITIES,
        bucketer=exec_plan.bucketer, membership=membership,
    )
    # record the FE chunk split the run ACTUALLY uses into the committed
    # plan sidecars — what the relaunch re-bases instead of re-deciding
    fe_owners = np.asarray([c % nprocs for c in range(len(chunk_sizes))],
                           np.int32)
    attach_fe_chunks_to_sidecars(manifest.dir, fe_owners, chunk_sizes)
    my_chunks = [c for c in range(len(chunk_sizes))
                 if int(fe_owners[c]) == proc_id]
    fe_coord = PerHostStreamingFixedEffectCoordinate(
        chunk_sizes, fe_loaders(my_chunks), D_FE, FE_PROBLEM,
        plan=exec_plan, ctx=ctx, num_processes=nprocs,
    )
    re_coord = make_re_coord(manifest, proc_id, num_processes=nprocs,
                             mesh=ctx)
    t0 = time.perf_counter()
    result = run_cd(fe_coord, re_coord, proc_id, num_iterations=1)
    mh.barrier("seed-done")
    print(
        f"SEEDOK proc={proc_id} elapsed={time.perf_counter() - t0:.2f}s "
        f"obj={result.objective_history[-1]:.9g}",
        flush=True,
    )
    # the process simply exits here: host 1 never comes back — the
    # supervisor relaunches a SMALLER cohort (phase ``relaunch``)
elif PHASE == "relaunch":
    # ---- the survivor, alone: re-plan + delta transfer + resume -----------
    assert proc_id == 0 and nprocs == 1
    t0 = time.perf_counter()
    res = relaunch_replan(
        coord_root, 0, 1,
        state_root_pairs=[
            ({0: state_root(0), 1: state_root(1)}, state_root(0)),
        ],
    )
    print(
        f"replanned_to_v{res.plan.version} adopted={len(res.adopted)} "
        f"state_files={res.state_files_adopted} moved={len(res.moved)} "
        f"no-reingest",
        flush=True,
    )
    # FE chunk share from the re-based plan, not a fresh decision
    my_chunks = res.plan.owned_fe_chunks(0, membership=res.membership)
    assert sorted(my_chunks) == list(range(len(chunk_sizes))), my_chunks
    print(f"fe_chunks={len(my_chunks)}/{len(chunk_sizes)}", flush=True)
    fe_coord = PerHostStreamingFixedEffectCoordinate(
        chunk_sizes, fe_loaders(my_chunks), D_FE, FE_PROBLEM,
        plan=exec_plan, ctx=None, num_processes=1,
    )
    # epochs continue ABOVE the interrupted numbering so the restored
    # checkpoint's state dirs (epoch-0...) are never collided with
    re_coord = make_re_coord(res.manifest, 0, initial_epoch=10)
    result = run_cd(fe_coord, re_coord, 0, num_iterations=2)
    means = re_coord.entity_means_by_raw_id(result.coefficients["per-user"])
    np.savez(
        os.path.join(outdir, "means-host0.npz"),
        names=np.asarray(sorted(means), dtype=object),
        stack=np.stack([means[k] for k in sorted(means)])
        if means else np.zeros((0, 0)),
    )
    np.savez(
        os.path.join(outdir, "run.npz"),
        fe=np.asarray(result.coefficients["fixed"]),
        total_scores=np.asarray(result.total_scores),
        objectives=np.asarray(result.objective_history, np.float64),
    )
    print(
        f"RELAUNCHOK blocks={len(res.manifest.blocks)} "
        f"iters={len(result.objective_history) // 2} "
        f"elapsed={time.perf_counter() - t0:.2f}s "
        f"obj={result.objective_history[-1]:.9g}",
        flush=True,
    )
else:
    raise SystemExit(f"unknown RELAUNCH_PHASE {PHASE!r}")
