"""Off-heap (native memory-mapped) feature index store tests.

(PalDBIndexMapTest analogue: global-offset lookup semantics, round-trips,
cross-implementation parity between the C++ and pure-Python readers, and
exact index agreement with the in-memory IndexMap.)
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.io import offheap
from photon_ml_tpu.io.index_map import INTERCEPT_KEY, IndexMap, feature_key


def _keys(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        feature_key(f"name{rng.integers(0, 10_000_000)}", f"t{i % 7}")
        for i in range(n)
    ]


class TestNativeLibrary:
    def test_native_compiles(self):
        # g++ is part of the environment contract; the native path must build
        assert offheap.native_available()


@pytest.fixture(scope="module", params=[False, True], ids=["native", "python"])
def force_python(request):
    if not request.param and not offheap.native_available():
        pytest.skip("native lib unavailable")
    return request.param


class TestOffHeapStore:
    def test_roundtrip_and_indexmap_parity(self, tmp_path, force_python):
        keys = sorted(set(_keys(500, seed=1)))
        store_dir = str(tmp_path / "store")
        offheap.build_offheap_store(store_dir, keys, add_intercept=True, num_partitions=4)
        store = offheap.OffHeapIndexMap(store_dir, force_python=force_python)
        ref = IndexMap.build(keys, add_intercept=True, num_partitions=4)

        assert len(store) == len(ref)
        for k in keys:
            assert store.get_index(k) == ref.get_index(k)
        for i in range(len(ref)):
            assert store.get_feature_name(i) == ref.get_feature_name(i)
        assert store.intercept_index == ref.intercept_index
        assert store.get_index(INTERCEPT_KEY) == ref.intercept_index
        store.close()

    def test_missing_keys(self, tmp_path, force_python):
        store_dir = str(tmp_path / "store")
        offheap.build_offheap_store(store_dir, ["a\x01", "b\x01"], add_intercept=False)
        store = offheap.OffHeapIndexMap(store_dir, force_python=force_python)
        assert store.get_index("zzz\x01") == -1
        assert store.get_feature_name(99) is None
        assert store.intercept_index == -1
        assert "a\x01" in store and "zzz\x01" not in store
        store.close()

    def test_empty_partitions(self, tmp_path, force_python):
        # more partitions than keys -> some partitions are empty
        store_dir = str(tmp_path / "store")
        offheap.build_offheap_store(store_dir, ["only\x01key"], num_partitions=8)
        store = offheap.OffHeapIndexMap(store_dir, force_python=force_python)
        assert store.get_index("only\x01key") == 0
        assert store.get_feature_name(0) == "only\x01key"
        store.close()

    def test_unicode_keys(self, tmp_path, force_python):
        keys = ["café\x01t", "日本\x01", "emoji\U0001f600\x01x"]
        store_dir = str(tmp_path / "store")
        offheap.build_offheap_store(store_dir, keys, add_intercept=False)
        store = offheap.OffHeapIndexMap(store_dir, force_python=force_python)
        for k in keys:
            idx = store.get_index(k)
            assert idx >= 0
            assert store.get_feature_name(idx) == k
        store.close()

    def test_name_to_index_view(self, tmp_path, force_python):
        keys = sorted(set(_keys(50, seed=3)))
        store_dir = str(tmp_path / "store")
        offheap.build_offheap_store(store_dir, keys, add_intercept=True)
        store = offheap.OffHeapIndexMap(store_dir, force_python=force_python)
        view = store.name_to_index
        assert len(view) == len(store)
        assert view[INTERCEPT_KEY] == store.intercept_index
        store.close()


class TestCrossImplementationParity:
    def test_python_reads_native_build_and_vice_versa(self, tmp_path):
        if not offheap.native_available():
            pytest.skip("native lib unavailable")
        keys = sorted(set(_keys(300, seed=2)))
        store_dir = str(tmp_path / "store")
        offheap.build_offheap_store(store_dir, keys, num_partitions=2)
        native = offheap.OffHeapIndexMap(store_dir)
        python = offheap.OffHeapIndexMap(store_dir, force_python=True)
        for k in keys[:100]:
            assert native.get_index(k) == python.get_index(k)
        for i in range(0, len(keys), 7):
            assert native.get_feature_name(i) == python.get_feature_name(i)
        native.close()
        python.close()


class TestDriverIntegration:
    def test_load_index_map_autodetect(self, tmp_path):
        keys = ["f1\x01", "f2\x01"]
        store_dir = str(tmp_path / "store")
        offheap.build_offheap_store(store_dir, keys)
        m = offheap.load_index_map(store_dir)
        assert isinstance(m, offheap.OffHeapIndexMap)

        json_dir = tmp_path / "json"
        json_dir.mkdir()
        IndexMap.build(keys).save(str(json_dir / "feature-index.json"))
        m2 = offheap.load_index_map(str(json_dir))
        assert isinstance(m2, IndexMap)
        assert m.get_index("f1\x01") == m2.get_index("f1\x01")

    def test_feature_indexing_job_offheap_and_game_training(self, tmp_path):
        # end-to-end: indexing job writes OFFHEAP stores; GAME training
        # consumes them via --offheap-indexmap-dir
        import sys

        sys.path.insert(0, os.path.dirname(__file__))
        from game_test_utils import make_glmix_data
        from test_game_drivers import COMMON_FLAGS, _write_game_avro

        from photon_ml_tpu.cli import feature_indexing, game_training_driver

        rng = np.random.default_rng(5)
        gd, truth = make_glmix_data(
            rng, num_users=8, rows_per_user_range=(20, 40), d_fixed=4, d_random=3
        )
        data = {
            "y": gd.response,
            "x_fixed": truth["x_fixed"],
            "x_random": truth["x_random"],
            "user_raw": [gd.id_vocabs["userId"][i] for i in gd.ids["userId"]],
        }
        train_dir = tmp_path / "train"
        train_dir.mkdir()
        _write_game_avro(str(train_dir / "p.avro"), data, range(gd.num_rows))

        idx_dir = str(tmp_path / "idx")
        written = feature_indexing.main(
            [
                "--data-input-dirs", str(train_dir),
                "--output-dir", idx_dir,
                "--partition-num", "2",
                "--format", "OFFHEAP",
                "--feature-shard-id-to-feature-section-keys-map",
                "global:fixedFeatures|per_user:userFeatures",
            ]
        )
        assert len(written) == 2
        assert offheap.is_offheap_store(os.path.join(idx_dir, "global"))

        driver = game_training_driver.main(
            [
                "--train-input-dirs", str(train_dir),
                "--output-dir", str(tmp_path / "out"),
                "--num-iterations", "1",
                "--offheap-indexmap-dir", idx_dir,
                "--model-output-mode", "NONE",
            ]
            + COMMON_FLAGS
        )
        # trained against the offheap maps; objective must be finite + improving
        _, result, _ = driver.results[driver.best_index]
        assert np.isfinite(result.objective_history[-1])
        assert result.objective_history[-1] < result.objective_history[0]


class TestWriterBytesIdentity:
    """The native (g++/ctypes) and pure-Python writers emit IDENTICAL
    ``.pmix`` partition files for the same key set, and each reader opens
    the other's output. The serving model store leans on this: a store
    exported wherever a compiler happens to exist (or not) serves
    everywhere, and two servers mmap'ing byte-identical files share
    physical pages regardless of which toolchain built them."""

    KEYS = sorted(set(_keys(400, seed=7)))

    @staticmethod
    def _store_bytes(store_dir):
        out = {}
        for name in sorted(os.listdir(store_dir)):
            with open(os.path.join(store_dir, name), "rb") as f:
                out[name] = f.read()
        return out

    @pytest.fixture()
    def both_dirs(self, tmp_path):
        if not offheap.native_available():
            pytest.skip("native lib unavailable")
        nat = str(tmp_path / "native")
        py = str(tmp_path / "python")
        offheap.build_offheap_store(
            nat, self.KEYS, add_intercept=True, num_partitions=3
        )
        offheap.build_offheap_store(
            py, self.KEYS, add_intercept=True, num_partitions=3,
            force_python=True,
        )
        return nat, py

    def test_partition_files_bitwise_identical(self, both_dirs):
        nat, py = both_dirs
        nat_bytes = self._store_bytes(nat)
        py_bytes = self._store_bytes(py)
        assert set(nat_bytes) == set(py_bytes)
        pmix = [n for n in nat_bytes if n.endswith(offheap.PARTITION_SUFFIX)]
        assert len(pmix) == 3
        for name in nat_bytes:
            assert nat_bytes[name] == py_bytes[name], f"{name} differs"

    def test_each_reader_opens_the_others_output(self, both_dirs):
        nat, py = both_dirs
        # native reader on the pure-Python writer's store, and vice versa
        for store_dir in (nat, py):
            for force_python in (False, True):
                store = offheap.OffHeapIndexMap(
                    store_dir, force_python=force_python
                )
                assert len(store) == len(self.KEYS) + 1  # + intercept
                for k in self.KEYS[:50]:
                    idx = store.get_index(k)
                    assert idx >= 0
                    assert store.get_feature_name(idx) == k
                assert store.get_index("no-such-key\x01") == -1
                store.close()

    def test_slab_index_writers_identical(self, tmp_path):
        """Same identity for the serving entity->slab-row stores (the
        feature machinery generalized — no intercept slot)."""
        if not offheap.native_available():
            pytest.skip("native lib unavailable")
        entities = [f"user-{i:04d}" for i in range(117)]
        nat = str(tmp_path / "rows-native")
        py = str(tmp_path / "rows-python")
        offheap.build_slab_index(nat, entities, num_partitions=2)
        offheap.build_slab_index(py, entities, num_partitions=2, force_python=True)
        assert self._store_bytes(nat) == self._store_bytes(py)
        rows_nat = offheap.SlabRowIndex(py)  # cross-open
        rows_py = offheap.SlabRowIndex(nat, force_python=True)
        assert rows_nat.num_rows == rows_py.num_rows == len(entities)
        for e in entities[:40]:
            assert rows_nat.get_row(e) == rows_py.get_row(e) >= 0
        rows_nat.close()
        rows_py.close()
