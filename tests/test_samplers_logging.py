"""Down-samplers, PhotonLogger, Timer, and CoefficientSummary units.

Reference specs: sampler/BinaryClassificationDownSampler.scala:31-60,
sampler/DefaultDownSampler.scala:26-45, util/PhotonLogger.scala:38-520
(tmp file copied to output on close), util/Timer.scala:32-235,
supervised/model/CoefficientSummary.scala.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_ml_tpu.data.sampler import (
    down_sample_binary,
    down_sample_default,
    maybe_down_sample,
)
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.objective import GLMBatch
from photon_ml_tpu.types import TaskType


def _batch(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    x = DenseFeatures(jnp.asarray(rng.normal(size=(n, 4)).astype(np.float32)))
    labels = jnp.asarray((rng.random(n) < 0.25).astype(np.float32))
    return GLMBatch(x, labels, jnp.zeros((n,)), jnp.ones((n,)))


class TestDownSamplers:
    def test_binary_keeps_all_positives(self):
        b = _batch()
        out = down_sample_binary(b, 0.3, jax.random.PRNGKey(0))
        pos = np.asarray(b.labels) > 0.5
        w = np.asarray(out.weights)
        # every positive survives with weight exactly 1 (never rescaled)
        assert (w[pos] == 1.0).all()
        # negatives are either dropped (0) or rescaled to 1/rate
        neg_w = np.unique(w[~pos])
        assert all(v == 0.0 or v == pytest.approx(1 / 0.3) for v in neg_w)

    def test_binary_is_unbiased(self):
        """E[sum of weights over negatives] must equal the original negative
        mass (the 1/rate rescale, BinaryClassificationDownSampler.scala:48)."""
        b = _batch(n=20000)
        neg_mass = float(np.sum(np.asarray(b.labels) <= 0.5))
        kept = np.mean([
            float(jnp.sum(down_sample_binary(b, 0.4, jax.random.PRNGKey(s)).weights
                          * (b.labels <= 0.5)))
            for s in range(5)
        ])
        assert kept == pytest.approx(neg_mass, rel=0.05)

    def test_default_uniform_unbiased(self):
        b = _batch(n=20000)
        out = down_sample_default(b, 0.5, jax.random.PRNGKey(1))
        w = np.asarray(out.weights)
        assert set(np.unique(w)).issubset({0.0, 2.0})
        assert w.sum() == pytest.approx(b.labels.shape[0], rel=0.05)

    def test_maybe_down_sample_dispatch_and_noop(self):
        b = _batch()
        # rate None / >= 1: identity (no-op hook, GeneralizedLinear
        # OptimizationProblem.downSample)
        assert maybe_down_sample(b, TaskType.LOGISTIC_REGRESSION, None, 7) is b
        assert maybe_down_sample(b, TaskType.LOGISTIC_REGRESSION, 1.0, 7) is b
        # logistic -> binary sampler (positives untouched)
        out = maybe_down_sample(b, TaskType.LOGISTIC_REGRESSION, 0.5, 7)
        pos = np.asarray(b.labels) > 0.5
        assert (np.asarray(out.weights)[pos] == 1.0).all()
        # linear -> uniform sampler (positives CAN be dropped)
        out2 = maybe_down_sample(b, TaskType.LINEAR_REGRESSION, 0.5, 7)
        assert (np.asarray(out2.weights)[pos] == 0.0).any()

    def test_deterministic_under_same_seed(self):
        b = _batch()
        w1 = maybe_down_sample(b, TaskType.LOGISTIC_REGRESSION, 0.5, 11).weights
        w2 = maybe_down_sample(b, TaskType.LOGISTIC_REGRESSION, 0.5, 11).weights
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))

    def test_training_with_downsampling_still_converges(self):
        """The zero-weight representation must flow through a real solve."""
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.types import OptimizerType

        b = _batch(n=2000)
        prob = GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=40, tolerance=1e-7),
            RegularizationContext.l2(1e-2),
        )
        sampled = maybe_down_sample(b, TaskType.LOGISTIC_REGRESSION, 0.5, 3)
        model, res = prob.run(sampled, NormalizationContext.identity())
        assert np.isfinite(np.asarray(model.coefficients.means)).all()
        assert res.iterations > 0


class TestPhotonLogger:
    def test_levels_and_close_copies_to_output(self, tmp_path):
        from photon_ml_tpu.utils.logging import LEVEL_WARN, PhotonLogger

        out = tmp_path / "logs" / "driver.log"  # parent does not exist yet
        logger = PhotonLogger(str(out), level=LEVEL_WARN, echo=False)
        tmp_file = logger._tmp_path
        logger.info("below threshold — filtered")
        logger.warn("warn line")
        logger.error("error line")
        logger.close()
        text = out.read_text()
        assert "warn line" in text and "error line" in text
        assert "below threshold" not in text
        assert "[WARN]" in text and "[ERROR]" in text
        # tmp file removed; close is idempotent; writes after close dropped
        assert not os.path.exists(tmp_file)
        logger.close()
        logger.error("after close")
        assert "after close" not in out.read_text()

    def test_context_manager_and_no_output_path(self):
        from photon_ml_tpu.utils.logging import PhotonLogger

        with PhotonLogger(None, echo=False) as logger:
            logger.info("hello")
            tmp_file = logger._tmp_path
        assert not os.path.exists(tmp_file)


class TestTimer:
    def test_measure_and_summary(self):
        from photon_ml_tpu.utils.timer import Timer

        lines = []
        t = Timer(log_fn=lines.append)
        with t.measure("phase-a"):
            pass
        t.start("phase-b")
        dt = t.stop("phase-b")
        assert dt >= 0.0
        s = t.summary()
        assert "phase-a" in s and "phase-b" in s
        assert any("phase-a" in l for l in lines)

    def test_stop_without_start_raises(self):
        from photon_ml_tpu.utils.timer import Timer

        with pytest.raises(RuntimeError):
            Timer().stop("never-started")
        # double-start is rejected too
        t = Timer()
        t.start("x")
        with pytest.raises(RuntimeError):
            t.start("x")


class TestCoefficientSummary:
    def test_from_samples_quartiles(self):
        from photon_ml_tpu.bootstrap import CoefficientSummary

        s = CoefficientSummary.from_samples(np.asarray([1.0, 2.0, 3.0, 4.0, 5.0]))
        assert (s.min, s.max, s.mean, s.median) == (1.0, 5.0, 3.0, 3.0)
        assert s.q1 == 2.0 and s.q3 == 4.0
        assert s.variance == pytest.approx(2.5)
        assert not s.contains_zero()
        z = CoefficientSummary.from_samples(np.asarray([-1.0, 1.0]))
        assert z.contains_zero()

    def test_single_sample_variance_zero(self):
        from photon_ml_tpu.bootstrap import CoefficientSummary

        s = CoefficientSummary.from_samples(np.asarray([2.5]))
        assert s.variance == 0.0 and s.min == s.max == 2.5
