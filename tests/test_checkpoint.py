"""Checkpoint/resume tests for coordinate descent (SURVEY.md §5.4 upgrade)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.algorithm import (
    CoordinateDescent,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.checkpoint import (
    CheckpointState,
    CoordinateDescentCheckpointer,
    fingerprint,
)
from photon_ml_tpu.data.game import RandomEffectDataConfig, build_fixed_effect_batch, build_random_effect_dataset
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationProblem
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import OptimizerType, TaskType

from game_test_utils import make_glmix_data


@pytest.fixture(scope="module")
def glmix():
    rng = np.random.default_rng(11)
    return make_glmix_data(rng, num_users=8, rows_per_user_range=(15, 35),
                           d_fixed=4, d_random=3)


def _build_cd(data):
    fixed = FixedEffectCoordinate(
        build_fixed_effect_batch(data, "global", dense=True),
        GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=30, tolerance=1e-7),
            RegularizationContext.l2(1e-2),
        ),
    )
    random = RandomEffectCoordinate(
        build_random_effect_dataset(data, RandomEffectDataConfig("userId", "per_user")),
        TaskType.LOGISTIC_REGRESSION,
        OptimizerType.LBFGS,
        OptimizerConfig(max_iterations=25, tolerance=1e-6),
        RegularizationContext.l2(1e-1),
    )
    labels = jnp.asarray(data.response)
    loss_fn = lambda scores: jnp.sum(losses.logistic.loss(scores, labels))
    return CoordinateDescent({"fixed": fixed, "random": random}, loss_fn)


class TestCheckpointer:
    def test_save_restore_roundtrip(self, tmp_path):
        ckpt = CoordinateDescentCheckpointer(str(tmp_path), "fp1")
        params = {"a": jnp.arange(4.0), "b": jnp.ones((2, 3))}
        scores = {"a": jnp.zeros(5), "b": jnp.ones(5)}
        total = jnp.full(5, 2.0)
        ckpt.save(CheckpointState(3, params, scores, total, [1.0, 0.5], [{"AUC": 0.7}]))

        restored = ckpt.restore(params, scores, total)
        assert restored.step == 3
        np.testing.assert_array_equal(np.asarray(restored.params["a"]), np.arange(4.0))
        np.testing.assert_array_equal(np.asarray(restored.total_scores), np.full(5, 2.0))
        assert restored.objective_history == [1.0, 0.5]
        assert restored.validation_history == [{"AUC": 0.7}]

    def test_latest_wins_and_retention(self, tmp_path):
        ckpt = CoordinateDescentCheckpointer(str(tmp_path), "fp", keep=2)
        params = {"a": jnp.zeros(2)}
        scores = {"a": jnp.zeros(2)}
        for step in (1, 2, 3, 4):
            ckpt.save(
                CheckpointState(step, {"a": jnp.full(2, float(step))}, scores,
                                jnp.zeros(2), [], [])
            )
        assert ckpt.latest_step() == 4
        # retention keeps only the last 2
        dirs = [d for d in os.listdir(tmp_path) if d.startswith("step-")]
        assert sorted(dirs) == ["step-3", "step-4"]
        restored = ckpt.restore(params, scores, jnp.zeros(2))
        np.testing.assert_array_equal(np.asarray(restored.params["a"]), [4.0, 4.0])

    def test_fingerprint_mismatch_refuses(self, tmp_path):
        ckpt = CoordinateDescentCheckpointer(str(tmp_path), "fpA")
        params = {"a": jnp.zeros(2)}
        ckpt.save(CheckpointState(1, params, params, jnp.zeros(2), [], []))
        other = CoordinateDescentCheckpointer(str(tmp_path), "fpB")
        with pytest.raises(ValueError, match="fingerprint"):
            other.restore(params, params, jnp.zeros(2))

    def test_empty_dir_returns_none(self, tmp_path):
        ckpt = CoordinateDescentCheckpointer(str(tmp_path), "fp")
        assert ckpt.restore({}, {}, jnp.zeros(1)) is None
        assert ckpt.latest_step() is None

    def test_fingerprint_stability(self):
        a = fingerprint({"coords": ["x", "y"], "n": 10})
        b = fingerprint({"n": 10, "coords": ["x", "y"]})  # key order irrelevant
        c = fingerprint({"coords": ["x", "y"], "n": 11})
        assert a == b and a != c


class TestCoordinateDescentResume:
    def test_resume_matches_uninterrupted_run(self, glmix, tmp_path):
        data, _ = glmix
        n = data.num_rows

        # uninterrupted 2-iteration run
        full = _build_cd(data).run(2, n)

        # run 1 iteration with checkpointing ("crash" after iteration 1)...
        ckpt_dir = str(tmp_path / "ckpt")
        ckpt1 = CoordinateDescentCheckpointer(ckpt_dir, "run")
        _build_cd(data).run(1, n, ckpt1)
        assert ckpt1.latest_step() == 2  # 1 iteration x 2 coordinates

        # ...then resume asking for the full 2 iterations
        ckpt2 = CoordinateDescentCheckpointer(ckpt_dir, "run")
        resumed = _build_cd(data).run(2, n, ckpt2)

        np.testing.assert_allclose(
            np.asarray(resumed.total_scores), np.asarray(full.total_scores),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(resumed.coefficients["fixed"]),
            np.asarray(full.coefficients["fixed"]),
            rtol=1e-5, atol=1e-5,
        )
        assert len(resumed.objective_history) == len(full.objective_history)
        assert resumed.objective_history[-1] == pytest.approx(
            full.objective_history[-1], rel=1e-5
        )

    def test_completed_run_resumes_to_noop(self, glmix, tmp_path):
        data, _ = glmix
        n = data.num_rows
        ckpt_dir = str(tmp_path / "ckpt")
        first = _build_cd(data).run(1, n, CoordinateDescentCheckpointer(ckpt_dir, "r"))
        again = _build_cd(data).run(1, n, CoordinateDescentCheckpointer(ckpt_dir, "r"))
        np.testing.assert_array_equal(
            np.asarray(first.total_scores), np.asarray(again.total_scores)
        )
        # no additional objective evaluations happened on the no-op resume
        assert again.objective_history == first.objective_history


class TestDriverCheckpointFlag:
    def test_game_driver_checkpoint_dir(self, tmp_path):
        import sys

        sys.path.insert(0, os.path.dirname(__file__))
        from test_game_drivers import COMMON_FLAGS, _write_game_avro
        from game_test_utils import make_glmix_data as mk

        from photon_ml_tpu.cli import game_training_driver

        rng = np.random.default_rng(13)
        gd, truth = mk(rng, num_users=6, rows_per_user_range=(15, 30),
                       d_fixed=3, d_random=2)
        data = {
            "y": gd.response,
            "x_fixed": truth["x_fixed"],
            "x_random": truth["x_random"],
            "user_raw": [gd.id_vocabs["userId"][i] for i in gd.ids["userId"]],
        }
        train_dir = tmp_path / "train"
        train_dir.mkdir()
        _write_game_avro(str(train_dir / "p.avro"), data, range(gd.num_rows))

        ckpt_dir = str(tmp_path / "ckpt")
        args = [
            "--train-input-dirs", str(train_dir),
            "--output-dir", str(tmp_path / "out"),
            "--num-iterations", "1",
            "--checkpoint-dir", ckpt_dir,
            "--model-output-mode", "NONE",
        ] + COMMON_FLAGS
        d1 = game_training_driver.main(args)
        assert os.path.isdir(os.path.join(ckpt_dir, "combo-0"))
        # second run resumes from the complete checkpoint: same final objective
        d2 = game_training_driver.main(args)
        assert d2.results[0][1].objective_history == d1.results[0][1].objective_history


class TestCrashMidWriteResume:
    """Crash debris tolerance (resilience subsystem): a killed writer leaves
    a stale temp dir and possibly a truncated arrays.npz on a non-atomic
    filesystem; restore() must ignore both and resume from the last
    COMPLETE step."""

    def _save_steps(self, tmp_path, steps):
        ckpt = CoordinateDescentCheckpointer(str(tmp_path), "fp", keep=10)
        scores = {"a": jnp.zeros(2)}
        for step in steps:
            ckpt.save(
                CheckpointState(step, {"a": jnp.full(2, float(step))}, scores,
                                jnp.zeros(2), [float(step)], [])
            )
        return ckpt

    def test_restore_ignores_stale_tmp_and_truncated_npz(self, tmp_path):
        self._save_steps(tmp_path, (1, 2))

        # crash debris 1: a stale temp dir from a writer killed mid-write
        stale = tmp_path / ".ckpt-deadbeef"
        stale.mkdir()
        (stale / "arrays.npz").write_bytes(b"PK\x03\x04 partial garbage")

        # crash debris 2: step-3 got its meta written but arrays.npz is
        # truncated (crash between file writes on a non-atomic filesystem)
        import shutil as _sh

        _sh.copytree(tmp_path / "step-2", tmp_path / "step-3")
        meta_path = tmp_path / "step-3" / "meta.json"
        import json as _json

        meta = _json.loads(meta_path.read_text())
        meta["step"] = 3
        meta_path.write_text(_json.dumps(meta))
        arrays_path = tmp_path / "step-3" / "arrays.npz"
        arrays_path.write_bytes(arrays_path.read_bytes()[:20])  # truncate

        params = {"a": jnp.zeros(2)}
        scores = {"a": jnp.zeros(2)}
        ckpt = CoordinateDescentCheckpointer(str(tmp_path), "fp", keep=10)
        restored = ckpt.restore(params, scores, jnp.zeros(2))
        # fell back to step 2, the last complete checkpoint
        assert restored is not None and restored.step == 2
        np.testing.assert_array_equal(np.asarray(restored.params["a"]), [2.0, 2.0])
        # the stale temp dir was swept on checkpointer construction
        assert not stale.exists()

    def test_descent_resumes_through_crash_debris(self, glmix, tmp_path):
        data, _ = glmix
        n = data.num_rows
        ckpt_dir = str(tmp_path / "ckpt")
        full = _build_cd(data).run(2, n)

        _build_cd(data).run(1, n, CoordinateDescentCheckpointer(ckpt_dir, "run"))
        # simulate a crash mid-write of the NEXT checkpoint
        os.makedirs(os.path.join(ckpt_dir, ".ckpt-wip"))
        with open(os.path.join(ckpt_dir, ".ckpt-wip", "arrays.npz"), "wb") as f:
            f.write(b"\x00" * 64)

        resumed = _build_cd(data).run(
            2, n, CoordinateDescentCheckpointer(ckpt_dir, "run")
        )
        np.testing.assert_allclose(
            np.asarray(resumed.total_scores), np.asarray(full.total_scores),
            rtol=1e-5, atol=1e-5,
        )

    def test_save_retries_through_injected_write_faults(self, tmp_path):
        from photon_ml_tpu import resilience
        from photon_ml_tpu.resilience import faults

        plan = faults.FaultPlan(
            [faults.FaultSpec("io.checkpoint_write", at=1, times=1)]
        )
        cfg = resilience.ResilienceConfig(
            io_policy=resilience.RetryPolicy(max_attempts=3, base_delay=0.0)
        )
        with faults.fault_scope(plan), resilience.resilience_scope(cfg):
            ckpt = self._save_steps(tmp_path, (1,))
        assert plan.fire_count("io.checkpoint_write") == 1
        assert ckpt.latest_step() == 1  # the retry completed the write
        # no temp-dir debris from the failed attempt
        assert not [d for d in os.listdir(tmp_path) if d.startswith(".ckpt-")]
