"""Chaos tests: GAME training end-to-end under injected faults.

The acceptance bar for the resilience subsystem: with transient I/O
failures (retryable, rate 0.3), one corrupt Avro block (skip mode), and one
injected NaN coordinate step, a GAME training run completes and matches the
clean-run objective after rollback; a killed-then-restarted run resumes
from the last checkpoint to the same final model. Everything runs in plain
pytest via the deterministic fault registry (photon_ml_tpu.resilience).
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.cli import game_training_driver
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.resilience import faults

from game_test_utils import make_glmix_data
from test_game_drivers import COMMON_FLAGS, GAME_EXAMPLE_SCHEMA

NUM_ITERATIONS = 10  # enough cycles that descent reaches its fixed point


@pytest.fixture(scope="module")
def chaos_train_dir(tmp_path_factory):
    """Two-part-file training dir; part-1 written in small blocks with ONE
    block corrupted (so skip mode drops a bounded row range, not a file)."""
    base = tmp_path_factory.mktemp("chaos")
    rng = np.random.default_rng(20260803)
    gd, truth = make_glmix_data(
        rng, num_users=8, rows_per_user_range=(20, 40), d_fixed=4, d_random=3
    )
    data = {
        "y": gd.response,
        "x_fixed": truth["x_fixed"],
        "x_random": truth["x_random"],
        "user_raw": [gd.id_vocabs["userId"][i] for i in gd.ids["userId"]],
    }
    n = gd.num_rows
    split = n // 2

    def records(rows):
        for r in rows:
            yield {
                "uid": str(r),
                "label": float(data["y"][r]),
                "fixedFeatures": [
                    {"name": f"f{j}", "term": "", "value": float(v)}
                    for j, v in enumerate(data["x_fixed"][r])
                    if v != 0.0
                ],
                "userFeatures": [
                    {"name": f"u{j}", "term": "", "value": float(v)}
                    for j, v in enumerate(data["x_random"][r])
                    if v != 0.0
                ],
                "metadataMap": {"userId": data["user_raw"][r]},
                "weight": None,
                "offset": None,
            }

    train_dir = base / "train"
    train_dir.mkdir()
    avro_io.write_container(
        str(train_dir / "part-0.avro"), records(range(split)), GAME_EXAMPLE_SCHEMA
    )
    avro_io.write_container(
        str(train_dir / "part-1.avro"),
        records(range(split, n)),
        GAME_EXAMPLE_SCHEMA,
        block_size=16,
    )

    # corrupt the middle block of part-1 (deflate payload garbled in place)
    part1 = str(train_dir / "part-1.avro")
    raw = open(part1, "rb").read()
    syncs = []
    start = 0
    while True:
        hit = raw.find(avro_io.DEFAULT_SYNC, start)
        if hit < 0:
            break
        syncs.append(hit)
        start = hit + 1
    assert len(syncs) >= 4, "need multiple blocks to corrupt just one"
    lo, hi = syncs[1] + 16, syncs[2]
    garbled = bytearray(raw)
    mid = (lo + hi) // 2
    for i in range(mid, min(mid + 8, hi)):
        garbled[i] ^= 0xFF
    with open(part1, "wb") as f:
        f.write(bytes(garbled))
    return str(train_dir), str(base)


def _run_driver(train_dir, out_dir, num_iterations, extra=(), plan=None):
    args = [
        "--train-input-dirs", train_dir,
        "--output-dir", out_dir,
        "--num-iterations", str(num_iterations),
        "--model-output-mode", "NONE",
        "--on-corrupt", "skip",
        "--corrupt-skip-budget", "4",
        "--io-retries", "8",
        "--io-retry-base-delay", "0",
        # single intercept (global only): a per-shard intercept pair is
        # nearly collinear and makes the alternating descent contract too
        # slowly to reach its fixed point in a test-sized iteration budget
        "--feature-shard-id-to-intercept-map", "global:true|per_user:false",
    ] + COMMON_FLAGS + list(extra)  # extras AFTER so they can override
    if plan is None:
        return game_training_driver.main(args)
    with faults.fault_scope(plan):
        return game_training_driver.main(args)


@pytest.mark.faults
class TestGameChaos:
    def test_chaos_run_completes_and_matches_clean_objective(
        self, chaos_train_dir, tmp_path
    ):
        train_dir, _ = chaos_train_dir
        clean = _run_driver(
            train_dir, str(tmp_path / "clean"), NUM_ITERATIONS
        )
        plan = faults.FaultPlan(
            [
                # transient read failures on ~30% of block reads, healed by
                # the 8-attempt retry policy
                faults.FaultSpec("io.read_block", rate=0.3, seed=13, times=None),
                # one poisoned coordinate update (step 3 = the fixed effect's
                # second solve), rolled back by the divergence guard
                faults.FaultSpec("optim.step", at=3, kind="nan"),
            ]
        )
        chaos = _run_driver(
            train_dir,
            str(tmp_path / "chaos"),
            NUM_ITERATIONS,
            extra=("--divergence-guard", "rollback"),
            plan=plan,
        )
        # the injected faults actually fired
        assert plan.fire_count("io.read_block") > 0
        assert plan.fire_count("optim.step") == 1
        events = chaos.results[0][1].guard_events
        assert len(events) == 1 and events[0].action == "rollback"
        assert events[0].step == 3

        # training data identical (same skipped block), rollback re-converges:
        # final objectives agree to well under 1e-6 relative
        obj_clean = clean.results[0][1].objective_history[-1]
        obj_chaos = chaos.results[0][1].objective_history[-1]
        assert np.isfinite(obj_chaos)
        assert abs(obj_chaos - obj_clean) <= 1e-6 * max(1.0, abs(obj_clean))

        # and the final models agree coordinate-by-coordinate (loose bound:
        # near the optimum the objective is flat, so f32 solves stall at
        # slightly different coefficient vectors of equal objective)
        for name, w in clean.results[0][1].coefficients.items():
            np.testing.assert_allclose(
                np.asarray(chaos.results[0][1].coefficients[name]),
                np.asarray(w),
                atol=0.01,
            )

    def test_killed_then_restarted_resumes_to_same_model(
        self, chaos_train_dir, tmp_path
    ):
        train_dir, _ = chaos_train_dir

        def io_plan():
            # fresh counters per run: transient faults on block reads AND
            # checkpoint writes, all healed by retry
            return faults.FaultPlan(
                [
                    faults.FaultSpec("io.read_block", rate=0.3, seed=5, times=None),
                    faults.FaultSpec("io.checkpoint_write", rate=0.3, seed=6, times=None),
                ]
            )

        straight = _run_driver(
            train_dir,
            str(tmp_path / "straight"),
            4,
            extra=("--checkpoint-dir", str(tmp_path / "ckpt-a")),
            plan=io_plan(),
        )
        # "kill" after 2 of 4 iterations...
        _run_driver(
            train_dir,
            str(tmp_path / "killed"),
            2,
            extra=("--checkpoint-dir", str(tmp_path / "ckpt-b")),
            plan=io_plan(),
        )
        # ...leave crash debris next to the checkpoint...
        debris = tmp_path / "ckpt-b" / "combo-0" / ".ckpt-crashed"
        debris.mkdir()
        (debris / "arrays.npz").write_bytes(b"\x00" * 32)
        # ...and restart for the full 4 iterations: resumes from step 4
        resumed = _run_driver(
            train_dir,
            str(tmp_path / "resumed"),
            4,
            extra=("--checkpoint-dir", str(tmp_path / "ckpt-b")),
            plan=io_plan(),
        )
        r_straight = straight.results[0][1]
        r_resumed = resumed.results[0][1]
        assert r_resumed.objective_history == pytest.approx(
            r_straight.objective_history, rel=1e-6
        )
        for name, w in r_straight.coefficients.items():
            np.testing.assert_allclose(
                np.asarray(r_resumed.coefficients[name]),
                np.asarray(w),
                rtol=1e-6, atol=1e-7,
            )


@pytest.mark.faults
@pytest.mark.preempt
class TestPreemptionChaos:
    """Cooperative preemption end-to-end through the training driver: a
    deterministic "SIGTERM" (PHOTON_PREEMPT_AT) lands mid-run, the driver
    drains to the boundary, writes an emergency checkpoint, and the
    --max-restarts supervisor relaunches in-process to a final model
    BITWISE-equal to an uninterrupted run."""

    def _reset(self):
        from photon_ml_tpu.resilience import preemption

        preemption.reset()

    def test_preempt_mid_cycle_supervised_rerun_bitwise(
        self, chaos_train_dir, tmp_path, monkeypatch
    ):
        train_dir, _ = chaos_train_dir
        straight = _run_driver(
            train_dir,
            str(tmp_path / "straight"),
            4,
            extra=("--checkpoint-dir", str(tmp_path / "ckpt-a")),
        )
        self._reset()
        # fire at the 3rd update boundary; the supervisor relaunches once
        # and the relaunched attempt resumes from the emergency checkpoint.
        # --checkpoint-async additionally exercises the background-commit
        # path end-to-end (the emergency save fences via wait()).
        monkeypatch.setenv("PHOTON_PREEMPT_AT", "cycle:3")
        try:
            resumed = _run_driver(
                train_dir,
                str(tmp_path / "resumed"),
                4,
                extra=(
                    "--checkpoint-dir", str(tmp_path / "ckpt-b"),
                    "--checkpoint-async", "true",
                    "--max-restarts", "2",
                ),
            )
        finally:
            self._reset()
        # the spec actually fired (the flag machinery consumed it)
        r_straight = straight.results[0][1]
        r_resumed = resumed.results[0][1]
        assert r_resumed.objective_history == r_straight.objective_history
        for name, w in r_straight.coefficients.items():
            np.testing.assert_array_equal(
                np.asarray(r_resumed.coefficients[name]), np.asarray(w)
            )
        # the emergency checkpoint landed (step 3, retired or superseded by
        # later saves — SOME step dir exists and the run completed)
        assert any(
            d.startswith("step-")
            for d in os.listdir(tmp_path / "ckpt-b" / "combo-0")
        )

    def test_preempt_without_restart_budget_exits_with_code(
        self, chaos_train_dir, tmp_path, monkeypatch
    ):
        from photon_ml_tpu.resilience import preemption

        train_dir, _ = chaos_train_dir
        self._reset()
        monkeypatch.setenv("PHOTON_PREEMPT_AT", "cycle:2")
        try:
            with pytest.raises(SystemExit) as ei:
                _run_driver(
                    train_dir,
                    str(tmp_path / "out"),
                    4,
                    extra=("--checkpoint-dir", str(tmp_path / "ckpt")),
                )
        finally:
            self._reset()
        assert ei.value.code == preemption.PREEMPT_EXIT_CODE
        # the emergency checkpoint is on disk for the NEXT (supervised) run
        assert os.path.exists(tmp_path / "ckpt" / "combo-0" / "step-2")

    def test_injected_preempt_signal_via_photon_faults(
        self, chaos_train_dir, tmp_path
    ):
        """The seeded fault registry can deliver the preemption too
        (PHOTON_FAULTS="preempt.signal:at=N") — same drain, same resume."""
        train_dir, _ = chaos_train_dir
        self._reset()
        plan = faults.FaultPlan([faults.FaultSpec("preempt.signal", at=4)])
        try:
            resumed = _run_driver(
                train_dir,
                str(tmp_path / "out"),
                4,
                extra=(
                    "--checkpoint-dir", str(tmp_path / "ckpt"),
                    "--max-restarts", "1",
                ),
                plan=plan,
            )
        finally:
            self._reset()
        assert plan.fire_count("preempt.signal") == 1
        assert len(resumed.results[0][1].objective_history) == 8
