"""Convergence-compacted solve scheduler (optim/scheduler.py).

The load-bearing claims, pinned BITWISE:

  * resumable kernels: an LBFGS / OWL-QN / TRON solve chunked at ANY K and
    resumed from its paused state equals the one-shot kernel bit for bit;
  * compaction: gathering active lanes into smaller ladder-sized batches
    between chunks (and scattering finished lanes back to entity order)
    changes no entity's result bits, through the plain / bucketed /
    streaming random-effect coordinates;
  * reuse: compacted batches land on ladder rungs and REUSE compiled chunk
    executables — no per-active-count recompiles.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from game_test_utils import make_glmix_data

from photon_ml_tpu.algorithm.random_effect import (
    RandomEffectCoordinate,
    entity_lane_fns,
)
from photon_ml_tpu.compile import compile_stats
from photon_ml_tpu.data.game import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_advance_, lbfgs_init_, lbfgs_result
from photon_ml_tpu.optim.scheduler import (
    SolveSchedule,
    compacted_solve,
    resolve_schedule,
    solve_stats,
)
from photon_ml_tpu.optim.tron import tron_advance_, tron_init_, tron_result
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.types import OptimizerType, TaskType

pytestmark = pytest.mark.compaction


def assert_results_bitwise(a, b):
    """Every array field of two OptResults equal bit for bit (NaN == NaN:
    histories carry NaN past each lane's final iteration)."""
    for name, x, y in zip(a._fields, a, b):
        if x is None or y is None:
            assert x is y, name
            continue
        assert np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True), name


def quadratic(A, b):
    def vg(w):
        g = A @ w - b
        return 0.5 * jnp.dot(w, A @ w) - jnp.dot(b, w), g

    return vg


def make_spd(rng, d, cond=200.0):
    q, _ = np.linalg.qr(rng.normal(size=(d, d)))
    eig = np.geomspace(1.0, cond, d)
    return jnp.asarray((q * eig) @ q.T, jnp.float32)


# ---------------------------------------------------------------------------
# resumable kernels
# ---------------------------------------------------------------------------


class TestResumableKernels:
    @pytest.mark.parametrize("chunk", [1, 3, 7, 100])
    def test_lbfgs_chunked_equals_one_shot(self, rng, chunk):
        d = 10
        A = make_spd(rng, d)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        vg = quadratic(A, b)
        cfg = OptimizerConfig(max_iterations=50, tolerance=1e-7)
        one = jax.jit(
            lambda w: lbfgs_result(
                lbfgs_advance_(vg, lbfgs_init_(vg, w, cfg), cfg)
            )
        )(jnp.zeros(d, jnp.float32))
        st = jax.jit(lambda w: lbfgs_init_(vg, w, cfg))(jnp.zeros(d, jnp.float32))
        adv = jax.jit(
            lambda s, lim: lbfgs_advance_(vg, s, cfg, iteration_limit=lim)
        )
        lim = 0
        while lim < cfg.max_iterations:
            lim = min(lim + chunk, cfg.max_iterations)
            st = adv(st, jnp.int32(lim))
        assert_results_bitwise(lbfgs_result(st), one)

    @pytest.mark.parametrize("chunk", [1, 4, 100])
    def test_owlqn_chunked_equals_one_shot(self, rng, chunk):
        d = 12
        b = jnp.asarray(rng.normal(size=d) * 2.0, jnp.float32)
        vg = lambda w: (0.5 * jnp.sum((w - b) ** 2), w - b)
        cfg = OptimizerConfig(max_iterations=60, tolerance=1e-8)
        l1 = 0.7
        one = jax.jit(
            lambda w: lbfgs_result(
                lbfgs_advance_(
                    vg, lbfgs_init_(vg, w, cfg, l1_weight=l1), cfg, l1_weight=l1
                )
            )
        )(jnp.zeros(d, jnp.float32))
        st = jax.jit(lambda w: lbfgs_init_(vg, w, cfg, l1_weight=l1))(
            jnp.zeros(d, jnp.float32)
        )
        adv = jax.jit(
            lambda s, lim: lbfgs_advance_(
                vg, s, cfg, l1_weight=l1, iteration_limit=lim
            )
        )
        lim = 0
        while lim < cfg.max_iterations:
            lim = min(lim + chunk, cfg.max_iterations)
            st = adv(st, jnp.int32(lim))
        assert_results_bitwise(lbfgs_result(st), one)
        # the one-shot OWL-QN really produced sparsity (the branch under test)
        assert np.sum(np.asarray(one.coefficients) == 0.0) > 0

    @pytest.mark.parametrize("chunk", [1, 4, 100])
    def test_tron_chunked_equals_one_shot(self, rng, chunk):
        d = 10
        A = make_spd(rng, d)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        vg = quadratic(A, b)
        hvp = lambda w, v: A @ v
        cfg = OptimizerConfig(max_iterations=30, tolerance=1e-6)
        one = jax.jit(
            lambda w: tron_result(
                tron_advance_(vg, hvp, tron_init_(vg, w, cfg), cfg)
            )
        )(jnp.zeros(d, jnp.float32))
        st = jax.jit(lambda w: tron_init_(vg, w, cfg))(jnp.zeros(d, jnp.float32))
        adv = jax.jit(
            lambda s, lim: tron_advance_(vg, hvp, s, cfg, iteration_limit=lim)
        )
        lim = 0
        while lim < cfg.max_iterations:
            lim = min(lim + chunk, cfg.max_iterations)
            st = adv(st, jnp.int32(lim))
        assert_results_bitwise(tron_result(st), one)

    def test_one_shot_wrappers_unchanged(self, rng):
        """lbfgs_minimize_/tron_minimize_ still converge to the analytic
        optimum (the wrapper preserves the pre-resumable API)."""
        from photon_ml_tpu.optim.lbfgs import lbfgs_minimize
        from photon_ml_tpu.optim.tron import tron_minimize

        d = 8
        A = make_spd(rng, d, cond=50.0)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        w_star = jnp.linalg.solve(A, b)
        res = lbfgs_minimize(
            quadratic(A, b), jnp.zeros(d, jnp.float32),
            OptimizerConfig(max_iterations=100, tolerance=1e-7),
        )
        np.testing.assert_allclose(res.coefficients, w_star, rtol=1e-3, atol=1e-3)
        res = tron_minimize(
            quadratic(A, b), lambda w, v: A @ v, jnp.zeros(d, jnp.float32),
            OptimizerConfig(max_iterations=50, tolerance=1e-6),
        )
        np.testing.assert_allclose(res.coefficients, w_star, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# compacted_solve
# ---------------------------------------------------------------------------


def skewed_lane_problem(rng, E=40, M=10, D=4, hard=4):
    """A few ill-conditioned lanes among many easy ones."""
    x = rng.normal(size=(E, M, D)).astype(np.float32)
    x[:hard] *= np.geomspace(1.0, 32.0, D).astype(np.float32)
    w_true = (rng.normal(size=(E, D)) * 0.5).astype(np.float32)
    z = np.einsum("emd,ed->em", x.astype(np.float64), w_true)
    y = (1.0 / (1.0 + np.exp(-z)) > rng.random((E, M))).astype(np.float32)
    data = tuple(
        jnp.asarray(a)
        for a in (x, y, np.zeros((E, M), np.float32), np.ones((E, M), np.float32))
    )
    return data, jnp.zeros((E, D), jnp.float32)


class TestCompactedSolve:
    @pytest.mark.parametrize(
        "optimizer,reg",
        [
            (OptimizerType.LBFGS, RegularizationContext.l2(0.5)),
            (OptimizerType.LBFGS, RegularizationContext.elastic_net(0.3, 0.5)),
            (OptimizerType.TRON, RegularizationContext.l2(0.5)),
        ],
        ids=["lbfgs-l2", "owlqn-l1", "tron"],
    )
    @pytest.mark.parametrize("chunk", [1, 5, 64])
    def test_bitwise_vs_one_shot(self, rng, optimizer, reg, chunk):
        data, w0 = skewed_lane_problem(rng)
        cfg = (
            OptimizerConfig(max_iterations=25, tolerance=1e-6)
            if optimizer == OptimizerType.TRON
            else OptimizerConfig(max_iterations=60, tolerance=1e-7)
        )
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=optimizer,
            optimizer_config=cfg,
            regularization=reg,
        )
        solve_one, *_ = entity_lane_fns(**kw)
        one = jax.jit(jax.vmap(solve_one))(*data, w0)
        res = compacted_solve(
            data, w0, schedule=SolveSchedule(chunk_size=chunk), **kw
        )
        assert_results_bitwise(res, one)

    def test_ledger_and_reuse(self, rng):
        """Saved lane-iterations are positive on a skewed distribution, and
        a second identical solve reuses every compiled chunk executable."""
        data, w0 = skewed_lane_problem(rng, E=40, hard=4)
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(max_iterations=80, tolerance=1e-8),
            regularization=RegularizationContext.l2(1.0),
        )
        schedule = SolveSchedule(chunk_size=8)
        solve_stats.reset()
        compacted_solve(data, w0, schedule=schedule, label="warm", **kw)
        rec = solve_stats.snapshot()[-1]
        assert rec.lanes == 40
        assert rec.executed > 0
        assert rec.executed < rec.baseline  # compaction genuinely saved work
        assert rec.saved == rec.baseline - rec.executed
        # batches shrank at least once and ride the ladder
        assert any(c.batch_lanes < 40 for c in rec.chunks)
        sites = ("scheduler.init", "scheduler.chunk",
                 "scheduler.compact", "scheduler.scatter")
        before = {s: compile_stats.traces_of(s) for s in sites}
        compacted_solve(data, w0, schedule=schedule, label="reuse", **kw)
        for s in sites:
            assert compile_stats.traces_of(s) == before[s], (
                f"{s} recompiled on an identical warm solve"
            )

    def test_resolve_schedule_spellings(self, monkeypatch):
        assert resolve_schedule("off") is None
        assert resolve_schedule(False) is None
        assert resolve_schedule(0) is None
        assert resolve_schedule("on").chunk_size == SolveSchedule().chunk_size
        assert resolve_schedule(5).chunk_size == 5
        assert resolve_schedule("12").chunk_size == 12
        with pytest.raises(ValueError):
            resolve_schedule("sideways")
        with pytest.raises(ValueError):
            resolve_schedule("-3")
        monkeypatch.delenv("PHOTON_SOLVE_CHUNK", raising=False)
        assert resolve_schedule(None) is None
        monkeypatch.setenv("PHOTON_SOLVE_CHUNK", "9")
        assert resolve_schedule(None).chunk_size == 9
        monkeypatch.setenv("PHOTON_SOLVE_CHUNK", "off")
        assert resolve_schedule(None) is None


# ---------------------------------------------------------------------------
# coordinate wiring
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def glmix():
    rng = np.random.default_rng(77)
    data, _ = make_glmix_data(
        rng, num_users=40, rows_per_user_range=(3, 30), d_fixed=4, d_random=3
    )
    return data


class TestCoordinateWiring:
    def test_random_effect_coordinate_bitwise(self, glmix):
        ds = build_random_effect_dataset(
            glmix, RandomEffectDataConfig("userId", "per_user")
        )
        kw = dict(
            dataset=ds,
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer=OptimizerType.LBFGS,
            regularization=RegularizationContext.l2(0.1),
        )
        plain = RandomEffectCoordinate(**kw)
        sched = RandomEffectCoordinate(
            **kw, solve_schedule=SolveSchedule(chunk_size=6)
        )
        assert getattr(plain, "cd_jit", True)
        assert sched.cd_jit is False
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        w_plain, res_plain = jax.jit(plain.update)(
            resid, plain.initial_coefficients()
        )
        w_sched, res_sched = sched.update(resid, sched.initial_coefficients())
        assert np.array_equal(np.asarray(w_plain), np.asarray(w_sched))
        assert_results_bitwise(res_sched, jax.tree.map(jnp.asarray, res_plain))
        # scoring off the compacted coefficients matches too
        assert np.array_equal(
            np.asarray(plain.score(w_plain)), np.asarray(sched.score(w_sched))
        )

    def test_random_effect_rejects_traced_lambda(self, glmix):
        ds = build_random_effect_dataset(
            glmix, RandomEffectDataConfig("userId", "per_user")
        )
        coord = RandomEffectCoordinate(
            dataset=ds,
            task=TaskType.LOGISTIC_REGRESSION,
            solve_schedule=SolveSchedule(chunk_size=4),
        )
        with pytest.raises(ValueError, match="compaction"):
            coord.update(
                jnp.zeros((glmix.num_rows,), jnp.float32),
                coord.initial_coefficients(),
                reg_weight=jnp.asarray(0.5),
            )

    @pytest.mark.slow  # ~18s: tier-1 rides the 870s budget's edge (ROADMAP re-anchor note); the streaming-coordinate wiring pin above and the per-regularizer compacted-solve pins keep the scheduler bitwise contract tier-1
    def test_bucketed_coordinate_bitwise(self, glmix):
        from photon_ml_tpu.algorithm.bucketed_random_effect import (
            BucketedRandomEffectCoordinate,
        )

        cfg = RandomEffectDataConfig("userId", "per_user")
        kw = dict(
            data=glmix,
            config=cfg,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization=RegularizationContext.l2(0.2),
        )
        plain = BucketedRandomEffectCoordinate(**kw)
        sched = BucketedRandomEffectCoordinate(
            **kw,
            bundle=plain.bundle,  # share the built stacks
            solve_schedule=SolveSchedule(chunk_size=6),
        )
        assert sched.cd_jit is False
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        st_plain, _ = plain.update(resid, plain.initial_coefficients())
        st_sched, _ = sched.update(resid, sched.initial_coefficients())
        for a, b in zip(st_plain, st_sched):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow  # ~9s of GSPMD compiles; tier-1 still drives this
    # path end-to-end via test_exec_plan's mesh-scheduled driver run
    def test_plain_coordinate_composes_with_mesh(self, glmix):
        """RandomEffectCoordinate(mesh_ctx=...) — the GSPMD-sharded
        scheduled path behind the deleted --solve-compaction x
        --distributed fence: pads + shards the entity axis, trims the
        tracker to real entities, and matches the unsharded scheduled
        solve under the mesh path's allclose contract."""
        from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh

        ds = build_random_effect_dataset(
            glmix, RandomEffectDataConfig("userId", "per_user")
        )
        kw = dict(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer_config=OptimizerConfig(max_iterations=20, tolerance=1e-9),
            regularization=RegularizationContext.l2(0.1),
            solve_schedule=SolveSchedule(chunk_size=4),
        )
        plain = RandomEffectCoordinate(ds, **kw)
        mesh = RandomEffectCoordinate(
            ds, mesh_ctx=MeshContext(data_mesh()), **kw
        )
        assert mesh.num_entities % 8 == 0  # padded to the device multiple
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        w_plain, _ = plain.update(resid, plain.initial_coefficients())
        w_mesh, trk = mesh.update(resid, mesh.initial_coefficients())
        assert np.asarray(trk.reason).shape[0] == mesh.true_entities
        np.testing.assert_allclose(
            np.asarray(w_plain), np.asarray(w_mesh)[: mesh.true_entities],
            rtol=1e-6, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(plain.score(w_plain)), np.asarray(mesh.score(w_mesh)),
            rtol=1e-6, atol=1e-6,
        )

    def test_plain_coordinate_mesh_requires_schedule(self, glmix):
        from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh

        ds = build_random_effect_dataset(
            glmix, RandomEffectDataConfig("userId", "per_user")
        )
        with pytest.raises(ValueError, match="one-shot mesh solves"):
            RandomEffectCoordinate(
                ds, task=TaskType.LOGISTIC_REGRESSION,
                mesh_ctx=MeshContext(data_mesh()),
            )

    @pytest.mark.slow  # per-bucket GSPMD compiles (~27s); the plain-
    # coordinate mesh test above pins the same mechanism in tier-1
    def test_bucketed_composes_with_mesh(self, glmix):
        """The bucketed-compaction x mesh_ctx fence is DELETED: scheduled
        buckets GSPMD-shard their entity axis over the mesh and run the
        shared chunk kernels — same allclose contract as the one-shot
        shard_map engine (XLA may fuse a lane's reductions differently per
        per-device batch; the BITWISE guarantee is the streaming
        owner-computes path's, pinned elsewhere)."""
        from photon_ml_tpu.algorithm.bucketed_random_effect import (
            BucketedRandomEffectCoordinate,
        )
        from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh

        kw = dict(
            data=glmix,
            config=RandomEffectDataConfig("userId", "per_user"),
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer_config=OptimizerConfig(max_iterations=20, tolerance=1e-9),
            regularization=RegularizationContext.l2(0.1),
            solve_schedule=SolveSchedule(chunk_size=4),
        )
        plain = BucketedRandomEffectCoordinate(**kw)
        mesh = BucketedRandomEffectCoordinate(
            mesh_ctx=MeshContext(data_mesh()), **kw
        )
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        w_plain, _ = plain.update(resid, plain.initial_coefficients())
        w_mesh, _ = mesh.update(resid, mesh.initial_coefficients())
        for j, (sub, wa, wb) in enumerate(zip(plain._subs, w_plain, w_mesh)):
            np.testing.assert_allclose(
                np.asarray(wa),
                np.asarray(wb)[: sub.dataset.num_entities],
                rtol=1e-6, atol=1e-6, err_msg=f"bucket {j}",
            )
        np.testing.assert_allclose(
            np.asarray(plain.score(w_plain)), np.asarray(mesh.score(w_mesh)),
            rtol=1e-6, atol=1e-6,
        )
        np.testing.assert_allclose(
            float(plain.regularization_term(w_plain)),
            float(mesh.regularization_term(w_mesh)), rtol=1e-6,
        )

    def test_streaming_coordinate_bitwise(self, glmix, tmp_path):
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            StreamingRandomEffectCoordinate,
            write_re_entity_blocks,
        )

        manifest = write_re_entity_blocks(
            glmix,
            RandomEffectDataConfig("userId", "per_user"),
            str(tmp_path / "blocks"),
            block_entities=16,
        )
        kw = dict(
            manifest=manifest,
            task=TaskType.LOGISTIC_REGRESSION,
            regularization=RegularizationContext.l2(0.1),
        )
        plain = StreamingRandomEffectCoordinate(
            **kw, state_root=str(tmp_path / "state-plain")
        )
        sched = StreamingRandomEffectCoordinate(
            **kw,
            state_root=str(tmp_path / "state-sched"),
            solve_schedule=SolveSchedule(chunk_size=6),
        )
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        st_plain, res_plain = plain.update(resid, plain.initial_coefficients())
        st_sched, res_sched = sched.update(resid, sched.initial_coefficients())
        for i in range(len(manifest.blocks)):
            assert np.array_equal(st_plain.block(i), st_sched.block(i)), i
        for a, b in zip(res_plain, res_sched):
            assert_results_bitwise(
                jax.tree.map(np.asarray, b), jax.tree.map(np.asarray, a)
            )
        # scoring off the two states matches bitwise as well
        assert np.array_equal(
            np.asarray(plain.score(st_plain)), np.asarray(sched.score(st_sched))
        )

    def test_coordinate_descent_end_to_end(self, glmix):
        """A full CD run with a scheduled RE coordinate equals the
        unscheduled run bitwise (the cd_jit=False raw-update path)."""
        from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
        from photon_ml_tpu.ops import losses

        loss = losses.for_task(TaskType.LOGISTIC_REGRESSION)
        labels = jnp.asarray(glmix.response)
        weights = jnp.asarray(glmix.weight)
        loss_fn = lambda total: jnp.sum(weights * loss.loss(total, labels))

        def run(schedule):
            ds = build_random_effect_dataset(
                glmix, RandomEffectDataConfig("userId", "per_user")
            )
            coord = RandomEffectCoordinate(
                dataset=ds,
                task=TaskType.LOGISTIC_REGRESSION,
                regularization=RegularizationContext.l2(0.1),
                solve_schedule=schedule,
            )
            cd = CoordinateDescent({"per_user": coord}, loss_fn)
            return cd.run(num_iterations=2, num_rows=glmix.num_rows)

        base = run(None)
        comp = run(SolveSchedule(chunk_size=7))
        assert np.array_equal(
            np.asarray(base.coefficients["per_user"]),
            np.asarray(comp.coefficients["per_user"]),
        )
        np.testing.assert_array_equal(
            np.asarray(base.objective_history), np.asarray(comp.objective_history)
        )
