"""tools/photon_lint: the unified JAX-invariant static-analysis framework.

Replaces tests/test_lint_excepts.py + tests/test_lint_jit_sites.py: the
two legacy package-clean gates are now ONE parametrized tier-1 test over
every rule of the shared engine, plus engine-level coverage (suppression
grammar, allowlist staleness, --json schema, exit codes) and a fixture
corpus proving each rule fires on its seeded violation.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from tools.photon_lint import engine  # noqa: E402
from tools.photon_lint.rules import RULES  # noqa: E402
from tools.photon_lint.rules.fault_sites import FaultSitesRule  # noqa: E402
from tools.photon_lint.rules.jit_sites import JitSitesRule  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lint_fixtures")

#: rule -> (bad fixture, pretend relpath or None, expected finding lines)
CORPUS = {
    "broad-except": (
        "broad_except_bad.py", None, {11, 18, 25, 40, 48, 55},
    ),
    "jit-sites": (
        "jit_sites_bad.py", None, {14, 17, 22, 27, 28, 29},
    ),
    "traced-construction": (
        "traced_construction_bad.py", None, {18, 23, 30, 36, 48, 57},
    ),
    "bitwise-reduction": (
        # the rule is scoped to ops//optim/ path segments, so the fixture
        # is presented under a pretend ops/ relpath
        os.path.join("ops", "bitwise_reduction_bad.py"),
        "photon_ml_tpu/ops/fixture.py",
        {9, 13, 17, 21, 25, 36},
    ),
    "static-key-honesty": (
        "static_key_bad.py", None, {15, 23, 28},
    ),
    "fault-sites": (
        "fault_sites_bad.py", None, {10, 14, 19, 23, 27},
    ),
    "env-reads": (
        # scoped to the photon_ml_tpu package, so presented under a
        # pretend package relpath (tools/ and bench.py orchestrate
        # subprocess envs by design)
        "env_reads_bad.py",
        "photon_ml_tpu/ops/fixture.py",
        {10, 14, 18, 22, 26, 30},
    ),
}

CLEAN = {
    "broad-except": ("broad_except_ok.py", None),
    "jit-sites": ("jit_sites_ok.py", None),
    "traced-construction": ("traced_construction_ok.py", None),
    "bitwise-reduction": (
        os.path.join("ops", "bitwise_reduction_ok.py"),
        "photon_ml_tpu/ops/fixture.py",
    ),
    "static-key-honesty": ("static_key_ok.py", None),
    "fault-sites": ("fault_sites_ok.py", None),
    "env-reads": ("env_reads_ok.py", "photon_ml_tpu/ops/fixture.py"),
}


def _scan_fixture(rule, fname, relpath):
    with open(os.path.join(FIXTURES, fname), encoding="utf-8") as f:
        src = f.read()
    return engine.scan_source(
        src, path=fname, relpath=relpath or fname, rule_names=[rule]
    )


# ---------------------------------------------------------------------------
# the fixture corpus: every rule fires on its seeded bad example
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule", sorted(CORPUS))
def test_rule_fires_on_seeded_violations(rule):
    fname, relpath, expected = CORPUS[rule]
    findings = _scan_fixture(rule, fname, relpath)
    got = {f.line for f in findings if f.rule == rule}
    assert got == expected, [str(f) for f in findings]


@pytest.mark.parametrize("rule", sorted(CLEAN))
def test_rule_clean_on_ok_fixture(rule):
    fname, relpath = CLEAN[rule]
    findings = _scan_fixture(rule, fname, relpath)
    assert not [f for f in findings if f.rule == rule], [
        str(f) for f in findings
    ]


# ---------------------------------------------------------------------------
# THE tier-1 gate: the live tree lints clean under every rule
# (replaces the two legacy test_package_is_clean tests)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live_scan():
    """ONE full-scope scan with every rule (the engine parses each file
    once and shares the tree across rules — the same pass tier-1 pays)."""
    return engine.run(root=REPO)


@pytest.mark.parametrize("rule", sorted(RULES) + ["suppression"])
def test_live_tree_is_clean(rule, live_scan):
    findings, stats = live_scan
    assert stats["full_scope"] and stats["files_scanned"] > 100
    mine = [f for f in findings if f.rule == rule]
    assert not mine, "\n".join(str(f) for f in mine)


def test_default_scope_covers_the_hot_paths():
    """serve/, ops/fused_sparse.py, tools/ and bench.py are all inside the
    default scan scope — a bare jit or broad except cannot land there
    without tripping tier-1."""
    paths = [os.path.join(REPO, p) for p in engine.DEFAULT_SCOPE]
    scanned = {
        os.path.relpath(p, REPO).replace(os.sep, "/")
        for p in engine.iter_py_files(paths)
    }
    assert "bench.py" in scanned
    assert "photon_ml_tpu/ops/fused_sparse.py" in scanned
    assert "photon_ml_tpu/resilience/sites.py" in scanned
    assert any(p.startswith("photon_ml_tpu/serve/") for p in scanned)
    assert any(p.startswith("tools/photon_lint/") for p in scanned)


def test_fused_schedule_in_scan_scope():
    """The fused device loop (PR 19) is inside the default scan scope — a
    bare jit, an unjustified whole-batch reduce, or an unregistered fault
    site in the rung program cannot land without tripping tier-1 (the
    scheduler.rung program is exactly where one-ulp drift would silently
    break the device-vs-host bitwise pin)."""
    paths = [os.path.join(REPO, p) for p in engine.DEFAULT_SCOPE]
    scanned = {
        os.path.relpath(p, REPO).replace(os.sep, "/")
        for p in engine.iter_py_files(paths)
    }
    assert "photon_ml_tpu/optim/fused_schedule.py" in scanned
    assert "photon_ml_tpu/optim/scheduler.py" in scanned
    assert "photon_ml_tpu/compile/overrides.py" in scanned


def test_fleet_package_in_scan_scope():
    """The serving-fleet package (PR 11) is inside the default scan scope,
    module by module — a bare jit, broad except, or unregistered fault
    site in the router/replica/swap path cannot land without tripping
    tier-1."""
    paths = [os.path.join(REPO, p) for p in engine.DEFAULT_SCOPE]
    scanned = {
        os.path.relpath(p, REPO).replace(os.sep, "/")
        for p in engine.iter_py_files(paths)
    }
    for mod in ("plan", "replica", "router", "swap", "transport", "__init__"):
        assert f"photon_ml_tpu/serve/fleet/{mod}.py" in scanned
    assert "photon_ml_tpu/cli/fleet_driver.py" in scanned


def test_survivable_loop_surfaces_in_scan_scope():
    """The operator control plane (tools/fleetctl.py) and the multihost
    driver carrying the relaunch re-plan / delta-agreement glue are inside
    the default scan scope — a broad except or unregistered fault site in
    either cannot land without tripping tier-1."""
    paths = [os.path.join(REPO, p) for p in engine.DEFAULT_SCOPE]
    scanned = {
        os.path.relpath(p, REPO).replace(os.sep, "/")
        for p in engine.iter_py_files(paths)
    }
    assert "tools/fleetctl.py" in scanned
    assert "photon_ml_tpu/cli/game_multihost_driver.py" in scanned
    assert "photon_ml_tpu/parallel/elastic.py" in scanned
    assert "photon_ml_tpu/retrain/warm.py" in scanned


def test_exec_plan_module_in_scan_scope():
    """The execution-plan module (compile/plan.py) is inside the default
    scan scope: its resolve() consults env vars and constructs policy
    objects — exactly what the jit-sites / traced-construction rules
    exist to police if it ever leaks into a staged context."""
    paths = [os.path.join(REPO, p) for p in engine.DEFAULT_SCOPE]
    scanned = {
        os.path.relpath(p, REPO).replace(os.sep, "/")
        for p in engine.iter_py_files(paths)
    }
    assert "photon_ml_tpu/compile/plan.py" in scanned
    assert "photon_ml_tpu/compile/__init__.py" in scanned


def test_convergence_module_in_scan_scope():
    """The adaptive-scheduling convergence module (optim/convergence.py)
    is inside the default scan scope — its ledger I/O and env-resolved
    policy are exactly the surfaces the broad-except / fault-sites rules
    police."""
    paths = [os.path.join(REPO, p) for p in engine.DEFAULT_SCOPE]
    scanned = {
        os.path.relpath(p, REPO).replace(os.sep, "/")
        for p in engine.iter_py_files(paths)
    }
    assert "photon_ml_tpu/optim/convergence.py" in scanned


# ---------------------------------------------------------------------------
# engine: suppression-tag grammar
# ---------------------------------------------------------------------------


def test_suppression_requires_justification():
    src = "try:\n    pass\nexcept Exception:  # lint: broad-except\n    pass\n"
    findings = engine.scan_source(src, rule_names=["broad-except"])
    rules = {f.rule for f in findings}
    # the bare tag does NOT suppress, and is itself a finding
    assert "broad-except" in rules and "suppression" in rules


def test_suppression_with_justification_suppresses():
    src = (
        "try:\n    pass\n"
        "except Exception:  # lint: broad-except — fence, re-raised\n"
        "    raise\n"
    )
    assert not engine.scan_source(src, rule_names=["broad-except"])


def test_legacy_tag_requires_justification():
    src = "try:\n    pass\nexcept Exception:  # noqa: BLE001\n    pass\n"
    findings = engine.scan_source(src, rule_names=["broad-except"])
    assert {f.rule for f in findings} == {"broad-except", "suppression"}


def test_unknown_rule_in_tag_is_a_finding():
    src = "x = 1  # lint: no-such-rule — because\n"
    findings = engine.scan_source(src, rule_names=["broad-except"])
    assert any(
        f.rule == "suppression" and "unknown rule" in f.message
        for f in findings
    )


def test_tag_in_string_literal_does_not_count():
    """Tags are matched via tokenize: a tag INSIDE a string neither
    suppresses nor trips grammar validation."""
    src = 's = "# lint: broad-except"\ntry:\n    pass\nexcept Exception:\n    pass\n'
    findings = engine.scan_source(src, rule_names=["broad-except"])
    assert {f.rule for f in findings} == {"broad-except"}


def test_multiline_handler_tag_on_any_clause_line():
    """PR-8 satellite: the tag may sit on any line of a multi-line
    handler-type clause (the legacy linter only looked at node.lineno)."""
    src = (
        "try:\n    pass\n"
        "except (ValueError,\n"
        "        Exception):  # noqa: BLE001 — second clause line\n"
        "    raise\n"
    )
    assert not engine.scan_source(src, rule_names=["broad-except"])


def test_attribute_broad_except_flagged():
    """PR-8 satellite: ``except builtins.Exception`` escaped the legacy
    linter (ast.Attribute, not ast.Name)."""
    src = "import builtins\ntry:\n    pass\nexcept builtins.Exception:\n    pass\n"
    findings = engine.scan_source(src, rule_names=["broad-except"])
    assert any("builtins.Exception" in f.message for f in findings)


# ---------------------------------------------------------------------------
# engine: allowlist staleness + fault-site registry integrity
# ---------------------------------------------------------------------------


def test_stale_jit_allowlist_entry_fails():
    rule = JitSitesRule(root=REPO, allowlist={"x.py:gone": "was migrated"})
    findings = engine.scan_source(
        "VALUE = 1\n", path="x.py", relpath="x.py", rules=[rule]
    )
    assert not findings
    stale = list(rule.finalize(full_scope=False))
    assert stale and "stale" in stale[0][2]


def test_live_jit_allowlist_entry_not_stale():
    rule = JitSitesRule(root=REPO, allowlist={"x.py:f": "read-only"})
    src = "import jax\ndef f(x):\n    return jax.jit(x)\n"
    assert not engine.scan_source(src, path="x.py", relpath="x.py", rules=[rule])
    assert not list(rule.finalize(full_scope=False))


def test_stale_env_reads_allowlist_entry_fails():
    """PR-18 satellite: a legacy env-read site migrated onto the single
    resolver must shrink the allowlist, or the entry silently stops
    protecting anything (the jit-sites staleness discipline)."""
    from tools.photon_lint.rules.env_reads import EnvReadsRule

    rule = EnvReadsRule(
        root=REPO,
        allowlist={"photon_ml_tpu/x.py:gone": "was migrated"},
    )
    findings = engine.scan_source(
        "VALUE = 1\n", path="x.py", relpath="photon_ml_tpu/x.py",
        rules=[rule],
    )
    assert not findings
    stale = list(rule.finalize(full_scope=False))
    assert stale and "stale" in stale[0][2]


def test_live_env_reads_allowlist_entry_not_stale():
    from tools.photon_lint.rules.env_reads import EnvReadsRule

    rule = EnvReadsRule(
        root=REPO,
        allowlist={"photon_ml_tpu/x.py:f": "legacy resolver"},
    )
    src = "import os\ndef f():\n    return os.environ.get('K')\n"
    assert not engine.scan_source(
        src, path="x.py", relpath="photon_ml_tpu/x.py", rules=[rule]
    )
    assert not list(rule.finalize(full_scope=False))


def test_env_writes_never_flagged_anywhere():
    """Pinning a child environment (bench arms, test harnesses) is
    legitimate in-package too: only READS are the planner's business."""
    src = (
        "import os\n"
        "os.environ['PHOTON_SOLVE_CHUNK'] = 'off'\n"
        "os.environ.pop('PHOTON_SPARSE_KERNEL', None)\n"
        "del os.environ['PHOTON_SHAPE_LADDER']\n"
    )
    assert not engine.scan_source(
        src, relpath="photon_ml_tpu/ops/x.py", rule_names=["env-reads"]
    )


def test_unused_fault_registry_entry_fails():
    rule = FaultSitesRule(
        root=REPO,
        fault_sites={"io.read_block": 10, "io.never_wired": 20},
        preempt_sites={"cycle": 30},
    )
    src = (
        "from photon_ml_tpu.resilience import faults, preemption\n"
        "faults.inject('io.read_block')\n"
        "preemption.check('cycle')\n"
    )
    assert not engine.scan_source(src, rules=[rule])
    unused = list(rule.finalize(full_scope=True))
    assert len(unused) == 1 and "io.never_wired" in unused[0][2]
    # partial scans (--changed) must NOT report unused entries: the usage
    # may simply be in an unscanned file
    assert not list(rule.finalize(full_scope=False))


def test_registry_parse_matches_runtime_module():
    """The ast-parsed registry the rule enforces IS the module production
    code imports."""
    from photon_ml_tpu.resilience import sites

    rule = FaultSitesRule(root=REPO)
    assert set(rule._fault_sites) == set(sites.FAULT_SITES)
    assert set(rule._preempt_sites) == set(sites.PREEMPT_SITES)
    # and the wired sites the stack grew through PRs 1-7 are all present
    assert {
        "io.read_block", "io.checkpoint_write", "io.cache_read",
        "multihost.barrier", "optim.step", "preempt.signal",
    } <= set(sites.FAULT_SITES)
    assert set(sites.PREEMPT_SITES) == {
        "cycle", "block", "chunk", "bucket", "rung",
    }


# ---------------------------------------------------------------------------
# jit-sites: pjit / named_call coverage (PR-8 satellite)
# ---------------------------------------------------------------------------


def test_pjit_variants_flagged():
    for src in (
        "from jax.experimental.pjit import pjit\nf = pjit(lambda x: x)\n",
        "import jax\nf = jax.pjit(lambda x: x)\n",
    ):
        findings = engine.scan_source(src, rule_names=["jit-sites"])
        assert findings, src
    # annotated pjit passes
    assert not engine.scan_source(
        "from jax.experimental.pjit import pjit\n"
        "f = pjit(lambda x: x, donate_argnums=(0,))\n",
        rule_names=["jit-sites"],
    )


def test_named_call_outside_annotated_jit_flagged():
    findings = engine.scan_source(
        "import jax\ng = jax.named_call(lambda x: x)\n",
        rule_names=["jit-sites"],
    )
    assert findings and "named_call" in findings[0].message
    # nested inside an annotated jit it is that site's plumbing
    assert not engine.scan_source(
        "import jax\n"
        "g = jax.jit(jax.named_call(lambda x: x), donate_argnums=(0,))\n",
        rule_names=["jit-sites"],
    )


def test_qualname_resolution_in_messages():
    src = (
        "import jax\n"
        "class C:\n"
        "    def m(self):\n"
        "        return jax.jit(lambda x: x)\n"
    )
    (f,) = engine.scan_source(src, path="<test>", rule_names=["jit-sites"])
    assert "<test>:C.m" in f.message and f.line == 4


# ---------------------------------------------------------------------------
# the CLI: --json schema, exit codes, --changed scoping, jax-free import
# ---------------------------------------------------------------------------


def _run_cli(*args, **kw):
    return subprocess.run(
        [sys.executable, "-m", "tools.photon_lint", *args],
        capture_output=True, text=True, cwd=REPO, timeout=300, **kw,
    )


def test_cli_default_scope_clean_and_json_schema():
    proc = _run_cli("--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == 1
    assert payload["files_scanned"] > 100
    assert set(RULES) <= set(payload["rules"])
    assert "suppression" in payload["rules"]
    assert len(payload["rules"]) >= 7  # 2 migrated + 4 new + suppression
    assert payload["findings"] == [] and payload["counts"] == {}


def test_cli_findings_exit_1_with_locations():
    bad = os.path.join(FIXTURES, "jit_sites_bad.py")
    proc = _run_cli("--rule", "jit-sites", bad)
    assert proc.returncode == 1
    assert "jit_sites_bad.py:14" in proc.stdout
    payload = json.loads(_run_cli("--rule", "jit-sites", "--json", bad).stdout)
    assert payload["counts"]["jit-sites"] == 6
    f = payload["findings"][0]
    assert set(f) == {"rule", "path", "line", "message"}


def test_cli_unknown_rule_exit_2():
    proc = _run_cli("--rule", "no-such-rule")
    assert proc.returncode == 2 and "unknown rule" in proc.stderr


def test_changed_scope_filter():
    from tools.photon_lint.__main__ import scope_filter

    names = [
        "photon_ml_tpu/ops/objective.py",  # in scope
        "bench.py",                        # in scope
        "tools/photon_lint/engine.py",     # in scope
        "tests/test_photon_lint.py",       # tests are NOT in the scan scope
        "README.md",                       # not python
        "photon_ml_tpu/does_not_exist.py", # deleted files are skipped
    ]
    got = {
        os.path.relpath(p, REPO).replace(os.sep, "/")
        for p in scope_filter(names, REPO)
    }
    assert got == {
        "photon_ml_tpu/ops/objective.py", "bench.py",
        "tools/photon_lint/engine.py",
    }


def test_changed_mode_runs_clean_and_fast():
    """--changed is the pre-commit hook path: whatever the working tree
    state, scanning only the diff must stay quick and clean."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.photon_lint", "--changed"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_runner_never_imports_jax():
    """Like bench.py --list-sections: the linter must work on a host where
    importing jax would crash outright (pre-commit, device-free CI)."""
    tripwire = (
        "import builtins, sys\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    if name == 'jax' or name.startswith(('jax.', 'photon_ml_tpu')):\n"
        "        raise RuntimeError(f'{name} imported by photon_lint')\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "from tools.photon_lint.__main__ import main\n"
        "sys.exit(main(['--list-rules']))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", tripwire],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "fault-sites" in proc.stdout


# ---------------------------------------------------------------------------
# legacy CLI shims: same findings through the shared engine
# ---------------------------------------------------------------------------


def test_legacy_shims_clean_on_live_tree(capsys):
    import lint_excepts
    import lint_jit_sites

    for shim in (lint_excepts, lint_jit_sites):
        rc = shim.main([])
        out = capsys.readouterr()
        assert rc == 0, f"{shim.__name__}:\n{out.out}{out.err}"


def test_legacy_check_source_api_parity():
    import lint_excepts
    import lint_jit_sites

    bad = "try:\n    pass\nexcept:\n    pass\n"
    legacy = list(lint_excepts.check_source("<test>", bad))
    via_engine = engine.scan_source(bad, path="<test>", rule_names=["broad-except"])
    assert [ln for ln, _ in legacy] == [f.line for f in via_engine] == [3]

    bad_jit = "import jax\nf = jax.jit(lambda x: x)\n"
    legacy = list(lint_jit_sites.check_source("<test>", bad_jit))
    via_engine = engine.scan_source(bad_jit, path="<test>", rule_names=["jit-sites"])
    assert [ln for ln, _ in legacy] == [f.line for f in via_engine] == [2]
    # the ALLOWLIST is the engine's (single source of truth)
    from tools.photon_lint.rules.jit_sites import ALLOWLIST

    assert lint_jit_sites.ALLOWLIST is ALLOWLIST
