"""Optimizer kernel tests on analytic objectives (reference OptimizerIntegTest
/ IntegTestObjective strategy: known minima, statistical assertions) plus
cross-checks against scipy and closed forms, plus vmap batching.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.features import DenseFeatures
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective
from photon_ml_tpu.optim import OptimizerConfig, lbfgs_minimize, tron_minimize
from photon_ml_tpu.optim.lbfgs import lbfgs_minimize_
from photon_ml_tpu.types import ConvergenceReason


def quadratic(A, b):
    """f(w) = 0.5 w^T A w - b^T w; minimum at A^{-1} b."""

    def vg(w):
        g = A @ w - b
        return 0.5 * jnp.dot(w, A @ w) - jnp.dot(b, w), g

    return vg


def make_spd(rng, d, cond=50.0):
    q, _ = np.linalg.qr(rng.normal(size=(d, d)))
    eig = np.geomspace(1.0, cond, d)
    return (q * eig) @ q.T


def test_lbfgs_quadratic_exact(rng):
    d = 12
    A = jnp.asarray(make_spd(rng, d), jnp.float32)
    b = jnp.asarray(rng.normal(size=d), jnp.float32)
    res = lbfgs_minimize(quadratic(A, b), jnp.zeros(d, jnp.float32),
                         OptimizerConfig(max_iterations=100, tolerance=1e-7))
    w_star = jnp.linalg.solve(A, b)
    np.testing.assert_allclose(res.coefficients, w_star, rtol=1e-3, atol=1e-3)
    assert int(res.reason) in (ConvergenceReason.GRADIENT_CONVERGED,
                               ConvergenceReason.FUNCTION_VALUES_CONVERGED)


def test_tron_quadratic_exact(rng):
    d = 12
    A = jnp.asarray(make_spd(rng, d), jnp.float32)
    b = jnp.asarray(rng.normal(size=d), jnp.float32)
    vg = quadratic(A, b)
    res = tron_minimize(vg, lambda w, v: A @ v, jnp.zeros(d, jnp.float32),
                        OptimizerConfig(max_iterations=50, tolerance=1e-6))
    w_star = jnp.linalg.solve(A, b)
    np.testing.assert_allclose(res.coefficients, w_star, rtol=1e-3, atol=1e-3)


def make_logreg(rng, n=200, d=8, l2=1e-2):
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (1.0 / (1.0 + np.exp(-x @ w_true)) > rng.random(n)).astype(np.float32)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()
    vg = lambda w: obj.value_and_grad(w, batch, norm, l2)
    hvp = lambda w, v: obj.hessian_vector(w, v, batch, norm, l2)
    # scipy ground truth (float64)
    def f64(w):
        z = x.astype(np.float64) @ w
        val = np.sum(np.maximum(z, 0) + np.log1p(np.exp(-np.abs(z))) - y * z)
        return val + 0.5 * l2 * np.sum(w * w)
    ref = scipy.optimize.minimize(f64, np.zeros(d), method="L-BFGS-B",
                                  options={"maxiter": 500, "ftol": 1e-14, "gtol": 1e-10})
    return vg, hvp, jnp.asarray(ref.x, jnp.float32), d


def test_lbfgs_logistic_vs_scipy(rng):
    vg, _, w_ref, d = make_logreg(rng)
    res = lbfgs_minimize(vg, jnp.zeros(d, jnp.float32),
                         OptimizerConfig(max_iterations=200, tolerance=1e-7))
    np.testing.assert_allclose(res.coefficients, w_ref, rtol=2e-2, atol=2e-2)


def test_tron_logistic_vs_scipy(rng):
    vg, hvp, w_ref, d = make_logreg(rng)
    res = tron_minimize(vg, hvp, jnp.zeros(d, jnp.float32),
                        OptimizerConfig(max_iterations=30, tolerance=1e-6))
    np.testing.assert_allclose(res.coefficients, w_ref, rtol=2e-2, atol=2e-2)


def test_owlqn_lasso_closed_form(rng):
    """min 0.5||w - b||^2 + l1*||w||_1 has solution soft_threshold(b, l1)."""
    d = 16
    b = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 2.0
    l1 = 0.8
    vg = lambda w: (0.5 * jnp.sum((w - b) ** 2), w - b)
    res = lbfgs_minimize(vg, jnp.zeros(d, jnp.float32),
                         OptimizerConfig(max_iterations=200, tolerance=1e-8), l1_weight=l1)
    want = jnp.sign(b) * jnp.maximum(jnp.abs(b) - l1, 0.0)
    np.testing.assert_allclose(res.coefficients, want, rtol=1e-3, atol=1e-3)
    # sparsity: exact zeros, not merely small values
    assert np.sum(np.asarray(res.coefficients) == 0.0) == np.sum(np.abs(np.asarray(b)) <= l1)


def test_owlqn_elastic_net_logistic_sparsity(rng):
    n, d = 300, 20
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = np.zeros(d, np.float32)
    w_true[:3] = [2.0, -2.0, 1.5]  # only 3 informative features
    y = (1.0 / (1.0 + np.exp(-x @ w_true)) > rng.random(n)).astype(np.float32)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    obj = GLMObjective(losses.logistic)
    norm = NormalizationContext.identity()
    vg = lambda w: obj.value_and_grad(w, batch, norm, 0.0)
    res = lbfgs_minimize(vg, jnp.zeros(d, jnp.float32),
                         OptimizerConfig(max_iterations=200, tolerance=1e-7), l1_weight=10.0)
    w = np.asarray(res.coefficients)
    assert np.sum(w != 0.0) <= 10  # strong L1 produces real sparsity
    assert np.abs(w[0]) > 0 and np.abs(w[1]) > 0  # informative features survive
    assert w[0] > 0 and w[1] < 0


def test_lbfgs_vmap_batched_solves(rng):
    """vmap over independent problems — the GAME random-effect pattern."""
    E, d = 5, 6
    As = jnp.asarray(np.stack([make_spd(rng, d) for _ in range(E)]), jnp.float32)
    bs = jnp.asarray(rng.normal(size=(E, d)), jnp.float32)
    cfg = OptimizerConfig(max_iterations=80, tolerance=1e-7)

    def solve_one(A, b):
        return lbfgs_minimize_(quadratic(A, b), jnp.zeros(d, jnp.float32), cfg).coefficients

    ws = jax.jit(jax.vmap(solve_one))(As, bs)
    want = jnp.linalg.solve(As, bs[..., None])[..., 0]
    np.testing.assert_allclose(ws, want, rtol=5e-3, atol=5e-3)


def test_poisson_tron(rng):
    n, d = 150, 5
    x = (rng.normal(size=(n, d)) * 0.5).astype(np.float32)
    w_true = (rng.normal(size=d) * 0.5).astype(np.float32)
    lam = np.exp(x @ w_true)
    y = rng.poisson(lam).astype(np.float32)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
    obj = GLMObjective(losses.poisson)
    norm = NormalizationContext.identity()
    l2 = 1e-3
    vg = lambda w: obj.value_and_grad(w, batch, norm, l2)
    hvp = lambda w, v: obj.hessian_vector(w, v, batch, norm, l2)
    res = tron_minimize(vg, hvp, jnp.zeros(d, jnp.float32),
                        OptimizerConfig(max_iterations=50, tolerance=1e-6))
    def f64(w):
        z = x.astype(np.float64) @ w
        return np.sum(np.exp(z) - y * z) + 0.5 * l2 * np.sum(w * w)
    ref = scipy.optimize.minimize(f64, np.zeros(d), method="L-BFGS-B",
                                  options={"maxiter": 500, "ftol": 1e-14, "gtol": 1e-10})
    np.testing.assert_allclose(res.coefficients, ref.x.astype(np.float32), rtol=3e-2, atol=3e-2)


def test_state_tracking(rng):
    d = 8
    A = jnp.asarray(make_spd(rng, d), jnp.float32)
    b = jnp.asarray(rng.normal(size=d), jnp.float32)
    res = lbfgs_minimize(quadratic(A, b), jnp.zeros(d, jnp.float32),
                         OptimizerConfig(max_iterations=60, tolerance=1e-7))
    it = int(res.iterations)
    vals = np.asarray(res.value_history)[: it + 1]
    assert np.all(np.isfinite(vals))
    assert vals[-1] <= vals[0]  # monotone-ish improvement overall
    assert np.all(np.isnan(np.asarray(res.value_history)[it + 1:]))


class TestConvergenceReasons:
    """Every solver's stopping paths report the right ConvergenceReason —
    the codes the driver's convergence summaries and the compaction
    scheduler's active-lane masks are built on (reason == 0 IS the lane's
    'still active' flag)."""

    def _nan_off_origin(self):
        """Objective finite only at w = 0 with a nonzero gradient: every
        trial point the line search / trust region proposes evaluates to
        NaN, so the in-kernel non-finite rejection must fire."""

        def vg(w):
            at_origin = jnp.all(w == 0.0)
            f = jnp.where(at_origin, 1.0, jnp.nan)
            return f, jnp.ones_like(w)

        return vg

    # ---- LBFGS ----------------------------------------------------------
    def test_lbfgs_gradient_converged(self, rng):
        d = 6
        A = jnp.asarray(make_spd(rng, d, cond=5.0), jnp.float32)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        # loose gradient tol: grad_ok must fire while F still moves (an
        # f32 value-stall would otherwise report FUNCTION_VALUES_CONVERGED)
        res = lbfgs_minimize(quadratic(A, b), jnp.zeros(d, jnp.float32),
                             OptimizerConfig(max_iterations=100, tolerance=1e-3))
        assert int(res.reason) == ConvergenceReason.GRADIENT_CONVERGED

    def test_lbfgs_max_iterations(self, rng):
        d = 12
        A = jnp.asarray(make_spd(rng, d, cond=1e4), jnp.float32)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        res = lbfgs_minimize(quadratic(A, b), jnp.zeros(d, jnp.float32),
                             OptimizerConfig(max_iterations=2, tolerance=1e-12))
        assert int(res.reason) == ConvergenceReason.MAX_ITERATIONS
        assert int(res.iterations) == 2

    def test_lbfgs_line_search_failure(self):
        res = lbfgs_minimize(self._nan_off_origin(), jnp.zeros(4, jnp.float32),
                             OptimizerConfig(max_iterations=20, tolerance=1e-9))
        assert int(res.reason) == ConvergenceReason.OBJECTIVE_NOT_IMPROVING
        # the carried state stayed at the last good iterate
        assert np.all(np.asarray(res.coefficients) == 0.0)

    # ---- OWL-QN branch (l1 > 0) ----------------------------------------
    def test_owlqn_gradient_converged(self, rng):
        d = 8
        b = jnp.asarray(rng.normal(size=d) * 2.0, jnp.float32)
        vg = lambda w: (0.5 * jnp.sum((w - b) ** 2), w - b)
        res = lbfgs_minimize(vg, jnp.zeros(d, jnp.float32),
                             OptimizerConfig(max_iterations=100, tolerance=1e-6),
                             l1_weight=0.5)
        assert int(res.reason) == ConvergenceReason.GRADIENT_CONVERGED

    def test_owlqn_max_iterations(self, rng):
        d = 12
        A = jnp.asarray(make_spd(rng, d, cond=1e4), jnp.float32)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        res = lbfgs_minimize(quadratic(A, b), jnp.zeros(d, jnp.float32),
                             OptimizerConfig(max_iterations=2, tolerance=1e-12),
                             l1_weight=0.3)
        assert int(res.reason) == ConvergenceReason.MAX_ITERATIONS

    def test_owlqn_line_search_failure(self):
        res = lbfgs_minimize(self._nan_off_origin(), jnp.zeros(4, jnp.float32),
                             OptimizerConfig(max_iterations=20, tolerance=1e-9),
                             l1_weight=0.5)
        assert int(res.reason) == ConvergenceReason.OBJECTIVE_NOT_IMPROVING

    # ---- TRON -----------------------------------------------------------
    def test_tron_gradient_converged(self, rng):
        d = 6
        A = jnp.asarray(make_spd(rng, d, cond=5.0), jnp.float32)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        res = tron_minimize(quadratic(A, b), lambda w, v: A @ v,
                            jnp.zeros(d, jnp.float32),
                            OptimizerConfig(max_iterations=50, tolerance=1e-3))
        assert int(res.reason) == ConvergenceReason.GRADIENT_CONVERGED

    def test_tron_max_iterations(self, rng):
        d = 12
        A = jnp.asarray(make_spd(rng, d, cond=1e6), jnp.float32)
        b = jnp.asarray(rng.normal(size=d), jnp.float32)
        res = tron_minimize(quadratic(A, b), lambda w, v: A @ v,
                            jnp.zeros(d, jnp.float32),
                            OptimizerConfig(max_iterations=2, tolerance=1e-14,
                                            max_cg_iterations=1))
        assert int(res.reason) == ConvergenceReason.MAX_ITERATIONS
        assert int(res.iterations) == 2

    def test_tron_improvement_failures(self):
        """Every trial rejected (NaN off origin) -> the improvement-failure
        budget trips, the TRON line-search-failure analogue."""
        res = tron_minimize(self._nan_off_origin(), lambda w, v: v,
                            jnp.zeros(4, jnp.float32),
                            OptimizerConfig(max_iterations=20, tolerance=1e-9,
                                            max_improvement_failures=5))
        assert int(res.reason) == ConvergenceReason.OBJECTIVE_NOT_IMPROVING
        assert int(res.iterations) == 5  # one iteration per rejected trial
        assert np.all(np.asarray(res.coefficients) == 0.0)


class TestVmappedLambdaGrid:
    """train_glm_grid_vmapped: all lambdas as lanes of ONE batched kernel —
    must reach the same per-lambda optima as the sequential warm-started
    grid (ModelTraining.scala semantics), since both converge."""

    def test_matches_sequential_grid(self, rng):
        import numpy as np

        from photon_ml_tpu.ops.features import DenseFeatures
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.ops.objective import GLMBatch
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.training import train_glm_grid, train_glm_grid_vmapped
        from photon_ml_tpu.types import OptimizerType, TaskType

        n, d = 300, 7
        x = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        y = (1.0 / (1.0 + np.exp(-x @ w_true)) > rng.random(n)).astype(np.float32)
        batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
        norm = NormalizationContext.identity()
        problem = GLMOptimizationProblem(
            TaskType.LOGISTIC_REGRESSION,
            OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=80, tolerance=1e-10),
            RegularizationContext.l2(1.0),
        )
        lams = [0.1, 1.0, 10.0]
        seq = train_glm_grid(problem, batch, norm, lams)
        par = train_glm_grid_vmapped(problem, batch, norm, lams)
        assert par.weights == seq.weights == [10.0, 1.0, 0.1]
        for ms, mp in zip(seq.models, par.models):
            # cold vs. warm-started trajectories in f32: same optimum,
            # slightly different final rounding
            np.testing.assert_allclose(
                np.asarray(mp.coefficients.means),
                np.asarray(ms.coefficients.means),
                rtol=2e-3,
                atol=2e-4,
            )
        # every lane produced a real convergence record
        for res in par.results:
            assert int(res.iterations) > 0

    def test_vmapped_grid_with_tron(self, rng):
        import numpy as np

        from photon_ml_tpu.ops.features import DenseFeatures
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.ops.objective import GLMBatch
        from photon_ml_tpu.ops.regularization import RegularizationContext
        from photon_ml_tpu.optim.common import OptimizerConfig
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.training import train_glm_grid_vmapped
        from photon_ml_tpu.types import OptimizerType, TaskType

        n, d = 200, 5
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.asarray(y))
        problem = GLMOptimizationProblem(
            TaskType.LINEAR_REGRESSION,
            OptimizerType.TRON,
            OptimizerConfig(max_iterations=15, tolerance=1e-8),
            RegularizationContext.l2(1.0),
        )
        par = train_glm_grid_vmapped(
            problem, batch, NormalizationContext.identity(), [0.5, 5.0]
        )
        # heavier lambda shrinks its lane's solution
        n_small = float(jnp.linalg.norm(par.models[1].coefficients.means))
        n_big = float(jnp.linalg.norm(par.models[0].coefficients.means))
        assert n_big < n_small
