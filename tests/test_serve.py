"""Online scoring service tests (photon_ml_tpu/serve).

Covers the serving acceptance claims end-to-end on CPU:

  * ModelStore export/open: mmap'd slabs, entity->row probes, feature maps
    shared with the batch driver via --offheap-indexmap-dir.
  * MicroBatcher: coalescing, ladder padding, response slicing, error fans.
  * BITWISE parity: concurrently-served scores equal the batch
    game_scoring_driver's device output for the same inputs (offset term
    included), which itself equals the --host-scoring oracle.
  * Warm start: a second server process over a filled persistent XLA cache
    reports zero new compiles (CompileStats-asserted).
  * Live model swap: by-reference roll with zero new compiles, zero
    dropped requests, new coefficients served after.
  * JSON-lines loop: scoring, stats, swap, shutdown, malformed input.
"""

import concurrent.futures
import io
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from game_test_utils import (
    game_avro_records,
    make_glmix_data,
    save_synthetic_game_model,
    serve_requests_from_records,
    write_game_avro,
)

from photon_ml_tpu.compile import ShapeBucketer, compile_stats
from photon_ml_tpu.serve import (
    MicroBatcher,
    ModelStore,
    ModelSwapper,
    RowBatch,
    ScoringServer,
    ServeStats,
    build_model_store,
    is_model_store,
)

pytestmark = pytest.mark.serve

SECTIONS = {"global": ["fixedFeatures"], "per_user": ["userFeatures"]}
SECTIONS_FLAG = "global:fixedFeatures|per_user:userFeatures"


@pytest.fixture(scope="module")
def serving_world(tmp_path_factory):
    """One synthetic model + avro scoring inputs (with offsets) + built
    serve store, shared by the module."""
    base = tmp_path_factory.mktemp("serve")
    rng = np.random.default_rng(42)
    data, truth = make_glmix_data(
        rng, num_users=10, rows_per_user_range=(6, 12), d_fixed=5, d_random=3
    )
    offsets = rng.normal(size=data.num_rows).astype(np.float32)
    model_dir = str(base / "model")
    w_fixed, entity_means, fmap, umap = save_synthetic_game_model(
        model_dir, rng, d_fixed=5, d_random=3, num_users=10
    )
    in_dir = base / "in"
    in_dir.mkdir()
    write_game_avro(
        str(in_dir / "part-0.avro"), data, range(data.num_rows), truth, offsets
    )
    store_dir = str(base / "store")
    build_model_store(model_dir, store_dir, bucketer=ShapeBucketer())
    records = list(game_avro_records(data, range(data.num_rows), truth, offsets))
    return {
        "base": base,
        "model_dir": model_dir,
        "in_dir": str(in_dir),
        "store_dir": store_dir,
        "records": records,
        "requests": serve_requests_from_records(records),
        "w_fixed": w_fixed,
        "entity_means": entity_means,
        "data": data,
    }


def _run_scoring_driver(world, out_dir, host=False):
    from photon_ml_tpu.cli import game_scoring_driver

    args = [
        "--input-dirs", world["in_dir"],
        "--game-model-input-dir", world["model_dir"],
        "--output-dir", str(out_dir),
        "--offheap-indexmap-dir", os.path.join(world["store_dir"], "features"),
        "--feature-shard-id-to-feature-section-keys-map", SECTIONS_FLAG,
        "--evaluator-type", "AUC,RMSE",
        "--delete-output-dir-if-exists", "true",
    ]
    if host:
        args += ["--host-scoring", "true"]
    return game_scoring_driver.main(args)


# ---------------------------------------------------------------------------
# ModelStore
# ---------------------------------------------------------------------------


class TestModelStore:
    def test_detect_and_meta(self, serving_world):
        assert is_model_store(serving_world["store_dir"])
        store = ModelStore(serving_world["store_dir"])
        assert [f.name for f in store.fixed] == ["fixed"]
        assert [r.name for r in store.random] == ["per-user"]
        assert store.meta["shards"]["global"]["dim"] == 6  # 5 features + intercept
        store.close()

    def test_fixed_coefficients_roundtrip(self, serving_world):
        store = ModelStore(serving_world["store_dir"])
        w = np.asarray(store.fixed[0].coefficients)
        # densified against the STORE's map: compare value multiset (the
        # store's feature order may differ from the training IndexMap's)
        assert sorted(np.round(w, 6)) == sorted(
            np.round(serving_world["w_fixed"], 6)
        )
        store.close()

    def test_entity_rows_and_slab(self, serving_world):
        store = ModelStore(serving_world["store_dir"])
        re = store.random[0]
        assert re.entities == 10
        # ladder-padded slab rows (10 -> 16 on the default 8:2 ladder)
        assert re.slab.shape[0] == 16
        umap = store.feature_maps["per_user"]
        for raw, vec in serving_world["entity_means"].items():
            row = store.entity_row("per-user", raw)
            assert 0 <= row < 10
            # value multiset parity per entity row (store feature order)
            assert sorted(np.round(np.asarray(re.slab[row]), 6)) == sorted(
                np.round(vec, 6)
            )
        assert store.entity_row("per-user", "never-seen") == -1
        assert store.entity_row("per-user", None) == -1
        # padded rows are all-zero
        assert not np.asarray(re.slab[10:]).any()
        assert len(umap) == 4
        store.close()

    def test_checkpoint_ref_roundtrip(self, serving_world):
        from photon_ml_tpu.checkpoint import CheckpointRefError, rebuild_from_ref

        store = ModelStore(serving_world["store_dir"])
        ref = store.__checkpoint_ref__()
        rebuilt = rebuild_from_ref(store, ref)
        assert rebuilt.store_dir == store.store_dir
        rebuilt.close()
        with pytest.raises(CheckpointRefError):
            rebuild_from_ref(store, {"kind": "game-serve-store",
                                     "store_dir": "/nonexistent"})
        with pytest.raises(CheckpointRefError):
            rebuild_from_ref(store, {"kind": "something-else"})
        store.close()

    def test_unknown_coordinate_raises(self, serving_world):
        store = ModelStore(serving_world["store_dir"])
        with pytest.raises(KeyError):
            store.entity_row("no-such-coordinate", "u0")
        store.close()


# ---------------------------------------------------------------------------
# MicroBatcher
# ---------------------------------------------------------------------------


def _one_row_batch(value: float, k: int = 2) -> RowBatch:
    return RowBatch(
        offset=np.asarray([value], np.float32),
        shard_idx={"s": np.zeros((1, k), np.int32)},
        shard_val={"s": np.zeros((1, k), np.float32)},
        ent_row={"c": np.asarray([-1], np.int32)},
    )


class TestMicroBatcher:
    def test_coalesces_and_slices(self):
        seen = []

        def score(batch):
            seen.append(batch.num_rows)
            return batch.offset * 2.0

        b = MicroBatcher(
            score, max_batch_rows=64, max_wait_ms=50.0,
            bucketer=ShapeBucketer(), stats=ServeStats(),
        ).start()
        futs = [b.submit(_one_row_batch(float(i))) for i in range(20)]
        got = np.concatenate([f.result() for f in futs])
        np.testing.assert_array_equal(got, np.arange(20, dtype=np.float32) * 2)
        b.close()
        # coalesced: far fewer device calls than requests, every batch
        # padded to a ladder rung
        assert len(seen) < 20
        assert all(n in (8, 16, 32, 64) for n in seen)
        snap = b.stats.snapshot()
        assert snap["requests"] == 20
        assert 0 < snap["batch_fill_ratio"] <= 1.0

    def test_wait_bound_flushes_single_request(self):
        b = MicroBatcher(
            lambda batch: batch.offset, max_batch_rows=1024, max_wait_ms=5.0,
            bucketer=None, stats=ServeStats(),
        ).start()
        # one lonely request must not wait for a full batch
        assert b.submit(_one_row_batch(3.0)).result(timeout=10) == [3.0]
        b.close()

    def test_batch_cap_flushes_without_wait(self):
        release = threading.Event()
        calls = []

        def score(batch):
            release.wait(10)
            calls.append(batch.num_rows)
            return batch.offset

        b = MicroBatcher(
            score, max_batch_rows=4, max_wait_ms=10_000.0,
            bucketer=None, stats=ServeStats(),
        ).start()
        futs = [b.submit(_one_row_batch(float(i))) for i in range(8)]
        release.set()
        for f in futs:
            f.result(timeout=10)
        b.close()
        # a saturated queue never waits the window out: row cap flushes
        assert max(calls) <= 4 and len(calls) >= 2

    @pytest.mark.slow  # ~10s randomized sweep; the cap contract stays tier-1 via test_batch_cap_flushes_without_wait / test_coalesces_and_slices
    def test_multi_row_requests_never_overshoot_cap(self):
        """A coalesced batch must stay <= max_batch_rows even when multi-
        row requests arrive (overshoot would pad to an unwarmed ladder
        rung — a request-path compile); the overflow request is carried to
        the next batch instead."""
        release = threading.Event()
        calls = []

        def score(batch):
            release.wait(30)
            calls.append(batch.num_rows)
            return batch.offset

        b = MicroBatcher(
            score, max_batch_rows=8, max_wait_ms=10_000.0,
            bucketer=None, stats=ServeStats(),
        ).start()
        sizes = [6, 5, 4, 8, 3]  # 6+5 would overshoot; so would 4+8
        futs = [
            b.submit(
                RowBatch(
                    offset=np.arange(n, dtype=np.float32),
                    shard_idx={"g": np.zeros((n, 1), np.int32)},
                    shard_val={"g": np.zeros((n, 1), np.float32)},
                    ent_row={},
                )
            )
            for n in sizes
        ]
        release.set()
        for f, n in zip(futs, sizes):
            np.testing.assert_array_equal(
                f.result(timeout=30), np.arange(n, dtype=np.float32)
            )
        b.close()
        assert max(calls) <= 8

    def test_error_fans_to_all_members(self):
        def score(batch):
            raise RuntimeError("device fell over")

        b = MicroBatcher(
            score, max_batch_rows=8, max_wait_ms=20.0,
            bucketer=None, stats=ServeStats(),
        ).start()
        futs = [b.submit(_one_row_batch(1.0)) for _ in range(3)]
        for f in futs:
            with pytest.raises(RuntimeError, match="device fell over"):
                f.result(timeout=10)
        assert b.stats.snapshot()["errors"] >= 1
        b.close()

    def test_drain_fence(self):
        b = MicroBatcher(
            lambda batch: batch.offset, max_batch_rows=8, max_wait_ms=1.0,
            bucketer=None, stats=ServeStats(),
        ).start()
        futs = [b.submit(_one_row_batch(float(i))) for i in range(10)]
        assert b.drain(timeout=10)
        assert all(f.done() for f in futs)
        assert b.outstanding() == 0
        b.close()

    def test_score_fn_pinning_groups_generations(self):
        """Requests pinned to different scoring closures never share a
        device call (the swap-correctness invariant)."""
        calls = []

        def fn_a(batch):
            calls.append(("a", batch.num_rows))
            return batch.offset

        def fn_b(batch):
            calls.append(("b", batch.num_rows))
            return batch.offset + 100.0

        b = MicroBatcher(
            fn_a, max_batch_rows=64, max_wait_ms=100.0,
            bucketer=None, stats=ServeStats(),
        ).start()
        futs = []
        for i in range(6):
            futs.append(b.submit(_one_row_batch(float(i)),
                                 score_fn=fn_a if i % 2 == 0 else fn_b))
        vals = np.concatenate([f.result(timeout=10) for f in futs])
        b.close()
        expect = np.asarray([0, 101, 2, 103, 4, 105], np.float32)
        np.testing.assert_array_equal(vals, expect)


# ---------------------------------------------------------------------------
# Serving parity + oracle (offset term + evaluators covered end-to-end)
# ---------------------------------------------------------------------------


class TestServingParity:
    def test_device_driver_matches_host_oracle_with_offsets(
        self, serving_world, tmp_path
    ):
        """The batch driver's device path vs the reference-style host
        oracle, on data WITH a nonzero offset term, metrics included."""
        dev = _run_scoring_driver(serving_world, tmp_path / "dev")
        host = _run_scoring_driver(serving_world, tmp_path / "host", host=True)
        np.testing.assert_allclose(dev.scores, host.scores, rtol=1e-5, atol=1e-6)
        # offsets actually mattered (scores shift by them)
        offs = np.asarray([r["offset"] for r in serving_world["records"]])
        assert np.abs(offs).max() > 0.1
        assert set(dev.metrics) == {"AUC", "RMSE"}
        for k in dev.metrics:
            assert dev.metrics[k] == pytest.approx(host.metrics[k], rel=1e-4)

    def test_served_scores_bitwise_equal_batch_driver(
        self, serving_world, tmp_path
    ):
        """THE serving acceptance bit: concurrent single-row requests
        through the micro-batched server == the batch driver's device
        scores, bitwise."""
        drv = _run_scoring_driver(serving_world, tmp_path / "drv")
        server = ScoringServer(
            ModelStore(serving_world["store_dir"]), shard_sections=SECTIONS,
            max_batch_rows=16, max_wait_ms=5.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=8)
        wm = compile_stats.watermark()
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futs = list(
                pool.map(lambda q: server.submit_rows([q]),
                         serving_world["requests"])
            )
        served = np.concatenate([f.result(timeout=60) for f in futs])
        assert np.array_equal(served, drv.scores)
        # steady-state requests hit warmed executables only
        assert wm.new_traces() == 0
        assert server.new_request_compiles() == 0
        snap = server.stats.snapshot()
        assert snap["requests"] == len(serving_world["requests"])
        assert snap["batches"] < snap["requests"]  # coalescing happened
        server.close()

    def test_multi_row_requests_and_cold_entities(self, serving_world, tmp_path):
        drv = _run_scoring_driver(serving_world, tmp_path / "drv2")
        server = ScoringServer(
            ModelStore(serving_world["store_dir"]), shard_sections=SECTIONS,
            max_batch_rows=32, max_wait_ms=1.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=8)
        reqs = serving_world["requests"]
        # one request carrying ALL rows (wider than max_batch_rows: split
        # into cap-sized sub-batches, so no batch pads past the warmed
        # ladder top — zero request-path compiles); plus a cold-entity
        # request
        served = server.score_rows(reqs)
        assert np.array_equal(served, drv.scores)
        assert len(reqs) > server.batcher.max_batch_rows
        assert server.new_request_compiles() == 0
        cold = dict(reqs[0], ids={"userId": "cold-user-999"})
        base = dict(reqs[0], ids={})
        np.testing.assert_array_equal(
            server.score_rows([cold]), server.score_rows([base])
        )
        server.close()

    def test_empty_rows(self, serving_world):
        server = ScoringServer(
            ModelStore(serving_world["store_dir"]), shard_sections=SECTIONS,
            max_batch_rows=8, max_wait_ms=1.0, stats=ServeStats(),
        )
        assert server.score_rows([]).shape == (0,)
        server.close()


# ---------------------------------------------------------------------------
# Warm start (persistent cache) — fresh-process arms
# ---------------------------------------------------------------------------


_WARM_CHILD = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from photon_ml_tpu import compat
from photon_ml_tpu.compile import compile_stats
from photon_ml_tpu.serve import ModelStore, ScoringServer, ServeStats
assert compat.enable_persistent_cache({cache!r})
compile_stats.install_xla_listeners()
server = ScoringServer(ModelStore({store!r}),
                       shard_sections={{"global": ["fixedFeatures"],
                                        "per_user": ["userFeatures"]}},
                       max_batch_rows=8, max_wait_ms=1.0, stats=ServeStats())
report = server.warmup(warm_nnz=4)
scores = server.score_rows([{{"features": {{"fixedFeatures":
    [{{"name": "f0", "term": "", "value": 1.0}}]}},
    "ids": {{"userId": "u0"}}, "offset": 0.5}}])
server.close()
print(json.dumps({{"misses": compile_stats.xla_cache_misses,
                   "hits": compile_stats.xla_cache_hits,
                   "warm": report, "score": float(scores[0]),
                   "fully_warm": compile_stats.xla_cache_misses == 0}}))
"""


@pytest.mark.slow
class TestWarmStart:
    def test_second_process_is_fully_warm(self, serving_world, tmp_path):
        """Cold process fills the persistent cache; an identical warm
        process reports ZERO new XLA compiles — the zero-per-request-
        compile startup claim, CompileStats-asserted across processes."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cache = str(tmp_path / "xla-cache")
        child = _WARM_CHILD.format(
            repo=repo, cache=cache, store=serving_world["store_dir"]
        )
        results = []
        for _ in range(2):
            out = subprocess.run(
                [sys.executable, "-c", child], capture_output=True,
                text=True, timeout=600, cwd=repo,
            )
            assert out.returncode == 0, out.stderr[-2000:]
            results.append(json.loads(out.stdout.strip().splitlines()[-1]))
        cold, warm = results
        assert cold["misses"] > 0, "cold start should have compiled"
        assert not cold["fully_warm"]
        assert warm["fully_warm"], warm
        assert warm["misses"] == 0
        assert warm["hits"] > 0
        assert warm["score"] == cold["score"]


# ---------------------------------------------------------------------------
# Live model swap
# ---------------------------------------------------------------------------


class TestModelSwap:
    @pytest.fixture()
    def second_store(self, serving_world):
        """A perturbed model with the SAME entity count (same ladder rung)."""
        base = serving_world["base"]
        model2 = str(base / "model2")
        if not os.path.isdir(model2):
            save_synthetic_game_model(
                model2, np.random.default_rng(43), d_fixed=5, d_random=3,
                num_users=10,
            )
            build_model_store(model2, str(base / "store2"),
                              bucketer=ShapeBucketer())
        return str(base / "store2")

    def test_swap_zero_compiles_zero_drops(self, serving_world, second_store):
        server = ScoringServer(
            ModelStore(serving_world["store_dir"]), shard_sections=SECTIONS,
            max_batch_rows=16, max_wait_ms=2.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=8)
        before = server.score_rows(serving_world["requests"][:4])
        swapper = ModelSwapper(server)
        wm = compile_stats.watermark()
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futs = [
                pool.submit(server.score_rows, [q])
                for q in serving_world["requests"]
            ]
            report = swapper.swap(second_store)
            results = [f.result(timeout=60) for f in futs]
        assert report["new_compiles"] == 0
        assert report["shape_compatible"]
        assert report["dropped_requests"] == 0
        assert wm.new_traces() == 0
        assert len(results) == len(serving_world["requests"])
        assert all(len(r) == 1 for r in results)
        # the new model actually serves now
        after = server.score_rows(serving_world["requests"][:4])
        assert not np.allclose(before, after)
        assert server.model.generation == 2
        assert server.stats.snapshot()["swaps"] == 1
        server.close()

    def test_swap_refuses_missing_store(self, serving_world):
        from photon_ml_tpu.checkpoint import CheckpointRefError

        server = ScoringServer(
            ModelStore(serving_world["store_dir"]), shard_sections=SECTIONS,
            max_batch_rows=8, max_wait_ms=1.0, stats=ServeStats(),
        )
        swapper = ModelSwapper(server)
        with pytest.raises(CheckpointRefError):
            swapper.swap("/nonexistent/store")
        # old model keeps serving after the refused swap
        assert server.model.generation == 1
        assert len(server.score_rows(serving_world["requests"][:2])) == 2
        server.close()

    def test_swap_detects_shape_change(self, serving_world, tmp_path):
        """An entity count crossing a ladder rung is reported (and refused
        under require_compatible)."""
        from photon_ml_tpu.checkpoint import CheckpointRefError

        model3 = str(tmp_path / "model3")
        save_synthetic_game_model(
            model3, np.random.default_rng(44), d_fixed=5, d_random=3,
            num_users=20,  # 20 -> rung 32 vs 10 -> rung 16
        )
        store3 = str(tmp_path / "store3")
        build_model_store(model3, store3, bucketer=ShapeBucketer())
        server = ScoringServer(
            ModelStore(serving_world["store_dir"]), shard_sections=SECTIONS,
            max_batch_rows=8, max_wait_ms=1.0, stats=ServeStats(),
        )
        swapper = ModelSwapper(server)
        with pytest.raises(CheckpointRefError, match="slab"):
            swapper.swap(store3, require_compatible=True)
        assert server.model.generation == 1
        server.close()


# ---------------------------------------------------------------------------
# JSON-lines request loop
# ---------------------------------------------------------------------------


class TestJsonLinesLoop:
    def _serve(self, serving_world, lines, swapper_for=None):
        from photon_ml_tpu.serve import serve_json_lines

        server = ScoringServer(
            ModelStore(serving_world["store_dir"]), shard_sections=SECTIONS,
            max_batch_rows=8, max_wait_ms=1.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=8)
        swapper = ModelSwapper(server) if swapper_for else None
        out = io.StringIO()
        handled = serve_json_lines(
            server, io.StringIO("\n".join(lines) + "\n"), out, swapper=swapper
        )
        server.close()
        return handled, [json.loads(l) for l in out.getvalue().splitlines()]

    def test_score_stats_shutdown(self, serving_world, tmp_path):
        drv = _run_scoring_driver(serving_world, tmp_path / "loop-drv")
        reqs = serving_world["requests"]
        lines = [
            json.dumps({"id": f"r{i}", "rows": [q]})
            for i, q in enumerate(reqs)
        ]
        lines += [json.dumps({"cmd": "stats", "id": "st"}),
                  json.dumps({"cmd": "shutdown"}),
                  json.dumps({"id": "after", "rows": [reqs[0]]})]
        handled, responses = self._serve(serving_world, lines)
        assert handled == len(reqs)  # the post-shutdown line never ran
        by_id = {r.get("id"): r for r in responses}
        served = np.asarray(
            [by_id[f"r{i}"]["scores"][0] for i in range(len(reqs))],
            np.float32,
        )
        # f64 JSON round-trip preserves every f32 exactly
        assert np.array_equal(served, drv.scores)
        assert "stats" in by_id["st"]
        assert "after" not in by_id

    def test_bad_lines_fail_softly(self, serving_world):
        lines = [
            "this is not json",
            json.dumps({"rows": []}),
            json.dumps({"rows": "nope"}),
            json.dumps({"cmd": "swap", "store_dir": "/nonexistent"}),
            json.dumps({"id": "ok", "rows": [serving_world["requests"][0]]}),
            json.dumps({"cmd": "shutdown"}),
        ]
        handled, responses = self._serve(serving_world, lines)
        assert handled == 1
        errs = [r for r in responses if "error" in r]
        assert len(errs) == 4
        ok = [r for r in responses if r.get("id") == "ok"]
        assert len(ok) == 1 and len(ok[0]["scores"]) == 1

    def test_swap_command(self, serving_world):
        base = serving_world["base"]
        model2 = str(base / "model2-loop")
        save_synthetic_game_model(
            model2, np.random.default_rng(45), d_fixed=5, d_random=3,
            num_users=10,
        )
        store2 = str(base / "store2-loop")
        build_model_store(model2, store2, bucketer=ShapeBucketer())
        q = serving_world["requests"][0]
        lines = [
            json.dumps({"id": "pre", "rows": [q]}),
            json.dumps({"cmd": "swap", "store_dir": store2, "id": "sw"}),
            json.dumps({"id": "post", "rows": [q]}),
            json.dumps({"cmd": "shutdown"}),
        ]
        handled, responses = self._serve(serving_world, lines, swapper_for=True)
        by_id = {r.get("id"): r for r in responses}
        assert by_id["sw"]["swap"]["new_compiles"] == 0
        assert by_id["pre"]["scores"] != by_id["post"]["scores"]


# ---------------------------------------------------------------------------
# ServeStats
# ---------------------------------------------------------------------------


class TestServeStats:
    def test_percentiles_and_summary(self):
        s = ServeStats()
        for ms in range(1, 101):
            s.record_request(ms / 1e3)
        s.record_batch(rows_real=75, rows_padded=100, num_requests=100)
        snap = s.snapshot()
        assert snap["requests"] == 100
        assert 45 <= snap["p50_ms"] <= 55
        assert 95 <= snap["p99_ms"] <= 100
        assert snap["batch_fill_ratio"] == 0.75
        text = s.summary()
        assert "p50" in text and "p99" in text and "fill" in text
        s.reset()
        assert s.snapshot()["requests"] == 0


# ---------------------------------------------------------------------------
# Serve driver CLI
# ---------------------------------------------------------------------------


class TestServeDriver:
    def test_build_store_only_then_serve(self, serving_world, tmp_path):
        from photon_ml_tpu.cli import serve_driver

        store_dir = str(tmp_path / "driver-store")
        d = serve_driver.main([
            "--model-store-dir", store_dir,
            "--game-model-input-dir", serving_world["model_dir"],
            "--build-store-only", "true",
        ])
        assert is_model_store(store_dir)
        assert d.server is None

        reqs = serving_world["requests"]
        in_text = "\n".join(
            [json.dumps({"id": str(i), "rows": [q]})
             for i, q in enumerate(reqs[:5])]
            + [json.dumps({"cmd": "shutdown"})]
        ) + "\n"
        out = io.StringIO()
        driver = serve_driver.GameServeDriver(
            serve_driver.parse_serve_params([
                "--model-store-dir", store_dir,
                "--feature-shard-id-to-feature-section-keys-map",
                SECTIONS_FLAG,
                "--max-batch-rows", "8",
                "--warm-nnz", "4",
            ])
        )
        driver.run(in_stream=io.StringIO(in_text), out_stream=out)
        assert driver.handled == 5
        responses = [json.loads(l) for l in out.getvalue().splitlines()]
        assert sum(1 for r in responses if "scores" in r) == 5

    def test_parse_validation(self):
        from photon_ml_tpu.cli.game_params import GameServeParams

        with pytest.raises(ValueError, match="model-store-dir"):
            GameServeParams().validate()
        with pytest.raises(ValueError, match="assert-warm"):
            GameServeParams(model_store_dir="x", assert_warm=True).validate()
        with pytest.raises(ValueError, match="max-batch-rows"):
            GameServeParams(model_store_dir="x", max_batch_rows=0).validate()
        with pytest.raises(ValueError, match="shape-canonicalization"):
            GameServeParams(
                model_store_dir="x", shape_canonicalization="nope"
            ).validate()
        # --assert-warm with warmup disabled would hold vacuously
        with pytest.raises(ValueError, match="warmup"):
            GameServeParams(
                model_store_dir="x", assert_warm=True,
                persistent_cache_dir="c", warmup=False,
            ).validate()
        # defaults are valid
        GameServeParams(model_store_dir="x").validate()


# ---------------------------------------------------------------------------
# Quantized serving stores (store_dtype bf16/int8; serve/quantize.py)
# ---------------------------------------------------------------------------


class TestQuantizedStore:
    """The accuracy/speed dial: bf16/int8 slabs under a PINNED error
    budget, with the f32 default untouched (bitwise stays bitwise)."""

    @pytest.fixture(scope="class")
    def q_world(self, serving_world, tmp_path_factory):
        base = tmp_path_factory.mktemp("qstores")
        stores = {"f32": serving_world["store_dir"]}
        metas = {"f32": ModelStore(serving_world["store_dir"]).meta}
        for dt in ("bf16", "int8"):
            sd = str(base / f"store-{dt}")
            metas[dt] = build_model_store(
                serving_world["model_dir"], sd,
                bucketer=ShapeBucketer(), store_dtype=dt,
            )
            stores[dt] = sd
        return {"base": base, "stores": stores, "metas": metas}

    def _server(self, store_dir):
        server = ScoringServer(
            ModelStore(store_dir), shard_sections=SECTIONS,
            max_batch_rows=16, max_wait_ms=1.0, stats=ServeStats(),
        )
        server.warmup(warm_nnz=8)
        return server

    def test_export_bytes_and_pinned_budget(self, q_world):
        from photon_ml_tpu.serve import quantize

        slab_path = os.path.join(
            q_world["stores"]["f32"], "random", "per-user", "slab.npy"
        )
        f32_bytes = os.path.getsize(slab_path)
        true_slab = np.asarray(
            ModelStore(q_world["stores"]["f32"]).random[0].slab
        )
        for dt in ("bf16", "int8"):
            store = ModelStore(q_world["stores"][dt])
            assert store.store_dtype == dt
            re = store.random[0]
            q = re.quantization
            # the pinned-budget contract: realized error recorded at
            # export, within the analytic budget
            assert 0 < q["realized_max_abs_coeff_err"] <= q["coeff_err_budget"]
            # realized error against the TRUE slab honors the per-row bound
            row_budget = quantize.row_coeff_budget(
                dt, np.max(np.abs(true_slab), axis=1)
            )
            err = np.abs(re.dequantized().astype(np.float64) - true_slab)
            assert np.all(err <= row_budget[:, None])
            # bytes: the dial actually pays (raw slab payloads; npy
            # headers wash out at real sizes but count against us here)
            got = os.path.getsize(
                os.path.join(
                    q_world["stores"][dt], "random", "per-user", "slab.npy"
                )
            )
            if dt == "bf16":
                assert got <= 0.55 * f32_bytes + 128
            else:
                scales = os.path.getsize(
                    os.path.join(
                        q_world["stores"][dt], "random", "per-user",
                        "scales.npy",
                    )
                )
                assert got + scales <= 0.55 * f32_bytes + 256
            store.close()

    def test_version1_meta_opens_as_f32_and_future_version_refused(
        self, q_world, tmp_path
    ):
        import shutil

        v1 = str(tmp_path / "v1-store")
        shutil.copytree(q_world["stores"]["f32"], v1)
        meta_path = os.path.join(v1, "meta.json")
        with open(meta_path) as f:
            meta = json.load(f)
        # a PR-6-era export: version 1, no store_dtype / quantization keys
        meta["version"] = 1
        meta.pop("store_dtype", None)
        for e in meta["random"]:
            e.pop("quantization", None)
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        store = ModelStore(v1)
        assert store.store_dtype == "f32"
        assert store.random[0].scales is None
        store.close()
        meta["version"] = 99
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        with pytest.raises(IOError, match="version-99"):
            ModelStore(v1)

    def test_quantized_scores_within_budget_f32_bitwise(self, q_world, serving_world):
        from game_test_utils import assert_scores_match_store

        reqs = serving_world["requests"]
        f32_server = self._server(q_world["stores"]["f32"])
        oracle = f32_server.score_rows(reqs)
        f32_server.close()
        for dt in ("f32", "bf16", "int8"):
            server = self._server(q_world["stores"][dt])
            served = server.score_rows(reqs)
            # f32 goes through the helper's BITWISE branch; bf16/int8
            # through the pinned per-score budget from store meta
            assert_scores_match_store(
                served, oracle, server.store.meta, reqs, SECTIONS,
                err_msg=f"store_dtype={dt}",
            )
            if dt != "f32":
                assert not np.array_equal(served, oracle), (
                    "quantized scores bitwise-equal to f32 — the dtype "
                    "dial is not actually engaged"
                )
            server.close()

    def test_same_dtype_swap_compile_free_dtype_change_flagged(
        self, q_world, serving_world, tmp_path
    ):
        # a second int8 export of a perturbed model (same shapes)
        model2 = str(tmp_path / "model2")
        save_synthetic_game_model(
            model2, np.random.default_rng(77), d_fixed=5, d_random=3,
            num_users=10,
        )
        store2 = str(tmp_path / "store2-int8")
        build_model_store(
            model2, store2, bucketer=ShapeBucketer(), store_dtype="int8"
        )
        server = self._server(q_world["stores"]["int8"])
        swapper = ModelSwapper(server)
        report = swapper.swap(store2)
        assert report["new_compiles"] == 0
        assert report["shape_compatible"]
        assert report["dropped_requests"] == 0
        # dtype change is a loud validation problem (and refused under
        # require_compatible) — never a silent recompile
        problems = swapper.validate_compatible(
            ModelStore(q_world["stores"]["bf16"])
        )
        assert any("dtype" in p for p in problems)
        from photon_ml_tpu.checkpoint import CheckpointRefError

        with pytest.raises(CheckpointRefError, match="dtype"):
            swapper.swap(q_world["stores"]["bf16"], require_compatible=True)
        server.close()

    def test_corrupt_scale_sidecar_refuses_open(self, q_world, tmp_path):
        import shutil

        broken = str(tmp_path / "broken-int8")
        shutil.copytree(q_world["stores"]["int8"], broken)
        scales_path = os.path.join(broken, "random", "per-user", "scales.npy")
        n_rows = np.load(scales_path).shape[0]
        # non-finite scales: mmap-able but poisonous — must refuse, not serve
        np.save(scales_path, np.full(n_rows, np.nan, np.float32))
        with pytest.raises(IOError, match="corrupt"):
            ModelStore(broken)
        # unreadable garbage: ditto, with the actionable re-export message
        with open(scales_path, "wb") as f:
            f.write(b"not an npy file")
        with pytest.raises(IOError, match="missing or unreadable"):
            ModelStore(broken)
        os.unlink(scales_path)
        with pytest.raises(IOError, match="missing or unreadable"):
            ModelStore(broken)

    def test_over_budget_meta_refuses_open(self, q_world, tmp_path):
        import shutil

        tampered = str(tmp_path / "tampered-int8")
        shutil.copytree(q_world["stores"]["int8"], tampered)
        meta_path = os.path.join(tampered, "meta.json")
        with open(meta_path) as f:
            meta = json.load(f)
        q = meta["random"][0]["quantization"]
        q["realized_max_abs_coeff_err"] = q["coeff_err_budget"] * 2
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        with pytest.raises(IOError, match="budget"):
            ModelStore(tampered)

    def test_serve_dequant_fault_injection(self, q_world):
        from photon_ml_tpu.resilience import faults

        plan = faults.FaultPlan(
            [faults.FaultSpec(site="serve.dequant", at=1)]
        )
        with faults.fault_scope(plan):
            with pytest.raises(OSError, match="serve.dequant"):
                ModelStore(q_world["stores"]["int8"])
        assert plan.fire_count("serve.dequant") == 1
        # f32 stores never pass the dequant gate (no quantized slabs)
        plan2 = faults.FaultPlan(
            [faults.FaultSpec(site="serve.dequant", at=1)]
        )
        with faults.fault_scope(plan2):
            ModelStore(q_world["stores"]["f32"]).close()
        assert plan2.fire_count("serve.dequant") == 0

    def test_store_footprint_gauges(self, q_world):
        server = self._server(q_world["stores"]["int8"])
        snap = server.stats.snapshot()
        assert snap["store_dtype"] == "int8"
        assert snap["store_slab_bytes"] > 0
        assert snap["store_mapped_bytes"] > 0
        assert "int8" in server.stats.summary()
        server.close()

    def test_export_over_budget_slab_fails(self, tmp_path):
        """A quantization whose realized error exceeds the analytic
        budget must fail the EXPORT (never write a serving store)."""
        from photon_ml_tpu.serve import quantize

        slab = np.random.default_rng(3).normal(size=(8, 6)).astype(np.float32)
        stored, scales = quantize.quantize_slab(slab, "int8")
        with pytest.raises(IOError, match="budget"):
            # a tampered quantization (wrong scales) realizes over budget
            quantize.slab_error_report(slab, stored, scales * 2.0, "int8")

    def test_non_finite_slab_fails_export_and_open(self, q_world, tmp_path):
        """A NaN coefficient (the optim.step corruption fault mode) must
        FAIL the budget gate, not slide through it — every comparison
        against a NaN realized error is False, so the gate must be
        written as `not (realized <= budget)`."""
        import shutil

        from photon_ml_tpu.serve import quantize

        slab = np.random.default_rng(4).normal(size=(8, 6)).astype(np.float32)
        slab[3, 2] = np.nan
        for dt in ("bf16", "int8"):
            stored, scales = quantize.quantize_slab(slab, dt)
            with pytest.raises(IOError, match="budget"):
                quantize.slab_error_report(slab, stored, scales, dt)
        # a NaN smuggled into an already-written store's meta (e.g. by a
        # pre-fix exporter) is refused at open the same way
        tampered = str(tmp_path / "nan-meta-int8")
        shutil.copytree(q_world["stores"]["int8"], tampered)
        meta_path = os.path.join(tampered, "meta.json")
        with open(meta_path) as f:
            meta = json.load(f)
        meta["random"][0]["quantization"]["realized_max_abs_coeff_err"] = (
            float("nan")
        )
        with open(meta_path, "w") as f:
            json.dump(meta, f)
        with pytest.raises(IOError, match="budget"):
            ModelStore(tampered)
