"""Subprocess worker for the streaming-RE peak-RSS gate: train the same
random-effect dataset either in-memory or block-streamed under a memory
budget, and report ru_maxrss. Run: worker.py <streaming|inmemory> <outdir>."""

import os
import resource
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")  # never touch the TPU tunnel

import jax.numpy as jnp  # noqa: E402

from photon_ml_tpu.algorithm import (  # noqa: E402
    RandomEffectCoordinate,
    StreamingRandomEffectCoordinate,
    write_re_entity_blocks,
)
from photon_ml_tpu.data.game import (  # noqa: E402
    GameData,
    HostFeatures,
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.ops.regularization import RegularizationContext  # noqa: E402
from photon_ml_tpu.optim.common import OptimizerConfig  # noqa: E402
from photon_ml_tpu.types import OptimizerType, TaskType  # noqa: E402

mode, outdir = sys.argv[1], sys.argv[2]
E, LO, HI, D = 3000, 152, 160, 64
BUDGET = 16_000_000

rng = np.random.default_rng(5)
rows_per = rng.integers(LO, HI + 1, size=E)
n = int(rows_per.sum())
ids = np.repeat(np.arange(E, dtype=np.int32), rows_per)
ids = ids[rng.permutation(n)]
# dense features straight into CSR form (no (n, D) dense intermediate copy
# beyond the values themselves — the values ARE the dataset)
values = rng.normal(size=n * D).astype(np.float32)
feats = HostFeatures(
    np.arange(n + 1, dtype=np.int64) * D,
    np.tile(np.arange(D, dtype=np.int32), n),
    values,
    D,
)
y = (rng.random(n) < 0.5).astype(np.float32)
data = GameData(
    response=y,
    offset=np.zeros(n, np.float32),
    weight=np.ones(n, np.float32),
    ids={"userId": ids},
    id_vocabs={"userId": [f"u{i}" for i in range(E)]},
    shards={"per_user": feats},
)
slab_bytes = E * int(rows_per.max()) * D * 4  # the in-memory x-stack cost

cfg = OptimizerConfig(max_iterations=8, tolerance=1e-7)
reg = RegularizationContext.l2(0.3)
config = RandomEffectDataConfig("userId", "per_user")
resid = jnp.zeros((n,), jnp.float32)

if mode == "streaming":
    manifest = write_re_entity_blocks(
        data, config, outdir, memory_budget_bytes=BUDGET
    )
    assert manifest.max_block_bytes <= BUDGET, manifest.max_block_bytes
    coord = StreamingRandomEffectCoordinate(
        manifest, TaskType.LOGISTIC_REGRESSION,
        optimizer_config=cfg, regularization=reg,
    )
    w, _ = coord.update(resid, coord.initial_coefficients())
    total = float(jnp.sum(coord.score(w)))
else:
    ds = build_random_effect_dataset(data, config)
    coord = RandomEffectCoordinate(
        ds, TaskType.LOGISTIC_REGRESSION,
        optimizer_config=cfg, regularization=reg,
    )
    w, _ = coord.update(resid, coord.initial_coefficients())
    total = float(jnp.sum(coord.score(w)))

peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024  # kB on linux
print(f"checksum {total:.4f}", file=sys.stderr)
print(f"RSS mode={mode} peak_rss={peak} slab_bytes={slab_bytes} budget={BUDGET}")
