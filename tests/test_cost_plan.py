"""The cost-based plan optimizer (photon_ml_tpu.compile.cost + the
planner pass in ExecutionPlan.resolve): prior cost algebra in lane-
iteration units, --plan off pinned bitwise to the pre-planner behavior,
the torn-sidecar degrade-to-priors path (recorded as a decision, never an
exception), and the preemption-resume round trip whose final sidecar must
land byte-identical to an uninterrupted run's. The bench-side acceptance
gates (auto within bound of best hand-tuned arm on skewed AND uniform,
warm rerun revising a decision) live in bench.py's plan_auto section with
their lockstep tests in test_bench_sync.py; the fleet aggregation view is
covered in test_fleetctl.py (TestPlanStatus); the no-new-env-reads rule
in test_photon_lint.py."""

import json
import math
import os

import pytest

from photon_ml_tpu.compile.cost import (
    CHUNK_PAUSE_COST,
    COST_MODEL_FILENAME,
    DRIFT_THRESHOLD,
    EMA_ALPHA,
    PRIOR_EASY_ITERS,
    PRIOR_HARD_ITERS,
    TRACE_COST,
    CostModel,
    WorkloadProfile,
)
from photon_ml_tpu.compile.plan import ExecutionPlan, PlanError

pytestmark = pytest.mark.plan

SKEWED = WorkloadProfile(num_lanes=512, max_rows=3200, median_rows=32, dim=16)
UNIFORM = WorkloadProfile(num_lanes=512, max_rows=32, median_rows=32, dim=16)


@pytest.fixture(autouse=True)
def _clean_plan_env(monkeypatch):
    for var in ("PHOTON_PLAN", "PHOTON_SHAPE_LADDER", "PHOTON_SOLVE_CHUNK",
                "PHOTON_SPARSE_KERNEL", "PHOTON_PREFETCH_DEPTH"):
        monkeypatch.delenv(var, raising=False)


class TestCostUnits:
    """The analytic priors ARE the contract the planner reasons in; pin
    the algebra, not just the argmin."""

    def test_signatures_partition_workloads(self):
        assert SKEWED.signature() == "skewed"
        assert UNIFORM.signature() == "uniform"
        assert WorkloadProfile().signature() == "unknown"

    def test_schedule_priors_pay_skew_and_pause_tariff(self):
        m = CostModel()
        lanes = SKEWED.num_lanes
        assert m.prior("schedule", "one-shot", SKEWED) == (
            lanes * PRIOR_HARD_ITERS
        )
        hard_frac = 8.0 / lanes
        for c in (2, 8, 32):
            per_easy = math.ceil(PRIOR_EASY_ITERS / c) * c
            per_hard = math.ceil(PRIOR_HARD_ITERS / c) * c
            expect = lanes * (
                (1.0 - hard_frac) * per_easy + hard_frac * per_hard
            ) + CHUNK_PAUSE_COST * math.ceil(PRIOR_HARD_ITERS / c)
            assert m.prior("schedule", f"chunk:{c}", SKEWED) == expect

    def test_uniform_prior_prefers_one_shot(self):
        action, _, _ = CostModel().choose(
            "schedule",
            ("one-shot", "chunk:2", "chunk:4", "chunk:8", "chunk:16",
             "chunk:32"),
            UNIFORM,
        )
        assert action == "one-shot"  # no tail to chase: chunking only pays

    def test_unknown_action_never_wins(self):
        m = CostModel()
        assert m.prior(
            "schedule", "chunk:oops-not-a-number", SKEWED
        ) == float("inf")
        assert m.prior("nonsense-policy", "x", SKEWED) == float("inf")
        action, _, _ = m.choose("ladder", ("off", "on", "sideways"), SKEWED)
        assert action in ("off", "on")

    def test_observe_is_ema_and_predict_prefers_it(self):
        m = CostModel()
        prior = m.prior("schedule", "chunk:8", SKEWED)
        m.observe("schedule", "chunk:8", SKEWED, 1000.0)
        assert m.predict("schedule", "chunk:8", SKEWED) == 1000.0
        m.observe("schedule", "chunk:8", SKEWED, 2000.0)
        expect = EMA_ALPHA * 2000.0 + (1 - EMA_ALPHA) * 1000.0
        assert m.predict("schedule", "chunk:8", SKEWED) == expect
        # the other signature is untouched: shapes never contaminate
        assert m.predict("schedule", "chunk:8", UNIFORM) == m.prior(
            "schedule", "chunk:8", UNIFORM
        )
        assert prior != 1000.0  # the observation actually displaced it

    def test_drifted_flags_only_past_threshold(self):
        m = CostModel()
        m.observe("schedule", "chunk:8", SKEWED, 1000.0, predicted=1000.0)
        m.observe(
            "schedule", "chunk:8", SKEWED,
            1000.0 * (1 + DRIFT_THRESHOLD) + 1, predicted=1000.0,
        )
        assert len(m.drifted()) == 1

    def test_merge_is_count_weighted(self):
        a, b = CostModel(), CostModel()
        a.observe("ladder", "on", SKEWED, 100.0)
        a.observe("ladder", "on", SKEWED, 100.0)  # n=2, cost 100
        b.observe("ladder", "on", SKEWED, 400.0)  # n=1
        merged = a.merge(b)
        key = "ladder=on@skewed"
        assert merged.observations[key]["n"] == 3
        assert merged.observations[key]["cost"] == pytest.approx(200.0)


class TestPlanOffBitwise:
    """--plan off (the default) must be bitwise today's behavior: no
    planner decisions, no cost model, no sidecar writes, record_realized
    a no-op."""

    def test_default_resolution_untouched(self, tmp_path):
        p = ExecutionPlan.resolve()
        q = ExecutionPlan.resolve(
            plan="off", workload=SKEWED, cost_model_dir=str(tmp_path)
        )
        for field in ("bucketer", "schedule", "sharding", "sparse_kernel",
                      "prefetch_depth", "decisions", "sparse_candidates"):
            assert getattr(p, field) == getattr(q, field)
        assert q.plan_mode == "off" and q.cost_model is None
        q.record_realized("schedule", 123.0)
        assert q.save_cost_model(str(tmp_path)) is None
        assert not os.path.exists(tmp_path / COST_MODEL_FILENAME)
        assert "plan=auto" not in q.describe()

    def test_bad_plan_spec_refused(self):
        with pytest.raises(ValueError, match="PHOTON_PLAN"):
            ExecutionPlan.resolve(plan="definitely-not-a-mode")

    def test_explicit_knobs_always_win_under_auto(self):
        p = ExecutionPlan.resolve(
            plan="auto", workload=SKEWED, solve_compaction="4",
            shape_canonicalization="on", prefetch_depth=7,
        )
        assert p.schedule.chunk_size == 4
        assert p.prefetch_depth == 7
        pinned = [d for d in p.decisions
                  if d.policy == "schedule" and d.action == "pinned"]
        assert len(pinned) == 1  # audited, not overridden

    def test_auto_under_fused_cycle_plans_device_not_chunk(self):
        # the planner must not resolve INTO a combination the explicit
        # path would refuse: under fused_cycle the host chunk loop's
        # pauses cannot compose, so it never proposes a chunk — but the
        # fused DEVICE loop can, and on a skewed workload it wins
        p = ExecutionPlan.resolve(
            plan="auto", workload=SKEWED, fused_cycle=True,
        )
        assert not [d for d in p.decisions
                    if d.policy == "schedule"
                    and d.action.startswith("planned:chunk")]
        assert p.schedule is not None and p.schedule.loop == "device"
        assert p.cycle_fusion == "solve"
        planned = [d for d in p.decisions
                   if d.policy == "schedule"
                   and d.action.startswith("planned:device")]
        assert len(planned) == 1


class TestSidecarCorruption:
    """A torn/missing cost-model.json degrades to static priors LOUDLY —
    a recorded decision, never an exception, never a half-read model."""

    def test_missing_dir_resolves_from_priors(self):
        p = ExecutionPlan.resolve(plan="auto", workload=SKEWED)
        src = next(d for d in p.decisions if d.policy == "cost-model")
        assert src.action == "priors"
        assert p.cost_model.source == "static-priors"

    def test_torn_sidecar_degrades_with_recorded_decision(self, tmp_path):
        (tmp_path / COST_MODEL_FILENAME).write_text('{"format": 1, "obs')
        p = ExecutionPlan.resolve(
            plan="auto", workload=SKEWED, cost_model_dir=str(tmp_path)
        )
        src = next(d for d in p.decisions if d.policy == "cost-model")
        assert src.action == "degraded"
        assert "static priors" in src.reason
        assert p.cost_model.source == "static-priors"
        # and the planner still planned — degradation is not paralysis
        assert [d for d in p.decisions if d.policy == "schedule"]

    def test_wrong_format_and_wrong_types_also_degrade(self, tmp_path):
        for payload in ('{"format": 99}', '{"format": 1, "observations": 3}',
                        "[]"):
            (tmp_path / COST_MODEL_FILENAME).write_text(payload)
            assert CostModel.load(str(tmp_path)) is None

    def test_save_is_atomic_no_tmp_left_behind(self, tmp_path):
        m = CostModel()
        m.observe("schedule", "chunk:8", SKEWED, 900.0)
        path = m.save(str(tmp_path))
        assert os.path.basename(path) == COST_MODEL_FILENAME
        assert os.listdir(tmp_path) == [COST_MODEL_FILENAME]
        again = CostModel.load(str(tmp_path))
        assert again.to_json() == m.to_json()


class TestPreemptionResume:
    """A run preempted after persisting its sidecar, then resumed, must
    land on the SAME cost model bytes as a run that was never interrupted
    (the convergence-ledger discipline: tmp+rename means a crash leaves
    the prior sidecar intact, and the EMA is deterministic)."""

    REALIZED = (("schedule", 9332.0), ("ladder", 250.0), ("sharding", 8432.0))

    def _run(self, directory, observations):
        plan = ExecutionPlan.resolve(
            plan="auto", workload=SKEWED, cost_model_dir=directory
        )
        for policy, realized in observations:
            plan.record_realized(policy, realized)
        plan.save_cost_model(directory)
        return plan

    def test_resume_lands_on_uninterrupted_cost_model(self, tmp_path):
        clean = tmp_path / "clean"
        bumpy = tmp_path / "bumpy"
        clean.mkdir(), bumpy.mkdir()
        # uninterrupted: two full epochs of realized feedback
        self._run(str(clean), self.REALIZED)
        self._run(str(clean), self.REALIZED)
        # preempted: first epoch persists, then the SECOND attempt dies
        # mid-write (a torn tmp file the atomic rename never promoted)
        self._run(str(bumpy), self.REALIZED)
        (bumpy / (COST_MODEL_FILENAME + ".tmp")).write_text('{"form')
        # resume: re-resolve from the surviving sidecar, replay the epoch
        resumed = self._run(str(bumpy), self.REALIZED)
        src = next(
            d for d in resumed.decisions if d.policy == "cost-model"
        )
        assert src.action == "loaded"  # resumed from the prior epoch
        clean_bytes = (clean / COST_MODEL_FILENAME).read_bytes()
        bumpy_bytes = (bumpy / COST_MODEL_FILENAME).read_bytes()
        assert clean_bytes == bumpy_bytes

    def test_realized_costs_attach_to_decisions(self, tmp_path):
        plan = self._run(str(tmp_path), self.REALIZED)
        sched = next(d for d in plan.decisions if d.policy == "schedule")
        assert sched.realized_cost == 9332.0
        assert sched.predicted_cost is not None
        assert "realized=9332" in sched.describe()
        # a second resolve now predicts FROM the realized value
        warm = ExecutionPlan.resolve(
            plan="auto", workload=SKEWED, cost_model_dir=str(tmp_path)
        )
        choice = next(
            d for d in warm.decisions if d.policy == "schedule"
        ).planned_choice()
        assert warm.cost_model.predict(
            "schedule", choice, SKEWED
        ) <= 9332.0


class TestManifestExport:
    """retrain.json carries the cost model under --plan auto and stays
    byte-stable without it (back-compat both directions)."""

    def test_manifest_round_trips_cost_model(self, tmp_path):
        from photon_ml_tpu.retrain.manifest import RetrainManifest

        m = CostModel()
        m.observe("schedule", "chunk:8", SKEWED, 900.0)
        manifest = RetrainManifest(
            output_dir=str(tmp_path), model_dir=str(tmp_path),
            task="LOGISTIC_REGRESSION", file_stats=[], ingest_inputs=[],
            ingest_digest="d", updating_sequence=[], coordinates={},
            cost_model=m.to_json(),
        )
        manifest.save(str(tmp_path))
        back = RetrainManifest.load(str(tmp_path))
        assert back.cost_model == m.to_json()

    def test_manifest_without_cost_model_stays_clean(self, tmp_path):
        from photon_ml_tpu.retrain.manifest import RetrainManifest

        manifest = RetrainManifest(
            output_dir=str(tmp_path), model_dir=str(tmp_path),
            task="LOGISTIC_REGRESSION", file_stats=[], ingest_inputs=[],
            ingest_digest="d", updating_sequence=[], coordinates={},
        )
        path = manifest.save(str(tmp_path))
        raw = json.loads(open(path).read())
        assert "cost_model" not in raw  # --plan off: bytes as before
        assert RetrainManifest.load(str(tmp_path)).cost_model is None


class TestLadderPlanning:
    def test_planner_turns_ladder_on_for_skewed(self):
        p = ExecutionPlan.resolve(plan="auto", workload=SKEWED)
        dec = next(d for d in p.decisions if d.policy == "ladder")
        assert dec.planned_choice() == "on" and p.bucketer is not None

    def test_realized_trace_cost_can_flip_ladder_off(self, tmp_path):
        plan = ExecutionPlan.resolve(
            plan="auto", workload=SKEWED, cost_model_dir=str(tmp_path)
        )
        assert plan.bucketer is not None
        # reality: the ladder re-traced wildly (say a pathological rung
        # spread) — costlier than the flat-shape alternative's prior
        off_prior = plan.cost_model.prior("ladder", "off", SKEWED)
        plan.record_realized("ladder", 4.0 * off_prior)
        plan.record_realized("ladder", 4.0 * off_prior)
        plan.save_cost_model(str(tmp_path))
        warm = ExecutionPlan.resolve(
            plan="auto", workload=SKEWED, cost_model_dir=str(tmp_path)
        )
        dec = next(d for d in warm.decisions if d.policy == "ladder")
        assert dec.planned_choice() == "off" and warm.bucketer is None
        assert TRACE_COST > 0  # the unit the realized cost was paid in
