"""Incremental delta retraining (photon_ml_tpu.retrain).

Covers the planner (file/coordinate/block classification), the bitwise
warm-start round trip, frozen coordinates in coordinate descent, the delta
streaming-block build (prior blocking pinned, payload reuse, row-count
guard), chaos degrade-to-cold for the new fault sites, the CacheStats
registry, and the driver loop end-to-end: prior run -> all-unchanged
short-circuit -> 90%-style delta run with frozen blocks bitwise-equal to
the prior model -> warm-started lambda grid.
"""

import dataclasses
import json
import os
import shutil
import time

import numpy as np
import pytest

from photon_ml_tpu import retrain
from photon_ml_tpu.io import model_io
from photon_ml_tpu.io.tensor_cache import CacheStats, TensorCache, cache_stats
from photon_ml_tpu.resilience import faults
from photon_ml_tpu.resilience.sites import FAULT_SITES
from photon_ml_tpu.retrain.manifest import CoordinateRecord, RetrainManifest

from game_test_utils import make_glmix_data, write_game_avro

pytestmark = pytest.mark.retrain


# ---------------------------------------------------------------------------
# shared synthetic workload: files partitioned BY USER GROUP so a changed
# file dirties only its own entities (the daily-delta shape)
# ---------------------------------------------------------------------------

NUM_USERS = 30
USERS_PER_FILE = 6  # 5 files; mutating one dirties ~20% of users


def _write_partitioned(train_dir, gd, truth, mutate_file=None, drop_rows=0):
    """Write (or, with ``mutate_file``, rewrite ONLY that file) the
    user-partitioned daily layout — the unmutated files keep their stats."""
    user_of_row = gd.ids["userId"]
    os.makedirs(train_dir, exist_ok=True)
    file_rows = []
    for k in range(NUM_USERS // USERS_PER_FILE):
        rows = np.nonzero(
            (user_of_row >= USERS_PER_FILE * k)
            & (user_of_row < USERS_PER_FILE * (k + 1))
        )[0]
        if k == mutate_file and drop_rows:
            rows = rows[:-drop_rows]
        file_rows.append(rows)
        if mutate_file is None or k == mutate_file:
            write_game_avro(
                os.path.join(train_dir, f"part-{k}.avro"), gd, rows, truth
            )
    return file_rows


def _flags(train_dir, out_dir, extra=()):
    return [
        "--train-input-dirs", train_dir,
        "--output-dir", out_dir,
        "--task-type", "LOGISTIC_REGRESSION",
        "--feature-shard-id-to-feature-section-keys-map",
        "global:fixedFeatures|per_user:userFeatures",
        "--updating-sequence", "fixed,per-user",
        "--fixed-effect-data-configurations", "fixed:global,1",
        "--random-effect-data-configurations",
        "per-user:userId,per_user,1,-1,-1,-1,INDEX_MAP",
        "--fixed-effect-optimization-configurations",
        "fixed:20,1e-7,0.01,1,LBFGS,L2",
        "--random-effect-optimization-configurations",
        "per-user:15,1e-6,0.1,1,LBFGS,L2",
        "--delete-output-dir-if-exists", "true",
        "--re-memory-budget-mb", "0.001",  # blocks of 6 = one per file
        "--num-iterations", "2",
    ] + list(extra)


@pytest.fixture(scope="module")
def delta_runs(tmp_path_factory):
    """prior cold run -> unchanged rerun (short-circuit) -> delta run with
    one mutated file. One fixture, many asserts — driver runs are the
    expensive part of this suite."""
    from photon_ml_tpu.cli import game_training_driver

    base = tmp_path_factory.mktemp("retrain")
    rng = np.random.default_rng(11)
    # uniform per-user counts: the count-sorted blocking then preserves
    # vocab (= file cohort) order, and the 0.001MB budget cuts blocks of
    # exactly 6 entities — one block per file, so mutating one file
    # dirties exactly one block and freezes the other four
    gd, truth = make_glmix_data(
        rng, num_users=NUM_USERS, rows_per_user_range=(10, 11),
        d_fixed=5, d_random=3,
    )
    train_dir = str(base / "train")
    _write_partitioned(train_dir, gd, truth)
    cache_dir = str(base / "tcache")

    out1 = str(base / "run1")
    d1 = game_training_driver.main(
        _flags(train_dir, out1, ["--tensor-cache", cache_dir])
    )

    out2 = str(base / "run2")
    d2 = game_training_driver.main(
        _flags(train_dir, out2,
               ["--tensor-cache", cache_dir, "--warm-start-from", out1])
    )

    # mutate the LAST file: drop 2 rows (entities stay, data moves)
    time.sleep(0.02)  # mtime_ns must move even on coarse filesystems
    _write_partitioned(
        train_dir, gd, truth, mutate_file=NUM_USERS // USERS_PER_FILE - 1,
        drop_rows=2,
    )
    out3 = str(base / "run3")
    d3 = game_training_driver.main(
        _flags(train_dir, out3,
               ["--tensor-cache", cache_dir, "--warm-start-from", out1])
    )
    return dict(
        base=base, train_dir=train_dir, gd=gd, truth=truth,
        d1=d1, out1=out1, d2=d2, out2=out2, d3=d3, out3=out3,
    )


# ---------------------------------------------------------------------------
# planner units
# ---------------------------------------------------------------------------


def _tiny_manifest(tmp_path, files, **over):
    from photon_ml_tpu.io.tensor_cache import file_stat_token

    model_dir = os.path.join(str(tmp_path), "model")
    os.makedirs(model_dir, exist_ok=True)
    kw = dict(
        output_dir=str(tmp_path),
        model_dir=model_dir,
        task="LOGISTIC_REGRESSION",
        file_stats=file_stat_token(files),
        ingest_inputs={"sections": {}, "id_types": ["userId"]},
        ingest_digest="d0",
        updating_sequence=["fixed", "per-user"],
        coordinates={
            "fixed": CoordinateRecord(kind="fixed", opt_config="cfgA"),
            "per-user": CoordinateRecord(kind="random", opt_config="cfgB"),
        },
    )
    kw.update(over)
    return RetrainManifest(**kw)


def _touch(path, content=b"x"):
    with open(path, "wb") as f:
        f.write(content)


class TestDiffFiles:
    def test_classification(self, tmp_path):
        a, b, c = (str(tmp_path / n) for n in ("a", "b", "c"))
        for p in (a, b, c):
            _touch(p)
        m = _tiny_manifest(tmp_path, [a, b, c])
        time.sleep(0.02)
        _touch(b, b"different content entirely")
        d = str(tmp_path / "d")
        _touch(d)
        fd = retrain.diff_files(m.stat_by_path(), [a, b, d])
        assert fd.unchanged == (os.path.abspath(a),)
        assert fd.changed == (os.path.abspath(b),)
        assert fd.new == (os.path.abspath(d),)
        assert fd.removed == (os.path.abspath(c),)
        assert not fd.clean

    def test_clean(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        fd = retrain.diff_files(m.stat_by_path(), [a])
        assert fd.clean


class TestPlanDelta:
    def _plan(self, tmp_path, files, combo=None, **over):
        m = _tiny_manifest(tmp_path, files, **over)
        return m, retrain.plan_delta(
            m, files,
            task=over.get("task", "LOGISTIC_REGRESSION"),
            updating_sequence=["fixed", "per-user"],
            ingest_inputs=m.ingest_inputs,
            combo_configs=(
                {"fixed": "cfgA", "per-user": "cfgB"} if combo is None else combo
            ),
        )

    def test_all_unchanged_short_circuits(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        _, plan = self._plan(tmp_path, [a])
        assert plan.short_circuit
        assert plan.frozen_coordinates() == {"fixed", "per-user"}

    def test_changed_file_dirties_everything(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        time.sleep(0.02)
        _touch(a, b"new day new bytes")
        plan = retrain.plan_delta(
            m, [a], task="LOGISTIC_REGRESSION",
            updating_sequence=["fixed", "per-user"],
            ingest_inputs=m.ingest_inputs,
            combo_configs={"fixed": "cfgA", "per-user": "cfgB"},
        )
        assert not plan.short_circuit
        assert {c.status for c in plan.coordinates.values()} == {"dirty"}

    def test_config_change_blocks_freezing(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        _, plan = self._plan(
            tmp_path, [a], combo={"fixed": "cfgA", "per-user": "DIFFERENT"}
        )
        assert not plan.short_circuit
        assert plan.coordinates["fixed"].status == "unchanged"
        assert plan.coordinates["per-user"].status == "dirty"

    def test_new_coordinate_mixes_frozen_and_cold(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        plan = retrain.plan_delta(
            m, [a], task="LOGISTIC_REGRESSION",
            updating_sequence=["fixed", "per-user", "per-item"],
            ingest_inputs=m.ingest_inputs,
            combo_configs={"fixed": "cfgA", "per-user": "cfgB",
                           "per-item": "cfgC"},
        )
        assert not plan.short_circuit  # sequence grew
        assert plan.coordinates["per-item"].status == "new"
        assert plan.coordinates["fixed"].status == "unchanged"

    def test_changed_validation_side_blocks_short_circuit(self, tmp_path):
        """Training identical but the validation inputs/evaluators moved:
        no wholesale short-circuit (the run must re-score) — yet every
        coordinate stays frozen, so it still solves nothing."""
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(
            tmp_path, [a], eval_identity={"validate_files": [["v", 1, 2]]}
        )
        plan = retrain.plan_delta(
            m, [a], task="LOGISTIC_REGRESSION",
            updating_sequence=["fixed", "per-user"],
            ingest_inputs=m.ingest_inputs,
            combo_configs={"fixed": "cfgA", "per-user": "cfgB"},
            eval_identity={"validate_files": [["v2", 9, 9]]},
        )
        assert not plan.short_circuit
        assert plan.frozen_coordinates() == {"fixed", "per-user"}
        assert any("validation" in d.reason for d in plan.decisions)

    def test_multi_combo_grid_disables_freezing(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        plan = retrain.plan_delta(
            m, [a], task="LOGISTIC_REGRESSION",
            updating_sequence=["fixed", "per-user"],
            ingest_inputs=m.ingest_inputs,
            combo_configs=None,  # multi-combo grid
        )
        assert not plan.short_circuit
        assert {c.status for c in plan.coordinates.values()} == {"dirty"}


class TestManifestRoundTrip:
    def test_save_load(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a], data_cache_key="k123")
        m.save(str(tmp_path))
        loaded = RetrainManifest.load(str(tmp_path))
        assert loaded.coordinates["fixed"].opt_config == "cfgA"
        assert loaded.data_cache_key == "k123"
        assert loaded.stat_by_path() == m.stat_by_path()

    def test_format_mismatch_raises(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        path = m.save(str(tmp_path))
        with open(path) as f:
            raw = json.load(f)
        raw["format"] = 999
        with open(path, "w") as f:
            json.dump(raw, f)
        with pytest.raises(ValueError, match="format"):
            RetrainManifest.load(str(tmp_path))

    def test_vanished_model_dir_rejected(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        m.save(str(tmp_path))
        shutil.rmtree(m.model_dir)
        with pytest.raises(FileNotFoundError):
            retrain.load_prior_manifest(str(tmp_path))


# ---------------------------------------------------------------------------
# fault sites + chaos degrade
# ---------------------------------------------------------------------------


@pytest.mark.faults
class TestFaultSites:
    def test_sites_registered(self):
        assert "retrain.delta_plan" in FAULT_SITES
        assert "io.cache_invalidate" in FAULT_SITES

    def test_delta_plan_fault_raises_into_caller(self, tmp_path):
        a = str(tmp_path / "a")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        m.save(str(tmp_path))
        plan = faults.parse_fault_env("retrain.delta_plan:rate=1.0,seed=1")
        with faults.fault_scope(plan):
            with pytest.raises(faults.InjectedIOError):
                retrain.load_prior_manifest(str(tmp_path))
        # without the fault the same manifest loads fine
        assert retrain.load_prior_manifest(str(tmp_path)).task

    def test_malformed_but_parseable_manifest_degrades_to_cold(self, tmp_path):
        """Valid JSON, right format, garbage file_stats entries: the
        classification step itself must degrade, not crash the run."""
        from photon_ml_tpu.cli.game_params import parse_training_params
        from photon_ml_tpu.cli.game_training_driver import GameTrainingDriver

        train_dir = str(tmp_path / "train")
        os.makedirs(train_dir)
        a = os.path.join(train_dir, "part-0.avro")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        path = m.save(str(tmp_path))
        with open(path) as f:
            raw = json.load(f)
        raw["file_stats"] = [[a, 123]]  # missing mtime — malformed token
        with open(path, "w") as f:
            json.dump(raw, f)
        params = parse_training_params(_flags(
            train_dir, str(tmp_path / "out"),
            ["--warm-start-from", str(tmp_path)],
        ))
        driver = GameTrainingDriver(params)
        driver._maybe_plan_delta([a])
        assert driver.delta_plan is None and driver.retrain_prior is None

    def test_corrupt_manifest_degrades_driver_to_cold(self, tmp_path):
        """The driver records a cold run when the prior manifest is
        garbage — the delta plan stays None, nothing raises."""
        from photon_ml_tpu.cli.game_params import parse_training_params
        from photon_ml_tpu.cli.game_training_driver import GameTrainingDriver

        train_dir = str(tmp_path / "train")
        os.makedirs(train_dir)
        a = os.path.join(train_dir, "part-0.avro")
        _touch(a)
        prior_dir = str(tmp_path / "prior")
        os.makedirs(prior_dir)
        with open(os.path.join(prior_dir, "retrain.json"), "w") as f:
            f.write("{this is not json")
        params = parse_training_params(_flags(train_dir, str(tmp_path / "out"),
                                              ["--warm-start-from", prior_dir]))
        driver = GameTrainingDriver(params)
        driver._maybe_plan_delta([a])
        assert driver.retrain_prior is None
        assert driver.delta_plan is None

    def test_injected_fault_degrades_driver_to_cold(self, tmp_path):
        from photon_ml_tpu.cli.game_params import parse_training_params
        from photon_ml_tpu.cli.game_training_driver import GameTrainingDriver

        train_dir = str(tmp_path / "train")
        os.makedirs(train_dir)
        a = os.path.join(train_dir, "part-0.avro")
        _touch(a)
        m = _tiny_manifest(tmp_path, [a])
        m.save(str(tmp_path))
        params = parse_training_params(_flags(
            train_dir, str(tmp_path / "out"),
            ["--warm-start-from", str(tmp_path)],
        ))
        driver = GameTrainingDriver(params)
        plan = faults.parse_fault_env("retrain.delta_plan:rate=1.0,seed=1")
        with faults.fault_scope(plan):
            driver._maybe_plan_delta([a])
        assert driver.delta_plan is None  # recorded cold, never wrong-warm

    def test_cache_invalidate_fault_degrades_to_noop(self, tmp_path):
        stats = CacheStats()
        cache = TensorCache(str(tmp_path / "c"), stats=stats)
        key = cache.key_for([], {"k": 1})
        cache.put(key, {"a": np.arange(4)})
        plan = faults.parse_fault_env("io.cache_invalidate:rate=1.0,seed=1")
        with faults.fault_scope(plan):
            assert cache.invalidate(key) is False  # logged no-op, no raise
        assert cache.has(key)  # entry intact — harmless, never stale-served
        assert stats.invalidations == 0
        assert cache.invalidate(key) is True
        assert not cache.has(key)
        assert stats.invalidations == 1


# ---------------------------------------------------------------------------
# CacheStats registry
# ---------------------------------------------------------------------------


@pytest.mark.pipeline
class TestCacheStats:
    def test_counters(self, tmp_path):
        stats = CacheStats()
        cache = TensorCache(str(tmp_path / "c"), stats=stats)
        key = cache.key_for([], {"k": 1})
        assert cache.get(key) is None
        assert stats.misses == 1
        cache.put(key, {"a": np.arange(8, dtype=np.float32)})
        assert stats.writes == 1 and stats.bytes_written > 0
        hit = cache.get(key)
        assert hit is not None
        assert stats.hits == 1 and stats.bytes_reused >= 32
        s = stats.summary()
        assert "1 hits" in s and "1 misses" in s

    def test_broken_entry_counts(self, tmp_path):
        stats = CacheStats()
        cache = TensorCache(str(tmp_path / "c"), stats=stats)
        key = cache.key_for([], {"k": 2})
        cache.put(key, {"a": np.arange(4)})
        # rot the payload: meta promises an array the entry no longer has
        os.remove(os.path.join(cache.entry_dir(key), "a.npy"))
        assert cache.get(key) is None
        assert stats.broken == 1

    def test_process_registry_is_default(self, tmp_path):
        before = cache_stats.snapshot()["misses"]
        cache = TensorCache(str(tmp_path / "c"))
        assert cache.get(cache.key_for([], {"k": 3})) is None
        assert cache_stats.snapshot()["misses"] == before + 1


# ---------------------------------------------------------------------------
# warm-start round trip + frozen CD coordinates
# ---------------------------------------------------------------------------


class TestWarmRoundTrip:
    def test_dense_re_round_trip_bitwise(self, tmp_path, rng):
        """export -> reload -> gather reproduces the local coefficients
        bitwise (the property that makes frozen blocks exact)."""
        from photon_ml_tpu.algorithm.random_effect import global_coefficients
        from photon_ml_tpu.data.game import (
            RandomEffectDataConfig,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.io.index_map import IndexMap, feature_key
        from photon_ml_tpu.types import TaskType

        gd, truth = make_glmix_data(rng, num_users=8,
                                    rows_per_user_range=(5, 9), d_random=3)
        cfg = RandomEffectDataConfig(
            random_effect_id="userId", feature_shard_id="per_user",
        )
        ds = build_random_effect_dataset(gd, cfg)
        w_local = rng.normal(size=np.asarray(ds.local_to_global).shape).astype(
            np.float32
        )
        wg = np.asarray(global_coefficients(ds, w_local))
        imap = IndexMap.build([feature_key(f"u{j}", "") for j in range(3)],
                              add_intercept=False)
        vocab = gd.id_vocabs["userId"]
        entity_pos = np.asarray(ds.entity_pos)
        ids = gd.ids["userId"]
        pos_of_vocab = np.full(len(vocab), -1, np.int32)
        known = entity_pos >= 0
        pos_of_vocab[ids[known]] = entity_pos[known]
        means = {}
        for vi, raw in enumerate(vocab):
            if pos_of_vocab[vi] >= 0:
                means[raw] = wg[pos_of_vocab[vi]]
        model_io.save_random_effect(
            str(tmp_path), "per-user", TaskType.LOGISTIC_REGRESSION,
            means, imap, random_effect_id="userId",
            feature_shard_id="per_user",
        )
        reloaded = retrain.random_effect_entity_means(
            str(tmp_path), "per-user", imap
        )
        w_back = retrain.dense_random_effect_init(
            reloaded, vocab=vocab, pos_of_vocab=pos_of_vocab,
            local_to_global=np.asarray(ds.local_to_global),
        )
        ltg = np.asarray(ds.local_to_global)
        valid = ltg >= 0
        assert np.array_equal(w_back[valid], w_local[valid])

    def test_factored_prior_returns_none(self, tmp_path):
        from photon_ml_tpu.io.index_map import IndexMap, feature_key

        model_io.save_factored_random_effect(
            str(tmp_path), "per-user",
            {"u0": np.array([0.5, 0.5])}, np.ones((2, 3), np.float32),
            random_effect_id="userId", feature_shard_id="per_user",
        )
        imap = IndexMap.build([feature_key("u0", "")], add_intercept=False)
        assert retrain.random_effect_entity_means(
            str(tmp_path), "per-user", imap
        ) is None


class TestFrozenCoordinates:
    def _cd(self, gd, truth):
        import jax.numpy as jnp

        from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
        from photon_ml_tpu.algorithm.fixed_effect import FixedEffectCoordinate
        from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
        from photon_ml_tpu.data.game import (
            RandomEffectDataConfig,
            build_fixed_effect_batch,
            build_random_effect_dataset,
        )
        from photon_ml_tpu.ops import losses as losses_mod
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem
        from photon_ml_tpu.types import TaskType

        task = TaskType.LOGISTIC_REGRESSION
        coords = {
            "fixed": FixedEffectCoordinate(
                build_fixed_effect_batch(gd, "global", dense=True),
                GLMOptimizationProblem(task=task),
            ),
            "per-user": RandomEffectCoordinate(
                build_random_effect_dataset(
                    gd, RandomEffectDataConfig(
                        random_effect_id="userId",
                        feature_shard_id="per_user",
                    )
                ),
                task,
            ),
        }
        loss = losses_mod.for_task(task)
        labels = jnp.asarray(gd.response)
        weights = jnp.asarray(gd.weight)

        def loss_fn(total):
            return jnp.sum(weights * loss.loss(total, labels))

        return coords, CoordinateDescent(coords, loss_fn)

    def test_frozen_coordinate_carries_params_bitwise(self, rng):
        gd, truth = make_glmix_data(rng, num_users=6,
                                    rows_per_user_range=(5, 9))
        _, cd1 = self._cd(gd, truth)
        r1 = cd1.run(2, gd.num_rows)
        _, cd2 = self._cd(gd, truth)
        init = {k: np.asarray(v) for k, v in r1.coefficients.items()}
        import jax.numpy as jnp

        r2 = cd2.run(
            2, gd.num_rows,
            initial_params={k: jnp.asarray(v) for k, v in init.items()},
            frozen={"per-user"},
        )
        assert np.array_equal(
            np.asarray(r2.coefficients["per-user"]), init["per-user"]
        )
        # the unfrozen coordinate genuinely trained
        assert len(r2.objective_history) == 4

    def test_run_grid_accepts_partial_init_params(self, rng):
        """A coordinate missing from init_params (new since the prior
        model) starts cold in run_grid, exactly like run() — no KeyError."""
        import jax.numpy as jnp

        gd, truth = make_glmix_data(rng, num_users=4,
                                    rows_per_user_range=(5, 8))
        _, cd1 = self._cd(gd, truth)
        r1 = cd1.run(1, gd.num_rows)
        _, cd2 = self._cd(gd, truth)
        results = cd2.run_grid(
            {"fixed": jnp.asarray([0.0, 0.5]),
             "per-user": jnp.asarray([0.1, 1.0])},
            1, gd.num_rows,
            init_params={"fixed": jnp.asarray(r1.coefficients["fixed"])},
        )
        assert len(results) == 2
        for r in results:
            assert np.isfinite(r.objective_history[-1])

    def test_frozen_requires_initial_params(self, rng):
        gd, truth = make_glmix_data(rng, num_users=4,
                                    rows_per_user_range=(5, 8))
        _, cd = self._cd(gd, truth)
        with pytest.raises(ValueError, match="initial_params"):
            cd.run(1, gd.num_rows, frozen={"per-user"})

    def test_frozen_unknown_name_raises(self, rng):
        gd, truth = make_glmix_data(rng, num_users=4,
                                    rows_per_user_range=(5, 8))
        _, cd = self._cd(gd, truth)
        with pytest.raises(ValueError, match="not in the updating"):
            cd.run(1, gd.num_rows, initial_params={}, frozen={"nope"})


# ---------------------------------------------------------------------------
# delta streaming-block build (unit level: no driver)
# ---------------------------------------------------------------------------


def _subset_game_data(gd, keep):
    """GameData restricted to the kept row indices (CSR resliced)."""
    from photon_ml_tpu.data.game import GameData, HostFeatures

    keep = np.asarray(keep)
    shards = {}
    for s, f in gd.shards.items():
        counts = np.diff(f.indptr)[keep]
        parts_i, parts_v = [], []
        for r in keep:
            parts_i.append(f.indices[f.indptr[r]:f.indptr[r + 1]])
            parts_v.append(f.values[f.indptr[r]:f.indptr[r + 1]])
        shards[s] = HostFeatures(
            np.concatenate([[0], np.cumsum(counts)]).astype(np.int64),
            (np.concatenate(parts_i) if parts_i else np.zeros(0)).astype(np.int32),
            (np.concatenate(parts_v) if parts_v else np.zeros(0)).astype(np.float32),
            f.dim,
        )
    return GameData(
        response=gd.response[keep], offset=gd.offset[keep],
        weight=gd.weight[keep],
        ids={k: v[keep] for k, v in gd.ids.items()},
        id_vocabs=dict(gd.id_vocabs), shards=shards,
    )


class TestDeltaBlockBuild:
    @pytest.fixture()
    def prior_blocks(self, tmp_path, rng):
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            write_re_entity_blocks,
        )
        from photon_ml_tpu.data.game import RandomEffectDataConfig

        gd, truth = make_glmix_data(
            rng, num_users=20, rows_per_user_range=(6, 10), d_random=3
        )
        cfg = RandomEffectDataConfig(
            random_effect_id="userId", feature_shard_id="per_user",
        )
        manifest = write_re_entity_blocks(
            gd, cfg, str(tmp_path / "prior-blocks"), block_entities=5
        )
        return gd, cfg, manifest

    def test_unchanged_blocks_reuse_payload_bitwise(self, tmp_path, prior_blocks):
        gd, cfg, prior = prior_blocks
        vocab = gd.id_vocabs["userId"]
        dirty_raw = {vocab[3]}  # one dirty entity
        manifest, deltas = retrain.build_delta_streaming_manifest(
            gd, cfg, str(tmp_path / "new-blocks"), prior, dirty_raw,
            block_entities=5,
        )
        statuses = {d.status for d in deltas}
        assert "unchanged" in statuses
        assert len(deltas) == len(prior.blocks)
        for d in deltas:
            if d.status != "unchanged":
                continue
            old = np.load(os.path.join(
                prior.dir, prior.blocks[d.prior_index]["file"]))
            new = np.load(os.path.join(
                manifest.dir, manifest.blocks[d.index]["file"]))
            for field in ("x", "labels", "weights", "entity_pos",
                          "local_to_global", "row_sel", "entity_ids"):
                assert np.array_equal(old[field], new[field]), field

    def test_dirty_entities_dirty_their_block(self, tmp_path, prior_blocks):
        gd, cfg, prior = prior_blocks
        vocab = gd.id_vocabs["userId"]
        dirty_raw = {vocab[3]}
        _, deltas = retrain.build_delta_streaming_manifest(
            gd, cfg, str(tmp_path / "nb"), prior, dirty_raw, block_entities=5,
        )
        # the block holding entity 3 must be dirty with the recorded reason
        dirty = [d for d in deltas if d.status == "dirty"]
        assert dirty and any("dirty entities" in d.reason for d in dirty)

    def test_row_count_guard_demotes_to_dirty(self, tmp_path, prior_blocks):
        """An entity that silently LOST rows (not in any changed file's new
        content) must not reuse the stale payload."""
        gd, cfg, prior = prior_blocks
        ids = gd.ids["userId"]
        victim = int(ids[0])
        drop = np.nonzero(ids == victim)[0][:1]
        keep = np.setdiff1d(np.arange(gd.num_rows), drop)
        gd2 = _subset_game_data(gd, keep)
        _, deltas = retrain.build_delta_streaming_manifest(
            gd2, cfg, str(tmp_path / "nb"), prior, set(), block_entities=5,
        )
        demoted = [d for d in deltas if "row count moved" in d.reason]
        assert len(demoted) == 1 and demoted[0].status == "dirty"

    def test_lost_prior_block_file_degrades_to_rebuild(self, tmp_path, prior_blocks):
        gd, cfg, prior = prior_blocks
        os.remove(os.path.join(prior.dir, prior.blocks[0]["file"]))
        manifest, deltas = retrain.build_delta_streaming_manifest(
            gd, cfg, str(tmp_path / "nb"), prior, set(), block_entities=5,
        )
        assert any("unreadable" in d.reason for d in deltas)
        # every block still written and loadable — never a missing block
        assert len(manifest.blocks) == len(prior.blocks)
        for i in range(len(manifest.blocks)):
            manifest.load_block(i)

    def test_new_entities_append_as_new_blocks(self, tmp_path, rng, prior_blocks):
        gd, cfg, prior = prior_blocks
        # prior manifest built over users 0..14 only: rebuild a prior with
        # a SUBSET vocab by slicing rows of users < 15
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            write_re_entity_blocks,
        )

        ids = gd.ids["userId"]
        sub = _subset_game_data(gd, np.nonzero(ids < 15)[0])
        # re-densify the subset's vocab (15 users)
        sub.id_vocabs["userId"] = gd.id_vocabs["userId"][:15]
        prior_sub = write_re_entity_blocks(
            sub, cfg, str(tmp_path / "prior-sub"), block_entities=5
        )
        _, deltas = retrain.build_delta_streaming_manifest(
            gd, cfg, str(tmp_path / "nb"), prior_sub, set(), block_entities=5,
        )
        assert any(d.status == "new" for d in deltas)

    def test_pinned_block_outgrowing_budget_reblocks(self, tmp_path):
        """Daily growth steady state: a pinned block whose rows grew past
        the memory budget must re-block fresh (recorded), not fail a
        retrain a cold run of the same config would survive."""
        from photon_ml_tpu.algorithm.streaming_random_effect import (
            write_re_entity_blocks,
        )
        from photon_ml_tpu.data.game import (
            GameData,
            RandomEffectDataConfig,
        )
        from game_test_utils import dense_to_csr

        rng = np.random.default_rng(5)

        def mk(rows_per_user):
            n = int(np.sum(rows_per_user))
            user_of_row = np.repeat(
                np.arange(len(rows_per_user), dtype=np.int32), rows_per_user
            )
            return GameData(
                response=(rng.random(n) > 0.5).astype(np.float32),
                offset=np.zeros(n, np.float32),
                weight=np.ones(n, np.float32),
                ids={"userId": user_of_row},
                id_vocabs={"userId": [f"u{i}" for i in range(len(rows_per_user))]},
                shards={
                    "global": dense_to_csr(
                        rng.normal(size=(n, 4)).astype(np.float32)),
                    "per_user": dense_to_csr(
                        rng.normal(size=(n, 3)).astype(np.float32)),
                },
            )

        cfg = RandomEffectDataConfig(
            random_effect_id="userId", feature_shard_id="per_user",
        )
        prior = write_re_entity_blocks(
            mk(np.full(12, 6)), cfg, str(tmp_path / "p"),
            memory_budget_bytes=600,
        )
        grown = np.full(12, 6)
        grown[0] = 30  # user 0's data grew 5x since yesterday
        gd2 = mk(grown)
        # every entity dirty: this test is about the budget demotion, not
        # payload reuse (the synthetic day-2 rows are all different)
        manifest, deltas = retrain.build_delta_streaming_manifest(
            gd2, cfg, str(tmp_path / "nb"), prior,
            set(gd2.id_vocabs["userId"]), memory_budget_bytes=600,
        )
        assert any("outgrew the budget" in d.reason for d in deltas)
        # every block written respects the budget and loads
        for i in range(len(manifest.blocks)):
            assert manifest.blocks[i]["x_bytes"] <= 600
            manifest.load_block(i)

    def test_cache_hit_recovers_classifications(self, tmp_path, prior_blocks):
        gd, cfg, prior = prior_blocks
        cache = TensorCache(str(tmp_path / "cache"), stats=CacheStats())
        key = "k" * 64
        m1, d1 = retrain.build_delta_streaming_manifest(
            gd, cfg, str(tmp_path / "nb"), prior, set(), block_entities=5,
            tensor_cache=cache, cache_key=key,
        )
        m2, d2 = retrain.build_delta_streaming_manifest(
            gd, cfg, str(tmp_path / "nb2"), prior, set(), block_entities=5,
            tensor_cache=cache, cache_key=key,
        )
        assert m2.dir == m1.dir  # served from the cache entry
        assert [(d.index, d.status) for d in d2] == [
            (d.index, d.status) for d in d1
        ]


# ---------------------------------------------------------------------------
# driver end-to-end: the retrain loop
# ---------------------------------------------------------------------------


class TestDriverDeltaLoop:
    def test_prior_run_writes_manifest(self, delta_runs):
        out1 = delta_runs["out1"]
        m = RetrainManifest.load(out1)
        assert m.coordinates["per-user"].kind == "streaming_random"
        assert os.path.isdir(m.coordinates["per-user"].streaming_manifest_dir)
        assert m.data_cache_key

    def test_unchanged_rerun_short_circuits_bitwise(self, delta_runs):
        d2, out1, out2 = (delta_runs[k] for k in ("d2", "out1", "out2"))
        assert d2.delta_plan is not None and d2.delta_plan.short_circuit
        assert d2.results == []  # no training happened
        # the re-exported model is byte-identical to the prior
        for root, _, files in os.walk(os.path.join(out1, "best")):
            rel = os.path.relpath(root, os.path.join(out1, "best"))
            for f in files:
                a = os.path.join(root, f)
                b = os.path.join(out2, "best", rel, f)
                with open(a, "rb") as fa, open(b, "rb") as fb:
                    assert fa.read() == fb.read(), (rel, f)

    def test_delta_run_freezes_unchanged_blocks(self, delta_runs):
        d3 = delta_runs["d3"]
        deltas = d3.block_deltas["per-user"]
        frozen = d3._frozen_blocks["per-user"]
        assert frozen  # some blocks genuinely skipped their solves
        assert {d.status for d in deltas} >= {"unchanged", "dirty"}
        assert frozen == {d.index for d in deltas if d.status == "unchanged"}

    def test_frozen_block_entities_bitwise_equal_prior(self, delta_runs):
        d1, d3 = delta_runs["d1"], delta_runs["d3"]
        out1, out3 = delta_runs["out1"], delta_runs["out3"]
        imap = d3.shard_index_maps["per_user"]
        means1, _, _, _ = model_io.load_random_effect(
            os.path.join(out1, "best"), "per-user", imap)
        means3, _, _, _ = model_io.load_random_effect(
            os.path.join(out3, "best"), "per-user", imap)
        m3 = d3.streaming_manifests["per-user"]
        frozen_raws = set()
        for i in d3._frozen_blocks["per-user"]:
            bm = m3.load_block_meta(i)
            frozen_raws.update(m3.vocab[v] for v in bm.entity_ids)
        assert frozen_raws
        for raw in frozen_raws:
            assert np.array_equal(means1[raw], means3[raw]), raw

    def test_dirty_blocks_actually_resolve(self, delta_runs):
        """Dirty entities see new data — their coefficients must move."""
        d3 = delta_runs["d3"]
        out1, out3 = delta_runs["out1"], delta_runs["out3"]
        imap = d3.shard_index_maps["per_user"]
        means1, _, _, _ = model_io.load_random_effect(
            os.path.join(out1, "best"), "per-user", imap)
        means3, _, _, _ = model_io.load_random_effect(
            os.path.join(out3, "best"), "per-user", imap)
        dirty = d3.delta_plan.dirty_entities["userId"]
        assert dirty
        moved = [r for r in dirty if not np.array_equal(means1[r], means3[r])]
        assert moved  # warm-started, but genuinely re-solved on new data

    def test_superseded_ingest_entry_invalidated(self, delta_runs):
        d1, d3 = delta_runs["d1"], delta_runs["d3"]
        cache = d3._tensor_cache()
        assert not cache.has(d1._data_cache_key)  # superseded + invalidated
        assert cache.has(d3._data_cache_key)

    def test_delta_manifest_chains(self, delta_runs):
        """run3's manifest supports a FOURTH run warm-starting from it."""
        m = RetrainManifest.load(delta_runs["out3"])
        assert os.path.isdir(m.coordinates["per-user"].streaming_manifest_dir)
        loaded = retrain.load_prior_manifest(delta_runs["out3"])
        assert loaded.model_dir.endswith("best")


class TestUnchangedStreamingReuse:
    def test_unchanged_coordinate_reuses_prior_layout_verbatim(self, delta_runs):
        """Sibling config change (fixed lambda moved, files clean): the
        streaming coordinate is unchanged — its prior block layout must be
        opened verbatim (no rebuild) and its coefficients stay bitwise."""
        from photon_ml_tpu.cli import game_training_driver

        out3 = delta_runs["out3"]
        d3 = delta_runs["d3"]
        train_dir = delta_runs["train_dir"]
        out4 = str(delta_runs["base"] / "run4")
        flags = _flags(train_dir, out4, ["--warm-start-from", out3])
        flags[flags.index("fixed:20,1e-7,0.01,1,LBFGS,L2")] = (
            "fixed:20,1e-7,0.5,1,LBFGS,L2"  # only the FIXED lambda moves
        )
        d4 = game_training_driver.main(flags)
        prior_rec = RetrainManifest.load(out3).coordinates["per-user"]
        assert d4.delta_plan.coordinates["per-user"].status == "unchanged"
        assert os.path.samefile(
            d4.streaming_manifests["per-user"].dir,
            prior_rec.streaming_manifest_dir,
        )
        imap = d4.shard_index_maps["per_user"]
        means3, _, _, _ = model_io.load_random_effect(
            os.path.join(out3, "best"), "per-user", imap)
        means4, _, _, _ = model_io.load_random_effect(
            os.path.join(out4, "best"), "per-user", imap)
        for raw, row in means3.items():
            assert np.array_equal(row, means4[raw]), raw
        # the fixed coordinate genuinely re-solved at the new lambda
        f3, _, _, _ = model_io.load_fixed_effect(
            os.path.join(out3, "best"), "fixed",
            d4.shard_index_maps["global"])
        f4, _, _, _ = model_io.load_fixed_effect(
            os.path.join(out4, "best"), "fixed",
            d4.shard_index_maps["global"])
        assert not np.array_equal(f3, f4)


class TestWarmGrid:
    def test_grid_lanes_warm_start_from_prior(self, tmp_path, rng):
        """Lambda-grid delta run: every lane seeds from the prior selected
        model through run_grid(init_params=) — the PR-2 hook generalized."""
        from photon_ml_tpu.cli import game_training_driver

        gd, truth = make_glmix_data(
            rng, num_users=8, rows_per_user_range=(8, 12), d_fixed=4,
            d_random=3,
        )
        train_dir = str(tmp_path / "train")
        os.makedirs(train_dir)
        write_game_avro(os.path.join(train_dir, "part-0.avro"), gd,
                        range(gd.num_rows), truth)
        common = [
            "--task-type", "LOGISTIC_REGRESSION",
            "--feature-shard-id-to-feature-section-keys-map",
            "global:fixedFeatures|per_user:userFeatures",
            "--updating-sequence", "fixed,per-user",
            "--fixed-effect-data-configurations", "fixed:global,1",
            "--random-effect-data-configurations",
            "per-user:userId,per_user,1,-1,-1,-1,INDEX_MAP",
            "--fixed-effect-optimization-configurations",
            "fixed:25,1e-7,0.01,1,LBFGS,L2",
            "--delete-output-dir-if-exists", "true",
            "--num-iterations", "2",
        ]
        out1 = str(tmp_path / "run1")
        game_training_driver.main(
            ["--train-input-dirs", train_dir, "--output-dir", out1,
             "--random-effect-optimization-configurations",
             "per-user:25,1e-6,0.1,1,LBFGS,L2"] + common
        )
        out2 = str(tmp_path / "run2")
        d2 = game_training_driver.main(
            ["--train-input-dirs", train_dir, "--output-dir", out2,
             "--warm-start-from", out1,
             "--vmapped-grid", "true",
             "--random-effect-optimization-configurations",
             "per-user:25,1e-6,0.1,1,LBFGS,L2;"
             "per-user:25,1e-6,1.0,1,LBFGS,L2"] + common
        )
        assert len(d2.results) == 2  # both lambda lanes trained
        assert d2._warm_init() is not None  # lanes seeded from the prior
        for _, result, _ in d2.results:
            assert np.isfinite(result.objective_history[-1])
