"""Shared per-dtype comparison tolerances for solver-output assertions.

The seed's equivalence tests carried ad-hoc atol/rtol constants tuned per
test; the streaming-vs-in-memory descent comparison failed at seed HEAD on
ONE element in 868 (abs diff ~7.6e-4 against atol=5e-4) purely because two
float32 reduction orders disagreed by a few ulps amplified through 25 LBFGS
iterations. These helpers centralize the policy instead:

  * tolerances scale with the DTYPE actually computed in (float32 runs get
    float32-sized slack; an x64 run tightens automatically);
  * two named regimes: ``elementwise`` (one pass, no iteration-to-iteration
    amplification) and ``solver`` (iterated optimization output, where ulp
    noise compounds through line searches and curvature updates).

Use ``assert_allclose(actual, desired, kind="solver")`` in place of
hand-picked constants.
"""

from __future__ import annotations

import numpy as np

# (rtol, atol) per (dtype kind, regime): scaled from the dtype's eps —
# elementwise ~1e3 eps, solver ~1e5 eps (the observed compounding of ~25
# iterations of f32 reductions, with margin), never looser than the seed's
# loosest hand-tuned constant
_TOLERANCES = {
    ("f4", "elementwise"): (1e-4, 1e-5),
    ("f4", "solver"): (1e-2, 2e-3),
    ("f8", "elementwise"): (1e-9, 1e-11),
    ("f8", "solver"): (1e-7, 1e-9),
}


def tolerances_for(dtype, kind: str = "solver"):
    """(rtol, atol) for comparing arrays computed in ``dtype``.

    ``kind``: "elementwise" for single-pass computations, "solver" for
    iterated optimizer output (ulp noise compounds per iteration).
    """
    dt = np.dtype(dtype)
    key = f"{dt.kind}{dt.itemsize}"
    if (key, kind) not in _TOLERANCES:
        raise KeyError(
            f"no tolerance policy for dtype {dt} kind {kind!r} "
            f"(known: {sorted(set(k for k, _ in _TOLERANCES))} x "
            f"{sorted(set(k for _, k in _TOLERANCES))})"
        )
    return _TOLERANCES[(key, kind)]


def assert_allclose(
    actual, desired, kind: str = "solver", dtype=None, err_msg: str = ""
):
    """np.testing.assert_allclose with the shared per-dtype policy.

    The policy dtype is the NARROWER of the two inputs' dtypes (comparing
    a float32 result against a float64 oracle is still a float32-accuracy
    comparison), unless ``dtype`` names the computation dtype explicitly —
    needed when f32 device scalars were accumulated into python floats
    (e.g. objective histories), which would otherwise masquerade as f64.
    """
    a = np.asarray(actual)
    d = np.asarray(desired)
    dt = np.dtype(dtype) if dtype is not None else min(
        a.dtype, d.dtype, key=lambda t: np.dtype(t).itemsize
    )
    rtol, atol = tolerances_for(dt, kind)
    np.testing.assert_allclose(
        a, d, rtol=rtol, atol=atol, err_msg=err_msg or f"({kind} @ {dt})"
    )
