"""Shared per-dtype comparison tolerances for solver-output assertions.

The seed's equivalence tests carried ad-hoc atol/rtol constants tuned per
test; the streaming-vs-in-memory descent comparison failed at seed HEAD on
ONE element in 868 (abs diff ~7.6e-4 against atol=5e-4) purely because two
float32 reduction orders disagreed by a few ulps amplified through 25 LBFGS
iterations. These helpers centralize the policy instead:

  * tolerances scale with the DTYPE actually computed in (float32 runs get
    float32-sized slack; an x64 run tightens automatically);
  * two named regimes: ``elementwise`` (one pass, no iteration-to-iteration
    amplification) and ``solver`` (iterated optimization output, where ulp
    noise compounds through line searches and curvature updates).

Use ``assert_allclose(actual, desired, kind="solver")`` in place of
hand-picked constants.
"""

from __future__ import annotations

import numpy as np

# (rtol, atol) per (dtype kind, regime): scaled from the dtype's eps —
# elementwise ~1e3 eps, solver ~1e5 eps (the observed compounding of ~25
# iterations of f32 reductions, with margin), never looser than the seed's
# loosest hand-tuned constant
_TOLERANCES = {
    ("f4", "elementwise"): (1e-4, 1e-5),
    ("f4", "solver"): (1e-2, 2e-3),
    ("f8", "elementwise"): (1e-9, 1e-11),
    ("f8", "solver"): (1e-7, 1e-9),
}


def tolerances_for(dtype, kind: str = "solver"):
    """(rtol, atol) for comparing arrays computed in ``dtype``.

    ``kind``: "elementwise" for single-pass computations, "solver" for
    iterated optimizer output (ulp noise compounds per iteration).
    """
    dt = np.dtype(dtype)
    key = f"{dt.kind}{dt.itemsize}"
    if (key, kind) not in _TOLERANCES:
        raise KeyError(
            f"no tolerance policy for dtype {dt} kind {kind!r} "
            f"(known: {sorted(set(k for k, _ in _TOLERANCES))} x "
            f"{sorted(set(k for _, k in _TOLERANCES))})"
        )
    return _TOLERANCES[(key, kind)]


def assert_allclose(
    actual, desired, kind: str = "solver", dtype=None, err_msg: str = ""
):
    """np.testing.assert_allclose with the shared per-dtype policy.

    The policy dtype is the NARROWER of the two inputs' dtypes (comparing
    a float32 result against a float64 oracle is still a float32-accuracy
    comparison), unless ``dtype`` names the computation dtype explicitly —
    needed when f32 device scalars were accumulated into python floats
    (e.g. objective histories), which would otherwise masquerade as f64.
    """
    a = np.asarray(actual)
    d = np.asarray(desired)
    dt = np.dtype(dtype) if dtype is not None else min(
        a.dtype, d.dtype, key=lambda t: np.dtype(t).itemsize
    )
    rtol, atol = tolerances_for(dt, kind)
    np.testing.assert_allclose(
        a, d, rtol=rtol, atol=atol, err_msg=err_msg or f"({kind} @ {dt})"
    )


# ---------------------------------------------------------------------------
# Quantized-serving error budgets (photon_ml_tpu/serve/quantize.py)
#
# A quantized serving store (store_dtype bf16/int8) trades bitwise parity
# for a PINNED per-coefficient error budget recorded in store meta at
# export. Scores inherit an analytic per-score bound from it:
#
#   |score_q - score_f32|  <=  sum_RE ||values||_1 * coeff_err_budget
#
# (fixed-effect vectors stay f32, so only random-effect coordinates
# contribute), plus a small slack for the f32 rounding noise between the
# two kernel runs. These helpers are the ONE budget policy the serve
# tests, fleet tests, and the quantized_serving bench section share —
# a budgeted comparison, not a tolerance guess.
# ---------------------------------------------------------------------------


def quant_score_budget(coeff_err_budget, values_l1, ref_scores=None):
    """(n,) per-score error budget: ``||v||_1 * coeff budget`` plus f32
    summation-noise slack (absolute + relative to the reference score —
    the quantized and f32 kernels run the identical op sequence, so their
    rounding disagreement is a few ulps of the score magnitude)."""
    budget = np.asarray(values_l1, np.float64) * float(coeff_err_budget)
    slack = 1e-6
    if ref_scores is not None:
        slack = slack + 1e-6 * np.abs(np.asarray(ref_scores, np.float64))
    return budget + slack


def assert_within_budget(actual, desired, budget, err_msg: str = ""):
    """Elementwise ``|actual - desired| <= budget`` (a hard pinned bound,
    NOT an allclose tolerance) with a worst-offender diagnostic."""
    a = np.asarray(actual, np.float64)
    d = np.asarray(desired, np.float64)
    b = np.broadcast_to(np.asarray(budget, np.float64), a.shape)
    diff = np.abs(a - d)
    if np.all(diff <= b):
        return
    i = int(np.argmax(diff - b))
    raise AssertionError(
        f"score exceeds its pinned quantization budget at row {i}: "
        f"|{a[i]:.8g} - {d[i]:.8g}| = {diff[i]:.3e} > budget {b[i]:.3e} "
        f"({int((diff > b).sum())}/{a.size} rows over). {err_msg}"
    )
