"""Pointwise loss unit tests: analytic values + derivative consistency."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import losses


ALL = [losses.logistic, losses.squared, losses.poisson, losses.smoothed_hinge]


@pytest.mark.parametrize("loss", ALL, ids=lambda l: l.name)
def test_d1_matches_autodiff(loss):
    # grid avoids z=0 / t∈{0,1} kinks where autodiff picks an arbitrary subgradient
    z = jnp.linspace(-4.0, 4.0, 41) + 0.0123
    for y in (0.0, 1.0):
        yv = jnp.full_like(z, y)
        want = jax.vmap(jax.grad(lambda zz, yy: loss.loss(zz, yy)))(z, yv)
        got = loss.d1(z, yv)
        np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("loss", [losses.logistic, losses.squared, losses.poisson])
def test_d2_matches_autodiff(loss):
    z = jnp.linspace(-4.0, 4.0, 41) + 0.0123
    for y in (0.0, 1.0, 3.0):
        yv = jnp.full_like(z, y)
        want = jax.vmap(jax.grad(jax.grad(lambda zz, yy: loss.loss(zz, yy))))(z, yv)
        np.testing.assert_allclose(loss.d2(z, yv), want, atol=1e-5)


def test_logistic_stability():
    # No overflow at extreme margins; loss(z,1) -> 0 as z -> +inf
    z = jnp.array([-500.0, -50.0, 0.0, 50.0, 500.0])
    y1 = jnp.ones_like(z)
    v = losses.logistic.loss(z, y1)
    assert np.all(np.isfinite(v))
    np.testing.assert_allclose(v[-1], 0.0, atol=1e-6)
    np.testing.assert_allclose(losses.logistic.loss(z, jnp.zeros_like(z))[0], 0.0, atol=1e-6)
    np.testing.assert_allclose(losses.logistic.loss(jnp.array([0.0]), jnp.array([0.0]))[0],
                               np.log(2.0), rtol=1e-6)


def test_squared_values():
    np.testing.assert_allclose(losses.squared.loss(jnp.array([3.0]), jnp.array([1.0]))[0], 2.0)


def test_poisson_values():
    z, y = jnp.array([0.5]), jnp.array([2.0])
    np.testing.assert_allclose(losses.poisson.loss(z, y)[0], np.exp(0.5) - 1.0, rtol=1e-5)


def test_smoothed_hinge_piecewise():
    # t = (2y-1)z; y=1 -> t=z. Regions: z<=0: 0.5-z; 0<z<1: (1-z)^2/2; z>=1: 0
    y = jnp.ones((5,))
    z = jnp.array([-1.0, 0.0, 0.5, 1.0, 2.0])
    want = np.array([1.5, 0.5, 0.125, 0.0, 0.0])
    np.testing.assert_allclose(losses.smoothed_hinge.loss(z, y), want, atol=1e-6)


def test_for_task_lookup():
    from photon_ml_tpu.types import TaskType

    assert losses.for_task(TaskType.LOGISTIC_REGRESSION) is losses.logistic
    assert losses.for_task("LINEAR_REGRESSION") is losses.squared
