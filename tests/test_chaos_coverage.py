"""Chaos-coverage gate: every registered fault and preemption site must
be exercised by at least one chaos test.

The resilience registry (photon_ml_tpu/resilience/sites.py) is the
contract photon_lint enforces on the PRODUCTION side: an inject() call
against an unregistered site fails lint. This gate closes the TEST side:
a site someone registers (and wires into production code) without ever
pointing a FaultSpec / PHOTON_FAULTS grammar / PHOTON_PREEMPT_AT plan at
it is dead chaos — the failure path ships unexercised. The scan matches
the concrete idioms the suite uses to aim chaos at a site:

  * ``FaultSpec("io.read_block", ...)`` / ``faults.inject("optim.step"``
  * the env grammar: ``"io.block_transfer:rate=1.0,seed=5"``
  * preemption plans: ``install_plan({"rung": 1})`` / ``"cycle:3"``

test_photon_lint.py is excluded — it enumerates the registry by name
without exercising anything, and counting it would let a site pass the
gate on bookkeeping alone.

Sites that genuinely CANNOT be reached from a single-process test may be
exempted below with a recorded reason; an exemption for a site the scan
DOES find covered fails the gate too (stale exemptions rot the list).
"""

import os
import re

from photon_ml_tpu.resilience.sites import FAULT_SITES, PREEMPT_SITES

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)

#: test files that NAME sites without exercising them (registry audits),
#: plus this gate itself — never counted as coverage
_REGISTRY_ONLY = {"test_photon_lint.py", "test_chaos_coverage.py"}

#: site -> reason, for fault sites only exercisable with >1 real process.
#: Every current site is coverable single-process (subprocess harnesses
#: included), so the list is empty — the structure stays so the NEXT
#: multi-process-only site records WHY it is exempt instead of silently
#: shrinking the gate.
EXEMPT_FAULT_SITES = {}

#: same, for preemption sites
EXEMPT_PREEMPT_SITES = {}

#: the fault sites the day-in-the-life harness must seed chaos at
#: (ISSUE/ROADMAP floor for the lifecycle run — the sites a real day
#: actually crosses: routing, scatter, the swap barrier, membership,
#: elastic block transfer)
DAY_IN_LIFE_REQUIRED_SITES = (
    "serve.route",
    "serve.replica_scatter",
    "serve.fleet_swap_barrier",
    "multihost.membership",
    "io.block_transfer",
)


def _chaos_test_sources():
    """filename -> source for every test module that may exercise chaos."""
    out = {}
    for name in sorted(os.listdir(TESTS_DIR)):
        if not name.endswith(".py") or name in _REGISTRY_ONLY:
            continue
        with open(os.path.join(TESTS_DIR, name)) as f:
            out[name] = f.read()
    return out


def _fault_site_pattern(site):
    # a quoted site name followed by a closing quote (FaultSpec/inject
    # call) or a grammar separator (the PHOTON_FAULTS env spec)
    return re.compile(r"[\"']" + re.escape(site) + r"[\"':@,]")


def _preempt_site_pattern(site):
    # a quoted bare site (install_plan key, .site assertion) or the
    # PHOTON_PREEMPT_AT "site:N" grammar
    return re.compile(r"[\"']" + re.escape(site) + r"(:\d+)?[\"']")


def test_every_fault_site_has_a_chaos_test():
    sources = _chaos_test_sources()
    uncovered = []
    for site in sorted(FAULT_SITES):
        if site in EXEMPT_FAULT_SITES:
            continue
        pat = _fault_site_pattern(site)
        if not any(pat.search(src) for src in sources.values()):
            uncovered.append(site)
    assert not uncovered, (
        f"fault sites registered but never exercised by any chaos test: "
        f"{uncovered} — aim a FaultSpec/PHOTON_FAULTS at each, or record "
        "a reasoned exemption in EXEMPT_FAULT_SITES"
    )


def test_every_preempt_site_has_a_chaos_test():
    sources = _chaos_test_sources()
    uncovered = []
    for site in PREEMPT_SITES:
        if site in EXEMPT_PREEMPT_SITES:
            continue
        pat = _preempt_site_pattern(site)
        if not any(pat.search(src) for src in sources.values()):
            uncovered.append(site)
    assert not uncovered, (
        f"preemption sites registered but never exercised by any test: "
        f"{uncovered} — aim a PHOTON_PREEMPT_AT plan at each, or record "
        "a reasoned exemption in EXEMPT_PREEMPT_SITES"
    )


def test_exemptions_name_real_sites_and_are_not_stale():
    """An exemption must (a) name a registered site and (b) still be
    NEEDED — a site that is exempt AND covered is a stale entry hiding
    future regressions."""
    unknown = [s for s in EXEMPT_FAULT_SITES if s not in FAULT_SITES]
    unknown += [s for s in EXEMPT_PREEMPT_SITES if s not in PREEMPT_SITES]
    assert not unknown, f"exemptions name unregistered sites: {unknown}"
    sources = _chaos_test_sources()
    stale = [
        site for site in EXEMPT_FAULT_SITES
        if any(_fault_site_pattern(site).search(s) for s in sources.values())
    ]
    stale += [
        site for site in EXEMPT_PREEMPT_SITES
        if any(
            _preempt_site_pattern(site).search(s) for s in sources.values()
        )
    ]
    assert not stale, (
        f"exempted sites are ALSO covered by tests — remove the stale "
        f"exemptions: {stale}"
    )


def test_day_in_life_seeds_chaos_at_the_required_sites():
    """The lifecycle harness must seed chaos at every site a real day
    crosses — the floor is pinned so a refactor cannot quietly drop one
    of the arms."""
    with open(os.path.join(REPO_ROOT, "tools", "day_in_life.py")) as f:
        src = f.read()
    missing = [
        site for site in DAY_IN_LIFE_REQUIRED_SITES
        if not _fault_site_pattern(site).search(src)
    ]
    assert not missing, (
        f"tools/day_in_life.py no longer seeds chaos at {missing}"
    )


def test_required_day_sites_are_registered():
    missing = [
        s for s in DAY_IN_LIFE_REQUIRED_SITES if s not in FAULT_SITES
    ]
    assert not missing, f"required day sites not in FAULT_SITES: {missing}"
