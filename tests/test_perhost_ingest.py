"""Per-host ingest + collective shuffle (VERDICT r3 next-round #4).

Single-process, 8 virtual devices: the shuffle's device all_to_all and the
slab build run exactly as they do multi-host (the 2-process harness in
test_multihost.py adds the cross-process layer + the memory-scaling assert).

The sharded-vs-unsharded equivalence tests here are the mandated
compensating control for check_vma=False on the PerHostRandomEffectSolver
shard_map (VERDICT r3 weak #5).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from game_test_utils import make_glmix_data

from photon_ml_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_ml_tpu.data.game import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.parallel.mesh import MeshContext, data_mesh
from photon_ml_tpu.parallel import shuffle as sh
from photon_ml_tpu.parallel.perhost_ingest import (
    HostRows,
    PerHostRandomEffectSolver,
    _unpack_u64,
    per_host_re_dataset,
)
from photon_ml_tpu.types import OptimizerType, TaskType


def _host_rows_from_game(data, lo, hi, shard="per_user", id_type="userId"):
    """Fake one host's file decode: rows [lo, hi) of a GameData in global
    sparse padded form (what the per-partition Avro decode produces)."""
    feats = data.shards[shard]
    nnz = np.diff(feats.indptr)[lo:hi]
    k = max(int(nnz.max()) if len(nnz) else 1, 1)
    n = hi - lo
    fi = np.full((n, k), -1, np.int32)
    fv = np.zeros((n, k), np.float32)
    for r in range(n):
        s, e = feats.indptr[lo + r], feats.indptr[lo + r + 1]
        fi[r, : e - s] = feats.indices[s:e]
        fv[r, : e - s] = feats.values[s:e]
    vocab = data.id_vocabs[id_type]
    return HostRows(
        entity_raw_ids=[vocab[i] for i in data.ids[id_type][lo:hi]],
        row_index=np.arange(lo, hi, dtype=np.int64),
        labels=data.response[lo:hi].astype(np.float32),
        weights=data.weight[lo:hi].astype(np.float32),
        offsets=data.offset[lo:hi].astype(np.float32),
        feat_idx=fi,
        feat_val=fv,
        global_dim=feats.dim,
    )


@pytest.fixture(scope="module")
def glmix():
    rng = np.random.default_rng(99)
    data, _ = make_glmix_data(
        rng, num_users=30, rows_per_user_range=(6, 18), d_fixed=4, d_random=3
    )
    return data


@pytest.fixture(scope="module")
def ctx():
    return MeshContext(data_mesh())


class TestShufflePrimitives:
    def test_stable_keys_and_priority_are_process_independent(self):
        ids = [f"user-{i}" for i in range(50)]
        k1 = sh.stable_entity_keys(ids)
        k2 = sh.stable_entity_keys(list(ids))
        np.testing.assert_array_equal(k1, k2)
        assert len(np.unique(k1)) == 50
        p = sh.stable_row_priority(k1, np.arange(50, dtype=np.int64))
        # priorities must differ per row and be reproducible
        assert len(np.unique(p)) == 50
        np.testing.assert_array_equal(
            p, sh.stable_row_priority(k1, np.arange(50, dtype=np.int64))
        )

    def test_stable_key_is_not_crc_linear(self):
        # regression: with the old dual-CRC32 key, these two same-length ids
        # collided in the full 64-bit key (CRC32 linearity makes the salted
        # second stream collide whenever the first does). blake2b must keep
        # them distinct, and same-length ids must be full-width hashed.
        assert sh.stable_entity_key("id0009685295") != sh.stable_entity_key(
            "id0012060020"
        )
        ids = [f"e{i:012d}" for i in range(200_000)]
        assert len(np.unique(sh.stable_entity_keys(ids))) == 200_000

    def test_balanced_owner_load_spread(self):
        rng = np.random.default_rng(3)
        counts = rng.integers(0, 100, size=256).astype(np.int64)
        owners = sh.balanced_bucket_owners(counts, 8)
        loads = np.bincount(owners, weights=counts, minlength=8)
        assert loads.max() - loads.min() <= counts.max()

    def test_collective_sum_max_single_process(self, ctx):
        v = np.arange(10, dtype=np.int64)
        np.testing.assert_array_equal(sh.collective_sum(v, ctx, 1), v)
        np.testing.assert_array_equal(sh.collective_max(v, ctx, 1), v)

    def test_collective_single_process_never_dispatches(self, ctx, monkeypatch):
        """Single-process, the local value IS the reduction — computed
        host-side, so a dead backend (the r5 UNAVAILABLE wedge) cannot
        raise out of per_host_re_dataset's metadata exchange."""

        def boom(*a, **k):
            raise RuntimeError("UNAVAILABLE: device client is wedged")

        monkeypatch.setattr(jax, "make_array_from_process_local_data", boom)
        v = np.asarray([7, -3, 12], np.int64)
        np.testing.assert_array_equal(sh.collective_sum(v, ctx, 1), v)
        np.testing.assert_array_equal(sh.collective_max(v, ctx, 1), v)

    def test_collective_degrades_with_warning_when_backend_dies(
        self, ctx, monkeypatch, caplog
    ):
        """A backend failure under a single-process runtime degrades to the
        local value with a logged warning (multi-host would desynchronize,
        but jax.process_count()==1 here means no other host is waiting)."""
        import logging

        def boom(*a, **k):
            raise RuntimeError("UNAVAILABLE: device client is wedged")

        monkeypatch.setattr(jax, "make_array_from_process_local_data", boom)
        v = np.asarray([5.0, -1.0], np.float32)
        with caplog.at_level(logging.WARNING):
            out = sh.collective_max(v, ctx, 2)  # claims 2 processes
        np.testing.assert_array_equal(out, v)
        assert any("degraded" in r.message for r in caplog.records)

    def test_exchange_routes_every_row_to_its_destination(self, ctx):
        rng = np.random.default_rng(5)
        n = 500
        dest = rng.integers(0, ctx.num_devices, size=n).astype(np.int64)
        ints = np.stack([np.arange(n), dest], axis=1).astype(np.int64)
        flts = rng.normal(size=(n, 3)).astype(np.float32)
        ex = sh.exchange_rows(dest, ints, flts, ctx, 1, 0)
        got_rows = np.concatenate([b[:, 0] for b in ex.int_rows])
        assert sorted(got_rows.tolist()) == list(range(n))  # nothing lost
        for d, (bi, bf) in enumerate(zip(ex.int_rows, ex.float_rows)):
            np.testing.assert_array_equal(bi[:, 1], d)  # landed at its dest
            # float payload rode along with its row
            for row, f in zip(bi[:, 0], bf):
                np.testing.assert_allclose(f, flts[row], rtol=1e-6)


class TestPerHostIngestEquivalence:
    def test_matches_unsharded_coordinate(self, glmix, ctx):
        """One 'host' (single process) through the full shuffle+slab path
        must reproduce the plain RandomEffectCoordinate fit: same per-entity
        coefficients (matched via entity keys) and identical global scores."""
        data = glmix
        rows = _host_rows_from_game(data, 0, data.num_rows)
        sd = per_host_re_dataset(rows, ctx)
        assert sd.num_entities == len(data.id_vocabs["userId"])

        cfg = OptimizerConfig(max_iterations=30, tolerance=1e-9)
        reg = RegularizationContext.l2(0.3)
        solver = PerHostRandomEffectSolver(
            sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg, ctx
        )
        resid = jnp.zeros((data.num_rows,), jnp.float32)
        w, _ = solver.update(resid, solver.initial_coefficients())
        scores = solver.score(w)

        # oracle: the single-device entity-major path on the same data
        re_ds = build_random_effect_dataset(
            data, RandomEffectDataConfig("userId", "per_user")
        )
        local = RandomEffectCoordinate(
            re_ds, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg
        )
        w_ref, _ = local.update(resid, local.initial_coefficients())
        ref_scores = local.score(w_ref)

        # match entities by raw-id key; compare coefficients in GLOBAL space
        # (local column orders differ between the two builds)
        from photon_ml_tpu.algorithm.random_effect import global_coefficients
        from photon_ml_tpu.parallel.perhost_ingest import _unpack_u64

        w_ref_glob = np.asarray(global_coefficients(re_ds, w_ref))
        mask = np.asarray(sd.entity_mask)
        keys = np.asarray(sd.entity_keys)
        got_keys = _unpack_u64(keys[mask, 0], keys[mask, 1])
        w_np = np.asarray(w) [mask]
        l2g = np.asarray(sd.local_to_global)[mask]
        vocab = data.id_vocabs["userId"]
        # the reference build permutes entities into balanced tensor order;
        # recover each entity id's tensor position from a row it owns
        ids = data.ids["userId"]
        entity_pos = np.asarray(re_ds.entity_pos)
        pos_of = {}
        for r in range(data.num_rows):
            pos_of.setdefault(int(ids[r]), int(entity_pos[r]))
        ref_key_of = {
            sh.stable_entity_key(v): pos_of[e] for e, v in enumerate(vocab)
        }
        for i, key in enumerate(got_keys):
            e = ref_key_of[int(key)]
            dense = np.zeros(sd.global_dim, np.float32)
            valid = l2g[i] >= 0
            dense[l2g[i][valid]] = w_np[i][valid]
            np.testing.assert_allclose(
                dense, w_ref_glob[e], rtol=5e-4, atol=5e-5
            )
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(ref_scores), rtol=5e-4, atol=5e-4
        )

    def test_active_cap_partitioning_invariance(self, glmix, ctx):
        """With a reservoir cap, the fitted model must be IDENTICAL whatever
        host/file split ingested the rows — the determinism the reference's
        zipWithUniqueId reservoir lacks (RandomEffectDataSet.scala:281-285).
        Single-process proxy: permute the row order (as a different file
        assignment would) and check bit-identical slabs."""
        data = glmix
        rows_a = _host_rows_from_game(data, 0, data.num_rows)
        sd_a = per_host_re_dataset(rows_a, ctx, active_upper_bound=5)

        perm = np.random.default_rng(1).permutation(data.num_rows)
        rows_b = HostRows(
            entity_raw_ids=[rows_a.entity_raw_ids[i] for i in perm],
            row_index=rows_a.row_index[perm],
            labels=rows_a.labels[perm],
            weights=rows_a.weights[perm],
            offsets=rows_a.offsets[perm],
            feat_idx=rows_a.feat_idx[perm],
            feat_val=rows_a.feat_val[perm],
            global_dim=rows_a.global_dim,
        )
        sd_b = per_host_re_dataset(rows_b, ctx, active_upper_bound=5)
        for f in ("row_index", "x", "labels", "weights", "base_offsets",
                  "local_to_global", "entity_keys", "score_row_index"):
            np.testing.assert_array_equal(
                np.asarray(getattr(sd_a, f)), np.asarray(getattr(sd_b, f)), err_msg=f
            )

    def test_cap_rescales_weights(self, glmix, ctx):
        data = glmix
        rows = _host_rows_from_game(data, 0, data.num_rows)
        cap = 4
        sd = per_host_re_dataset(rows, ctx, active_upper_bound=cap)
        # every entity keeps at most cap active rows, and the kept weights of
        # a capped entity sum to ~ the entity's original total weight
        ri = np.asarray(sd.row_index)
        w = np.asarray(sd.weights)
        keys = np.asarray(sd.entity_keys)
        mask = np.asarray(sd.entity_mask)
        ids = data.ids["userId"]
        from photon_ml_tpu.parallel.perhost_ingest import _unpack_u64

        key_to_entity = {
            sh.stable_entity_key(v): e for e, v in enumerate(data.id_vocabs["userId"])
        }
        for lane in np.nonzero(mask)[0]:
            n_active = int((ri[lane] >= 0).sum())
            assert n_active <= cap
            e = key_to_entity[int(_unpack_u64(keys[lane, :1], keys[lane, 1:2])[0])]
            total = data.weight[ids == e].sum()
            np.testing.assert_allclose(w[lane].sum(), total, rtol=1e-4)


class TestAvroPerHostDecode:
    def test_avro_host_rows_match_direct_build(self, glmix, ctx, tmp_path):
        """host_rows_from_avro over a host's file subset -> the same slabs
        as the direct in-memory HostRows (partitioning invariance across
        BOTH the file assignment and the decode path)."""
        import os

        from photon_ml_tpu.io import avro as avro_io
        from photon_ml_tpu.io import schemas
        from photon_ml_tpu.io.index_map import IndexMap
        from photon_ml_tpu.parallel.perhost_ingest import host_rows_from_avro

        data = glmix
        feats = data.shards["per_user"]
        vocab = data.id_vocabs["userId"]
        schema = {
            "name": "PerHostAvro", "type": "record", "namespace": "t",
            "fields": [
                {"name": "label", "type": "double"},
                {"name": "userFeatures",
                 "type": {"type": "array", "items": schemas.FEATURE}},
                {"name": "metadataMap",
                 "type": ["null", {"type": "map", "values": "string"}],
                 "default": None},
            ],
        }
        # split rows into 3 part files (the global sorted file list)
        n = data.num_rows
        bounds = [0, n // 3, 2 * (n // 3), n]
        for p in range(3):
            lo, hi = bounds[p], bounds[p + 1]

            def records():
                for r in range(lo, hi):
                    s, e = feats.indptr[r], feats.indptr[r + 1]
                    yield {
                        "label": float(data.response[r]),
                        "userFeatures": [
                            {"name": f"u{j}", "term": "", "value": float(v)}
                            for j, v in zip(feats.indices[s:e], feats.values[s:e])
                        ],
                        "metadataMap": {"userId": vocab[data.ids["userId"][r]]},
                    }

            avro_io.write_container(
                str(tmp_path / f"part-{p}.avro"), records(), schema
            )
        # index map matching the in-memory feature space (u<j> -> j), no
        # intercept so dims align with the raw CSR
        imap = IndexMap(
            {f"u{j}\x01": j for j in range(feats.dim)},
            [f"u{j}\x01" for j in range(feats.dim)],
        )
        rows_avro = host_rows_from_avro(
            [str(tmp_path / f"part-{p}.avro") for p in range(3)],
            [0, 1, 2],
            imap, "userId", "per_user", ["userFeatures"],
            intercept=False, row_stride=1 << 22,
        )
        assert rows_avro.num_rows == n and rows_avro.global_dim == feats.dim
        # strided ids are sparse: the scoring-capable build must refuse them
        # (silent out-of-bounds scatter drop otherwise), slab-build-only is
        # allowed, and densify_row_ids recovers the dense [0, N) layout
        from photon_ml_tpu.parallel.perhost_ingest import densify_row_ids

        with pytest.raises(ValueError, match="dense"):
            per_host_re_dataset(rows_avro, ctx)
        sd_sparse = per_host_re_dataset(rows_avro, ctx, slab_build_only=True)
        assert not sd_sparse.row_ids_dense
        rows_dense = densify_row_ids(rows_avro, 1 << 22, ctx)
        # files are in global order and rows contiguous, so dense ids are
        # exactly the original row order
        np.testing.assert_array_equal(rows_dense.row_index, np.arange(n))
        sd_avro = per_host_re_dataset(rows_dense, ctx)
        assert sd_avro.row_ids_dense

        rows_mem = _host_rows_from_game(data, 0, n)
        # identical rows under identical (densified) GLOBAL ids -> same
        # entity grouping and training tensors
        sd_mem = per_host_re_dataset(rows_mem, ctx)
        np.testing.assert_array_equal(
            np.asarray(sd_avro.entity_keys), np.asarray(sd_mem.entity_keys)
        )
        np.testing.assert_array_equal(
            np.asarray(sd_avro.local_to_global), np.asarray(sd_mem.local_to_global)
        )
        # identical dense row ids -> identical priorities -> the slabs match
        # exactly, row order included
        np.testing.assert_array_equal(
            np.asarray(sd_avro.row_index), np.asarray(sd_mem.row_index)
        )
        np.testing.assert_allclose(
            np.asarray(sd_avro.x), np.asarray(sd_mem.x), rtol=1e-6
        )


class TestPerHostCoordinateDescent:
    @pytest.mark.slow  # ~10s: the perhost-coordinate-in-CD contract stays tier-1 via test_perhost_composes_with_fused_cycle and TestBucketedPerHost::test_bucketed_in_coordinate_descent
    def test_full_descent_with_perhost_coordinate(self, glmix, ctx):
        """PerHostRandomEffectSolver as a CoordinateDescent coordinate:
        fixed + per-host RE descent must match the plain (unsharded)
        two-coordinate descent — objectives AND final scores."""
        import jax.numpy as jnp

        from photon_ml_tpu.algorithm import (
            CoordinateDescent,
            FixedEffectCoordinate,
        )
        from photon_ml_tpu.data.game import build_fixed_effect_batch
        from photon_ml_tpu.ops import losses
        from photon_ml_tpu.optim.problem import GLMOptimizationProblem

        data = glmix
        labels = jnp.asarray(data.response)
        loss_fn = lambda s: jnp.sum(losses.logistic.loss(s, labels))
        cfg = OptimizerConfig(max_iterations=25, tolerance=1e-9)
        reg = RegularizationContext.l2(0.3)

        def fixed():
            return FixedEffectCoordinate(
                build_fixed_effect_batch(data, "global", dense=True),
                GLMOptimizationProblem(
                    TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg,
                    RegularizationContext.l2(0.05),
                ),
            )

        rows = _host_rows_from_game(data, 0, data.num_rows)
        sd = per_host_re_dataset(rows, ctx)
        perhost = PerHostRandomEffectSolver(
            sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg, ctx
        )
        cd_sharded = CoordinateDescent({"fixed": fixed(), "re": perhost}, loss_fn)
        r_sharded = cd_sharded.run(num_iterations=2, num_rows=data.num_rows)

        re_ds = build_random_effect_dataset(
            data, RandomEffectDataConfig("userId", "per_user")
        )
        plain = RandomEffectCoordinate(
            re_ds, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg
        )
        cd_plain = CoordinateDescent({"fixed": fixed(), "re": plain}, loss_fn)
        r_plain = cd_plain.run(num_iterations=2, num_rows=data.num_rows)

        np.testing.assert_allclose(
            np.asarray(r_sharded.objective_history),
            np.asarray(r_plain.objective_history),
            rtol=5e-4,
        )
        np.testing.assert_allclose(
            np.asarray(r_sharded.total_scores),
            np.asarray(r_plain.total_scores),
            rtol=5e-3, atol=5e-4,
        )


def test_perhost_composes_with_fused_cycle(glmix, ctx):
    """Single-process, the per-host coordinate's arrays are addressable, so
    it composes with the fused-cycle descent; results match unfused."""
    import jax.numpy as jnp

    from photon_ml_tpu.algorithm import CoordinateDescent
    from photon_ml_tpu.ops import losses

    data = glmix
    labels = jnp.asarray(data.response)
    loss_fn = lambda s: jnp.sum(losses.logistic.loss(s, labels))
    cfg = OptimizerConfig(max_iterations=15, tolerance=1e-8)
    reg = RegularizationContext.l2(0.3)
    rows = _host_rows_from_game(data, 0, data.num_rows)
    sd = per_host_re_dataset(rows, ctx)

    def solver():
        return PerHostRandomEffectSolver(
            sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg, ctx
        )

    plain = CoordinateDescent({"re": solver()}, loss_fn).run(
        num_iterations=2, num_rows=data.num_rows
    )
    fused = CoordinateDescent({"re": solver()}, loss_fn, fused_cycle=True).run(
        num_iterations=2, num_rows=data.num_rows
    )
    np.testing.assert_allclose(
        np.asarray(fused.objective_history),
        np.asarray(plain.objective_history), rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(fused.total_scores), np.asarray(plain.total_scores),
        rtol=1e-4, atol=1e-5,
    )


def test_routed_scoring_cold_entities_and_features(glmix, ctx):
    """score_routed_rows cold-start semantics (RandomEffectModel.scala:
    129-158): rows of an entity with no model score 0; features an entity
    never saw in training contribute 0."""
    data = glmix
    rows = _host_rows_from_game(data, 0, data.num_rows)
    sd = per_host_re_dataset(rows, ctx)
    cfg = OptimizerConfig(max_iterations=15, tolerance=1e-8)
    solver = PerHostRandomEffectSolver(
        sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg,
        RegularizationContext.l2(0.3), ctx,
    )
    from photon_ml_tpu.parallel.perhost_ingest import score_routed_rows

    w, _ = solver.update(
        jnp.zeros((data.num_rows,), jnp.float32), solver.initial_coefficients()
    )

    d = data.shards["per_user"].dim
    probe = HostRows(
        entity_raw_ids=[
            data.id_vocabs["userId"][0],   # known entity, known feature
            "never-seen-entity",            # cold entity
            data.id_vocabs["userId"][0],   # known entity, UNSEEN feature
        ],
        row_index=np.asarray([0, 1, 2], np.int64),
        labels=np.zeros(3, np.float32),
        weights=np.ones(3, np.float32),
        offsets=np.zeros(3, np.float32),
        # row 2 probes feature d — beyond every training feature, so it
        # appears in no entity's local map
        feat_idx=np.asarray([[0], [0], [d]], np.int32),
        feat_val=np.ones((3, 1), np.float32),
        global_dim=d + 1,  # widen so the unseen feature index is in range
    )
    scores = score_routed_rows(sd, w, probe, 3, ctx)
    assert scores[1] == 0.0  # cold entity -> 0
    assert scores[2] == 0.0  # unseen feature -> 0
    # known entity + known feature -> exactly w[entity, local(0)]
    key0 = sh.stable_entity_key(data.id_vocabs["userId"][0])
    keys = np.asarray(sd.entity_keys)
    mask = np.asarray(sd.entity_mask)
    lanes = np.nonzero(mask)[0]
    lane = lanes[np.nonzero(
        _unpack_u64(keys[lanes, 0], keys[lanes, 1]) == key0
    )[0][0]]
    l2g = np.asarray(sd.local_to_global)[lane]
    j = int(np.nonzero(l2g == 0)[0][0])
    expected = float(np.asarray(w)[lane, j])
    assert scores[0] == pytest.approx(expected, rel=1e-5)


# ---------------------------------------------------------------------------
# size-bucketed per-host slabs (VERDICT r4 next-round #2)
# ---------------------------------------------------------------------------


def _skewed_host_rows(giant_rows=1024, singletons=400, d=3, seed=7):
    """One giant entity among singletons — the uncapped skew case the
    global-max padding blows up on."""
    rng = np.random.default_rng(seed)
    n = giant_rows + singletons
    ids = ["giant"] * giant_rows + [f"s{i}" for i in range(singletons)]
    fi = rng.integers(0, d, size=(n, 2)).astype(np.int32)
    fi[:, 1] = np.where(fi[:, 1] == fi[:, 0], (fi[:, 1] + 1) % d, fi[:, 1])
    fv = rng.normal(size=(n, 2)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    return HostRows(
        entity_raw_ids=ids,
        row_index=np.arange(n, dtype=np.int64),
        labels=y,
        weights=np.ones(n, np.float32),
        offsets=np.zeros(n, np.float32),
        feat_idx=fi,
        feat_val=fv,
        global_dim=d,
    )


class TestBucketedPerHost:
    def _solvers(self, rows, ctx, size_buckets):
        from photon_ml_tpu.parallel.perhost_ingest import (
            BucketedShardedREData,
            PerHostBucketedRandomEffectSolver,
        )

        cfg = OptimizerConfig(max_iterations=30, tolerance=1e-9)
        reg = RegularizationContext.l2(0.3)
        sd = per_host_re_dataset(rows, ctx)
        bd = per_host_re_dataset(rows, ctx, size_buckets=size_buckets)
        assert isinstance(bd, BucketedShardedREData)
        mono = PerHostRandomEffectSolver(
            sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg, ctx
        )
        buck = PerHostBucketedRandomEffectSolver(
            bd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg, ctx
        )
        return sd, bd, mono, buck

    def test_bucketed_matches_monolithic(self, glmix, ctx):
        """Multi-bucket slabs must train and score exactly like the single
        global-width slab: same entities, same scores (the compensating
        equivalence control for the bucketed solver's check_vma=False)."""
        rows = _host_rows_from_game(glmix, 0, glmix.num_rows)
        sd, bd, mono, buck = self._solvers(rows, ctx, size_buckets=4)
        assert len(bd.buckets) >= 2  # rows-per-user 6..18 spans >1 width
        assert bd.num_entities == sd.num_entities
        assert sum(b.num_entities for b in bd.buckets) == sd.num_entities

        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        w_m, _ = mono.update(resid, mono.initial_coefficients())
        s_m = mono.score(w_m)
        w_b, _ = buck.update(resid, buck.initial_coefficients())
        s_b = buck.score(w_b)
        np.testing.assert_allclose(
            np.asarray(s_b), np.asarray(s_m), rtol=5e-4, atol=5e-4
        )
        # regularization over the tuple state matches the monolithic term
        np.testing.assert_allclose(
            float(buck.regularization_term(w_b)),
            float(mono.regularization_term(w_m)),
            rtol=5e-4,
        )

    def test_skew_padding_collapses(self, ctx):
        """One 1024-row entity among 400 singletons: bucketed slab volume
        must be a small fraction of the global-max-padded volume, and the
        scores must still match the monolithic build exactly."""
        rows = _skewed_host_rows()
        sd, bd, mono, buck = self._solvers(rows, ctx, size_buckets=8)

        mono_elems = int(np.prod(sd.x.shape))
        assert bd.padded_elements * 10 < mono_elems, (
            f"bucketed {bd.padded_elements} vs monolithic {mono_elems}"
        )
        # the widths really are per-bucket (not all global max)
        caps = sorted(b.samples_cap for b in bd.buckets)
        assert caps[0] == 1 and caps[-1] == 1024

        resid = jnp.zeros((rows.num_rows,), jnp.float32)
        w_m, _ = mono.update(resid, mono.initial_coefficients())
        w_b, _ = buck.update(resid, buck.initial_coefficients())
        np.testing.assert_allclose(
            np.asarray(buck.score(w_b)), np.asarray(mono.score(w_m)),
            rtol=5e-4, atol=5e-4,
        )

    def test_bucketed_in_coordinate_descent(self, glmix, ctx):
        """The bucketed solver is a drop-in CoordinateDescent coordinate
        (tuple-state pytree), matching the monolithic descent."""
        from photon_ml_tpu.algorithm import CoordinateDescent
        from photon_ml_tpu.ops import losses

        data = glmix
        labels = jnp.asarray(data.response)
        loss_fn = lambda s: jnp.sum(losses.logistic.loss(s, labels))
        rows = _host_rows_from_game(data, 0, data.num_rows)
        _, _, mono, buck = self._solvers(rows, ctx, size_buckets=4)

        r_m = CoordinateDescent({"re": mono}, loss_fn).run(
            num_iterations=2, num_rows=data.num_rows
        )
        r_b = CoordinateDescent({"re": buck}, loss_fn).run(
            num_iterations=2, num_rows=data.num_rows
        )
        np.testing.assert_allclose(
            np.asarray(r_b.objective_history),
            np.asarray(r_m.objective_history), rtol=5e-4,
        )
        np.testing.assert_allclose(
            np.asarray(r_b.total_scores), np.asarray(r_m.total_scores),
            rtol=5e-3, atol=5e-4,
        )

    def test_bucketed_routed_scoring_matches_device_scoring(self, glmix, ctx):
        """score_routed_rows over a bucketed build (per-bucket coefficient
        tuple) must match the device-side owner-computes scoring."""
        from photon_ml_tpu.parallel.perhost_ingest import score_routed_rows

        rows = _host_rows_from_game(glmix, 0, glmix.num_rows)
        _, bd, _, buck = self._solvers(rows, ctx, size_buckets=4)
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        w_b, _ = buck.update(resid, buck.initial_coefficients())
        device_scores = np.asarray(buck.score(w_b))
        routed = score_routed_rows(bd, w_b, rows, glmix.num_rows, ctx)
        np.testing.assert_allclose(routed, device_scores, rtol=1e-4, atol=1e-5)


class TestPerHostProjectors:
    """Projector scope of the per-host ingest (ProjectorType.scala:22-30):
    IDENTITY and RANDOM local spaces, built collectively, must agree with
    the single-device build and with each other where the optima coincide.
    The factored equivalence test here is the mandated compensating control
    for check_vma=False on the PerHostFactoredRandomEffectCoordinate
    shard_map (VERDICT r4 #10 fence)."""

    def _fit(self, sd, ctx, l2=0.3):
        cfg = OptimizerConfig(max_iterations=40, tolerance=1e-10)
        solver = PerHostRandomEffectSolver(
            sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg,
            RegularizationContext.l2(l2), ctx,
        )
        resid = jnp.zeros((sd.num_rows,), jnp.float32)
        w, _ = solver.update(resid, solver.initial_coefficients())
        return solver, w

    def test_identity_matches_index_map(self, glmix, ctx):
        """IDENTITY and INDEX_MAP solve the same optimization in different
        bases: unseen features get zero gradient and L2 pulls them to 0, so
        the optima (and scores) coincide."""
        rows = _host_rows_from_game(glmix, 0, glmix.num_rows)
        sd_im = per_host_re_dataset(rows, ctx, projector="INDEX_MAP")
        sd_id = per_host_re_dataset(rows, ctx, projector="IDENTITY")
        assert sd_id.local_dim == rows.global_dim
        # IDENTITY lanes carry the identity local->global map
        mask = np.asarray(sd_id.entity_mask)
        l2g = np.asarray(sd_id.local_to_global)
        np.testing.assert_array_equal(
            l2g[mask], np.tile(np.arange(rows.global_dim), (mask.sum(), 1))
        )
        _, w_im = self._fit(sd_im, ctx)
        s_im = np.asarray(self._fit(sd_im, ctx)[0].score(w_im))
        solver_id, w_id = self._fit(sd_id, ctx)
        s_id = np.asarray(solver_id.score(w_id))
        np.testing.assert_allclose(s_id, s_im, rtol=5e-4, atol=5e-4)

    def test_random_matches_single_device_build(self, glmix, ctx):
        """The per-host RANDOM build with a shared matrix must reproduce the
        single-device RANDOM dataset's fit: same projected space -> same
        optimum -> same scores; back-projection through the matrix gives
        the saved global-space coefficients."""
        from photon_ml_tpu.parallel.perhost_ingest import score_routed_rows
        from photon_ml_tpu.projectors import (
            ProjectionMatrixProjector,
            gaussian_random_projection_matrix,
        )

        data = glmix
        rows = _host_rows_from_game(data, 0, data.num_rows)
        k_dim = 4
        pm = gaussian_random_projection_matrix(
            k_dim, rows.global_dim, keep_intercept=True, seed=77
        )
        sd = per_host_re_dataset(
            rows, ctx, projector="RANDOM", projection_matrix=pm
        )
        assert sd.local_dim == pm.shape[0]
        solver, w = self._fit(sd, ctx)
        scores = np.asarray(solver.score(w))

        cfg_ds = RandomEffectDataConfig(
            "userId", "per_user", projector="RANDOM",
            random_projection_dim=k_dim, seed=77,
        )
        re_ds = build_random_effect_dataset(
            data, cfg_ds, projector=ProjectionMatrixProjector(jnp.asarray(pm))
        )
        opt_cfg = OptimizerConfig(max_iterations=40, tolerance=1e-10)
        reg = RegularizationContext.l2(0.3)
        local = RandomEffectCoordinate(
            re_ds, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
            opt_cfg, reg,
        )
        w_ref, _ = local.update(
            jnp.zeros((data.num_rows,), jnp.float32),
            local.initial_coefficients(),
        )
        ref_scores = np.asarray(local.score(w_ref))
        np.testing.assert_allclose(scores, ref_scores, rtol=1e-3, atol=5e-4)
        # routed scoring projects through the shared matrix on the host path
        routed = score_routed_rows(sd, w, rows, data.num_rows, ctx)
        np.testing.assert_allclose(routed, scores, rtol=1e-4, atol=1e-5)

    def test_random_composes_with_buckets(self, glmix, ctx):
        """size_buckets>1 + RANDOM: every bucket's slab lives in the shared
        projected space and the bucketed solver scores identically to the
        monolithic RANDOM solver."""
        from photon_ml_tpu.parallel.perhost_ingest import (
            PerHostBucketedRandomEffectSolver,
        )

        rows = _host_rows_from_game(glmix, 0, glmix.num_rows)
        kwargs = dict(projector="RANDOM", projection_dim=4,
                      projection_seed=13)
        sd = per_host_re_dataset(rows, ctx, **kwargs)
        bd = per_host_re_dataset(rows, ctx, size_buckets=4, **kwargs)
        assert all(b.local_dim == sd.local_dim for b in bd.buckets)
        cfg = OptimizerConfig(max_iterations=40, tolerance=1e-10)
        reg = RegularizationContext.l2(0.3)
        mono = PerHostRandomEffectSolver(
            sd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg, ctx
        )
        buck = PerHostBucketedRandomEffectSolver(
            bd, TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS, cfg, reg, ctx
        )
        resid = jnp.zeros((glmix.num_rows,), jnp.float32)
        w_m, _ = mono.update(resid, mono.initial_coefficients())
        w_b, _ = buck.update(resid, buck.initial_coefficients())
        np.testing.assert_allclose(
            np.asarray(buck.score(w_b)), np.asarray(mono.score(w_m)),
            rtol=5e-4, atol=5e-4,
        )

    @pytest.mark.slow  # ~14s: the factored-distributed contract stays tier-1 via test_parallel.py test_distributed_factored_matches_local and the bucket composition via test_random_composes_with_buckets here
    def test_factored_perhost_matches_single_device(self, glmix, ctx):
        """PerHostFactoredRandomEffectCoordinate (entity-sharded v, psum'd
        latent refit) must reproduce the single-device
        FactoredRandomEffectCoordinate on an IDENTITY dataset: same scores
        and same latent matrix trajectory. THE compensating equivalence
        test for its check_vma=False shard_map."""
        from photon_ml_tpu.algorithm.factored_random_effect import (
            FactoredRandomEffectCoordinate,
            MFOptimizationConfig,
        )
        from photon_ml_tpu.parallel.perhost_factored import (
            PerHostFactoredRandomEffectCoordinate,
        )

        data = glmix
        rows = _host_rows_from_game(data, 0, data.num_rows)
        sd = per_host_re_dataset(rows, ctx, projector="IDENTITY")
        mf = MFOptimizationConfig(2, 3)
        cfg = OptimizerConfig(max_iterations=25, tolerance=1e-10)
        reg = RegularizationContext.l2(0.5)
        fac = PerHostFactoredRandomEffectCoordinate(
            sd, TaskType.LOGISTIC_REGRESSION, mf_config=mf,
            re_optimizer_config=cfg, re_regularization=reg,
            latent_optimizer_config=cfg, latent_regularization=reg, ctx=ctx,
        )
        resid = jnp.zeros((data.num_rows,), jnp.float32)
        st, _ = fac.update(resid, fac.initial_coefficients())
        scores = np.asarray(fac.score(st))

        re_ds = build_random_effect_dataset(
            data, RandomEffectDataConfig("userId", "per_user",
                                         projector="IDENTITY")
        )
        oracle = FactoredRandomEffectCoordinate(
            re_ds, TaskType.LOGISTIC_REGRESSION, mf_config=mf,
            re_optimizer_config=cfg, re_regularization=reg,
            latent_optimizer_config=cfg, latent_regularization=reg,
        )
        st_ref, _ = oracle.update(resid, oracle.initial_coefficients())
        ref_scores = np.asarray(oracle.score(st_ref))
        np.testing.assert_allclose(scores, ref_scores, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(
            np.asarray(st.matrix), np.asarray(st_ref.matrix),
            rtol=2e-3, atol=2e-3,
        )
        # flattened coefficients W = V M land on the save path per host
        W = np.asarray(fac.random_effect_coefficients(st))
        assert W.shape == (np.asarray(sd.entity_mask).shape[0], sd.global_dim)
        factors = fac.latent_factors_by_raw_id(st)
        assert len(factors) == sd.num_entities
