"""The survivable production loop: relaunch-time re-plan, plan-versioned
fixed-effect chunk ownership, multihost delta-retrain agreement, and the
warm-start builders that feed them.

Fast single-process coverage drives the REAL production code paths with
the same identity-routing trick as test_elastic_reshard (a fleet of
per-physical-host manifests built from the full dataset, plus the
single-process collective passthrough for the driver's agreement votes).
The 2-process supervised-relaunch arm — kill a host, relaunch ONE
survivor, re-plan, delta-transfer, resume bitwise — lives in
tests/relaunch_replan_worker.py (slow-marked)."""

import os
import socket
import subprocess
import sys
import types

import numpy as np
import pytest

import jax.numpy as jnp

from game_test_utils import make_glmix_data

from photon_ml_tpu.data.game import RandomEffectDataConfig
from photon_ml_tpu.io import model_io
from photon_ml_tpu.io.index_map import IndexMap, feature_key
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.parallel.elastic import (
    ElasticError,
    FleetMembership,
    relaunch_replan,
)
from photon_ml_tpu.parallel.perhost_ingest import (
    HostRows,
    csr_to_padded,
    host_file_share,
)
from photon_ml_tpu.parallel.perhost_streaming import (
    EntityShardPlan,
    PerHostSpilledREState,
    _PLAN_BLOCK_OF,
    _PLAN_OWNERS,
    attach_fe_chunks_to_sidecars,
    build_perhost_streaming_manifest,
    load_plan_sidecars,
    write_plan_sidecars,
)
from photon_ml_tpu.resilience import faults
from photon_ml_tpu.retrain.manifest import CoordinateRecord, RetrainManifest
from photon_ml_tpu.types import OptimizerType, TaskType

import photon_ml_tpu.cli.game_multihost_driver as mhd

pytestmark = pytest.mark.elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "relaunch_replan_worker.py")

RE_CFG = RandomEffectDataConfig("userId", "per_user")
RE_OPT = OptimizerConfig(max_iterations=6, tolerance=1e-8)
RE_REG = RegularizationContext.l2(0.2)
BLOCK_ENTITIES = 8
LADDER = "8:2.0"
TASK = TaskType.LOGISTIC_REGRESSION


def _sorted_vocab_data(rng=None, **kw):
    rng = rng or np.random.default_rng(41)
    data, _ = make_glmix_data(rng, **kw)
    vocab = data.id_vocabs["userId"]
    order = np.argsort(np.asarray(vocab, dtype=object))
    remap = np.empty(len(vocab), np.int64)
    remap[order] = np.arange(len(vocab))
    data.ids["userId"] = remap[data.ids["userId"]].astype(np.int32)
    data.id_vocabs["userId"] = [vocab[i] for i in order]
    return data


def _host_rows(data):
    feats = data.shards["per_user"]
    fi, fv = csr_to_padded(feats, data.num_rows)
    vocab = data.id_vocabs["userId"]
    return HostRows(
        entity_raw_ids=[vocab[i] for i in data.ids["userId"]],
        row_index=np.arange(data.num_rows, dtype=np.int64),
        labels=data.response.astype(np.float32),
        weights=data.weight.astype(np.float32),
        offsets=data.offset.astype(np.float32),
        feat_idx=fi, feat_val=fv, global_dim=feats.dim,
    )


@pytest.fixture(scope="module")
def glmix():
    return _sorted_vocab_data(
        num_users=40, rows_per_user_range=(3, 12), d_fixed=4, d_random=3
    )


def _build_cohort(data, coord_root, membership):
    """One committed ``process-<pid>`` manifest per physical host of the
    membership (identity routing at num_processes=1; block content is
    host-invariant — the PR 9 foundation test_elastic_reshard pins)."""
    rows = _host_rows(data)
    manifests = {}
    for p in sorted(set(membership.binding.values())):
        manifests[p] = build_perhost_streaming_manifest(
            rows, RE_CFG, os.path.join(coord_root, f"process-{p}"),
            None, 1, p, block_entities=BLOCK_ENTITIES, bucketer=LADDER,
            shared_vocab=data.id_vocabs["userId"],
            membership=FleetMembership(
                membership.version, list(membership.hosts),
                dict(membership.binding),
            ),
        )
    return manifests


def _two_host_membership():
    return FleetMembership(1, [0, 1], {0: 0, 1: 1})


class _Log:
    def __init__(self):
        self.infos, self.warns = [], []

    def info(self, msg):
        self.infos.append(str(msg))

    def warn(self, msg):
        self.warns.append(str(msg))


# ---------------------------------------------------------------------------
# fixed-effect chunk ownership rides in the versioned plan
# ---------------------------------------------------------------------------


class TestFeChunkPlan:
    @pytest.fixture()
    def plan_dir(self, glmix, tmp_path):
        _build_cohort(glmix, str(tmp_path / "re"), _two_host_membership())
        return str(tmp_path / "re" / "process-0")

    def test_plan_without_fe_ownership_refuses(self, plan_dir):
        plan = EntityShardPlan.from_sidecars(plan_dir)
        assert plan.fe_chunk_owners is None
        with pytest.raises(ValueError, match="no FE chunk ownership"):
            plan.owned_fe_chunks(0)

    def test_explicit_owners_partition_and_validate(self, plan_dir):
        plan = EntityShardPlan.from_sidecars(plan_dir)
        fe = plan.with_fe_chunks([5, 3, 2], owners=[0, 1, 0])
        assert fe.owned_fe_chunks(0) == [0, 2]
        assert fe.owned_fe_chunks(1) == [1]
        with pytest.raises(ValueError, match="disagree on the chunk count"):
            plan.with_fe_chunks([5, 3, 2], owners=[0, 1])

    def test_default_owners_cover_every_chunk(self, plan_dir):
        plan = EntityShardPlan.from_sidecars(plan_dir)
        fe = plan.with_fe_chunks([4, 4, 4, 4, 4])
        covered = sorted(
            c for h in plan.host_list() for c in fe.owned_fe_chunks(h)
        )
        assert covered == list(range(5))

    def test_sidecar_round_trip_and_replan_rebase(self, plan_dir):
        attach_fe_chunks_to_sidecars(plan_dir, [0, 1, 0, 1], [9, 7, 5, 3])
        plan = EntityShardPlan.from_sidecars(plan_dir)
        assert plan.fe_chunk_owners.tolist() == [0, 1, 0, 1]
        assert plan.fe_chunk_costs.tolist() == [9, 7, 5, 3]
        # the RE routing arrays are untouched by the FE attach
        meta, owners, block_of = load_plan_sidecars(plan_dir)
        assert int(meta["version"]) == plan.version
        # survivor re-plan: FE chunks re-base onto the new host set just
        # like entity blocks — every chunk lands on a live owner
        survivor = plan.replan([0])
        assert survivor.version == plan.version + 1
        assert sorted(survivor.owned_fe_chunks(0)) == [0, 1, 2, 3]
        grown = plan.replan([0, 1, 2])
        covered = sorted(
            c for h in (0, 1, 2) for c in grown.owned_fe_chunks(h)
        )
        assert covered == [0, 1, 2, 3]

    def test_attach_refuses_pre_versioned_sidecars(self, tmp_path):
        d = str(tmp_path / "pre")
        os.makedirs(d)
        np.save(os.path.join(d, "tmp.npy"), np.zeros(3, np.int32))
        os.replace(os.path.join(d, "tmp.npy"), os.path.join(d, _PLAN_OWNERS))
        np.save(os.path.join(d, "tmp.npy"), np.zeros(5, np.int32))
        os.replace(os.path.join(d, "tmp.npy"),
                   os.path.join(d, _PLAN_BLOCK_OF))
        with pytest.raises(ValueError, match="pre-versioned"):
            attach_fe_chunks_to_sidecars(d, [0], [1])


# ---------------------------------------------------------------------------
# relaunch-time re-plan (the supervised-relaunch seam, unit level)
# ---------------------------------------------------------------------------


class TestRelaunchReplan:
    def _seed_state(self, manifests, tmp_path):
        """Fabricated spill roots: one epoch dir per host holding that
        host's owned blocks' coefficient files (value = host id + 1)."""
        roots = {}
        for p, man in manifests.items():
            root = str(tmp_path / f"spill-{p}")
            os.makedirs(os.path.join(root, "epoch-0"))
            for b, gid in zip(man.blocks, man.global_block_ids):
                np.save(
                    os.path.join(root, "epoch-0", f"coefs-g{gid:05d}.npy"),
                    np.full((b["num_entities"], b["local_dim"]),
                            float(p + 1), np.float32),
                )
            roots[p] = root
        return roots

    def test_survivor_adopts_only_moved_blocks(self, glmix, tmp_path):
        coord_root = str(tmp_path / "re")
        manifests = _build_cohort(glmix, coord_root, _two_host_membership())
        attach_fe_chunks_to_sidecars(
            manifests[0].dir, [0, 1, 0], [10, 8, 6]
        )
        roots = self._seed_state(manifests, tmp_path)
        res = relaunch_replan(
            coord_root, 0, 1,
            state_root_pairs=[({0: roots[0], 1: roots[1]}, roots[0])],
        )
        n_blocks = len(res.plan.owners)
        assert res.plan.version == 2
        assert res.membership.hosts == [0]
        # the survivor's re-based manifest covers EVERY global block
        assert sorted(res.manifest.global_block_ids) == list(range(n_blocks))
        # only the dead host's blocks were copied; the survivor's own
        # files stayed put (delta transfer, not a re-ingest)
        assert sorted(res.adopted) == sorted(manifests[1].global_block_ids)
        assert res.adopted  # the 2-host split genuinely moved blocks
        by_gid = {g: b for g, b in zip(manifests[1].global_block_ids,
                                       manifests[1].blocks)}
        for g in res.adopted:
            src = os.path.join(manifests[1].dir, by_gid[g]["file"])
            dst = os.path.join(manifests[0].dir, by_gid[g]["file"])
            with open(src, "rb") as a, open(dst, "rb") as b:
                assert a.read() == b.read()
            # the spilled coefficients rode along, epoch dir by name
            moved = np.load(os.path.join(
                roots[0], "epoch-0", f"coefs-g{g:05d}.npy"
            ))
            assert float(moved[0, 0]) == 2.0
        assert res.state_files_adopted == len(res.adopted)
        # FE chunk ownership re-based with the plan: all chunks -> host 0
        assert sorted(res.plan.owned_fe_chunks(0, res.membership)) == [0, 1, 2]
        assert any("no re-ingest" in d for d in res.decisions)

    def test_chaos_site_fires_at_entry(self, glmix, tmp_path):
        coord_root = str(tmp_path / "re")
        _build_cohort(glmix, coord_root, _two_host_membership())
        with faults.fault_scope(faults.FaultPlan(
            [faults.FaultSpec("multihost.relaunch_replan", at=1)]
        )):
            with pytest.raises(OSError):
                relaunch_replan(coord_root, 0, 1)
        # the failure left the prior layout intact: a retry succeeds
        res = relaunch_replan(coord_root, 0, 1)
        assert res.plan.version == 2

    def test_stale_cohort_member_refused(self, glmix, tmp_path):
        coord_root = str(tmp_path / "re")
        _build_cohort(glmix, coord_root, _two_host_membership())
        d0 = os.path.join(coord_root, "process-0")
        meta, owners, block_of = load_plan_sidecars(d0)
        # simulate a re-shard that crashed mid-commit: host 0 moved to v2,
        # host 1 never did — resuming from mixed versions must refuse
        write_plan_sidecars(
            d0, owners, block_of, version=2,
            hosts=[int(h) for h in meta["hosts"]],
            binding={int(h): int(q) for h, q in meta["binding"].items()},
            block_costs=np.asarray(meta["block_costs"], np.int64),
            num_entities=int(meta["num_entities"]),
            num_processes=int(meta["num_processes"]),
        )
        with pytest.raises(ElasticError, match="stale"):
            relaunch_replan(coord_root, 0, 1)

    def test_empty_root_refused(self, tmp_path):
        os.makedirs(str(tmp_path / "empty"))
        with pytest.raises(ElasticError, match="nothing to re-plan"):
            relaunch_replan(str(tmp_path / "empty"), 0, 1)


# ---------------------------------------------------------------------------
# warm-start builders (satellite: bucketed + per-host streaming)
# ---------------------------------------------------------------------------


class TestWarmBuilders:
    def test_bucketed_export_seed_export_is_bitwise(self):
        """export -> bucketed_random_effect_init -> export is the identity
        (the property that makes a warm-started bucket exact)."""
        from photon_ml_tpu.algorithm.bucketed_random_effect import (
            BucketedDatasetBundle,
            BucketedRandomEffectCoordinate,
        )
        from photon_ml_tpu.retrain import bucketed_random_effect_init

        rng = np.random.default_rng(7)
        data, _ = make_glmix_data(
            rng, num_users=14, rows_per_user_range=(2, 12), d_random=3
        )
        bundle = BucketedDatasetBundle.build(data, RE_CFG)
        coord = BucketedRandomEffectCoordinate(
            data, RE_CFG, TASK, bundle=bundle
        )
        state = tuple(
            jnp.asarray(rng.normal(size=np.asarray(w).shape)
                        .astype(np.float32))
            for w in coord.initial_coefficients()
        )
        means = coord.entity_means_by_raw_id(state)
        assert means  # the fixture produced positioned entities
        stacks = bucketed_random_effect_init(means, bundle)
        assert len(stacks) == len(bundle.buckets)
        means_back = coord.entity_means_by_raw_id(
            tuple(jnp.asarray(s) for s in stacks)
        )
        assert sorted(means_back) == sorted(means)
        for raw, row in means.items():
            np.testing.assert_array_equal(means_back[raw], row, err_msg=raw)

    def test_unknown_entities_stay_cold(self):
        from photon_ml_tpu.algorithm.bucketed_random_effect import (
            BucketedDatasetBundle,
        )
        from photon_ml_tpu.retrain import bucketed_random_effect_init

        rng = np.random.default_rng(8)
        data, _ = make_glmix_data(
            rng, num_users=6, rows_per_user_range=(2, 6), d_random=3
        )
        bundle = BucketedDatasetBundle.build(data, RE_CFG)
        stacks = bucketed_random_effect_init({}, bundle)
        for s in stacks:
            assert not s.any()  # no prior rows -> the cold init everywhere

    def test_perhost_seed_export_round_trip(self, glmix, tmp_path):
        """Per-host twin: spill random coefficients, export them, seed a
        fresh state from the export — the re-export is bitwise-equal."""
        from photon_ml_tpu.parallel.perhost_streaming import (
            PerHostStreamingRandomEffectCoordinate,
        )
        from photon_ml_tpu.retrain import seed_perhost_spilled_state

        man = _build_cohort(
            glmix, str(tmp_path / "re"), FleetMembership.initial(1)
        )[0]
        coord = PerHostStreamingRandomEffectCoordinate(
            man, TASK, OptimizerType.LBFGS, RE_OPT, RE_REG,
            state_root=str(tmp_path / "state"), ctx=None, num_processes=1,
        )
        rng = np.random.default_rng(9)
        state = PerHostSpilledREState(
            dir=str(tmp_path / "spill"),
            shapes=[(b["num_entities"], b["local_dim"]) for b in man.blocks],
            global_ids=[int(g) for g in man.global_block_ids],
            plan_version=int(man.plan_version),
        )
        for i, b in enumerate(man.blocks):
            state.write(i, rng.normal(
                size=(b["num_entities"], b["local_dim"])
            ).astype(np.float32))
        means = coord.entity_means_by_raw_id(state)
        assert means
        seeded = seed_perhost_spilled_state(
            man, means, str(tmp_path / "seeded")
        )
        assert seeded.global_ids == [int(g) for g in man.global_block_ids]
        means_back = coord.entity_means_by_raw_id(seeded)
        assert sorted(means_back) == sorted(means)
        for raw, row in means.items():
            np.testing.assert_array_equal(means_back[raw], row, err_msg=raw)


# ---------------------------------------------------------------------------
# multihost driver glue (single-process collective passthrough)
# ---------------------------------------------------------------------------


def _mh():
    return types.SimpleNamespace(process_id=0, num_processes=1)


class TestRelaunchAdoption:
    def _p(self, tmp_path):
        return types.SimpleNamespace(
            updating_sequence=["per-user"],
            random_effect_data_configs={"per-user": RE_CFG},
            factored_configs={},
            output_dir=str(tmp_path),
        )

    def test_smaller_cohort_adopts(self, glmix, tmp_path):
        p = self._p(tmp_path)
        _build_cohort(
            glmix, os.path.join(str(tmp_path), "streaming-re", "per-user"),
            _two_host_membership(),
        )
        log = _Log()
        adopted = mhd._attempt_relaunch_adoption(p, _mh(), None, log)
        assert set(adopted) == {"per-user"}
        res = adopted["per-user"]
        assert res.plan.version == 2
        assert res.membership.hosts == [0]
        assert res.adopted

    def test_same_cohort_is_a_plain_resume(self, glmix, tmp_path):
        p = self._p(tmp_path)
        _build_cohort(
            glmix, os.path.join(str(tmp_path), "streaming-re", "per-user"),
            FleetMembership.initial(1),
        )
        log = _Log()
        assert mhd._attempt_relaunch_adoption(p, _mh(), None, log) == {}
        assert any("same cohort" in m for m in log.infos)
        assert not log.warns

    def test_no_prior_layout_falls_back_to_ingest(self, tmp_path):
        log = _Log()
        assert mhd._attempt_relaunch_adoption(
            self._p(tmp_path), _mh(), None, log
        ) == {}
        assert any(
            "relaunch re-plan unavailable" in m for m in log.warns
        )


class TestFeChunkShare:
    def test_adopted_plan_drives_the_share(self, glmix, tmp_path):
        coord_root = str(tmp_path / "re")
        manifests = _build_cohort(glmix, coord_root, _two_host_membership())
        attach_fe_chunks_to_sidecars(manifests[0].dir, [0, 1, 0], [4, 4, 2])
        res = relaunch_replan(coord_root, 0, 1)
        files = ["part-0", "part-1", "part-2"]
        log = _Log()
        share = mhd._fe_chunk_share(files, {"per-user": res}, _mh(), log)
        assert sorted(share) == [(f, c) for c, f in enumerate(files)]
        assert any("re-based plan v2" in m for m in log.infos)

    def test_ownership_width_mismatch_falls_back(self, glmix, tmp_path):
        coord_root = str(tmp_path / "re")
        manifests = _build_cohort(glmix, coord_root, _two_host_membership())
        attach_fe_chunks_to_sidecars(manifests[0].dir, [0, 1, 0], [4, 4, 2])
        res = relaunch_replan(coord_root, 0, 1)
        files = ["part-0", "part-1"]  # the input set changed size
        log = _Log()
        share = mhd._fe_chunk_share(files, {"per-user": res}, _mh(), log)
        assert share == host_file_share(files, 1, 0)
        assert any("positional" in m for m in log.infos)

    def test_no_adoption_is_the_positional_share(self):
        files = [f"part-{i}" for i in range(5)]
        share = mhd._fe_chunk_share(files, {}, _mh(), _Log())
        assert share == host_file_share(files, 1, 0)


class TestMultihostWarm:
    """_prepare_multihost_warm at num_processes=1: the collective vote is
    the local passthrough, so the agreement/poison seams run for real."""

    def _p(self, tmp_path, prior_dir, **over):
        kw = dict(
            warm_start_from=str(prior_dir),
            task_type=TASK,
            updating_sequence=["global"],
            fixed_effect_data_configs={
                "global": types.SimpleNamespace(feature_shard_id="global"),
            },
            random_effect_data_configs={},
            factored_configs={},
            feature_shard_sections=None,
            feature_shard_intercepts=None,
            offheap_indexmap_dir=None,
            feature_name_and_term_set_path=None,
            validate_input_dirs=None,
            evaluators=None,
            output_dir=str(tmp_path),
        )
        kw.update(over)
        return types.SimpleNamespace(**kw)

    def _prior(self, p, prior_dir, files, coordinates, plan):
        from photon_ml_tpu.io.tensor_cache import file_stat_token

        model_dir = os.path.join(str(prior_dir), "model")
        os.makedirs(model_dir, exist_ok=True)
        man = RetrainManifest(
            output_dir=str(prior_dir),
            model_dir=model_dir,
            task=TASK.value,
            file_stats=file_stat_token(files),
            ingest_inputs=mhd._mh_ingest_inputs(p, plan),
            ingest_digest="d0",
            updating_sequence=list(p.updating_sequence),
            coordinates=coordinates,
            eval_identity=mhd._mh_eval_identity(p),
        )
        man.save(str(prior_dir))
        return model_dir

    def test_no_flag_is_a_cold_run(self, tmp_path):
        p = types.SimpleNamespace(warm_start_from=None)
        assert mhd._prepare_multihost_warm(
            p, _mh(), None, _Log(), None, {}, [], {}, [{}]
        ) == (None, {}, set())

    def test_unusable_prior_degrades_to_recorded_cold(self, tmp_path):
        plan = types.SimpleNamespace(bucketer=None)
        p = self._p(tmp_path, tmp_path / "never-written")
        log = _Log()
        out = mhd._prepare_multihost_warm(
            p, _mh(), None, log, plan, {}, [], {}, [{}]
        )
        assert out == (None, {}, set())
        assert any("failed on at least one host" in m for m in log.warns)
        assert any("recorded decision" in m for m in log.warns)

    def test_agreed_fixed_effect_warm_and_frozen(self, tmp_path):
        from photon_ml_tpu.io.tensor_cache import file_stat_token  # noqa: F401

        plan = types.SimpleNamespace(bucketer=None)
        a = str(tmp_path / "part-0")
        with open(a, "wb") as f:
            f.write(b"train bytes")
        prior_dir = tmp_path / "prior"
        p = self._p(tmp_path, prior_dir)
        imap = IndexMap.build(
            [feature_key(f"f{i}") for i in range(6)], add_intercept=False
        )
        model_dir = self._prior(
            p, prior_dir, [a],
            {"global": CoordinateRecord(
                kind="fixed", opt_config=str(mhd.CoordinateOptConfig())
            )},
            plan,
        )
        rng = np.random.default_rng(11)
        means = rng.normal(size=(len(imap),)).astype(np.float32)
        model_io.save_fixed_effect(model_dir, "global", TASK, means, imap)
        log = _Log()
        warm, frozen_blocks, frozen = mhd._prepare_multihost_warm(
            p, _mh(), None, log, plan, {"global": imap}, [a], {}, [{}]
        )
        assert warm is not None and set(warm) == {"global"}
        np.testing.assert_array_equal(np.asarray(warm["global"]), means)
        assert frozen == {"global"} and frozen_blocks == {}
        assert any("agreed across 1 hosts" in m for m in log.infos)
        assert not log.warns

    def test_agreed_streaming_warm_freezes_every_owned_block(
        self, glmix, tmp_path
    ):
        plan = types.SimpleNamespace(bucketer=None)
        man = _build_cohort(
            glmix, str(tmp_path / "re"), FleetMembership.initial(1)
        )[0]
        a = str(tmp_path / "part-0")
        with open(a, "wb") as f:
            f.write(b"train bytes")
        prior_dir = tmp_path / "prior"
        p = self._p(
            tmp_path, prior_dir,
            updating_sequence=["per-user"],
            fixed_effect_data_configs={},
            random_effect_data_configs={"per-user": RE_CFG},
        )
        gdim = _host_rows(glmix).global_dim
        imap = IndexMap.build(
            [feature_key(f"f{i}") for i in range(gdim)], add_intercept=False
        )
        model_dir = self._prior(
            p, prior_dir, [a],
            {"per-user": CoordinateRecord(
                kind="streaming_random",
                opt_config=str(mhd.CoordinateOptConfig()),
                streaming_manifest_dir=man.dir,
            )},
            plan,
        )
        rng = np.random.default_rng(12)
        vocab = glmix.id_vocabs["userId"]
        prior_means = {
            vocab[0]: rng.normal(size=(len(imap),)).astype(np.float32),
            vocab[3]: rng.normal(size=(len(imap),)).astype(np.float32),
        }
        model_io.save_random_effect(
            model_dir, "per-user", TASK, prior_means, imap,
            random_effect_id="userId", feature_shard_id="per_user",
        )
        log = _Log()
        warm, frozen_blocks, frozen = mhd._prepare_multihost_warm(
            p, _mh(), None, log, plan, {"per_user": imap}, [a],
            {"per-user": man}, [{}],
        )
        assert warm is not None and set(warm) == {"per-user"}
        assert isinstance(warm["per-user"], PerHostSpilledREState)
        assert frozen == {"per-user"}
        assert frozen_blocks["per-user"] == frozenset(
            range(len(man.blocks))
        )
        assert any("agreed across 1 hosts" in m for m in log.infos)

    def test_chaos_fault_degrades_to_cold(self, tmp_path):
        plan = types.SimpleNamespace(bucketer=None)
        a = str(tmp_path / "part-0")
        with open(a, "wb") as f:
            f.write(b"train bytes")
        prior_dir = tmp_path / "prior"
        p = self._p(tmp_path, prior_dir)
        imap = IndexMap.build([feature_key("f0")], add_intercept=False)
        model_dir = self._prior(
            p, prior_dir, [a],
            {"global": CoordinateRecord(
                kind="fixed", opt_config=str(mhd.CoordinateOptConfig())
            )},
            plan,
        )
        model_io.save_fixed_effect(
            model_dir, "global", TASK, np.zeros(1, np.float32), imap
        )
        log = _Log()
        with faults.fault_scope(faults.FaultPlan(
            [faults.FaultSpec("retrain.multihost_delta_agree", at=1)]
        )):
            out = mhd._prepare_multihost_warm(
                p, _mh(), None, log, plan, {"global": imap}, [a], {}, [{}]
            )
        assert out == (None, {}, set())
        assert any("recorded decision" in m for m in log.warns)
        # the seam is once-per-plan: the very next attempt warms normally
        warm, _, frozen = mhd._prepare_multihost_warm(
            p, _mh(), None, _Log(), plan, {"global": imap}, [a], {}, [{}]
        )
        assert warm is not None and frozen == {"global"}


def test_multihost_fingerprint_is_cohort_invariant():
    """The relaunch contract: the CD checkpoint fingerprint must NOT bake
    in num_processes, or a smaller/larger cohort could never resume the
    prior cohort's checkpoints (MIGRATION.md pins this)."""
    import inspect

    src = inspect.getsource(mhd)
    assert '"multihost": True' in src
    assert '"multihost": mh.num_processes' not in src


# ---------------------------------------------------------------------------
# the 2-process supervised-relaunch arm (slow): seed on 2 hosts, kill one,
# relaunch ONE survivor, resume bitwise vs the single-host reference
# ---------------------------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _communicate(procs, timeout=900):
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, (
            f"worker failed rc={p.returncode}:\n{out[-3000:]}\n{err[-3000:]}"
        )
        outs.append(out)
    return outs


def _single_host_reference(tmp_path):
    """The flags-off single-host streaming 2-iteration CD run of the
    workers' seeded dataset — bitwise-equal (PR 9 pinned) to an
    uninterrupted run on ANY topology, including the survivor's."""
    from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
    from photon_ml_tpu.algorithm.streaming_fixed_effect import (
        StreamingFixedEffectCoordinate,
    )
    from photon_ml_tpu.algorithm.streaming_random_effect import (
        StreamingRandomEffectCoordinate,
        write_re_entity_blocks,
    )
    from photon_ml_tpu.optim.problem import GLMOptimizationProblem
    from photon_ml_tpu.optim.streaming import ChunkedGLMSource
    from photon_ml_tpu.ops import losses as losses_mod

    data = _sorted_vocab_data(
        np.random.default_rng(97),
        num_users=60, rows_per_user_range=(4, 16), d_fixed=5, d_random=4,
    )
    N = data.num_rows
    man = write_re_entity_blocks(
        data, RE_CFG, str(tmp_path / "ref-blocks"), block_entities=16
    )
    re_ref = StreamingRandomEffectCoordinate(
        man, TASK, OptimizerType.LBFGS, RE_OPT, RE_REG,
        state_root=str(tmp_path / "ref-state"),
    )
    gf = data.shards["global"]
    x_fe = np.zeros((N, gf.dim), np.float32)
    x_fe[np.repeat(np.arange(N), np.diff(gf.indptr)), gf.indices] = gf.values
    fe_ref = StreamingFixedEffectCoordinate(
        ChunkedGLMSource.from_arrays(
            x_fe, data.response.astype(np.float32), 128
        ),
        GLMOptimizationProblem(
            TASK, OptimizerType.LBFGS,
            OptimizerConfig(max_iterations=6, tolerance=1e-8),
            RegularizationContext.l2(0.5),
        ),
    )
    labels = jnp.asarray(data.response.astype(np.float32))
    weights = jnp.asarray(data.weight.astype(np.float32))
    loss = losses_mod.for_task(TASK)
    cd = CoordinateDescent(
        {"fixed": fe_ref, "per-user": re_ref},
        lambda s: jnp.sum(weights * loss.loss(s, labels)),
    )
    ref = cd.run(num_iterations=2, num_rows=N)
    ref_means = re_ref.entity_means_by_raw_id(ref.coefficients["per-user"])
    return ref, ref_means


@pytest.mark.slow
def test_supervised_relaunch_smaller_cohort_resumes_bitwise(tmp_path):
    """THE relaunch acceptance gate: a 2-host cohort runs one checkpointed
    iteration and dies (the simulated preemption that does NOT come back);
    a supervisor relaunches ONE survivor, which re-plans from the sidecars,
    delta-copies only the dead host's block/state files, re-derives its FE
    chunk share from the plan, resumes from the step-aligned checkpoint —
    and finishes bitwise-equal to an uninterrupted single-host run."""
    env = {
        **os.environ,
        "PHOTON_SOLVE_CHUNK": "off",
        "PHOTON_SPARSE_KERNEL": "off",
        "PHOTON_SHAPE_LADDER": "off",
    }
    port = _free_port()
    seed = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=REPO, env={**env, "RELAUNCH_PHASE": "seed"},
        )
        for i in range(2)
    ]
    outs = _communicate(seed)
    assert all("SEEDOK" in o for o in outs)
    assert all("resumed_from_step=0" in o for o in outs)
    survivor = subprocess.Popen(
        [sys.executable, WORKER, "0", "1", "-", str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env={**env, "RELAUNCH_PHASE": "relaunch"},
    )
    out, = _communicate([survivor])
    assert "RELAUNCHOK" in out
    assert "replanned_to_v2" in out
    assert "no-reingest" in out
    assert "adopted=0 " not in out  # the dead host's blocks genuinely moved
    assert "resumed_from_step=2" in out  # iteration 1 NOT recomputed
    assert "fe_chunks=" in out
    ref, ref_means = _single_host_reference(tmp_path)
    run = np.load(tmp_path / "run.npz")
    np.testing.assert_array_equal(
        run["fe"], np.asarray(ref.coefficients["fixed"])
    )
    np.testing.assert_array_equal(
        run["total_scores"], np.asarray(ref.total_scores)
    )
    np.testing.assert_array_equal(
        run["objectives"], np.asarray(ref.objective_history, np.float64)
    )
    z = np.load(tmp_path / "means-host0.npz", allow_pickle=True)
    merged = {str(n): v for n, v in zip(z["names"], z["stack"])}
    assert sorted(merged) == sorted(ref_means)
    for k, vec in ref_means.items():
        np.testing.assert_array_equal(merged[k], vec, err_msg=k)
