"""Day-in-the-life SLO machinery tests (photon_ml_tpu/slo + tools/day_in_life).

Covers the acceptance claims:

  * Streaming quantiles: the hybrid digest is BIT-IDENTICAL to the exact
    nearest-rank percentile while inside ``exact_limit`` (the old
    sorted-deque behavior every existing ServeStats assertion relies on),
    and within tight relative error of the true percentile over a
    200k-sample stream it could never hold in memory.
  * SLO spec validation: unknown degradation kinds, inverted latency
    bounds, and out-of-range budgets are refused at declaration time.
  * The ledger: per-phase attribution, the FleetStats counter-delta
    auto-attribution (a counter that moved without a declaration CANNOT
    escape), and every violation rule the enforce() gate checks.
  * The mini day: a full 6-phase lifecycle run (swap chaos, delta
    rollout, elasticity replan, dtype migration) completes with zero
    violations and banks the sidecar — the tier-1 sibling of the
    slow-marked full-fat day (real delta retrain + TCP kill arm).
"""

import json
import os
import sys

import numpy as np
import pytest

from photon_ml_tpu.slo import (
    DEGRADATION_KINDS,
    FLEET_COUNTER_KINDS,
    SLO_LEDGER_FILE,
    PhaseSLO,
    SLOLedger,
    SLOSpec,
    SLOViolation,
    StreamingQuantileDigest,
    exact_percentile,
)
from photon_ml_tpu.slo.quantiles import P2Quantile

pytestmark = pytest.mark.slo

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ),
)


# ---------------------------------------------------------------------------
# streaming quantiles
# ---------------------------------------------------------------------------


class TestStreamingQuantiles:
    def test_exact_regime_bit_identical_to_nearest_rank(self):
        """Inside exact_limit the digest IS the old sorted-path formula —
        bitwise, for every tracked and untracked q."""
        rng = np.random.default_rng(3)
        vals = rng.lognormal(sigma=1.0, size=400).tolist()
        d = StreamingQuantileDigest((0.50, 0.99), exact_limit=1000)
        for v in vals:
            d.add(v)
        assert d.exact
        srt = sorted(vals)
        for q in (0.10, 0.50, 0.90, 0.99):
            assert d.quantile(q) == exact_percentile(srt, q)

    def test_streaming_regime_tracks_true_percentiles(self):
        """200k samples through a 1000-sample buffer: P² stays within 1%
        (p50) / 2% (p99) of the true percentile — the digest never
        windows to the newest samples."""
        rng = np.random.default_rng(7)
        vals = rng.lognormal(mean=0.0, sigma=0.6, size=200_000)
        d = StreamingQuantileDigest((0.50, 0.99), exact_limit=1000)
        for v in vals:
            d.add(v)
        assert not d.exact
        assert d.count == 200_000
        for q, tol in ((0.50, 0.01), (0.99, 0.02)):
            true = float(np.percentile(vals, q * 100))
            assert abs(d.quantile(q) - true) / true < tol

    def test_flip_happens_exactly_past_the_limit(self):
        d = StreamingQuantileDigest((0.50,), exact_limit=10)
        for i in range(10):
            d.add(float(i))
        assert d.exact
        d.add(10.0)
        assert not d.exact
        # estimator regime only knows the tracked quantiles
        with pytest.raises(KeyError):
            d.quantile(0.75)
        assert d.quantile(0.50) > 0.0

    def test_reset_returns_to_exact(self):
        d = StreamingQuantileDigest((0.50,), exact_limit=5)
        for i in range(20):
            d.add(float(i))
        assert not d.exact
        d.reset()
        assert d.count == 0
        assert d.quantile(0.50) == 0.0
        d.add(3.0)
        assert d.exact and d.quantile(0.50) == 3.0

    def test_validation(self):
        with pytest.raises(ValueError):
            StreamingQuantileDigest((0.5,), exact_limit=4)
        with pytest.raises(ValueError):
            P2Quantile.from_sorted(0.5, [1.0, 2.0, 3.0])
        with pytest.raises(ValueError):
            P2Quantile(1.5, [0] * 5, [1, 2, 3, 4, 5])

    def test_empty_digest_answers_zero(self):
        assert StreamingQuantileDigest().quantile(0.99) == 0.0

    def test_serve_stats_small_sample_agreement(self):
        """ServeStats (now digest-backed) must report the SAME p50/p99 the
        exact sorted path always computed for small samples — pinned
        against exact_percentile on the identical latency list."""
        from photon_ml_tpu.serve import ServeStats

        rng = np.random.default_rng(11)
        lats = rng.lognormal(mean=-6.0, sigma=0.8, size=500).tolist()
        stats = ServeStats()
        for lat in lats:
            stats.record_request(lat)
        snap = stats.snapshot()
        srt = sorted(lats)
        assert snap["p50_ms"] == round(exact_percentile(srt, 0.50) * 1e3, 3)
        assert snap["p99_ms"] == round(exact_percentile(srt, 0.99) * 1e3, 3)


# ---------------------------------------------------------------------------
# spec validation
# ---------------------------------------------------------------------------


class TestSpec:
    def test_unknown_degradation_kind_refused(self):
        with pytest.raises(ValueError, match="unknown degradation"):
            PhaseSLO("p", p50_ms=1, p99_ms=2, allowed_degradations=("nope",))

    def test_inverted_latency_refused(self):
        with pytest.raises(ValueError, match="p50 <= p99"):
            PhaseSLO("p", p50_ms=5, p99_ms=2)

    def test_bad_budgets_refused(self):
        with pytest.raises(ValueError, match="fraction"):
            PhaseSLO("p", p50_ms=1, p99_ms=2, error_budget=1.5)
        with pytest.raises(ValueError, match="staleness"):
            PhaseSLO("p", p50_ms=1, p99_ms=2, staleness_budget=-1)

    def test_duplicate_phase_refused(self):
        p = PhaseSLO("p", p50_ms=1, p99_ms=2)
        with pytest.raises(ValueError, match="duplicate"):
            SLOSpec([p, p])

    def test_undeclared_phase_lookup_fails(self):
        spec = SLOSpec([PhaseSLO("a", p50_ms=1, p99_ms=2)])
        with pytest.raises(KeyError, match="no declared SLO"):
            spec.phase("b")

    def test_json_roundtrip(self, tmp_path):
        spec = SLOSpec([
            PhaseSLO(
                "peak", p50_ms=10, p99_ms=100, error_budget=0.05,
                staleness_budget=3,
                allowed_degradations=("hedged_fallback",),
                chaos_window=True,
            ),
            PhaseSLO("drain", p50_ms=5, p99_ms=50),
        ])
        path = str(tmp_path / "spec.json")
        spec.save(path)
        loaded = SLOSpec.load(path)
        assert loaded.to_json() == spec.to_json()
        assert loaded.phase("peak").chaos_window is True

    def test_every_fleet_counter_kind_is_registered(self):
        for kind in FLEET_COUNTER_KINDS.values():
            assert kind in DEGRADATION_KINDS


# ---------------------------------------------------------------------------
# the ledger + the gate
# ---------------------------------------------------------------------------


def _spec(**kw):
    defaults = dict(p50_ms=1e6, p99_ms=1e6)
    defaults.update(kw)
    return SLOSpec([PhaseSLO("phase", **defaults)])


class TestLedger:
    def test_phase_protocol_enforced(self):
        led = SLOLedger(_spec())
        with pytest.raises(RuntimeError, match="no phase open"):
            led.record_request(0.001)
        led.begin_phase("phase")
        with pytest.raises(RuntimeError, match="still open"):
            led.begin_phase("phase")
        with pytest.raises(RuntimeError, match="still open"):
            led.finalize()
        led.end_phase()
        led.enforce()

    def test_clean_phase_passes(self):
        led = SLOLedger(_spec(p50_ms=100, p99_ms=200))
        led.begin_phase("phase")
        for _ in range(50):
            led.record_request(0.001, num_rows=2)
        rec = led.end_phase()
        assert rec["requests"] == 50 and rec["rows"] == 100
        assert rec["violations"] == []
        payload = led.enforce()
        assert payload["ok"] is True

    def test_p99_violation_detected(self):
        led = SLOLedger(_spec(p50_ms=0.4, p99_ms=0.5))
        led.begin_phase("phase")
        for _ in range(100):
            led.record_request(0.001)  # 1ms > 0.5ms p99
        led.end_phase()
        with pytest.raises(SLOViolation, match="p99"):
            led.enforce()

    def test_error_budget_spend(self):
        led = SLOLedger(_spec(error_budget=0.10))
        led.begin_phase("phase")
        for _ in range(100):
            led.record_request(0.001)
        led.record_error(5)
        rec = led.end_phase()
        assert rec["error_budget"]["spend"] == pytest.approx(0.05)
        assert rec["error_budget"]["used"] == pytest.approx(0.5)
        led.enforce()

        led2 = SLOLedger(_spec(error_budget=0.01))
        led2.begin_phase("phase")
        for _ in range(100):
            led2.record_request(0.001)
        led2.record_error(5)
        led2.end_phase()
        with pytest.raises(SLOViolation, match="error-budget"):
            led2.enforce()

    def test_drops_outside_chaos_window_fail_even_in_budget(self):
        led = SLOLedger(_spec(error_budget=0.5, chaos_window=False))
        led.begin_phase("phase")
        for _ in range(100):
            led.record_request(0.001)
        led.record_drop()
        led.end_phase()
        with pytest.raises(SLOViolation, match="outside a declared chaos"):
            led.enforce()

        led2 = SLOLedger(_spec(error_budget=0.5, chaos_window=True))
        led2.begin_phase("phase")
        for _ in range(100):
            led2.record_request(0.001)
        led2.record_drop()
        led2.end_phase()
        led2.enforce()  # charged to the budget instead

    def test_staleness_budget(self):
        led = SLOLedger(_spec(staleness_budget=2))
        led.begin_phase("phase")
        led.record_request(0.001)
        led.mark_flip(1)
        led.record_stale_answer(3)
        rec = led.end_phase()
        assert rec["flip_generation"] == 1
        with pytest.raises(SLOViolation, match="staleness budget"):
            led.enforce()

    def test_mixed_generation_always_fails(self):
        led = SLOLedger(_spec())
        led.begin_phase("phase")
        led.record_request(0.001)
        led.record_mixed_generation()
        led.end_phase()
        with pytest.raises(SLOViolation, match="mixed-generation"):
            led.enforce()

    def test_divergence_always_fails(self):
        led = SLOLedger(_spec())
        led.begin_phase("phase")
        led.record_request(0.001)
        led.record_divergence()
        led.end_phase()
        with pytest.raises(SLOViolation, match="bitwise oracle"):
            led.enforce()

    def test_undeclared_degradation_fails_at_count_one(self):
        led = SLOLedger(_spec(allowed_degradations=()))
        led.begin_phase("phase")
        led.record_request(0.001)
        led.attribute("swap_abort_chaos", detail="injected barrier fault")
        rec = led.end_phase()
        assert rec["degradation_details"] == [
            "swap_abort_chaos: injected barrier fault"
        ]
        with pytest.raises(SLOViolation, match="undeclared degradation"):
            led.enforce()

        led2 = SLOLedger(_spec(allowed_degradations=("swap_abort_chaos",)))
        led2.begin_phase("phase")
        led2.record_request(0.001)
        led2.attribute("swap_abort_chaos")
        led2.end_phase()
        led2.enforce()

    def test_unknown_attribution_kind_is_a_programming_error(self):
        led = SLOLedger(_spec())
        led.begin_phase("phase")
        with pytest.raises(ValueError, match="unknown degradation kind"):
            led.attribute("not_a_kind")
        led.end_phase()

    def test_fleet_counter_deltas_auto_attributed(self):
        """A FleetStats counter that moves during a phase lands in the
        ledger WITHOUT any driver cooperation — the structural 'never
        silent' rule. Undeclared, it fails the gate."""
        from photon_ml_tpu.serve import FleetStats

        stats = FleetStats()
        stats.record_hedge()  # pre-phase activity must NOT be attributed
        led = SLOLedger(_spec(allowed_degradations=("cold_entity_zero",)))
        led.begin_phase("phase", stats=stats)
        led.record_request(0.001)
        stats.record_degraded_rows(4)
        stats.record_routed_retry()
        rec = led.end_phase()
        assert rec["degradations"]["cold_entity_zero"] == 4
        assert rec["degradations"]["chaos_absorbed_retry"] == 1
        assert "hedged_fallback" not in rec["degradations"]
        with pytest.raises(SLOViolation, match="chaos_absorbed_retry"):
            led.enforce()

    def test_sidecar_roundtrip(self, tmp_path):
        led = SLOLedger(_spec())
        led.begin_phase("phase")
        led.record_request(0.002)
        led.record_bytes_moved(1234)
        led.end_phase()
        path = led.write(str(tmp_path))
        assert os.path.basename(path) == SLO_LEDGER_FILE
        with open(path) as f:
            payload = json.load(f)
        assert payload["format"] == 1
        assert payload["ok"] is True
        assert payload["totals"]["bytes_moved"] == 1234

    def test_sidecar_banked_even_over_budget(self, tmp_path):
        """write() never enforces: an over-budget ledger is still banked
        so fleetctl can show WHAT went over."""
        led = SLOLedger(_spec())
        led.begin_phase("phase")
        led.record_request(0.001)
        led.record_divergence()
        led.end_phase()
        path = led.write(str(tmp_path))
        with open(path) as f:
            payload = json.load(f)
        assert payload["ok"] is False and payload["violations_total"] == 1


# ---------------------------------------------------------------------------
# fleetctl --slo aggregation
# ---------------------------------------------------------------------------


class TestFleetctlSLO:
    def _bank(self, directory, *, divergent=0):
        led = SLOLedger(
            SLOSpec([PhaseSLO("peak", p50_ms=1e6, p99_ms=1e6)])
        )
        led.begin_phase("peak")
        for _ in range(10):
            led.record_request(0.001)
        led.record_divergence(divergent)
        if divergent:
            led.attribute("swap_abort_chaos")  # undeclared -> 2nd violation
        led.end_phase()
        led.write(str(directory))

    def test_aggregates_and_flags_over_budget(self, tmp_path):
        import fleetctl

        clean = tmp_path / "clean"
        dirty = tmp_path / "dirty"
        torn = tmp_path / "torn"
        clean.mkdir(), dirty.mkdir(), torn.mkdir()
        self._bank(clean)
        self._bank(dirty, divergent=2)
        (torn / SLO_LEDGER_FILE).write_text("{not json")

        agg = fleetctl.read_slo_ledgers(
            [str(clean), str(dirty), str(torn), str(tmp_path / "absent")]
        )
        assert agg["sidecars"] == 2
        assert agg["unreadable"] == 1
        assert agg["requests"] == 20
        assert agg["ok"] is False
        assert agg["over_budget_total"] == 1
        flagged = agg["over_budget"][0]
        assert flagged["phase"] == "peak"
        assert any("diverged" in v for v in flagged["violations"])
        # per-phase totals merged across sidecars
        assert agg["phases"]["peak"]["requests"] == 20
        assert agg["phases"]["peak"]["violations"] == 2

    def test_nothing_scanned_returns_none(self, tmp_path):
        import fleetctl

        assert fleetctl.read_slo_ledgers([str(tmp_path)]) is None
        assert fleetctl.read_slo_ledgers([]) is None


# ---------------------------------------------------------------------------
# the mini day (tier-1) and the full-fat day (slow sibling)
# ---------------------------------------------------------------------------

#: the lifecycle attributions every day run must exhibit — one per arm
LIFECYCLE_KINDS = (
    "swap_abort_chaos",
    "rollout_abort_chaos",
    "mixed_dtype_refusal",
    "migration_compiles",
    "chaos_absorbed_retry",
)


def _assert_day_result(result, out_dir):
    led = result["ledger"]
    assert led["ok"] is True, led
    assert led["violations_total"] == 0
    names = [p["name"] for p in led["phases"]]
    assert names == [
        "morning_ramp", "midday_peak", "retrain_window",
        "elastic_event", "dtype_migration", "night_drain",
    ]
    for p in led["phases"]:
        assert p["requests"] > 0, p["name"]
        assert p["p99_ms"] >= p["p50_ms"] > 0.0, p["name"]
    degr = led["totals"]["degradations"]
    for kind in LIFECYCLE_KINDS:
        assert degr.get(kind, 0) >= 1, (kind, degr)
    assert led["totals"]["mixed_generation"] == 0
    assert led["totals"]["bytes_moved"] > 0
    # the sidecar banked where fleetctl will look
    with open(os.path.join(out_dir, SLO_LEDGER_FILE)) as f:
        assert json.load(f)["ok"] is True
    # population scale: millions declared, cold draws sampled from it
    pop = result["extra"]["population"]
    assert pop["universe"] >= 1_000_000


class TestDayInLife:
    def test_mini_day_end_to_end(self, tmp_path):
        """The full 6-phase lifecycle — swap chaos, provenance-refused +
        chaos-aborted + real delta rollout, elasticity replan under
        membership/block-transfer chaos, mixed-dtype refusal + bf16
        migration + clean same-dtype roll — under one enforced error
        budget, downsized to tier-1 wall (synthetic models, in-process
        replicas). The slow sibling below runs the full-fat arms."""
        from day_in_life import DayConfig, run_day

        result = run_day(DayConfig(
            out_dir=str(tmp_path),
            real_retrain=False,
            kill_arm=False,
            phase_seconds=1.0,
            peak_qps=60.0,
            traffic_threads=2,
            cold_pool=8,
            exact_limit=512,
        ))
        _assert_day_result(result, str(tmp_path))

    @pytest.mark.slow
    def test_full_day_real_retrain_and_kill_arm(self, tmp_path):
        """Full-fat day: REAL delta retrain (--warm-start-from) under
        traffic and the TCP replica kill -9 arm (heartbeat detection,
        replica_killed attribution). Tier-1 sibling:
        test_mini_day_end_to_end covers the same phase sequence with
        synthetic models and in-process replicas."""
        from day_in_life import DayConfig, run_day

        result = run_day(DayConfig(
            out_dir=str(tmp_path),
            real_retrain=True,
            kill_arm=True,
            phase_seconds=2.0,
            peak_qps=80.0,
            traffic_threads=2,
            cold_pool=12,
        ))
        _assert_day_result(result, str(tmp_path))
        degr = result["ledger"]["totals"]["degradations"]
        assert degr.get("replica_killed", 0) == 1
        assert "elastic_heartbeat_detect_s" in result["extra"]
