"""GLM CLI param cross-validation matrix, date-range discovery edges, and
model-selection criteria.

Reference specs: Params.scala:175-197 (cross-field validation),
util/DateRange.scala + IOUtils.scala:85-130 (daily/yyyy/MM/dd discovery),
ModelSelection.scala:31-86 (per-task selection metric + direction).
"""

import datetime
import os

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.cli import glm_params
from photon_ml_tpu.utils.date_range import DateRange, expand_date_range_paths
from photon_ml_tpu.types import TaskType


def _parse(extra):
    return glm_params.parse_from_command_line(
        ["--training-data-directory", "/tmp/in",
         "--output-directory", "/tmp/out",
         "--task", "LOGISTIC_REGRESSION"] + extra
    )


class TestGLMParamsValidation:
    def test_minimal_flags_parse(self):
        p = _parse([])
        assert p.task_type == TaskType.LOGISTIC_REGRESSION
        assert p.regularization_weights == [0.1, 1.0, 10.0, 100.0]

    @pytest.mark.parametrize("extra,msg", [
        (["--optimizer", "TRON", "--regularization-type", "L1"], "TRON"),
        (["--optimizer", "TRON", "--regularization-type", "ELASTIC_NET"], "TRON"),
        (["--task", "SMOOTHED_HINGE_LOSS_LINEAR_SVM", "--optimizer", "TRON"],
         "first-order"),
        (["--regularization-type", "ELASTIC_NET", "--elastic-net-alpha", "1.5"],
         "alpha"),
        (["--regularization-weights", "1,-5"], "negative"),
        (["--validate-per-iteration", "true"], "validating-data-directory"),
        (["--diagnostic-mode", "ALL"], "validating-data-directory"),
    ])
    def test_invalid_combos_rejected(self, extra, msg):
        with pytest.raises(ValueError, match=msg):
            _parse(extra)

    def test_valid_combos_accepted(self):
        # TRON+L2 is the reference's GAME default; hinge+LBFGS is legal
        _parse(["--optimizer", "TRON", "--regularization-type", "L2"])
        _parse(["--task", "SMOOTHED_HINGE_LOSS_LINEAR_SVM", "--optimizer", "LBFGS"])
        _parse(["--regularization-type", "ELASTIC_NET",
                "--elastic-net-alpha", "0.5"])

    def test_obsolete_spark_flags_accepted(self):
        p = _parse(["--kryo", "true", "--min-partitions", "4",
                    "--tree-aggregate-depth", "2"])
        assert p.tree_aggregate_depth == 2  # parsed, ignored downstream


class TestDateRange:
    def test_from_string_and_days(self):
        dr = DateRange.from_string("20260101-20260103")
        assert dr.days() == [datetime.date(2026, 1, d) for d in (1, 2, 3)]

    def test_inverted_range_rejected(self):
        with pytest.raises(ValueError, match="invalid date range"):
            DateRange.from_string("20260103-20260101")

    def test_malformed_rejected(self):
        with pytest.raises(ValueError):
            DateRange.from_string("2026-01-01")

    def test_from_days_ago_anchored(self):
        today = datetime.date(2026, 7, 30)
        dr = DateRange.from_days_ago("3-1", today=today)
        assert dr.start == datetime.date(2026, 7, 27)
        assert dr.end == datetime.date(2026, 7, 29)

    def test_expand_skips_missing_days(self, tmp_path):
        for d in (1, 3):
            os.makedirs(tmp_path / "daily" / "2026" / "01" / f"{d:02d}")
        got = expand_date_range_paths(
            str(tmp_path), DateRange.from_string("20260101-20260104")
        )
        assert [p[-2:] for p in got] == ["01", "03"]

    def test_expand_empty_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            expand_date_range_paths(
                str(tmp_path), DateRange.from_string("20260101-20260102")
            )

    def test_expand_error_on_missing(self, tmp_path):
        os.makedirs(tmp_path / "daily" / "2026" / "01" / "01")
        with pytest.raises(FileNotFoundError):
            expand_date_range_paths(
                str(tmp_path), DateRange.from_string("20260101-20260102"),
                error_on_missing=True,
            )


class TestModelSelection:
    def _models(self, task, coef_list):
        from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel

        return [
            (lam, GeneralizedLinearModel(Coefficients(jnp.asarray(c)), task))
            for lam, c in coef_list
        ]

    def _batch(self, task):
        from photon_ml_tpu.ops.features import DenseFeatures
        from photon_ml_tpu.ops.objective import GLMBatch

        rng = np.random.default_rng(0)
        x = rng.normal(size=(500, 3)).astype(np.float32)
        w = np.asarray([1.0, -2.0, 0.5], np.float32)
        z = x @ w
        if task == TaskType.LOGISTIC_REGRESSION:
            y = (1 / (1 + np.exp(-z)) > rng.random(500)).astype(np.float32)
        elif task == TaskType.POISSON_REGRESSION:
            # small rates so exp(z) is well-calibrated for the true weights
            y = rng.poisson(np.exp(0.3 * z)).astype(np.float32)
        else:
            y = (z + 0.1 * rng.normal(size=500)).astype(np.float32)
        return GLMBatch(
            DenseFeatures(jnp.asarray(x)), jnp.asarray(y),
            jnp.zeros((500,)), jnp.ones((500,)),
        )

    def test_logistic_picks_highest_auc(self):
        from photon_ml_tpu.model_selection import select_best_model

        batch = self._batch(TaskType.LOGISTIC_REGRESSION)
        good = [1.0, -2.0, 0.5]
        bad = [-1.0, 2.0, -0.5]  # anti-correlated -> AUC < 0.5
        best_lam, best_model, all_m = select_best_model(
            self._models(TaskType.LOGISTIC_REGRESSION,
                         [(0.1, bad), (1.0, good)]),
            batch,
        )
        assert best_lam == 1.0
        assert len(all_m) == 2

    def test_linear_picks_lowest_rmse(self):
        from photon_ml_tpu.model_selection import select_best_model

        batch = self._batch(TaskType.LINEAR_REGRESSION)
        best_lam, _, _ = select_best_model(
            self._models(TaskType.LINEAR_REGRESSION,
                         [(0.1, [0.0, 0.0, 0.0]), (1.0, [1.0, -2.0, 0.5])]),
            batch,
        )
        assert best_lam == 1.0  # true weights -> smallest RMSE

    def test_poisson_picks_highest_loglik(self):
        from photon_ml_tpu.model_selection import select_best_model

        batch = self._batch(TaskType.POISSON_REGRESSION)
        best_lam, _, _ = select_best_model(
            self._models(TaskType.POISSON_REGRESSION,
                         [(0.1, [0.3, -0.6, 0.15]), (1.0, [0.0, 0.0, 0.0])]),
            batch,
        )
        assert best_lam == 0.1

    def test_empty_raises(self):
        from photon_ml_tpu.model_selection import select_best_model

        with pytest.raises(ValueError, match="no models"):
            select_best_model([], self._batch(TaskType.LINEAR_REGRESSION))

    def test_selection_metric_map_covers_all_tasks(self):
        from photon_ml_tpu.model_selection import selection_metric_for

        for t in TaskType:
            assert isinstance(selection_metric_for(t), str)
