"""Factored random effect: alternating (v, M) optimization + MF model.

Reference behavior: algorithm/FactoredRandomEffectCoordinate.scala:36-285
(alternating RE-solve in latent space + latent matrix refit over Kronecker
features), model/MatrixFactorizationModel.scala (latent-factor dot scoring),
optimization/game/MFOptimizationConfiguration.scala (config parsing).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.algorithm.factored_random_effect import (
    FactoredRandomEffectCoordinate,
    FactoredState,
    MFOptimizationConfig,
)
from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_ml_tpu.algorithm.fixed_effect import FixedEffectCoordinate
from photon_ml_tpu.data.game import RandomEffectDataConfig, build_random_effect_dataset
from photon_ml_tpu.models.game import FactoredRandomEffectModel, MatrixFactorizationModel
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.optim.common import OptimizerConfig
from photon_ml_tpu.types import TaskType
from tests.game_test_utils import make_glmix_data


@pytest.fixture
def rng():
    return np.random.default_rng(3)


def _identity_re_dataset(rng, num_users=10, d_random=6):
    data, truth = make_glmix_data(rng, num_users=num_users, d_random=d_random, noise=0.1)
    config = RandomEffectDataConfig(
        random_effect_id="userId", feature_shard_id="per_user", projector="IDENTITY"
    )
    return data, truth, build_random_effect_dataset(data, config)


def test_mf_config_parse():
    cfg = MFOptimizationConfig.parse("3,7")
    assert cfg.num_inner_iterations == 3
    assert cfg.latent_space_dimension == 7


def test_initial_state_shapes(rng):
    data, _, ds = _identity_re_dataset(rng)
    coord = FactoredRandomEffectCoordinate(
        dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION,
        mf_config=MFOptimizationConfig(1, 3),
    )
    st = coord.initial_coefficients()
    assert st.v.shape == (ds.num_entities, 3)
    assert st.matrix.shape == (3, ds.local_dim)
    np.testing.assert_allclose(np.asarray(st.v), 0.0)


def test_latent_objective_matches_explicit_kronecker(rng):
    """The implicit-Kronecker margin <M, v x^T> must equal the margin of the
    flattened M against explicitly materialized kron(x, v) features
    (FactoredRandomEffectCoordinate.scala:267-284 semantics)."""
    k, d = 3, 5
    x = rng.normal(size=(d,)).astype(np.float32)
    v = rng.normal(size=(k,)).astype(np.float32)
    M = rng.normal(size=(k, d)).astype(np.float32)
    implicit = float(v @ (M @ x))
    # kron(x, v)[j*k + i] = x_j * v_i against column-major flattened M
    kron = np.kron(x, v)
    m_flat_colmajor = M.ravel(order="F")
    explicit = float(kron @ m_flat_colmajor)
    np.testing.assert_allclose(implicit, explicit, rtol=1e-5)


def test_update_reduces_loss_and_scores(rng):
    data, truth, ds = _identity_re_dataset(rng)
    coord = FactoredRandomEffectCoordinate(
        dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION,
        mf_config=MFOptimizationConfig(num_inner_iterations=2, latent_space_dimension=3),
        re_optimizer_config=OptimizerConfig(max_iterations=10, tolerance=1e-6),
        latent_optimizer_config=OptimizerConfig(max_iterations=10, tolerance=1e-6),
    )
    st0 = coord.initial_coefficients()
    loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
    resid = jnp.zeros(data.num_rows)

    def data_loss(scores):
        return float(
            jnp.sum(loss.loss(jnp.asarray(scores), jnp.asarray(data.response)))
        )

    loss0 = data_loss(coord.score(st0))
    st1, res = coord.update(resid, st0)
    loss1 = data_loss(coord.score(st1))
    assert loss1 < loss0
    assert np.isfinite(np.asarray(res.value)).all()
    # latent matrix actually moved
    assert not np.allclose(np.asarray(st1.matrix), np.asarray(st0.matrix))


def test_score_gather_matches_dense_math(rng):
    data, truth, ds = _identity_re_dataset(rng)
    coord = FactoredRandomEffectCoordinate(
        dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION,
        mf_config=MFOptimizationConfig(1, 4),
    )
    st = FactoredState(
        v=jnp.asarray(rng.normal(size=(ds.num_entities, 4)).astype(np.float32)),
        matrix=jnp.asarray(rng.normal(size=(4, ds.local_dim)).astype(np.float32)),
    )
    scores = np.asarray(coord.score(st))
    # check a handful of rows against dense math
    W = np.asarray(st.v) @ np.asarray(st.matrix)  # (E, d)
    for row in [0, 7, data.num_rows - 1]:
        pos = int(ds.entity_pos[row])
        x_row = truth["x_random"][row]
        np.testing.assert_allclose(scores[row], x_row @ W[pos], rtol=1e-4, atol=1e-5)


def test_regularization_term(rng):
    from photon_ml_tpu.ops.regularization import RegularizationContext

    data, _, ds = _identity_re_dataset(rng, num_users=4)
    coord = FactoredRandomEffectCoordinate(
        dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION,
        mf_config=MFOptimizationConfig(1, 2),
        re_regularization=RegularizationContext.l2(2.0),
        latent_regularization=RegularizationContext.l2(4.0),
    )
    st = FactoredState(
        v=jnp.ones((ds.num_entities, 2)),
        matrix=jnp.ones((2, ds.local_dim)),
    )
    expected = 0.5 * 2.0 * ds.num_entities * 2 + 0.5 * 4.0 * 2 * ds.local_dim
    np.testing.assert_allclose(float(coord.regularization_term(st)), expected, rtol=1e-5)


def test_in_coordinate_descent_with_fixed_effect(rng):
    """Full GAME: fixed effect + factored random effect through CD."""
    data, truth, ds = _identity_re_dataset(rng, num_users=8)
    from photon_ml_tpu.data.game import build_fixed_effect_batch

    from photon_ml_tpu.optim.problem import GLMOptimizationProblem

    batch = build_fixed_effect_batch(data, "global")
    fixed = FixedEffectCoordinate(
        batch=batch,
        problem=GLMOptimizationProblem(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer_config=OptimizerConfig(max_iterations=20, tolerance=1e-6),
        ),
    )
    factored = FactoredRandomEffectCoordinate(
        dataset=ds,
        task=TaskType.LOGISTIC_REGRESSION,
        mf_config=MFOptimizationConfig(1, 3),
        re_optimizer_config=OptimizerConfig(max_iterations=8, tolerance=1e-6),
        latent_optimizer_config=OptimizerConfig(max_iterations=8, tolerance=1e-6),
    )
    loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
    y = jnp.asarray(data.response)
    cd = CoordinateDescent(
        {"fixed": fixed, "factored-re": factored},
        training_loss=lambda s: jnp.sum(loss.loss(s, y)),
    )
    result = cd.run(num_iterations=2, num_rows=data.num_rows)
    assert result.objective_history[-1] < result.objective_history[0]
    assert isinstance(result.coefficients["factored-re"], FactoredState)


def test_matrix_factorization_model(rng):
    mf = MatrixFactorizationModel(
        row_effect_type="userId",
        col_effect_type="movieId",
        row_latent_factors=jnp.asarray(rng.normal(size=(5, 3)).astype(np.float32)),
        col_latent_factors=jnp.asarray(rng.normal(size=(7, 3)).astype(np.float32)),
    )
    rows = jnp.asarray([0, 2, 4, -1])
    cols = jnp.asarray([1, 6, -1, 3])
    s = np.asarray(mf.score(rows, cols))
    expected0 = float(
        np.asarray(mf.row_latent_factors)[0] @ np.asarray(mf.col_latent_factors)[1]
    )
    np.testing.assert_allclose(s[0], expected0, rtol=1e-5)
    # missing factors -> score 0 (reference cogroup semantics)
    assert s[2] == 0.0 and s[3] == 0.0
    assert mf.num_latent_factors == 3
    assert "k=3" in mf.to_summary_string()


def test_factored_model_to_random_effect_model(rng):
    frem = FactoredRandomEffectModel(
        latent_coefficients=jnp.asarray(rng.normal(size=(4, 2)).astype(np.float32)),
        latent_matrix=jnp.asarray(rng.normal(size=(2, 6)).astype(np.float32)),
        random_effect_id="userId",
        feature_shard_id="per_user",
        task=TaskType.LOGISTIC_REGRESSION,
    )
    rem = frem.to_random_effect_model(jnp.tile(jnp.arange(6, dtype=jnp.int32), (4, 1)))
    assert rem.coefficients.shape == (4, 6)
    np.testing.assert_allclose(
        np.asarray(rem.coefficients),
        np.asarray(frem.latent_coefficients) @ np.asarray(frem.latent_matrix),
        rtol=1e-5,
    )
