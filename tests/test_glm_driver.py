"""End-to-end GLM driver tests (DriverIntegTest.scala analogue).

Runs the staged CLI pipeline on tiny synthetic LIBSVM/Avro datasets and
asserts stage history, output layout, and model quality — the reference's
MockDriver.runLocally pattern (integTest MockDriver.scala:37-115).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.cli.glm_driver import Driver, DriverStage, main
from photon_ml_tpu.cli.glm_params import GLMParams, InputFormatType, parse_from_command_line
from photon_ml_tpu.diagnostics.types import DiagnosticMode
from photon_ml_tpu.types import (
    NormalizationType,
    OptimizerType,
    RegularizationType,
    TaskType,
)
from photon_ml_tpu.utils.io_utils import read_models_from_text


def _write_libsvm(path, n=400, d=6, seed=3, task="logistic"):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32) * 2.0  # strong signal -> high AUC
    z = x @ w
    if task == "logistic":
        y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(int)
        labels = 2 * y - 1  # {-1, 1} labels exercise remapping
    else:
        labels = z + rng.normal(size=n).astype(np.float32) * 0.1
    with open(path, "w") as f:
        for i in range(n):
            feats = " ".join(f"{j + 1}:{x[i, j]:.5f}" for j in range(d))
            f.write(f"{labels[i]} {feats}\n")
    return x, labels


@pytest.fixture
def libsvm_dirs(tmp_path):
    train = tmp_path / "train"
    val = tmp_path / "validate"
    train.mkdir()
    val.mkdir()
    _write_libsvm(train / "part-0.txt", n=500, seed=3)
    _write_libsvm(val / "part-0.txt", n=200, seed=4)
    return str(train), str(val), str(tmp_path / "out")


def _base_params(train, out, **kw):
    defaults = dict(
        training_data_dir=train,
        output_dir=out,
        task_type=TaskType.LOGISTIC_REGRESSION,
        input_file_format=InputFormatType.LIBSVM,
        regularization_weights=[1.0, 10.0],
        delete_output_dirs_if_exist=True,
    )
    defaults.update(kw)
    return GLMParams(**defaults)


class TestWideSparseRegime:
    """Driver-level coverage of the sparse-wide regime the reference exists
    for (~2M features, Driver.scala:334 OOM note; VERDICT r2 #3): D >= 100k
    forces the padded-sparse layout end-to-end through the staged driver."""

    def test_wide_d_sparse_driver_run(self, tmp_path):
        d, n, nnz = 150_000, 400, 25
        rng = np.random.default_rng(17)
        # planted signal on a small active set so AUC is learnable
        active = rng.choice(d, size=64, replace=False)
        w_true = np.zeros(d, np.float32)
        w_true[active] = rng.normal(size=64).astype(np.float32)
        train = tmp_path / "train"
        train.mkdir()
        with open(train / "part-0.txt", "w") as f:
            for _ in range(n):
                cols = np.unique(
                    np.concatenate([
                        rng.choice(active, size=8, replace=False),
                        rng.integers(0, d, size=nnz - 8),
                    ])
                )
                vals = rng.normal(size=len(cols)).astype(np.float32)
                z = float(vals @ w_true[cols])
                y = 1 if rng.random() < 1 / (1 + np.exp(-z)) else -1
                f.write(
                    f"{y} " + " ".join(f"{c + 1}:{v:.4f}" for c, v in zip(cols, vals)) + "\n"
                )
        params = _base_params(
            str(train),
            str(tmp_path / "out"),
            regularization_weights=[1.0],
            feature_dimension=d,
        )
        driver = Driver(params)
        driver.run()
        # wide D must select the padded-sparse layout, not a dense (N, D)
        from photon_ml_tpu.ops.features import SparseFeatures

        assert isinstance(driver.train_batch.features, SparseFeatures)
        assert driver.train_batch.features.dim == d + 1  # + intercept
        (_, model), = driver.models
        w = np.asarray(model.coefficients.means)
        assert w.shape == (d + 1,)
        assert np.all(np.isfinite(w))
        # training AUC on the planted signal clears chance comfortably
        from photon_ml_tpu.evaluation import area_under_roc_curve

        scores = driver.train_batch.features.matvec(
            jnp.asarray(model.coefficients.means)
        )
        auc = float(
            area_under_roc_curve(
                scores, driver.train_batch.labels, driver.train_batch.weights
            )
        )
        assert auc > 0.8, auc


class TestDriverStages:
    def test_full_pipeline_stage_history(self, libsvm_dirs):
        train, val, out = libsvm_dirs
        driver = Driver(_base_params(train, out, validating_data_dir=val))
        driver.run()
        assert driver.stage == DriverStage.VALIDATED
        assert driver.stage_history == [
            DriverStage.INIT, DriverStage.PREPROCESSED, DriverStage.TRAINED
        ]
        assert driver.best_reg_weight in (1.0, 10.0)
        auc = driver.validation_metrics[driver.best_reg_weight]["Area under ROC"]
        assert auc > 0.7  # separable-ish synthetic data

    def test_train_only_stops_at_trained(self, libsvm_dirs):
        train, _, out = libsvm_dirs
        driver = Driver(_base_params(train, out))
        driver.run()
        assert driver.stage == DriverStage.TRAINED
        assert driver.best_model is None

    def test_stage_regression_rejected(self, libsvm_dirs):
        train, _, out = libsvm_dirs
        driver = Driver(_base_params(train, out))
        driver.run()
        with pytest.raises(RuntimeError):
            driver.preprocess()


class TestDriverOutputs:
    def test_model_text_output_roundtrip(self, libsvm_dirs):
        train, val, out = libsvm_dirs
        driver = Driver(_base_params(train, out, validating_data_dir=val))
        driver.run()
        models = read_models_from_text(os.path.join(out, "output"))
        assert set(models) == {1.0, 10.0}
        # intercept row present, named like the reference
        assert any(name == "(INTERCEPT)" for name, _ in models[1.0])
        best = read_models_from_text(os.path.join(out, "best"))
        assert set(best) == {driver.best_reg_weight}
        assert os.path.exists(os.path.join(out, "photon-ml-tpu.log"))

    def test_summarization_output(self, libsvm_dirs, tmp_path):
        train, _, out = libsvm_dirs
        sumdir = str(tmp_path / "summary")
        driver = Driver(
            _base_params(
                train, out,
                normalization_type=NormalizationType.STANDARDIZATION,
                summarization_output_dir=sumdir,
            )
        )
        driver.run()
        assert os.listdir(sumdir)

    def test_existing_output_dir_rejected_without_flag(self, libsvm_dirs):
        train, _, out = libsvm_dirs
        os.makedirs(out, exist_ok=True)
        with open(os.path.join(out, "junk"), "w") as f:
            f.write("x")
        with pytest.raises(FileExistsError):
            Driver(_base_params(train, out, delete_output_dirs_if_exist=False)).run()


class TestDriverVariants:
    def test_tron_matches_lbfgs(self, libsvm_dirs):
        train, val, out = libsvm_dirs
        d1 = Driver(_base_params(train, out, validating_data_dir=val))
        d1.run()
        d2 = Driver(
            _base_params(
                train, out,
                validating_data_dir=val,
                optimizer_type=OptimizerType.TRON,
            )
        )
        d2.run()
        w1 = d1.models[0][1].means_as_numpy()
        w2 = d2.models[0][1].means_as_numpy()
        np.testing.assert_allclose(w1, w2, atol=5e-3)

    def test_elastic_net_produces_sparsity(self, libsvm_dirs):
        train, _, out = libsvm_dirs
        driver = Driver(
            _base_params(
                train, out,
                regularization_type=RegularizationType.ELASTIC_NET,
                elastic_net_alpha=0.8,
                regularization_weights=[50.0],
            )
        )
        driver.run()
        w = driver.models[0][1].means_as_numpy()
        assert np.sum(w == 0.0) > 0  # exact zeros from OWL-QN

    def test_normalization_standardization(self, libsvm_dirs):
        train, val, out = libsvm_dirs
        raw = Driver(_base_params(train, out, validating_data_dir=val))
        raw.run()
        std = Driver(
            _base_params(
                train, out,
                validating_data_dir=val,
                normalization_type=NormalizationType.STANDARDIZATION,
            )
        )
        std.run()
        # back-transformed model must score equivalently in raw space
        a1 = raw.validation_metrics[1.0]["Area under ROC"]
        a2 = std.validation_metrics[1.0]["Area under ROC"]
        assert a2 == pytest.approx(a1, abs=0.05)

    def test_linear_regression_on_dense(self, tmp_path):
        train = tmp_path / "train"
        train.mkdir()
        _write_libsvm(train / "d.txt", n=300, seed=9, task="linear")
        driver = Driver(
            _base_params(
                str(train), str(tmp_path / "out"),
                task_type=TaskType.LINEAR_REGRESSION,
                regularization_weights=[0.01],
            )
        )
        driver.run()
        assert driver.stage == DriverStage.TRAINED

    def test_box_constraints_respected(self, libsvm_dirs):
        train, _, out = libsvm_dirs
        constraints = '[{"name": "*", "term": "*", "lowerBound": -0.1, "upperBound": 0.1}]'
        driver = Driver(
            _base_params(
                train, out,
                coefficient_box_constraints=constraints,
                regularization_weights=[1.0],
            )
        )
        driver.run()
        w = driver.models[0][1].means_as_numpy()
        intercept = driver.index_map.intercept_index
        mask = np.ones_like(w, bool)
        mask[intercept] = False
        assert np.all(w[mask] >= -0.1 - 1e-6) and np.all(w[mask] <= 0.1 + 1e-6)

    def test_diagnostic_mode_writes_report(self, libsvm_dirs):
        train, val, out = libsvm_dirs
        driver = Driver(
            _base_params(
                train, out,
                validating_data_dir=val,
                regularization_weights=[1.0],
                diagnostic_mode=DiagnosticMode.VALIDATE,
            )
        )
        driver.run()
        assert driver.stage == DriverStage.DIAGNOSED
        report = os.path.join(out, "model-diagnostic.html")
        assert os.path.exists(report)
        html = open(report).read()
        assert "Hosmer-Lemeshow" in html and "Feature importance" in html

    def test_diagnostic_avro_records(self, libsvm_dirs):
        """Machine-readable report records in the reference's schemas
        (EvaluationResultAvro + FeatureSummarizationResultAvro,
        photon-avro-schemas/; VERDICT r2 missing #5) are written alongside
        the HTML and round-trip through the avro codec."""
        from photon_ml_tpu.io import avro as avro_io

        train, val, out = libsvm_dirs
        driver = Driver(
            _base_params(
                train, out,
                validating_data_dir=val,
                regularization_weights=[1.0, 10.0],
                diagnostic_mode=DiagnosticMode.VALIDATE,
            )
        )
        driver.run()
        diag = os.path.join(out, "diagnostics")
        evals = list(avro_io.read_container(os.path.join(diag, "evaluation-results.avro")))
        assert len(evals) == 2  # one per lambda
        rec = evals[0]
        ctx = rec["evaluationContext"]
        assert ctx["modelTrainingContext"]["modelSource"] == "PHOTONML"
        assert ctx["modelTrainingContext"]["trainingTask"] == "LOGISTIC_REGRESSION"
        assert ctx["modelTrainingContext"]["convergenceReason"] in (
            "FUNCTION_VALUES_CONVERGED", "GRADIENT_CONVERGED", "MAX_ITERATIONS",
            "OBJECTIVE_NOT_IMPROVING", None,
        )
        assert rec["scalarMetrics"]["Area under ROC"] > 0.7
        roc = rec["curves"]["roc"]
        assert roc["xLabel"] == "false positive rate"
        pts = roc["points"]
        # a valid ROC: monotone from (0,0) to (1,1)
        assert pts[0] == {"x": 0.0, "y": 0.0} and pts[-1] == {"x": 1.0, "y": 1.0}
        assert all(b["x"] >= a["x"] and b["y"] >= a["y"] for a, b in zip(pts, pts[1:]))
        assert "precisionRecall" in rec["curves"]

        feats = list(avro_io.read_container(os.path.join(diag, "feature-summaries.avro")))
        assert len(feats) == len(driver.index_map)
        assert {"mean", "variance", "min", "max", "numNonzeros"} <= set(
            feats[0]["metrics"]
        )
        assert any(f["featureName"] == "(INTERCEPT)" for f in feats)


class TestAvroPath:
    def test_avro_roundtrip_training(self, tmp_path):
        # synth avro data via the writer, then drive the AVRO ingest path
        from photon_ml_tpu.io import avro_data
        from photon_ml_tpu.io.index_map import IndexMap, feature_key
        from photon_ml_tpu.io.libsvm import read_libsvm

        raw = tmp_path / "raw.txt"
        _write_libsvm(raw, n=300, d=5, seed=11)
        ds = read_libsvm(str(raw))
        names = [feature_key(f"f{j}") for j in range(5)]
        imap = IndexMap.build(names, add_intercept=True)
        # remap libsvm columns onto named features
        ds2 = ds
        train_dir = tmp_path / "train-avro"
        train_dir.mkdir()
        # build records manually: feature j -> name f{j}
        from photon_ml_tpu.io import avro as avro_io
        from photon_ml_tpu.io import schemas

        def recs():
            for r in range(ds2.num_rows):
                idx, val = ds2.row_slice(r)
                feats = [
                    {"name": f"f{j}", "term": "", "value": float(v)}
                    for j, v in zip(idx, val)
                    if j < 5
                ]
                yield {
                    "uid": str(r),
                    "label": float(ds2.labels[r]),
                    "features": feats,
                    "metadataMap": None,
                    "weight": None,
                    "offset": None,
                }

        avro_io.write_container(
            str(train_dir / "part-0.avro"), recs(), schemas.TRAINING_EXAMPLE
        )
        driver = Driver(
            GLMParams(
                training_data_dir=str(train_dir),
                output_dir=str(tmp_path / "out"),
                task_type=TaskType.LOGISTIC_REGRESSION,
                input_file_format=InputFormatType.AVRO,
                regularization_weights=[1.0],
                delete_output_dirs_if_exist=True,
            )
        )
        driver.run()
        assert driver.stage == DriverStage.TRAINED
        assert len(driver.index_map) == 6  # 5 features + intercept


class TestCommandLine:
    def test_parse_reference_flags(self):
        params = parse_from_command_line(
            [
                "--training-data-directory", "/tmp/in",
                "--output-directory", "/tmp/out",
                "--task", "LOGISTIC_REGRESSION",
                "--regularization-weights", "0.5,5",
                "--optimizer", "TRON",
                "--regularization-type", "L2",
                "--intercept", "true",
                "--num-iterations", "30",
                "--input-file-format", "LIBSVM",
            ]
        )
        assert params.task_type == TaskType.LOGISTIC_REGRESSION
        assert params.regularization_weights == [0.5, 5.0]
        assert params.optimizer_type == OptimizerType.TRON
        assert params.max_num_iterations == 30

    def test_tron_l1_rejected(self):
        with pytest.raises(ValueError, match="TRON"):
            parse_from_command_line(
                [
                    "--training-data-directory", "/tmp/in",
                    "--output-directory", "/tmp/out",
                    "--task", "LOGISTIC_REGRESSION",
                    "--optimizer", "TRON",
                    "--regularization-type", "L1",
                ]
            )

    def test_diagnostic_requires_validation_dir(self):
        with pytest.raises(ValueError, match="diagnostic"):
            parse_from_command_line(
                [
                    "--training-data-directory", "/tmp/in",
                    "--output-directory", "/tmp/out",
                    "--task", "LOGISTIC_REGRESSION",
                    "--diagnostic-mode", "VALIDATE",
                ]
            )

    def test_main_entry(self, libsvm_dirs):
        train, _, out = libsvm_dirs
        driver = main(
            [
                "--training-data-directory", train,
                "--output-directory", out,
                "--task", "LOGISTIC_REGRESSION",
                "--input-file-format", "LIBSVM",
                "--regularization-weights", "1.0",
                "--delete-output-dirs-if-exist", "true",
            ]
        )
        assert driver.stage == DriverStage.TRAINED


class TestValidatePerIteration:
    def test_per_iteration_metrics_logged_and_stored(self, libsvm_dirs):
        """--validate-per-iteration: validation metrics for EVERY
        iteration's model snapshot (Driver.scala:292-361 ModelTracker
        pass); the final iteration's metrics equal the final model's."""
        train, val, out = libsvm_dirs
        driver = Driver(_base_params(
            train, out,
            validating_data_dir=val,
            validate_per_iteration=True,
            regularization_weights=[1.0],
        ))
        driver.run()
        assert 1.0 in driver.per_iteration_metrics
        per_iter = driver.per_iteration_metrics[1.0]
        assert len(per_iter) >= 2  # converged over several iterations
        final = per_iter[-1]["Area under ROC"]
        assert final == pytest.approx(
            driver.validation_metrics[1.0]["Area under ROC"], abs=1e-6
        )
        # the trajectory's AUC improves from the first snapshot to the last
        assert final >= per_iter[0]["Area under ROC"] - 1e-6

    def test_off_by_default(self, libsvm_dirs):
        train, val, out = libsvm_dirs
        driver = Driver(_base_params(
            train, out, validating_data_dir=val, regularization_weights=[1.0]
        ))
        driver.run()
        assert driver.per_iteration_metrics == {}
        # and no tracking memory was carried
        assert driver.trained.results[0].coefficient_history is None


class TestStreamingOutOfCore:
    """--streaming-chunk-rows: out-of-core training (VERDICT r3 #5) must
    reproduce the in-memory run through the full staged driver."""

    def test_streaming_matches_in_memory(self, libsvm_dirs):
        train, val, out = libsvm_dirs
        mem = Driver(_base_params(
            train, out + "-mem", validating_data_dir=val,
            normalization_type=NormalizationType.STANDARDIZATION,
        ))
        mem.run()
        st = Driver(_base_params(
            train, out + "-st", validating_data_dir=val,
            normalization_type=NormalizationType.STANDARDIZATION,
            streaming_chunk_rows=128,
        ))
        st.run()
        assert st.stage == DriverStage.VALIDATED
        assert st.best_reg_weight == mem.best_reg_weight
        np.testing.assert_allclose(
            np.asarray(st.best_model.coefficients.means),
            np.asarray(mem.best_model.coefficients.means),
            rtol=2e-3, atol=2e-4,
        )
        # the spilled chunks are cleaned up once training completes
        chunk_dir = os.path.join(out + "-st", "stream-chunks")
        assert not os.path.exists(chunk_dir) or not os.listdir(chunk_dir)
        # streaming mode actually engaged (its source replaced the batch)
        assert st.streaming_source is not None and st.train_batch is None

    def test_streaming_tron_matches_in_memory(self, libsvm_dirs):
        """TRON over streamed chunks through the full staged driver (the r4
        restriction is gone): one streamed pass per CG Hessian-vector
        product, same solution as the in-memory TRON run."""
        train, val, out = libsvm_dirs
        mem = Driver(_base_params(
            train, out + "-tron-mem", validating_data_dir=val,
            optimizer_type=OptimizerType.TRON,
        ))
        mem.run()
        st = Driver(_base_params(
            train, out + "-tron-st", validating_data_dir=val,
            optimizer_type=OptimizerType.TRON,
            streaming_chunk_rows=128,
        ))
        st.run()
        assert st.stage == DriverStage.VALIDATED
        assert st.best_reg_weight == mem.best_reg_weight
        np.testing.assert_allclose(
            np.asarray(st.best_model.coefficients.means),
            np.asarray(mem.best_model.coefficients.means),
            rtol=2e-3, atol=2e-4,
        )
