"""Evaluator tests: AUC vs brute-force pairs, metrics vs closed forms,
precision@K vs naive grouping."""

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.evaluation import (
    EvaluatorType,
    area_under_roc_curve,
    evaluator_for,
    precision_at_k,
    rmse,
)
from photon_ml_tpu.evaluation.evaluators import mean_absolute_error


def brute_auc(scores, labels, weights=None):
    if weights is None:
        weights = np.ones_like(scores)
    pos = [(s, w) for s, l, w in zip(scores, labels, weights) if l > 0.5 and w > 0]
    neg = [(s, w) for s, l, w in zip(scores, labels, weights) if l <= 0.5 and w > 0]
    num = 0.0
    for sp, wp in pos:
        for sn, wn in neg:
            num += wp * wn * (1.0 if sp > sn else 0.5 if sp == sn else 0.0)
    return num / (sum(w for _, w in pos) * sum(w for _, w in neg))


def test_auc_matches_bruteforce(rng):
    n = 60
    scores = np.round(rng.normal(size=n), 1).astype(np.float32)  # force ties
    labels = (rng.random(n) > 0.4).astype(np.float32)
    got = float(area_under_roc_curve(jnp.asarray(scores), jnp.asarray(labels)))
    np.testing.assert_allclose(got, brute_auc(scores, labels), atol=1e-5)


def test_auc_weighted_and_padded(rng):
    n = 40
    scores = rng.normal(size=n).astype(np.float32)
    labels = (rng.random(n) > 0.5).astype(np.float32)
    weights = (rng.random(n) * 2).astype(np.float32)
    weights[-8:] = 0.0  # padding
    got = float(area_under_roc_curve(jnp.asarray(scores), jnp.asarray(labels),
                                     jnp.asarray(weights)))
    np.testing.assert_allclose(got, brute_auc(scores, labels, weights), atol=1e-5)


def test_auc_perfect_and_random():
    scores = jnp.asarray([0.1, 0.2, 0.8, 0.9])
    labels = jnp.asarray([0.0, 0.0, 1.0, 1.0])
    assert float(area_under_roc_curve(scores, labels)) == 1.0
    assert float(area_under_roc_curve(-scores, labels)) == 0.0


def test_rmse_mae():
    s = jnp.asarray([1.0, 2.0, 3.0])
    y = jnp.asarray([0.0, 2.0, 5.0])
    np.testing.assert_allclose(float(rmse(s, y)), np.sqrt((1 + 0 + 4) / 3), rtol=1e-6)
    np.testing.assert_allclose(float(mean_absolute_error(s, y)), 1.0, rtol=1e-6)


def test_precision_at_k(rng):
    # 3 groups with known top-k composition
    g = jnp.asarray([0, 0, 0, 1, 1, 1, 2, 2, 2], jnp.int32)
    s = jnp.asarray([3.0, 2.0, 1.0, 3.0, 2.0, 1.0, 3.0, 2.0, 1.0])
    l = jnp.asarray([1.0, 0.0, 1.0, 1.0, 1.0, 0.0, 0.0, 0.0, 1.0])
    # top-2 hits: g0 -> 1, g1 -> 2, g2 -> 0 ; mean precision@2 = (0.5+1+0)/3
    got = float(precision_at_k(s, l, g, k=2))
    np.testing.assert_allclose(got, (1 + 2 + 0) / (3 * 2), atol=1e-6)


def test_evaluator_direction():
    auc = evaluator_for(EvaluatorType.AUC)
    assert auc.better_than(0.9, 0.8)
    r = evaluator_for(EvaluatorType.RMSE)
    assert r.better_than(0.1, 0.5)


def test_summary_stats(rng):
    from photon_ml_tpu.ops.features import DenseFeatures
    from photon_ml_tpu.ops.objective import GLMBatch
    from photon_ml_tpu.ops.stats import summarize

    x = rng.normal(size=(50, 4)).astype(np.float32)
    batch = GLMBatch.create(DenseFeatures(jnp.asarray(x)), jnp.zeros(50))
    s = summarize(batch)
    np.testing.assert_allclose(np.asarray(s.mean), x.mean(0), atol=1e-5)
    np.testing.assert_allclose(np.asarray(s.variance), x.var(0, ddof=1), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s.max), x.max(0), atol=1e-6)
    np.testing.assert_allclose(float(s.count), 50.0)

    # padding rows excluded
    x2 = np.concatenate([x, np.full((5, 4), 100.0, np.float32)])
    w = np.concatenate([np.ones(50), np.zeros(5)]).astype(np.float32)
    batch2 = GLMBatch(DenseFeatures(jnp.asarray(x2)), jnp.zeros(55), jnp.zeros(55),
                      jnp.asarray(w))
    s2 = summarize(batch2)
    np.testing.assert_allclose(np.asarray(s2.mean), x.mean(0), atol=1e-5)
    np.testing.assert_allclose(np.asarray(s2.max), x.max(0), atol=1e-6)
