"""Worker for the 2-process entity-sharded STREAMING coordinate-descent
harness (launched by test_perhost_streaming.py; also runnable by hand:

    python tests/perhost_streaming_worker.py <proc_id> <nprocs> <port> <outdir>

The full dataset is DEFINED globally (seeded); each process "decodes" only
its contiguous row block (the per-host Avro-partition analogue), then runs
the per-host streaming path end-to-end: entity-count agreement -> agreed
global blocking -> entity routing (one all_to_all) -> owned-block build ->
streaming CD over {streaming fixed effect (per-host chunks, exact mesh
merges), streaming random effect (owner-computes block solves)}. The test
asserts the run is BITWISE-equal to the single-host streaming run of the
same data — the acceptance gate of the entity-sharded multihost streaming
PR.

Chaos mode (env PERHOST_LOSE_HOST=<pid>): that process dies hard
(os._exit) after spilling its first block inside the update — a LOST host
mid-block. The survivors' post-update barrier must convert the infinite
hang into a diagnosable BarrierTimeoutError (PHOTON_BARRIER_TIMEOUT)."""

import os
import sys
import time

proc_id, nprocs, port, outdir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.parallel import multihost

mh = multihost.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nprocs,
    process_id=proc_id,
)
ctx = mh.mesh_context()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from game_test_utils import make_glmix_data  # noqa: E402

from photon_ml_tpu.algorithm.coordinate_descent import CoordinateDescent  # noqa: E402
from photon_ml_tpu.algorithm.streaming_fixed_effect import (  # noqa: E402
    PerHostStreamingFixedEffectCoordinate,
)
from photon_ml_tpu.data.game import RandomEffectDataConfig  # noqa: E402
from photon_ml_tpu.ops import losses as losses_mod  # noqa: E402
from photon_ml_tpu.ops.regularization import RegularizationContext  # noqa: E402
from photon_ml_tpu.optim.common import OptimizerConfig  # noqa: E402
from photon_ml_tpu.optim.problem import GLMOptimizationProblem  # noqa: E402
from photon_ml_tpu.parallel.mesh import MeshContext  # noqa: E402
from photon_ml_tpu.parallel.perhost_ingest import HostRows, csr_to_padded  # noqa: E402
from photon_ml_tpu.parallel.perhost_streaming import (  # noqa: E402
    PerHostStreamingRandomEffectCoordinate,
    build_perhost_streaming_manifest,
)
from photon_ml_tpu.types import OptimizerType, TaskType  # noqa: E402

# ---- the globally seeded dataset (identical in every process) -------------
rng = np.random.default_rng(97)
data, _ = make_glmix_data(
    rng, num_users=60, rows_per_user_range=(4, 16), d_fixed=5, d_random=4
)
N = data.num_rows
D_FE = data.shards["global"].dim
CHUNK_ROWS = 128
BLOCK_ENTITIES = 16
RE_CFG = RandomEffectDataConfig("userId", "per_user")
FE_PROBLEM = GLMOptimizationProblem(
    TaskType.LOGISTIC_REGRESSION, OptimizerType.LBFGS,
    OptimizerConfig(max_iterations=6, tolerance=1e-8),
    RegularizationContext.l2(0.5),
)
RE_OPT = OptimizerConfig(max_iterations=6, tolerance=1e-8)
RE_REG = RegularizationContext.l2(0.2)

# this host "decodes" only its contiguous row block of the random-effect rows
lo = proc_id * (N // nprocs)
hi = N if proc_id == nprocs - 1 else (proc_id + 1) * (N // nprocs)
feats = data.shards["per_user"]
fi_all, fv_all = csr_to_padded(feats, N)
vocab0 = data.id_vocabs["userId"]
host_rows = HostRows(
    entity_raw_ids=[vocab0[i] for i in data.ids["userId"][lo:hi]],
    row_index=np.arange(lo, hi, dtype=np.int64),
    labels=data.response[lo:hi].astype(np.float32),
    weights=data.weight[lo:hi].astype(np.float32),
    offsets=data.offset[lo:hi].astype(np.float32),
    feat_idx=fi_all[lo:hi],
    feat_val=fv_all[lo:hi],
    global_dim=feats.dim,
)

# ---- the execution plan: every policy resolved ONCE from the env ----------
# (PHOTON_SOLVE_CHUNK / PHOTON_SPARSE_KERNEL / PHOTON_SHAPE_LADDER) — the
# all-flags-on harness arm drives compaction + the sparse race through the
# same worker by exporting the env vars; the default run resolves all-off
from photon_ml_tpu.compile.plan import ExecutionPlan  # noqa: E402

exec_plan = ExecutionPlan.resolve(
    distributed=(nprocs > 1), streaming=True, num_processes=nprocs
)

# ---- per-host streaming RE: agree -> plan -> route -> owned blocks --------
# NO shared_vocab: the raw-id agreement collective is the production path
manifest = build_perhost_streaming_manifest(
    host_rows, RE_CFG, os.path.join(outdir, f"re-host{proc_id}"),
    ctx, nprocs, proc_id, block_entities=BLOCK_ENTITIES,
    bucketer=exec_plan.bucketer,
)
re_coord = PerHostStreamingRandomEffectCoordinate(
    manifest, TaskType.LOGISTIC_REGRESSION,
    optimizer=OptimizerType.LBFGS, optimizer_config=RE_OPT,
    regularization=RE_REG,
    state_root=os.path.join(outdir, f"re-state-host{proc_id}"),
    plan=exec_plan,
    ctx=ctx, num_processes=nprocs,
)

lose = os.environ.get("PERHOST_LOSE_HOST")
if lose is not None:
    # ---- chaos: this host dies HARD after its first block spill ----------
    from photon_ml_tpu.algorithm import streaming_random_effect as sre

    if int(lose) == proc_id:
        orig_write = sre.SpilledREState.write

        def dying_write(self, i, arr):
            orig_write(self, i, arr)
            print("LOSTHOST-DYING", flush=True)
            os._exit(17)

        sre.SpilledREState.write = dying_write
    mh.write_heartbeat(os.path.join(outdir, "heartbeats"), step=0)
    try:
        re_coord.update(
            jnp.zeros((N,), jnp.float32), re_coord.initial_coefficients()
        )
        mh.barrier("post-update", timeout=float(
            os.environ.get("PHOTON_BARRIER_TIMEOUT", "25")
        ))
        print("LOSTHOST-UNDETECTED", flush=True)  # should be unreachable
        sys.exit(0)
    except multihost.BarrierTimeoutError as e:
        hb = mh.describe_heartbeats(os.path.join(outdir, "heartbeats"))
        print(f"LOSTHOST-DETECTED BarrierTimeoutError: {e} | {hb}", flush=True)
        sys.exit(3)

# ---- per-host streaming FE: global chunk list, round-robin ownership ------
x_fe = np.zeros((N, D_FE), np.float32)
gf = data.shards["global"]
nnz = np.diff(gf.indptr)
x_fe[np.repeat(np.arange(N), nnz), gf.indices] = gf.values
chunk_sizes = [
    min(CHUNK_ROWS, N - c * CHUNK_ROWS)
    for c in range((N + CHUNK_ROWS - 1) // CHUNK_ROWS)
]
owned_loaders = {}
for c in range(len(chunk_sizes)):
    if c % nprocs != proc_id:
        continue
    s = c * CHUNK_ROWS
    e = s + chunk_sizes[c]

    def load(s=s, e=e):
        return {"x": x_fe[s:e], "y": data.response[s:e].astype(np.float32)}

    owned_loaders[c] = load
fe_coord = PerHostStreamingFixedEffectCoordinate(
    chunk_sizes, owned_loaders, D_FE, FE_PROBLEM,
    plan=exec_plan,
    ctx=ctx, num_processes=nprocs,
)

# ---- one streaming CD run over both coordinates ---------------------------
labels = jnp.asarray(data.response.astype(np.float32))
weights = jnp.asarray(data.weight.astype(np.float32))
loss = losses_mod.for_task(TaskType.LOGISTIC_REGRESSION)
loss_fn = lambda s: jnp.sum(weights * loss.loss(s, labels))
t0 = time.perf_counter()
cd = CoordinateDescent({"fixed": fe_coord, "per-user": re_coord}, loss_fn)
result = cd.run(num_iterations=2, num_rows=N)
elapsed = time.perf_counter() - t0

mh.barrier("cd-done")
# every host writes ITS owned entities' back-projected means (the per-host
# model-save layout: the coefficient state never crosses hosts)
means = re_coord.entity_means_by_raw_id(result.coefficients["per-user"])
np.savez(
    os.path.join(outdir, f"means-host{proc_id}.npz"),
    names=np.asarray(sorted(means), dtype=object),
    stack=np.stack([means[k] for k in sorted(means)])
    if means else np.zeros((0, 0)),
)
if mh.coordinator_only_io():
    np.savez(
        os.path.join(outdir, "run.npz"),
        fe=np.asarray(result.coefficients["fixed"]),
        total_scores=np.asarray(result.total_scores),
        objectives=np.asarray(result.objective_history, np.float64),
    )
mh.barrier("saved")
sched_note = ""
if exec_plan.schedule is not None:
    from photon_ml_tpu.optim.scheduler import solve_stats

    t = solve_stats.totals()
    sched_note = (
        f" compaction_saved={t['saved_lane_iterations']}"
        f"/{t['baseline_lane_iterations']}"
    )
print(
    f"PHSOK proc={proc_id} sec_per_iter={elapsed / 2:.3f} "
    f"obj={result.objective_history[-1]:.9g}{sched_note}",
    flush=True,
)
