"""Fused logistic value+grad Pallas kernel tests (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.fused_glm import (
    fused_logistic_value_and_grad,
    reference_logistic_value_and_grad,
)


def _data(rng, n, d, dtype=jnp.float32):
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = (rng.normal(size=d) * 0.2).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    wt = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    return (
        jnp.asarray(x, dtype),
        jnp.asarray(y),
        jnp.asarray(wt),
        jnp.asarray(w),
        x,
    )


class TestFusedLogistic:
    def test_matches_reference_f32(self, rng):
        x, y, wt, w, _ = _data(rng, 512, 64)
        v, g = fused_logistic_value_and_grad(x, y, wt, w, block_rows=128)
        v_ref, g_ref = reference_logistic_value_and_grad(x, y, wt, w)
        assert float(v) == pytest.approx(float(v_ref), rel=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-4)

    def test_bf16_storage_close_to_f32(self, rng):
        x, y, wt, w, x_np = _data(rng, 1024, 32, dtype=jnp.bfloat16)
        v, g = fused_logistic_value_and_grad(x, y, wt, w, block_rows=256)
        v_ref, g_ref = reference_logistic_value_and_grad(
            jnp.asarray(x_np), y, wt, w
        )
        assert float(v) == pytest.approx(float(v_ref), rel=2e-2)
        ref_norm = float(jnp.linalg.norm(g_ref))
        assert float(jnp.linalg.norm(g - g_ref)) < 0.03 * ref_norm

    def test_l2_term(self, rng):
        x, y, wt, w, _ = _data(rng, 256, 16)
        v, g = fused_logistic_value_and_grad(x, y, wt, w, l2=0.5, block_rows=128)
        v_ref, g_ref = reference_logistic_value_and_grad(x, y, wt, w, l2=0.5)
        assert float(v) == pytest.approx(float(v_ref), rel=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-4)

    def test_ragged_n_padded(self, rng):
        # N not a multiple of block_rows -> internal zero-weight padding
        x, y, wt, w, _ = _data(rng, 300, 8)
        v, g = fused_logistic_value_and_grad(x, y, wt, w, block_rows=128)
        v_ref, g_ref = reference_logistic_value_and_grad(x, y, wt, w)
        assert float(v) == pytest.approx(float(v_ref), rel=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-4)

    def test_zero_weight_rows_excluded(self, rng):
        x, y, wt, w, _ = _data(rng, 256, 8)
        wt0 = wt.at[:64].set(0.0)
        v, _ = fused_logistic_value_and_grad(x, y, wt0, w, block_rows=64)
        v_ref, _ = reference_logistic_value_and_grad(x, y, wt0, w)
        assert float(v) == pytest.approx(float(v_ref), rel=1e-5)

    def test_matches_objective_module(self, rng):
        """Consistency with the framework's GLMObjective path."""
        from photon_ml_tpu.ops import losses
        from photon_ml_tpu.ops.features import DenseFeatures
        from photon_ml_tpu.ops.normalization import NormalizationContext
        from photon_ml_tpu.ops.objective import GLMBatch, GLMObjective

        x, y, wt, w, _ = _data(rng, 512, 24)
        batch = GLMBatch(DenseFeatures(x), y, jnp.zeros_like(y), wt)
        obj = GLMObjective(losses.logistic)
        v_obj, g_obj = obj.value_and_grad(w, batch, NormalizationContext.identity(), 0.3)
        v, g = fused_logistic_value_and_grad(x, y, wt, w, l2=0.3, block_rows=128)
        assert float(v) == pytest.approx(float(v_obj), rel=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_obj), rtol=1e-4, atol=1e-4)
